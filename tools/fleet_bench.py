"""Fleet-level throughput: what the mining *application* delivers end-to-end.

`bench.py` measures the bare kernel; the capability this framework rebuilds
is the fleet (SURVEY §3.6): client → server/scheduler → LSP → miner →
kernel → min-fold → Result.  This tool stands up the real binaries on
loopback — `apps.server` and `apps.miner` as subprocesses, an in-process
LSP client — runs a big job, and reports **delivered nonces/s** next to the
kernel rate, so scheduler/transport overhead is a measured number instead
of a guess.

Jobs run in order:

- a **warm-up job** (default 4e9 nonces) that pays the one-time costs —
  TPU runtime init, the dynamic kernel's one build (persistent-cached
  across runs), and the scheduler's EWMA rate ramp from `min_chunk` to
  full-size chunks;
- **class warm-ups**: one tiny job per digit class the timed job touches
  beyond the warm-up range (same contract as bench.py: compiles precede
  the measurement window);
- the **timed job** (default 2e10 nonces), whose delivered rate is the
  steady-state fleet number the JSON line reports.  The warm-up wall time
  is reported alongside so cold-start cost stays visible;
- optionally the **kill drill** (`--kill-drill`): the same fresh-range job
  clean and with a mid-job miner SIGKILL+respawn, asserting identical
  `(hash, nonce)` — the scheduler's reassignment invariant on hardware.

`--cpu-miners N` adds N native C++ workers to the fleet (heterogeneous
scheduling under one scheduler; liveness-checked).

Fault tolerance IS the harness (same lesson as bench.py round 1): the
tunnelled TPU runtime sometimes wedges a fresh process at init, and a
wedged miner would hang the job forever.  The miner runs with
``BMT_MINER_LOG`` chunk-timing on; a monitor watches that log and the
process, and a miner that dies or stalls past ``--stall`` seconds is
killed and respawned — the scheduler's dead-conn reassignment then
carries the job, which is the framework's own recovery path doing the
work (miner restarts are counted in the JSON line).

Usage: python tools/fleet_bench.py [--nonces N] [--warmup N] [--backend B]
       [--kernel-rate R] [--miner-log FILE]   (prints one JSON line)
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))  # repo root

from bitcoin_miner_tpu import workloads as workloads_mod  # noqa: E402
from bitcoin_miner_tpu.bitcoin.message import Message, MsgType  # noqa: E402
from bitcoin_miner_tpu.utils.metrics import Histogram  # noqa: E402

REPO = Path(__file__).resolve().parents[1]

#: The resolved range-fold workload (ISSUE 9).  main() resolves it
#: AFTER argparse — ``--workload`` first, env BMT_WORKLOAD second — and
#: exports the env so the server/miner/federation subprocesses serve
#: the same hash family this tool's oracle validates against.  Import
#: time pins the default only: a stale BMT_WORKLOAD must not kill
#: ``--help`` (or a valid flag) before the parser ever runs.
WORKLOAD = workloads_mod.resolve(None)

#: Request→result latency of every job this bench ran (warm-ups, class
#: warms, timed, drills) — p50/p95/p99 land in the BENCH JSON line so the
#: perf trajectory has a latency axis next to nonces/s (ISSUE 6).
LATENCY = Histogram()


def log(*a) -> None:
    print(*a, file=sys.stderr, flush=True)


def _read_last_fleet_state(path: str) -> dict:
    """Last decodable row of the server's fleet log (torn tails from a
    concurrent append are skipped)."""
    last = None
    try:
        with open(path) as f:
            for line in f:
                if not line.endswith("\n"):
                    break
                try:
                    last = json.loads(line)
                except ValueError:
                    continue
    except OSError:
        return None
    return last


def _wait_listening(proc: subprocess.Popen, timeout: float) -> None:
    import select

    deadline = time.monotonic() + timeout
    line = ""
    while time.monotonic() < deadline:
        # select before readline: a server that wedges without printing
        # anything must trip the deadline, not block this tool forever.
        ready, _, _ = select.select([proc.stdout], [], [], 1.0)
        if ready:
            line = proc.stdout.readline()
            if "Server listening" in line:
                return
        if proc.poll() is not None:
            break
    raise RuntimeError(f"server did not come up (last: {line!r})")


class MinerKeeper:
    """Owns the miner subprocess: spawns it, watches its chunk-timing log
    for liveness, kills + respawns on wedge/death.  ``telemetry`` is the
    server's sidecar hostport (ISSUE 7): a respawned miner re-arms its
    exporter too, so the fleet view keeps seeing the replacement."""

    def __init__(
        self, port: int, backend: str, log_path: str,
        telemetry: str = None,
    ) -> None:
        self.port = port
        self.backend = backend
        self.log_path = log_path
        self.telemetry = telemetry
        self.restarts = 0
        self.proc: subprocess.Popen = None
        self.spawn()

    def spawn(self) -> None:
        self._log_f = open(self.log_path, "ab", buffering=0)
        argv = [
            sys.executable,
            "-m",
            "bitcoin_miner_tpu.apps.miner",
            f"127.0.0.1:{self.port}",
            "--backend",
            self.backend,
        ]
        if self.telemetry:
            argv += [
                "--telemetry", self.telemetry,
                "--telemetry-interval", "1.0",
                "--source", "tpu-miner",
            ]
        self.proc = subprocess.Popen(
            argv,
            cwd=str(REPO),
            env={**os.environ, "BMT_MINER_LOG": "1"},
            stdout=subprocess.DEVNULL,
            stderr=self._log_f,
        )
        self._progress_size = -1
        self._progress_at = time.monotonic()

    def progressing(self, stall_timeout: float) -> bool:
        """True while the miner looks alive: process up and log growing
        within stall_timeout."""
        try:
            size = os.stat(self.log_path).st_size
        except OSError:
            size = 0
        now = time.monotonic()
        if size != self._progress_size:
            self._progress_size = size
            self._progress_at = now
        if self.proc.poll() is not None:
            return False
        return (now - self._progress_at) < stall_timeout

    def restart(self, reason: str = "wedged/dead") -> None:
        self.restarts += 1
        log(f"miner {reason}; restart #{self.restarts}")
        self.kill()
        time.sleep(2.0)  # let the tunnel release the previous client
        self.spawn()

    def kill(self) -> None:
        if self.proc.poll() is None:
            self.proc.send_signal(signal.SIGKILL)
            try:
                self.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                pass
        self._log_f.close()


def run_job(
    client, keeper: MinerKeeper, data: str, max_nonce: int, deadline: float,
    stall: float, lower: int = 0, kill_after: float = 0.0,
) -> dict:
    """Submit one Request; wait for the Result with the keeper watching the
    miner.  Validates the Result against the hashlib per-nonce oracle.

    ``kill_after`` > 0: SIGKILL the miner that many seconds into the job
    and respawn it — the fault-injection leg of the kill drill; the
    scheduler's dead-conn reassignment must carry the job to the same
    answer."""
    t0 = time.monotonic()
    client.write(Message.request(data, lower, max_nonce).marshal())
    box: list = []

    def _read() -> None:
        try:
            box.append(client.read())
        except BaseException as e:
            box.append(e)

    reader = threading.Thread(target=_read, daemon=True)
    reader.start()
    kill_fired = False
    while reader.is_alive():
        armed = kill_after > 0.0 and not kill_fired
        reader.join(timeout=0.5 if armed else 5.0)
        if (
            armed
            and reader.is_alive()  # a post-Result kill proves nothing
            and time.monotonic() - t0 >= kill_after
        ):
            log(f"kill drill: SIGKILL miner at t+{kill_after:.1f}s")
            keeper.restart(reason="kill drill")  # scheduler must reassign
            kill_fired = True
        if reader.is_alive():
            if time.monotonic() - t0 > deadline:
                raise RuntimeError(f"job exceeded {deadline:.0f}s deadline")
            if not keeper.progressing(stall):
                # The scheduler reassigns the dead conn's chunks once the
                # replacement joins — the job continues where it left off.
                keeper.restart()
    out = box[0]
    if isinstance(out, BaseException):
        raise out
    dt = time.monotonic() - t0
    LATENCY.observe(dt)
    msg = Message.unmarshal(out)
    assert msg is not None and msg.type == MsgType.RESULT, out
    # Full-argmin verification of a 2e10 job is beyond any CPU oracle; the
    # scheduler already hashlib-validates every chunk Result, and the
    # kernel tiers are oracle-tested.  Assert the returned pair is at
    # least a real in-range hash of the job.
    assert lower <= msg.nonce <= max_nonce, (msg.nonce, lower, max_nonce)
    assert WORKLOAD.hash_nonce(data, msg.nonce) == msg.hash, (
        msg.hash, msg.nonce, WORKLOAD.name,
    )
    return {
        "wall_s": dt,
        "hash": msg.hash,
        "nonce": msg.nonce,
        "kill_fired": kill_fired,
    }


def _wait_replica(proc: subprocess.Popen, timeout: float) -> None:
    import select

    deadline = time.monotonic() + timeout
    line = ""
    while time.monotonic() < deadline:
        ready, _, _ = select.select([proc.stdout], [], [], 1.0)
        if ready:
            line = proc.stdout.readline()
            if "listening on port" in line:
                return
        if proc.poll() is not None:
            break
    raise RuntimeError(f"replica did not come up (last: {line!r})")


class _FedCell:
    """One subprocess replica + its cpu-miner subprocess."""

    def __init__(self, name, port, fed_port, peers_spec, tmp):
        self.name, self.port, self.fed_port = name, port, fed_port
        argv = [
            sys.executable, "-m", "bitcoin_miner_tpu.apps.federation",
            str(port), f"--cell={name}", f"--fed-port={fed_port}",
            "--gossip-interval=0.3",
        ]
        if peers_spec:
            argv.append(f"--peers={peers_spec}")
        self.proc = subprocess.Popen(
            argv,
            cwd=tmp,
            env={**os.environ, "PYTHONPATH": str(REPO)},
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
            text=True,
        )
        _wait_replica(self.proc, 30)
        self._mlog = open(os.path.join(tmp, f"miner.{name}.log"), "wb")
        self.miner = subprocess.Popen(
            [
                sys.executable, "-m", "bitcoin_miner_tpu.apps.miner",
                f"127.0.0.1:{port}", "--backend", "cpu",
            ],
            cwd=str(REPO),
            stdout=subprocess.DEVNULL,
            stderr=self._mlog,
        )

    def alive(self):
        return self.proc.poll() is None

    def kill(self, miner_only=False):
        if self.miner.poll() is None:
            self.miner.send_signal(signal.SIGKILL)
        if not miner_only and self.proc.poll() is None:
            self.proc.send_signal(signal.SIGKILL)


def _fed_request_once(port, data, lo, hi, deadline_s=30.0):
    """One wire request with a hard deadline (a minerless cell that would
    have to sweep hangs instead of answering — the black-box zero-work
    discriminator the probes rely on)."""
    from bitcoin_miner_tpu import lsp

    try:
        c = lsp.Client("127.0.0.1", port)
    except (lsp.LspError, OSError):
        return None
    got = None
    try:
        c.write(Message.request(data, lo, hi).marshal())
        box: list = []

        def _read() -> None:
            try:
                box.append(c.read())
            except BaseException as e:
                box.append(e)

        rt = threading.Thread(target=_read, daemon=True)
        rt.start()
        rt.join(timeout=deadline_s)
        if box and not isinstance(box[0], BaseException):
            m = Message.unmarshal(box[0])
            if m is not None and m.type == MsgType.RESULT:
                got = (m.hash, m.nonce)
    finally:
        try:
            c.close()
        except lsp.LspError:
            pass
    return got


def _fed_batch(cells, jobs, oracle, clients=6, deadline_s=120.0,
               on_index=None):
    """Spray the jobs across the cells' public ports from ``clients``
    worker threads (round-robin start + failover to the next live cell),
    validating every Result against the oracle.  Returns wall seconds."""
    errors: list = []
    cursor = [0]
    lock = threading.Lock()

    def worker():
        while True:
            with lock:
                if cursor[0] >= len(jobs) or errors:
                    return
                i = cursor[0]
                cursor[0] += 1
            if on_index is not None:
                on_index(i)
            data, lo, hi = jobs[i]
            got = None
            live = [c for c in cells if c.alive()]
            for k in range(len(live)):
                cell = live[(i + k) % len(live)]
                got = _fed_request_once(cell.port, data, lo, hi, deadline_s)
                if got is not None:
                    break
            if got != oracle[(data, lo, hi)]:
                errors.append(f"job {i} ({data},{lo},{hi}): got {got}")

    t0 = time.monotonic()
    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=deadline_s * len(jobs))
    wall = time.monotonic() - t0
    if errors:
        raise RuntimeError("federation batch failed: " + "; ".join(errors[:5]))
    return wall


def run_federation_bench(args) -> int:
    """The federation leg (ISSUE 8), all real subprocesses — N
    ``apps.federation`` replicas each with its own cpu-miner process, so
    (unlike an in-process harness) the cells genuinely compute in
    parallel:

    1. a timed sweep-bound duplicate-heavy batch across N replicas, then
       the SAME batch on a fresh 1-replica federation — the 1→N jobs/s
       scaling number (BENCH_pr8.json);
    2. an untimed drill batch with one whole cell SIGKILLed mid-batch:
       every Result still oracle-bit-exact through the survivors;
    3. black-box zero-work probes: every miner is killed, then a repeat
       of a solved signature must answer at EVERY live replica (cache via
       routing) and a never-issued covered sub-range must answer at a
       NON-home replica's federation port (gossiped spans) — a cell that
       had to sweep would hang its minerless scheduler past the deadline.

    Prints one JSON line."""
    import random

    from bitcoin_miner_tpu.federation.drill import run_all as run_fed_drills
    from bitcoin_miner_tpu.federation.ring import Ring

    min_hash_range = WORKLOAD.min_range

    # Resilience legs first (ISSUE 12), in-process so counters are
    # readable: shed-storm (zero false-death markings), drain-handoff
    # (successor resumes from stashed progress, strictly fewer nonces
    # swept than from-scratch), death-detect (SIGKILL-shaped silence
    # declared dead with no forward-path connect timeout spent), and
    # ack-retransmit (partition heals via ack-gap retransmit before any
    # full sync).  Each failing drill replays from its seed with
    # `python tools/chaos_replay.py --fed-drill NAME --seed N`.
    resilience = run_fed_drills(seed=args.chaos_seed)
    for rep in resilience:
        log(f"resilience drill {rep['name']}: "
            f"{'ok' if rep['ok'] else 'FAILED'} {rep}")
    bad = [rep["name"] for rep in resilience if not rep["ok"]]
    if bad:
        raise RuntimeError(
            f"federation resilience drill(s) failed: {', '.join(bad)} "
            f"(replay: python tools/chaos_replay.py --fed-drill <name> "
            f"--seed {args.chaos_seed})"
        )

    n = max(2, args.federation)
    names = [f"r{i}" for i in range(n)]
    base = args.port or 3000 + (os.getpid() * 7919) % 40000
    tmp = tempfile.mkdtemp(prefix="fed_bench_")
    rng = random.Random(7)
    # Sweep-bound duplicate-heavy jobs: ~1e6-nonce ranges keep each cpu
    # miner busy seconds per distinct signature, so the scaling number
    # measures the cells' parallel sweep capacity, not client overhead.
    n_jobs, max_nonce = 36, 1_000_000
    issued: list = []
    jobs: list = []
    for _ in range(n_jobs):
        if issued and rng.random() < 0.35:
            jobs.append(rng.choice(issued))
        else:
            sig = (f"fed{len(issued)}", 0,
                   rng.randint(max_nonce // 2, max_nonce))
            issued.append(sig)
            jobs.append(sig)
    oracle = {s: min_hash_range(s[0], s[1], s[2]) for s in set(jobs)}
    log(f"workload: {len(jobs)} jobs, {len(issued)} distinct, "
        f"max_nonce {max_nonce}")

    cells: list = []
    single: list = []
    try:
        for i, name in enumerate(names):
            peers = ",".join(
                f"{o}=127.0.0.1:{base + 2 * j + 1}"
                for j, o in enumerate(names) if o != name
            )
            cells.append(
                _FedCell(name, base + 2 * i, base + 2 * i + 1, peers, tmp)
            )
        log(f"federation up: {n} replicas on {[c.port for c in cells]}")
        wall_n = _fed_batch(cells, jobs, oracle)
        rate_n = len(jobs) / wall_n
        log(f"{n}-replica leg: {rate_n:.2f} jobs/s over {wall_n:.2f}s")

        # The 1-replica comparison: a fresh single cell, fresh data keys
        # (same shapes) so nothing is pre-solved.
        sjobs = [(f"s{d[3:]}" if d.startswith("fed") else d, lo, hi)
                 for d, lo, hi in jobs]
        soracle = {s: min_hash_range(s[0], s[1], s[2]) for s in set(sjobs)}
        single.append(_FedCell("solo", base + 100, base + 101, "", tmp))
        wall_1 = _fed_batch(single, sjobs, soracle)
        rate_1 = len(sjobs) / wall_1
        log(f"1-replica leg: {rate_1:.2f} jobs/s over {wall_1:.2f}s "
            f"(scaling {rate_n / rate_1:.2f}x)")

        # Cell-kill drill: fresh keys, one whole cell SIGKILLed mid-batch.
        ring = Ring(names)
        wide = max(jobs, key=lambda s: s[2] - s[1])
        probe_name = next(nm for nm in names if nm != ring.home(wide[0]))
        victim = next(nm for nm in names if nm != probe_name)
        vcell = next(c for c in cells if c.name == victim)
        djobs = [(f"k{i}", 0, 400_000) for i in range(6)]
        doracle = {s: min_hash_range(s[0], s[1], s[2]) for s in set(djobs)}
        kill_at = len(djobs) // 2
        fired = [False]

        def maybe_kill(i):
            if i >= kill_at and not fired[0]:
                fired[0] = True
                log(f"cell-kill drill: SIGKILL cell {victim} mid-batch")
                vcell.kill()

        _fed_batch(cells, djobs, doracle, on_index=maybe_kill)
        log(f"cell-kill drill: all {len(djobs)} Results bit-exact "
            f"through the survivors")

        # Zero-work probes: no miner anywhere — an answer now can only
        # come from caches/spans; a sweep would hang past the deadline.
        time.sleep(2.0)  # let gossip full-sync the batch's spans
        for c in cells:
            c.kill(miner_only=True)
        time.sleep(0.5)
        repeat_ok = True
        # A signature homed on a LIVE cell: the probe proves the routing +
        # cache path, not dead-home failover (the drill above covered
        # that); a victim-homed key would burn the deadline on connect
        # timeouts to the killed cell.
        data, lo, hi = next(
            s for s in jobs if ring.home(s[0]) != victim
        )
        for c in cells:
            if not c.alive():
                continue
            got = _fed_request_once(c.port, data, lo, hi, deadline_s=10.0)
            ok = got == oracle[(data, lo, hi)]
            log(f"repeat probe at {c.name} (minerless): {ok}")
            repeat_ok = repeat_ok and ok
        h_star, n_star = oracle[wide]
        probe_cell = next(c for c in cells if c.name == probe_name)
        gossip_ok = None
        if n_star > wide[1] and probe_cell.alive():
            want = min_hash_range(wide[0], n_star, wide[2])
            got = _fed_request_once(
                probe_cell.fed_port, wide[0], n_star, wide[2],
                deadline_s=10.0,
            )
            gossip_ok = got == want
            log(f"gossip probe at {probe_name}'s fed port (minerless): "
                f"got {got}, want {want} -> {gossip_ok}")
        if not repeat_ok or gossip_ok is False:
            raise RuntimeError(
                f"zero-work probes failed: repeat={repeat_ok} "
                f"gossip={gossip_ok}"
            )
        # SIGTERM-drain leg (ISSUE 12): the subprocess handler end to
        # end — a live cell SIGTERM'd must announce the drain (DRAINING
        # broadcast + successor handoff happen inside) and exit 0, not
        # die mid-flight like the SIGKILL drill above.
        sigterm_ok = None
        tcell = next((c for c in cells if c.alive()), None)
        if tcell is not None:
            log(f"SIGTERM-drain leg: draining cell {tcell.name}")
            tcell.proc.send_signal(signal.SIGTERM)
            try:
                tcell.proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                sigterm_ok = False
            else:
                out = tcell.proc.stdout.read() or ""
                sigterm_ok = (
                    tcell.proc.returncode == 0 and "draining" in out
                )
            log(f"SIGTERM-drain leg: exit={tcell.proc.returncode} "
                f"ok={sigterm_ok}")
            if not sigterm_ok:
                raise RuntimeError(
                    f"SIGTERM drain failed on {tcell.name}: "
                    f"exit={tcell.proc.returncode}"
                )
        print(
            json.dumps(
                {
                    "metric": "federation_fleet_jobs_per_sec",
                    "value": round(rate_n, 3),
                    "unit": "jobs/s",
                    "workload": WORKLOAD.name,
                    "replicas": n,
                    # Scaling is bounded by the host: N cells can only
                    # compute in parallel up to the core count.
                    "host_cpus": os.cpu_count(),
                    "jobs": len(jobs),
                    "distinct_signatures": len(issued),
                    "max_nonce": max_nonce,
                    "wall_s": round(wall_n, 3),
                    "single_jobs_per_sec": round(rate_1, 3),
                    "single_wall_s": round(wall_1, 3),
                    "scaling_vs_single": round(rate_n / rate_1, 3),
                    "cell_killed_mid_batch": victim,
                    "kill_drill_bit_exact": True,
                    "repeat_zero_work_all_replicas": repeat_ok,
                    "cross_replica_zero_work_probe": gossip_ok,
                    "sigterm_drain_exit0": sigterm_ok,
                    "resilience": {
                        rep["name"]: {
                            k: v for k, v in rep.items() if k != "name"
                        }
                        for rep in resilience
                    },
                }
            ),
            flush=True,
        )
        return 0
    finally:
        for c in cells + single:
            c.kill()


def run_dispatch_compare(args) -> int:
    """The adaptive-dispatch leg (ISSUE 10): static fixed-size chunking vs
    the 10^k ladder + straggler-tail stealing (+ speculative span prefill)
    on the SAME seeded chaos weather and the same induced straggler.

    An in-process loopback fleet (the chaos-drill substrate): one serve
    loop over a Gateway, ``--dc-miners`` hashlib miner threads of which
    miner-0 is the induced straggler — it computes at ``--dc-slow-rate``
    nonces/s and flat-out wedges every third chunk for ``--dc-wedge-s``
    seconds (the live-but-hung regime the steal scan exists for).  Each
    leg runs the same job batch through 2 client workers, sampling
    ``fleet.utilization`` (busy/live miners) under the event lock; every
    Result is validated against the hashlib oracle.  The adaptive leg
    then runs the zero-chunk probes: an exact repeat (cache), a solved
    sub-range (spans), and — after the idle fleet speculatively extends
    the hot key — an overlapping query past the originally requested
    range (prefill).  Prints one JSON line (the BENCH_pr10 artifact)."""
    import threading

    from bitcoin_miner_tpu import lsp
    from bitcoin_miner_tpu.apps import client as client_mod
    from bitcoin_miner_tpu.apps import miner as miner_mod
    from bitcoin_miner_tpu.apps import server as server_mod
    from bitcoin_miner_tpu.apps.scheduler import Scheduler
    from bitcoin_miner_tpu.gateway import Gateway, SpanStore
    from bitcoin_miner_tpu.lspnet.chaos import CHAOS, standard_scenarios
    from bitcoin_miner_tpu.utils import sanitize
    from bitcoin_miner_tpu.utils.metrics import METRICS

    min_hash_range = WORKLOAD.min_range
    # epoch_limit 10: burst loss must degrade the wire, not roll dice on
    # WHICH miner gets disconnected — a leg that happens to lose its
    # straggler for half the batch measures luck, not dispatch policy.
    params = lsp.Params(10, 100, 5)
    n_jobs, job_nonces = args.dc_jobs, args.dc_nonces
    n_miners = args.dc_miners

    def leg(tag: str, adaptive: bool) -> dict:
        CHAOS.reset()
        if args.chaos:
            CHAOS.seed(args.chaos_seed)
            CHAOS.run(
                standard_scenarios(params.epoch_seconds)[args.chaos],
                loop_every=args.chaos_loop,
            )
        before = METRICS.snapshot()
        server = lsp.Server(0, params, label="server")
        # Both legs share the straggler-re-queue policy (factor 4, floor
        # 1 s) and the same upper chunk envelope: what differs is ONLY the
        # dispatch plane under test.  The rate-based re-queue deadline is
        # exactly as tight as chunk sizing lets it be — a right-sized
        # 0.2 s straggler chunk times out in ~1 s, a fixed 4 s chunk not
        # for 16 s — which is the point of the ladder.
        if adaptive:
            sched = Scheduler(
                min_chunk=500,
                max_chunk=args.dc_static_chunk,
                target_chunk_seconds=args.dc_target_s,
                straggler_min_seconds=1.0,
                steal_factor=2.0,
                steal_min_seconds=0.6,
            )
        else:
            sched = Scheduler(
                min_chunk=args.dc_static_chunk,
                max_chunk=args.dc_static_chunk,
                adaptive_chunks=False,
                steal_factor=0.0,
                straggler_min_seconds=1.0,
            )
        gw = Gateway(
            sched, rate=None, spans=SpanStore(),
            prefill=args.dc_prefill if adaptive else 0,
            # Speculate only after a full second of continuous idleness:
            # inter-job gaps in the sequential batch are not idleness.
            prefill_idle_s=1.0,
        )
        lock = sanitize.make_lock(f"dispatch-compare.{tag}")
        threading.Thread(
            target=server_mod.serve,
            args=(server, gw),
            kwargs={"tick_interval": 0.1, "lock": lock},
            daemon=True,
        ).start()
        stop = threading.Event()

        def make_search(slow: bool):
            # The induced straggler sweeps at dc_slow_rate nonces/s and,
            # every dc_wedge_every_s seconds of wall time, its NEXT chunk
            # wedges flat for dc_wedge_s (a stuck-runtime episode).  Time-
            # based cadence: a per-chunk cadence would wedge more often
            # the smaller its chunks, punishing the leg that sizes a slow
            # miner down — the opposite of how real runtimes fail.  The
            # cadence clock starts at the FIRST SERVED CHUNK, not at
            # process setup: the straggler must first complete an honest
            # slow chunk so the scheduler learns its rate — the regime
            # under test is a known-slow miner whose fixed-size chunk
            # rides under the rate-aware re-queue deadline (4x expected),
            # not a cold miner the 1 s floor quarantines instantly.
            state = {"wedge_at": None}

            def search(d, lo, hi):
                if slow:
                    now = time.monotonic()
                    if state["wedge_at"] is None:
                        state["wedge_at"] = now + args.dc_wedge_every_s
                    if now >= state["wedge_at"]:
                        state["wedge_at"] = now + args.dc_wedge_every_s
                        time.sleep(args.dc_wedge_s)  # live-but-hung chunk
                    else:
                        time.sleep((hi - lo + 1) / args.dc_slow_rate)
                return min_hash_range(d, lo, hi)

            return search

        for i in range(n_miners):
            threading.Thread(
                target=miner_mod.run_miner_resilient,
                args=("127.0.0.1", server.port, make_search(i == 0)),
                kwargs={"params": params, "max_retries": 12,
                        "backoff_base": 0.05, "backoff_cap": 0.5,
                        "label": f"miner-{i}", "stop": stop},
                daemon=True,
            ).start()
        util: list = []
        sampling = threading.Event()

        def sampler() -> None:
            while not stop.is_set():
                if sampling.is_set():
                    with lock:
                        st = gw.stats()
                    # Only while a real request is in flight: inter-job
                    # wire gaps would otherwise penalize the FASTER leg
                    # (same wall-clock gap over a shorter wall).
                    if st["miners"] and st["gw_inflight"]:
                        util.append(
                            (st["miners"] - st["idle_miners"]) / st["miners"]
                        )
                time.sleep(0.05)

        threading.Thread(target=sampler, daemon=True).start()
        try:
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                with lock:
                    if gw.stats()["miners"] == n_miners:
                        break
                time.sleep(0.05)
            else:
                raise RuntimeError(f"{tag}: miners never joined")

            jobs = [(f"dc-{tag}-{i}", job_nonces - 1) for i in range(n_jobs)]
            results: dict = {}
            cursor = [0]
            qlock = threading.Lock()

            def worker(w: int) -> None:
                while True:
                    with qlock:
                        if cursor[0] >= len(jobs):
                            return
                        i = cursor[0]
                        cursor[0] += 1
                    data, mx = jobs[i]
                    results[data] = client_mod.request_with_retry(
                        "127.0.0.1", server.port, data, mx,
                        retries=8, backoff_base=0.1, params=params,
                        label=f"client-{tag}-{w}",
                    )

            sampling.set()
            t0 = time.monotonic()
            workers = [
                threading.Thread(target=worker, args=(w,), daemon=True)
                for w in range(max(1, args.dc_clients))
            ]
            for t in workers:
                t.start()
            for t in workers:
                t.join(timeout=args.dc_deadline)
            wall = time.monotonic() - t0
            sampling.clear()
            if any(t.is_alive() for t in workers):
                raise RuntimeError(f"{tag}: batch exceeded {args.dc_deadline}s")
            for data, mx in jobs:
                want = min_hash_range(data, 0, mx)
                if results.get(data) != want:
                    raise RuntimeError(
                        f"{tag}: {data} got {results.get(data)}, want {want}"
                    )
            out = {
                "wall_s": round(wall, 3),
                "jobs_per_sec": round(n_jobs / wall, 3),
                "utilization_mean": round(
                    sum(util) / len(util), 3) if util else None,
            }

            if adaptive:
                out.update(_dispatch_probes(
                    gw, lock, server.port, params, jobs[0][0], job_nonces,
                    args, min_hash_range,
                ))
        finally:
            stop.set()
            CHAOS.reset()
            server.close()
        after = METRICS.snapshot()
        for k in ("sched.steals", "sched.chunk_size_adapt",
                  "sched.prefill_chunks", "sched.chunks_straggler_requeued",
                  "gateway.prefill_jobs", "gateway.prefill_preempted"):
            delta = after.get(k, 0) - before.get(k, 0)
            if delta or adaptive:
                out[k] = delta
        return out

    def _dispatch_probes(
        gw, lock, port, params, hot_data, job_nonces, args, min_hash_range
    ) -> dict:
        """Zero-chunk probes on the adaptive leg's live fleet: exact
        repeat (cache), solved sub-range (spans — also marks the key hot),
        then a query overlapping the speculative extension the idle fleet
        prefilled past the hot span."""
        probes: dict = {}

        def zero_chunk_request(mx: int):
            # Real (non-speculative) chunks only: the idle fleet may keep
            # prefilling between probes, and those chunks are exactly the
            # point — they must not read as the probe having swept.
            def real_chunks() -> int:
                return (
                    METRICS.get("sched.chunks_assigned")
                    - METRICS.get("sched.prefill_chunks")
                )

            before = real_chunks()
            got = client_mod.request_with_retry(
                "127.0.0.1", port, hot_data, mx,
                retries=5, backoff_base=0.1, params=params,
                label="client-probe",
            )
            return real_chunks() == before, got

        zero, got = zero_chunk_request(job_nonces - 1)
        probes["repeat_zero_chunks"] = (
            zero and got == min_hash_range(hot_data, 0, job_nonces - 1)
        )
        sub_hi = job_nonces // 2 - 1
        zero, got = zero_chunk_request(sub_hi)
        probes["subrange_zero_chunks"] = (
            zero and got == min_hash_range(hot_data, 0, sub_hi)
        )
        # Idle fleet: the serve ticker's gateway tick speculates past the
        # hot span.  Wait until the extension's sweep lands in the store.
        deadline = time.monotonic() + args.dc_deadline
        ext_hi = job_nonces + args.dc_prefill // 2 - 1
        covered = False
        while time.monotonic() < deadline:
            with lock:
                _best, gaps = gw.spans.cover(hot_data, 0, ext_hi)
            if not gaps:
                covered = True
                break
            time.sleep(0.1)
        probes["prefill_covered"] = covered
        if covered:
            zero, got = zero_chunk_request(ext_hi)
            probes["prefill_zero_chunks"] = (
                zero and got == min_hash_range(hot_data, 0, ext_hi)
            )
        else:
            probes["prefill_zero_chunks"] = False
        return probes

    static = leg("static", adaptive=False)
    adaptive = leg("adaptive", adaptive=True)
    speedup = (
        adaptive["jobs_per_sec"] / static["jobs_per_sec"]
        if static["jobs_per_sec"] else None
    )
    log(f"static:   {static}")
    log(f"adaptive: {adaptive}")
    log(f"speedup: {speedup:.2f}x")
    print(
        json.dumps(
            {
                "metric": "dispatch_adaptive_speedup",
                "value": round(speedup, 3),
                "unit": "x vs static chunking",
                "workload": WORKLOAD.name,
                "jobs": n_jobs,
                "job_nonces": job_nonces,
                "miners": n_miners,
                "induced_straggler": {
                    "slow_rate_nps": args.dc_slow_rate,
                    "wedge_every_s": args.dc_wedge_every_s,
                    "wedge_s": args.dc_wedge_s,
                },
                **(
                    {
                        "chaos": {
                            "scenario": args.chaos,
                            "seed": args.chaos_seed,
                            "loop_s": args.chaos_loop,
                        }
                    }
                    if args.chaos
                    else {}
                ),
                "static": static,
                "adaptive": adaptive,
                "utilization_gain": (
                    round(
                        adaptive["utilization_mean"]
                        - static["utilization_mean"], 3,
                    )
                    if adaptive.get("utilization_mean") is not None
                    and static.get("utilization_mean") is not None
                    else None
                ),
            }
        ),
        flush=True,
    )
    return 0


def run_depth_compare(args) -> int:
    """The --adaptive-depth arbitration leg (ISSUE 15 satellite, ROADMAP
    PR-14 follow-on d): the SAME job batch through the same in-process
    fleet twice — static 2-deep assignment windows vs ``adaptive_depth``
    re-sizing off the observed ``hist.device_dispatch_s`` p50 — with the
    miners on a SIEVE-ENABLED jax pipeline (``SweepPipeline(backend=
    "xla", sieve=True)``), since threshold freshness under shallow
    windows is the effect being arbitrated.  Prints one JSON line with
    the same-seed pair; the default only flips if the adaptive leg wins
    it."""
    import threading

    from bitcoin_miner_tpu import lsp
    from bitcoin_miner_tpu.apps import client as client_mod
    from bitcoin_miner_tpu.apps import miner as miner_mod
    from bitcoin_miner_tpu.apps import server as server_mod
    from bitcoin_miner_tpu.apps.scheduler import Scheduler
    from bitcoin_miner_tpu.gateway import Gateway, SpanStore
    from bitcoin_miner_tpu.utils import sanitize
    from bitcoin_miner_tpu.utils.metrics import METRICS

    min_hash_range = WORKLOAD.min_range
    params = lsp.Params(10, 200, 5)

    class _SievePipelineSearch:
        """The miner's async search on the sieve-enabled jax tier: the
        depth window under test gates how stale each dispatch's enqueued
        sieve threshold is."""

        def __init__(self) -> None:
            from concurrent.futures import Future

            from bitcoin_miner_tpu.ops.sweep import SweepPipeline

            self._Future = Future
            self._p = SweepPipeline(backend="xla", sieve=True)

        def submit(self, data, lower, upper):
            out = self._Future()

            def _done(src) -> None:
                e = src.exception()
                if e is not None:
                    out.set_exception(e)
                else:
                    r = src.result()
                    out.set_result((r.hash, r.nonce))

            self._p.submit(data, lower, upper).add_done_callback(_done)
            return out

        def close(self) -> None:
            self._p.close()

    def leg(tag: str, adaptive: bool) -> dict:
        # Fresh registry per leg: hist.device_dispatch_s is cumulative
        # (histograms have no delta view), and the adaptive leg must make
        # its depth decisions from ITS OWN cold-start samples — a warm
        # cross-leg p50 is evidence a cold production server never gets,
        # and the stamped per-leg dispatch_p50_s would otherwise mix legs.
        METRICS.reset()
        before = METRICS.snapshot()
        server = lsp.Server(0, params, label="server")
        sched = Scheduler(adaptive_depth=adaptive)
        gw = Gateway(sched, rate=None, spans=SpanStore())
        lock = sanitize.make_lock(f"depth-compare.{tag}")
        threading.Thread(
            target=server_mod.serve,
            args=(server, gw),
            kwargs={"tick_interval": 0.1, "lock": lock},
            daemon=True,
        ).start()
        searches = [_SievePipelineSearch() for _ in range(args.dp_miners)]
        for i, s in enumerate(searches):
            mc = lsp.Client("127.0.0.1", server.port, params,
                            label=f"miner-{i}")
            threading.Thread(
                target=miner_mod.run_miner, args=(mc, s), daemon=True
            ).start()
        try:
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                with lock:
                    if gw.stats()["miners"] == args.dp_miners:
                        break
                time.sleep(0.05)
            else:
                raise RuntimeError(f"{tag}: miners never joined")
            jobs = [
                (f"depth-{tag}-{i}", args.dp_nonces - 1)
                for i in range(args.dp_jobs)
            ]
            results: dict = {}
            cursor = [0]
            qlock = threading.Lock()

            def worker(w: int) -> None:
                while True:
                    with qlock:
                        if cursor[0] >= len(jobs):
                            return
                        i = cursor[0]
                        cursor[0] += 1
                    data, mx = jobs[i]
                    results[data] = client_mod.request_with_retry(
                        "127.0.0.1", server.port, data, mx,
                        retries=4, backoff_base=0.1, params=params,
                        label=f"client-{tag}-{w}",
                    )

            t0 = time.monotonic()
            workers = [
                threading.Thread(target=worker, args=(w,), daemon=True)
                for w in range(max(1, args.dp_clients))
            ]
            for t in workers:
                t.start()
            for t in workers:
                t.join(timeout=args.dp_deadline)
            wall = time.monotonic() - t0
            if any(t.is_alive() for t in workers):
                raise RuntimeError(f"{tag}: batch exceeded {args.dp_deadline}s")
            for data, mx in jobs:
                want = min_hash_range(data, 0, mx)
                if results.get(data) != want:
                    raise RuntimeError(
                        f"{tag}: {data} got {results.get(data)}, want {want}"
                    )
        finally:
            server.close()
            # Tear the leg's device pipelines down HERE, not on the
            # miners' epoch-loss schedule (~2 s after the close): the
            # next leg must not share wall time with this leg's pipeline
            # worker threads (the same cross-leg hygiene as the METRICS
            # reset above).  SweepPipeline.close is idempotent with the
            # miner loop's own close-on-exit.
            for s in searches:
                try:
                    s.close()
                except Exception:
                    pass
            time.sleep(2.5)  # epoch-loss window: miner threads fully exit
        after = METRICS.snapshot()
        h = METRICS.histogram("hist.device_dispatch_s")
        snap = h.snapshot() if h is not None else {}
        return {
            "wall_s": round(wall, 3),
            "jobs_per_sec": round(args.dp_jobs / wall, 3),
            "depth_adapts": after.get("sched.depth_adapt", 0)
            - before.get("sched.depth_adapt", 0),
            "dispatch_p50_s": round(snap.get("p50", 0.0), 6),
        }

    static = leg("static", adaptive=False)
    adaptive = leg("adaptive", adaptive=True)
    speedup = (
        round(adaptive["jobs_per_sec"] / static["jobs_per_sec"], 3)
        if static["jobs_per_sec"] else None
    )
    log(f"static:   {static}")
    log(f"adaptive: {adaptive}")
    log(f"speedup: {speedup}x")
    print(
        json.dumps(
            {
                "metric": "adaptive_depth_speedup",
                "value": speedup,
                "unit": "x vs static 2-deep windows",
                "workload": WORKLOAD.name,
                "backend": "xla",
                "sieve": True,
                "jobs": args.dp_jobs,
                "job_nonces": args.dp_nonces,
                "miners": args.dp_miners,
                "static": static,
                "adaptive": adaptive,
            }
        ),
        flush=True,
    )
    return 0


def run_mixed_fleet(args) -> int:
    """The --mixed-fleet heterogeneous-tier proving leg (ISSUE 20).

    One in-process fleet, one job stream, miners on DIFFERENT kernel
    tiers of the same workload's ladder — the device (xla) rung next to
    the cpu and hashlib host rungs, the shape a real mixed fleet has
    when only some hosts carry accelerators.  Defaults to the
    ``blake2b64`` workload (the second kernel family this leg proves;
    any workload whose ladder spans a jax tier + host tiers works via
    ``--workload``).

    What it proves, all stamped into one JSON line:

    - **bit-exact**: a small job is checked against the workload's
      hashlib oracle exactly, and the big timed job against the device
      kernel's own sweep — heterogeneous min-folding changes nothing;
    - **chunk sizes diverge**: the scheduler's per-miner EWMA chunking
      sizes each tier's chunks to its measured rate — the device rung's
      ``mean_chunk_nonces`` strictly above every host rung's;
    - **no slow-rung drag**: the per-tier split of the miner-side chunk
      wall time (the per-tier view of ``hist.miner_chunk_s``) stays in
      one band across tiers — a hashlib rung 5-6x slower per nonce gets
      proportionally smaller chunks, not proportionally longer stalls,
      so the fleet's chunk p50 is not set by its slowest rung.
    """
    import statistics
    import threading

    from bitcoin_miner_tpu import lsp
    from bitcoin_miner_tpu.apps import client as client_mod
    from bitcoin_miner_tpu.apps import miner as miner_mod
    from bitcoin_miner_tpu.apps import server as server_mod
    from bitcoin_miner_tpu.apps.scheduler import Scheduler
    from bitcoin_miner_tpu.gateway import Gateway, SpanStore
    from bitcoin_miner_tpu.ops.sweep import sweep_min_hash
    from bitcoin_miner_tpu.utils import sanitize

    if args.workload or os.environ.get("BMT_WORKLOAD"):
        wl = WORKLOAD
    else:
        wl = workloads_mod.resolve("blake2b64")
        log("mixed-fleet: defaulting to the blake2b64 workload ladder")
    tiers = [t for t in ("xla", "cpu", "hashlib") if t in wl.tiers]
    if len(tiers) < 2 or "xla" not in tiers:
        raise SystemExit(
            f"--mixed-fleet needs a workload whose ladder spans the xla "
            f"tier and a host tier; {wl.name!r} has {'->'.join(wl.tiers)}"
        )
    target_s = 0.3

    class _TierTimer:
        """Per-tier chunk accounting around an async search: the same
        submit→resolve wall time the miner observes into
        ``hist.miner_chunk_s``, split by tier (and with the chunk SIZE
        kept, which the process-global histogram cannot carry)."""

        def __init__(self, inner, rec) -> None:
            self._inner, self._rec = inner, rec

        def submit(self, data, lower, upper):
            t0 = time.monotonic()
            fut = self._inner.submit(data, lower, upper)

            def _done(f) -> None:
                if not f.cancelled() and f.exception() is None:
                    self._rec.append(
                        (upper - lower + 1, time.monotonic() - t0)
                    )

            fut.add_done_callback(_done)
            return fut

        def prewarm(self, data, upper) -> None:
            p = getattr(self._inner, "prewarm", None)
            if p is not None:
                p(data, upper)

        def close(self) -> None:
            self._inner.close()

    params = lsp.Params(10, 200, 5)
    server = lsp.Server(0, params, label="server")
    sched = Scheduler(
        workload=workloads_mod.resolve_nondefault(wl),
        target_chunk_seconds=target_s,
    )
    gw = Gateway(sched, rate=None, spans=SpanStore())
    lock = sanitize.make_lock("mixed-fleet")
    threading.Thread(
        target=server_mod.serve,
        args=(server, gw),
        kwargs={"tick_interval": 0.1, "lock": lock},
        daemon=True,
    ).start()
    recs = {t: [] for t in tiers}
    searches = [_TierTimer(wl.make_async_search(t), recs[t]) for t in tiers]
    try:
        for i, (t, s) in enumerate(zip(tiers, searches)):
            mc = lsp.Client(
                "127.0.0.1", server.port, params, label=f"miner-{t}"
            )
            threading.Thread(
                target=miner_mod.run_miner, args=(mc, s),
                kwargs={"close_search": False}, daemon=True,
            ).start()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            with lock:
                if gw.stats()["miners"] == len(tiers):
                    break
            time.sleep(0.05)
        else:
            raise RuntimeError("mixed-fleet: miners never joined")

        def job(data: str, mx: int):
            return client_mod.request_with_retry(
                "127.0.0.1", server.port, data, mx,
                retries=4, backoff_base=0.1, params=params,
                label="client-mixed",
            )

        # Distinct data per job — the gateway's span store prefills
        # overlapping ranges of SAME-data jobs from completed spans
        # (correct serving behavior, but here it would quietly shrink
        # the swept portion of the proving job) — at the SAME length,
        # since device kernels are compiled per message length and the
        # warm-up must pay the timed job's compiles.
        data, data_warm, data_oracle = (
            "mixed-fleet/0", "mixed-fleet/1", "mixed-fleet/2",
        )
        # Compile every digit class the jobs touch BEFORE any job runs:
        # kernel factories are lru_cached process-wide and the miners
        # run in this process, so these compiles are exactly the ones
        # the xla miner would otherwise pay mid-job (same contract as
        # the subprocess fleet's class warm-up jobs).  Default
        # host_lane_budget mirrors the pipeline: tiny classes host-route
        # and compile nothing.
        top = args.mf_nonces - 1
        for d in range(6, len(str(top)) + 1):
            hi = min(10**d - 1, top)
            sweep_min_hash(
                data, max(10 ** (d - 1), hi - 50_000 + 1), hi,
                backend="xla", workload=wl,
            )
        # Oracle job: small enough to sweep with the pure-Python oracle,
        # large enough that every tier serves chunks of it.
        oracle_n = args.mf_oracle_nonces
        got = job(data_oracle, oracle_n - 1)
        want = wl.min_range(data_oracle, 0, oracle_n - 1)
        if tuple(got) != want:
            raise RuntimeError(
                f"mixed-fleet oracle job mismatch: {got} vs {want}"
            )
        log(f"oracle job OK over [0,{oracle_n - 1}]: {got}")
        # Warm-up job: device compiles + per-miner EWMA ramp out of the
        # way before the proving job is timed.
        job(data_warm, args.mf_warmup - 1)
        for rec in recs.values():
            rec.clear()
        log(f"timed job: {args.mf_nonces:.1e} nonces across {tiers}")
        t0 = time.monotonic()
        got = job(data, args.mf_nonces - 1)
        wall = time.monotonic() - t0
        # The device kernel's own single-process sweep (oracle-gated at
        # tier-1 and in the oracle job above) arbitrates the big range.
        r = sweep_min_hash(
            data, 0, args.mf_nonces - 1, backend="xla", workload=wl
        )
        if tuple(got) != (r.hash, r.nonce):
            raise RuntimeError(
                f"mixed-fleet timed job mismatch: {got} vs "
                f"{(r.hash, r.nonce)}"
            )
    finally:
        server.close()
        for s in searches:
            try:
                s.close()
            except Exception:
                pass
        time.sleep(2.5)  # epoch-loss window: miner threads fully exit

    per_tier = {}
    for t in tiers:
        rec = recs[t]
        if not rec:
            raise RuntimeError(
                f"mixed-fleet: the {t} tier served no chunks of the timed "
                "job — nothing heterogeneous was proven"
            )
        sizes = [s for s, _ in rec]
        dts = [dt for _, dt in rec]
        per_tier[t] = {
            "chunks": len(rec),
            "nonces": sum(sizes),
            "mean_chunk_nonces": round(sum(sizes) / len(rec)),
            "miner_chunk_p50_s": round(statistics.median(dts), 4),
        }
        log(f"tier {t}: {per_tier[t]}")
    # Chunk sizes must DIVERGE: the EWMA sized the device rung's chunks
    # strictly larger than the oracle rung's.  The scheduler's size
    # ladder is decade-quantized, so adjacent tiers within ~3x of each
    # other (cpu vs either neighbor, under this process's GIL
    # contention) may legitimately share a rung — the robust
    # heterogeneous claim is the ladder's two ENDS a decade apart,
    # asserted strictly, with the middle rung weakly ordered above the
    # bottom; all three means are stamped regardless.
    means = {t: per_tier[t]["mean_chunk_nonces"] for t in tiers}
    bottom = tiers[-1]
    diverged = means["xla"] > means[bottom] and all(
        means[t] >= means[bottom] for t in tiers
    )
    if not diverged:
        raise RuntimeError(
            f"mixed-fleet: chunk sizes did not diverge down the ladder: "
            f"{means}"
        )
    # No slow-rung drag: every rung's chunk p50 sits in one band — the
    # slow tiers trade chunk SIZE, not chunk LATENCY.  4x the adaptive
    # target (plus ramp slack) is the same slack factor the scheduler's
    # own straggler detector uses.
    p50s = {t: per_tier[t]["miner_chunk_p50_s"] for t in tiers}
    drag = max(p50s.values()) > 4.0 * target_s
    if drag:
        raise RuntimeError(
            f"mixed-fleet: slow-rung drag — per-tier chunk p50 {p50s} "
            f"vs target {target_s}s"
        )
    rate = args.mf_nonces / wall
    print(
        json.dumps(
            {
                "metric": "mixed_fleet_nonces_per_sec",
                "value": round(rate),
                "unit": "nonces/s",
                "workload": wl.name,
                "tiers": tiers,
                "nonces": args.mf_nonces,
                "wall_s": round(wall, 3),
                "oracle_job_nonces": oracle_n,
                "bitexact": True,
                "chunk_sizes_diverged": True,
                "slow_rung_drag": False,
                "target_chunk_seconds": target_s,
                "per_tier": per_tier,
            }
        ),
        flush=True,
    )
    return 0


def run_autoscale_bench(args) -> int:
    """The self-scaling capacity plane leg (ISSUE 18): the SAME seeded
    open-loop Poisson arrival schedule — a warm phase, then a ramp past
    one worker's capacity — against a fixed 1-worker fleet and against
    the autoscale controller closing the loop from SLO burn alerts to
    worker spawns.  Asserts the whole causal chain on the autoscaled
    leg: the request-latency burn alert FIRES, the controller SCALES UP
    (within its hold/cooldown discipline), p99 recovers vs the fixed
    leg, and after the ramp the controller CLEAN-DRAINS back to the
    floor — every retired worker exits 0 (SIGTERM drain, not SIGKILL)
    and every answer is bit-exact on the oracle.

    Workers are real ``apps.miner`` subprocesses spawned/retired by the
    controller's own :class:`ProcessActuator`, throttled to
    ``--as-throttle-nps`` (BMT_MINER_THROTTLE_NPS) so each worker is one
    deterministic unit of capacity — the box has one core, so UNPACED
    cpu workers would all share it and scale-up would add no throughput;
    the pace is stamped into the JSON line (same honesty contract as the
    dispatch leg's induced straggler).  Prints one JSON line (the
    BENCH_pr18 artifact)."""
    import random
    import threading

    from bitcoin_miner_tpu import lsp
    from bitcoin_miner_tpu.apps import client as client_mod
    from bitcoin_miner_tpu.apps import server as server_mod
    from bitcoin_miner_tpu.apps.scheduler import Scheduler
    from bitcoin_miner_tpu.autoscale import (
        AutoscaleConfig, AutoscaleController, ControllerPump,
        ProcessActuator,
    )
    from bitcoin_miner_tpu.gateway import Gateway, SpanStore
    from bitcoin_miner_tpu.utils import sanitize
    from bitcoin_miner_tpu.utils.metrics import METRICS
    from bitcoin_miner_tpu.utils.slo import SloEngine, default_slos
    from bitcoin_miner_tpu.utils.telemetry import TelemetryHub

    min_hash_range = WORKLOAD.min_range
    # Miner-binary default params: the workers are REAL subprocesses the
    # actuator spawns with the frozen CLI, so the in-process server must
    # speak the params they default to.
    params = lsp.Params()
    nonces = args.as_nonces
    throttle = args.as_throttle_nps
    service_s = nonces / throttle  # one job, one worker, no queue
    slo_threshold_s = args.as_slo_threshold_s or round(1.5 * service_s, 3)

    # ONE seeded arrival schedule, shared by both legs: open-loop Poisson
    # at warm_x of one worker's capacity for warm_s seconds, then
    # overload_x (past one worker, under max_workers) for overload_s.
    rng = random.Random(args.as_seed)
    arrivals: list = []
    t = 0.0
    for rate_x, until in (
        (args.as_warm_x, args.as_warm_s),
        (args.as_overload_x, args.as_warm_s + args.as_overload_s),
    ):
        lam = rate_x * throttle / nonces  # jobs/s
        while True:
            t += rng.expovariate(lam)
            if t >= until:
                t = until  # phase boundary: unused tail draw
                break
            arrivals.append(t)
    if len(arrivals) < 4:
        raise RuntimeError(f"degenerate schedule: {len(arrivals)} arrivals")

    def _pct(xs: list, q: float):
        if not xs:
            return None
        s = sorted(xs)
        return round(s[min(len(s) - 1, int(round(q * (len(s) - 1))))], 3)

    tmp = tempfile.mkdtemp(prefix="autoscale_bench_")

    def leg(tag: str, autoscaled: bool) -> dict:
        METRICS.reset()
        server = lsp.Server(0, params, label="server")
        # Chunks a few tenths long so one queued job range-splits across
        # every live worker — scale-up must shorten the in-flight job's
        # tail, not just drain the backlog behind it.
        sched = Scheduler(
            min_chunk=5_000, max_chunk=nonces, target_chunk_seconds=0.4,
        )
        gw = Gateway(sched, rate=None, spans=SpanStore())
        lock = sanitize.make_lock(f"autoscale-bench.{tag}")
        # Burn evidence: the serve ticker drives the hub each beat; the
        # gateway observes hist.request_s in-process, so the request-p95
        # SLO needs no miner exporters.  Windows sized to the leg (6/15 s)
        # with a low burn threshold: the ramp must fire the alert in
        # seconds, not the production default's minutes.
        slo = SloEngine([
            s for s in default_slos(
                request_threshold_s=slo_threshold_s, objective=0.9,
                fast_window_s=6.0, slow_window_s=15.0,
                burn_threshold=2.0, min_events=3,
            ) if s.name == "request-p95"
        ])
        hub = TelemetryHub(0, params=params, slo=slo,
                           publish_interval=0.5).start()
        threading.Thread(
            target=server_mod.serve,
            args=(server, gw),
            kwargs={"tick_interval": 0.1, "lock": lock, "telemetry": hub},
            daemon=True,
        ).start()
        workers = ProcessActuator(
            server.port, backend="cpu", log_dir=tmp,
            extra_env={"BMT_MINER_THROTTLE_NPS": str(throttle)},
        )
        pump = None
        alerts_seen: set = set()
        timeline: list = []
        mon_stop = threading.Event()
        try:
            workers.spawn(1)  # the floor worker both legs start from
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                with lock:
                    if gw.stats()["miners"] >= 1:
                        break
                time.sleep(0.05)
            else:
                raise RuntimeError(f"{tag}: floor worker never joined")

            controller = None
            if autoscaled:
                cfg = AutoscaleConfig(
                    min_workers=1, max_workers=args.as_max_workers, step=1,
                    hold_ticks=args.as_hold,
                    up_cooldown_s=args.as_up_cooldown,
                    down_cooldown_s=args.as_down_cooldown, util_low=0.5,
                )

                def burn():
                    st = hub.last_state() or {}
                    alerts = (st.get("slo") or {}).get("alerts") or None
                    if alerts:
                        alerts_seen.update(alerts)
                    return alerts

                controller = AutoscaleController(
                    workers, burn=burn,
                    utilization=lambda: METRICS.gauges().get(
                        "fleet.utilization"),
                    config=cfg,
                )
                pump = ControllerPump(
                    controller, interval=args.as_interval).start()

            t0 = time.monotonic()

            def monitor() -> None:
                while not mon_stop.wait(1.0):
                    row = {
                        "t": round(time.monotonic() - t0, 1),
                        "live": workers.live(),
                    }
                    if controller is not None:
                        st = controller.status()
                        row["state"] = st["state"]
                        row["target"] = st["target"]
                    timeline.append(row)

            threading.Thread(target=monitor, daemon=True).start()

            latencies: dict = {}
            results: dict = {}
            rec = threading.Lock()

            def fire(i: int, t_arr: float) -> None:
                delay = t0 + t_arr - time.monotonic()
                if delay > 0:
                    time.sleep(delay)
                data = f"as-{tag}-{i}"
                start = time.monotonic()
                got = client_mod.request_with_retry(
                    "127.0.0.1", server.port, data, nonces - 1,
                    retries=8, backoff_base=0.1, params=params,
                    label=f"client-{tag}-{i}",
                )
                with rec:
                    latencies[i] = time.monotonic() - start
                    results[i] = got

            clients = [
                threading.Thread(target=fire, args=(i, t_arr), daemon=True)
                for i, t_arr in enumerate(arrivals)
            ]
            for c in clients:
                c.start()
            batch_deadline = t0 + args.as_deadline
            for c in clients:
                c.join(timeout=max(0.1, batch_deadline - time.monotonic()))
            if any(c.is_alive() for c in clients):
                raise RuntimeError(f"{tag}: batch exceeded "
                                   f"{args.as_deadline}s open-loop deadline")
            wall = time.monotonic() - t0
            for i in range(len(arrivals)):
                want = min_hash_range(f"as-{tag}-{i}", 0, nonces - 1)
                if results.get(i) != want:
                    raise RuntimeError(
                        f"{tag}: job {i} got {results.get(i)}, want {want}")

            out = {
                "wall_s": round(wall, 3),
                "jobs": len(arrivals),
                "p50_s": _pct(list(latencies.values()), 0.50),
                "p95_s": _pct(list(latencies.values()), 0.95),
                "p99_s": _pct(list(latencies.values()), 0.99),
                "workers_peak": max(
                    (r["live"] for r in timeline), default=1),
            }
            if autoscaled:
                # The ramp is over: the controller must now walk the
                # fleet back down to the floor through clean drains.
                drain_deadline = time.monotonic() + (
                    args.as_max_workers
                    * (args.as_down_cooldown + args.as_interval * args.as_hold)
                    + 30.0
                )
                while time.monotonic() < drain_deadline:
                    # live() drops at SIGTERM; the None codes clear when
                    # the drained workers finish their in-flight chunks
                    # and actually exit — wait for both.
                    if (workers.live() == 1
                            and None not in workers.exit_codes()):
                        break
                    time.sleep(0.2)
                else:
                    raise RuntimeError(
                        f"{tag}: never drained back to the floor "
                        f"(live={workers.live()}, "
                        f"codes={workers.exit_codes()})")
                codes = workers.exit_codes()
                if any(c != 0 for c in codes):
                    raise RuntimeError(
                        f"{tag}: non-clean worker exits {codes} "
                        "(0 = SIGTERM drain finished its chunks)")
                if not alerts_seen:
                    raise RuntimeError(f"{tag}: burn alert never fired")
                scale_ups = METRICS.get("autoscale.scale_ups")
                scale_downs = METRICS.get("autoscale.scale_downs")
                if not scale_ups or not scale_downs:
                    raise RuntimeError(
                        f"{tag}: controller never closed the loop "
                        f"(ups={scale_ups} downs={scale_downs})")
                out.update({
                    "alerts_fired": sorted(alerts_seen),
                    "scale_ups": scale_ups,
                    "scale_downs": scale_downs,
                    "actions_suppressed": METRICS.get(
                        "autoscale.actions_suppressed"),
                    "reweights": METRICS.get("autoscale.reweights"),
                    "actuator_failures": METRICS.get(
                        "autoscale.actuator_failures"),
                    "drained_exit_codes": codes,
                    "end_live": workers.live(),
                    "timeline": timeline,
                })
            return out
        finally:
            mon_stop.set()
            if pump is not None:
                pump.stop()
            workers.stop_all()
            hub.close()
            server.close()

    fixed = leg("fixed", autoscaled=False)
    autoscaled = leg("auto", autoscaled=True)
    if autoscaled["p99_s"] >= fixed["p99_s"]:
        raise RuntimeError(
            f"autoscaling did not recover p99: {autoscaled['p99_s']}s vs "
            f"fixed {fixed['p99_s']}s")
    speedup = round(fixed["p99_s"] / autoscaled["p99_s"], 3)
    log(f"fixed:      {fixed}")
    log(f"autoscaled: {autoscaled}")
    log(f"p99 speedup: {speedup}x")
    print(
        json.dumps(
            {
                "metric": "autoscale_p99_speedup",
                "value": speedup,
                "unit": "x p99 latency vs fixed 1-worker fleet, same "
                        "seeded arrival schedule",
                "workload": WORKLOAD.name,
                "job_nonces": nonces,
                "worker_throttle_nps": throttle,
                "slo_threshold_s": slo_threshold_s,
                "schedule": {
                    "seed": args.as_seed,
                    "warm_s": args.as_warm_s,
                    "warm_x": args.as_warm_x,
                    "overload_s": args.as_overload_s,
                    "overload_x": args.as_overload_x,
                    "arrivals": len(arrivals),
                },
                "controller": {
                    "min_workers": 1,
                    "max_workers": args.as_max_workers,
                    "step": 1,
                    "hold_ticks": args.as_hold,
                    "up_cooldown_s": args.as_up_cooldown,
                    "down_cooldown_s": args.as_down_cooldown,
                    "util_low": 0.5,
                    "interval_s": args.as_interval,
                },
                "fixed": fixed,
                "autoscaled": autoscaled,
            }
        ),
        flush=True,
    )
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nonces", type=int, default=2 * 10**10)
    ap.add_argument("--warmup", type=int, default=4 * 10**9)
    ap.add_argument(
        "--backend", default="auto", choices=["auto", "pallas", "xla", "cpu"]
    )
    ap.add_argument(
        "--kernel-rate",
        type=float,
        default=1.947e9,
        help="single-chip kernel rate to compare against (BENCH_r05)",
    )
    ap.add_argument(
        "--cpu-miners",
        type=int,
        default=0,
        help="spawn this many additional native C++ (--backend cpu) miners "
        "alongside the main miner — the heterogeneous fleet of "
        "BASELINE.json:9 on real hardware; the scheduler range-splits "
        "across all workers and min-folds their Results",
    )
    ap.add_argument(
        "--kill-drill",
        action="store_true",
        help="after the timed job, run one job clean and the same job with "
        "a mid-job miner SIGKILL+respawn; assert both return the identical "
        "(hash, nonce) — the scheduler's reassignment invariant on the "
        "real fleet",
    )
    ap.add_argument("--drill-nonces", type=int, default=6 * 10**9)
    ap.add_argument(
        "--chaos",
        metavar="SCENARIO",
        default=None,
        help="apply this named seeded lspnet.chaos schedule in the SERVER "
        "process for the whole run (looped every --chaos-loop seconds so "
        "it stays active through the timed job) and report degraded-"
        "network throughput; names: lspnet.standard_scenarios()",
    )
    ap.add_argument("--chaos-seed", type=int, default=1)
    ap.add_argument(
        "--chaos-loop",
        type=float,
        default=10.0,
        help="replay period for the --chaos scenario (seconds)",
    )
    ap.add_argument(
        "--workload",
        default=None,
        metavar="NAME",
        help="registered range-fold workload to bench (ISSUE 9); exported "
        "as BMT_WORKLOAD to the server/miner/federation subprocesses so "
        "the whole fleet serves one hash family; default: the frozen "
        "sha256d contract",
    )
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--timeout", type=float, default=600.0)
    ap.add_argument(
        "--stall",
        type=float,
        default=90.0,
        help="restart the miner if its chunk log stalls this many seconds",
    )
    ap.add_argument(
        "--miner-log",
        metavar="FILE",
        default=None,
        help="path for the miner's chunk-timing stderr log (default: temp)",
    )
    ap.add_argument(
        "--trace",
        metavar="FILE",
        default=None,
        help="arm the server's structured event log (BMT_TRACE) and write "
        "it here; analyze with python -m tools.trace",
    )
    ap.add_argument(
        "--telemetry",
        action="store_true",
        help="arm the fleet metrics plane (ISSUE 7): the server opens a "
        "telemetry sidecar port + SLO engine, every miner exports "
        "snapshots to it, and the fleet-merged histograms + SLO verdicts "
        "are stamped into the JSON line (watch live: python -m tools.dash "
        "--connect)",
    )
    ap.add_argument(
        "--dispatch-compare",
        action="store_true",
        help="adaptive-dispatch leg (ISSUE 10): static fixed chunking vs "
        "the 10^k ladder + straggler-tail stealing + span prefill on an "
        "in-process loopback fleet with an induced straggler (combine "
        "with --chaos SCENARIO for the degraded-network artifact); "
        "prints its own JSON line and exits",
    )
    ap.add_argument("--dc-jobs", type=int, default=12)
    ap.add_argument("--dc-nonces", type=int, default=80_000,
                    help="nonces per dispatch-compare job")
    ap.add_argument("--dc-miners", type=int, default=3)
    ap.add_argument("--dc-static-chunk", type=int, default=20_000,
                    help="fixed chunk size of the static comparison leg")
    ap.add_argument("--dc-target-s", type=float, default=0.1,
                    help="adaptive leg per-chunk service-time target")
    ap.add_argument("--dc-slow-rate", type=float, default=10_000.0,
                    help="induced straggler's sweep rate (nonces/s)")
    ap.add_argument("--dc-wedge-s", type=float, default=2.0,
                    help="induced straggler's stuck-runtime episode length (s)")
    ap.add_argument("--dc-wedge-every-s", type=float, default=1.0,
                    help="seconds between the straggler's wedge episodes")
    ap.add_argument("--dc-prefill", type=int, default=20_000,
                    help="speculative prefill job size (adaptive leg)")
    ap.add_argument("--dc-clients", type=int, default=1,
                    help="concurrent client workers; 1 = sequential jobs, "
                    "the regime where a straggler-held tail idles the "
                    "healthy miners")
    ap.add_argument("--dc-deadline", type=float, default=120.0)
    ap.add_argument(
        "--depth-compare",
        action="store_true",
        help="adaptive pipeline-depth arbitration (ISSUE 15 satellite): "
        "static 2-deep vs --adaptive-depth windows on a sieve-enabled "
        "xla fleet, same job batch; prints its own JSON line and exits",
    )
    ap.add_argument("--dp-jobs", type=int, default=6,
                    help="jobs per depth-compare leg")
    ap.add_argument("--dp-nonces", type=int, default=2_000_000,
                    help="nonces per depth-compare job")
    ap.add_argument("--dp-miners", type=int, default=2)
    ap.add_argument("--dp-clients", type=int, default=2)
    ap.add_argument("--dp-deadline", type=float, default=300.0)
    ap.add_argument(
        "--autoscale",
        action="store_true",
        help="self-scaling capacity plane leg (ISSUE 18): the same seeded "
        "open-loop arrival ramp against a fixed 1-worker fleet and "
        "against the SLO-burn-driven controller (spawn on burn, clean "
        "drain after); asserts alert->scale-up->p99-recovery->drain and "
        "bit-exact answers; prints its own JSON line and exits",
    )
    ap.add_argument("--as-nonces", type=int, default=40_000,
                    help="nonces per autoscale-leg job (short jobs: burn "
                    "evidence arrives at completion, so the completion "
                    "rate is the controller's evidence rate)")
    ap.add_argument("--as-throttle-nps", type=float, default=50_000.0,
                    help="per-worker pace (BMT_MINER_THROTTLE_NPS): one "
                    "deterministic unit of capacity per worker on a "
                    "1-core box")
    ap.add_argument("--as-warm-s", type=float, default=6.0,
                    help="seconds of in-capacity warm arrivals")
    ap.add_argument("--as-warm-x", type=float, default=0.25,
                    help="warm arrival rate as a multiple of one "
                    "worker's capacity")
    ap.add_argument("--as-overload-s", type=float, default=20.0,
                    help="seconds of past-capacity ramp arrivals")
    ap.add_argument("--as-overload-x", type=float, default=2.2,
                    help="ramp arrival rate as a multiple of one worker's "
                    "capacity (must exceed 1, stay under --as-max-workers)")
    ap.add_argument("--as-max-workers", type=int, default=3)
    ap.add_argument("--as-interval", type=float, default=0.25,
                    help="controller tick interval (s)")
    ap.add_argument("--as-hold", type=int, default=3,
                    help="consecutive burning/quiet ticks before acting")
    ap.add_argument("--as-up-cooldown", type=float, default=3.0)
    ap.add_argument("--as-down-cooldown", type=float, default=6.0)
    ap.add_argument("--as-slo-threshold-s", type=float, default=None,
                    help="request-p95 SLO latency threshold "
                    "(default: 1.5x one job's unqueued service time)")
    ap.add_argument("--as-deadline", type=float, default=120.0,
                    help="open-loop batch deadline per leg (s)")
    ap.add_argument("--as-seed", type=int, default=1,
                    help="arrival-schedule seed (both legs share it)")
    ap.add_argument(
        "--mixed-fleet",
        action="store_true",
        help="heterogeneous-tier leg (ISSUE 20): one in-process fleet "
        "with one miner per kernel tier of the workload's ladder "
        "(xla + cpu + hashlib; default workload blake2b64) on one job — "
        "asserts the answer is bit-exact, per-tier chunk sizes diverge "
        "with measured rates, and no slow rung drags the chunk p50; "
        "prints its own JSON line and exits",
    )
    ap.add_argument("--mf-nonces", type=int, default=24_000_000,
                    help="nonces in the timed mixed-fleet job")
    ap.add_argument("--mf-warmup", type=int, default=4_000_000,
                    help="nonces in the mixed-fleet warm-up job")
    ap.add_argument("--mf-oracle-nonces", type=int, default=120_000,
                    help="nonces in the oracle-checked mixed-fleet job")
    ap.add_argument(
        "--federation",
        type=int,
        default=0,
        metavar="N",
        help="federation leg (ISSUE 8): N real apps.federation replica "
        "subprocesses, duplicate-heavy batch with a mid-batch cell "
        "SIGKILL and a minerless cross-replica gossip probe; prints its "
        "own JSON line and exits",
    )
    args = ap.parse_args()

    global WORKLOAD
    try:
        WORKLOAD = workloads_mod.resolve(
            args.workload or os.environ.get("BMT_WORKLOAD") or None
        )
    except ValueError as e:
        ap.error(str(e))
    if args.workload:
        # Subprocess fleets (MinerKeeper, server, federation cells) all
        # spawn with {**os.environ}: one export reaches every process.
        os.environ["BMT_WORKLOAD"] = WORKLOAD.name

    if args.dispatch_compare:
        if args.chaos:
            from bitcoin_miner_tpu.lspnet.chaos import standard_scenarios

            if args.chaos not in standard_scenarios():
                raise SystemExit(
                    f"unknown --chaos scenario {args.chaos!r}; valid: "
                    f"{sorted(standard_scenarios())}"
                )
        return run_dispatch_compare(args)

    if args.depth_compare:
        return run_depth_compare(args)

    if args.mixed_fleet:
        return run_mixed_fleet(args)

    if args.autoscale:
        return run_autoscale_bench(args)

    if args.federation:
        return run_federation_bench(args)

    port = args.port or 3000 + (os.getpid() * 7919) % 50000
    data = "cmu440"
    tmp = tempfile.mkdtemp(prefix="fleet_bench_")
    miner_log = args.miner_log or os.path.join(tmp, "miner.log")
    server = None
    keeper = None
    client = None
    cpu_miners: list = []
    try:
        server_env = {**os.environ, "PYTHONPATH": str(REPO)}
        tele_addr = None
        fleet_log = None
        if args.telemetry:
            # The sidecar port rides next to the serving port; the server
            # appends the merged view to a fleet log this tool reads back
            # for the JSON stamp (and tools.dash can tail live).
            tport = port + 1
            tele_addr = f"127.0.0.1:{tport}"
            fleet_log = os.path.join(tmp, "fleet.jsonl")
            server_env.update(
                BMT_TELEMETRY_PORT=str(tport),
                BMT_FLEET_LOG=fleet_log,
                BMT_SLO="1",
            )
            log(f"telemetry: sidecar on :{tport}, fleet log -> {fleet_log}")
        if args.trace:
            # The server process owns the gateway/scheduler events; its
            # ticker drains them to the file (apps/server.main reads
            # BMT_TRACE, the env spelling of --trace=FILE).
            server_env["BMT_TRACE"] = os.path.abspath(args.trace)
            log(f"trace: server event log -> {args.trace}")
        if args.chaos:
            from bitcoin_miner_tpu.lspnet.chaos import standard_scenarios

            # Validate HERE: the server subprocess's "unknown scenario"
            # warning goes to a devnulled stderr, and a typoed name would
            # otherwise stamp a chaos config onto a clean-network number.
            if args.chaos not in standard_scenarios():
                raise SystemExit(
                    f"unknown --chaos scenario {args.chaos!r}; valid: "
                    f"{sorted(standard_scenarios())}"
                )
            # The server arms the schedule at startup (apps/server.main);
            # its tx shapes both the chunk stream to miners and the Result
            # stream to clients — the degraded-network leg of the bench.
            server_env.update(
                BMT_CHAOS_SCENARIO=args.chaos,
                BMT_CHAOS_LOOP=str(args.chaos_loop),
                LSPNET_CHAOS_SEED=str(args.chaos_seed),
            )
            log(f"chaos: {args.chaos} (seed {args.chaos_seed}, "
                f"looped every {args.chaos_loop:.1f}s) armed in the server")
        server = subprocess.Popen(
            [sys.executable, "-m", "bitcoin_miner_tpu.apps.server", str(port)],
            cwd=tmp,  # server writes ./log.txt (reference parity)
            env=server_env,
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
            text=True,
        )
        _wait_listening(server, 30)
        log(f"server up on :{port}; miner log -> {miner_log}")
        keeper = MinerKeeper(port, args.backend, miner_log, telemetry=tele_addr)
        for i in range(args.cpu_miners):
            cpu_log = open(os.path.join(tmp, f"cpu_miner_{i}.log"), "wb")
            cpu_argv = [
                sys.executable,
                "-m",
                "bitcoin_miner_tpu.apps.miner",
                f"127.0.0.1:{port}",
                "--backend",
                "cpu",
            ]
            if tele_addr:
                cpu_argv += [
                    "--telemetry", tele_addr,
                    "--telemetry-interval", "1.0",
                    "--source", f"cpu-miner-{i}",
                ]
            cpu_miners.append(
                subprocess.Popen(
                    cpu_argv,
                    cwd=str(REPO),
                    stdout=subprocess.DEVNULL,
                    stderr=cpu_log,
                )
            )
        if cpu_miners:
            log(f"spawned {len(cpu_miners)} native cpu miners (logs in {tmp})")

        from bitcoin_miner_tpu import lsp

        client = lsp.Client("127.0.0.1", port)
        log(f"warm-up job: {args.warmup:.1e} nonces (compiles + EWMA ramp)")
        warm = run_job(
            client, keeper, data, args.warmup - 1, args.timeout, args.stall
        )
        log(
            f"warm-up done in {warm['wall_s']:.2f}s "
            f"({args.warmup / warm['wall_s'] / 1e9:.3f}e9 n/s incl. ramp)"
        )
        # Class warm: every digit class the timed job will touch must be
        # built before timing starts (same contract as bench.py, which
        # compiles before its measurement window) — a class's first use
        # costs ~9 s of tracing + ~5 s of executable load per process even
        # on a persistent-cache hit, and the main warm-up job only covers
        # the classes below `--warmup`.  A tiny job per uncovered digit
        # class pays that cost here instead of mid-measurement.  The
        # mid-job path is still covered: the miner prewarms one class
        # ahead of each assignment (SweepPipeline.prewarm_async).
        # The drill range sits beyond the timed job; its digit classes must
        # be warm too, or the "clean" drill leg absorbs a first-use build.
        top = args.nonces - 1
        if args.kill_drill:
            top = args.nonces + args.drill_nonces - 1
        for d in range(len(str(args.warmup - 1)) + 1, len(str(top)) + 1):
            t0 = time.monotonic()
            hi = min(10**d - 1, top)
            run_job(
                client, keeper, data, hi, args.timeout, args.stall,
                lower=max(0, hi - 10**6 + 1),
            )
            log(f"class d={d} warm-up done in {time.monotonic() - t0:.2f}s")
        log(f"timed job: {args.nonces:.1e} nonces")
        timed = run_job(
            client, keeper, data, args.nonces - 1, args.timeout, args.stall
        )
        rate = args.nonces / timed["wall_s"]
        log(
            f"fleet delivered {rate / 1e9:.3f}e9 n/s over {timed['wall_s']:.2f}s "
            f"({rate / args.kernel_rate:.1%} of the {args.kernel_rate / 1e9:.3f}e9 kernel rate)"
        )
        # A cpu miner that died mid-bench would make the "heterogeneous
        # fleet" artifact describe a fleet that never ran — refuse.
        dead = [i for i, m in enumerate(cpu_miners) if m.poll() is not None]
        if dead:
            raise RuntimeError(
                f"cpu miner(s) {dead} died during the bench; see "
                f"{tmp}/cpu_miner_*.log"
            )
        drill = None
        if args.kill_drill:
            # Same range, clean vs mid-job miner SIGKILL: the argmin over a
            # fixed range is deterministic, so any correct execution —
            # including one the scheduler had to reassemble from a dead
            # miner's reassigned chunks — must return the identical pair.
            d_lo = args.nonces  # fresh range, beyond the timed job
            d_hi = d_lo + args.drill_nonces - 1
            log(f"kill drill: clean job over [{d_lo},{d_hi}]")
            clean = run_job(
                client, keeper, data, d_hi, args.timeout, args.stall,
                lower=d_lo,
            )
            kill_at = max(1.0, 0.4 * clean["wall_s"])
            log(f"kill drill: same job, SIGKILL at t+{kill_at:.1f}s")
            killed = run_job(
                client, keeper, data, d_hi, args.timeout, args.stall,
                lower=d_lo, kill_after=kill_at,
            )
            if not killed["kill_fired"]:
                # A Result that lands before the kill makes the drill a
                # second clean run — no fault-tolerance evidence at all.
                raise RuntimeError(
                    "kill drill: Result arrived before the SIGKILL fired; "
                    "raise --drill-nonces"
                )
            match = (clean["hash"], clean["nonce"]) == (
                killed["hash"], killed["nonce"],
            )
            drill = {
                "match": match,
                "hash": clean["hash"],
                "nonce": clean["nonce"],
                "clean_wall_s": round(clean["wall_s"], 3),
                "killed_wall_s": round(killed["wall_s"], 3),
                # Exactly one deliberate kill per drill (kill_fired was
                # asserted above); any further restarts in the drill window
                # were involuntary wedge recoveries and stay in
                # miner_restarts.
                "deliberate_kills": 1,
            }
            log(f"kill drill: match={match} ({clean} vs {killed})")
            if not match:
                raise RuntimeError(f"kill drill mismatch: {clean} vs {killed}")
        # Fleet-plane stamp (ISSUE 7): the merged view + SLO verdicts the
        # server's hub last published, read back while it is still up.
        fleet_stamp = _read_last_fleet_state(fleet_log) if fleet_log else None
        if args.telemetry and fleet_stamp is None:
            log("warning: --telemetry armed but no fleet state was published")
        print(
            json.dumps(
                {
                    "metric": "fleet_nonces_per_sec",
                    "value": round(rate),
                    "unit": "nonces/s",
                    "workload": WORKLOAD.name,
                    "vs_baseline": round(rate / 1e9, 4),
                    "kernel_rate": round(args.kernel_rate),
                    "vs_kernel": round(rate / args.kernel_rate, 4),
                    "nonces": args.nonces,
                    "wall_s": round(timed["wall_s"], 3),
                    "warmup_nonces": args.warmup,
                    "warmup_wall_s": round(warm["wall_s"], 3),
                    "latency_s": {
                        k: round(v, 4)
                        for k, v in LATENCY.snapshot().items()
                        if k in ("p50", "p95", "p99")
                    } | {"count": LATENCY.count()},
                    # Involuntary (wedge/death) recoveries only; the
                    # drill's deliberate kill is counted in kill_drill.
                    "miner_restarts": keeper.restarts
                    - (drill["deliberate_kills"] if drill else 0),
                    "backend": args.backend,
                    **(
                        {
                            "chaos": {
                                "scenario": args.chaos,
                                "seed": args.chaos_seed,
                                "loop_s": args.chaos_loop,
                            }
                        }
                        if args.chaos
                        else {}
                    ),
                    **(
                        {"cpu_miners": args.cpu_miners}
                        if args.cpu_miners
                        else {}
                    ),
                    **({"kill_drill": drill} if drill is not None else {}),
                    **(
                        {
                            "fleet": {
                                "sources": fleet_stamp["sources"],
                                "stale_sources": fleet_stamp["stale_sources"],
                                "hists": fleet_stamp["hists"],
                                "stragglers": [
                                    s["source"]
                                    for s in fleet_stamp.get("stragglers", [])
                                ],
                            },
                            "slo": {
                                s["name"]: {
                                    "ok": s["ok"],
                                    "burn_fast": s["burn_fast"],
                                    "burn_slow": s["burn_slow"],
                                }
                                for s in fleet_stamp.get("slo", {}).get(
                                    "slos", []
                                )
                            },
                        }
                        if fleet_stamp is not None
                        else {}
                    ),
                }
            ),
            flush=True,
        )
        return 0
    finally:
        if client is not None:
            try:
                client.close()
            except Exception:
                pass
        if keeper is not None:
            keeper.kill()
        for m in cpu_miners:
            if m.poll() is None:
                m.send_signal(signal.SIGKILL)
        if server is not None and server.poll() is None:
            server.send_signal(signal.SIGTERM)
            try:
                server.wait(timeout=5)
            except subprocess.TimeoutExpired:
                server.kill()


if __name__ == "__main__":
    sys.exit(main())
