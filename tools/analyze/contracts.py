"""Pass 3: the frozen-contract checker.

The reference implementation froze three surfaces (SURVEY §3.6) that any
perf PR could silently drift — exactly the AsicBoost lesson from
PAPERS.md: aggressive pipeline optimization is only safe when the
contract surface is pinned by machinery.  Golden vectors here were
generated from the frozen implementations and hard-coded; the pass
recomputes and compares, no network, no device:

- **bitcoin/message**: Go-JSON byte-exact ``marshal`` for Join / Request /
  Result (field order, separators, u64 masking) and ``unmarshal``
  round-trips including the poison-rejection rules.
- **lsp/message**: byte-exact codec incl. base64 payloads and the
  ``null`` nil-payload convention.
- **bitcoin/hash**: ``Hash(msg, nonce)`` vectors (single SHA-256 over
  ``"<msg> <nonce>"``, big-endian first 8 bytes).
- **workload registry** (ISSUE 9): every registered workload's own
  golden vectors are recomputed through its ``hash_nonce`` — the same
  pin the reference contract gets, so no workload's hash family can
  drift silently; the DEFAULT workload must additionally agree with the
  reference ``bitcoin/hash`` vectors byte-for-byte (the sha256d path is
  the frozen contract, registry or not).  Workloads that ship their own
  device kernel family (ISSUE 20: blake2b64 / ``ops/blake2b.py``) get a
  second recompute through that tier itself — single-nonce device
  sweeps — because a from-scratch kernel can drift while the hashlib
  oracle stays green.
- **CLI stdout**: the usage strings (driven through ``main()`` with a
  wrong argc) and the literal ``Result``/``Disconnected``/``Server
  listening`` prints, pinned at source level.

``modules`` overrides exist so the seeded-violation fixtures
(tests/fixtures_analyze) can demonstrate every rule firing against a
deliberately broken codec.
"""

from __future__ import annotations

import io
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional

from .common import REPO_ROOT, Finding

PASS = "contracts"

#: Hash(msg, nonce) golden vectors — frozen from bitcoin/hash.py, which is
#: itself pinned to the reference bitcoin/hash.go:13-17.
HASH_VECTORS = (
    ("hello", 0, 13593802692011500125),
    ("hello", 12345, 6725106177369798965),
    ("bitcoin", 999999999999, 12216901194327863447),
    ("", 1, 16224919167884709661),
    ("chaos", 4000, 9384656945151152569),
)

#: (constructor name, args, frozen bytes) for the mining wire protocol.
BITCOIN_VECTORS = (
    ("join", (), b'{"Type":0,"Data":"","Lower":0,"Upper":0,"Hash":0,"Nonce":0}'),
    (
        "request",
        ("abc", 0, 100),
        b'{"Type":1,"Data":"abc","Lower":0,"Upper":100,"Hash":0,"Nonce":0}',
    ),
    (
        "result",
        ((1 << 64) - 1, 42),
        b'{"Type":2,"Data":"","Lower":0,"Upper":0,"Hash":18446744073709551615,"Nonce":42}',
    ),
)

#: (constructor name, args, frozen bytes) for the LSP transport codec.
LSP_VECTORS = (
    ("connect", (), b'{"Type":0,"ConnID":0,"SeqNum":0,"Size":0,"Payload":null}'),
    (
        "data",
        (7, 3, 2, b"hi"),
        b'{"Type":1,"ConnID":7,"SeqNum":3,"Size":2,"Payload":"aGk="}',
    ),
    ("ack", (7, 3), b'{"Type":2,"ConnID":7,"SeqNum":3,"Size":0,"Payload":null}'),
)

#: Junk each codec must reject with None, never an exception.  Per-codec:
#: the mining codec validates u64 range/type on its own fields; the LSP
#: codec (like Go's) ignores unknown fields, so its poison set is only
#: structural junk.
BITCOIN_POISON = (
    b"",
    b"not json",
    b"[1,2]",
    b'{"Type":1,"Lower":-1}',
    b'{"Type":1,"Lower":true}',
    b'{"Type":1,"Data":7}',
)
LSP_POISON = (b"", b"not json", b"[1,2]", b'{"Type":"x"}', b'{"Payload":"%%%"}')

#: (relative file, required literal) — the frozen stdout prints, pinned at
#: source level so a refactor cannot rewrite them unnoticed.
SOURCE_PINS = (
    (
        "bitcoin_miner_tpu/apps/client.py",
        'print("Result", result[0], result[1], file=out)',
    ),
    ("bitcoin_miner_tpu/apps/client.py", 'print("Disconnected", file=out)'),
    (
        "bitcoin_miner_tpu/apps/server.py",
        'print("Server listening on port", port)',
    ),
)

#: Usage lines printed on wrong argc (argv shapes frozen by the reference).
USAGE = (
    ("client", "Usage: ./client <hostport> <message> <maxNonce>"),
    ("server", "Usage: ./server <port> [--checkpoint=FILE]"),
    ("miner", "Usage: ./miner <hostport>"),
)


def _default_modules() -> Dict[str, Any]:
    from bitcoin_miner_tpu.apps import client as client_mod
    from bitcoin_miner_tpu.apps import miner as miner_mod
    from bitcoin_miner_tpu.apps import server as server_mod
    from bitcoin_miner_tpu.bitcoin import hash as hash_mod
    from bitcoin_miner_tpu.bitcoin import message as bmsg
    from bitcoin_miner_tpu.lsp import message as lmsg

    return {
        "bitcoin_message": bmsg,
        "lsp_message": lmsg,
        "hash": hash_mod,
        "client": client_mod,
        "server": server_mod,
        "miner": miner_mod,
    }


def _check_codec(
    name: str,
    mod: Any,
    vectors: tuple,
    poison: tuple,
    findings: List[Finding],
    path: str,
) -> None:
    Message = getattr(mod, "Message", None)
    if Message is None:
        findings.append(
            Finding(PASS, "codec-missing", path, 1, name, "no Message class")
        )
        return
    for ctor, args, frozen in vectors:
        try:
            got = getattr(Message, ctor)(*args).marshal()
        except Exception as e:  # a crash IS a contract break
            findings.append(
                Finding(
                    PASS, "codec-marshal", path, 1, f"{name}.{ctor}",
                    f"marshal raised {e!r}",
                )
            )
            continue
        if got != frozen:
            findings.append(
                Finding(
                    PASS,
                    "codec-marshal",
                    path,
                    1,
                    f"{name}.{ctor}",
                    f"marshal drifted from the frozen wire bytes: "
                    f"{got!r} != {frozen!r}",
                )
            )
        back = Message.unmarshal(frozen)
        if back is None or back.marshal() != frozen:
            findings.append(
                Finding(
                    PASS,
                    "codec-roundtrip",
                    path,
                    1,
                    f"{name}.{ctor}",
                    f"unmarshal(frozen) does not round-trip: {back!r}",
                )
            )
    for junk in poison:
        try:
            if Message.unmarshal(junk) is not None and junk != b"":
                findings.append(
                    Finding(
                        PASS,
                        "codec-poison",
                        path,
                        1,
                        name,
                        f"unmarshal accepted poison {junk!r} (Go's decoder "
                        f"rejects it; a poison Request crashes miners)",
                    )
                )
        except Exception as e:
            findings.append(
                Finding(
                    PASS, "codec-poison", path, 1, name,
                    f"unmarshal raised {e!r} on junk instead of returning None",
                )
            )


#: Every registered workload must pin at least this many golden vectors.
WORKLOAD_MIN_GOLDEN = 3

#: The registry's frozen default must agree with the reference hash
#: contract — the rest of the checker pins that name's behavior.
WORKLOAD_DEFAULT_NAME = "sha256d"

_WORKLOADS_PATH = "bitcoin_miner_tpu/workloads/__init__.py"


def _check_workloads(findings: List[Finding]) -> None:
    """The per-workload golden-vector pass (ISSUE 9): recompute every
    registered workload's pinned vectors, require a minimum pin count,
    and hold the default to the reference contract."""
    from bitcoin_miner_tpu import workloads

    if workloads.DEFAULT_WORKLOAD != WORKLOAD_DEFAULT_NAME:
        findings.append(
            Finding(
                PASS, "workload-default", _WORKLOADS_PATH, 1,
                workloads.DEFAULT_WORKLOAD,
                f"registry default drifted from the frozen "
                f"{WORKLOAD_DEFAULT_NAME!r}",
            )
        )
    for name in workloads.names():
        w = workloads.get(name)
        if len(w.golden) < WORKLOAD_MIN_GOLDEN:
            findings.append(
                Finding(
                    PASS, "workload-golden-missing", _WORKLOADS_PATH, 1, name,
                    f"workload pins only {len(w.golden)} golden vectors "
                    f"(need >= {WORKLOAD_MIN_GOLDEN}) — an unpinned hash "
                    "family can drift silently",
                )
            )
        for data, nonce, frozen in w.golden:
            try:
                got = w.hash_nonce(data, nonce)
            except Exception as e:  # a crash IS a contract break
                findings.append(
                    Finding(
                        PASS, "workload-vector", _WORKLOADS_PATH, 1,
                        f"{name}({data!r},{nonce})", f"hash_nonce raised {e!r}",
                    )
                )
                continue
            if got != frozen:
                findings.append(
                    Finding(
                        PASS, "workload-vector", _WORKLOADS_PATH, 1,
                        f"{name}({data!r},{nonce})",
                        f"drifted: {got} != frozen {frozen}",
                    )
                )
    _check_device_tiers(findings)
    # The default's oracle must equal the reference contract itself.
    try:
        w = workloads.get(WORKLOAD_DEFAULT_NAME)
    except ValueError:
        findings.append(
            Finding(
                PASS, "workload-default", _WORKLOADS_PATH, 1,
                WORKLOAD_DEFAULT_NAME, "frozen default not registered",
            )
        )
        return
    for msg, nonce, frozen in HASH_VECTORS:
        if w.hash_nonce(msg, nonce) != frozen:
            findings.append(
                Finding(
                    PASS, "workload-default", _WORKLOADS_PATH, 1,
                    f"{WORKLOAD_DEFAULT_NAME}({msg!r},{nonce})",
                    "default workload disagrees with the reference "
                    "bitcoin/hash contract vectors",
                )
            )


#: Workload name -> device tier whose KERNEL (not just the hash_nonce
#: oracle) must reproduce the golden vectors (ISSUE 20).  The oracle
#: recompute above pins each family's host reference; for families that
#: also ship a device kernel (ops/blake2b.py — a from-scratch u32-pair
#: reimplementation of the compression function, not a hashlib call),
#: the kernel's arithmetic is a SECOND independent surface that can
#: drift while the oracle stays green, and the sweep drivers would then
#: serve wrong minima whenever that tier wins the ladder.  Single-nonce
#: sweeps ([n, n], host_lane_budget=0 so nothing routes to a host fold)
#: force every golden through the full device path: layout build,
#: midstate fold, device compression, min-fold epilogue.
WORKLOAD_DEVICE_TIERS = {"blake2b64": "xla"}


def _check_device_tiers(findings: List[Finding]) -> None:
    from bitcoin_miner_tpu import workloads
    from bitcoin_miner_tpu.ops.sweep import sweep_min_hash
    from bitcoin_miner_tpu.utils.platform import enable_compile_cache

    # The golden vectors span ~5 kernel shape classes; the persistent
    # XLA cache makes every run after the first pay import cost only
    # (matters: this pass runs in pre-commit --changed and three tier-1
    # subprocesses).
    enable_compile_cache()

    for name, tier in WORKLOAD_DEVICE_TIERS.items():
        try:
            w = workloads.get(name)
        except ValueError:
            findings.append(
                Finding(
                    PASS, "workload-device-tier", _WORKLOADS_PATH, 1, name,
                    f"device-tier-pinned workload {name!r} not registered",
                )
            )
            continue
        if tier not in w.tiers:
            findings.append(
                Finding(
                    PASS, "workload-device-tier", _WORKLOADS_PATH, 1, name,
                    f"workload no longer ladders the pinned device tier "
                    f"{tier!r} (tiers: {w.tiers})",
                )
            )
            continue
        for data, nonce, frozen in w.golden:
            try:
                r = sweep_min_hash(
                    data, nonce, nonce, backend=tier, workload=w
                )
            except Exception as e:  # a crash IS a contract break
                findings.append(
                    Finding(
                        PASS, "workload-device-vector", _WORKLOADS_PATH, 1,
                        f"{name}:{tier}({data!r},{nonce})",
                        f"device sweep raised {e!r}",
                    )
                )
                continue
            if r.hash != frozen or r.nonce != nonce:
                findings.append(
                    Finding(
                        PASS, "workload-device-vector", _WORKLOADS_PATH, 1,
                        f"{name}:{tier}({data!r},{nonce})",
                        f"device tier drifted from the frozen vector: "
                        f"({r.hash}, {r.nonce}) != ({frozen}, {nonce})",
                    )
                )


def run(
    root: Path,
    scan_dirs: Any = None,
    modules: Optional[Dict[str, Any]] = None,
) -> List[Finding]:
    findings: List[Finding] = []
    fixture_mode = modules is not None
    mods = modules if modules is not None else _default_modules()

    if "bitcoin_message" in mods:
        _check_codec(
            "bitcoin.Message",
            mods["bitcoin_message"],
            BITCOIN_VECTORS,
            BITCOIN_POISON,
            findings,
            "bitcoin_miner_tpu/bitcoin/message.py" if not fixture_mode else "bad_contract.py",
        )
    if "lsp_message" in mods:
        _check_codec(
            "lsp.Message",
            mods["lsp_message"],
            LSP_VECTORS,
            LSP_POISON,
            findings,
            "bitcoin_miner_tpu/lsp/message.py" if not fixture_mode else "bad_contract.py",
        )
    if "hash" in mods:
        hash_nonce: Callable = mods["hash"].hash_nonce
        for msg, nonce, frozen in HASH_VECTORS:
            got = hash_nonce(msg, nonce)
            if got != frozen:
                findings.append(
                    Finding(
                        PASS,
                        "hash-vector",
                        "bitcoin_miner_tpu/bitcoin/hash.py" if not fixture_mode else "bad_contract.py",
                        1,
                        f"Hash({msg!r},{nonce})",
                        f"drifted: {got} != frozen {frozen}",
                    )
                )

    if not fixture_mode:
        _check_workloads(findings)

    for binary, frozen in USAGE:
        mod = mods.get(binary)
        if mod is None:
            continue
        out = io.StringIO()
        try:
            if binary == "client":
                mod.main([binary], out=out)
                got = out.getvalue()
            else:
                # server/miner mains print to real stdout; capture it.
                import contextlib

                with contextlib.redirect_stdout(out):
                    mod.main([binary])
                got = out.getvalue()
        except SystemExit:
            got = out.getvalue()
        except Exception as e:
            got = f"<raised {e!r}>"
        if got != frozen:
            findings.append(
                Finding(
                    PASS,
                    "cli-usage",
                    f"bitcoin_miner_tpu/apps/{binary}.py",
                    1,
                    binary,
                    f"usage stdout drifted: {got!r} != frozen {frozen!r}",
                )
            )

    if not fixture_mode:
        for relpath, literal in SOURCE_PINS:
            src_path = REPO_ROOT / relpath
            try:
                src = src_path.read_text()
            except OSError:
                src = ""
            if literal not in src:
                findings.append(
                    Finding(
                        PASS,
                        "stdout-pin",
                        relpath,
                        1,
                        literal.split("(")[0],
                        f"frozen print literal missing from source: "
                        f"{literal!r} (reference stdout contract)",
                    )
                )
    return findings
