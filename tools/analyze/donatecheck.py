"""Pass 8: the JAX donation-safety pass (ISSUE 19).

The hot device plane (:func:`ops.sweep.make_hot_step`, ``_HotLoop``)
lives on ``donate_argnums``: the carry's device buffer is reused in
place by every step, so the whole plane is correct only while three
disciplines hold.  This pass makes them build-time properties:

- ``donate-no-rebind`` — a call to a donated callable whose result does
  not rebind the donated operand.  After the call the operand's buffer
  is dead; keeping the old name live is a use-after-donate waiting to
  happen (and XLA falls back to a silent copy if the handle is still
  referenced).
- ``donate-read-after-call`` — the donated operand is read again after
  the donated call (before any rebind) in the same suite.  Dead-buffer
  read: on TPU this raises; under some backends it silently reads
  stale memory.
- ``donate-materialize`` — a class whose attribute is passed as a
  donated operand (the job carry) materialises that attribute
  mid-job: ``int()``/``float()``/``list()``/``tuple()`` over it,
  ``np.asarray``/``np.array``/``jnp.asarray`` of it, iteration over
  it (incl. comprehensions), or ``.block_until_ready()``.  Each
  materialisation is a full device sync — the exact stall the hot
  plane exists to avoid.  The sanctioned job-end single fetch is
  annotated ``# donate-ok: <reason>``.

Donated callables are recognised two ways: a literal
``jax.jit(..., donate_argnums=...)`` binding (the argnums literal is
read), and a binding from a hot-step factory (any callee whose name
contains ``hot_step`` — the repo convention, ``donate_argnums=(0,)``).
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .common import (
    DONATE_OK_RE,
    Finding,
    comment_in_span,
    file_comments,
    iter_py_files,
    rel,
    walk_shallow,
)

PASS = "donate"

#: Donation discipline only binds on the device plane; scanning the whole
#: tree would tax test helpers that never see a donated buffer.
DONATE_SCAN_DIRS = ("bitcoin_miner_tpu/ops", "bitcoin_miner_tpu/parallel")

#: The hot-step factory convention: any callee spelled like one returns a
#: jitted step donating its first argument (the carry).
HOT_FACTORY_RE = re.compile(r"hot_step")

_MATERIALIZE_NAMES = {"int", "float", "list", "tuple"}
_MATERIALIZE_ATTRS = {"asarray", "array"}


def _dotted(node: ast.AST) -> Optional[Tuple[str, ...]]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


def _jit_donate_argnums(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """argnums of a literal ``jax.jit(..., donate_argnums=...)`` call, or
    None if this is not one (or the literal cannot be read)."""
    d = _dotted(call.func)
    if d is None or d[-1] != "jit":
        return None
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)):
            out = []
            for e in v.elts:
                if not (isinstance(e, ast.Constant) and isinstance(e.value, int)):
                    return None
                out.append(e.value)
            return tuple(out)
        return None
    return None


def _donated_argnums_of(value: ast.AST) -> Optional[Tuple[int, ...]]:
    """argnums when ``value`` builds a donated callable, else None."""
    if not isinstance(value, ast.Call):
        return None
    nums = _jit_donate_argnums(value)
    if nums is not None:
        return nums
    d = _dotted(value.func)
    if d is not None and HOT_FACTORY_RE.search(d[-1]):
        return (0,)
    return None


def _contains(expr: ast.AST, dotted: Tuple[str, ...]) -> bool:
    return any(_dotted(n) == dotted for n in ast.walk(expr))


def _flat_targets(targets: Sequence[ast.AST]) -> List[Tuple[str, ...]]:
    out: List[Tuple[str, ...]] = []
    stack = list(targets)
    while stack:
        t = stack.pop()
        if isinstance(t, (ast.Tuple, ast.List)):
            stack.extend(t.elts)
        else:
            d = _dotted(t)
            if d is not None:
                out.append(d)
    return out


class _FileChecker:
    def __init__(self, path: str, source: str, findings: List[Finding]) -> None:
        self.path = path
        self.comments = file_comments(source)
        self.findings = findings
        self.tree = ast.parse(source)
        self.donated: Dict[Tuple[str, ...], Tuple[int, ...]] = {}
        # Every binding of a donated callable, wherever it happens
        # (module level, __init__, the dispatch body) — an over-approx
        # keyed by the bound name's dotted spelling.
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Assign):
                nums = _donated_argnums_of(node.value)
                if nums is None:
                    continue
                for d in _flat_targets(node.targets):
                    self.donated[d] = nums

    def _emit(self, rule: str, node: ast.AST, symbol: str, msg: str) -> None:
        self.findings.append(
            Finding(PASS, rule, self.path, node.lineno, symbol, msg)
        )

    def _ok(self, stmt: ast.stmt) -> bool:
        return (
            comment_in_span(
                self.comments, stmt.lineno,
                getattr(stmt, "end_lineno", None), DONATE_OK_RE,
            )
            is not None
        )

    # ------------------------------------------------------ linear suites

    def _donated_call_in(self, stmt: ast.stmt) -> Optional[Tuple[ast.Call, Tuple[int, ...]]]:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                d = _dotted(node.func)
                if d is not None and d in self.donated:
                    return node, self.donated[d]
        return None

    def _check_suite(self, symbol: str, suite: Sequence[ast.stmt]) -> None:
        for i, stmt in enumerate(suite):
            hit = None
            if isinstance(stmt, (ast.Assign, ast.Expr, ast.AugAssign, ast.AnnAssign)):
                hit = self._donated_call_in(stmt)
            if hit is None:
                continue
            call, nums = hit
            operands = [
                _dotted(call.args[n])
                for n in nums
                if n < len(call.args)
            ]
            operands = [o for o in operands if o is not None]
            if not operands:
                continue
            rebound = (
                _flat_targets(stmt.targets)
                if isinstance(stmt, ast.Assign)
                else []
            )
            for op in operands:
                spelled = ".".join(op)
                if op not in rebound:
                    if not self._ok(stmt):
                        self._emit(
                            "donate-no-rebind", call, symbol,
                            f"donated call does not rebind {spelled} — the "
                            f"operand's buffer is dead after this call; "
                            f"assign the result back "
                            f"({spelled}, ... = step({spelled}, ...))",
                        )
                    continue  # unrebound: read-after is the same finding
                # Rebound at the call: scan the rest of the suite for a
                # read BEFORE any further rebind (dead-handle window is
                # closed here, but a sibling alias read is still wrong
                # for a second donated call; keep it linear and local).
            for later in suite[i + 1:]:
                if any(op in _flat_targets(later.targets) for op in operands) if isinstance(later, ast.Assign) else False:
                    break
                for op in operands:
                    if op in rebound:
                        continue
                    if any(
                        _contains(e, op)
                        for e in ast.walk(later)
                        if isinstance(e, ast.expr)
                    ) and not self._ok(later):
                        self._emit(
                            "donate-read-after-call", later, symbol,
                            f"{'.'.join(op)} read after being donated — "
                            f"its device buffer was reused by the donated "
                            f"call above; rebind it from the call result "
                            f"before any further use",
                        )
                        break
                else:
                    continue
                break

    def _check_functions(self) -> None:
        def visit(node: ast.AST, prefix: str) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    name = f"{prefix}.{child.name}" if prefix else child.name
                    for suite in self._suites(child):
                        self._check_suite(name, suite)
                    visit(child, name)
                elif isinstance(child, ast.ClassDef):
                    visit(child, child.name)
                else:
                    visit(child, prefix)

        visit(self.tree, "")

    @staticmethod
    def _suites(fn: ast.AST) -> List[Sequence[ast.stmt]]:
        out: List[Sequence[ast.stmt]] = [fn.body] if getattr(fn, "body", None) else []
        for node in walk_shallow(fn):
            for field in ("body", "orelse", "finalbody"):
                suite = getattr(node, field, None)
                if isinstance(suite, list) and suite and isinstance(suite[0], ast.stmt):
                    out.append(suite)
        return out

    # -------------------------------------------------- carry materialise

    def _carry_attrs(self, cls: ast.ClassDef) -> Set[str]:
        """Attributes of ``cls`` ever passed as a donated operand."""
        out: Set[str] = set()
        for node in ast.walk(cls):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func)
            if d is None or d not in self.donated:
                continue
            for n in self.donated[d]:
                if n < len(node.args):
                    od = _dotted(node.args[n])
                    if od is not None and len(od) == 2 and od[0] == "self":
                        out.add(od[1])
        return out

    def _check_materialize(self) -> None:
        for cls in ast.walk(self.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            carries = self._carry_attrs(cls)
            if not carries:
                continue
            for method in cls.body:
                if not isinstance(method, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                symbol = f"{cls.name}.{method.name}"
                for attr in carries:
                    self._check_method_materialize(symbol, method, attr)

    def _check_method_materialize(
        self, symbol: str, method: ast.AST, attr: str
    ) -> None:
        carry = ("self", attr)

        def emit(node: ast.AST, how: str) -> None:
            stmt = _stmt_of(method, node)
            if stmt is not None and self._ok(stmt):
                return
            self._emit(
                "donate-materialize", node, symbol,
                f"self.{attr} is a donated job carry — {how} is a full "
                f"device sync mid-job; carry reads belong at job end "
                f"(annotate the sanctioned fetch with # donate-ok:)",
            )

        for node in ast.walk(method):
            if isinstance(node, ast.Call):
                f = node.func
                if (
                    isinstance(f, ast.Name)
                    and f.id in _MATERIALIZE_NAMES
                    and any(_contains(a, carry) for a in node.args)
                ):
                    emit(node, f"{f.id}() over it")
                elif (
                    isinstance(f, ast.Attribute)
                    and f.attr in _MATERIALIZE_ATTRS
                    and any(_contains(a, carry) for a in node.args)
                ):
                    emit(node, f".{f.attr}() of it")
                elif (
                    isinstance(f, ast.Attribute)
                    and f.attr == "block_until_ready"
                    and _contains(f.value, carry)
                ):
                    emit(node, ".block_until_ready() on it")
            elif isinstance(node, ast.For) and _contains(node.iter, carry):
                emit(node, "iterating it")
            elif isinstance(node, (ast.GeneratorExp, ast.ListComp, ast.SetComp, ast.DictComp)):
                if any(_contains(g.iter, carry) for g in node.generators):
                    emit(node, "iterating it")

    def check(self) -> None:
        if not self.donated:
            return
        self._check_functions()
        self._check_materialize()


def _stmt_of(fn: ast.AST, target: ast.AST) -> Optional[ast.stmt]:
    best: Optional[ast.stmt] = None
    for node in ast.walk(fn):
        if isinstance(node, ast.stmt) and target in ast.walk(node):
            if best is None or node.lineno >= best.lineno:
                best = node
    return best


def run(root: Path, scan_dirs: Optional[Tuple[str, ...]] = None) -> List[Finding]:
    """``scan_dirs=None`` scans the whole tree (fixture mode); repo mode
    passes :data:`DONATE_SCAN_DIRS` (see __main__.py)."""
    findings: List[Finding] = []
    for path in iter_py_files(root, scan_dirs):
        try:
            source = path.read_text()
            checker = _FileChecker(rel(path, root), source, findings)
        except (SyntaxError, UnicodeDecodeError):
            continue
        checker.check()
    return findings
