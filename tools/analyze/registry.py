"""The guarded-surface registry: which objects the thread-discipline
machinery (static ``lock`` pass + runtime sanitizer) watches, and how.

Two disciplines exist in this codebase:

- ``external``: pure policy objects with NO internal locking, serialized
  by their owner's event lock (apps/server.serve's ``lock``).  The static
  pass verifies they stay lock- and thread-free inside; the runtime
  sanitizer (utils/sanitize.guard) wraps their instances in serve() and
  raises on any off-lock access once shared.  The serve-loop locals that
  hold them are annotated ``# guarded-by: lock`` at their assignment.
- ``internal``: objects that own a lock and take it themselves; their
  fields carry ``# guarded-by: <lockattr>`` annotations and the static
  pass enforces every access happens under ``with self.<lockattr>:`` (or
  in a helper annotated/`` _locked``-suffixed as called-under-lock).
"""

from __future__ import annotations

#: Externally-serialized policy classes: (module path, class name).
#: The static pass fails if any of these grows a ``threading.`` dependency
#: (an externally-serialized object must never sprout its own threads or
#: locks — that is how two lock disciplines start to interleave).
EXTERNAL_CLASSES = (
    ("bitcoin_miner_tpu/apps/scheduler.py", "Scheduler"),
    ("bitcoin_miner_tpu/gateway/core.py", "Gateway"),
    ("bitcoin_miner_tpu/gateway/cache.py", "ResultCache"),
    ("bitcoin_miner_tpu/gateway/cache.py", "SpanStore"),
    ("bitcoin_miner_tpu/gateway/admission.py", "FairQueue"),
    ("bitcoin_miner_tpu/gateway/admission.py", "TokenBucket"),
    ("bitcoin_miner_tpu/utils/wfq.py", "VirtualClockWFQ"),
    ("bitcoin_miner_tpu/utils/intervals.py", "IntervalMap"),
    ("bitcoin_miner_tpu/federation/gossip.py", "GossipSpanStore"),
    ("bitcoin_miner_tpu/federation/ring.py", "Ring"),
    # Workloads (ISSUE 9) are stateless policy shared read-only by every
    # thread of a process: they must never grow locks or threads (their
    # device-tier factories may RETURN threaded machinery — SweepPipeline
    # et al. — but the workload object itself stays inert).
    ("bitcoin_miner_tpu/workloads/base.py", "Workload"),
    ("bitcoin_miner_tpu/workloads/sha256.py", "Sha256Workload"),
    ("bitcoin_miner_tpu/workloads/blake2b.py", "Blake2bWorkload"),
    # The autoscale CONTROLLER is pure policy serialized by its driver
    # (ControllerPump's single thread, or a test's hand crank); the
    # thread lives in autoscale/actuator.ControllerPump, deliberately
    # outside this class (ISSUE 18).
    ("bitcoin_miner_tpu/autoscale/controller.py", "AutoscaleController"),
)

#: Internally-locked classes expected to carry ``# guarded-by:`` field
#: annotations.  The static pass warns (rule ``lock-unannotated``) if one
#: of these classes has no annotated fields at all — the annotation set
#: must not silently rot away in a refactor.
INTERNAL_CLASSES = (
    ("bitcoin_miner_tpu/utils/metrics.py", "Metrics"),
    ("bitcoin_miner_tpu/utils/metrics.py", "Histogram"),
    ("bitcoin_miner_tpu/utils/metrics.py", "RateMeter"),
    ("bitcoin_miner_tpu/utils/trace.py", "Tracer"),
    ("bitcoin_miner_tpu/lspnet/chaos.py", "NetSim"),
    ("bitcoin_miner_tpu/utils/fleetview.py", "FleetView"),
    ("bitcoin_miner_tpu/utils/slo.py", "SloEngine"),
    ("bitcoin_miner_tpu/utils/telemetry.py", "TelemetryHub"),
    ("bitcoin_miner_tpu/federation/replica.py", "Replica"),
)

#: Functions whose locals carry ``# guarded-by: <lockvar>`` annotations
#: (the serve-loop discipline).  Informational — the static pass discovers
#: annotations wherever they appear; this names the load-bearing one.
ANNOTATED_FUNCTIONS = (("bitcoin_miner_tpu/apps/server.py", "serve"),)
