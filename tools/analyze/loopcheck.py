"""Pass 7: the asyncio loop-discipline lint (ISSUE 19).

The serving hot paths live on event loops now — the ``AsyncIngress``
read loop, the LSP sync facades' private loops, the federation cell's
one shared fed-port/gossip/forwarder loop — and ONE blocking call on a
loop stalls every conn riding it.  The static half of that contract:

**On-loop code** is any ``async def`` (a coroutine body always runs on
its loop) plus any plain ``def`` whose header carries ``# on-loop:``
(the ``_LoopBridge`` hop targets, ``call_soon_threadsafe`` callbacks,
``_LoopThread`` bodies).  Nested defs inside on-loop code are on-loop
too (they are loop-side closures).  Rules, suppressed per statement
with ``# loop-ok: <reason>``:

- ``loop-blocking-call`` — a blocking primitive in on-loop code:
  ``time.sleep``, ``open()``, or a non-awaited ``.result()`` /
  ``.acquire()`` / ``.read()``/``.readline()`` / ``.recv()`` call (the
  Future-wait / lock-wait / file- and socket-I/O signatures).
- ``loop-lock`` — a synchronous ``with <lock>:`` in on-loop code (any
  context expression spelled like a lock: a name or attribute containing
  ``lock`` / ``_mu``).  The event plane takes the event lock on the
  ingress loop BY DESIGN — that path is a plain method reached through
  the read loop, not an annotated/async body, so it is out of scope
  here; the runtime detector (utils/sanitize.py) guards it with the
  lock->loop edge query instead of a blanket ban.
- ``loop-off-thread-write`` — a class field declared loop-owned
  (``# on-loop: <loopattr>`` on its ``self.<field> = ...`` assignment)
  is called/mutated from a method that is NOT on-loop, outside the
  ``threading.current_thread() is self.<attr>`` identity fast path and
  outside a ``call_soon_threadsafe``/``run_coroutine_threadsafe`` hop.
  The finding message spells the fix (``hop via
  self.<loopattr>.call_soon_threadsafe(...)``) — lockfix.py's
  ``--fix`` mode parses that spelling to auto-wrap the simple cases.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

from .common import (
    LOOP_OK_RE,
    ON_LOOP_RE,
    Finding,
    comment_in_span,
    file_comments,
    iter_py_files,
    rel,
    walk_shallow,
)

PASS = "loop"

#: Attribute-call names that block the calling thread (the Future-wait /
#: lock-wait / file- and socket-I/O signatures).  ``.join`` is NOT here:
#: ``str.join`` is everywhere and a statically-typed receiver is beyond
#: a lint — thread joins on a loop surface via ``sanitize.blocking``.
_BLOCKING_ATTRS = {"result", "acquire", "read", "readline", "readlines",
                   "recv", "recv_into", "accept"}

_HOP_CALLS = {"call_soon_threadsafe", "run_coroutine_threadsafe"}


def _dotted(node: ast.AST) -> Optional[Tuple[str, ...]]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


def _header_match(comments: Dict[int, str], fn: ast.AST, pattern) -> Optional[object]:
    """A pattern match on the function HEADER only (def line through the
    line before the first body statement) — body comments must not mark
    the whole function."""
    first_body = fn.body[0].lineno if getattr(fn, "body", None) else fn.lineno
    return comment_in_span(comments, fn.lineno, max(fn.lineno, first_body - 1), pattern)


def _ok(comments: Dict[int, str], stmt: ast.AST) -> bool:
    return (
        comment_in_span(
            comments, stmt.lineno, getattr(stmt, "end_lineno", None), LOOP_OK_RE
        )
        is not None
    )


def _lockish(expr: ast.AST) -> bool:
    """A context expression spelled like a lock: ``self._lock``,
    ``lock``, ``self._mu``, ``self._prewarm_lock`` ..."""
    d = _dotted(expr)
    if d is None:
        return False
    leaf = d[-1].lower()
    return "lock" in leaf or leaf in ("_mu", "mu")


def _awaited_calls(fn: ast.AST) -> Set[ast.Call]:
    """Call nodes that sit directly under an ``await`` (or inside one's
    argument chain of asyncio.wait_for-style wrappers) — they yield, not
    block."""
    out: Set[ast.Call] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Await):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    out.add(sub)
    return out


class _FileChecker:
    def __init__(
        self, path: str, source: str, findings: List[Finding]
    ) -> None:
        self.path = path
        self.comments = file_comments(source)
        self.findings = findings
        self.tree = ast.parse(source)

    def _emit(self, rule: str, node: ast.AST, symbol: str, msg: str) -> None:
        self.findings.append(
            Finding(PASS, rule, self.path, node.lineno, symbol, msg)
        )

    # ------------------------------------------------------- on-loop bodies

    def _on_loop_functions(self) -> List[Tuple[str, ast.AST]]:
        """(symbol, fn) for every on-loop function: async defs, annotated
        defs, and their nested defs."""
        out: List[Tuple[str, ast.AST]] = []

        def visit(node: ast.AST, prefix: str, inherited: bool) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    name = f"{prefix}.{child.name}" if prefix else child.name
                    on_loop = (
                        inherited
                        or isinstance(child, ast.AsyncFunctionDef)
                        or _header_match(self.comments, child, ON_LOOP_RE)
                        is not None
                    )
                    if on_loop:
                        out.append((name, child))
                    visit(child, name, on_loop)
                elif isinstance(child, ast.ClassDef):
                    visit(child, child.name, False)
                else:
                    visit(child, prefix, inherited)

        visit(self.tree, "", False)
        return out

    def _check_body(self, symbol: str, fn: ast.AST) -> None:
        awaited = _awaited_calls(fn)
        for stmt in walk_shallow(fn):
            # nested defs are checked under their own symbol (walk_shallow
            # does not descend into them)
            if not isinstance(stmt, ast.stmt) or self._ok_stmt(stmt):
                continue
            if isinstance(stmt, ast.With):
                for item in stmt.items:
                    if _lockish(item.context_expr):
                        self._emit(
                            "loop-lock",
                            stmt,
                            symbol,
                            "synchronous lock taken in on-loop code — a "
                            "contended acquire stalls every conn on the "
                            "loop; move the locked work off-loop or use "
                            "the call_soon_threadsafe hop",
                        )
            for node in self._own_exprs(stmt):
                for call in ast.walk(node):
                    if not isinstance(call, ast.Call) or call in awaited:
                        continue
                    d = _dotted(call.func)
                    if d == ("time", "sleep"):
                        self._emit(
                            "loop-blocking-call", call, symbol,
                            "time.sleep() in on-loop code — use "
                            "asyncio.sleep (or move the wait off-loop)",
                        )
                    elif isinstance(call.func, ast.Name) and call.func.id == "open":
                        self._emit(
                            "loop-blocking-call", call, symbol,
                            "file I/O (open) in on-loop code blocks the "
                            "loop for the whole syscall",
                        )
                    elif (
                        isinstance(call.func, ast.Attribute)
                        and call.func.attr in _BLOCKING_ATTRS
                    ):
                        self._emit(
                            "loop-blocking-call", call, symbol,
                            f".{call.func.attr}() in on-loop code is a "
                            "blocking wait — await the async spelling or "
                            "hop the work off the loop",
                        )

    def _ok_stmt(self, stmt: ast.stmt) -> bool:
        return _ok(self.comments, stmt)

    @staticmethod
    def _own_exprs(stmt: ast.stmt) -> List[ast.AST]:
        """The statement's own expressions (compound statements contribute
        their headers; their suites re-enter via the ast.walk over fn)."""
        if isinstance(stmt, (ast.If, ast.While)):
            return [stmt.test]
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            return [stmt.iter]
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            return [i.context_expr for i in stmt.items]
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Try)):
            return []
        return [stmt]

    # ------------------------------------------------- loop-owned fields

    def _check_loop_owned_fields(self) -> None:
        for cls in ast.walk(self.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            owned: Dict[str, str] = {}  # field -> loop attr
            for stmt in ast.walk(cls):
                if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
                    continue
                t = stmt.targets[0]
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    m = comment_in_span(
                        self.comments, stmt.lineno,
                        getattr(stmt, "end_lineno", None), ON_LOOP_RE,
                    )
                    if m is not None:
                        owned[t.attr] = m.group(1) or "_loop"
            if not owned:
                continue
            for method in cls.body:
                if not isinstance(method, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if isinstance(method, ast.AsyncFunctionDef):
                    continue  # on-loop by definition
                if method.name == "__init__":
                    continue  # construction happens on the loop
                if _header_match(self.comments, method, ON_LOOP_RE) is not None:
                    continue
                self._check_off_thread_writes(cls.name, method, owned)

    def _check_off_thread_writes(
        self, cls_name: str, method: ast.FunctionDef, owned: Dict[str, str]
    ) -> None:
        guarded = self._identity_guarded_nodes(method)
        for node in walk_shallow(method):
            if not isinstance(node, ast.Call) or node in guarded:
                continue
            f = node.func
            if not (
                isinstance(f, ast.Attribute)
                and isinstance(f.value, ast.Attribute)
                and isinstance(f.value.value, ast.Name)
                and f.value.value.id == "self"
                and f.value.attr in owned
            ):
                continue
            stmt = self._stmt_of(method, node)
            if stmt is not None and self._ok_stmt(stmt):
                continue
            field, meth = f.value.attr, f.attr
            loopattr = owned[field]
            self._emit(
                "loop-off-thread-write",
                node,
                f"{cls_name}.{method.name}",
                f"call on loop-owned field self.{field} off the loop "
                f"thread — hop via self.{loopattr}.call_soon_threadsafe"
                f"(self.{field}.{meth}, ...)",
            )

    @staticmethod
    def _stmt_of(fn: ast.AST, target: ast.AST) -> Optional[ast.stmt]:
        """The innermost statement containing ``target``."""
        best: Optional[ast.stmt] = None
        for node in ast.walk(fn):
            if isinstance(node, ast.stmt) and target in ast.walk(node):
                if best is None or node.lineno >= best.lineno:
                    best = node
        return best

    @staticmethod
    def _identity_guarded_nodes(method: ast.FunctionDef) -> Set[ast.AST]:
        """Nodes inside (a) the body of a thread-identity fast path
        (``if threading.current_thread() is self.<attr>:``) or (b) the
        arguments of a threadsafe hop call — both are the sanctioned
        spellings, not violations."""
        out: Set[ast.AST] = set()
        for node in ast.walk(method):
            if isinstance(node, ast.If):
                has_identity = any(
                    isinstance(c, ast.Call)
                    and (d := _dotted(c.func)) is not None
                    and d[-1] == "current_thread"
                    for c in ast.walk(node.test)
                )
                if has_identity:
                    for stmt in node.body:
                        out.update(ast.walk(stmt))
            if isinstance(node, ast.Call):
                d = _dotted(node.func)
                if d is not None and d[-1] in _HOP_CALLS:
                    for arg in list(node.args) + [kw.value for kw in node.keywords]:
                        out.update(ast.walk(arg))
        return out

    def check(self) -> None:
        for symbol, fn in self._on_loop_functions():
            self._check_body(symbol, fn)
        self._check_loop_owned_fields()


def run(root: Path, scan_dirs: Optional[Tuple[str, ...]] = None) -> List[Finding]:
    findings: List[Finding] = []
    for path in iter_py_files(root, scan_dirs):
        try:
            source = path.read_text()
            checker = _FileChecker(rel(path, root), source, findings)
        except (SyntaxError, UnicodeDecodeError):
            continue
        checker.check()
    return findings
