"""Pass 6: the metric-registry cross-check.

``utils/metrics.py`` carries a documented registry block (the contiguous
``#:`` comment lines directly above the ``METRICS = Metrics()``
assignment).  Before this pass, that block was free-form documentation —
nothing stopped a new ``METRICS.inc("gatway.requets")`` typo from minting
a silently-uncounted counter, or a refactor from leaving a documented
name that nothing increments (both happened: ``lsp.dropped_horizon`` and
the whole ``gateway.span_*`` family shipped undocumented).

Rules:

- ``metric-undocumented`` — a name passed to an emitter anywhere in the
  scan tree does not appear in the registry block.
- ``metric-unused`` — a registry name no emitter anywhere ever emits
  (documented-but-never-incremented: dead doc or a dropped call site).
- ``metric-kind-mismatch`` — the emitter does not match the name's kind:
  ``hist.*`` names take ``observe``, ``gauge.*`` AND ``fleet.*`` names
  take ``set_gauge`` (the merged fleet-view levels the telemetry hub
  publishes, ISSUE 7), everything else takes ``inc``.
- ``metric-dynamic-name`` — an emitter whose name argument is not a
  string literal (a computed name can never be registry-checked; read
  paths like ``METRICS.get(f"sched.{k}")`` are exempt — only emitters
  mint names).  A ``# metric-ok: <names...>`` comment on the statement
  declares which documented names the dynamic emit covers (``chaos.*``
  glob form marks a whole documented prefix) — the declared names count
  as emitted and the finding is suppressed.

Emitters are calls on the process-wide registry object: a ``METRICS``
receiver with method ``inc`` / ``observe`` / ``set_gauge``.  Local
``Metrics()`` instances (unit tests, fixtures) are out of scope in repo
mode because tests are outside the scan dirs.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

from .common import Finding, comment_in_span, file_comments, iter_py_files, rel

PASS = "metrics"

#: Emitter method -> the name-prefix kind it must be used with.
EMITTERS = {"inc": "counter", "observe": "hist", "set_gauge": "gauge"}

#: ``# metric-ok: name [name...]`` — declares the documented names a
#: dynamic emit covers (``prefix.*`` marks every documented name under
#: that prefix).
METRIC_OK_RE = re.compile(r"metric-ok:\s*([A-Za-z0-9_.*,\s]+)")

#: A registry line: ``#:``, >= 2 spaces, a dotted lowercase name, then a
#: description.  Header/prose lines (one space, capitalised, no dotted
#: name) never match.
_REGISTRY_LINE = re.compile(r"^#:\s{2,}([a-z][a-z0-9_]*(?:\.[a-z0-9_]+)+)\s+\S")

_METRICS_ASSIGN = re.compile(r"^METRICS\s*=", re.MULTILINE)


def _name_kind(name: str) -> str:
    if name.startswith("hist."):
        return "hist"
    if name.startswith(
        (
            "gauge.", "fleet.", "fed.peer_state", "fed.conns_live",
            "gw.conns_live", "kernel.thresh_staleness",
            "autoscale.target_workers",
        )
    ):
        # fed.peer_state[.<peer>] is the per-peer membership gauge family
        # (ISSUE 12) and fed.conns_live the federation transport's
        # live-conn level (ISSUE 18); the rest of fed.* stays
        # counter-kind.  gw.conns_live is the ingress live-conn gauge
        # (ISSUE 15) — the only gauge-kind name under gw.*.
        # kernel.thresh_staleness is the hot plane's sieve-threshold lag
        # level (ISSUE 16) — the one gauge-kind name under kernel.*,
        # while sweep.* stays counter-kind.  autoscale.target_workers is
        # the controller's worker-target level (ISSUE 18); the other
        # autoscale.* names count actions and stay counters.
        return "gauge"
    return "counter"


def _parse_registry(source: str) -> Optional[Dict[str, int]]:
    """name -> line number, from the contiguous ``#:`` block directly
    above the module-level ``METRICS = ...`` assignment; None if the file
    defines no registry."""
    lines = source.splitlines()
    assign_at = None
    for i, line in enumerate(lines):
        if _METRICS_ASSIGN.match(line):
            assign_at = i
            break
    if assign_at is None:
        return None
    out: Dict[str, int] = {}
    j = assign_at - 1
    while j >= 0 and lines[j].startswith("#:"):
        m = _REGISTRY_LINE.match(lines[j])
        if m:
            out[m.group(1)] = j + 1
        j -= 1
    return out


def _emitter_calls(
    tree: ast.Module, comments: Dict[int, str]
) -> List[Tuple[str, Optional[str], int, Optional[str]]]:
    """Every ``METRICS.<emitter>(...)`` call: (method, literal name or
    None when dynamic, line, metric-ok declaration text or None)."""
    out: List[Tuple[str, Optional[str], int, Optional[str]]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not (
            isinstance(f, ast.Attribute)
            and f.attr in EMITTERS
            and isinstance(f.value, ast.Name)
            and f.value.id == "METRICS"
        ):
            continue
        name: Optional[str] = None
        if node.args and isinstance(node.args[0], ast.Constant) and isinstance(
            node.args[0].value, str
        ):
            name = node.args[0].value
        ok = comment_in_span(
            comments, node.lineno, getattr(node, "end_lineno", None),
            METRIC_OK_RE,
        )
        out.append((f.attr, name, node.lineno, ok.group(1) if ok else None))
    return out


def run(root: Path, scan_dirs: Optional[Tuple[str, ...]] = None) -> List[Finding]:
    findings: List[Finding] = []
    registry: Dict[str, int] = {}
    registry_path: Optional[str] = None
    uses: List[Tuple[str, str, Optional[str], int, Optional[str]]] = []
    for path in iter_py_files(root, scan_dirs):
        try:
            source = path.read_text()
            tree = ast.parse(source)
        except (SyntaxError, UnicodeDecodeError):
            continue  # the lock pass reports parse errors once
        rpath = rel(path, root)
        reg = _parse_registry(source)
        if reg is not None:
            # One registry per scan tree (utils/metrics.py in repo mode,
            # bad_metric.py in fixture mode); a second one merges so the
            # cross-check still covers every documented name.
            registry.update(reg)
            registry_path = registry_path or rpath
        for method, name, line, ok in _emitter_calls(tree, file_comments(source)):
            uses.append((rpath, method, name, line, ok))
    if registry_path is None:
        return findings  # no registry in this tree: nothing to check against

    emitted: Set[str] = set()
    for rpath, method, name, line, ok in uses:
        if ok is not None:
            # Declared coverage of a dynamic (or literal) emit: each
            # token is marked emitted; ``prefix.*`` covers the whole
            # documented prefix.  Unknown literal tokens still fail.
            for token in re.split(r"[,\s]+", ok.strip()):
                if not token:
                    continue
                if token.endswith(".*"):
                    prefix = token[:-1]  # keep the trailing dot
                    emitted.update(
                        n for n in registry if n.startswith(prefix)
                    )
                elif token in registry:
                    emitted.add(token)
                else:
                    findings.append(
                        Finding(
                            PASS,
                            "metric-undocumented",
                            rpath,
                            line,
                            token,
                            "metric-ok declares a name that is not in the "
                            "documented registry block",
                        )
                    )
            if name is None:
                continue  # dynamic emit, coverage declared: done
        if name is None:
            findings.append(
                Finding(
                    PASS,
                    "metric-dynamic-name",
                    rpath,
                    line,
                    f"METRICS.{method}",
                    "metric name is not a string literal — computed names "
                    "cannot be registry-checked; emit a documented literal "
                    "or declare coverage with `# metric-ok: <names>`",
                )
            )
            continue
        emitted.add(name)
        if name not in registry:
            findings.append(
                Finding(
                    PASS,
                    "metric-undocumented",
                    rpath,
                    line,
                    name,
                    "name is not in the documented registry block in "
                    "utils/metrics.py — add it (or fix the typo)",
                )
            )
        elif EMITTERS[method] != _name_kind(name):
            findings.append(
                Finding(
                    PASS,
                    "metric-kind-mismatch",
                    rpath,
                    line,
                    name,
                    f"emitted via {method}() but the name's prefix says "
                    f"{_name_kind(name)} (hist.* -> observe, gauge.*/"
                    f"fleet.* -> set_gauge, else inc)",
                )
            )
    for name, line in sorted(registry.items()):
        if name not in emitted:
            findings.append(
                Finding(
                    PASS,
                    "metric-unused",
                    registry_path,
                    line,
                    name,
                    "documented in the registry but never emitted anywhere "
                    "in the scan tree — dead doc, or its call site was "
                    "dropped",
                )
            )
    return findings
