"""Pass: the race-sanitizer machinery check (the runtime leg).

``BMT_SANITIZE=1`` is a *runtime* tool — it finds races while the chaos
soak and gateway suites actually run (tests/test_analyze.py wires it into
both).  What a static analyzer run can and does verify, in milliseconds:

- **Repo mode**: the machinery itself works end to end — a TrackedLock +
  Monitor around a real ``Scheduler`` driven correctly from two threads
  is silent; the same setup driven with a deliberate off-lock access and
  an ABBA acquisition raises.  A sanitizer that cannot detect is worse
  than none (green soaks would certify nothing), so "failed to fire" is
  itself a finding.
- **Fixture mode** (a ``bad_race.py`` under ``--root``): import it and
  run each ``provoke_*()``; every RaceError / LockOrderError raised is
  reported as a finding — the seeded violation demonstrably fires, and
  the CLI exits non-zero on it.
"""

from __future__ import annotations

import importlib.util
import threading
from pathlib import Path
from typing import Any, List, Optional, Tuple

from .common import Finding, rel

PASS = "sanitize"


def _load_module(path: Path) -> Any:
    spec = importlib.util.spec_from_file_location(path.stem, path)
    assert spec is not None and spec.loader is not None
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _machinery_selftest() -> List[Finding]:
    """Repo mode: the sanitizer must be quiet on disciplined use and loud
    on violations, against the real guarded classes."""
    from bitcoin_miner_tpu.apps.scheduler import Scheduler
    from bitcoin_miner_tpu.utils import sanitize

    findings: List[Finding] = []
    path = "bitcoin_miner_tpu/utils/sanitize.py"
    sanitize.force(True)
    try:
        sanitize.reset_order_graph()
        lock = sanitize.make_lock("analyze.selftest")
        sched = sanitize.guard(Scheduler(), lock, "scheduler")
        errors: List[BaseException] = []

        def disciplined() -> None:
            try:
                for i in range(50):
                    with lock:
                        sched.stats()
            except BaseException as e:  # noqa: BLE001 — report, don't die
                errors.append(e)

        threads = [threading.Thread(target=disciplined) for _ in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            findings.append(
                Finding(
                    PASS,
                    "false-positive",
                    path,
                    1,
                    "Monitor",
                    f"sanitizer raised on correctly-locked access: "
                    f"{errors[0]!r}",
                )
            )
        # Detection leg: an off-lock access after sharing MUST raise.
        try:
            sched.stats()
            findings.append(
                Finding(
                    PASS,
                    "failed-to-fire",
                    path,
                    1,
                    "Monitor",
                    "off-lock access to a shared guarded object did not "
                    "raise RaceError — the sanitizer is blind",
                )
            )
        except sanitize.RaceError:
            pass
        # Lock-order leg: ABBA must raise deterministically.
        sanitize.reset_order_graph()
        a = sanitize.TrackedLock("analyze.A")
        b = sanitize.TrackedLock("analyze.B")
        with a:
            with b:
                pass
        try:
            with b:
                with a:
                    pass
            findings.append(
                Finding(
                    PASS,
                    "failed-to-fire",
                    path,
                    1,
                    "TrackedLock",
                    "ABBA acquisition did not raise LockOrderError",
                )
            )
        except sanitize.LockOrderError:
            pass
    finally:
        sanitize.force(None)
        sanitize.reset_order_graph()
    return findings


def run(root: Path, scan_dirs: Optional[Tuple[str, ...]] = None) -> List[Finding]:
    from bitcoin_miner_tpu.utils import sanitize

    # Fixture mode only applies when scanning an explicit --root tree;
    # repo mode (scan_dirs set) must not trip over the checked-in fixtures.
    fixture = None
    if scan_dirs is None and root.is_dir():
        for c in [root / "bad_race.py", *sorted(root.rglob("bad_race.py"))]:
            if c.exists() and "__pycache__" not in c.parts:
                fixture = c
                break
    if fixture is None:
        return _machinery_selftest()

    findings: List[Finding] = []
    mod = _load_module(fixture)
    sanitize.force(True)
    try:
        sanitize.reset_order_graph()
        for name in dir(mod):
            if not name.startswith("provoke"):
                continue
            try:
                getattr(mod, name)()
            except (sanitize.RaceError, sanitize.LockOrderError) as e:
                findings.append(
                    Finding(
                        PASS,
                        "race-detected",
                        rel(fixture, root),
                        1,
                        name,
                        f"{type(e).__name__}: {e}",
                    )
                )
    finally:
        sanitize.force(None)
        sanitize.reset_order_graph()
    return findings
