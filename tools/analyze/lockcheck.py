"""Pass 1: the AST lock-discipline checker.

Annotation vocabulary (see README "Static analysis & sanitizers"):

- ``self.field = ...  # guarded-by: _lock`` — every access to
  ``self.field`` outside ``__init__`` must happen inside a
  ``with self._lock:`` block, in a method whose name ends in ``_locked``,
  or in a method whose ``def`` line carries its own
  ``# guarded-by: _lock`` (the called-under-the-lock helper convention).
- ``def _helper(self):  # guarded-by: _lock`` — the helper body is
  assumed to hold ``_lock`` (callee side), and every ``self._helper()``
  call site must itself hold ``_lock`` (caller side).
- ``var = ...  # guarded-by: lock`` on a function local — the serve-loop
  discipline: every *read* of ``var`` in that function and its closures
  must be inside ``with lock:``.  Nested ``def``s annotated the same way
  are assumed-holding and get the call-site check.
- ``# unguarded: <reason>`` anywhere on the statement suppresses the
  finding (the documented-intentional escape hatch, e.g. the chaos
  engine's benign racy ``_enabled`` fast path).

Explicit ``lock.acquire()`` / ``lock.release()`` pairs are understood
beyond ``with`` blocks (ISSUE 5): a bare ``self._lock.acquire()`` (or
``lock.acquire()``) *statement* marks the lock held for the statements
that follow it in the same suite — including a ``try`` body whose
``finally`` releases, the canonical pairing idiom — and a ``release()``
anywhere inside a compound statement ends the credit when that statement
completes, so a read AFTER the release is flagged again.  Only bare
expression statements earn held-credit: an assigned
``ok = lock.acquire(timeout=...)`` may have failed, and an acquire inside
a conditional branch is not assumed on the fall-through path.

Two registry rules ride along: externally-serialized policy classes
(Scheduler, Gateway, ...) must never grow a ``threading.`` dependency,
and internally-locked classes must not lose their annotations entirely.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

from .common import (
    GUARDED_BY_RE,
    UNGUARDED_RE,
    Finding,
    comment_in_span,
    file_comments,
    iter_py_files,
    rel,
)
from .registry import EXTERNAL_CLASSES, INTERNAL_CLASSES

PASS = "lock"


def _stmt_suppressed(comments: Dict[int, str], stmt: ast.stmt) -> bool:
    return (
        comment_in_span(
            comments, stmt.lineno, getattr(stmt, "end_lineno", None), UNGUARDED_RE
        )
        is not None
    )


def _def_line_guard(comments: Dict[int, str], fn: ast.FunctionDef) -> Optional[str]:
    """A ``# guarded-by: X`` on the def line (not the whole body)."""
    text = comments.get(fn.lineno)
    if text:
        m = GUARDED_BY_RE.search(text)
        if m:
            return m.group(1)
    return None


def _with_locks(stmt: ast.With) -> Set[str]:
    """Lock names a ``with`` statement acquires: ``self.X`` -> X,
    bare ``name`` -> name."""
    out: Set[str] = set()
    for item in stmt.items:
        name = _lock_name(item.context_expr)
        if name is not None:
            out.add(name)
    return out


def _lock_name(e: ast.AST) -> Optional[str]:
    """``self.X`` -> ``X``, bare ``name`` -> ``name`` (the two spellings
    the annotation vocabulary uses for lock references)."""
    if (
        isinstance(e, ast.Attribute)
        and isinstance(e.value, ast.Name)
        and e.value.id == "self"
    ):
        return e.attr
    if isinstance(e, ast.Name):
        return e.id
    return None


def _pair_call(stmt: ast.stmt, which: str) -> Set[str]:
    """Lock names from a bare ``X.acquire()`` / ``X.release()`` expression
    statement.  Statements only: an assigned ``ok = lock.acquire(...)``
    may have returned False, so it earns no held-credit."""
    out: Set[str] = set()
    if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
        f = stmt.value.func
        if isinstance(f, ast.Attribute) and f.attr == which:
            name = _lock_name(f.value)
            if name is not None:
                out.add(name)
    return out


def _releases_within(stmt: ast.stmt) -> Set[str]:
    """Every lock ``release()``d anywhere inside ``stmt`` — nested defs
    excluded (a closure's release happens on some later call, not on this
    control path).  Used to END the held-credit once a compound statement
    (typically ``try ... finally: lock.release()``) completes."""
    out: Set[str] = set()
    stack: List[ast.AST] = [stmt]
    while stack:
        node = stack.pop()
        if (
            isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            and node is not stmt
        ):
            continue
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "release":
                name = _lock_name(f.value)
                if name is not None:
                    out.add(name)
        stack.extend(ast.iter_child_nodes(node))
    return out


class _ClassChecker:
    """Field + helper-call discipline within one class."""

    def __init__(
        self, path: str, cls: ast.ClassDef, comments: Dict[int, str]
    ) -> None:
        self.path = path
        self.cls = cls
        self.comments = comments
        self.guarded_fields: Dict[str, str] = {}  # field -> lock attr
        self.guarded_methods: Dict[str, str] = {}  # helper -> lock attr
        self.findings: List[Finding] = []
        self._collect()

    def _collect(self) -> None:
        for fn in self.cls.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            guard = _def_line_guard(self.comments, fn)
            if guard is not None:
                self.guarded_methods[fn.name] = guard
            for stmt in ast.walk(fn):
                if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                    continue
                m = comment_in_span(
                    self.comments,
                    stmt.lineno,
                    getattr(stmt, "end_lineno", None),
                    GUARDED_BY_RE,
                )
                if m is None:
                    continue
                targets = (
                    stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
                )
                for t in targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        self.guarded_fields[t.attr] = m.group(1)

    def check(self) -> List[Finding]:
        if not self.guarded_fields and not self.guarded_methods:
            return []
        for fn in self.cls.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if fn.name == "__init__" or fn.name.endswith("_locked"):
                continue  # construction window / callee-holds convention
            pre_held: Set[str] = set()
            guard = _def_line_guard(self.comments, fn)
            if guard is not None:
                pre_held.add(guard)
            self._walk(fn.body, pre_held, fn.name, fn)
        return self.findings

    def _walk(
        self,
        body: List[ast.stmt],
        held: Set[str],
        method: str,
        func: ast.AST,
    ) -> None:
        held = set(held)  # sequential acquire()/release() mutate a copy
        for stmt in body:
            acq = _pair_call(stmt, "acquire")
            rel = _pair_call(stmt, "release")
            if acq or rel:
                held |= acq
                held -= rel
                continue
            if isinstance(stmt, ast.With):
                inner = held | _with_locks(stmt)
                self._check_exprs(stmt, held, method, stmt, header_only=True)
                self._walk(stmt.body, inner, method, func)
                continue
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # A nested closure runs later, on whatever thread calls it:
                # it inherits no held locks (unless its own def says so).
                nested_held: Set[str] = set()
                guard = _def_line_guard(self.comments, stmt)
                if guard is not None:
                    nested_held.add(guard)
                self._walk(stmt.body, nested_held, method, stmt)
                continue
            self._check_exprs(stmt, held, method, stmt, header_only=False)
            for field_name in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field_name, None)
                if sub:
                    self._walk(sub, held, method, func)
            for handler in getattr(stmt, "handlers", ()) or ():
                self._walk(handler.body, held, method, func)
            # try/finally: lock.release() (or any release in a branch):
            # the credit ends when the compound statement completes.
            held -= _releases_within(stmt)

    def _check_exprs(
        self,
        stmt: ast.stmt,
        held: Set[str],
        method: str,
        span_stmt: ast.stmt,
        header_only: bool,
    ) -> None:
        """Check the expressions directly attached to ``stmt`` (for
        compound statements, only the header — children walk separately
        with their own held sets)."""
        nodes: List[ast.AST] = []
        if header_only or isinstance(
            stmt,
            (ast.If, ast.While, ast.For, ast.AsyncFor, ast.Try, ast.With),
        ):
            # Header expressions only: test/iter/items — body handled in _walk.
            for attr in ("test", "iter", "items"):
                v = getattr(stmt, attr, None)
                if v is None:
                    continue
                if attr == "items":
                    nodes.extend(i.context_expr for i in v)
                else:
                    nodes.append(v)
        else:
            nodes.append(stmt)
        for root in nodes:
            for node in ast.walk(root):
                self._check_node(node, held, method, span_stmt)

    def _check_node(
        self, node: ast.AST, held: Set[str], method: str, stmt: ast.stmt
    ) -> None:
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        ):
            lock = self.guarded_fields.get(node.attr)
            if lock is not None and lock not in held:
                if not _stmt_suppressed(self.comments, stmt):
                    self.findings.append(
                        Finding(
                            PASS,
                            "field-off-lock",
                            self.path,
                            node.lineno,
                            f"{self.cls.name}.{node.attr}",
                            f"access in {method}() without holding "
                            f"self.{lock} (add `with self.{lock}:` or an "
                            f"`# unguarded:` justification)",
                        )
                    )
        if isinstance(node, ast.Call):
            f = node.func
            if (
                isinstance(f, ast.Attribute)
                and isinstance(f.value, ast.Name)
                and f.value.id == "self"
            ):
                lock = self.guarded_methods.get(f.attr)
                if lock is not None and lock not in held:
                    if not _stmt_suppressed(self.comments, stmt):
                        self.findings.append(
                            Finding(
                                PASS,
                                "helper-off-lock",
                                self.path,
                                node.lineno,
                                f"{self.cls.name}.{f.attr}",
                                f"call from {method}() without holding "
                                f"self.{lock} (the helper's def line says "
                                f"it runs under that lock)",
                            )
                        )


class _FunctionChecker:
    """Function-local discipline: ``var = ...  # guarded-by: lock``."""

    def __init__(
        self, path: str, fn: ast.FunctionDef, comments: Dict[int, str]
    ) -> None:
        self.path = path
        self.fn = fn
        self.comments = comments
        self.guarded_locals: Dict[str, str] = {}
        self.guarded_funcs: Dict[str, str] = {}  # nested def -> lock var
        self.findings: List[Finding] = []
        self._collect(fn)

    def _collect(self, fn: ast.AST) -> None:
        for node in ast.walk(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                guard = _def_line_guard(self.comments, node)
                if guard is not None and node is not self.fn:
                    self.guarded_funcs[node.name] = guard
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                m = comment_in_span(
                    self.comments,
                    node.lineno,
                    getattr(node, "end_lineno", None),
                    GUARDED_BY_RE,
                )
                if m is None:
                    continue
                targets = (
                    node.targets if isinstance(node, ast.Assign) else [node.target]
                )
                for t in targets:
                    if isinstance(t, ast.Name):
                        self.guarded_locals[t.id] = m.group(1)

    def check(self) -> List[Finding]:
        if not self.guarded_locals:
            return []
        self._walk(self.fn.body, set())
        return self.findings

    def _walk(self, body: List[ast.stmt], held: Set[str]) -> None:
        held = set(held)  # sequential acquire()/release() mutate a copy
        for stmt in body:
            acq = _pair_call(stmt, "acquire")
            rel = _pair_call(stmt, "release")
            if acq or rel:
                held |= acq
                held -= rel
                continue
            if isinstance(stmt, ast.With):
                self._check_stmt_header(stmt, held)
                self._walk(stmt.body, held | _with_locks(stmt))
                continue
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                nested: Set[str] = set()
                guard = _def_line_guard(self.comments, stmt)
                if guard is not None:
                    nested.add(guard)
                self._walk(stmt.body, nested)
                continue
            if isinstance(stmt, (ast.If, ast.While, ast.For, ast.AsyncFor, ast.Try)):
                self._check_stmt_header(stmt, held)
                for field_name in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, field_name, None)
                    if sub:
                        self._walk(sub, held)
                for handler in getattr(stmt, "handlers", ()) or ():
                    self._walk(handler.body, held)
                held -= _releases_within(stmt)  # credit ends with the try
                continue
            self._check_expr(stmt, held, stmt)

    def _check_stmt_header(self, stmt: ast.stmt, held: Set[str]) -> None:
        for attr in ("test", "iter"):
            v = getattr(stmt, attr, None)
            if v is not None:
                self._check_expr(v, held, stmt)
        for item in getattr(stmt, "items", ()) or ():
            self._check_expr(item.context_expr, held, stmt)

    def _check_expr(self, root: ast.AST, held: Set[str], stmt: ast.stmt) -> None:
        for node in ast.walk(root):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                lock = self.guarded_locals.get(node.id)
                if lock is not None and lock not in held:
                    if not _stmt_suppressed(self.comments, stmt):
                        self.findings.append(
                            Finding(
                                PASS,
                                "local-off-lock",
                                self.path,
                                node.lineno,
                                f"{self.fn.name}:{node.id}",
                                f"read of {node.id} outside `with {lock}:` "
                                f"(annotated guarded-by at its assignment)",
                            )
                        )
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                lock = self.guarded_funcs.get(node.func.id)
                if lock is not None and lock not in held:
                    if not _stmt_suppressed(self.comments, stmt):
                        self.findings.append(
                            Finding(
                                PASS,
                                "helper-off-lock",
                                self.path,
                                node.lineno,
                                f"{self.fn.name}:{node.func.id}",
                                f"call outside `with {lock}:` (the nested "
                                f"def's line says it runs under that lock)",
                            )
                        )


def _registry_rules(
    path: str, tree: ast.Module, annotated_classes: Set[str]
) -> List[Finding]:
    findings: List[Finding] = []
    ext = {c for p, c in EXTERNAL_CLASSES if p == path}
    internal = {c for p, c in INTERNAL_CLASSES if p == path}
    for node in tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        if node.name in ext:
            for sub in ast.walk(node):
                if (
                    isinstance(sub, ast.Attribute)
                    and isinstance(sub.value, ast.Name)
                    and sub.value.id == "threading"
                ):
                    findings.append(
                        Finding(
                            PASS,
                            "external-grew-threading",
                            path,
                            sub.lineno,
                            node.name,
                            "externally-serialized policy class uses "
                            "threading — it must stay lock- and thread-free "
                            "(the serve event lock is its only discipline)",
                        )
                    )
        if node.name in internal and node.name not in annotated_classes:
            findings.append(
                Finding(
                    PASS,
                    "lock-unannotated",
                    path,
                    node.lineno,
                    node.name,
                    "internally-locked class has no `# guarded-by:` field "
                    "annotations left — the discipline surface rotted away",
                )
            )
    return findings


def run(root: Path, scan_dirs: Optional[Tuple[str, ...]] = None) -> List[Finding]:
    findings: List[Finding] = []
    for path in iter_py_files(root, scan_dirs):
        try:
            source = path.read_text()
            tree = ast.parse(source)
        except (SyntaxError, UnicodeDecodeError) as e:
            findings.append(
                Finding(PASS, "parse-error", rel(path, root), 1, path.name, str(e))
            )
            continue
        comments = file_comments(source)
        rpath = rel(path, root)
        annotated: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                checker = _ClassChecker(rpath, node, comments)
                if checker.guarded_fields:
                    annotated.add(node.name)
                findings.extend(checker.check())
        for node in tree.body:  # module-level functions only (serve, main)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                findings.extend(_FunctionChecker(rpath, node, comments).check())
        findings.extend(_registry_rules(rpath, tree, annotated))
    return findings
