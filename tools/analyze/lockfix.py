"""``python -m tools.analyze lockcheck --fix`` — the mechanical lock fixer.

The lock pass (lockcheck.py) FINDS unguarded accesses; this mode fixes
the subset a machine can fix safely and shows its work for the rest.
Since ISSUE 19 it also repairs loopcheck's ``loop-off-thread-write``
findings: a bare fire-and-forget call on a loop-owned field
(``self._server.write(conn, payload)``) rewrites to the threadsafe hop
the finding message spells (``self._loop.call_soon_threadsafe(
self._server.write, conn, payload)``) — but only when the statement is
a simple expression with plain-name/attribute/constant arguments, no
keywords, and no return-value use.  Anything else (an assignment that
needs the result, starred/keyword args, compound headers, closures)
gets a review block: a fire-and-forget hop cannot return a value or
evaluate rich argument expressions at hop time without changing
semantics.

For the lock rules proper:

- **Safe to wrap**: the flagged access sits in a SIMPLE statement — an
  expression, assignment, augmented assignment or ``return`` occupying
  its own suite slot — that touches exactly one missing lock and
  contains no ``acquire``/``release``/``with`` lock machinery of its
  own.  The statement is rewritten in place as::

      with self._lock:          # (or `with lock:` for serve-loop locals)
          <original statement>

  Adjacent flagged statements needing the same lock in the same suite
  fold into one ``with`` block rather than N nested one-liners.
- **Not safe**: the access lives in a compound-statement header (an
  ``if`` test, a loop iterator, a ``with`` item), inside a lambda or
  comprehension, in a ``def`` line, or the statement needs two
  different locks.  Wrapping those mechanically would change control
  flow (a guarded loop header does not guard the body) — so the fixer
  emits an annotated unified diff of what a human should review
  instead, and leaves the file byte-identical.

Exit status: 0 when every finding was fixed (or there were none),
1 when findings remain that need review.  The rewrite is idempotent:
re-running after a fix finds nothing to do.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List, Optional, Tuple

import re

from . import lockcheck, loopcheck
from .common import Finding, iter_py_files, rel

#: Statement types a machine may wrap: single-suite-slot, no control
#: flow of their own — moving them under a ``with`` cannot change what
#: executes, only what lock is held while it does.
_SIMPLE_STMTS = (ast.Expr, ast.Assign, ast.AugAssign, ast.AnnAssign, ast.Return)

#: loopcheck's message spelling is this module's parse contract (same
#: deal as the lock markers below).
_HOP_RE = re.compile(
    r"hop via self\.(\w+)\.call_soon_threadsafe\(self\.(\w+)\.(\w+), "
)

#: Argument shapes the hop rewrite may carry over verbatim: evaluated at
#: call-schedule time either way, no observable reorder.
_SIMPLE_ARGS = (ast.Name, ast.Attribute, ast.Constant)


def _lock_spelling(symbol: str, lock: str) -> str:
    """The ``with`` target for a finding: class-field findings guard with
    ``self.<lock>``, serve-loop local findings with the bare name."""
    return f"self.{lock}" if "." in symbol else lock


def _finding_lock(f: Finding) -> Optional[str]:
    """The missing lock name, recovered from the finding message (the
    message formats in lockcheck.py are this module's parse contract)."""
    msg = f.message
    for marker in ("without holding self.", "outside `with "):
        at = msg.find(marker)
        if at >= 0:
            rest = msg[at + len(marker):]
            name = rest.split(None, 1)[0].rstrip(":`(")
            return name.rstrip("`:")
    return None


class _StmtIndex(ast.NodeVisitor):
    """Map line numbers to their innermost enclosing SIMPLE statement,
    and record lines that are compound headers / defs / lambdas —
    the not-safe territory."""

    def __init__(self) -> None:
        self.simple: Dict[int, ast.stmt] = {}  # line -> simple stmt covering it
        self.unsafe_lines: set = set()

    def visit(self, node: ast.AST) -> None:
        if isinstance(node, _SIMPLE_STMTS):
            end = getattr(node, "end_lineno", node.lineno)
            contains_lambda = any(
                isinstance(n, (ast.Lambda, ast.ListComp, ast.SetComp,
                               ast.DictComp, ast.GeneratorExp))
                for n in ast.walk(node)
            )
            for line in range(node.lineno, end + 1):
                if contains_lambda:
                    self.unsafe_lines.add(line)
                else:
                    self.simple.setdefault(line, node)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            self.unsafe_lines.add(node.lineno)
        elif isinstance(node, ast.stmt):
            # Compound statement: its HEADER lines are unsafe (wrapping
            # an `if` test or a loop iter under a lock would not guard
            # the body it controls); body lines index via children.
            first_body = min(
                (b.lineno for attr in ("body", "orelse", "finalbody")
                 for b in getattr(node, attr, []) or []),
                default=getattr(node, "end_lineno", node.lineno) + 1,
            )
            for line in range(node.lineno, first_body):
                self.unsafe_lines.add(line)
        self.generic_visit(node)


def _wrap(
    lines: List[str], stmt: ast.stmt, lock_ref: str
) -> List[str]:
    """The replacement block: ``with <lock_ref>:`` + the statement
    re-indented one level (list of lines, no trailing newlines)."""
    start, end = stmt.lineno - 1, getattr(stmt, "end_lineno", stmt.lineno) - 1
    body = lines[start:end + 1]
    indent = body[0][: len(body[0]) - len(body[0].lstrip())]
    out = [f"{indent}with {lock_ref}:"]
    out.extend("    " + ln if ln.strip() else ln for ln in body)
    return out


def _diff(path: str, old: List[str], new: List[str], note: str) -> str:
    import difflib

    body = "".join(
        difflib.unified_diff(
            [ln + "\n" for ln in old],
            [ln + "\n" for ln in new],
            fromfile=f"a/{path}",
            tofile=f"b/{path}",
        )
    )
    return f"# lockcheck --fix: {note}\n{body}"


def fix(
    root: Path, scan_dirs: Optional[Tuple[str, ...]] = None,
    write: bool = True,
) -> Tuple[int, List[str]]:
    """Run the lock pass, apply every safe fix in place, and return
    ``(fixed_count, review_diffs)`` — the diffs are the annotated
    not-safe findings a human must place by hand."""
    findings = lockcheck.run(root, scan_dirs) + loopcheck.run(root, scan_dirs)
    by_file: Dict[str, List[Finding]] = {}
    for f in findings:
        if f.rule in (
            "field-off-lock", "helper-off-lock", "local-off-lock",
            "loop-off-thread-write",
        ):
            by_file.setdefault(f.path, []).append(f)
    fixed = 0
    reviews: List[str] = []
    paths = {rel(p, root): p for p in iter_py_files(root, scan_dirs)}
    for rpath, flist in sorted(by_file.items()):
        path = paths.get(rpath)
        if path is None:
            continue
        source = path.read_text()
        lines = source.splitlines()
        try:
            tree = ast.parse(source)
        except SyntaxError:
            continue  # the lock pass already reported it
        index = _StmtIndex()
        index.visit(tree)
        # Plan every edit as (start0, end0, replacement lines) and apply
        # the whole batch bottom-up at the end, so the hop rewrites and
        # the lock wraps cannot shift each other's line numbers.
        edits: List[Tuple[int, int, List[str]]] = []
        flist = _plan_hops(flist, index, lines, reviews, edits)
        # Group findings by their enclosing simple statement; a finding
        # with no simple statement (or on an unsafe line) needs review.
        per_stmt: Dict[int, Tuple[ast.stmt, str]] = {}
        for f in flist:
            lock = _finding_lock(f)
            stmt = index.simple.get(f.line)
            if (
                lock is None
                or stmt is None
                or f.line in index.unsafe_lines
                or _has_lock_machinery(stmt)
            ):
                reviews.append(_review_entry(f, lines, lock))
                continue
            key = stmt.lineno
            prev = per_stmt.get(key)
            ref = _lock_spelling(f.symbol, lock)
            if prev is not None and prev[1] != ref:
                # Two different locks wanted on one statement: no single
                # mechanical wrap is correct.
                reviews.append(_review_entry(f, lines, lock))
                per_stmt.pop(key, None)
                continue
            per_stmt[key] = (stmt, ref)
        for _, (stmt, ref) in per_stmt.items():
            start = stmt.lineno - 1
            end = getattr(stmt, "end_lineno", stmt.lineno) - 1
            edits.append((start, end, _wrap(lines, stmt, ref)))
        if not edits:
            continue
        # Apply bottom-up so earlier line numbers stay valid.
        new_lines = list(lines)
        for start, end, repl in sorted(edits, reverse=True):
            new_lines[start:end + 1] = repl
            fixed += 1
        if write:
            path.write_text(
                "\n".join(new_lines) + ("\n" if source.endswith("\n") else "")
            )
        else:
            reviews.append(
                _diff(rpath, lines, new_lines, "proposed (dry run)")
            )
    return fixed, reviews


def _plan_hops(
    flist: List[Finding],
    index: "_StmtIndex",
    lines: List[str],
    reviews: List[str],
    edits: List[Tuple[int, int, List[str]]],
) -> List[Finding]:
    """Plan the ``loop-off-thread-write`` rewrites; returns the findings
    the lock rules should still consider (everything else)."""
    rest: List[Finding] = []
    for f in flist:
        if f.rule != "loop-off-thread-write":
            rest.append(f)
            continue
        m = _HOP_RE.search(f.message)
        stmt = index.simple.get(f.line)
        if (
            m is None
            or stmt is None
            or not isinstance(stmt, ast.Expr)
            or f.line in index.unsafe_lines
        ):
            reviews.append(_hop_review_entry(f, lines))
            continue
        loopattr, field, meth = m.groups()
        call = stmt.value
        if not (
            isinstance(call, ast.Call)
            and isinstance(call.func, ast.Attribute)
            and call.func.attr == meth
            and isinstance(call.func.value, ast.Attribute)
            and call.func.value.attr == field
            and isinstance(call.func.value.value, ast.Name)
            and call.func.value.value.id == "self"
            and not call.keywords
            and all(isinstance(a, _SIMPLE_ARGS) for a in call.args)
        ):
            reviews.append(_hop_review_entry(f, lines))
            continue
        start = stmt.lineno - 1
        end = getattr(stmt, "end_lineno", stmt.lineno) - 1
        head = lines[start]
        indent = head[: len(head) - len(head.lstrip())]
        args = "".join(f", {ast.unparse(a)}" for a in call.args)
        edits.append((start, end, [
            f"{indent}self.{loopattr}.call_soon_threadsafe("
            f"self.{field}.{meth}{args})"
        ]))
    return rest


def _hop_review_entry(f: Finding, lines: List[str]) -> str:
    """An annotated context block for an off-loop write the fixer
    refuses to hop mechanically."""
    at = f.line - 1
    lo, hi = max(0, at - 2), min(len(lines), at + 3)
    ctx = "\n".join(
        f"{'>' if i == at else ' '} {i + 1:4d} {lines[i]}"
        for i in range(lo, hi)
    )
    return (
        f"# lockcheck --fix: NOT auto-hoppable — {f.path}:{f.line} "
        f"{f.symbol} writes a loop-owned field but needs its return "
        f"value, rich argument expressions, or sits in a compound "
        f"header/closure; a fire-and-forget call_soon_threadsafe hop "
        f"would change semantics.  Hop it by hand:\n{ctx}\n"
    )


def _has_lock_machinery(stmt: ast.stmt) -> bool:
    """A statement already juggling locks (acquire/release calls or a
    nested ``with``) is never auto-wrapped: the author is mid-discipline
    and a second layer could deadlock or mask the real fix."""
    for node in ast.walk(stmt):
        if isinstance(node, ast.With):
            return True
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr in ("acquire", "release"):
                return True
    return False


def _review_entry(f: Finding, lines: List[str], lock: Optional[str]) -> str:
    """An annotated context block for a finding the fixer refuses."""
    at = f.line - 1
    lo, hi = max(0, at - 2), min(len(lines), at + 3)
    ctx = "\n".join(
        f"{'>' if i == at else ' '} {i + 1:4d} {lines[i]}"
        for i in range(lo, hi)
    )
    want = lock or "?"
    return (
        f"# lockcheck --fix: NOT auto-fixable — {f.path}:{f.line} "
        f"{f.symbol} needs `{want}` but sits in a compound header, "
        f"closure, or multi-lock statement; guard it by hand:\n{ctx}\n"
    )
