"""Pass 4: the JAX trace-safety lint over ``ops/`` and ``parallel/``.

The recompile/tracer-leak bug class: code that runs fine the first time a
kernel traces, then either crashes on the second shape ("concretization
of a traced value") or silently retraces every call (a 9-20 s stall per
chunk on the tunnelled TPU).  The lint finds its textual signatures
inside **kernel bodies** — functions it identifies as jit-traced:

- decorated with ``@jax.jit`` / ``@partial(jax.jit, ...)``,
- passed by name to ``jax.jit(...)`` in the same module,
- defined (at any nesting depth) inside a kernel factory — a function
  whose name matches ``(make|build).*(kernel|minhash|sieve|factored|
  hot|call)``, the repo's factory convention (``make_kernel_body``,
  ``_build_call``, ``_make_sharded_kernel``, the ISSUE 13 sieve
  factories — both of the two-stage sieve's passes live inside these
  bodies on both backends, so the race/contract checks gate them like
  the old code — the ISSUE 14 factored factories, and the ISSUE 16 hot
  plane's ``make_hot_step``, whose donated ring-loop step bodies trace
  like any kernel body),
- or explicitly marked with ``# jit-kernel`` on its def line.

Rules (suppress a deliberate line with ``# trace-ok: <reason>``):

- ``trace-concretize``: ``int()``/``float()``/``bool()`` over a tainted
  (tracer-reaching) expression, or any ``.item()``/``.tolist()`` call —
  host concretization inside the traced body.
- ``trace-branch``: Python ``if``/``while`` whose test is tainted —
  data-dependent control flow must go through ``jnp.where``/``lax.cond``.
- ``trace-wallclock``: ``time.*()`` / ``datetime.now`` inside a traced
  body — traces once, freezes forever (and breaks retrace caching).
- ``trace-rng``: stateful host RNG (``random.*``, ``np.random.*``)
  inside a traced body — not reproducible, not shardable; thread
  ``jax.random`` keys instead.
- ``trace-unhashable-static``: an ``lru_cache``/``cache``-decorated
  function (the kernel-factory memo idiom) or a jit with
  ``static_argnums``/``static_argnames`` whose parameters carry
  list/dict/set defaults — unhashable statics are a fresh compile per
  call at best, a TypeError at worst.

Taint is a per-function over-approximation: parameters and results of
``jnp.``/``jax.``/``lax.`` calls are tracers; names assigned from tainted
expressions are tainted; ``.shape``/``.dtype``/``.ndim``, ``len()`` and
``range()`` launder (static under trace).  Heuristic by design — the
suppression comment is the escape hatch, and the frozen fixtures in
tests/fixtures_analyze pin what must keep firing.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

from .common import (
    JIT_KERNEL_RE,
    TRACE_OK_RE,
    Finding,
    comment_in_span,
    file_comments,
    iter_py_files,
    rel,
)

PASS = "trace"

#: Kernel-factory naming convention the lint keys on; ``hot`` (ISSUE 16)
#: admits the always-hot plane's donated-step factories (make_hot_step),
#: whose ring-loop step bodies trace like any kernel body; ``blake2b``
#: (ISSUE 20) admits the second kernel family's factories
#: (``make_blake2b_kernel_body`` / ``_make_blake2b_kernel`` /
#: ``build_kernel_for`` in ops/blake2b.py and the sharded wrapper in
#: parallel/sweep.py) so the u32-pair compression bodies are gated like
#: the sha256 plane's — its module-level device primitives (``_G``,
#: ``_compress_pairs``, ...) carry explicit ``# jit-kernel`` marks since
#: they sit outside any factory.
FACTORY_RE = re.compile(
    r"(make|build).*(kernel|minhash|sieve|factored|hot|call|blake2b)"
)

#: Default scan scope in repo mode: the accelerator layers.
TRACE_SCAN_DIRS = (
    "bitcoin_miner_tpu/ops",
    "bitcoin_miner_tpu/parallel",
    # Workload kernel factories (ISSUE 9): any jit/factory-pattern kernel
    # body a registered workload ships is linted like ops/ and parallel/.
    "bitcoin_miner_tpu/workloads",
)

_TRACED_MODULES = ("jnp", "lax")
_LAUNDER_ATTRS = {"shape", "dtype", "ndim", "size"}
_LAUNDER_CALLS = {"len", "range", "isinstance", "getattr", "type"}
_CONCRETIZERS = {"int", "float", "bool"}
_WALLCLOCK = {
    ("time", "time"),
    ("time", "monotonic"),
    ("time", "perf_counter"),
    ("time", "process_time"),
    ("datetime", "now"),
    ("datetime", "utcnow"),
}


def _dotted(node: ast.AST) -> Optional[Tuple[str, ...]]:
    """('jax', 'jit') for jax.jit, ('jit',) for bare jit."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


def _is_jit_expr(node: ast.AST) -> bool:
    """jax.jit / jit / partial(jax.jit, ...) as a decorator or callee."""
    if isinstance(node, ast.Call):
        d = _dotted(node.func)
        if d and d[-1] == "partial" and node.args:
            return _is_jit_expr(node.args[0])
        return _is_jit_expr(node.func)
    d = _dotted(node)
    return d is not None and d[-1] == "jit"


class _Taint:
    """Per-function tracer-taint over-approximation."""

    def __init__(self, params: Set[str]) -> None:
        self.names: Set[str] = set(params)

    def tainted(self, node: ast.AST) -> bool:
        for n in ast.walk(node):
            if isinstance(n, ast.Name) and n.id in self.names:
                if not self._laundered(node, n):
                    return True
            if isinstance(n, ast.Call):
                d = _dotted(n.func)
                if d and d[0] in _TRACED_MODULES:
                    return True
        return False

    def _laundered(self, root: ast.AST, name: ast.Name) -> bool:
        """True if every path from ``root`` to ``name`` passes through a
        shape/dtype/len() laundering node.  Approximated: check the
        direct parent chain via a containment scan (cheap, good enough
        for lint granularity)."""
        for n in ast.walk(root):
            if isinstance(n, ast.Attribute) and n.attr in _LAUNDER_ATTRS:
                if name in ast.walk(n):
                    return True
            if isinstance(n, ast.Call):
                d = _dotted(n.func)
                if d and d[-1] in _LAUNDER_CALLS and name in list(ast.walk(n)):
                    return True
        return False


class _KernelBodyChecker:
    def __init__(
        self, path: str, comments: Dict[int, str], findings: List[Finding]
    ) -> None:
        self.path = path
        self.comments = comments
        self.findings = findings

    def _ok(self, stmt: ast.stmt) -> bool:
        return (
            comment_in_span(
                self.comments,
                stmt.lineno,
                getattr(stmt, "end_lineno", None),
                TRACE_OK_RE,
            )
            is not None
        )

    def check(self, fn: ast.FunctionDef) -> None:
        taint = _Taint({a.arg for a in fn.args.args if a.arg != "self"})
        self._walk(fn.body, fn.name, taint)

    def _emit(self, rule: str, node: ast.AST, symbol: str, msg: str) -> None:
        self.findings.append(
            Finding(PASS, rule, self.path, node.lineno, symbol, msg)
        )

    def _walk(self, body: List[ast.stmt], fname: str, taint: _Taint) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # Nested defs inside a kernel body are kernel code too
                # (pl.when closures); they share the enclosing taint.
                inner = _Taint(taint.names | {a.arg for a in stmt.args.args})
                self._walk(stmt.body, f"{fname}.{stmt.name}", inner)
                continue
            if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                value = stmt.value
                if value is not None and taint.tainted(value):
                    targets = (
                        stmt.targets
                        if isinstance(stmt, ast.Assign)
                        else [stmt.target]
                    )
                    for t in targets:
                        # Whole-name (and tuple-unpack) bindings only: a
                        # subscript store (`d[k] = tracer`) must not taint
                        # the container name — `if k in d:` over static
                        # keys is legal and common in kernel factories.
                        elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
                        for n in elts:
                            if isinstance(n, ast.Name):
                                taint.names.add(n.id)
            if isinstance(stmt, (ast.If, ast.While)) and not self._ok(stmt):
                if taint.tainted(stmt.test):
                    self._emit(
                        "trace-branch",
                        stmt,
                        fname,
                        "Python branch on a traced value inside a kernel "
                        "body — use jnp.where / lax.cond / lax.while_loop",
                    )
            if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_exprs(stmt, fname, taint)
            for field_name in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field_name, None)
                if sub:
                    self._walk(sub, fname, taint)
            for handler in getattr(stmt, "handlers", ()) or ():
                self._walk(handler.body, fname, taint)

    def _scan_exprs(self, stmt: ast.stmt, fname: str, taint: _Taint) -> None:
        if self._ok(stmt):
            return
        # Only the statement's own expressions; nested suites re-enter via
        # _walk so their statements get their own suppression checks.
        roots: List[ast.AST] = []
        if isinstance(stmt, (ast.If, ast.While)):
            roots = [stmt.test]
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            roots = [stmt.iter]
        elif isinstance(stmt, ast.With):
            roots = [i.context_expr for i in stmt.items]
        elif isinstance(stmt, ast.Try):
            roots = []
        else:
            roots = [stmt]
        for root in roots:
            for node in ast.walk(root):
                if not isinstance(node, ast.Call):
                    continue
                d = _dotted(node.func)
                if (
                    isinstance(node.func, ast.Name)
                    and node.func.id in _CONCRETIZERS
                    and node.args
                    and taint.tainted(node.args[0])
                ):
                    self._emit(
                        "trace-concretize",
                        node,
                        fname,
                        f"{node.func.id}() over a traced value inside a "
                        "kernel body — concretization error / silent "
                        "retrace",
                    )
                if isinstance(node.func, ast.Attribute) and node.func.attr in (
                    "item",
                    "tolist",
                ):
                    self._emit(
                        "trace-concretize",
                        node,
                        fname,
                        f".{node.func.attr}() inside a kernel body fetches "
                        "to host mid-trace",
                    )
                if d is not None and len(d) >= 2 and (d[-2], d[-1]) in _WALLCLOCK:
                    self._emit(
                        "trace-wallclock",
                        node,
                        fname,
                        f"{'.'.join(d)}() inside a traced body freezes one "
                        "timestamp into the compiled kernel",
                    )
                if d is not None and (
                    d[0] == "random"
                    or (len(d) >= 2 and d[0] in ("np", "numpy") and d[1] == "random")
                ):
                    self._emit(
                        "trace-rng",
                        node,
                        fname,
                        f"stateful host RNG {'.'.join(d)}() inside a traced "
                        "body — thread jax.random keys instead",
                    )


def _mutable_default_params(fn: ast.FunctionDef) -> List[str]:
    out = []
    args = fn.args
    defaults = list(args.defaults)
    params = args.args[len(args.args) - len(defaults):] if defaults else []
    for p, d in zip(params, defaults):
        if isinstance(d, (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp)):
            out.append(p.arg)
    for p, d in zip(args.kwonlyargs, args.kw_defaults):
        if isinstance(d, (ast.List, ast.Dict, ast.Set)):
            out.append(p.arg)
    return out


def _check_static_hashability(
    path: str, tree: ast.Module, findings: List[Finding]
) -> None:
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        memoized = any(
            (d_ := _dotted(dec.func if isinstance(dec, ast.Call) else dec))
            and d_[-1] in ("lru_cache", "cache")
            for dec in node.decorator_list
        )
        jit_static = any(
            isinstance(dec, ast.Call)
            and _is_jit_expr(dec)
            and any(
                kw.arg in ("static_argnums", "static_argnames")
                for kw in dec.keywords
            )
            for dec in node.decorator_list
        )
        if not (memoized or jit_static):
            continue
        bad = _mutable_default_params(node)
        if bad:
            findings.append(
                Finding(
                    PASS,
                    "trace-unhashable-static",
                    path,
                    node.lineno,
                    node.name,
                    f"memoized/static-arg function has unhashable default "
                    f"for {', '.join(bad)} — every call is a cache miss "
                    f"(or a TypeError); use tuples",
                )
            )


def _collect_kernel_bodies(
    tree: ast.Module, comments: Dict[int, str]
) -> List[ast.FunctionDef]:
    """See module docstring for the four identification routes."""
    kernels: List[ast.FunctionDef] = []
    jitted_names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_jit_expr(node.func):
            for a in node.args:
                if isinstance(a, ast.Name):
                    jitted_names.add(a.id)

    def visit(node: ast.AST, in_factory: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                marked = (
                    comments.get(child.lineno) is not None
                    and JIT_KERNEL_RE.search(comments[child.lineno]) is not None
                )
                decorated = any(
                    _is_jit_expr(d) for d in child.decorator_list
                )
                is_kernel = (
                    in_factory
                    or marked
                    or decorated
                    or child.name in jitted_names
                )
                if is_kernel and isinstance(child, ast.FunctionDef):
                    kernels.append(child)
                    continue  # its nested defs are checked by the body walk
                visit(child, in_factory or FACTORY_RE.search(child.name) is not None)
            else:
                visit(child, in_factory)

    visit(tree, False)
    return kernels


def run(root: Path, scan_dirs: Optional[Tuple[str, ...]] = None) -> List[Finding]:
    findings: List[Finding] = []
    for path in iter_py_files(root, scan_dirs):
        try:
            source = path.read_text()
            tree = ast.parse(source)
        except (SyntaxError, UnicodeDecodeError):
            continue
        comments = file_comments(source)
        rpath = rel(path, root)
        checker = _KernelBodyChecker(rpath, comments, findings)
        for fn in _collect_kernel_bodies(tree, comments):
            checker.check(fn)
        _check_static_hashability(rpath, tree, findings)
    return findings
