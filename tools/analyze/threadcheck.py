"""Pass 9: the thread-lifecycle sanitizer's static half (ISSUE 19).

Every ``threading.Thread(...)`` construction must have an owner that
reaps it, or say who does:

- ``thread-unjoined`` (class-owned) — a thread stored on the instance
  (``self._t = Thread(...)`` or appended to a ``self.<list>``) must be
  joined on the class's reaper path: a method named (or prefixed)
  ``close`` / ``stop`` / ``shutdown`` that calls ``self.<attr>.join()``
  or for-loops over ``self.<list>`` joining each element.  Daemon
  status does NOT exempt a class-owned thread: a daemon worker left
  running after close() still holds the object alive and still shows
  up in the leak census — the process exiting is not a lifecycle.
- ``thread-unjoined`` (function-local) — a fire-and-forget thread built
  in a function body is clean when it is a daemon or when the enclosing
  function joins (any ``.join()`` call in the function counts — the
  wait-for-workers idiom).  A non-daemon local thread nobody joins
  leaks a shutdown hang.

Both spellings accept ``# thread-owner: <owner.close>`` on the
construction statement, naming the out-of-band reaper (the tiered
watchdog deliberately abandons a wedged tier's thread, for example).
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import List, Optional, Set, Tuple

from .common import (
    THREAD_OWNER_RE,
    Finding,
    comment_in_span,
    file_comments,
    iter_py_files,
    rel,
    walk_shallow,
)

PASS = "thread"

_REAPER_PREFIXES = ("close", "stop", "shutdown")


def _dotted(node: ast.AST) -> Optional[Tuple[str, ...]]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


def _is_thread_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    d = _dotted(node.func)
    return d is not None and d[-1] == "Thread"


def _is_daemon(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "daemon":
            return isinstance(kw.value, ast.Constant) and kw.value.value is True
    return False


def _self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


class _FileChecker:
    def __init__(self, path: str, source: str, findings: List[Finding]) -> None:
        self.path = path
        self.comments = file_comments(source)
        self.findings = findings
        self.tree = ast.parse(source)

    def _emit(self, node: ast.AST, symbol: str, msg: str) -> None:
        self.findings.append(
            Finding(PASS, "thread-unjoined", self.path, node.lineno, symbol, msg)
        )

    def _owned(self, stmt: ast.stmt) -> bool:
        return (
            comment_in_span(
                self.comments, stmt.lineno,
                getattr(stmt, "end_lineno", None), THREAD_OWNER_RE,
            )
            is not None
        )

    # ------------------------------------------------------------- classes

    def _check_class(self, cls: ast.ClassDef) -> None:
        # attr -> (construction stmt, method name) for class-owned threads
        owned: List[Tuple[str, ast.stmt, str]] = []
        handled_ctors: Set[ast.Call] = set()
        for method in cls.body:
            if not isinstance(method, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            # local name -> ctor call, for the append-to-self-list idiom
            locals_: dict = {}
            for stmt in ast.walk(method):
                if isinstance(stmt, ast.Assign) and _is_thread_ctor(stmt.value):
                    handled = False
                    for t in stmt.targets:
                        attr = _self_attr(t)
                        if attr is not None:
                            owned.append((attr, stmt, method.name))
                            handled = True
                        elif isinstance(t, ast.Name):
                            locals_[t.id] = (stmt, stmt.value)
                    if handled:
                        handled_ctors.add(stmt.value)
                elif isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
                    # self.<list>.append(t) promotes local t to class-owned
                    call = stmt.value
                    f = call.func
                    if (
                        isinstance(f, ast.Attribute)
                        and f.attr == "append"
                        and (attr := _self_attr(f.value)) is not None
                        and len(call.args) == 1
                        and isinstance(call.args[0], ast.Name)
                        and call.args[0].id in locals_
                    ):
                        ctor_stmt, ctor = locals_.pop(call.args[0].id)
                        owned.append((attr, ctor_stmt, method.name))
                        handled_ctors.add(ctor)
        joined = self._reaper_joined_attrs(cls)
        for attr, stmt, method_name in owned:
            if attr in joined or self._owned(stmt):
                continue
            self._emit(
                stmt, f"{cls.name}.{method_name}",
                f"class-owned thread self.{attr} is never joined on a "
                f"close()/stop()/shutdown() path — join it in the reaper "
                f"or name its owner with # thread-owner:",
            )
        self._handled.update(handled_ctors)

    def _reaper_joined_attrs(self, cls: ast.ClassDef) -> Set[str]:
        """self-attrs joined on some reaper method of ``cls``."""
        out: Set[str] = set()
        for method in cls.body:
            if not isinstance(method, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not method.name.lstrip("_").startswith(_REAPER_PREFIXES):
                continue
            # for t in self.<list>: ... t.join() — map loop vars back
            loop_vars: dict = {}
            for node in ast.walk(method):
                if isinstance(node, ast.For) and isinstance(node.target, ast.Name):
                    attr = _self_attr(node.iter)
                    if attr is not None:
                        loop_vars[node.target.id] = attr
            for node in ast.walk(method):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "join"
                ):
                    continue
                recv = node.func.value
                attr = _self_attr(recv)
                if attr is not None:
                    out.add(attr)
                elif isinstance(recv, ast.Name) and recv.id in loop_vars:
                    out.add(loop_vars[recv.id])
        return out

    # ----------------------------------------------------- function-local

    def _check_function_local(self) -> None:
        def visit(node: ast.AST, prefix: str) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    name = f"{prefix}.{child.name}" if prefix else child.name
                    self._check_one_function(name, child)
                    visit(child, name)
                elif isinstance(child, ast.ClassDef):
                    visit(child, child.name)
                else:
                    visit(child, prefix)

        visit(self.tree, "")

    def _check_one_function(self, symbol: str, fn: ast.AST) -> None:
        # walk_shallow keeps nested defs' threads attributed to the
        # nested symbol, never double-reported under the outer one
        ctors: List[ast.Call] = []
        has_join = False
        for node in walk_shallow(fn):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"
            ):
                has_join = True
            if (
                _is_thread_ctor(node)
                and node not in self._handled
                and not _is_daemon(node)
            ):
                ctors.append(node)
        if has_join:
            return
        for call in ctors:
            stmt = self._stmt_of(fn, call)
            if stmt is not None and self._owned(stmt):
                continue
            self._emit(
                stmt if stmt is not None else call, symbol,
                "non-daemon thread is never joined — the process cannot "
                "exit while it runs; join it, make it a daemon with an "
                "owner, or name its reaper with # thread-owner:",
            )

    @staticmethod
    def _stmt_of(fn: ast.AST, target: ast.AST) -> Optional[ast.stmt]:
        best: Optional[ast.stmt] = None
        for node in ast.walk(fn):
            if isinstance(node, ast.stmt) and target in ast.walk(node):
                if best is None or node.lineno >= best.lineno:
                    best = node
        return best

    def check(self) -> None:
        self._handled: Set[ast.Call] = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ClassDef):
                self._check_class(node)
        self._check_function_local()


def run(root: Path, scan_dirs: Optional[Tuple[str, ...]] = None) -> List[Finding]:
    findings: List[Finding] = []
    for path in iter_py_files(root, scan_dirs):
        try:
            source = path.read_text()
            checker = _FileChecker(rel(path, root), source, findings)
        except (SyntaxError, UnicodeDecodeError):
            continue
        checker.check()
    return findings
