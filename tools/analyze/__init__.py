"""Repo-native static-analysis & sanitizer suite (``python -m tools.analyze``).

Five passes, one exit code:

- ``lock`` — AST lock-discipline checker (``# guarded-by:`` annotations,
  the ``with``-block rule, the ``_locked``/def-line helper conventions,
  the externally-serialized-class registry).  tools/analyze/lockcheck.py
- ``wfq`` — exactly one virtual-clock WFQ implementation
  (utils/wfq.py); floor-init / tie-break reimplementations anywhere else
  fail the build.  tools/analyze/wfqcheck.py
- ``contracts`` — frozen-reference golden vectors: wire codecs, hash
  values, CLI stdout.  tools/analyze/contracts.py
- ``trace`` — JAX trace-safety lint over ops/ and parallel/ (concretize /
  branch-on-tracer / wall-clock / RNG / unhashable-static bug class).
  tools/analyze/tracecheck.py
- ``sanitize`` — the runtime race sanitizer's machinery self-test (the
  BMT_SANITIZE=1 leg lives in the test suites).  tools/analyze/sanitcheck.py
- ``metrics`` — every counter/histogram/gauge name emitted anywhere must
  appear in the documented registry block in utils/metrics.py, and vice
  versa (documented-but-never-emitted fails).  tools/analyze/metriccheck.py

Grandfathered findings live in tools/analyze/ratchet.json and may only
shrink.  See README "Static analysis & sanitizers".
"""

from __future__ import annotations

from .common import Finding, apply_ratchet, load_ratchet, save_ratchet  # noqa: F401
from . import contracts, lockcheck, metriccheck, sanitcheck, tracecheck, wfqcheck  # noqa: F401

PASSES = {
    "lock": lockcheck.run,
    "wfq": wfqcheck.run,
    "contracts": contracts.run,
    "trace": tracecheck.run,
    "sanitize": sanitcheck.run,
    "metrics": metriccheck.run,
}
