"""Repo-native static-analysis & sanitizer suite (``python -m tools.analyze``).

Nine passes, one exit code:

- ``lock`` — AST lock-discipline checker (``# guarded-by:`` annotations,
  the ``with``-block rule, the ``_locked``/def-line helper conventions,
  the externally-serialized-class registry).  tools/analyze/lockcheck.py
- ``wfq`` — exactly one virtual-clock WFQ implementation
  (utils/wfq.py); floor-init / tie-break reimplementations anywhere else
  fail the build.  tools/analyze/wfqcheck.py
- ``contracts`` — frozen-reference golden vectors: wire codecs, hash
  values, CLI stdout.  tools/analyze/contracts.py
- ``trace`` — JAX trace-safety lint over ops/ and parallel/ (concretize /
  branch-on-tracer / wall-clock / RNG / unhashable-static bug class).
  tools/analyze/tracecheck.py
- ``sanitize`` — the runtime race sanitizer's machinery self-test (the
  BMT_SANITIZE=1 leg lives in the test suites).  tools/analyze/sanitcheck.py
- ``metrics`` — every counter/histogram/gauge name emitted anywhere must
  appear in the documented registry block in utils/metrics.py, and vice
  versa (documented-but-never-emitted fails).  tools/analyze/metriccheck.py
- ``loop`` — asyncio loop-discipline lint: blocking primitives in
  coroutines / ``# on-loop:`` code, sync locks on the loop, off-thread
  writes bypassing the ``call_soon_threadsafe`` hop.
  tools/analyze/loopcheck.py
- ``donate`` — JAX donation-safety pass over ops/ + parallel/:
  use-after-donate, donated calls that don't rebind the carry, mid-job
  carry materialisation.  tools/analyze/donatecheck.py
- ``thread`` — thread-lifecycle sanitizer: every ``threading.Thread``
  construction joined on its class's close()/stop()/shutdown() path or
  annotated ``# thread-owner:``.  tools/analyze/threadcheck.py

Grandfathered findings live in tools/analyze/ratchet.json and may only
shrink.  See README "Static analysis & sanitizers".
"""

from __future__ import annotations

from .common import Finding, apply_ratchet, load_ratchet, save_ratchet  # noqa: F401
from . import (  # noqa: F401
    contracts,
    donatecheck,
    lockcheck,
    loopcheck,
    metriccheck,
    sanitcheck,
    threadcheck,
    tracecheck,
    wfqcheck,
)

PASSES = {
    "lock": lockcheck.run,
    "wfq": wfqcheck.run,
    "contracts": contracts.run,
    "trace": tracecheck.run,
    "sanitize": sanitcheck.run,
    "metrics": metriccheck.run,
    "loop": loopcheck.run,
    "donate": donatecheck.run,
    "thread": threadcheck.run,
}
