"""Shared plumbing for the repo-native analysis suite.

Findings, source-file iteration, comment-annotation parsing (the
``# guarded-by:`` / ``# unguarded:`` / ``# trace-ok:`` vocabulary — see
README "Static analysis & sanitizers"), and the only-shrink ratchet.
"""

from __future__ import annotations

import ast
import json
import re
import tokenize
from collections import Counter
from dataclasses import dataclass
from io import StringIO
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Tuple

REPO_ROOT = Path(__file__).resolve().parents[2]

#: Directories the repo-mode passes scan (relative to the repo root).
DEFAULT_SCAN_DIRS = ("bitcoin_miner_tpu", "tools")


@dataclass(frozen=True)
class Finding:
    pass_name: str  # lock | wfq | contracts | trace | sanitize
    rule: str
    path: str  # repo-relative (or fixture-relative) posix path
    line: int
    symbol: str
    message: str

    @property
    def key(self) -> str:
        """Ratchet identity: line numbers excluded so unrelated edits to a
        file do not churn the grandfather list."""
        return f"{self.pass_name}:{self.path}:{self.rule}:{self.symbol}"

    def render(self) -> str:
        return (
            f"{self.path}:{self.line}: [{self.pass_name}/{self.rule}] "
            f"{self.symbol}: {self.message}"
        )


def iter_py_files(root: Path, scan_dirs: Optional[Tuple[str, ...]] = None) -> Iterator[Path]:
    """Every .py file under ``root`` (restricted to ``scan_dirs`` when
    given), skipping caches and the analyzer's own fixture trees unless
    they are the scan root itself."""
    roots = (
        [root]
        if scan_dirs is None
        else [root / d for d in scan_dirs if (root / d).exists()]
    )
    for r in roots:
        if r.is_file():
            yield r
            continue
        for p in sorted(r.rglob("*.py")):
            if "__pycache__" in p.parts:
                continue
            yield p


def walk_shallow(fn: ast.AST) -> Iterator[ast.AST]:
    """``ast.walk`` over one function's OWN body: nested function/class
    defs (and lambdas) are yielded but not descended into, so a nested
    def's statements are attributed to the nested symbol, never double-
    reported under the enclosing one."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)
        ):
            stack.extend(ast.iter_child_nodes(node))


def rel(path: Path, root: Path) -> str:
    try:
        return path.resolve().relative_to(root.resolve()).as_posix()
    except ValueError:
        return path.as_posix()


# --------------------------------------------------------------------------
# Comment annotations
# --------------------------------------------------------------------------

GUARDED_BY_RE = re.compile(r"guarded-by:\s*([A-Za-z_][A-Za-z0-9_.]*)")
UNGUARDED_RE = re.compile(r"unguarded:")
TRACE_OK_RE = re.compile(r"trace-ok:")
JIT_KERNEL_RE = re.compile(r"jit-kernel\b")
#: Concurrency-plane vocabulary (ISSUE 19).  ``# on-loop:`` on a ``def``
#: declares the body runs on an event-loop thread (loopcheck lints it
#: like a coroutine); on a ``self.<field> = ...`` assignment it declares
#: the field loop-owned, with the optional argument naming the loop
#: attribute off-thread writers must hop through
#: (``# on-loop: _loop`` -> ``self._loop.call_soon_threadsafe``).
ON_LOOP_RE = re.compile(r"(?<![\w-])on-loop:?\s*([A-Za-z_][A-Za-z0-9_]*)?")
LOOP_OK_RE = re.compile(r"loop-ok:")
DONATE_OK_RE = re.compile(r"donate-ok:")
THREAD_OWNER_RE = re.compile(r"thread-owner:\s*(\S+)")


def file_comments(source: str) -> Dict[int, str]:
    """line number -> comment text (without the #) for one source file."""
    out: Dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string.lstrip("#").strip()
    except tokenize.TokenError:
        pass  # a truncated file still analyzes as far as it parses
    return out


def comment_in_span(
    comments: Dict[int, str], lineno: int, end_lineno: Optional[int], pattern: re.Pattern
) -> Optional[re.Match]:
    """First match of ``pattern`` in any comment on the statement's
    physical lines (trailing comments land on the last line of a
    multi-line statement)."""
    for ln in range(lineno, (end_lineno or lineno) + 1):
        text = comments.get(ln)
        if text:
            m = pattern.search(text)
            if m:
                return m
    return None


# --------------------------------------------------------------------------
# Ratchet: grandfathered findings, allowed only to shrink
# --------------------------------------------------------------------------


def load_ratchet(path: Path) -> Dict[str, int]:
    if not path.exists():
        return {}
    data = json.loads(path.read_text())
    return {str(k): int(v) for k, v in data.get("grandfathered", {}).items()}


def save_ratchet(path: Path, findings: List[Finding]) -> None:
    counts = Counter(f.key for f in findings)
    path.write_text(
        json.dumps(
            {
                "comment": (
                    "Grandfathered analysis findings — this file may only "
                    "shrink.  Regenerate with python -m tools.analyze "
                    "--update-ratchet after FIXING findings, never to admit "
                    "new ones."
                ),
                "grandfathered": dict(sorted(counts.items())),
            },
            indent=2,
        )
        + "\n"
    )


def apply_ratchet(
    findings: List[Finding], ratchet: Dict[str, int]
) -> Tuple[List[Finding], List[str]]:
    """Split findings into (new, stale-ratchet-keys).

    A finding key is grandfathered up to its ratchet count; any excess is
    new.  A ratchet entry whose key now fires FEWER times than recorded is
    stale — the ratchet must be shrunk to match (that is the only-shrink
    contract: progress is locked in the moment it happens).
    """
    counts = Counter(f.key for f in findings)
    budget = dict(ratchet)
    new: List[Finding] = []
    for f in findings:
        if budget.get(f.key, 0) > 0:
            budget[f.key] -= 1
        else:
            new.append(f)
    stale = sorted(
        k for k, allowed in ratchet.items() if counts.get(k, 0) < allowed
    )
    return new, stale
