"""Pass 2: the single-WFQ rule.

Exactly one virtual-clock WFQ implementation may exist —
``bitcoin_miner_tpu/utils/wfq.py``.  The correctness surface of that
discipline is two small idioms that history shows get copy-pasted and
then drift:

- **floor init**: ``min((p.vt for p in ... if p.items), default=0.0)`` —
  a new principal starting anywhere else either starves or is starved;
- **tie-break**: comparing ``(vt, seq)`` tuples — dropping ``seq`` makes
  selection nondeterministic across dict orders.

This pass flags any module outside utils/wfq.py that contains either
idiom: a ``min()``/``max()`` call with a ``default=`` keyword whose
arguments reach a ``.vt`` attribute, or a comparison between tuples
mentioning both ``.vt`` and ``.seq``.  Reuse the primitive instead.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import List, Optional, Tuple

from .common import Finding, iter_py_files, rel

PASS = "wfq"

#: The one sanctioned home of the discipline.
CANONICAL = "bitcoin_miner_tpu/utils/wfq.py"


def _mentions_attr(node: ast.AST, attr: str) -> bool:
    return any(
        isinstance(n, ast.Attribute) and n.attr == attr for n in ast.walk(node)
    )


def _check_tree(path: str, tree: ast.Module) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id in ("min", "max")
            and any(kw.arg == "default" for kw in node.keywords)
            and _mentions_attr(node, "vt")
        ):
            findings.append(
                Finding(
                    PASS,
                    "floor-init-reimplemented",
                    path,
                    node.lineno,
                    node.func.id,
                    "virtual-time floor computation outside utils/wfq.py — "
                    "use VirtualClockWFQ.add (the one copy of the rule)",
                )
            )
        if isinstance(node, ast.Compare):
            sides = [node.left, *node.comparators]
            tuple_sides = [s for s in sides if isinstance(s, ast.Tuple)]
            if any(
                _mentions_attr(s, "vt") and _mentions_attr(s, "seq")
                for s in tuple_sides
            ):
                findings.append(
                    Finding(
                        PASS,
                        "tiebreak-reimplemented",
                        path,
                        node.lineno,
                        "(vt, seq)",
                        "virtual-clock tie-break comparison outside "
                        "utils/wfq.py — use VirtualClockWFQ.select/pop",
                    )
                )
    return findings


def run(root: Path, scan_dirs: Optional[Tuple[str, ...]] = None) -> List[Finding]:
    findings: List[Finding] = []
    for path in iter_py_files(root, scan_dirs):
        rpath = rel(path, root)
        if rpath == CANONICAL:
            continue
        try:
            tree = ast.parse(path.read_text())
        except (SyntaxError, UnicodeDecodeError):
            continue  # the lock pass reports parse errors once
        findings.extend(_check_tree(rpath, tree))
    return findings
