"""CLI for the analysis suite: ``python -m tools.analyze``.

Exit 0 when every pass is clean (modulo the only-shrink ratchet),
non-zero on any new finding or stale ratchet entry.  Tier-1 runs this on
every PR (tests/test_analyze.py), so the passes stay fast,
``JAX_PLATFORMS=cpu``-safe, and network-free.

    python -m tools.analyze                      # all passes, repo mode
    python -m tools.analyze --pass lock,wfq      # a subset
    python -m tools.analyze --root tests/fixtures_analyze   # fixture tree
    python -m tools.analyze --update-ratchet     # after FIXING findings
    python -m tools.analyze --changed            # files changed vs HEAD
    python -m tools.analyze --changed main       # ... vs a ref
"""

from __future__ import annotations

import argparse
import subprocess
import sys
from pathlib import Path
from typing import List, Optional, Tuple

from . import PASSES
from .common import (
    DEFAULT_SCAN_DIRS,
    REPO_ROOT,
    Finding,
    apply_ratchet,
    load_ratchet,
    save_ratchet,
)
from .donatecheck import DONATE_SCAN_DIRS
from .tracecheck import TRACE_SCAN_DIRS

DEFAULT_RATCHET = Path(__file__).resolve().parent / "ratchet.json"

#: Per-file passes can run on exactly the changed files.  The rest
#: (contracts, sanitize, metrics) are whole-repo cross-checks: metrics
#: must re-balance emitters against the registry after ANY change, while
#: contracts/sanitize self-tests only depend on their trigger dirs.
_PER_FILE_PASSES = frozenset({"lock", "wfq", "trace", "loop", "donate", "thread"})
_WHOLE_PASS_TRIGGERS = {
    # contracts: workloads joined the trigger set with the registry's
    # golden pass (ISSUE 9), ops with the device-tier recompute
    # (ISSUE 20 — a kernel edit can drift the blake2b64 vectors while
    # bitcoin/lsp/apps are untouched).
    "contracts": ("bitcoin_miner_tpu/bitcoin", "bitcoin_miner_tpu/lsp",
                  "bitcoin_miner_tpu/apps", "bitcoin_miner_tpu/workloads",
                  "bitcoin_miner_tpu/ops", "bitcoin_miner_tpu/parallel",
                  "tools/analyze"),
    "sanitize": ("bitcoin_miner_tpu/utils", "bitcoin_miner_tpu/apps",
                 "tools/analyze"),
    "metrics": DEFAULT_SCAN_DIRS,
}


def _scan_dirs_for(name: str) -> Tuple[str, ...]:
    if name == "trace":
        return TRACE_SCAN_DIRS
    if name == "donate":
        return DONATE_SCAN_DIRS
    return DEFAULT_SCAN_DIRS


def _changed_files(root: Path, ref: str) -> Optional[List[str]]:
    """Repo-relative .py paths changed vs ``ref`` (committed diff, index,
    worktree, plus untracked), or None when git cannot answer."""
    out: List[str] = []
    for cmd in (
        ["git", "diff", "--name-only", ref, "--"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ):
        try:
            proc = subprocess.run(
                cmd, cwd=root, capture_output=True, text=True, timeout=30
            )
        except (OSError, subprocess.TimeoutExpired):
            return None
        if proc.returncode != 0:
            return None
        out.extend(line.strip() for line in proc.stdout.splitlines() if line.strip())
    return sorted(
        {p for p in out if p.endswith(".py") and (root / p).exists()}
    )


def main(argv: List[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="python -m tools.analyze")
    ap.add_argument(
        "mode",
        nargs="?",
        default=None,
        choices=["lockcheck"],
        help="subcommand: `lockcheck --fix` mechanically wraps safe "
        "unguarded accesses in `with <lock>:` and prints annotated "
        "diffs for the rest (ISSUE 12 carry-over)",
    )
    ap.add_argument(
        "--fix",
        action="store_true",
        help="with `lockcheck`: rewrite safe findings in place",
    )
    ap.add_argument(
        "--dry-run",
        action="store_true",
        help="with `lockcheck --fix`: print the would-be diffs, touch nothing",
    )
    ap.add_argument(
        "--pass",
        dest="passes",
        default="all",
        help=f"comma-separated subset of: {','.join(PASSES)} (default all)",
    )
    ap.add_argument(
        "--root",
        default=None,
        help="scan this tree instead of the repo (fixture mode: contracts/"
        "sanitize pick up bad_contract.py / bad_race.py found under it)",
    )
    ap.add_argument(
        "--ratchet",
        default=None,
        help="grandfather file (default tools/analyze/ratchet.json in repo "
        "mode, none in --root mode)",
    )
    ap.add_argument(
        "--update-ratchet",
        action="store_true",
        help="rewrite the ratchet from current findings (only for locking "
        "in FIXES — never to admit new findings)",
    )
    ap.add_argument(
        "--changed",
        nargs="?",
        const="HEAD",
        default=None,
        metavar="REF",
        help="incremental mode: per-file passes run only on files changed "
        "vs REF (default HEAD, incl. uncommitted + untracked); whole-repo "
        "passes run fully when a trigger dir changed, else skip.  Same "
        "exit codes — cheap enough for a pre-commit hook (see README)",
    )
    ap.add_argument("-q", "--quiet", action="store_true")
    args = ap.parse_args(argv)

    if args.mode == "lockcheck":
        if not args.fix:
            ap.error("lockcheck mode needs --fix (plain checking is "
                     "`--pass lock`)")
        from .lockfix import fix as lockfix_fix

        repo_mode = args.root is None
        root = REPO_ROOT if repo_mode else Path(args.root).resolve()
        scan = DEFAULT_SCAN_DIRS if repo_mode else None
        fixed, reviews = lockfix_fix(root, scan, write=not args.dry_run)
        for entry in reviews:
            print(entry)
        if not args.quiet:
            print(
                f"tools.analyze lockcheck --fix: {fixed} finding(s) "
                f"wrapped, {len(reviews)} left for review"
            )
        return 1 if reviews else 0

    names = (
        list(PASSES) if args.passes == "all" else [p.strip() for p in args.passes.split(",")]
    )
    unknown = [n for n in names if n not in PASSES]
    if unknown:
        print(f"unknown pass(es): {', '.join(unknown)}", file=sys.stderr)
        return 2

    repo_mode = args.root is None
    root = REPO_ROOT if repo_mode else Path(args.root).resolve()

    changed: Optional[List[str]] = None
    if args.changed is not None:
        if not repo_mode:
            print("--changed only applies in repo mode", file=sys.stderr)
            return 2
        if args.update_ratchet:
            print("--changed cannot update the ratchet (a partial scan "
                  "would erase unscanned grandfathers)", file=sys.stderr)
            return 2
        changed = _changed_files(root, args.changed)
        if changed is None:
            print(
                "--changed: git unavailable, running the full suite",
                file=sys.stderr,
            )

    findings: List[Finding] = []
    # pass name -> scanned-paths set (per-file) or None (ran fully);
    # passes skipped by --changed are absent, and their ratchet keys are
    # out of scope for the stale check this run.
    ran_scope: dict = {}
    for name in names:
        run = PASSES[name]
        if repo_mode:
            scan = _scan_dirs_for(name)
            if changed is not None:
                if name in _PER_FILE_PASSES:
                    scoped = tuple(
                        p for p in changed
                        if any(p == d or p.startswith(d + "/") for d in scan)
                    )
                    if not scoped:
                        continue
                    scan = scoped
                    ran_scope[name] = set(scoped)
                else:
                    triggers = _WHOLE_PASS_TRIGGERS.get(name, DEFAULT_SCAN_DIRS)
                    if not any(
                        p == d or p.startswith(d + "/")
                        for p in changed
                        for d in triggers
                    ):
                        continue
                    ran_scope[name] = None
            else:
                ran_scope[name] = None
        else:
            scan = None  # the whole fixture tree
            ran_scope[name] = None
        if name == "contracts" and not repo_mode:
            bad = list(root.rglob("bad_contract.py"))
            if not bad:
                continue  # nothing to check against in this tree
            import importlib.util

            spec = importlib.util.spec_from_file_location("bad_contract", bad[0])
            assert spec is not None and spec.loader is not None
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            findings.extend(
                run(root, None, modules={
                    "bitcoin_message": mod,
                    "hash": mod,
                })
            )
            continue
        findings.extend(run(root, scan))

    ratchet_path = (
        Path(args.ratchet)
        if args.ratchet
        else (DEFAULT_RATCHET if repo_mode else None)
    )
    if args.update_ratchet:
        if ratchet_path is None:
            print("--update-ratchet needs a ratchet path", file=sys.stderr)
            return 2
        save_ratchet(ratchet_path, findings)
        print(f"ratchet rewritten: {len(findings)} grandfathered finding(s)")
        return 0

    ratchet = load_ratchet(ratchet_path) if ratchet_path else {}
    if changed is not None:
        # An incremental run only sees the changed files' findings, so
        # only the matching ratchet slice participates — otherwise every
        # unscanned grandfather would read as stale.
        def _in_scope(key: str) -> bool:
            pass_name, path = key.split(":", 2)[:2]
            if pass_name not in ran_scope:
                return False
            scope = ran_scope[pass_name]
            return scope is None or path in scope

        ratchet = {k: v for k, v in ratchet.items() if _in_scope(k)}
    new, stale = apply_ratchet(findings, ratchet)
    grandfathered = len(findings) - len(new)

    for f in new:
        print(f.render())
    for key in stale:
        print(
            f"stale ratchet entry: {key} no longer fires at its recorded "
            f"count — shrink tools/analyze/ratchet.json (the only-shrink "
            f"contract: fixed findings stay fixed)"
        )
    if not args.quiet:
        print(
            f"tools.analyze: {len(names)} pass(es), {len(new)} new finding(s), "
            f"{grandfathered} grandfathered, {len(stale)} stale ratchet "
            f"entr{'y' if len(stale) == 1 else 'ies'}"
        )
    return 1 if new or stale else 0


if __name__ == "__main__":
    sys.exit(main())
