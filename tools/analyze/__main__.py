"""CLI for the analysis suite: ``python -m tools.analyze``.

Exit 0 when every pass is clean (modulo the only-shrink ratchet),
non-zero on any new finding or stale ratchet entry.  Tier-1 runs this on
every PR (tests/test_analyze.py), so the passes stay fast,
``JAX_PLATFORMS=cpu``-safe, and network-free.

    python -m tools.analyze                      # all passes, repo mode
    python -m tools.analyze --pass lock,wfq      # a subset
    python -m tools.analyze --root tests/fixtures_analyze   # fixture tree
    python -m tools.analyze --update-ratchet     # after FIXING findings
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path
from typing import List

from . import PASSES
from .common import (
    DEFAULT_SCAN_DIRS,
    REPO_ROOT,
    Finding,
    apply_ratchet,
    load_ratchet,
    save_ratchet,
)
from .tracecheck import TRACE_SCAN_DIRS

DEFAULT_RATCHET = Path(__file__).resolve().parent / "ratchet.json"


def main(argv: List[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="python -m tools.analyze")
    ap.add_argument(
        "mode",
        nargs="?",
        default=None,
        choices=["lockcheck"],
        help="subcommand: `lockcheck --fix` mechanically wraps safe "
        "unguarded accesses in `with <lock>:` and prints annotated "
        "diffs for the rest (ISSUE 12 carry-over)",
    )
    ap.add_argument(
        "--fix",
        action="store_true",
        help="with `lockcheck`: rewrite safe findings in place",
    )
    ap.add_argument(
        "--dry-run",
        action="store_true",
        help="with `lockcheck --fix`: print the would-be diffs, touch nothing",
    )
    ap.add_argument(
        "--pass",
        dest="passes",
        default="all",
        help=f"comma-separated subset of: {','.join(PASSES)} (default all)",
    )
    ap.add_argument(
        "--root",
        default=None,
        help="scan this tree instead of the repo (fixture mode: contracts/"
        "sanitize pick up bad_contract.py / bad_race.py found under it)",
    )
    ap.add_argument(
        "--ratchet",
        default=None,
        help="grandfather file (default tools/analyze/ratchet.json in repo "
        "mode, none in --root mode)",
    )
    ap.add_argument(
        "--update-ratchet",
        action="store_true",
        help="rewrite the ratchet from current findings (only for locking "
        "in FIXES — never to admit new findings)",
    )
    ap.add_argument("-q", "--quiet", action="store_true")
    args = ap.parse_args(argv)

    if args.mode == "lockcheck":
        if not args.fix:
            ap.error("lockcheck mode needs --fix (plain checking is "
                     "`--pass lock`)")
        from .lockfix import fix as lockfix_fix

        repo_mode = args.root is None
        root = REPO_ROOT if repo_mode else Path(args.root).resolve()
        scan = DEFAULT_SCAN_DIRS if repo_mode else None
        fixed, reviews = lockfix_fix(root, scan, write=not args.dry_run)
        for entry in reviews:
            print(entry)
        if not args.quiet:
            print(
                f"tools.analyze lockcheck --fix: {fixed} finding(s) "
                f"wrapped, {len(reviews)} left for review"
            )
        return 1 if reviews else 0

    names = (
        list(PASSES) if args.passes == "all" else [p.strip() for p in args.passes.split(",")]
    )
    unknown = [n for n in names if n not in PASSES]
    if unknown:
        print(f"unknown pass(es): {', '.join(unknown)}", file=sys.stderr)
        return 2

    repo_mode = args.root is None
    root = REPO_ROOT if repo_mode else Path(args.root).resolve()

    findings: List[Finding] = []
    for name in names:
        run = PASSES[name]
        if repo_mode:
            scan = TRACE_SCAN_DIRS if name == "trace" else DEFAULT_SCAN_DIRS
        else:
            scan = None  # the whole fixture tree
        if name == "contracts" and not repo_mode:
            bad = list(root.rglob("bad_contract.py"))
            if not bad:
                continue  # nothing to check against in this tree
            import importlib.util

            spec = importlib.util.spec_from_file_location("bad_contract", bad[0])
            assert spec is not None and spec.loader is not None
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            findings.extend(
                run(root, None, modules={
                    "bitcoin_message": mod,
                    "hash": mod,
                })
            )
            continue
        findings.extend(run(root, scan))

    ratchet_path = (
        Path(args.ratchet)
        if args.ratchet
        else (DEFAULT_RATCHET if repo_mode else None)
    )
    if args.update_ratchet:
        if ratchet_path is None:
            print("--update-ratchet needs a ratchet path", file=sys.stderr)
            return 2
        save_ratchet(ratchet_path, findings)
        print(f"ratchet rewritten: {len(findings)} grandfathered finding(s)")
        return 0

    ratchet = load_ratchet(ratchet_path) if ratchet_path else {}
    new, stale = apply_ratchet(findings, ratchet)
    grandfathered = len(findings) - len(new)

    for f in new:
        print(f.render())
    for key in stale:
        print(
            f"stale ratchet entry: {key} no longer fires at its recorded "
            f"count — shrink tools/analyze/ratchet.json (the only-shrink "
            f"contract: fixed findings stay fixed)"
        )
    if not args.quiet:
        print(
            f"tools.analyze: {len(names)} pass(es), {len(new)} new finding(s), "
            f"{grandfathered} grandfathered, {len(stale)} stale ratchet "
            f"entr{'y' if len(stale) == 1 else 'ies'}"
        )
    return 1 if new or stale else 0


if __name__ == "__main__":
    sys.exit(main())
