"""Serving-layer load generator: duplicate-heavy traffic against the gateway.

`fleet_bench.py` measures one big job's delivered nonces/s; this tool
measures the SERVING layer — many small jobs from many concurrent clients,
half of them duplicates, which is the regime the gateway exists for
(ISSUE 3 / ROADMAP "millions of users"): coalescing folds concurrent
twin sweeps into one, the content-addressed cache answers solved
signatures with zero device work, and admission keeps the inflow bounded.

The fleet is fully in-process (real loopback LSP: `apps.server.serve`
thread + miner threads on the cpu tier + N client threads), so one run
gives apples-to-apples legs:

- **gateway leg** — `serve` runs a :class:`Gateway`-wrapped scheduler;
- **baseline leg** (unless ``--no-baseline``) — the bare scheduler, where
  every duplicate burns the fleet again.

Every job's Result is validated bit-exact against the hashlib oracle
(cached answers included — a wrong cache hit fails the run), and the
gateway leg ends with a repeat-submission probe asserting a solved job
answers with ZERO new chunks assigned.  Prints one JSON line; `--fast`
keeps the whole thing under ~30 s on CPU so it gates tier-1
(tests/test_loadgen.py).

`--overlap` (ISSUE 5) switches to the interval-store regime: a
nested/overlapping-range workload over a few shared data keys, run twice
— an interval-store leg (`SpanStore` armed) vs an exact-match-cache leg
(`SpanStore(capacity=0)`) — bit-exact both, plus a probe asserting a
never-issued, fully covered SUB-RANGE of solved work answers with zero
chunks assigned.  The JSON line reports both legs' `swept_nonces` and
their reduction (the BENCH_pr5.json artifact).

`--open-loop RATE` (ISSUE 15) replaces the N closed-loop client threads
with **open-loop** load: Poisson arrivals at RATE requests/sec for
`--duration` seconds, each arrival an independent conn+request+close on
one shared asyncio loop against the event-loop ingress
(`apps.server.AsyncIngress`).  Closed-loop clients slow down when the
server does — they can never overload it; open-loop is how production
traffic actually arrives, so shed rate, p99 under saturation and the
failed fraction are finally measurable.

`--conn-scale` (ISSUE 15) is the ingress bench pair on the same seeded
workload: a **threaded-facade** leg (blocking `serve` + one loop thread
per conn, open-loop arrivals via thread-per-request) vs an **async
ingress** leg (`AsyncIngress` + AsyncClient conns multiplexed on ONE
loop), each ramping live conns and then taking open-loop load — live
conns, process thread counts (flat-in-conns for async), RSS, shed rate
and p99 stamped per leg, plus the repeat/sub-range zero-chunk probes
through the async path (the BENCH_pr15.json artifact).

Usage: python tools/loadgen.py [--fast] [--overlap] [--clients N]
       [--jobs N] [--dup F] [--max-nonce N] [--miners N] [--no-baseline]
       [--seed N] [--open-loop RATE] [--duration S] [--conn-scale]
"""

from __future__ import annotations

import argparse
import json
import random
import sys
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))  # repo root


def log(*a) -> None:
    print(*a, file=sys.stderr, flush=True)


def build_workload(args) -> list:
    """A duplicate-heavy job list: each entry is a ``(data, lower, upper)``
    signature; with probability ``--dup`` a job repeats an earlier
    signature — biased toward RECENT ones, so some duplicates land while
    their twin is still sweeping (coalesce) and some after it solved
    (cache hit)."""
    rng = random.Random(args.seed)
    issued: list = []
    jobs: list = []
    for i in range(args.jobs):
        if issued and rng.random() < args.dup:
            if rng.random() < 0.5:
                sig = rng.choice(issued[-4:])  # recent: likely in flight
            else:
                sig = rng.choice(issued)  # any: likely already solved
        else:
            lo = 0
            hi = rng.randint(args.max_nonce // 2, args.max_nonce)
            sig = (f"load{len(issued)}", lo, hi)
            issued.append(sig)
        jobs.append(sig)
    return jobs


def build_overlap_workload(args, n_datas: int = 3) -> list:
    """Overlap-heavy jobs for the interval store (ISSUE 5): a few shared
    data keys, each hit by growing prefixes ``[0, hi]`` (extensions sweep
    only the new tail), interior sub-ranges ``[lo, hi]`` (answered from
    chunk spans), and exact repeats (both stores should catch those) —
    the many-clients regime where ranges nest and overlap but rarely
    repeat exactly.  ``n_datas`` widens the key family (the federation
    bench uses more keys so the ring has something to spread)."""
    rng = random.Random(args.seed)
    datas = [f"ov{i}" for i in range(n_datas)]
    issued: list = []
    jobs: list = []
    for _ in range(args.jobs):
        r = rng.random()
        if issued and r < 0.15:
            sig = rng.choice(issued)  # exact repeat
        elif r < 0.55:
            # growing prefix: nested [0, hi] family on one data key
            data = rng.choice(datas)
            hi = rng.randint(args.max_nonce // 4, args.max_nonce)
            sig = (data, 0, hi)
        else:
            # interior sub-range of the same families
            data = rng.choice(datas)
            lo = rng.randint(0, args.max_nonce // 2)
            hi = rng.randint(lo, args.max_nonce)
            sig = (data, lo, hi)
        issued.append(sig)
        jobs.append(sig)
    return jobs


def run_leg(
    gateway_on: bool, jobs: list, args, oracle: dict, spans_on: bool = True
) -> dict:
    """Stand up one in-process fleet, push the whole workload through it
    with ``--clients`` concurrent client threads, tear it down.  Returns
    the leg's timing + METRICS deltas.  ``spans_on=False`` runs the
    gateway with the interval store disabled — the exact-match-cache
    comparison leg of the --overlap bench."""
    from bitcoin_miner_tpu import lsp
    from bitcoin_miner_tpu.apps import client as client_mod
    from bitcoin_miner_tpu.apps import miner as miner_mod
    from bitcoin_miner_tpu.apps import server as server_mod
    from bitcoin_miner_tpu.apps.scheduler import Scheduler
    from bitcoin_miner_tpu.gateway import Gateway, ResultCache, SpanStore
    from bitcoin_miner_tpu.utils.metrics import METRICS, Histogram

    params = lsp.Params(epoch_limit=5, epoch_millis=200, window_size=5)
    server = lsp.Server(0, params)
    sched = Scheduler(min_chunk=args.min_chunk, workload=args.wl)
    engine = (
        Gateway(
            sched,
            cache=ResultCache(capacity=args.cache_size),
            spans=SpanStore() if spans_on else SpanStore(capacity=0),
            # All loopback clients share one peer addr, so a real rate
            # limit would throttle the whole bench as ONE client.
            rate=None,
            max_active=args.max_active,
        )
        if gateway_on
        else sched
    )
    threading.Thread(
        target=server_mod.serve,
        args=(server, engine),
        kwargs={"tick_interval": 0.05},
        daemon=True,
    ).start()
    search = miner_mod.make_search("cpu", workload=args.wl)
    for _ in range(args.miners):
        mc = lsp.Client("127.0.0.1", server.port, params)
        threading.Thread(
            target=miner_mod.run_miner, args=(mc, search), daemon=True
        ).start()

    before = METRICS.snapshot()
    errors: list = []
    cursor = [0]
    cursor_lock = threading.Lock()
    # Client-observed request→result latency (ISSUE 6): one mergeable
    # log-bucket histogram per leg, p50/p95/p99 into the BENCH JSON line
    # so the perf trajectory has a latency axis next to jobs/s.
    latency = Histogram()

    def worker(idx: int) -> None:
        while True:
            with cursor_lock:
                if cursor[0] >= len(jobs):
                    return
                job_i = cursor[0]
                cursor[0] += 1
            data, lo, hi = jobs[job_i]
            c = lsp.Client("127.0.0.1", server.port, params)
            t_req = time.monotonic()
            try:
                got = client_mod.request_once(c, data, hi, lower=lo)
            finally:
                c.close()
            latency.observe(time.monotonic() - t_req)
            want = oracle[(data, lo, hi)]
            if got != want:
                errors.append(
                    f"job {job_i} ({data},{lo},{hi}): got {got}, want {want}"
                )
                return

    t0 = time.monotonic()
    threads = [
        threading.Thread(target=worker, args=(i,), daemon=True)
        for i in range(args.clients)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=args.timeout)
        if t.is_alive():
            errors.append(f"worker timed out after {args.timeout:.0f}s")
    wall = time.monotonic() - t0

    repeat_zero_chunks = None
    subrange_zero_chunks = None
    if gateway_on and not errors:
        # Acceptance probe: a repeat of a SOLVED signature must answer
        # from the cache with zero new chunks assigned.
        assigned_before = METRICS.get("sched.chunks_assigned")
        data, lo, hi = jobs[0]
        c = lsp.Client("127.0.0.1", server.port, params)
        try:
            got = client_mod.request_once(c, data, hi, lower=lo)
        finally:
            c.close()
        if got != oracle[(data, lo, hi)]:
            errors.append(f"repeat probe wrong result: {got}")
        repeat_zero_chunks = (
            METRICS.get("sched.chunks_assigned") == assigned_before
        )
        if not repeat_zero_chunks:
            errors.append("repeat probe assigned chunks (cache missed)")
    if gateway_on and spans_on and not errors:
        subrange_zero_chunks = _subrange_probe(
            engine, server, params, jobs, errors, args.oracle_fn
        )

    server.close()
    after = METRICS.snapshot()
    deltas = {
        k: after[k] - before.get(k, 0)
        for k in sorted(after)
        if k.startswith(("gateway.", "sched."))
        and after[k] != before.get(k, 0)
    }
    if errors:
        raise RuntimeError(
            f"{'gateway' if gateway_on else 'baseline'} leg failed: "
            + "; ".join(errors[:5])
        )
    lat = latency.snapshot()
    return {
        "wall_s": wall,
        "jobs_per_sec": len(jobs) / wall if wall > 0 else 0.0,
        "counters": deltas,
        "repeat_zero_chunks": repeat_zero_chunks,
        "subrange_zero_chunks": subrange_zero_chunks,
        "latency_s": {
            "p50": round(lat["p50"], 6),
            "p95": round(lat["p95"], 6),
            "p99": round(lat["p99"], 6),
            "count": int(lat["count"]),
        },
    }


def _covered_subrange(spans_store, jobs, errors):
    """A NEVER-ISSUED strict sub-range of the widest issued signature
    that the interval store fully covers, or None (with the reason
    appended to ``errors``).  Candidates are built from the solved-span
    geometry: prefixes ending at a span boundary are covered whenever
    the spans are contiguous; prefixes/suffixes cut AT a recorded argmin
    keep the boundary span answerable by construction.  Each candidate
    is re-verified through the planner itself before use."""
    issued = set(jobs)
    data, lo, hi = max(jobs, key=lambda s: s[2] - s[1])
    span_map = spans_store._maps.get(data)
    if span_map is None:
        errors.append(f"no solved spans recorded for {data!r}")
        return None
    for s_lo, s_hi, _h, n in span_map.spans():
        for cand in ((lo, s_hi), (lo, n), (n, hi)):
            qlo, qhi = cand
            if not (lo <= qlo <= qhi <= hi) or (qlo, qhi) == (lo, hi):
                continue
            if (data, qlo, qhi) in issued:
                continue
            best, gaps = spans_store.cover(data, qlo, qhi)
            if not gaps and best is not None:
                return (data, qlo, qhi)
    errors.append("no fully covered strict sub-range found to probe")
    return None


def _subrange_probe(engine, server, params, jobs, errors, oracle_fn):
    """The ISSUE 5 acceptance probe: find a NEVER-ISSUED strict sub-range
    of the widest solved signature that the interval store fully covers,
    request it over the wire, and assert it answers bit-exact with zero
    chunks assigned (mirroring the exact-repeat `repeat_zero_chunks`
    probe).  ``oracle_fn`` is the selected workload's hashlib-tier
    min-range oracle."""
    from bitcoin_miner_tpu import lsp
    from bitcoin_miner_tpu.apps import client as client_mod
    from bitcoin_miner_tpu.utils.metrics import METRICS

    cand = _covered_subrange(engine.spans, jobs, errors)
    if cand is None:
        return False
    data, qlo, qhi = cand
    assigned_before = METRICS.get("sched.chunks_assigned")
    c = lsp.Client("127.0.0.1", server.port, params)
    try:
        got = client_mod.request_once(c, data, qhi, lower=qlo)
    finally:
        c.close()
    want = oracle_fn(data, qlo, qhi)
    if got != want:
        errors.append(
            f"subrange probe ({data},{qlo},{qhi}): got {got}, want {want}"
        )
    ok = METRICS.get("sched.chunks_assigned") == assigned_before
    if not ok:
        errors.append("subrange probe assigned chunks (interval store missed)")
    return ok


def _free_udp_port() -> int:
    import socket

    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def run_federation_leg(n_replicas: int, jobs: list, args, oracle: dict) -> dict:
    """Stand up ``n_replicas`` in-process federation cells (each with its
    own scheduler + miners), spray the workload round-robin across the
    replicas' PUBLIC ports — the load-balancer-spray regime consistent-
    hash routing exists for — and fail a client over to the next replica
    if its conn dies.  Returns timing + METRICS deltas + the federation
    probes (ISSUE 8):

    - ``repeat_zero_chunks``: a repeat of a solved signature submitted at
      EVERY replica answers with zero new chunks anywhere (routing lands
      it on the home's cache);
    - ``cross_replica_zero_chunks``: after gossip, a never-issued
      fully-covered sub-range queried at a NON-home replica's federation
      port (the local-serve path) answers bit-exact with zero chunks —
      a range solved anywhere answers everywhere;
    - ``gossip_max_frame_bytes``: the largest gossip datagram written
      (must respect the frozen 1000-byte wire ceiling with envelope
      headroom)."""
    from bitcoin_miner_tpu import lsp
    from bitcoin_miner_tpu.apps import client as client_mod
    from bitcoin_miner_tpu.apps import miner as miner_mod
    from bitcoin_miner_tpu.apps.scheduler import Scheduler
    from bitcoin_miner_tpu.federation import Replica, Ring
    from bitcoin_miner_tpu.utils.metrics import METRICS, Histogram

    params = lsp.Params(epoch_limit=5, epoch_millis=200, window_size=5)
    names = [f"r{i}" for i in range(n_replicas)]
    fed_ports = {name: _free_udp_port() for name in names}
    replicas = []
    for name in names:
        peers = {
            other: ("127.0.0.1", fed_ports[other])
            for other in names
            if other != name
        }
        replicas.append(
            Replica(
                name,
                peers,
                fed_port=fed_ports[name],
                params=params,
                scheduler=Scheduler(min_chunk=args.min_chunk, workload=args.wl),
                gossip_interval=0.2,
                tick_interval=0.05,
                workload=args.wl,
            ).start()
        )
    search = miner_mod.make_search("cpu", workload=args.wl)
    for rep in replicas:
        for _ in range(args.miners):
            mc = lsp.Client("127.0.0.1", rep.port, params)
            threading.Thread(
                target=miner_mod.run_miner, args=(mc, search), daemon=True
            ).start()

    ports = [rep.port for rep in replicas]
    before = METRICS.snapshot()
    errors: list = []
    cursor = [0]
    cursor_lock = threading.Lock()
    latency = Histogram()

    def one_request(start_idx: int, data: str, lo: int, hi: int):
        """Request with load-balancer failover: try each replica once."""
        for k in range(len(ports)):
            port = ports[(start_idx + k) % len(ports)]
            try:
                c = lsp.Client("127.0.0.1", port, params)
            except (lsp.LspError, OSError):
                continue
            try:
                got = client_mod.request_once(c, data, hi, lower=lo)
            finally:
                try:
                    c.close()
                except lsp.LspError:
                    pass
            if got is not None:
                return got
        return None

    def worker(idx: int) -> None:
        while True:
            with cursor_lock:
                if cursor[0] >= len(jobs):
                    return
                job_i = cursor[0]
                cursor[0] += 1
            data, lo, hi = jobs[job_i]
            t_req = time.monotonic()
            got = one_request(job_i, data, lo, hi)
            latency.observe(time.monotonic() - t_req)
            want = oracle[(data, lo, hi)]
            if got != want:
                errors.append(
                    f"job {job_i} ({data},{lo},{hi}): got {got}, want {want}"
                )
                return

    t0 = time.monotonic()
    threads = [
        threading.Thread(target=worker, args=(i,), daemon=True)
        for i in range(args.clients)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=args.timeout)
        if t.is_alive():
            errors.append(f"worker timed out after {args.timeout:.0f}s")
    wall = time.monotonic() - t0

    repeat_zero = cross_zero = None
    if not errors:
        # Repeat probe at EVERY replica: routing must land each repeat on
        # the home cell's cache/spans — zero chunks assigned anywhere.
        assigned = METRICS.get("sched.chunks_assigned")
        data, lo, hi = jobs[0]
        repeat_zero = True
        for i in range(len(ports)):
            got = one_request(i, data, lo, hi)
            if got != oracle[(data, lo, hi)]:
                errors.append(f"repeat probe at replica {i}: got {got}")
                repeat_zero = False
        if METRICS.get("sched.chunks_assigned") != assigned:
            errors.append("repeat probe assigned chunks at some replica")
            repeat_zero = False
    if not errors and n_replicas > 1:
        cross_zero = _cross_replica_probe(
            replicas, params, jobs, oracle, errors, args.oracle_fn, Ring,
            METRICS,
        )

    gossip_max = max(rep.gossip.max_frame_bytes for rep in replicas)
    for rep in replicas:
        rep.close()
    after = METRICS.snapshot()
    deltas = {
        k: after[k] - before.get(k, 0)
        for k in sorted(after)
        if k.startswith(("gateway.", "sched.", "federation."))
        and after[k] != before.get(k, 0)
    }
    if errors:
        raise RuntimeError(
            f"federation leg ({n_replicas} replicas) failed: "
            + "; ".join(errors[:5])
        )
    lat = latency.snapshot()
    return {
        "wall_s": wall,
        "jobs_per_sec": len(jobs) / wall if wall > 0 else 0.0,
        "counters": deltas,
        "repeat_zero_chunks": repeat_zero,
        "cross_replica_zero_chunks": cross_zero,
        "gossip_max_frame_bytes": gossip_max,
        "latency_s": {
            "p50": round(lat["p50"], 6),
            "p95": round(lat["p95"], 6),
            "p99": round(lat["p99"], 6),
            "count": int(lat["count"]),
        },
    }


def _cross_replica_probe(
    replicas, params, jobs, oracle, errors, oracle_fn, Ring, METRICS
):
    """The ISSUE 8 acceptance probe: a never-issued sub-range of solved
    work, fully covered BY GOSSIP on a replica that is NOT the data's
    home, must answer bit-exact with zero chunks assigned — through that
    replica's federation port (the local-serve path, so the answer
    provably comes from the probed replica's own spans)."""
    from bitcoin_miner_tpu import lsp
    from bitcoin_miner_tpu.apps import client as client_mod

    issued = {tuple(j) for j in jobs}
    ring = Ring([rep.cell for rep in replicas])
    by_name = {rep.cell: rep for rep in replicas}
    # Widest signature whose home is identifiable; probe a DIFFERENT cell.
    data, lo, hi = max(jobs, key=lambda s: s[2] - s[1])
    home = ring.home(data)
    probe_rep = next(rep for rep in replicas if rep.cell != home)
    # Wait for gossip (delta beats + full syncs) to cover a candidate
    # sub-range on the probed replica, built from its own span geometry.
    deadline = time.monotonic() + 10.0
    sub = None
    while time.monotonic() < deadline and sub is None:
        with probe_rep.lock:
            span_map = probe_rep.spans._maps.get(data)
            rows = span_map.spans() if span_map is not None else []
            for s_lo, s_hi, _h, n in rows:
                for cand in ((lo, s_hi), (lo, n), (n, hi)):
                    qlo, qhi = cand
                    if not (lo <= qlo <= qhi <= hi) or (qlo, qhi) == (lo, hi):
                        continue
                    if (data, qlo, qhi) in issued:
                        continue
                    best, gaps = probe_rep.spans.cover(data, qlo, qhi)
                    if not gaps and best is not None:
                        sub = (qlo, qhi)
                        break
                if sub is not None:
                    break
        if sub is None:
            time.sleep(0.1)
    if sub is None:
        errors.append(
            f"gossip never covered a probe sub-range of {data!r} on "
            f"{probe_rep.cell} (home {home})"
        )
        return False
    assigned = METRICS.get("sched.chunks_assigned")
    c = lsp.Client("127.0.0.1", by_name[probe_rep.cell].fed_port, params)
    try:
        got = client_mod.request_once(c, data, sub[1], lower=sub[0])
    finally:
        c.close()
    want = oracle_fn(data, sub[0], sub[1])
    if got != want:
        errors.append(
            f"cross-replica probe ({data},{sub[0]},{sub[1]}) on "
            f"{probe_rep.cell}: got {got}, want {want}"
        )
    ok = METRICS.get("sched.chunks_assigned") == assigned
    if not ok:
        errors.append("cross-replica probe assigned chunks (gossip missed)")
    return ok and got == want


# --------------------------------------------------------------------------
# Event-loop ingress benches (ISSUE 15): open-loop load + conn-scale pair
# --------------------------------------------------------------------------


def _rss_kb() -> int:
    """Current resident set (kB) — per-leg, unlike ru_maxrss which only
    ever grows across a process's legs."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1])
    except (OSError, ValueError, IndexError):
        pass
    import resource
    import sys as _sys

    rss = int(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss)
    # Fallback is a lifetime MAX, not the current figure — and Darwin
    # reports ru_maxrss in bytes where Linux uses kB.
    return rss // 1024 if _sys.platform == "darwin" else rss


def _serving_stack(kind: str, args, tick_interval: float = 0.05):
    """One in-process serving cell for the ingress benches: gateway-
    wrapped scheduler, ``--miners`` cpu-tier miners, and the requested
    transport shell — ``"threaded"`` (blocking facade + serve thread) or
    ``"async"`` (the event-loop AsyncIngress).  Returns
    ``(params, port, engine, close_fn)``."""
    from bitcoin_miner_tpu import lsp
    from bitcoin_miner_tpu.apps import miner as miner_mod
    from bitcoin_miner_tpu.apps import server as server_mod
    from bitcoin_miner_tpu.apps.scheduler import Scheduler
    from bitcoin_miner_tpu.gateway import Gateway, ResultCache, SpanStore

    # Long epochs: 10k-conn keepalive traffic scales with 1/epoch, and
    # the conn-scale leg's point is holding conns, not probing loss fast.
    params = lsp.Params(epoch_limit=8, epoch_millis=500, window_size=5)
    engine = Gateway(
        Scheduler(min_chunk=args.min_chunk, workload=args.wl),
        cache=ResultCache(capacity=args.cache_size),
        spans=SpanStore(),
        # All loopback clients share one peer addr (see run_leg); the
        # overload lever here is the bounded admission queue instead.
        rate=None,
        max_active=args.max_active,
        max_queued=args.ol_queue,
    )
    if kind == "async":
        ingress = server_mod.AsyncIngress(
            0, scheduler=engine, params=params, tick_interval=tick_interval
        ).start()
        port, close_fn = ingress.port, ingress.close
    else:
        server = lsp.Server(0, params)
        threading.Thread(
            target=server_mod.serve,
            args=(server, engine),
            kwargs={"tick_interval": tick_interval},
            daemon=True,
        ).start()
        port, close_fn = server.port, server.close
    search = miner_mod.make_search("cpu", workload=args.wl)
    for _ in range(args.miners):
        mc = lsp.Client("127.0.0.1", port, params)
        threading.Thread(
            target=miner_mod.run_miner, args=(mc, search), daemon=True
        ).start()
    return params, port, engine, close_fn


async def _request_result(c, data, lo, hi, timeout):
    """Write one Request on an open AsyncClient conn and read frames
    until its RESULT arrives; None on loss/shed/timeout.  The single
    wire-probe loop every async bench path shares."""
    import asyncio as _aio

    from bitcoin_miner_tpu import lsp
    from bitcoin_miner_tpu.bitcoin.message import Message, MsgType

    try:
        c.write(Message.request(data, lo, hi).marshal())
        while True:
            payload = await _aio.wait_for(c.read(), timeout)
            m = Message.unmarshal(payload)
            if m is not None and m.type == MsgType.RESULT:
                return (m.hash, m.nonce)
    except (lsp.LspError, _aio.TimeoutError):
        return None


async def _ol_one_async(port, params, sig, oracle, hist, stats, errors, timeout):
    """One open-loop arrival on the shared client loop: fresh conn,
    one request, read the Result, close.  A conn the gateway sheds (or
    that times out under saturation) counts ``failed`` — the client-side
    view; the authoritative shed count is the gateway.shed delta."""
    import asyncio as _aio
    import time as _t

    from bitcoin_miner_tpu import lsp

    data, lo, hi = sig
    t0 = _t.monotonic()
    try:
        c = await _aio.wait_for(
            lsp.AsyncClient.connect("127.0.0.1", port, params), timeout
        )
    except Exception:
        stats["failed"] += 1
        return
    try:
        got = await _request_result(c, data, lo, hi, timeout)
    finally:
        try:
            await _aio.wait_for(c.close(), 2.0)
        except Exception:
            pass
    if got is None:
        stats["failed"] += 1
    elif got != oracle[sig]:
        stats["wrong"] += 1
        errors.append(f"open-loop {sig}: got {got}, want {oracle[sig]}")
    else:
        stats["completed"] += 1
        hist.observe(_t.monotonic() - t0)


async def _ol_async(port, params, jobs, oracle, rate, duration, rng, hist,
                    stats, errors, timeout):
    """Poisson arrivals at ``rate``/s for ``duration``s, each an
    independent task on the one client loop — open-loop: the arrival
    process never waits for the server."""
    import asyncio as _aio
    import time as _t

    tasks: set = set()
    i = 0
    end = _t.monotonic() + duration
    while _t.monotonic() < end:
        sig = jobs[i % len(jobs)]
        i += 1
        stats["offered"] += 1
        t = _aio.ensure_future(
            _ol_one_async(port, params, sig, oracle, hist, stats, errors, timeout)
        )
        tasks.add(t)
        t.add_done_callback(tasks.discard)
        await _aio.sleep(rng.expovariate(rate))
    if tasks:
        # Drain: every in-flight request's own deadline (``timeout``) is
        # shorter than this wait, so stragglers here mean a wedged conn —
        # cancel them and let the cancellations finalize, then reconcile
        # ``undrained`` from the totals (a task finishing between wait()'s
        # snapshot and a naive len(pending) count would otherwise be
        # counted twice: once in completed/failed, once as undrained).
        _done, pending = await _aio.wait(set(tasks), timeout=timeout + 5)
        for t in pending:
            t.cancel()
        if pending:
            await _aio.gather(*pending, return_exceptions=True)
    stats["undrained"] = max(
        0,
        stats["offered"] - stats["completed"] - stats["failed"] - stats["wrong"],
    )


def _ol_threaded(port, params, jobs, oracle, rate, duration, rng, hist,
                 stats, stats_lock, errors, timeout, max_threads):
    """The threaded-facade open-loop generator: thread-per-arrival,
    capped at ``max_threads`` live request threads.  An arrival landing
    on a saturated pool is turned away at the CLIENT
    (``client_saturated``) — the thread-stack failure mode the async
    ingress exists to remove."""
    import time as _t

    from bitcoin_miner_tpu import lsp
    from bitcoin_miner_tpu.apps import client as client_mod

    sem = threading.Semaphore(max_threads)
    threads = []

    def one(sig):
        data, lo, hi = sig
        t0 = _t.monotonic()
        got = None
        try:
            try:
                c = lsp.Client("127.0.0.1", port, params)
            except (lsp.LspError, OSError):
                return
            try:
                got = client_mod.request_once(c, data, hi, lower=lo, timeout=timeout)
            except TimeoutError:
                got = None
            finally:
                try:
                    c.close()
                except lsp.LspError:
                    pass
        finally:
            with stats_lock:
                if got is None:
                    stats["failed"] += 1
                elif got != oracle[sig]:
                    stats["wrong"] += 1
                    errors.append(f"open-loop {sig}: got {got}, want {oracle[sig]}")
                else:
                    stats["completed"] += 1
                    hist.observe(_t.monotonic() - t0)
            sem.release()

    i = 0
    end = _t.monotonic() + duration
    while _t.monotonic() < end:
        sig = jobs[i % len(jobs)]
        i += 1
        with stats_lock:
            stats["offered"] += 1
        if not sem.acquire(blocking=False):
            with stats_lock:
                stats["client_saturated"] += 1
        else:
            th = threading.Thread(target=one, args=(sig,), daemon=True)
            th.start()
            threads.append(th)
            if len(threads) >= 2 * max_threads:
                # Prune finished threads as we go: at saturation rates the
                # list (and the leg's own RSS stamp) must not grow with
                # every arrival of the whole measurement window.
                threads = [t for t in threads if t.is_alive()]
        _t.sleep(rng.expovariate(rate))
    for th in threads:
        th.join(timeout=timeout + 5)


def _open_loop_phase(kind, port, params, jobs, oracle, args, errors, lt=None):
    """Run one open-loop measurement against an already-serving stack and
    return its stamp: offered/completed/failed counts, the authoritative
    gateway.shed delta and shed rate, and the completed-request latency
    quantiles (p99-under-saturation is the number closed-loop clients can
    never measure)."""
    import asyncio as _aio

    from bitcoin_miner_tpu.utils.metrics import METRICS, Histogram

    rng = random.Random(args.seed + 1)
    hist = Histogram()
    stats = {
        "offered": 0, "completed": 0, "failed": 0, "wrong": 0,
        "client_saturated": 0, "undrained": 0,
    }
    stats_lock = threading.Lock()
    shed_before = METRICS.get("gateway.shed")
    if kind == "async":
        fut = _aio.run_coroutine_threadsafe(
            _ol_async(port, params, jobs, oracle, args.open_loop,
                      args.duration, rng, hist, stats, errors,
                      args.ol_timeout),
            lt.loop,
        )
        fut.result(timeout=args.duration + args.ol_timeout + 30)
    else:
        _ol_threaded(port, params, jobs, oracle, args.open_loop,
                     args.duration, rng, hist, stats, stats_lock, errors,
                     args.ol_timeout, args.ol_max_threads)
    if stats["wrong"]:
        errors.append(f"{stats['wrong']} open-loop result(s) failed the oracle")
    shed = METRICS.get("gateway.shed") - shed_before
    lat = hist.snapshot()
    return {
        "rate": args.open_loop,
        "duration_s": args.duration,
        **stats,
        "shed": shed,
        "shed_rate": round(shed / stats["offered"], 4) if stats["offered"] else 0.0,
        "latency_s": {
            "p50": round(lat["p50"], 6),
            "p95": round(lat["p95"], 6),
            "p99": round(lat["p99"], 6),
            "count": int(lat["count"]),
        },
    }


def _async_probes(engine, port, params, lt, jobs, oracle, args, errors):
    """The zero-chunk acceptance probes THROUGH the async path (ISSUE 15
    acceptance: the bridge must not have broken the serving layer's reuse
    machinery): an exact repeat of a solved signature and a never-issued
    fully-covered sub-range, both answering bit-exact with zero chunks
    assigned, via AsyncClient conns on the shared loop."""
    import asyncio as _aio

    from bitcoin_miner_tpu.utils.metrics import METRICS, Histogram

    h = Histogram()  # throwaway latency sink for the probe helper

    def ask(sig):
        stats = {"offered": 0, "completed": 0, "failed": 0, "wrong": 0,
                 "client_saturated": 0}
        probe_oracle = {sig: oracle.get(sig, args.oracle_fn(*sig))}
        _aio.run_coroutine_threadsafe(
            _ol_one_async(
                port, params, sig, probe_oracle, h, stats, errors,
                args.ol_timeout,
            ),
            lt.loop,
        ).result(timeout=args.ol_timeout + 10)
        return stats["completed"] == 1

    solved = [s for s in jobs if engine.cache.get(s) is not None]
    if not solved:
        errors.append("no solved signature to repeat-probe")
        return None, None
    repeat_sig = solved[0]
    assigned = METRICS.get("sched.chunks_assigned")
    ok = ask(repeat_sig)
    repeat_zero = ok and METRICS.get("sched.chunks_assigned") == assigned
    if not repeat_zero:
        errors.append("async repeat probe missed the cache or failed")
    cand = _covered_subrange(engine.spans, jobs, errors)
    if cand is None:
        return repeat_zero, None
    data, qlo, qhi = cand
    assigned = METRICS.get("sched.chunks_assigned")
    ok = ask((data, qlo, qhi))
    sub_zero = ok and METRICS.get("sched.chunks_assigned") == assigned
    if not sub_zero:
        errors.append("async subrange probe assigned chunks (spans missed)")
    return repeat_zero, sub_zero


def _open_loop_main(jobs, distinct, args, oracle) -> int:
    """The standalone --open-loop bench: Poisson arrivals against the
    async ingress, one JSON line (`--fast` gates tier-1)."""
    from bitcoin_miner_tpu import lsp

    errors: list = []
    params, port, engine, close_fn = _serving_stack("async", args)
    lt = lsp.shared_loop("loadgen-aclients")
    try:
        stamp = _open_loop_phase(
            "async", port, params, jobs, oracle, args, errors, lt=lt
        )
        repeat_zero, sub_zero = _async_probes(
            engine, port, params, lt, jobs, oracle, args, errors
        )
    finally:
        close_fn()
        lt.stop()
    if errors:
        raise RuntimeError("open-loop leg failed: " + "; ".join(errors[:5]))
    out = {
        "metric": "loadgen_open_loop_completed_per_sec",
        "value": round(stamp["completed"] / args.duration, 3),
        "unit": "jobs/s",
        "workload": args.wl_name,
        "mode": "open-loop",
        "ingress": "async",
        "jobs": len(jobs),
        "distinct_signatures": len(distinct),
        "max_nonce": args.max_nonce,
        "miners": args.miners,
        "seed": args.seed,
        "fast": bool(args.fast),
        "open_loop": stamp,
        "repeat_zero_chunks": repeat_zero,
        "subrange_zero_chunks": sub_zero,
    }
    print(json.dumps(out), flush=True)
    return 0


def _conn_scale_leg(kind: str, jobs, oracle, args) -> dict:
    """One conn-scale leg: stand the stack up, ramp live conns (sampling
    the process thread count mid-ramp and at full ramp), prove every conn
    live with a bit-exact round trip, take open-loop load, then (async
    leg) run the zero-chunk probes.  Returns the leg's stamp."""
    import asyncio as _aio

    from bitcoin_miner_tpu import lsp
    from bitcoin_miner_tpu.apps import client as client_mod
    from bitcoin_miner_tpu.utils.metrics import METRICS

    errors: list = []
    params, port, engine, close_fn = _serving_stack(kind, args)
    lt = lsp.shared_loop("loadgen-aclients") if kind == "async" else None
    target = (
        args.conns if kind == "threaded" else args.conns * args.conn_multiple
    )
    conns: list = []

    async def _connect_batch(n):
        outs = await _aio.gather(
            *(
                lsp.AsyncClient.connect("127.0.0.1", port, params)
                for _ in range(n)
            ),
            return_exceptions=True,
        )
        return [c for c in outs if not isinstance(c, BaseException)]

    async def _verify_all(sig):
        data, lo, hi = sig
        out = []
        for s in range(0, len(conns), 100):
            out.extend(
                await _aio.gather(
                    *(
                        _request_result(c, data, lo, hi, args.ol_timeout)
                        for c in conns[s:s + 100]
                    )
                )
            )
        return out

    async def _close_batch(batch):
        await _aio.gather(
            *( _aio.wait_for(c.close(), 2.0) for c in batch),
            return_exceptions=True,
        )

    def ramp_to(n):
        while len(conns) < n:
            if kind == "threaded":
                try:
                    conns.append(lsp.Client("127.0.0.1", port, params))
                except (lsp.LspError, OSError) as e:
                    errors.append(f"conn ramp stalled at {len(conns)}: {e!r}")
                    return
            else:
                batch = min(50, n - len(conns))
                got = _aio.run_coroutine_threadsafe(
                    _connect_batch(batch), lt.loop
                ).result(timeout=60)
                if not got:
                    errors.append(f"conn ramp stalled at {len(conns)}")
                    return
                conns.extend(got)

    try:
        # Warm one signature so the liveness wave is pure cache hits.
        warm = jobs[0]
        wc = lsp.Client("127.0.0.1", port, params)
        try:
            got = client_mod.request_once(
                wc, warm[0], warm[2], lower=warm[1], timeout=args.ol_timeout
            )
        finally:
            wc.close()
        if got != oracle[warm]:
            errors.append(f"warm job wrong: got {got}, want {oracle[warm]}")
        ramp_to(target // 2)
        threads_half = threading.active_count()
        ramp_to(target)
        threads_full = threading.active_count()
        rss_kb = _rss_kb()
        # Liveness proof: every ramped conn completes a bit-exact round
        # trip of the warmed (cached) signature — full duplex, zero
        # device work, O(conns) only on the wire.
        if kind == "threaded":
            results = []
            for c in conns:
                try:
                    results.append(
                        client_mod.request_once(
                            c, warm[0], warm[2], lower=warm[1],
                            timeout=args.ol_timeout,
                        )
                    )
                except (lsp.LspError, TimeoutError):
                    results.append(None)
        else:
            results = _aio.run_coroutine_threadsafe(
                _verify_all(warm), lt.loop
            ).result(timeout=args.ol_timeout + 60)
        live = sum(1 for g in results if g == oracle[warm])
        # The gauge is published by the serve ticker (0.05 s cadence) —
        # a loopback ramp can finish inside one tick, so give it a few
        # beats before stamping the server-side corroboration.
        time.sleep(0.25)
        gauge_conns = METRICS.gauge("gw.conns_live")
        open_loop = _open_loop_phase(
            kind, port, params, jobs, oracle, args, errors, lt=lt
        )
        repeat_zero = sub_zero = None
        if kind == "async":
            repeat_zero, sub_zero = _async_probes(
                engine, port, params, lt, jobs, oracle, args, errors
            )
    finally:
        try:
            if kind == "threaded":
                for c in conns:
                    try:
                        c.close()
                    except lsp.LspError:
                        pass
            elif conns:
                for s in range(0, len(conns), 100):
                    _aio.run_coroutine_threadsafe(
                        _close_batch(conns[s:s + 100]), lt.loop
                    ).result(timeout=30)
        except Exception:
            pass  # teardown best-effort: the server close below reaps conns
        close_fn()
        if lt is not None:
            lt.stop()
    if errors:
        raise RuntimeError(f"conn-scale {kind} leg failed: " + "; ".join(errors[:5]))
    stamp = {
        "ingress": kind,
        "conns_target": target,
        "conns_live": live,
        "gw_conns_live_gauge": gauge_conns,
        "threads_at_half_ramp": threads_half,
        "threads_at_full_ramp": threads_full,
        "threads_flat": threads_full <= threads_half,
        "rss_kb": rss_kb,
        "open_loop": open_loop,
    }
    if kind == "async":
        stamp["repeat_zero_chunks"] = repeat_zero
        stamp["subrange_zero_chunks"] = sub_zero
    return stamp


def _conn_scale_main(jobs, distinct, args, oracle) -> int:
    """The --conn-scale bench pair (BENCH_pr15.json): threaded facade vs
    async ingress on the same seeded workload."""
    thr = _conn_scale_leg("threaded", jobs, oracle, args)
    log(f"threaded leg: {thr}")
    asy = _conn_scale_leg("async", jobs, oracle, args)
    log(f"async leg: {asy}")
    multiple = (
        round(asy["conns_live"] / thr["conns_live"], 2)
        if thr["conns_live"] else None
    )
    out = {
        "metric": "conn_scale_async_conn_multiple",
        "value": multiple,
        "unit": "x live conns vs threaded facade",
        "workload": args.wl_name,
        "mode": "conn-scale",
        "jobs": len(jobs),
        "distinct_signatures": len(distinct),
        "max_nonce": args.max_nonce,
        "miners": args.miners,
        "seed": args.seed,
        "fast": bool(args.fast),
        "open_loop_rate": args.open_loop,
        "threaded": thr,
        "async": asy,
        "repeat_zero_chunks": asy.get("repeat_zero_chunks"),
        "subrange_zero_chunks": asy.get("subrange_zero_chunks"),
    }
    print(json.dumps(out), flush=True)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--jobs", type=int, default=64)
    ap.add_argument("--dup", type=float, default=0.5,
                    help="fraction of jobs repeating an earlier signature")
    ap.add_argument("--max-nonce", type=int, default=60_000)
    ap.add_argument("--miners", type=int, default=2)
    ap.add_argument("--min-chunk", type=int, default=2000)
    ap.add_argument("--cache-size", type=int, default=1024)
    ap.add_argument("--max-active", type=int, default=64)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--timeout", type=float, default=300.0)
    ap.add_argument("--no-baseline", action="store_true",
                    help="skip the bare-scheduler comparison leg")
    ap.add_argument("--overlap", action="store_true",
                    help="interval-store bench: nested/overlapping ranges, "
                         "SpanStore leg vs exact-match-cache leg")
    ap.add_argument("--federation", type=int, default=0, metavar="N",
                    help="federation bench (ISSUE 8): overlap-heavy load "
                         "sprayed across N in-process gateway replicas "
                         "(consistent-hash routing + span gossip) vs the "
                         "same load on 1 replica; stamps the repeat and "
                         "cross-replica zero-chunk probes (BENCH_pr8.json)")
    ap.add_argument("--trace", metavar="FILE", default=None,
                    help="arm the structured event log during the gateway "
                         "leg and write it here (python -m tools.trace)")
    ap.add_argument("--trace-overhead", action="store_true",
                    help="run the gateway leg a second time with tracing "
                         "armed and report the jobs/s overhead (the ISSUE 6 "
                         "<5%% acceptance number)")
    ap.add_argument("--telemetry", action="store_true",
                    help="arm the fleet metrics plane during the gateway "
                         "leg (in-process TelemetryHub + exporter) and "
                         "stamp the fleet-merged histograms and SLO "
                         "verdicts into the JSON line (ISSUE 7)")
    ap.add_argument("--telemetry-overhead", action="store_true",
                    help="also run an un-telemetered gateway leg and report "
                         "the jobs/s overhead — the telemetered leg runs "
                         "FIRST (same leg-order discipline as "
                         "--trace-overhead: warmup bias inflates, never "
                         "masks, the ISSUE 7 <5%% acceptance number)")
    ap.add_argument("--workload", default=None, metavar="NAME",
                    help="registered range-fold workload to serve/bench "
                         "(ISSUE 9; default: the frozen sha256d contract; "
                         "env BMT_WORKLOAD)")
    ap.add_argument("--open-loop", type=float, default=None, metavar="RATE",
                    help="open-loop bench (ISSUE 15): Poisson arrivals at "
                         "RATE req/s for --duration seconds against the "
                         "async event-loop ingress — shed rate and p99 "
                         "under saturation, the way production traffic "
                         "actually arrives")
    ap.add_argument("--duration", type=float, default=8.0,
                    help="open-loop measurement window (seconds)")
    ap.add_argument("--conn-scale", action="store_true",
                    help="ingress bench pair (ISSUE 15): threaded-facade "
                         "leg vs async-ingress leg — live conns, thread "
                         "counts, RSS, open-loop shed/p99 per leg "
                         "(BENCH_pr15.json)")
    ap.add_argument("--conns", type=int, default=50,
                    help="threaded-facade leg live-conn ramp target "
                         "(the async leg ramps --conn-multiple x this)")
    ap.add_argument("--conn-multiple", type=int, default=10,
                    help="async leg conn multiple over the threaded leg")
    ap.add_argument("--ol-queue", type=int, default=32,
                    help="gateway max_queued for the ingress benches (the "
                         "overload lever: beyond it requests shed)")
    ap.add_argument("--ol-timeout", type=float, default=20.0,
                    help="per-request deadline in the ingress benches")
    ap.add_argument("--ol-max-threads", type=int, default=64,
                    help="threaded-leg open-loop request-thread cap "
                         "(arrivals past it are turned away client-side)")
    ap.add_argument("--fast", action="store_true",
                    help="tier-1 preset: small jobs, done in well under 30 s")
    args = ap.parse_args(argv)
    if (args.telemetry_overhead and (args.trace or args.trace_overhead)) or (
        args.trace_overhead and args.telemetry
    ):
        # An overhead number divides the armed leg by ONE bare leg; if the
        # armed leg carries the OTHER plane too, the stamped number
        # reports their combined cost.  Measure one plane at a time
        # (plain --telemetry with --trace is fine: both armed, no
        # overhead attribution happens).
        ap.error("an overhead measurement cannot run with the other "
                 "plane armed (--trace/--trace-overhead vs --telemetry/"
                 "--telemetry-overhead): measure one plane at a time")
    if args.conn_scale and args.open_loop is None:
        args.open_loop = 40.0  # the pair always takes open-loop load
    if args.open_loop is not None and args.open_loop <= 0:
        ap.error("--open-loop RATE must be > 0 (Poisson arrivals/sec)")
    if args.open_loop is not None and (args.federation or args.overlap):
        ap.error("--open-loop/--conn-scale are their own modes — run them "
                 "without --federation/--overlap")
    if args.fast:
        args.jobs = min(args.jobs, 24)
        args.max_nonce = min(args.max_nonce, 4000)
        args.timeout = min(args.timeout, 60.0)
        args.duration = min(args.duration, 3.0)
        if args.open_loop is not None:
            args.open_loop = min(args.open_loop, 40.0)
        args.conns = min(args.conns, 20)
        args.ol_timeout = min(args.ol_timeout, 15.0)

    import os

    from bitcoin_miner_tpu import workloads as workloads_mod

    try:
        wl = workloads_mod.resolve(
            args.workload or os.environ.get("BMT_WORKLOAD") or None
        )
    except ValueError as e:
        ap.error(str(e))
    # None = the frozen default's byte-identical scheduler/miner paths;
    # the JSON line always stamps the resolved name so trajectories with
    # different workloads never get compared as one series.
    args.wl = workloads_mod.resolve_nondefault(wl)
    args.wl_name = wl.name
    args.oracle_fn = min_hash_range = wl.min_range

    if args.federation:
        # Overlap-heavy workload over a wider key family, so the ring has
        # keys to spread and the duplicates still collapse per home cell.
        jobs = build_overlap_workload(
            args, n_datas=max(3, 2 * args.federation)
        )
    elif args.overlap:
        jobs = build_overlap_workload(args)
    else:
        jobs = build_workload(args)
    distinct = sorted(set(jobs))
    log(f"workload: {len(jobs)} jobs, {len(distinct)} distinct signatures, "
        f"{args.clients} clients, {args.miners} miners")
    oracle = {sig: min_hash_range(sig[0], sig[1], sig[2]) for sig in distinct}

    # Throwaway warm-up leg: pay the one-time costs (native backend build,
    # transport/module init) so neither timed leg absorbs them.
    run_leg(False, jobs[: min(4, len(jobs))], args, oracle)

    if args.federation:
        return _federation_main(jobs, distinct, args, oracle)
    if args.overlap:
        return _overlap_main(jobs, distinct, args, oracle)
    if args.conn_scale:
        return _conn_scale_main(jobs, distinct, args, oracle)
    if args.open_loop is not None:
        return _open_loop_main(jobs, distinct, args, oracle)

    import tempfile
    from contextlib import ExitStack

    from bitcoin_miner_tpu.utils.trace import tracing

    # Fleet metrics plane (ISSUE 7): a hub + exporter alongside the
    # gateway leg — the exporter ships the process registry at a bench-
    # aggressive cadence so the measured leg carries the real export
    # cost, and the hub's self-tick runs the merge + SLO burn evaluation
    # concurrently with serving (the overhead being measured).
    telem_on = args.telemetry or args.telemetry_overhead
    hub = exporter = None
    if telem_on:
        from bitcoin_miner_tpu.utils.slo import SloEngine, default_slos
        from bitcoin_miner_tpu.utils.telemetry import (
            TelemetryExporter,
            TelemetryHub,
        )

        hub = TelemetryHub(
            0, source=None, slo=SloEngine(default_slos()),
            publish_interval=0.25,
        ).start(self_tick=0.2)
        exporter = TelemetryExporter(
            "127.0.0.1", hub.port, "miner-pool", interval=0.2
        ).start()
        log(f"telemetry: hub on :{hub.port}, exporting every 0.2s")

    traced = plain = None
    with ExitStack() as stack:
        if args.trace:
            stack.enter_context(tracing(args.trace))
        elif args.trace_overhead:
            # No sink requested: trace into a throwaway temp file so the
            # flush path is part of the measured cost too.
            tf = stack.enter_context(
                tempfile.NamedTemporaryFile(suffix=".trace.jsonl")
            )
            stack.enter_context(tracing(tf.name))
        gw = run_leg(True, jobs, args, oracle)
    log(f"gateway leg: {gw['jobs_per_sec']:.2f} jobs/s over "
        f"{gw['wall_s']:.2f}s; counters {gw['counters']}")
    fleet_stamp = slo_stamp = None
    if telem_on:
        # One final tick AFTER the leg so the stamped state includes the
        # exporter's last beats, then tear the plane down — the plain
        # comparison leg below must run un-telemetered.
        state = hub.tick()
        exporter.stop()
        hub.close()
        fleet_stamp = {
            "sources": state["sources"],
            "stale_sources": state["stale_sources"],
            "hists": state["hists"],
        }
        slo_stamp = {
            s["name"]: {
                "ok": s["ok"],
                "burn_fast": s["burn_fast"],
                "burn_slow": s["burn_slow"],
            }
            for s in state.get("slo", {}).get("slos", [])
        }
        log(f"telemetry: {state['sources']} source(s), "
            f"alerts={state.get('slo', {}).get('alerts', [])}")
    if args.trace_overhead or args.telemetry_overhead:
        # The acceptance numbers (ISSUE 6 tracing, ISSUE 7 telemetry):
        # the SAME workload with the plane armed vs bare, the ARMED leg
        # always first whatever flag spelling armed it — any residual
        # leg-order warmup bias then inflates the reported overhead,
        # never masks it (conservative for a "<5%" acceptance claim).
        # One bare leg serves both comparisons.
        if args.trace_overhead:
            traced = gw
        plain = run_leg(True, jobs, args, oracle)
        log(f"bare gateway leg: {plain['jobs_per_sec']:.2f} jobs/s "
            f"over {plain['wall_s']:.2f}s")
    base = None
    if not args.no_baseline:
        base = run_leg(False, jobs, args, oracle)
        log(f"baseline leg: {base['jobs_per_sec']:.2f} jobs/s over "
            f"{base['wall_s']:.2f}s")

    out = {
        "metric": "loadgen_jobs_per_sec",
        "value": round(gw["jobs_per_sec"], 3),
        "unit": "jobs/s",
        "workload": args.wl_name,
        "clients": args.clients,
        "jobs": len(jobs),
        "distinct_signatures": len(distinct),
        "dup_fraction": args.dup,
        "max_nonce": args.max_nonce,
        "miners": args.miners,
        "seed": args.seed,
        "fast": bool(args.fast),
        "wall_s": round(gw["wall_s"], 3),
        "repeat_zero_chunks": gw["repeat_zero_chunks"],
        "latency_s": gw["latency_s"],
        "gateway_counters": {
            k: v for k, v in gw["counters"].items() if k.startswith("gateway.")
        },
        "swept_nonces": gw["counters"].get("sched.nonces_swept", 0),
        **(
            {
                "traced_jobs_per_sec": round(traced["jobs_per_sec"], 3),
                "trace_overhead": round(
                    1.0 - traced["jobs_per_sec"] / plain["jobs_per_sec"], 4
                )
                if plain["jobs_per_sec"] > 0
                else None,
            }
            if traced is not None and plain is not None
            else {}
        ),
        **(
            {"fleet": fleet_stamp, "slo": slo_stamp}
            if fleet_stamp is not None
            else {}
        ),
        **(
            {
                "telemetry_overhead": round(
                    1.0 - gw["jobs_per_sec"] / plain["jobs_per_sec"], 4
                )
                if plain["jobs_per_sec"] > 0
                else None
            }
            if args.telemetry_overhead and plain is not None
            else {}
        ),
        **(
            {
                "baseline_jobs_per_sec": round(base["jobs_per_sec"], 3),
                "baseline_wall_s": round(base["wall_s"], 3),
                "baseline_swept_nonces": base["counters"].get(
                    "sched.nonces_swept", 0
                ),
                "speedup_vs_baseline": round(
                    gw["jobs_per_sec"] / base["jobs_per_sec"], 3
                )
                if base["jobs_per_sec"] > 0
                else None,
            }
            if base is not None
            else {}
        ),
    }
    print(json.dumps(out), flush=True)
    return 0


def _federation_main(jobs, distinct, args, oracle) -> int:
    """The --federation bench: the same duplicate/overlap-heavy workload
    through N replicas vs 1 replica (both federation shells, so the delta
    isolates the replication), plus the ISSUE 8 probes.  One JSON line —
    the BENCH_pr8.json artifact."""
    n = max(2, args.federation)
    fed = run_federation_leg(n, jobs, args, oracle)
    log(f"federation leg ({n} replicas): {fed['jobs_per_sec']:.2f} jobs/s "
        f"over {fed['wall_s']:.2f}s; counters {fed['counters']}")
    single = run_federation_leg(1, jobs, args, oracle)
    log(f"single-replica leg: {single['jobs_per_sec']:.2f} jobs/s over "
        f"{single['wall_s']:.2f}s")

    out = {
        "metric": "loadgen_federation_jobs_per_sec",
        "value": round(fed["jobs_per_sec"], 3),
        "unit": "jobs/s",
        "workload": args.wl_name,
        "mode": "federation",
        "replicas": n,
        "clients": args.clients,
        "jobs": len(jobs),
        "distinct_signatures": len(distinct),
        "max_nonce": args.max_nonce,
        "miners_per_replica": args.miners,
        "seed": args.seed,
        "fast": bool(args.fast),
        "wall_s": round(fed["wall_s"], 3),
        "latency_s": fed["latency_s"],
        "repeat_zero_chunks": fed["repeat_zero_chunks"],
        "cross_replica_zero_chunks": fed["cross_replica_zero_chunks"],
        "gossip_max_frame_bytes": fed["gossip_max_frame_bytes"],
        "wire_ceiling_bytes": 1000,
        "federation_counters": {
            k: v for k, v in fed["counters"].items()
            if k.startswith(("federation.", "gateway."))
        },
        "swept_nonces": fed["counters"].get("sched.nonces_swept", 0),
        "single_jobs_per_sec": round(single["jobs_per_sec"], 3),
        "single_wall_s": round(single["wall_s"], 3),
        "single_swept_nonces": single["counters"].get("sched.nonces_swept", 0),
        "single_repeat_zero_chunks": single["repeat_zero_chunks"],
        "scaling_vs_single": round(
            fed["jobs_per_sec"] / single["jobs_per_sec"], 3
        )
        if single["jobs_per_sec"] > 0
        else None,
    }
    print(json.dumps(out), flush=True)
    return 0


def _overlap_main(jobs, distinct, args, oracle) -> int:
    """The --overlap bench: interval-store leg vs exact-match-cache leg
    (both gateways — the delta isolates the span store), one JSON line
    with both legs' swept nonces and their reduction (BENCH_pr5.json)."""
    spans = run_leg(True, jobs, args, oracle, spans_on=True)
    log(f"interval-store leg: {spans['jobs_per_sec']:.2f} jobs/s over "
        f"{spans['wall_s']:.2f}s; counters {spans['counters']}")
    exact = run_leg(True, jobs, args, oracle, spans_on=False)
    log(f"exact-cache leg: {exact['jobs_per_sec']:.2f} jobs/s over "
        f"{exact['wall_s']:.2f}s; counters {exact['counters']}")

    spans_swept = spans["counters"].get("sched.nonces_swept", 0)
    exact_swept = exact["counters"].get("sched.nonces_swept", 0)
    out = {
        "metric": "loadgen_overlap_jobs_per_sec",
        "value": round(spans["jobs_per_sec"], 3),
        "unit": "jobs/s",
        "workload": args.wl_name,
        "mode": "overlap",
        "clients": args.clients,
        "jobs": len(jobs),
        "distinct_signatures": len(distinct),
        "max_nonce": args.max_nonce,
        "miners": args.miners,
        "seed": args.seed,
        "fast": bool(args.fast),
        "wall_s": round(spans["wall_s"], 3),
        "repeat_zero_chunks": spans["repeat_zero_chunks"],
        "subrange_zero_chunks": spans["subrange_zero_chunks"],
        "latency_s": spans["latency_s"],
        "exact_latency_s": exact["latency_s"],
        "span_counters": {
            k: v for k, v in spans["counters"].items()
            if k.startswith("gateway.")
        },
        "swept_nonces": spans_swept,
        "exact_jobs_per_sec": round(exact["jobs_per_sec"], 3),
        "exact_wall_s": round(exact["wall_s"], 3),
        "exact_swept_nonces": exact_swept,
        "swept_reduction": round(1.0 - spans_swept / exact_swept, 3)
        if exact_swept > 0
        else None,
        "speedup_vs_exact": round(
            spans["jobs_per_sec"] / exact["jobs_per_sec"], 3
        )
        if exact["jobs_per_sec"] > 0
        else None,
    }
    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
