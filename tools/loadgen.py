"""Serving-layer load generator: duplicate-heavy traffic against the gateway.

`fleet_bench.py` measures one big job's delivered nonces/s; this tool
measures the SERVING layer — many small jobs from many concurrent clients,
half of them duplicates, which is the regime the gateway exists for
(ISSUE 3 / ROADMAP "millions of users"): coalescing folds concurrent
twin sweeps into one, the content-addressed cache answers solved
signatures with zero device work, and admission keeps the inflow bounded.

The fleet is fully in-process (real loopback LSP: `apps.server.serve`
thread + miner threads on the cpu tier + N client threads), so one run
gives apples-to-apples legs:

- **gateway leg** — `serve` runs a :class:`Gateway`-wrapped scheduler;
- **baseline leg** (unless ``--no-baseline``) — the bare scheduler, where
  every duplicate burns the fleet again.

Every job's Result is validated bit-exact against the hashlib oracle
(cached answers included — a wrong cache hit fails the run), and the
gateway leg ends with a repeat-submission probe asserting a solved job
answers with ZERO new chunks assigned.  Prints one JSON line; `--fast`
keeps the whole thing under ~30 s on CPU so it gates tier-1
(tests/test_loadgen.py).

Usage: python tools/loadgen.py [--fast] [--clients N] [--jobs N]
       [--dup F] [--max-nonce N] [--miners N] [--no-baseline] [--seed N]
"""

from __future__ import annotations

import argparse
import json
import random
import sys
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))  # repo root


def log(*a) -> None:
    print(*a, file=sys.stderr, flush=True)


def build_workload(args) -> list:
    """A duplicate-heavy job list: each entry is a ``(data, lower, upper)``
    signature; with probability ``--dup`` a job repeats an earlier
    signature — biased toward RECENT ones, so some duplicates land while
    their twin is still sweeping (coalesce) and some after it solved
    (cache hit)."""
    rng = random.Random(args.seed)
    issued: list = []
    jobs: list = []
    for i in range(args.jobs):
        if issued and rng.random() < args.dup:
            if rng.random() < 0.5:
                sig = rng.choice(issued[-4:])  # recent: likely in flight
            else:
                sig = rng.choice(issued)  # any: likely already solved
        else:
            lo = 0
            hi = rng.randint(args.max_nonce // 2, args.max_nonce)
            sig = (f"load{len(issued)}", lo, hi)
            issued.append(sig)
        jobs.append(sig)
    return jobs


def run_leg(gateway_on: bool, jobs: list, args, oracle: dict) -> dict:
    """Stand up one in-process fleet, push the whole workload through it
    with ``--clients`` concurrent client threads, tear it down.  Returns
    the leg's timing + METRICS deltas."""
    from bitcoin_miner_tpu import lsp
    from bitcoin_miner_tpu.apps import client as client_mod
    from bitcoin_miner_tpu.apps import miner as miner_mod
    from bitcoin_miner_tpu.apps import server as server_mod
    from bitcoin_miner_tpu.apps.scheduler import Scheduler
    from bitcoin_miner_tpu.gateway import Gateway, ResultCache
    from bitcoin_miner_tpu.utils.metrics import METRICS

    params = lsp.Params(epoch_limit=5, epoch_millis=200, window_size=5)
    server = lsp.Server(0, params)
    sched = Scheduler(min_chunk=args.min_chunk)
    engine = (
        Gateway(
            sched,
            cache=ResultCache(capacity=args.cache_size),
            rate=None,  # per-conn buckets never bind over LSP; see README
            max_active=args.max_active,
        )
        if gateway_on
        else sched
    )
    threading.Thread(
        target=server_mod.serve,
        args=(server, engine),
        kwargs={"tick_interval": 0.05},
        daemon=True,
    ).start()
    search = miner_mod.make_search("cpu")
    for _ in range(args.miners):
        mc = lsp.Client("127.0.0.1", server.port, params)
        threading.Thread(
            target=miner_mod.run_miner, args=(mc, search), daemon=True
        ).start()

    before = METRICS.snapshot()
    errors: list = []
    cursor = [0]
    cursor_lock = threading.Lock()

    def worker(idx: int) -> None:
        while True:
            with cursor_lock:
                if cursor[0] >= len(jobs):
                    return
                job_i = cursor[0]
                cursor[0] += 1
            data, lo, hi = jobs[job_i]
            c = lsp.Client("127.0.0.1", server.port, params)
            try:
                got = client_mod.request_once(c, data, hi)
            finally:
                c.close()
            want = oracle[(data, lo, hi)]
            if got != want:
                errors.append(
                    f"job {job_i} ({data},{lo},{hi}): got {got}, want {want}"
                )
                return

    t0 = time.monotonic()
    threads = [
        threading.Thread(target=worker, args=(i,), daemon=True)
        for i in range(args.clients)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=args.timeout)
        if t.is_alive():
            errors.append(f"worker timed out after {args.timeout:.0f}s")
    wall = time.monotonic() - t0

    repeat_zero_chunks = None
    if gateway_on and not errors:
        # Acceptance probe: a repeat of a SOLVED signature must answer
        # from the cache with zero new chunks assigned.
        assigned_before = METRICS.get("sched.chunks_assigned")
        data, lo, hi = jobs[0]
        c = lsp.Client("127.0.0.1", server.port, params)
        try:
            got = client_mod.request_once(c, data, hi)
        finally:
            c.close()
        if got != oracle[(data, lo, hi)]:
            errors.append(f"repeat probe wrong result: {got}")
        repeat_zero_chunks = (
            METRICS.get("sched.chunks_assigned") == assigned_before
        )
        if not repeat_zero_chunks:
            errors.append("repeat probe assigned chunks (cache missed)")

    server.close()
    after = METRICS.snapshot()
    deltas = {
        k: after[k] - before.get(k, 0)
        for k in sorted(after)
        if k.startswith(("gateway.", "sched."))
        and after[k] != before.get(k, 0)
    }
    if errors:
        raise RuntimeError(
            f"{'gateway' if gateway_on else 'baseline'} leg failed: "
            + "; ".join(errors[:5])
        )
    return {
        "wall_s": wall,
        "jobs_per_sec": len(jobs) / wall if wall > 0 else 0.0,
        "counters": deltas,
        "repeat_zero_chunks": repeat_zero_chunks,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--jobs", type=int, default=64)
    ap.add_argument("--dup", type=float, default=0.5,
                    help="fraction of jobs repeating an earlier signature")
    ap.add_argument("--max-nonce", type=int, default=60_000)
    ap.add_argument("--miners", type=int, default=2)
    ap.add_argument("--min-chunk", type=int, default=2000)
    ap.add_argument("--cache-size", type=int, default=1024)
    ap.add_argument("--max-active", type=int, default=64)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--timeout", type=float, default=300.0)
    ap.add_argument("--no-baseline", action="store_true",
                    help="skip the bare-scheduler comparison leg")
    ap.add_argument("--fast", action="store_true",
                    help="tier-1 preset: small jobs, done in well under 30 s")
    args = ap.parse_args(argv)
    if args.fast:
        args.jobs = min(args.jobs, 24)
        args.max_nonce = min(args.max_nonce, 4000)
        args.timeout = min(args.timeout, 60.0)

    from bitcoin_miner_tpu.bitcoin.hash import min_hash_range

    jobs = build_workload(args)
    distinct = sorted(set(jobs))
    log(f"workload: {len(jobs)} jobs, {len(distinct)} distinct signatures, "
        f"{args.clients} clients, {args.miners} miners")
    oracle = {sig: min_hash_range(sig[0], sig[1], sig[2]) for sig in distinct}

    # Throwaway warm-up leg: pay the one-time costs (native backend build,
    # transport/module init) so neither timed leg absorbs them.
    run_leg(False, jobs[: min(4, len(jobs))], args, oracle)

    gw = run_leg(True, jobs, args, oracle)
    log(f"gateway leg: {gw['jobs_per_sec']:.2f} jobs/s over "
        f"{gw['wall_s']:.2f}s; counters {gw['counters']}")
    base = None
    if not args.no_baseline:
        base = run_leg(False, jobs, args, oracle)
        log(f"baseline leg: {base['jobs_per_sec']:.2f} jobs/s over "
            f"{base['wall_s']:.2f}s")

    out = {
        "metric": "loadgen_jobs_per_sec",
        "value": round(gw["jobs_per_sec"], 3),
        "unit": "jobs/s",
        "clients": args.clients,
        "jobs": len(jobs),
        "distinct_signatures": len(distinct),
        "dup_fraction": args.dup,
        "max_nonce": args.max_nonce,
        "miners": args.miners,
        "seed": args.seed,
        "fast": bool(args.fast),
        "wall_s": round(gw["wall_s"], 3),
        "repeat_zero_chunks": gw["repeat_zero_chunks"],
        "gateway_counters": {
            k: v for k, v in gw["counters"].items() if k.startswith("gateway.")
        },
        "swept_nonces": gw["counters"].get("sched.nonces_swept", 0),
        **(
            {
                "baseline_jobs_per_sec": round(base["jobs_per_sec"], 3),
                "baseline_wall_s": round(base["wall_s"], 3),
                "baseline_swept_nonces": base["counters"].get(
                    "sched.nonces_swept", 0
                ),
                "speedup_vs_baseline": round(
                    gw["jobs_per_sec"] / base["jobs_per_sec"], 3
                )
                if base["jobs_per_sec"] > 0
                else None,
            }
            if base is not None
            else {}
        ),
    }
    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
