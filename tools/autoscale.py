"""Out-of-process autoscale supervisor (ISSUE 18).

The in-process spelling is ``apps.server --autoscale[=SPEC]`` (and the
federation cell's flag); this CLI is the same controller driven from
OUTSIDE the serving process, consuming the burn evidence the server
already publishes — the fleet-log JSONL (``--fleet-log=FILE`` on the
server) carries the merged SLO verdicts and the ``fleet.utilization``
gauge every publish beat:

    python -m tools.autoscale HOST:PORT --fleet-log fleet.jsonl
    python -m tools.autoscale HOST:PORT --fleet-log fleet.jsonl \
        --spec min=1,max=3,hold=2,weights=gold:4;free:1 \
        --telemetry 127.0.0.1:7001

Each beat (``interval`` in the spec, default 1s) the supervisor tails
the fleet log, feeds the last row's ``slo.alerts`` + ``fleet.utilization``
to the policy state machine (autoscale/controller.py — the same
hold/cooldown/retry semantics as in-process), and actuates miner worker
subprocesses against HOST:PORT.  A fleet log that stops growing for
``--stale-after`` seconds means the evidence is UNKNOWN — both providers
return None, which parks the controller in-band (no scale-up on stale
alerts, no scale-down on stale idleness).

One JSONL decision line lands on stdout whenever the controller acts or
changes state — the operator's timeline, same vocabulary as the dash
panel.  SIGINT drains every spawned worker cleanly before exit.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path
from typing import List, Optional

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))  # repo root

from bitcoin_miner_tpu.autoscale import (  # noqa: E402
    AutoscaleController,
    ProcessActuator,
    parse_autoscale_config,
)


class _FleetLogEvidence:
    """Burn/utilization providers tailing a fleet-log JSONL file.

    ``poll()`` (the supervisor beat) reads newly appended COMPLETE lines
    and keeps the last decodable row; torn tails (a concurrent append)
    are re-read next beat, exactly like tools/dash.py's tailer.  A file
    that has not produced a new row within ``stale_after`` seconds makes
    both providers return None — stale evidence must park the policy,
    not drive it.
    """

    def __init__(
        self, path: str, stale_after: float = 10.0, clock=time.monotonic,
    ) -> None:
        self._path = path
        self._stale_after = stale_after
        self._clock = clock
        self._pos = 0
        self._row: Optional[dict] = None
        self._fresh_at: Optional[float] = None

    def poll(self) -> None:
        last = None
        try:
            with open(self._path) as f:
                f.seek(self._pos)
                for line in f:
                    if not line.endswith("\n"):
                        break  # torn tail: reread from _pos next beat
                    self._pos += len(line)
                    try:
                        last = json.loads(line)
                    except ValueError:
                        continue
        except OSError:
            return  # not created yet / transient: evidence just goes stale
        if isinstance(last, dict):
            self._row = last
            self._fresh_at = self._clock()

    def _live_row(self) -> Optional[dict]:
        if self._row is None or self._fresh_at is None:
            return None
        if self._clock() - self._fresh_at > self._stale_after:
            return None
        return self._row

    def alerts(self) -> Optional[list]:
        row = self._live_row()
        if row is None:
            return None
        return (row.get("slo") or {}).get("alerts") or None

    def utilization(self) -> Optional[float]:
        row = self._live_row()
        if row is None:
            return None
        util = (row.get("gauges") or {}).get("fleet.utilization")
        return float(util) if util is not None else None


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.autoscale", description=__doc__.splitlines()[0]
    )
    ap.add_argument("server", metavar="HOST:PORT",
                    help="the serving port spawned workers mine against")
    ap.add_argument("--fleet-log", required=True, metavar="FILE",
                    help="the server's fleet-log JSONL (its burn evidence)")
    ap.add_argument("--spec", default="1", metavar="SPEC",
                    help="policy spec (autoscale.parse_autoscale_config "
                         "grammar; default: all defaults)")
    ap.add_argument("--telemetry", metavar="HOST:PORT", default=None,
                    help="server telemetry sidecar port for spawned "
                         "workers' exporters")
    ap.add_argument("--stale-after", type=float, default=10.0,
                    help="seconds without a new fleet-log row before the "
                         "evidence is treated as unknown (default 10)")
    ap.add_argument("--ticks", type=int, default=0,
                    help="stop after N controller beats (0 = run forever; "
                         "tests and scripted drains use this)")
    args = ap.parse_args(argv)
    try:
        cfg, driver = parse_autoscale_config(args.spec)
    except ValueError as e:
        ap.error(str(e))
    host, _, port_s = args.server.rpartition(":")
    try:
        port = int(port_s)
    except ValueError:
        ap.error(f"{args.server!r} is not HOST:PORT")
    workers = ProcessActuator(
        port,
        host=host or "127.0.0.1",
        backend=driver["backend"],
        telemetry=args.telemetry,
    )
    evidence = _FleetLogEvidence(args.fleet_log, stale_after=args.stale_after)
    # No weight/cell actuators out of process: the WFQ override surface
    # and the membership drain live inside the serving process (use its
    # --autoscale flag for those axes).  This supervisor is axis a only.
    controller = AutoscaleController(
        workers,
        burn=evidence.alerts,
        utilization=evidence.utilization,
        config=cfg,
    )
    ticks = 0
    last_printed = None
    try:
        while args.ticks <= 0 or ticks < args.ticks:
            evidence.poll()
            decision = controller.tick()
            ticks += 1
            key = (decision["state"], decision["live"],
                   decision["last_action"])
            if decision["acted"] or key != last_printed:
                last_printed = key
                print(json.dumps(decision), flush=True)
            time.sleep(driver["interval"])
    except KeyboardInterrupt:
        pass
    finally:
        workers.stop_all()
    return 0


if __name__ == "__main__":
    sys.exit(main())
