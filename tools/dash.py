"""Live terminal dashboard over the fleet metrics plane (ISSUE 7).

Renders the merged fleet view + SLO state the server's telemetry hub
publishes — either from the fleet-log JSONL file (``--fleet-log=FILE`` /
``BMT_FLEET_LOG`` on the server) or live over the telemetry sidecar
channel itself (subscribe mode):

    python -m tools.dash fleet.jsonl            # last state, one frame
    python -m tools.dash fleet.jsonl --follow   # tail the file live
    python -m tools.dash --connect HOST:PORT    # subscribe to the hub
    python -m tools.dash fleet.jsonl --once     # one frame, no ANSI
    python -m tools.dash --cells a=a.jsonl,b=b.jsonl   # federation view

``--cells`` is the federation mode (ISSUE 8): each cell's fleet log is
one replica's merged view; the frame shows them folded into ONE
federation view — per-source rows prefixed ``cell/``, counters summed
across cells (each miner exports to exactly one cell's hub, so cell
sums never double-count a source), stragglers and SLO alerts unioned
with their cell names.

One frame shows: source liveness (fresh/stale with ages), the SLO table
(burn rates fast/slow, firing state), flagged stragglers, the merged
latency histograms (p50/p95/p99 — ``-`` when empty, never a misleading
0), and the busiest counters.  ``--once`` renders a single frame without
clearing the screen (scripts, tests); the default loop redraws per
update until interrupted.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path
from typing import Iterator, List, Optional

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))  # repo root

from bitcoin_miner_tpu.utils.metrics import format_quantiles  # noqa: E402

#: Counters worth a dashboard row even when many exist (prefix order =
#: display order); everything else folds into the "other" count.
_COUNTER_PREFIXES = ("sched.", "gateway.", "miner.", "telemetry.", "slo.",
                     "federation.", "fed.", "gossip.", "autoscale.")

#: fed.peer_state gauge codes (ISSUE 12) rendered human-readable.
_PEER_STATES = ("OK", "SHEDDING", "DRAINING", "SUSPECT", "DEAD")


def _fmt_age(age_s: float) -> str:
    return f"{age_s:.1f}s" if age_s < 120 else f"{age_s / 60:.1f}m"


def render_frame(state: dict, width: int = 78) -> str:
    """One dashboard frame from a merged-state dict (the fleet-log row /
    subscriber payload shape: FleetView.merged_state + stragglers + slo)."""
    bar = "-" * width
    lines: List[str] = []
    total = state.get("sources", 0) + state.get("stale_sources", 0)
    lines.append(
        f"fleet: {state.get('sources', 0)}/{total} sources fresh"
        + (f", {state['stale_sources']} stale" if state.get("stale_sources") else "")
    )
    per = state.get("per_source") or {}
    for name in sorted(per):
        info = per[name]
        mark = "STALE" if info.get("stale") else "ok"
        lines.append(
            f"  {name:<24} {mark:<6} age={_fmt_age(info.get('age_s', 0.0))}"
        )
    slo = state.get("slo")
    if slo:
        lines.append(bar)
        lines.append("SLO                     burn fast/slow   state")
        for s in slo.get("slos", []):
            mark = "ALERT" if s.get("firing") else "ok"
            lines.append(
                f"  {s['name']:<20} {s['burn_fast']:>8.2f}/{s['burn_slow']:<8.2f} {mark}"
            )
    autoscale = state.get("autoscale")
    if autoscale:
        # The controller's own status() (hub extra, ISSUE 18) next to the
        # ticker's gauges: target vs live is the loop's error signal.
        gauges = state.get("gauges") or {}
        target = autoscale.get("target", gauges.get("autoscale.target_workers"))
        live = gauges.get("gauge.miners_live")
        lines.append(bar)
        lines.append(
            f"autoscale: {autoscale.get('state', '?'):<14} "
            f"target={target} live={int(live) if live is not None else '?'}"
        )
        if autoscale.get("last_action"):
            lines.append(f"  last action: {autoscale['last_action']}")
        if autoscale.get("suppress_reason"):
            lines.append(f"  suppressed:  {autoscale['suppress_reason']}")
        if autoscale.get("pending"):
            lines.append(f"  pending:     {autoscale['pending']}")
        weights = autoscale.get("weights")
        if weights:
            shown_w = " ".join(
                f"{t}={w:g}" for t, w in sorted(weights.items()))
            lines.append(f"  tenant weights (overload): {shown_w}")
    peer_states = {
        k[len("fed.peer_state."):]: v
        for k, v in (state.get("gauges") or {}).items()
        if k.startswith("fed.peer_state.")
    }
    if peer_states:
        lines.append(bar)
        lines.append("federation peers (membership):")
        for name in sorted(peer_states):
            code = int(peer_states[name])
            label = (
                _PEER_STATES[code]
                if 0 <= code < len(_PEER_STATES)
                else f"?{code}"
            )
            lines.append(f"  {name:<28} {label}")
    strag = state.get("stragglers")
    if strag:
        lines.append(bar)
        lines.append("stragglers:")
        for s in strag:
            lines.append(
                f"  {s['source']:<24} p50={s['p50_s']:.3g}s "
                f"(fleet {s['fleet_p50_s']:.3g}s, {s['ratio']:.1f}x)"
            )
    hists = state.get("hists") or {}
    if hists:
        lines.append(bar)
        lines.append("latency (p50/p95/p99)            n")
        for name in sorted(hists):
            s = hists[name]
            lines.append(
                f"  {name:<28} {format_quantiles(s):<20} {int(s.get('count', 0))}"
            )
    counters = state.get("counters") or {}
    shown = {
        k: v for k, v in counters.items()
        if k.startswith(_COUNTER_PREFIXES) and v
    }
    if shown:
        lines.append(bar)
        lines.append("counters:")
        for k in sorted(shown):
            lines.append(f"  {k:<36} {shown[k]}")
        rest = len([k for k in counters if k not in shown])
        if rest:
            lines.append(f"  (+{rest} more)")
    return "\n".join(lines)


def merge_cell_states(cells: dict) -> dict:
    """Fold per-cell merged states ({cell: state}) into one federation
    display state (the ``--cells`` frame).  Counters sum across cells;
    per-source rows, stragglers and firing SLOs carry a ``cell/`` prefix;
    histograms keep per-cell resolution under prefixed names (snapshot
    dicts carry quantiles, not buckets, so re-merging them numerically
    would fabricate data — prefixing shows the truth instead)."""
    out: dict = {
        "sources": 0,
        "stale_sources": 0,
        "per_source": {},
        "counters": {},
        "gauges": {},
        "hists": {},
        "stragglers": [],
    }
    slos: List[dict] = []
    for cell in sorted(cells):
        state = cells[cell]
        if not isinstance(state, dict):
            continue
        out["sources"] += state.get("sources", 0)
        out["stale_sources"] += state.get("stale_sources", 0)
        for name, info in (state.get("per_source") or {}).items():
            out["per_source"][f"{cell}/{name}"] = info
        for k, v in (state.get("counters") or {}).items():
            if isinstance(v, (int, float)):
                out["counters"][k] = out["counters"].get(k, 0) + v
        for k, v in (state.get("gauges") or {}).items():
            if k.startswith("fed.peer_state."):
                # Each cell's view of ITS peers: keep per-cell resolution
                # (`a` seeing `b` DEAD while `b` sees itself fine is
                # exactly the asymmetry worth showing).
                peer = k[len("fed.peer_state."):]
                out["gauges"][f"fed.peer_state.{cell}->{peer}"] = v
        for k, s in (state.get("hists") or {}).items():
            out["hists"][f"{cell}/{k}"] = s
        for s in state.get("stragglers") or []:
            out["stragglers"].append({**s, "source": f"{cell}/{s['source']}"})
        slo = state.get("slo")
        if slo:
            for s in slo.get("slos", []):
                slos.append({**s, "name": f"{cell}/{s['name']}"})
    if slos:
        out["slo"] = {
            "slos": slos,
            "alerts": [s["name"] for s in slos if s.get("firing")],
        }
    return out


# ------------------------------------------------------------------- inputs

def _states_from_file(path: str, follow: bool, poll_s: float) -> Iterator[dict]:
    """Parsed rows from a fleet-log JSONL file.  Non-follow mode yields
    just the LAST decodable row (the current state); follow mode starts
    there and then tails.  Torn final lines (a concurrent append) are
    skipped and retried on the next poll."""
    pos = 0
    last: Optional[dict] = None
    while True:
        try:
            with open(path) as f:
                f.seek(pos)
                for line in f:
                    if not line.endswith("\n"):
                        break  # torn tail: reread from pos next poll
                    pos += len(line)
                    try:
                        last = json.loads(line)
                    except ValueError:
                        continue
        except FileNotFoundError as e:
            # Follow mode races the server's FIRST publish (the hub only
            # creates the file on its first rate-limited beat): wait for
            # it instead of dying on a race the user cannot see.
            if follow:
                time.sleep(poll_s)
                continue
            raise SystemExit(f"cannot read {path}: {e}")
        except OSError as e:
            raise SystemExit(f"cannot read {path}: {e}")
        if last is not None:
            yield last
            last = None
        if not follow:
            return
        time.sleep(poll_s)


def _states_from_hub(hostport: str) -> Iterator[dict]:
    """Subscribe to a live hub over the telemetry sidecar channel and
    yield merged states as they are published."""
    from bitcoin_miner_tpu import lsp
    from bitcoin_miner_tpu.utils.telemetry import (
        FrameAssembler,
        encode_subscribe,
    )

    host, _, port = hostport.rpartition(":")
    try:
        client = lsp.Client(host or "127.0.0.1", int(port))
    except (lsp.LspError, OSError, ValueError) as e:
        raise SystemExit(f"cannot connect to telemetry hub {hostport}: {e}")
    asm = FrameAssembler()
    try:
        client.write(encode_subscribe())
        while True:
            try:
                payload = client.read()
            except lsp.LspError:
                return  # hub gone: end of stream
            done, obj = asm.feed(payload)
            if done and isinstance(obj, dict):
                yield obj
    finally:
        try:
            client.close()
        except lsp.LspError:
            pass


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.dash", description=__doc__.splitlines()[0]
    )
    ap.add_argument("file", nargs="?", default=None,
                    help="fleet-log JSONL file (server --fleet-log=FILE)")
    ap.add_argument("--connect", metavar="HOST:PORT", default=None,
                    help="subscribe to a live server's --telemetry-port")
    ap.add_argument("--cells", metavar="NAME=FILE[,NAME=FILE...]",
                    default=None,
                    help="federation view: merge several cells' fleet "
                         "logs into one frame (ISSUE 8)")
    ap.add_argument("--follow", action="store_true",
                    help="keep tailing the file (connect mode always follows)")
    ap.add_argument("--interval", type=float, default=1.0,
                    help="file poll interval in follow mode (seconds)")
    ap.add_argument("--once", action="store_true",
                    help="render one frame without ANSI clears and exit")
    args = ap.parse_args(argv)
    if args.cells is not None:
        if args.file is not None or args.connect is not None:
            ap.error("--cells replaces FILE/--connect")
        cells = {}
        missing = []
        for part in args.cells.split(","):
            name, sep, path = part.partition("=")
            if not sep or not name or not path:
                ap.error(f"--cells entry {part!r} is not NAME=FILE")
            try:
                states = list(_states_from_file(path, follow=False, poll_s=0))
            except SystemExit:
                states = []
            if states:
                cells[name] = states[-1]
            else:
                missing.append(f"{name} ({path})")
        if missing:
            # A federation frame silently missing a replica is exactly the
            # failure this dashboard exists to surface: name the holes.
            print(
                "dash: no fleet state for cell(s): " + ", ".join(missing),
                file=sys.stderr,
            )
        if not cells:
            print("no fleet states found", file=sys.stderr)
            return 1
        print(render_frame(merge_cell_states(cells)))
        return 0
    if (args.file is None) == (args.connect is None):
        ap.error("give a fleet-log FILE or --connect HOST:PORT (not both)")

    states = (
        _states_from_hub(args.connect)
        if args.connect
        else _states_from_file(args.file, args.follow and not args.once,
                               args.interval)
    )
    saw = False
    try:
        for state in states:
            frame = render_frame(state)
            if args.once:
                print(frame)
                return 0
            # Clear + home, then the frame — a live dashboard, not a log.
            sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
            sys.stdout.flush()
            saw = True
    except KeyboardInterrupt:
        return 0
    if not saw:
        print("no fleet states found", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
