"""Trace-file analysis: rebuild per-request timelines from the event log.

``utils/trace.py`` writes JSONL rows ``{"t", "trace", "span", "event",
"attrs"}``; this package turns a file of them back into **one tree per
request** (trace ids are minted at the gateway / scheduler entry point
and threaded through every layer) plus a stage/critical-path breakdown:
where did each request's wall time go — admission queue, scheduling,
kernel sweep, delivery?  Rows with a null trace id are fleet
infrastructure events (miner tier downgrades, reconnects, LSP
retransmits) and are reported alongside, so a seeded chaos drill's trace
is a deterministic diagnosis: replay the drill, read the trace, see WHY
a tier was abandoned while request N stalled.

CLI: ``python -m tools.trace FILE [--json] [--strict] [--requests N]``
(``--strict`` exits non-zero on orphan spans or unterminated trees —
the tier-1 loopback-fleet test runs it that way).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Tuple

#: (span, event) pairs that BIRTH a request tree.  A trace id whose
#: events include none of these is an orphan span — something emitted on
#: an id that was never minted at an entry point.
ROOT_EVENTS = {("gw", "request"), ("sched", "job_start")}

#: (span, event) pairs that CLOSE a tree.  Every request must reach one
#: — answered (result/job_done), refused (shed), or abandoned with its
#: progress stashed (job_orphaned / waiter_lost); a rooted tree with no
#: terminal is still open (in flight at snapshot time, or lost work).
TERMINAL_EVENTS = {
    ("gw", "result"),
    ("gw", "shed"),
    ("gw", "waiter_lost"),
    ("sched", "job_done"),
    ("sched", "job_orphaned"),
}

#: Stage names in timeline order (the breakdown report's row order).
STAGES = ("admission", "scheduling", "sweep", "deliver")


def load(path: str) -> List[dict]:
    """Parse one JSONL trace file; malformed lines are skipped (a torn
    final line from a killed server must not hide the rest)."""
    rows: List[dict] = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(row, dict) and "t" in row and "event" in row:
                rows.append(row)
    return rows


@dataclass
class RequestTree:
    """Every event carrying one trace id, in time order."""

    trace: int
    events: List[dict] = field(default_factory=list)

    def _find(self, span: str, event: str) -> Optional[dict]:
        for e in self.events:
            if e.get("span") == span and e.get("event") == event:
                return e
        return None

    def _all(self, span: str, event: str) -> List[dict]:
        return [
            e
            for e in self.events
            if e.get("span") == span and e.get("event") == event
        ]

    @property
    def root(self) -> Optional[dict]:
        for e in self.events:
            if (e.get("span"), e.get("event")) in ROOT_EVENTS:
                return e
        return None

    @property
    def terminal(self) -> Optional[dict]:
        for e in reversed(self.events):
            if (e.get("span"), e.get("event")) in TERMINAL_EVENTS:
                return e
        return None

    @property
    def complete(self) -> bool:
        return self.root is not None and self.terminal is not None

    @property
    def kind(self) -> str:
        """How the request was served: cache_hit / span_hit / coalesced /
        shed / swept / lost / open."""
        if self._find("gw", "cache_hit") is not None:
            return "cache_hit"
        if self._find("gw", "span_hit") is not None:
            return "span_hit"
        if self._find("gw", "coalesce") is not None:
            return "coalesced"
        if self._find("gw", "shed") is not None:
            return "shed"
        if self.terminal is None:
            return "open"
        if (
            self._find("sched", "job_done") is not None
            or self._find("gw", "result") is not None
        ):
            return "swept"
        return "lost"  # orphaned / waiter death closed it

    def signature(self) -> Optional[Tuple[str, int, int]]:
        root = self.root
        if root is None:
            return None
        a = root.get("attrs", {})
        if all(k in a for k in ("data", "lower", "upper")):
            return (a["data"], a["lower"], a["upper"])
        if all(k in a for k in ("lower", "upper")):
            return ("", a["lower"], a["upper"])
        return None

    @property
    def total_s(self) -> float:
        root, term = self.root, self.terminal
        if root is None or term is None:
            return 0.0
        return max(0.0, term["t"] - root["t"])

    def chunks(self) -> List[dict]:
        """Per-chunk timing rows: each dispatch consumes the next
        chunk_result with the same (miner, lo) in time order — a
        straggler-requeued chunk re-dispatched to the same miner gets at
        most one result attributed, never two copies of the same one.
        Unmatched dispatches (in flight / reassigned) carry elapsed None.
        """
        results: Dict[Tuple, List[dict]] = {}
        for e in self._all("sched", "chunk_result"):
            a = e.get("attrs", {})
            results.setdefault((a.get("miner"), a.get("lo")), []).append(e)
        out: List[dict] = []
        for d in self._all("sched", "dispatch"):
            a = d.get("attrs", {})
            pending = results.get((a.get("miner"), a.get("lo")))
            r = pending.pop(0) if pending else None
            out.append(
                {
                    "miner": a.get("miner"),
                    "lo": a.get("lo"),
                    "hi": a.get("hi"),
                    "t_dispatch": d["t"],
                    "elapsed": (
                        r.get("attrs", {}).get("elapsed")
                        if r is not None
                        else None
                    ),
                }
            )
        return out

    def stages(self) -> Dict[str, float]:
        """Wall-time breakdown of a swept request (empty for zero-work
        answers): admission (queue wait), scheduling (submit → first
        chunk on a miner), sweep (first dispatch → job done), deliver
        (job done → result on the wire)."""
        root = self.root
        if root is None:
            return {}
        queued = self._find("gw", "queued")
        admitted = self._find("gw", "admitted")
        submit = self._find("gw", "submit") or self._find("sched", "job_start")
        dispatches = self._all("sched", "dispatch")
        done = self._find("sched", "job_done")
        result = self._find("gw", "result")
        out: Dict[str, float] = {}
        if queued is not None and admitted is not None:
            out["admission"] = max(0.0, admitted["t"] - queued["t"])
        if submit is not None and dispatches:
            out["scheduling"] = max(0.0, dispatches[0]["t"] - submit["t"])
        if dispatches and done is not None:
            out["sweep"] = max(0.0, done["t"] - dispatches[0]["t"])
        if done is not None and result is not None:
            out["deliver"] = max(0.0, result["t"] - done["t"])
        return out

    def critical_stage(self) -> Optional[str]:
        """The stage that dominated this request's wall time."""
        stages = self.stages()
        if not stages:
            return None
        return max(stages.items(), key=lambda kv: kv[1])[0]


@dataclass
class TraceReport:
    trees: Dict[int, RequestTree]
    orphans: List[int]  # trace ids with events but no root
    fleet: List[dict]  # null-trace infrastructure events

    @property
    def complete(self) -> List[RequestTree]:
        return [t for t in self.trees.values() if t.complete]

    @property
    def open(self) -> List[RequestTree]:
        return [
            t
            for t in self.trees.values()
            if t.root is not None and t.terminal is None
        ]

    def stage_totals(self) -> Dict[str, float]:
        """Aggregate seconds per stage across every swept request — the
        critical-path view: which stage is the fleet's time actually
        going to?"""
        totals = {s: 0.0 for s in STAGES}
        for tree in self.trees.values():
            for name, dt in tree.stages().items():
                totals[name] = totals.get(name, 0.0) + dt
        return totals

    def fleet_summary(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for e in self.fleet:
            key = f"{e.get('span')}.{e.get('event')}"
            out[key] = out.get(key, 0) + 1
        return out

    def as_dict(self) -> dict:
        kinds: Dict[str, int] = {}
        for t in self.trees.values():
            kinds[t.kind] = kinds.get(t.kind, 0) + 1
        return {
            "requests": len(self.trees),
            "complete": len(self.complete),
            "open": sorted(t.trace for t in self.open),
            "orphans": sorted(self.orphans),
            "kinds": kinds,
            "stage_totals_s": {
                k: round(v, 6) for k, v in self.stage_totals().items()
            },
            "fleet_events": self.fleet_summary(),
            "trees": [
                {
                    "trace": t.trace,
                    "kind": t.kind,
                    "complete": t.complete,
                    "signature": list(t.signature() or ()) or None,
                    "total_s": round(t.total_s, 6),
                    "stages_s": {
                        k: round(v, 6) for k, v in t.stages().items()
                    },
                    "chunks": len(t.chunks()),
                    "events": len(t.events),
                }
                for t in sorted(self.trees.values(), key=lambda t: t.trace)
            ],
        }


def build(rows: List[dict]) -> TraceReport:
    """Group rows into request trees + fleet events (time-sorted)."""
    rows = sorted(rows, key=lambda r: r.get("t", 0.0))
    trees: Dict[int, RequestTree] = {}
    fleet: List[dict] = []
    for row in rows:
        tid = row.get("trace")
        if tid is None:
            fleet.append(row)
            continue
        tree = trees.get(tid)
        if tree is None:
            tree = trees[tid] = RequestTree(trace=tid)
        tree.events.append(row)
    orphans = [tid for tid, t in trees.items() if t.root is None]
    for tid in orphans:
        del trees[tid]
    return TraceReport(trees=trees, orphans=orphans, fleet=fleet)
