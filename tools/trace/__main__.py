"""CLI for the trace reconstructor: ``python -m tools.trace FILE``.

Reads one JSONL trace file (``--trace=FILE`` on the server, a drill's
``trace_path``, or ``BMT_TRACE`` in a subprocess bench), rebuilds one
tree per request, and prints the per-request timelines plus the
stage/critical-path breakdown.  ``--json`` emits the machine view (the
tier-1 loopback test asserts over it); ``--strict`` exits non-zero on
orphan spans or unterminated trees.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List

from . import STAGES, build, load


def main(argv: List[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="python -m tools.trace")
    ap.add_argument("file", help="JSONL trace file (utils/trace.py format)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable report on stdout")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 on orphan spans or unterminated trees")
    ap.add_argument("--requests", type=int, default=20, metavar="N",
                    help="show at most N per-request lines (default 20)")
    args = ap.parse_args(argv)

    try:
        rows = load(args.file)
    except OSError as e:
        print(f"tools.trace: cannot read {args.file}: {e}", file=sys.stderr)
        return 2
    report = build(rows)
    bad = bool(report.orphans or report.open)

    if args.as_json:
        print(json.dumps(report.as_dict()))
        return 1 if (args.strict and bad) else 0

    d = report.as_dict()
    print(
        f"trace: {len(rows)} records, {d['requests']} request(s) "
        f"({d['complete']} complete, {len(d['open'])} open, "
        f"{len(d['orphans'])} orphan span(s)), "
        f"{sum(d['fleet_events'].values())} fleet event(s)"
    )
    if d["kinds"]:
        kinds = ", ".join(f"{k}={v}" for k, v in sorted(d["kinds"].items()))
        print(f"served: {kinds}")

    totals = report.stage_totals()
    grand = sum(totals.values())
    if grand > 0:
        print("stage breakdown (aggregate over swept requests):")
        for name in STAGES:
            dt = totals.get(name, 0.0)
            print(f"  {name:<12} {dt:9.4f}s  {dt / grand:6.1%}")
    crit: dict = {}
    for tree in report.trees.values():
        stage = tree.critical_stage()
        if stage is not None:
            crit[stage] = crit.get(stage, 0) + 1
    if crit:
        dominant = ", ".join(
            f"{k}×{v}" for k, v in sorted(crit.items(), key=lambda kv: -kv[1])
        )
        print(f"critical stage per request: {dominant}")

    shown = 0
    for tree in sorted(report.trees.values(), key=lambda t: t.trace):
        if shown >= args.requests:
            remaining = len(report.trees) - shown
            print(f"  ... {remaining} more (raise --requests)")
            break
        sig = tree.signature()
        sig_s = f"{sig[0]!r}[{sig[1]},{sig[2]}]" if sig else "?"
        stages = " ".join(
            f"{k}={v:.4f}s" for k, v in tree.stages().items()
        )
        extra = f" {stages}" if stages else ""
        chunks = len(tree.chunks())
        chunk_s = f" chunks={chunks}" if chunks else ""
        print(
            f"  #{tree.trace} {tree.kind:<9} {sig_s} "
            f"total={tree.total_s:.4f}s{chunk_s}{extra}"
        )
        shown += 1

    if report.open:
        print(f"open trees (no terminal event): "
              f"{sorted(t.trace for t in report.open)}")
    if report.orphans:
        print(f"orphan spans (events on an unminted id): "
              f"{sorted(report.orphans)}")
    for key, n in sorted(report.fleet_summary().items()):
        print(f"fleet: {key} ×{n}")
    # The WHYs, verbatim, for the abandonment events a soak cares about —
    # and the capacity plane's decision -> action -> settled timeline
    # (ISSUE 18), so an autoscale drill's trace reads as a story.
    for e in report.fleet:
        if (e.get("event") in ("tier_downgrade", "wedge_detected", "gave_up")
                or e.get("span") == "autoscale"):
            print(f"fleet detail: t={e['t']:.3f} {e['span']}.{e['event']} "
                  f"{e.get('attrs', {})}")
    return 1 if (args.strict and bad) else 0


if __name__ == "__main__":
    sys.exit(main())
