#!/usr/bin/env python
"""Replay a chaos-soak scenario from the command line.

A failing chaos test prints its ``(scenario, seed)`` pair; this tool
re-runs that exact drill — same seeded fault decisions, same fleet shape —
outside pytest, so a failure can be bisected with extra logging or under a
debugger:

    python tools/chaos_replay.py --scenario burst-loss --seed 1234
    python tools/chaos_replay.py --list
    python tools/chaos_replay.py --scenario miner-partition --seed 7 \
        --miners 3 --kill-at 0.5 --max-nonce 8000 -v

Prints one JSON report line (the drill's oracle verdict + chaos/self-
healing counter totals) and exits non-zero on an oracle mismatch, so it
slots into shell bisection loops.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--scenario", default="burst-loss",
                        help="named schedule from lspnet.standard_scenarios")
    parser.add_argument("--seed", type=int, default=1)
    parser.add_argument("--data", default="chaos")
    parser.add_argument("--max-nonce", type=int, default=4000)
    parser.add_argument("--miners", type=int, default=2)
    parser.add_argument("--kill-at", type=float, default=None,
                        help="kill miner-0's conn this many seconds in")
    parser.add_argument("--epoch-millis", type=int, default=100)
    parser.add_argument("--timeout", type=float, default=120.0)
    parser.add_argument("--fed-drill", metavar="NAME", default=None,
                        help="replay a federation resilience drill "
                             "(ISSUE 12) instead of a chaos-soak scenario: "
                             "shed-storm, drain-handoff, death-detect, "
                             "ack-retransmit — same seeded decisions as "
                             "the failing fleet_bench --federation leg")
    parser.add_argument("--list", action="store_true",
                        help="list scenario names and exit")
    parser.add_argument("--trace", metavar="FILE", default=None,
                        help="write the drill's structured event log here "
                             "(JSONL; analyze with python -m tools.trace)")
    parser.add_argument("-v", "--verbose", action="store_true",
                        help="enable lspnet debug drop logging")
    args = parser.parse_args(argv)

    # Import after the path fix so the tool runs from any cwd.
    from bitcoin_miner_tpu import lspnet
    from bitcoin_miner_tpu.apps.drill import run_drill

    if args.list:
        from bitcoin_miner_tpu.federation.drill import DRILLS

        for name, sched in lspnet.standard_scenarios().items():
            print(f"{name:24s} {sched.desc}")
        for name in DRILLS:
            print(f"{name:24s} federation resilience drill (--fed-drill)")
        return 0
    if args.verbose:
        lspnet.enable_debug_logs(True)
    if args.fed_drill:
        from bitcoin_miner_tpu.federation.drill import run_fed_drill

        if args.trace:
            from bitcoin_miner_tpu.utils.trace import TRACE

            TRACE.enable(path=args.trace)
        try:
            report = run_fed_drill(args.fed_drill, seed=args.seed)
        except ValueError as e:
            print(f"chaos_replay: {e}", file=sys.stderr)
            return 2
        finally:
            if args.trace:
                from bitcoin_miner_tpu.utils.trace import TRACE

                TRACE.disable()
        print(json.dumps(report))
        return 0 if report.get("ok") else 1
    try:
        report = run_drill(
            args.scenario,
            seed=args.seed,
            data=args.data,
            max_nonce=args.max_nonce,
            n_miners=args.miners,
            kill_miner_at=args.kill_at,
            epoch_millis=args.epoch_millis,
            timeout=args.timeout,
            trace_path=args.trace,
        )
    except ValueError as e:  # e.g. a typoed --scenario name
        print(f"chaos_replay: {e}", file=sys.stderr)
        return 2
    print(json.dumps(report.as_dict()))
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
