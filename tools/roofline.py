"""Empirical VPU roofline for the Pallas SHA-256 sweep.

The sweep is pure elementwise uint32 work on the VPU (the MXU is useless
for SHA — SURVEY §7 hard-part 2), so its ceiling is the chip's sustained
u32 ALU rate, not FLOPs or HBM.  This tool measures that rate **in situ
with the production kernel**, by comparing sweeps whose tails have one vs
two vector compression blocks: the marginal cost of the extra block
isolates pure compression time from per-program overhead (epilogue,
masking, window DMA, grid bookkeeping).

Why not a synthetic micro-kernel: two environment facts defeat that
approach here, both discovered the hard way —

1. the tunnelled TPU backend returns cached results for byte-identical
   (executable, args) re-executions, so repeated identical dispatches
   measure RPC latency, not compute;
2. Mosaic's layout inference collapses work it can prove redundant:
   grid programs with no program_id dependence dedupe, and sublane-
   replicated tensors compute on one sublane — a naive probe quietly
   loses 64-1000x of its claimed work.

Static op accounting of the kernel (ops/pallas_sha256.py, per tail
block, k in-kernel digits):

  per round t=0..63:   s1e 11 + ch 3 + t1 4 + s0a 11 + maj 4 + t2 1
                       + e-add 1 + a-add 1                    = 36 ops
  schedule t=16..63:   s0 9 + s1 9 + 3 adds                   = 21 ops
  state add + w assembly + mask/accumulate                    ~ 40 ops

  -> ~3,350 u32 vector ops/nonce per vector block BEFORE constant-word
     folding (const-only chains run on the scalar unit and don't count
     against the VPU).

The derived figures are BOUNDS, not point estimates, because the marginal
block is partially scalar-folded itself (for DATA_2BLK only word 15 of
block 0 varies, so that block's leading rounds and most const-σ schedule
chains are scalar) and streams one fewer contrib tile than the 1-block
layout.  The marginal cost c therefore UNDERprices a full vector block:

  - 1/c            = UPPER bound on the 1-block nonces/s ceiling
                     (=> headroom <= 1/c / rate_1blk - 1)
  - OPS_PER_BLOCK/c = UPPER bound on sustained vector u32 ops/s
                     (the marginal block executes fewer than
                     OPS_PER_BLOCK vector ops)

Usage: python tools/roofline.py   (on the TPU; prints one JSON line)
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))  # repo root

OPS_PER_BLOCK = 64 * 36 + 48 * 21 + 40  # see module docstring

# Tail shapes for 10-digit nonces (base 1e9): 'cmu440' -> 1 vector block;
# 'y'*57 -> c_len 58, digits at bytes 58..68, low-6 digits straddle words
# 15/16 -> BOTH tail blocks carry vector words (a 60-byte prefix would
# leave block 0 fully constant => scalar-unit, measuring nothing).
DATA_1BLK = "cmu440"
DATA_2BLK = "y" * 57


MAX_K = 6  # explicit: the measurement premise below depends on it


def _rate(data: str, n: int) -> float:
    from bitcoin_miner_tpu.ops.sweep import sweep_min_hash

    base = 10**9
    sweep_min_hash(data, base, base + 10**6 - 1, max_k=MAX_K)  # compile
    t0 = time.perf_counter()
    r = sweep_min_hash(data, base, base + n - 1, max_k=MAX_K)
    dt = time.perf_counter() - t0
    assert r.lanes_swept == n
    return n / dt


def main() -> int:
    import jax

    from bitcoin_miner_tpu.ops.sha256 import build_layout

    assert build_layout(DATA_1BLK.encode(), 10).n_tail_blocks == 1
    lay2 = build_layout(DATA_2BLK.encode(), 10)
    assert lay2.n_tail_blocks == 2
    # Both blocks must carry low-digit words or block 0 folds to scalars.
    low_words = {p.word for p in lay2.digit_pos[lay2.digit_count - MAX_K :]}
    assert min(low_words) < 16 <= max(low_words), low_words

    dev = jax.devices()[0]
    kind = (getattr(dev, "device_kind", "") or dev.platform)
    n = 2 * 10**9
    r1 = _rate(DATA_1BLK, n)
    r2 = _rate(DATA_2BLK, n)
    # t = n * (blocks * c + o): the marginal block isolates c — a LOWER
    # bound on a full vector block's cost (see module docstring).
    c = 1 / r2 - 1 / r1  # seconds per nonce per (marginal) block
    # A non-positive marginal means a degenerate measurement (e.g. the
    # dispatch-caching hazard above) — refuse to publish nonsense bounds.
    assert c > 0, (r1, r2)
    sustained_ub = OPS_PER_BLOCK / c
    ceiling_ub = 1 / c
    headroom_ub = ceiling_ub / r1 - 1
    print(
        f"device={kind}  "
        f"1blk {r1 / 1e9:.2f}e9 n/s  2blk {r2 / 1e9:.2f}e9 n/s  "
        f"marginal block {c * 1e9:.3f} ns -> <= {sustained_ub / 1e12:.1f} T "
        f"u32-ops/s sustained; 1blk ceiling <= {ceiling_ub / 1e9:.2f}e9 n/s "
        f"(headroom over current rate <= {headroom_ub:.0%})",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": "vpu_u32_ops_per_sec_sustained_upper_bound",
                "value": round(sustained_ub),
                "ops_per_block_unfolded": OPS_PER_BLOCK,
                "rate_1blk": round(r1),
                "rate_2blk": round(r2),
                "marginal_block_ns": round(c * 1e9, 4),
                "ceiling_1blk_upper_bound": round(ceiling_ub),
                "headroom_upper_bound": round(headroom_ub, 4),
                "device_kind": kind,
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
