"""Empirical VPU roofline for the Pallas SHA-256 sweep.

The sweep is pure elementwise uint32 work on the VPU (the MXU is useless
for SHA — SURVEY §7 hard-part 2), so its ceiling is the chip's sustained
u32 ALU rate, not FLOPs or HBM.  This tool measures that rate **in situ
with the production kernel**, by comparing sweeps whose tails have one vs
two vector compression blocks: the marginal cost of the extra block
isolates pure compression time from per-program overhead (epilogue,
masking, window DMA, grid bookkeeping).

Why not a synthetic micro-kernel: two environment facts defeat that
approach here, both discovered the hard way —

1. the tunnelled TPU backend returns cached results for byte-identical
   (executable, args) re-executions, so repeated identical dispatches
   measure RPC latency, not compute;
2. Mosaic's layout inference collapses work it can prove redundant:
   grid programs with no program_id dependence dedupe, and sublane-
   replicated tensors compute on one sublane — a naive probe quietly
   loses 64-1000x of its claimed work.

**Exact folded op counts** (r5, replacing the r4 upper-bound handwave):
the kernel's constant-word folding keeps every all-scalar sub-expression
off the VPU (ops/sha256.py `compress` docstring), so the static count
that matters is the number of ops with at least one *vector* input.  That
is computed here exactly, by abstract interpretation: `compress` is run
on tracer values carrying only a scalar/vector flag, counting each op
whose result is vector, for the exact word layout of the measured data
shapes.  With both measured rates and both exact counts, the marginal-
block algebra yields a *point estimate* of sustained VPU throughput, not
a bound:

    1/r1 = (ops1 + EPI)/S + o        (o = per-nonce non-ALU overhead:
    1/r2 = (ops2 + EPI)/S + o         grid/DMA/bookkeeping, identical for
                                      both shapes — same batch/tile/cpb/k)
    =>  S = (ops2 - ops1) / (1/r2 - 1/r1)

and the compute-only ceiling of the flagship shape is

    ceiling = S / (ops1 + EPI)        (reached iff o -> 0).

The model's fidelity caveat: "scalar stays scalar" mirrors Mosaic's lazy
broadcast, but Mosaic's own CSE may trim a few more ops and register
pressure may add spill traffic the count can't see; treat the ceiling as
good to a few percent, which is enough to size the remaining headroom.

Usage: python tools/roofline.py   (on the TPU; prints one JSON line)
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))  # repo root

# Tail shapes for 10-digit nonces (base 1e9).  The production kernel is
# digit-position-DYNAMIC (ops/pallas_sha256.make_pallas_minhash_dyn): its
# vector-word set is the whole dyn window, not just this d-class's digit
# words, and the op model below mirrors that.  'cmu440' -> 1 vector
# block, dyn window = words 2..6; 'y'*54 -> c_len 55, digits at bytes
# 55..64, dyn window = words 14..18.  BOTH tail blocks carry vector words
# (a 60-byte prefix would leave block 0 fully constant => scalar-unit,
# measuring nothing) AND both shapes stream exactly FIVE contrib VMEM
# windows per program, so the per-program overhead o really is identical
# between the two measurements.  If you change a probe shape, re-check
# the two dyn windows (pallas_sha256.dyn_params) are the same width —
# unequal window streaming folds an asymmetry into the marginal.
DATA_1BLK = "cmu440"
DATA_2BLK = "y" * 54


MAX_K = 6  # explicit: the measurement premise below depends on it

from bitcoin_miner_tpu.ops.pallas_sha256 import DEFAULT_CPB as CPB  # noqa: E402

# Per-nonce VPU ops of the kernel OUTSIDE compress, hand-counted from
# ops/pallas_sha256.py's kernel body (per row-visit per lane).  This is
# the BASELINE kernel's reduction epilogue — and the sieve kernel's
# pass-2 (survivor-groups-only) epilogue, which is the same full fold:
#   valid mask        2 cmp + 1 and                  = 3
#   h0/h1 select      2 where                        = 2
#   sign-flip         2 xor (bitcast is layout-free) = 2
#   idx               1 add + 1 where                = 2
#   running-min fold  9 cmp/and/or + 3 where = 12, skipped on the first
#                     of the cpb rows                = 12 * (CPB-1)/CPB
# amortised once per program over cpb rows:
#   lane index i      ~5 (2 iota + mul + 2 add)      = 5 / CPB
#   accumulator RMW   12                             = 12 / CPB
EPILOGUE_OPS = 3 + 2 + 2 + 2 + 12 * (CPB - 1) / CPB + (5 + 12) / CPB

# The sieve kernel's PASS-1 epilogue (ISSUE 13) — the survivor predicate
# that replaces all of the above on non-survivor groups, hand-counted
# from the sieve branch of ops/pallas_sha256.py's kernel body:
#   valid mask        2 cmp + 1 and                  = 3
#   h0 select         1 where (no h1 chain at all)   = 1
#   sign-flip         1 xor                          = 1
#   predicate         1 cmp (h0b <= th)              = 1
#   OR-accumulate     1 or, skipped on the first row = 1 * (CPB-1)/CPB
# amortised once per program over cpb rows:
#   lane index i      ~5                             = 5 / CPB
#   any(surv) reduce  ~1 (one cross-lane reduce)     = 1 / CPB
SIEVE_PASS1_EPILOGUE = 3 + 1 + 1 + 1 + 1 * (CPB - 1) / CPB + (5 + 1) / CPB


class _Tr:
    """Abstract value for the folded-op count: tracks only vectorness."""

    __array_ufunc__ = None  # make numpy scalars defer to our reflected ops
    __slots__ = ("vec",)

    def __init__(self, vec: bool) -> None:
        self.vec = vec


_COUNT = [0]


def _op(*xs):
    vec = any(isinstance(x, _Tr) and x.vec for x in xs)
    if vec:
        _COUNT[0] += 1  # result is vector => one VPU op
    return _Tr(vec)


for _name in ("add", "xor", "and", "or"):
    setattr(_Tr, f"__{_name}__", lambda self, o: _op(self, o))
    setattr(_Tr, f"__r{_name}__", lambda self, o: _op(self, o))
for _name in ("lshift", "rshift"):
    setattr(_Tr, f"__{_name}__", lambda self, o: _op(self, o))


def count_vector_ops(
    data: str,
    d: int,
    k: int,
    h0_only: bool = False,
    factored_k_in: "int | None" = None,
) -> int:
    """Exact VPU op count per nonce for one full tail hash of ``data`` at
    digit count ``d`` with ``k`` in-kernel digits: the contrib-word ORs of
    the kernel's w assembly plus every vector op inside each block's
    `compress` (final block in final_only form — or its ``"h0"``
    output-mask form with ``h0_only=True``, the sieve kernel's pass 1),
    threading the state's vectorness across blocks exactly as the kernel
    does.

    Vector words mirror the PRODUCTION (digit-position-dynamic) kernel:
    every word of the dyn window is a vector (OR with a runtime contrib
    tile, zero or not), not just the d-class's own digit words — this is
    the dyn kernel's documented cost and must be in the op model or the
    sustained-throughput estimate comes out biased low.

    ``factored_k_in`` (ISSUE 14) models the per-class STATIC factored
    kernel instead: only the k_in INNER digit words are vector — the
    outer digits are per-group SMEM scalars, the pre-inner-word round
    prefix runs once per group on the scalar unit (which the tracer sees
    automatically: every all-scalar sub-expression counts zero), and
    there is no dyn window at all (ops/sweep.py ``_build_kernel`` on why
    the factored form must be static)."""
    from bitcoin_miner_tpu.ops.pallas_sha256 import dyn_params
    from bitcoin_miner_tpu.ops.sha256 import build_layout, compress

    layout = build_layout(data.encode(), d)
    if factored_k_in is not None:
        split = layout.factor(k, factored_k_in)
        cwords = {p.word for p in split.inner_pos}
    else:
        window = dyn_params(layout, k)
        if window is not None:
            cwords = set(range(window[0], window[1] + 1))
        else:  # d == k static fallback: only the digit words are vector
            cwords = {p.word for p in layout.digit_pos[layout.digit_count - k :]}
    state = tuple(_Tr(False) for _ in range(8))  # midstate scalars
    total = 0
    for b in range(layout.n_tail_blocks):
        w = []
        for widx in range(b * 16, (b + 1) * 16):
            if widx in cwords:
                total += 1  # the contrib | base assembly OR
                w.append(_Tr(True))
            else:
                w.append(_Tr(False))
        _COUNT[0] = 0
        last = b == layout.n_tail_blocks - 1
        fo = ("h0" if h0_only else True) if last else False
        state = compress(state, w, final_only=fo)
        total += _COUNT[0]
    return total


def sieve_op_report(data: str, d: int, k: int) -> dict:
    """Per-pass op accounting for the two-stage sieve kernel (ISSUE 13),
    so its claimed savings are auditable without TPU time:

    - ``pass1`` = h0-only compression + the survivor-predicate epilogue —
      what EVERY lane pays;
    - ``pass2`` = the full (h0, h1) compression + the argmin-bookkeeping
      epilogue — what lanes in SURVIVOR groups pay *again* (a vanishing
      fraction once the running min tightens: its h0 falls like
      U32_MAX / nonces_swept);
    - ``baseline`` = the current kernel (full compression + bookkeeping
      on 100% of lanes), for the steady-state comparison.
    """
    full = count_vector_ops(data, d, k)
    h0 = count_vector_ops(data, d, k, h0_only=True)
    baseline = full + EPILOGUE_OPS
    pass1 = h0 + SIEVE_PASS1_EPILOGUE
    pass2 = full + EPILOGUE_OPS
    return {
        "compress_full_ops": full,
        "compress_h0_ops": h0,
        "baseline_epilogue_ops": round(EPILOGUE_OPS, 2),
        "sieve_pass1_epilogue_ops": round(SIEVE_PASS1_EPILOGUE, 2),
        "baseline_ops_per_lane": round(baseline, 2),
        "sieve_pass1_ops_per_lane": round(pass1, 2),
        "sieve_pass2_ops_per_lane": round(pass2, 2),
        # Steady state (survivor fraction -> 0): pass 1 is the whole cost.
        "sieve_steady_state_savings": round(1 - pass1 / baseline, 4),
    }


def factored_op_report(data: str, d: int, k: int) -> dict:
    """Per-pass op accounting for the FACTORED kernel (ISSUE 14) at the
    default inner split (ops/sweep.py ``default_factor_k_in``), so the
    claimed compression-side savings are auditable without TPU time.

    The factored epilogue is op-for-op the baseline's (same valid mask —
    the per-group bounds are scalar-clipped host bounds — same selects,
    flips, idx add and running-min fold; the outer-digit patching, scalar
    round prefix and group bookkeeping all live on the scalar unit), so
    EPILOGUE_OPS / SIEVE_PASS1_EPILOGUE carry over unchanged and the
    whole delta is the compression + assembly count: the inner-word-only
    vector set drops the flagship 1-block shape from 3002 to 2910
    (h0-only 3001 → 2909).  The reduction is reported against BOTH the
    unfactored baseline and the PR-13 sieve pass-1 count — the
    acceptance yardstick (3008.6 ops/lane on the flagship shape).
    """
    from bitcoin_miner_tpu.ops.sweep import default_factor_k_in

    k_in = default_factor_k_in(k)
    base = sieve_op_report(data, d, k)
    full = count_vector_ops(data, d, k, factored_k_in=k_in)
    h0 = count_vector_ops(data, d, k, h0_only=True, factored_k_in=k_in)
    f_plain = full + EPILOGUE_OPS
    f_pass1 = h0 + SIEVE_PASS1_EPILOGUE
    return {
        "k_in": k_in,
        "k_out": k - k_in,
        "compress_full_ops": full,
        "compress_h0_ops": h0,
        "factored_ops_per_lane": round(f_plain, 2),
        "factored_sieve_pass1_ops_per_lane": round(f_pass1, 2),
        # vs the unfactored kernels of the same sieve mode:
        "savings_vs_baseline": round(
            1 - f_plain / base["baseline_ops_per_lane"], 4
        ),
        "savings_vs_sieve_pass1": round(
            1 - f_pass1 / base["sieve_pass1_ops_per_lane"], 4
        ),
    }


def _rate(data: str, n: int) -> float:
    from bitcoin_miner_tpu.ops.sweep import sweep_min_hash

    base = 10**9
    sweep_min_hash(data, base, base + 10**6 - 1, max_k=MAX_K)  # compile
    t0 = time.perf_counter()
    r = sweep_min_hash(data, base, base + n - 1, max_k=MAX_K)
    dt = time.perf_counter() - t0
    assert r.lanes_swept == n
    return n / dt


def main() -> int:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--ops-only",
        action="store_true",
        help="print only the static per-pass op accounting for the sieve "
        "kernel (no device measurement — runs anywhere, incl. CI)",
    )
    args = ap.parse_args()

    if args.ops_only:
        rep = sieve_op_report(DATA_1BLK, 10, MAX_K)
        rep2 = sieve_op_report(DATA_2BLK, 10, MAX_K)
        frep = factored_op_report(DATA_1BLK, 10, MAX_K)
        frep2 = factored_op_report(DATA_2BLK, 10, MAX_K)
        print(
            f"sieve op accounting ({DATA_1BLK!r}, d=10, k={MAX_K}): pass 1 "
            f"{rep['sieve_pass1_ops_per_lane']} ops/lane vs baseline "
            f"{rep['baseline_ops_per_lane']} -> steady-state savings "
            f"{rep['sieve_steady_state_savings']:.1%} (pass 2 on survivor "
            f"groups: {rep['sieve_pass2_ops_per_lane']} more)",
            file=sys.stderr,
        )
        print(
            f"factored op accounting (k_in={frep['k_in']}): sieve pass 1 "
            f"{frep['factored_sieve_pass1_ops_per_lane']} ops/lane vs the "
            f"unfactored {rep['sieve_pass1_ops_per_lane']} -> "
            f"{frep['savings_vs_sieve_pass1']:.1%} off the compression "
            f"plateau (plain kernel {frep['factored_ops_per_lane']} vs "
            f"{rep['baseline_ops_per_lane']}, "
            f"{frep['savings_vs_baseline']:.1%})",
            file=sys.stderr,
        )
        print(
            json.dumps(
                {
                    "metric": "sieve_op_report",
                    "shape_1blk": {"data": DATA_1BLK, "d": 10, "k": MAX_K, **rep},
                    "shape_2blk": {"data": DATA_2BLK, "d": 10, "k": MAX_K, **rep2},
                    "factored_1blk": {
                        "data": DATA_1BLK, "d": 10, "k": MAX_K, **frep,
                    },
                    "factored_2blk": {
                        "data": DATA_2BLK, "d": 10, "k": MAX_K, **frep2,
                    },
                }
            )
        )
        return 0

    import jax

    from bitcoin_miner_tpu.ops.sha256 import build_layout

    assert build_layout(DATA_1BLK.encode(), 10).n_tail_blocks == 1
    lay2 = build_layout(DATA_2BLK.encode(), 10)
    assert lay2.n_tail_blocks == 2
    # Both blocks must carry low-digit words or block 0 folds to scalars.
    low_words = {p.word for p in lay2.digit_pos[lay2.digit_count - MAX_K :]}
    assert min(low_words) < 16 <= max(low_words), low_words

    ops1 = count_vector_ops(DATA_1BLK, 10, MAX_K)
    ops2 = count_vector_ops(DATA_2BLK, 10, MAX_K)

    dev = jax.devices()[0]
    kind = (getattr(dev, "device_kind", "") or dev.platform)
    n = 2 * 10**9
    r1 = _rate(DATA_1BLK, n)
    r2 = _rate(DATA_2BLK, n)
    c = 1 / r2 - 1 / r1  # seconds per nonce for the marginal (ops2-ops1)
    # A non-positive marginal means a degenerate measurement (e.g. the
    # dispatch-caching hazard above) — refuse to publish nonsense numbers.
    assert c > 0, (r1, r2)
    sustained = (ops2 - ops1) / c
    ceiling = sustained / (ops1 + EPILOGUE_OPS)
    headroom = ceiling / r1 - 1
    print(
        f"device={kind}  exact folded ops: 1blk {ops1} + {EPILOGUE_OPS:.1f} "
        f"epilogue, 2blk {ops2} (marginal {ops2 - ops1})\n"
        f"1blk {r1 / 1e9:.3f}e9 n/s  2blk {r2 / 1e9:.3f}e9 n/s  "
        f"marginal {c * 1e9:.3f} ns -> sustained {sustained / 1e12:.2f} T "
        f"u32-ops/s; 1blk compute ceiling {ceiling / 1e9:.2f}e9 n/s "
        f"(headroom over current rate {headroom:.0%})",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": "vpu_u32_ops_per_sec_sustained",
                "value": round(sustained),
                "ops_1blk": ops1,
                "ops_2blk": ops2,
                "epilogue_ops": round(EPILOGUE_OPS, 2),
                "rate_1blk": round(r1),
                "rate_2blk": round(r2),
                "marginal_ns": round(c * 1e9, 4),
                "ceiling_1blk": round(ceiling),
                "headroom": round(headroom, 4),
                "device_kind": kind,
                # Per-pass sieve accounting for the flagship shape: what
                # the measured rate's op model becomes with the sieve on.
                "sieve": sieve_op_report(DATA_1BLK, 10, MAX_K),
                # And the factored form's (ISSUE 14) — the compression-
                # side lever the sieve audit named as the real plateau.
                "factored": factored_op_report(DATA_1BLK, 10, MAX_K),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
