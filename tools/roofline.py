"""Empirical VPU roofline for the Pallas SHA-256 sweep (VERDICT r3 item 2).

The sweep kernel is pure elementwise uint32 work on the VPU (the MXU is
useless for SHA — SURVEY §7 hard-part 2), so its ceiling is the chip's
sustained u32 ALU rate, not FLOPs or HBM.  This tool measures that rate
with a Pallas kernel whose op mix mirrors one SHA round — serially
dependent chains of shift/or/xor/add over 8 independent state registers
(the a..h analogue, the same ILP the real kernel exposes) — and divides by
the real kernel's op count to print the nonces/s ceiling.

Static op accounting of the real kernel (ops/pallas_sha256.py, one tail
block, k in-kernel digits):

  per round t=0..63:   s1e 11 + ch 3 + t1 4 + s0a 11 + maj 4 + t2 1
                       + e-add 1 + a-add 1                    = 36 ops
  schedule t=16..63:   s0 9 + s1 9 + 3 adds                   = 21 ops
  epilogue/assembly:   state add 8 + w-OR/broadcast ~16
                       + mask/min reduction ~16               ~ 40 ops

  -> 64*36 + 48*21 + 40 = 3352 u32 ops/nonce  (x tail blocks)

Usage: python tools/roofline.py   (on the TPU; prints one JSON line)
"""

from __future__ import annotations

import functools
import json
import sys
import time

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

OPS_PER_NONCE_1BLOCK = 64 * 36 + 48 * 21 + 40  # see module docstring

# One probe iteration = 8 parallel chains x 8 ops (shl, shr, or, xor, add,
# shl, shr, or) — the rotr+mix micro-pattern; each chain serially dependent
# like the SHA state recurrence.
OPS_PER_ITER = 8 * 8


@functools.lru_cache(maxsize=4)
def _make_probe(n_iters: int, tile: int, grid: int):
    sub = tile // 128

    def kernel(seed_ref, out_ref):
        # 8 independent serial chains, like SHA's a..h registers.  The
        # program id feeds every chain — without it all grid programs are
        # byte-identical (constant index maps, no id dependence) and the
        # compiler collapses the grid to one program's work.
        pid = pl.program_id(0).astype(jnp.uint32)
        # Every element distinct (row and column iota): a sublane-uniform
        # tensor gets a replicated Mosaic layout and is computed on one
        # sublane — 64x less work than the probe claims.
        lane = jax.lax.broadcasted_iota(
            jnp.uint32, (sub, 128), 0
        ) * jnp.uint32(131) + jax.lax.broadcasted_iota(jnp.uint32, (sub, 128), 1)
        s = tuple(
            jnp.full((sub, 128), seed_ref[i] + pid, dtype=jnp.uint32) + lane
            for i in range(8)
        )

        def rot_mix(x, c):
            r = (x << jnp.uint32(13)) | (x >> jnp.uint32(19))  # 3 ops
            x = (x ^ r) + c                                    # 2 ops
            return (x << jnp.uint32(7)) | (x >> jnp.uint32(25))  # 3 ops

        # 64 iterations unrolled per loop trip: the real kernel is one
        # straight-line 64-round block, and Mosaic only reaches peak issue
        # rate on unrolled code — a tiny fori_loop body measures loop
        # overhead, not the VPU (6x low on this chip).
        UNROLL = 64
        assert n_iters % UNROLL == 0

        def body(t, s):
            c = t.astype(jnp.uint32)
            for u in range(UNROLL):
                cu = c + jnp.uint32(u * 8)
                s = tuple(rot_mix(x, cu + jnp.uint32(i)) for i, x in enumerate(s))
            return s

        s = jax.lax.fori_loop(0, n_iters // UNROLL, body, s)
        acc = s[0]
        for x in s[1:]:
            acc = acc ^ x
        # Mosaic has no unsigned reductions; reduce in the int32 bitcast.
        # Accumulate across programs (grid programs run sequentially, like
        # the real kernel's SMEM min-fold) — a plain overwrite would leave
        # every program but the last dead and free to be skipped.
        local = jnp.max(jax.lax.bitcast_convert_type(acc, jnp.int32))

        @pl.when(pid == 0)
        def _init():
            out_ref[0] = local

        @pl.when(pid != 0)
        def _fold():
            out_ref[0] = out_ref[0] ^ local

    call = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
        out_shape=jax.ShapeDtypeStruct((1,), jnp.int32),
    )
    return jax.jit(lambda seed: call(seed))


def measure_peak(n_iters: int = 8192, tile: int = 8192, grid: int = 1024):
    """Sustained u32 elementwise ops/s with the SHA-like mix.

    Every call gets a DISTINCT seed: the tunnelled TPU backend returns
    cached results for byte-identical (executable, args) re-executions, so
    repeating one input measures RPC latency, not compute.  Per-call work
    is sized ~1 s so the ~15 ms dispatch overhead is noise.
    """
    probe = _make_probe(n_iters, tile, grid)
    probe(jnp.arange(8, dtype=jnp.uint32))[0].block_until_ready()  # compile
    reps = 3
    seeds = [
        jnp.arange(8, dtype=jnp.uint32) + jnp.uint32(1 + r) for r in range(reps)
    ]
    t0 = time.perf_counter()
    for s in seeds:
        out = probe(s)
    out[0].block_until_ready()
    dt = (time.perf_counter() - t0) / reps
    total_ops = grid * tile * n_iters * OPS_PER_ITER
    return total_ops / dt, dt


def main() -> int:
    dev = jax.devices()[0]
    ops_per_s, dt = measure_peak()
    ceiling = ops_per_s / OPS_PER_NONCE_1BLOCK
    print(
        f"device={dev.device_kind or dev.platform}  probe {dt * 1e3:.1f} ms"
        f"  sustained {ops_per_s / 1e12:.2f} T u32-ops/s",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": "vpu_u32_ops_per_sec",
                "value": round(ops_per_s),
                "ops_per_nonce": OPS_PER_NONCE_1BLOCK,
                "nonces_per_sec_ceiling": round(ceiling),
                "device_kind": getattr(dev, "device_kind", "") or dev.platform,
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
