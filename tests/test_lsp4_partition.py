"""Suite 4 parity: buffering across network partitions
(reference lsp/lsp4_test.go).

A partition is faked by flipping the global write-drop knob to 100% on both
sides (the network-toggler goroutine, lsp4_test.go:113-139).  LSP's send
buffers must hold everything written during the partition and flush it, in
order, once the network heals:

The reference's 12-scenario matrix (4 choreographies x 3 scales, up to
5 clients x 500 msgs) is mirrored in full:

- TestServerFastClose1-3 (:444-463): Close while the network is down must
  still drain once it returns.
- TestClientToServer / TestServerToClient1-3 (:465-505): bulk streams
  written entirely during a partition arrive in order after heal.
- TestRoundTrip1-3 (:507-526): buffered echo traffic across partitions.
"""

import time

import pytest

from bitcoin_miner_tpu import lsp, lspnet
from lsp_harness import spawn

EPOCH_MS = 100


def params(limit=60, w=32):
    # Generous epoch limit so connections survive the partitions.
    return lsp.Params(epoch_limit=limit, epoch_millis=EPOCH_MS, window_size=w)


@pytest.fixture(autouse=True)
def _reset_faults():
    lspnet.reset_faults()
    yield
    lspnet.reset_faults()


def partition(on: bool) -> None:
    lspnet.set_write_drop_percent(100 if on else 0)


def collecting_server(p):
    server = lsp.Server(0, p)
    received = []

    def loop():
        while True:
            try:
                _cid, payload = server.read()
                received.append(payload)
            except lsp.ConnLostError:
                continue
            except lsp.LspError:
                return

    spawn(loop)
    return server, received


# The reference runs each choreography at three scales (lsp4_test.go:444-526):
# 1 client x 10 msgs, 3 x 10, and 5 x 500 — mirrored here so the scenario
# matrix matches the reference suite's 12 entries.
MATRIX = [(1, 10), (3, 10), (5, 500)]


def _warm_up_clients(server, p, n_clients):
    """Connect n clients, learn each one's conn id via a warm-up message."""
    clients = [lsp.Client("127.0.0.1", server.port, p) for _ in range(n_clients)]
    cid_by_idx = {}
    for idx, c in enumerate(clients):
        c.write(b"warm%d" % idx)
    for _ in range(n_clients):
        cid, payload = server.read()
        cid_by_idx[int(payload[4:])] = cid
    return clients, cid_by_idx


@pytest.mark.parametrize("n_clients,n_msgs", MATRIX)
def test_client_to_server_bulk_during_partition(n_clients, n_msgs):
    """Streams written entirely during a partition arrive in order after
    heal (lsp4_test.go TestClientToServer1-3)."""
    p = params()
    server = lsp.Server(0, p)
    clients, cid_by_idx = _warm_up_clients(server, p, n_clients)
    received = {cid: [] for cid in cid_by_idx.values()}

    def collect():
        while True:
            try:
                cid, payload = server.read()
                received[cid].append(payload)
            except lsp.ConnLostError:
                continue
            except lsp.LspError:
                return

    spawn(collect)
    partition(True)
    for c in clients:
        for i in range(n_msgs):
            c.write(b"p%d" % i)
    time.sleep(3 * EPOCH_MS / 1000)  # a few epochs of darkness
    assert all(not msgs for msgs in received.values()), received
    partition(False)

    want = [b"p%d" % i for i in range(n_msgs)]
    deadline = time.time() + max(50, n_msgs) * EPOCH_MS / 1000
    while (
        any(len(m) < n_msgs for m in received.values())
        and time.time() < deadline
    ):
        time.sleep(0.02)
    for idx, cid in cid_by_idx.items():
        assert received[cid] == want, f"client {idx} stream wrong"
    for c in clients:
        c.close()
    server.close()


@pytest.mark.parametrize("n_clients,n_msgs", MATRIX)
def test_server_to_client_bulk_during_partition(n_clients, n_msgs):
    """Server streams buffered during a partition arrive in order after
    heal (lsp4_test.go TestServerToClient1-3)."""
    p = params()
    server = lsp.Server(0, p)
    clients, cid_by_idx = _warm_up_clients(server, p, n_clients)
    got = {idx: [] for idx in range(n_clients)}

    def reader(idx, c):
        while True:
            try:
                got[idx].append(c.read())
            except lsp.LspError:
                return

    readers = [spawn(lambda i=i, c=c: reader(i, c)) for i, c in enumerate(clients)]

    partition(True)
    for idx in range(n_clients):
        for i in range(n_msgs):
            server.write(cid_by_idx[idx], b"p%d" % i)
    time.sleep(3 * EPOCH_MS / 1000)
    assert all(not msgs for msgs in got.values()), got
    partition(False)

    want = [b"p%d" % i for i in range(n_msgs)]
    deadline = time.time() + max(50, n_msgs) * EPOCH_MS / 1000
    while any(len(m) < n_msgs for m in got.values()) and time.time() < deadline:
        time.sleep(0.02)
    for idx in range(n_clients):
        assert got[idx] == want, f"client {idx} stream wrong"
    for c in clients:
        c.close()
    server.close()
    for r in readers:
        r.join(timeout=5)


def test_client_fast_close_flushes_after_heal():
    """Close during a partition blocks, then completes once the network
    returns — and every message makes it (lsp4_test.go:444-463)."""
    p = params()
    server, received = collecting_server(p)
    client = lsp.Client("127.0.0.1", server.port, p)

    partition(True)
    total = 30
    for i in range(total):
        client.write(b"f%d" % i)

    close_done = []

    def closer():
        client.close()
        close_done.append(time.time())

    t = spawn(closer)
    time.sleep(3 * EPOCH_MS / 1000)
    assert not close_done, "close returned during the partition"
    partition(False)
    t.join(timeout=50 * EPOCH_MS / 1000)
    assert close_done, "close never completed after heal"

    want = [b"f%d" % i for i in range(total)]
    deadline = time.time() + 10
    while len(received) < total and time.time() < deadline:
        time.sleep(0.02)
    assert received == want
    server.close()


def _server_fast_close(n_clients: int, n_msgs: int) -> None:
    """Server writes to every client during a partition, then calls Close
    while the network is still down; Close must block until the heal lets
    everything drain, and every client must receive its full stream in
    order (lsp4_test.go:444-463 TestServerFastClose1-3)."""
    p = params()
    server = lsp.Server(0, p)
    clients = []
    got = {}

    def reader(idx, c):
        while True:
            try:
                got[idx].append(c.read())
            except lsp.LspError:
                return

    for idx in range(n_clients):
        c = lsp.Client("127.0.0.1", server.port, p)
        clients.append(c)
        got[idx] = []
        c.write(b"warm%d" % idx)

    # Learn each conn's id from its warm-up message.
    cid_by_idx = {}
    for _ in range(n_clients):
        cid, payload = server.read()
        cid_by_idx[int(payload[4:])] = cid
    readers = [spawn(lambda i=i, c=c: reader(i, c)) for i, c in enumerate(clients)]

    partition(True)
    want = [b"s%d" % i for i in range(n_msgs)]
    for idx in range(n_clients):
        for m in want:
            server.write(cid_by_idx[idx], m)

    close_done = []

    def closer():
        server.close()
        close_done.append(time.time())

    t = spawn(closer)
    time.sleep(3 * EPOCH_MS / 1000)
    assert not close_done, "server Close returned during the partition"
    for g in got.values():
        assert g == [], "data leaked through the partition"
    partition(False)
    t.join(timeout=100 * EPOCH_MS / 1000)
    assert close_done, "server Close never completed after heal"

    deadline = time.time() + 20
    while any(len(g) < n_msgs for g in got.values()) and time.time() < deadline:
        time.sleep(0.02)
    for idx in range(n_clients):
        assert got[idx] == want, f"client {idx} stream wrong"
    for c in clients:
        try:
            c.close()
        except lsp.LspError:
            pass
    for r in readers:
        r.join(timeout=5)


def test_server_fast_close_single_client():
    _server_fast_close(1, 10)


def test_server_fast_close_three_clients():
    _server_fast_close(3, 10)


def test_server_fast_close_five_clients_bulk():
    # TestServerFastClose3 scale: 5 clients x 500 messages.
    _server_fast_close(5, 500)


@pytest.mark.parametrize("n_clients,n_msgs", MATRIX)
def test_round_trip_buffered_both_ways(n_clients, n_msgs):
    """Buffered messages in client AND server across two partition phases
    (lsp4_test.go TestRoundTrip1-3): clients write their whole stream into
    a dead network; after heal the echo replies flow back; nothing leaks
    through the partition early."""
    p = params()
    server = lsp.Server(0, p)

    def echo_loop():
        while True:
            try:
                cid, payload = server.read()
                server.write(cid, payload)
            except lsp.ConnLostError:
                continue
            except lsp.LspError:
                return

    spawn(echo_loop)
    clients = [lsp.Client("127.0.0.1", server.port, p) for _ in range(n_clients)]
    got = {idx: [] for idx in range(n_clients)}

    def reader(idx, c):
        while True:
            try:
                got[idx].append(c.read())
            except lsp.LspError:
                return

    readers = [spawn(lambda i=i, c=c: reader(i, c)) for i, c in enumerate(clients)]

    partition(True)
    want = [b"rt%d" % i for i in range(n_msgs)]
    for c in clients:
        for m in want:
            c.write(m)
    time.sleep(3 * EPOCH_MS / 1000)
    assert all(not msgs for msgs in got.values()), "echo leaked through partition"
    partition(False)

    deadline = time.time() + max(80, 2 * n_msgs) * EPOCH_MS / 1000
    while any(len(m) < n_msgs for m in got.values()) and time.time() < deadline:
        time.sleep(0.02)
    for idx in range(n_clients):
        assert got[idx] == want, f"client {idx} echo stream wrong"
    for c in clients:
        c.close()
    server.close()
    for r in readers:
        r.join(timeout=5)


def test_round_trip_across_partitions():
    """Echo traffic while the network flaps (lsp4_test.go:507-526)."""
    p = params()
    server = lsp.Server(0, p)

    def echo_loop():
        while True:
            try:
                cid, payload = server.read()
                server.write(cid, payload)
            except lsp.ConnLostError:
                continue
            except lsp.LspError:
                return

    spawn(echo_loop)
    client = lsp.Client("127.0.0.1", server.port, p)

    flapping = True

    def toggler():
        on = False
        while flapping:
            partition(on)
            on = not on
            time.sleep(1.5 * EPOCH_MS / 1000)
        partition(False)

    t = spawn(toggler)
    try:
        for i in range(30):
            msg = b"rt%d" % i
            client.write(msg)
            assert client.read() == msg
    finally:
        flapping = False
        t.join(timeout=2)
    client.close()
    server.close()
