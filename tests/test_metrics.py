"""Observability counters (SURVEY §5: nonces/sec, retransmits, reassignment)."""

import time

from bitcoin_miner_tpu import lsp, lspnet
from bitcoin_miner_tpu.apps.scheduler import Scheduler
from bitcoin_miner_tpu.utils.metrics import METRICS, Metrics, RateMeter


def test_counter_basics():
    m = Metrics()
    m.inc("a")
    m.inc("a", 4)
    assert m.get("a") == 5
    assert m.snapshot() == {"a": 5}
    m.reset()
    assert m.get("a") == 0


def test_rate_meter():
    t = [0.0]
    r = RateMeter(clock=lambda: t[0])
    r.add(100)
    t[0] = 2.0
    assert r.rate() == 50.0


def test_scheduler_counters():
    base = METRICS.snapshot()
    s = Scheduler(validate_results=False, min_chunk=100)
    s.miner_joined(1)
    s.client_request(10, "d", 0, 99)
    s.lost(1)          # chunk goes back to pending
    s.miner_joined(2)  # and is reassigned
    s.result(2, hash_=5, nonce=5)
    snap = METRICS.snapshot()
    assert snap.get("sched.chunks_assigned", 0) - base.get("sched.chunks_assigned", 0) == 2
    assert snap.get("sched.chunks_reassigned", 0) - base.get("sched.chunks_reassigned", 0) == 1
    assert snap.get("sched.jobs_completed", 0) - base.get("sched.jobs_completed", 0) == 1


def test_lsp_retransmit_counter():
    base = METRICS.get("lsp.retransmits")
    params = lsp.Params(epoch_limit=10, epoch_millis=100, window_size=2)
    server = lsp.Server(0, params)
    client = lsp.Client("127.0.0.1", server.port, params)
    try:
        lspnet.set_client_write_drop_percent(100)  # data vanishes -> retransmit
        client.write(b"doomed")
        time.sleep(0.35)  # a few epochs of resends into the void
        lspnet.reset_faults()
        cid, payload = server.read()  # a retransmit finally lands
        assert payload == b"doomed"
        assert METRICS.get("lsp.retransmits") > base
    finally:
        lspnet.reset_faults()
        client.close()
        server.close()
