"""Observability counters (SURVEY §5: nonces/sec, retransmits, reassignment)."""

import time

import pytest

from bitcoin_miner_tpu import lsp, lspnet
from bitcoin_miner_tpu.apps.scheduler import Scheduler
from bitcoin_miner_tpu.utils.metrics import (
    METRICS,
    Histogram,
    Metrics,
    RateMeter,
    format_quantiles,
)


def test_counter_basics():
    m = Metrics()
    m.inc("a")
    m.inc("a", 4)
    assert m.get("a") == 5
    assert m.snapshot() == {"a": 5}
    m.reset()
    assert m.get("a") == 0


def test_empty_histogram_renders_dashes_not_zero():
    """ISSUE 7 satellite regression: a histogram with ZERO samples must
    render its quantiles as ``-`` on the health line / dashboard — its
    ``snapshot()`` p50/p95/p99 are numerically 0, and printing those
    reads as "instant" when the truth is "no data"."""
    h = Histogram()
    assert h.snapshot()["p50"] == 0.0  # the misleading raw number
    assert format_quantiles(h) == "-/-/-"
    assert format_quantiles(None) == "-/-/-"  # absent histogram too
    assert format_quantiles(h.snapshot()) == "-/-/-"  # snapshot-dict form
    h.observe(1.0)
    rendered = format_quantiles(h)
    assert "-" not in rendered and rendered.count("/") == 2
    # a populated zero bucket is REAL data: 0 is then the honest render
    z = Histogram()
    z.observe(0.0)
    assert format_quantiles(z) == "0/0/0"


def test_rate_meter():
    t = [0.0]
    r = RateMeter(clock=lambda: t[0])
    r.add(100)
    t[0] = 2.0
    assert r.rate() == 50.0


def test_rate_meter_sliding_window_forgets_stale_bursts():
    """window=N: rate() is the RECENT rate — a burst older than the window
    (pre-reconnect throughput, say) no longer props the number up."""
    t = [0.0]
    r = RateMeter(clock=lambda: t[0], window=10.0)
    r.add(1000)  # ancient burst
    t[0] = 100.0
    r.add(50)
    t[0] = 105.0
    r.add(50)
    # Window covers [95, 105]: only the two 50s count -> 100/10s.
    assert r.rate() == pytest.approx(10.0)
    # The lifetime average still sees everything (bench JSON number).
    assert r.lifetime() == pytest.approx(1100 / 105.0)


def test_rate_meter_window_normalizes_by_elapsed_at_startup():
    # 2 s into a 10 s window, 100 events is 50/s, not 100/window.
    t = [0.0]
    r = RateMeter(clock=lambda: t[0], window=10.0)
    t[0] = 2.0
    r.add(100)
    assert r.rate() == pytest.approx(50.0)


def test_rate_meter_window_memory_is_bounded():
    t = [5.0]
    r = RateMeter(clock=lambda: t[0], window=10.0)
    for i in range(100_000):  # a hot add loop inside one window
        r.add(1)
    assert len(r._events) <= 65  # bucketed, not one entry per add
    t[0] = 10.0
    assert r.rate() == pytest.approx(100_000 / 5.0)


def test_scheduler_counters():
    base = METRICS.snapshot()
    s = Scheduler(validate_results=False, min_chunk=100)
    s.miner_joined(1)
    s.client_request(10, "d", 0, 99)
    s.lost(1)          # chunk goes back to pending
    s.miner_joined(2)  # and is reassigned
    s.result(2, hash_=5, nonce=5)
    snap = METRICS.snapshot()
    assert snap.get("sched.chunks_assigned", 0) - base.get("sched.chunks_assigned", 0) == 2
    assert snap.get("sched.chunks_reassigned", 0) - base.get("sched.chunks_reassigned", 0) == 1
    assert snap.get("sched.jobs_completed", 0) - base.get("sched.jobs_completed", 0) == 1


def test_lsp_retransmit_counter():
    base = METRICS.get("lsp.retransmits")
    params = lsp.Params(epoch_limit=10, epoch_millis=100, window_size=2)
    server = lsp.Server(0, params)
    client = lsp.Client("127.0.0.1", server.port, params)
    try:
        lspnet.set_client_write_drop_percent(100)  # data vanishes -> retransmit
        client.write(b"doomed")
        time.sleep(0.35)  # a few epochs of resends into the void
        lspnet.reset_faults()
        cid, payload = server.read()  # a retransmit finally lands
        assert payload == b"doomed"
        assert METRICS.get("lsp.retransmits") > base
    finally:
        lspnet.reset_faults()
        client.close()
        server.close()
