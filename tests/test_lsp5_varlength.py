"""Suite 5 parity: variable-length / corrupt payload handling via the
``Size`` field (reference lsp/lsp5_test.go).

The lspnet mutator rewrites Data payloads in flight while keeping ``Size``
intact (lspnet/conn.go:119-146):

- LONG mode (lengthening 100%): payloads arrive with len > Size; the
  receiver must truncate to exactly Size bytes (lsp5_test.go:40-62).
- SHORT mode (shortening 100%): payloads arrive with len < Size; the
  receiver must never surface them to Read (lsp5_test.go:64-85).

The reference implementation itself never validated Size (SURVEY §8.5);
the tests define the required behavior, which this transport implements.
"""

import time

import pytest

from bitcoin_miner_tpu import lsp, lspnet
from bitcoin_miner_tpu.lsp.conn import ConnCore
from lsp_harness import spawn

EPOCH_MS = 100
PARAMS = lsp.Params(epoch_limit=5, epoch_millis=EPOCH_MS, window_size=4)


@pytest.fixture(autouse=True)
def _reset_faults():
    lspnet.reset_faults()
    yield
    lspnet.reset_faults()


def test_lengthened_messages_truncated_to_size_server_side():
    server = lsp.Server(0, PARAMS)
    client = lsp.Client("127.0.0.1", server.port, PARAMS)
    lspnet.set_msg_lengthening_percent(100)
    for i in range(10):
        msg = b"value-%d" % i
        client.write(msg)
        cid, payload = server.read()
        # Mutator appended bytes, receiver must truncate to Size exactly.
        assert payload == msg
    lspnet.reset_faults()
    client.close()
    server.close()


def test_lengthened_messages_truncated_to_size_client_side():
    server = lsp.Server(0, PARAMS)
    client = lsp.Client("127.0.0.1", server.port, PARAMS)
    client.write(b"hello")
    cid, _ = server.read()
    lspnet.set_msg_lengthening_percent(100)
    for i in range(10):
        msg = b"value-%d" % i
        server.write(cid, msg)
        assert client.read() == msg
    lspnet.reset_faults()
    client.close()
    server.close()


def test_shortened_messages_never_surface():
    server = lsp.Server(0, PARAMS)
    client = lsp.Client("127.0.0.1", server.port, PARAMS)
    surfaced = []

    def server_loop():
        while True:
            try:
                surfaced.append(server.read()[1])
            except lsp.ConnLostError:
                continue
            except lsp.LspError:
                return

    spawn(server_loop)
    lspnet.set_msg_shortening_percent(100)
    for i in range(5):
        client.write(b"secret-%d" % i)
    # Several epochs of retransmission: every copy is shortened in flight,
    # so nothing may ever reach the application.
    time.sleep(4 * EPOCH_MS / 1000)
    assert surfaced == [], surfaced
    lspnet.reset_faults()
    # After the network stops corrupting, retransmits deliver everything.
    deadline = time.time() + 30 * EPOCH_MS / 1000
    while len(surfaced) < 5 and time.time() < deadline:
        time.sleep(0.02)
    assert surfaced == [b"secret-%d" % i for i in range(5)]
    client.close()
    server.close()


def test_short_single_byte_payload_edge():
    """A 1-byte payload halves to 0 bytes — still len < Size, still dropped."""
    server = lsp.Server(0, PARAMS)
    client = lsp.Client("127.0.0.1", server.port, PARAMS)
    lspnet.set_msg_shortening_percent(100)
    client.write(b"x")
    time.sleep(3 * EPOCH_MS / 1000)
    lspnet.reset_faults()
    cid, payload = server.read()
    assert payload == b"x"
    client.close()
    server.close()


def test_negative_size_dropped_not_truncated():
    """A crafted Data with Size < 0 must be dropped entirely — a Python
    negative-index truncation (payload[:Size]) would otherwise deliver a
    mangled prefix AND consume the seq, poisoning the real retransmission."""
    sent, delivered = [], []
    core = ConnCore(1, PARAMS, sent.append, delivered.append)
    core.on_data(lsp.Message.data(1, 1, -3, b"hello"))
    assert delivered == [] and sent == []  # no delivery, no ack
    # The genuine seq-1 message must still go through afterwards.
    core.on_data(lsp.Message.data(1, 1, 5, b"hello"))
    assert delivered == [b"hello"]
