"""End-to-end request tracing + latency telemetry (ISSUE 6).

- Histogram unit/property tests: merge associativity, quantile bounds
  against sorted samples, the zero bucket.
- Tracer primitives: off-path no-op, ring overflow accounting, JSONL
  flush round-trip.
- Event-level trace propagation through gateway + scheduler: full sweep,
  coalescing fan-out, span-store partial-coverage planning, admission
  queue wait, shed, orphan/resume — each yields exactly one complete
  tree per original request, no orphan spans.
- Tier-1 e2e: a loopback fleet served with tracing armed, its file
  reconstructed by ``python -m tools.trace --json --strict`` into
  complete timelines with non-zero stage durations; and a seeded chaos
  drill whose trace reconstructs with no orphan spans.
"""

from __future__ import annotations

import json
import random
import sys
import threading
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:  # make `tools.trace` importable in-process
    sys.path.insert(0, str(REPO))

from tools.trace import RequestTree, build, load  # noqa: E402
from tools.trace.__main__ import main as trace_main  # noqa: E402

from bitcoin_miner_tpu.apps.scheduler import Scheduler
from bitcoin_miner_tpu.bitcoin.hash import min_hash_range
from bitcoin_miner_tpu.gateway import Gateway, ResultCache, SpanStore
from bitcoin_miner_tpu.utils import trace
from bitcoin_miner_tpu.utils.metrics import METRICS, Histogram, Metrics

pytestmark = pytest.mark.trace

_GROWTH = 2 ** 0.25  # the histogram bucket growth factor


@pytest.fixture(autouse=True)
def _tracer_hygiene():
    """Every test starts and ends with tracing disarmed and drained."""
    trace.TRACE.disable()
    trace.TRACE.drain()
    yield
    trace.TRACE.disable()
    trace.TRACE.drain()


# --------------------------------------------------------------------------
# 1. Histogram properties
# --------------------------------------------------------------------------


@pytest.mark.parametrize("seed", [1, 7, 42])
def test_histogram_quantile_bounded_by_sorted_samples(seed):
    """The estimate is the upper edge of the bucket holding the q-th
    sample, so: true quantile <= estimate < true quantile * growth."""
    rng = random.Random(seed)
    samples = [rng.lognormvariate(0.0, 2.0) for _ in range(500)]
    h = Histogram()
    for s in samples:
        h.observe(s)
    ordered = sorted(samples)
    for q in (0.01, 0.5, 0.9, 0.95, 0.99, 1.0):
        true = ordered[min(len(ordered) - 1, max(0, -(-int(q * 500)) - 1))]
        est = h.quantile(q)
        assert true <= est * (1 + 1e-12), (q, true, est)
        assert est <= true * _GROWTH * (1 + 1e-9), (q, true, est)


def test_histogram_merge_is_associative_and_commutative():
    rng = random.Random(3)
    parts = []
    for _ in range(3):
        h = Histogram()
        for _ in range(200):
            h.observe(rng.expovariate(1.0))
        h.observe(0.0)  # exercise the zero bucket through merges too
        parts.append(h)

    def merged(order):
        out = Histogram()
        for i in order:
            out.merge(parts[i])
        return out

    a = merged([0, 1, 2])
    b = merged([2, 0, 1])
    c = Histogram()
    ab = Histogram()
    ab.merge(parts[0])
    ab.merge(parts[1])
    c.merge(ab)
    c.merge(parts[2])
    for other in (b, c):
        assert a.buckets() == other.buckets()
        assert a.zero_count() == other.zero_count()
        assert a.count() == other.count()
        for q in (0.5, 0.95, 0.99):
            assert a.quantile(q) == other.quantile(q)


def test_histogram_zero_bucket_and_empty():
    h = Histogram()
    assert h.quantile(0.5) == 0.0  # empty
    h.observe(0.0)
    h.observe(-1.0)  # clamped into the zero bucket, not an error
    assert h.count() == 2
    assert h.zero_count() == 2
    assert h.quantile(0.99) == 0.0
    h.observe(4.0)
    assert h.quantile(0.5) == 0.0  # rank 2 of 3 still in the zero bucket
    assert h.quantile(1.0) >= 4.0


def test_histogram_mean_and_snapshot_shape():
    h = Histogram()
    for v in (1.0, 2.0, 3.0):
        h.observe(v)
    assert h.mean() == pytest.approx(2.0)
    snap = h.snapshot()
    assert set(snap) == {"count", "mean", "p50", "p95", "p99"}
    assert snap["count"] == 3.0


def test_metrics_snapshot_dists_view():
    m = Metrics()
    m.inc("a", 2)
    m.set_gauge("gauge.x", 1.5)
    m.observe("hist.y", 0.25)
    assert m.snapshot() == {"a": 2}  # default view: counters only
    full = m.snapshot(dists=True)
    assert full["a"] == 2
    assert full["gauge.x"] == 1.5
    assert full["hist.y"]["count"] == 1.0
    m.reset()
    assert m.snapshot(dists=True) == {}


# --------------------------------------------------------------------------
# 2. Tracer primitives
# --------------------------------------------------------------------------


def test_emit_is_noop_when_disabled():
    assert not trace.enabled()
    assert trace.new_id() is None
    trace.emit(1, "gw", "request", conn=1)
    trace.TRACE.record(1, "gw", "request")  # direct record still lands...
    assert len(trace.TRACE.drain()) == 1  # ...but emit() above did not


def test_tracer_flush_jsonl_roundtrip(tmp_path):
    path = tmp_path / "t.jsonl"
    with trace.tracing(str(path)):
        tid = trace.new_id()
        assert tid is not None
        trace.emit(tid, "gw", "request", data="x")
        trace.emit(None, "miner", "reconnect")
    rows = load(str(path))
    assert [r["event"] for r in rows] == ["request", "reconnect"]
    assert rows[0]["trace"] == tid and rows[1]["trace"] is None
    assert rows[0]["attrs"] == {"data": "x"}


def test_tracer_ring_overflow_drops_oldest_and_counts():
    trace.TRACE.enable(capacity=4)
    try:
        for i in range(10):
            trace.emit(None, "s", f"e{i}")
        assert trace.TRACE.dropped() == 6
        rows = trace.TRACE.drain()
        assert [r["event"] for r in rows] == ["e6", "e7", "e8", "e9"]
    finally:
        trace.TRACE.disable()


def test_tracer_partial_write_failure_neither_loses_nor_duplicates(
    tmp_path, monkeypatch
):
    """A flush that fails MID-append (e.g. ENOSPC) must restore exactly
    the rows not yet durable: the retry may not duplicate already-written
    events, and the torn final line must not corrupt the next row."""
    import os as _os

    path = tmp_path / "torn.jsonl"
    t = trace.Tracer()
    t.enable(path=str(path))
    for i in range(5):
        t.record(None, "s", f"e{i}")
    real_write = _os.write
    budget = [30]  # ~one row, then the disk "fills"

    def failing_write(fd, data):
        if budget[0] <= 0:
            raise OSError(28, "No space left on device")
        n = min(budget[0], len(data))
        budget[0] -= n
        return real_write(fd, data[:n])

    monkeypatch.setattr(_os, "write", failing_write)
    with pytest.raises(OSError):
        t.flush()
    monkeypatch.setattr(_os, "write", real_write)
    t.flush()  # disk healthy again: exactly the unwritten suffix lands
    t.disable()
    assert [r["event"] for r in load(str(path))] == [
        f"e{i}" for i in range(5)
    ]


def test_tracer_flush_appends_across_calls(tmp_path):
    path = tmp_path / "t.jsonl"
    trace.TRACE.enable(path=str(path))
    try:
        trace.emit(None, "s", "one")
        assert trace.TRACE.flush() == 1
        trace.emit(None, "s", "two")
        assert trace.TRACE.flush() == 1
        assert trace.TRACE.flush() == 0  # nothing buffered: no-op
    finally:
        trace.TRACE.disable()
    assert [r["event"] for r in load(str(path))] == ["one", "two"]


# --------------------------------------------------------------------------
# 3. Event-level propagation (gateway + scheduler, no sockets)
# --------------------------------------------------------------------------


def _gateway(**kw):
    kw.setdefault("rate", None)
    return Gateway(Scheduler(min_chunk=100), **kw)


def _solve(gw, miner, now):
    """Answer the miner's outstanding chunks (front job's data, so a job
    admitted from the queue mid-loop is answered correctly too) until the
    miner idles."""
    out = []
    for _ in range(64):
        m = gw.sched.miners.get(miner)
        if m is None or not m.queue:
            break
        front = m.queue[0]
        job = gw.sched.jobs.get(front.job)
        if job is None:
            break
        c_lo, c_hi = front.interval
        h, n = min_hash_range(job.data, c_lo, c_hi)
        out += gw.result(miner, h, n, now)
    return out


def test_full_sweep_yields_one_complete_tree():
    trace.TRACE.enable()
    gw = _gateway()
    gw.miner_joined(1, 0.0)
    gw.client_request(10, "d", 0, 299, 1.0)
    acts = _solve(gw, 1, 2.0)
    assert any(a[0] == 10 for a in acts)  # the Result reached the client
    report = build(trace.TRACE.drain())
    assert report.orphans == []
    assert len(report.trees) == 1
    (tree,) = report.trees.values()
    assert tree.kind == "swept" and tree.complete
    assert tree.signature() == ("d", 0, 299)
    stages = tree.stages()
    assert "sweep" in stages and stages["sweep"] >= 0.0
    assert len(tree.chunks()) >= 1
    assert all(c["elapsed"] is not None for c in tree.chunks())


def test_coalesced_twin_gets_linked_complete_tree():
    trace.TRACE.enable()
    before = _hist_count("hist.request_s")
    gw = _gateway()
    gw.miner_joined(1, 0.0)
    gw.client_request(10, "d", 0, 199, 1.0)
    gw.client_request(11, "d", 0, 199, 1.2)  # twin: coalesces
    _solve(gw, 1, 2.0)
    report = build(trace.TRACE.drain())
    assert report.orphans == [] and len(report.trees) == 2
    kinds = {t.kind for t in report.trees.values()}
    assert kinds == {"swept", "coalesced"}
    assert all(t.complete for t in report.trees.values())
    twin = next(t for t in report.trees.values() if t.kind == "coalesced")
    primary = next(t for t in report.trees.values() if t.kind == "swept")
    link = twin._find("gw", "coalesce")
    assert link is not None and link["attrs"]["into"] == primary.trace
    # One latency sample per ORIGINAL request.
    assert _hist_count("hist.request_s") - before == 2


def test_span_partial_coverage_traced_through_submit():
    trace.TRACE.enable()
    gw = _gateway()
    gw.miner_joined(1, 0.0)
    h, n = min_hash_range("d", 0, 149)
    gw.spans.add("d", 0, 149, h, n)  # half of [0, 299] already solved
    gw.client_request(10, "d", 0, 299, 1.0)
    _solve(gw, 1, 2.0)
    report = build(trace.TRACE.drain())
    (tree,) = report.trees.values()
    assert tree.complete and tree.kind == "swept"
    submit = tree._find("gw", "submit")
    assert submit is not None and submit["attrs"]["gaps"] == 1
    start = tree._find("sched", "job_start")
    assert start is not None and start["attrs"]["gaps"] == 1


def test_span_full_coverage_answers_as_span_hit():
    trace.TRACE.enable()
    gw = _gateway()
    h, n = min_hash_range("d", 0, 99)
    gw.spans.add("d", 0, 99, h, n)
    # A strict sub-range containing the span's argmin is answerable with
    # zero device work (the ISSUE 5 rule) — and must trace as span_hit.
    lo, hi = max(0, n - 5), min(99, n + 5)
    acts = gw.client_request(10, "d", lo, hi, 1.0)
    assert acts == [(10, acts[0][1])] and acts[0][1].nonce == n
    report = build(trace.TRACE.drain())
    (tree,) = report.trees.values()
    assert tree.complete and tree.kind == "span_hit"
    assert tree._find("gw", "result") is not None


def test_admission_queue_wait_is_traced_and_observed():
    trace.TRACE.enable()
    before = _hist_count("hist.admission_wait_s")
    gw = _gateway(max_active=1)
    gw.miner_joined(1, 0.0)
    gw.client_request(10, "a", 0, 199, 1.0)  # takes the one active slot
    gw.client_request(11, "b", 0, 199, 1.5)  # parked in the queue
    _solve(gw, 1, 3.5)  # completing "a" admits the parked "b" too
    report = build(trace.TRACE.drain())
    assert report.orphans == []
    parked = next(
        t for t in report.trees.values() if t.signature()[0] == "b"
    )
    assert parked.complete and parked.kind == "swept"
    queued = parked._find("gw", "queued")
    admitted = parked._find("gw", "admitted")
    assert queued is not None and admitted is not None
    assert admitted["attrs"]["wait"] >= 0.0
    assert "admission" in parked.stages()
    assert _hist_count("hist.admission_wait_s") - before == 1


def test_shed_request_tree_is_closed_not_orphaned():
    trace.TRACE.enable()
    gw = _gateway(max_active=1, max_queued=0)
    gw.miner_joined(1, 0.0)
    gw.client_request(10, "a", 0, 199, 1.0)
    gw.client_request(11, "b", 0, 199, 1.1)  # no slot, no queue: shed
    assert 11 in gw.drain_evictions()
    report = build(trace.TRACE.drain())
    shed = next(t for t in report.trees.values() if t.kind == "shed")
    assert shed.complete  # terminal: gw.shed


def test_orphaned_job_and_resubmit_are_two_closed_trees():
    """Client retry-with-resubmit: the original request's tree terminates
    in job_orphaned, the resubmission mints a FRESH tree that resumes —
    one tree per original request, none left open."""
    trace.TRACE.enable()
    sched = Scheduler(min_chunk=100)
    sched.miner_joined(1, 0.0)
    sched.client_request(10, "d", 0, 399, 1.0)
    # One chunk lands, then the client dies mid-job.
    m = sched.miners[1]
    c_lo, c_hi = m.queue[0].interval
    h, n = min_hash_range("d", c_lo, c_hi)
    sched.result(1, h, n, 1.5)
    sched.lost(10, 2.0)
    # The reconnected client resubmits the identical signature.
    sched.client_request(20, "d", 0, 399, 3.0)
    for _ in range(64):
        if not sched.miners[1].queue:
            break
        c_lo, c_hi = sched.miners[1].queue[0].interval
        h, n = min_hash_range("d", c_lo, c_hi)
        sched.result(1, h, n, 4.0)
    report = build(trace.TRACE.drain())
    assert report.orphans == [] and len(report.trees) == 2
    by_kind = sorted(t.kind for t in report.trees.values())
    assert by_kind == ["lost", "swept"]
    assert all(t.complete for t in report.trees.values())
    resumed = next(t for t in report.trees.values() if t.kind == "swept")
    assert resumed._find("sched", "job_resumed") is not None


def test_waiter_death_closes_its_tree():
    trace.TRACE.enable()
    gw = _gateway()
    gw.miner_joined(1, 0.0)
    gw.client_request(10, "d", 0, 199, 1.0)
    gw.client_request(11, "d", 0, 199, 1.2)  # coalesced twin
    gw.lost(11, 1.5)  # twin dies while parked on the shared sweep
    _solve(gw, 1, 2.0)
    report = build(trace.TRACE.drain())
    assert report.orphans == []
    assert all(t.complete for t in report.trees.values())
    twin = next(t for t in report.trees.values() if t.kind == "coalesced")
    assert twin._find("gw", "waiter_lost") is not None


def test_reconstructor_reports_orphan_spans():
    rows = [
        {"t": 1.0, "trace": 99, "span": "sched", "event": "dispatch",
         "attrs": {"miner": 1, "lo": 0, "hi": 9}},
        {"t": 2.0, "trace": 1, "span": "gw", "event": "request",
         "attrs": {"data": "d", "lower": 0, "upper": 9}},
    ]
    report = build(rows)
    assert report.orphans == [99]
    assert len(report.open) == 1  # rooted but never terminated


def _hist_count(name: str) -> int:
    h = METRICS.histogram(name)
    return h.count() if h is not None else 0


# --------------------------------------------------------------------------
# 4. Tier-1 e2e: traced loopback fleet -> python -m tools.trace
# --------------------------------------------------------------------------


def test_traced_loopback_fleet_reconstructs_complete_timelines(
    tmp_path, capsys
):
    """The ISSUE 6 acceptance loop: a real loopback fleet served with
    --trace semantics, then ``python -m tools.trace --json --strict``
    rebuilds every request's gateway→scheduler→miner→result timeline —
    complete, no orphan spans, non-zero stage durations."""
    from bitcoin_miner_tpu import lsp
    from bitcoin_miner_tpu.apps import client as client_mod
    from bitcoin_miner_tpu.apps import miner as miner_mod
    from bitcoin_miner_tpu.apps import server as server_mod

    trace_file = tmp_path / "fleet.trace.jsonl"
    params = lsp.Params(epoch_limit=5, epoch_millis=200, window_size=5)
    server = lsp.Server(0, params)
    engine = Gateway(
        Scheduler(min_chunk=500),
        cache=ResultCache(),
        spans=SpanStore(),
        rate=None,
    )
    trace.TRACE.enable(path=str(trace_file))
    try:
        threading.Thread(
            target=server_mod.serve,
            args=(server, engine),
            kwargs={"tick_interval": 0.05},
            daemon=True,
        ).start()
        search = miner_mod.make_search("cpu")
        for _ in range(2):
            mc = lsp.Client("127.0.0.1", server.port, params)
            threading.Thread(
                target=miner_mod.run_miner, args=(mc, search), daemon=True
            ).start()

        jobs = [("tr1", 0, 2000), ("tr1", 0, 2000), ("tr2", 0, 1500)]
        results = {}

        def run_one(i, sig):
            data, lo, hi = sig
            c = lsp.Client("127.0.0.1", server.port, params)
            try:
                results[i] = client_mod.request_once(c, data, hi, lower=lo)
            finally:
                c.close()

        threads = [
            threading.Thread(target=run_one, args=(i, s), daemon=True)
            for i, s in enumerate(jobs)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=90)
        # A repeat after completion: the cache answers it (still traced).
        run_one(len(jobs), jobs[0])
    finally:
        server.close()
        time.sleep(0.2)  # let the serve thread run its final flush
        trace.TRACE.disable()

    for i, sig in enumerate(jobs + [jobs[0]]):
        want = min_hash_range(sig[0], sig[1], sig[2])
        assert results[i] == want, (i, sig, results.get(i), want)

    rc = trace_main([str(trace_file), "--json", "--strict"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0, out
    assert out["orphans"] == [] and out["open"] == []
    assert out["requests"] == 4
    assert out["complete"] == 4
    kinds = out["kinds"]
    assert kinds.get("swept", 0) >= 2  # the two distinct signatures
    assert kinds.get("coalesced", 0) + kinds.get("cache_hit", 0) >= 2
    swept = [t for t in out["trees"] if t["kind"] == "swept"]
    for t in swept:
        assert t["total_s"] > 0.0
        assert t["stages_s"].get("sweep", 0.0) > 0.0
        assert t["chunks"] >= 1
    # The stage breakdown has real mass: the fleet's time went somewhere.
    assert sum(out["stage_totals_s"].values()) > 0.0
    # The human report renders without crashing too.
    assert trace_main([str(trace_file)]) == 0
    assert "stage breakdown" in capsys.readouterr().out


def test_chaos_drill_trace_reconstructs_with_no_orphans(tmp_path):
    """A seeded chaos drill with a trace file is a deterministic
    diagnosis: the drill stays oracle-exact AND its trace reconstructs
    every request tree closed (answered or explicitly orphaned), with
    the fleet's self-healing events alongside."""
    from bitcoin_miner_tpu.apps.drill import run_drill

    trace_file = tmp_path / "drill.trace.jsonl"
    # kill_miner_at: miner-0 dies mid-sweep, so the trace must show the
    # job's id surviving dead-miner reassignment (dispatches to the
    # replacement miner carry the same trace).
    report = run_drill(
        "burst-loss", seed=11, data="tracechaos", max_nonce=2000,
        n_miners=2, kill_miner_at=0.3, timeout=90.0,
        trace_path=str(trace_file),
    )
    assert report.ok, report.as_dict()
    rows = load(str(trace_file))
    assert rows, "drill produced no trace records"
    rep = build(rows)
    assert rep.orphans == []
    assert len(rep.complete) >= 1
    # Every tree is closed: answered, or closed by the orphan stash when
    # a retry superseded it mid-chaos.
    assert not rep.open, [t.trace for t in rep.open]
    swept = [t for t in rep.trees.values() if t.kind == "swept"]
    assert swept and all(t.total_s > 0.0 for t in swept)
