"""Shared test harness for the LSP suites.

Mirrors the reference's builder-style test systems (``lsp/lsp1_test.go:25-92``
``testSystem`` et al.): a server plus N concurrent clients driven from
threads over real loopback UDP, with the lspnet fault knobs as the fake
network and every timeout denominated in epochs so timing scales with
EpochMillis (lsp/lsp2_test.go:123-127 ``setMaxEpochs`` pattern).
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass, field
from typing import Callable, List, Optional

from bitcoin_miner_tpu import lsp, lspnet


def random_port() -> int:
    # The Go suites bind 3000 + rand.Intn(50000) (lsp1_test.go:70-75); we
    # let the OS assign (port 0) where possible, and use this only for
    # slow-start tests that need a port before the server exists.
    return 3000 + random.randint(10000, 50000)


@dataclass
class TestSystem:
    """Builder-style echo test system."""

    __test__ = False  # not a pytest collection target

    num_clients: int = 1
    num_msgs: int = 10
    window: int = 1
    epoch_millis: int = 100
    epoch_limit: int = 5
    max_epochs: int = 60  # global deadline, in epochs
    write_drop: int = 0  # symmetric write-drop percent while echoing
    sleep_max_ms: int = 0  # random client+server delays (setMaxSleepMillis,
    # lsp1_test.go TestBasic7-9 / TestSendReceive3)
    desc: str = ""

    errors: List[str] = field(default_factory=list)
    _threads: List[threading.Thread] = field(default_factory=list)

    @property
    def params(self) -> lsp.Params:
        return lsp.Params(self.epoch_limit, self.epoch_millis, self.window)

    @property
    def deadline(self) -> float:
        return self.max_epochs * self.epoch_millis / 1000.0

    def fail(self, msg: str) -> None:
        self.errors.append(msg)

    def run_echo(self) -> None:
        """N clients each write num_msgs values and verify the echoes
        (lsp1_test.go:124-160 per-client loop)."""
        lspnet.reset_faults()
        server = lsp.Server(0, self.params)
        stop = threading.Event()

        def maybe_sleep() -> None:
            if self.sleep_max_ms:
                import time

                time.sleep(random.uniform(0, self.sleep_max_ms) / 1000.0)

        def server_loop() -> None:
            while not stop.is_set():
                try:
                    cid, payload = server.read()
                    maybe_sleep()
                    server.write(cid, payload)
                except lsp.ConnLostError:
                    continue
                except lsp.LspError:
                    return

        st = threading.Thread(target=server_loop, daemon=True)
        st.start()

        if self.write_drop:
            lspnet.set_write_drop_percent(self.write_drop)

        def client_loop(idx: int) -> None:
            try:
                c = lsp.Client("127.0.0.1", server.port, self.params)
            except lsp.LspError as e:
                self.fail(f"client {idx} connect failed: {e}")
                return
            try:
                for i in range(self.num_msgs):
                    value = f"{idx}:{i}:{random.randint(0, 1_000_000)}".encode()
                    maybe_sleep()
                    c.write(value)
                    got = c.read()
                    if got != value:
                        self.fail(f"client {idx} echo mismatch: {got!r} != {value!r}")
                        return
            except lsp.LspError as e:
                self.fail(f"client {idx} transport error: {e}")
            finally:
                try:
                    c.close()
                except lsp.LspError:
                    pass

        self._threads = [
            threading.Thread(target=client_loop, args=(i,), daemon=True)
            for i in range(self.num_clients)
        ]
        for t in self._threads:
            t.start()
        for t in self._threads:
            t.join(timeout=self.deadline)
            if t.is_alive():
                self.fail(f"deadline exceeded ({self.max_epochs} epochs)")
        stop.set()
        lspnet.reset_faults()
        try:
            server.close()
        except lsp.LspError:
            pass
        assert not self.errors, self.errors


def spawn(fn: Callable[[], None]) -> threading.Thread:
    t = threading.Thread(target=fn, daemon=True)
    t.start()
    return t
