"""End-to-end mining integration tests (B8, BASELINE configs 1/3/5).

Real loopback UDP through the full LSP stack: a server thread running the
scheduler loop, miner threads on the CPU-oracle backend (byte-identical to
the Go reference's hot loop), and client threads using the frozen
request/response path.  Mirrors the reference test style (SURVEY §4):
everything in one process, epoch-denominated timeouts, the lspnet seam for
fault injection.
"""

import threading
import time

import pytest

from bitcoin_miner_tpu import lsp, lspnet
from bitcoin_miner_tpu.apps import client as client_mod
from bitcoin_miner_tpu.apps import miner as miner_mod
from bitcoin_miner_tpu.apps import server as server_mod
from bitcoin_miner_tpu.apps.scheduler import Scheduler
from bitcoin_miner_tpu.bitcoin.hash import min_hash_range
from bitcoin_miner_tpu.bitcoin.message import Message


PARAMS = lsp.Params(epoch_limit=5, epoch_millis=200, window_size=5)


@pytest.fixture(autouse=True)
def _clean_network():
    lspnet.reset_faults()
    yield
    lspnet.reset_faults()


class MiningSystem:
    """In-process cluster: scheduler server + N miner threads."""

    def __init__(self, n_miners: int = 2, min_chunk: int = 500):
        self.server = lsp.Server(0, PARAMS)
        self.port = self.server.port
        self.scheduler = Scheduler(min_chunk=min_chunk)
        self.server_thread = threading.Thread(
            target=server_mod.serve, args=(self.server, self.scheduler), daemon=True
        )
        self.server_thread.start()
        self.miner_clients = []
        self.miner_threads = []
        for _ in range(n_miners):
            self.add_miner()

    def add_miner(self, search=None):
        c = lsp.Client("127.0.0.1", self.port, PARAMS)
        t = threading.Thread(
            target=miner_mod.run_miner,
            args=(c, search or miner_mod.make_search("cpu")),
            daemon=True,
        )
        t.start()
        self.miner_clients.append(c)
        self.miner_threads.append(t)
        return c

    def request(self, data: str, max_nonce: int):
        c = lsp.Client("127.0.0.1", self.port, PARAMS)
        try:
            return client_mod.request_once(c, data, max_nonce)
        finally:
            c.close()

    def close(self):
        self.server.close()


def test_single_miner_correct_result():
    sys_ = MiningSystem(n_miners=1)
    try:
        res = sys_.request("cmu440", 4999)
        assert res == min_hash_range("cmu440", 0, 4999)
    finally:
        sys_.close()


def test_multi_miner_range_split_correct():
    sys_ = MiningSystem(n_miners=4, min_chunk=300)
    try:
        res = sys_.request("distributed", 7999)
        assert res == min_hash_range("distributed", 0, 7999)
    finally:
        sys_.close()


def test_concurrent_clients():
    sys_ = MiningSystem(n_miners=3, min_chunk=400)
    results = {}

    def one(job):
        data, mx = job
        results[job] = sys_.request(data, mx)

    jobs = [("alpha", 3000), ("beta", 4000), ("gamma", 2500)]
    try:
        threads = [threading.Thread(target=one, args=(j,)) for j in jobs]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
            assert not t.is_alive(), "client timed out"
        for data, mx in jobs:
            assert results[(data, mx)] == min_hash_range(data, 0, mx)
    finally:
        sys_.close()


def test_heterogeneous_backends():
    """A fast+slow fleet (10x rate skew) still min-folds correctly — the
    BASELINE config-3 shape (CPU + TPU mix) with the skew simulated."""

    def slow_search(data, lower, upper):
        time.sleep(0.05)
        return min_hash_range(data, lower, upper)

    sys_ = MiningSystem(n_miners=0, min_chunk=500)
    try:
        sys_.add_miner(miner_mod.make_search("cpu"))
        sys_.add_miner(slow_search)
        res = sys_.request("hetero", 6000)
        assert res == min_hash_range("hetero", 0, 6000)
    finally:
        sys_.close()


def test_miner_killed_mid_job_range_reassigned():
    """BASELINE config 5: kill a miner mid-job; the server must reassign its
    outstanding chunk and the final result must be unchanged."""
    block = threading.Event()
    killed = threading.Event()

    def stalling_search(data, lower, upper):
        if not killed.is_set():
            killed.set()
            block.wait(timeout=30)  # hold the chunk until we are killed
        return min_hash_range(data, lower, upper)

    sys_ = MiningSystem(n_miners=0, min_chunk=500)
    try:
        victim = sys_.add_miner(stalling_search)
        sys_.add_miner()

        out = {}

        def run_client():
            out["res"] = sys_.request("faulty", 4000)

        t = threading.Thread(target=run_client, daemon=True)
        t.start()
        assert killed.wait(timeout=30), "victim never got a chunk"
        victim.close()  # miner process dies; epochs declare it lost
        t.join(timeout=60)
        assert not t.is_alive(), "client never got a result"
        assert out["res"] == min_hash_range("faulty", 0, 4000)
    finally:
        block.set()
        sys_.close()


def test_client_death_cancels_job_and_server_survives():
    sys_ = MiningSystem(n_miners=1, min_chunk=200)
    try:
        c = lsp.Client("127.0.0.1", sys_.port, PARAMS)
        c.write(Message.request("doomed", 0, 10**7).marshal())
        time.sleep(0.3)  # let the job get scheduled
        c.close()
        deadline = time.time() + PARAMS.epoch_limit * PARAMS.epoch_seconds + 5
        while time.time() < deadline and sys_.scheduler.jobs:
            time.sleep(0.1)
        assert sys_.scheduler.jobs == {}, "job not cancelled after client death"
        # Server still serves new work afterwards.
        res = sys_.request("alive", 1500)
        assert res == min_hash_range("alive", 0, 1500)
    finally:
        sys_.close()


def test_mining_under_packet_loss():
    """Request/Result survive 20% write drop both ways (lsp retransmits)."""
    sys_ = MiningSystem(n_miners=2, min_chunk=500)
    try:
        lspnet.set_write_drop_percent(20)
        res = sys_.request("lossy", 3000)
        assert res == min_hash_range("lossy", 0, 3000)
    finally:
        lspnet.reset_faults()
        sys_.close()


def test_xla_backend_fleet():
    """The Request→sweep→Result glue through the JAX tier — the backend a
    real TPU miner runs (here on the virtual CPU mesh).  Round 1 only ever
    exercised the fleet with the cpu oracle; this pins the apps/miner.py
    routing of Request fields into sweep_min_hash."""
    sys_ = MiningSystem(n_miners=0, min_chunk=400)
    try:
        sys_.add_miner(miner_mod.make_search("xla"))
        sys_.add_miner()  # heterogeneous: xla + cpu oracle in one fleet
        res = sys_.request("xlatier", 2500)
        assert res == min_hash_range("xlatier", 0, 2500)
    finally:
        sys_.close()


def test_multichip_mesh_miner_fleet():
    """One miner process spanning the full 8-device virtual mesh via
    --devices (shard_map + pmin cascade), serving a real fleet job — the
    apps/miner.py glue over parallel/sweep.py (BASELINE's single ultra-fast
    worker shape)."""
    sys_ = MiningSystem(n_miners=0, min_chunk=500)
    try:
        sys_.add_miner(miner_mod.make_search("xla", devices=8))
        res = sys_.request("meshminer", 2500)
        assert res == min_hash_range("meshminer", 0, 2500)
    finally:
        sys_.close()


def test_checkpoint_resume_fleet_restart(tmp_path):
    """Kill the whole fleet mid-job; a restarted server resumes from the
    checkpoint file and completes WITHOUT re-sweeping finished sub-ranges
    (scheduler checkpoint/resume, SURVEY §5 beyond-parity)."""
    ckpt = str(tmp_path / "ckpt.json")
    data, mx = "resumable", 9999
    first_done = threading.Event()
    hold = threading.Event()

    def first_then_hang(d, lo, hi):
        r = min_hash_range(d, lo, hi)
        if first_done.is_set():
            hold.wait(timeout=30)  # freeze the fleet after one chunk lands
        first_done.set()
        return r

    # --- fleet 1: completes exactly one chunk, then is killed ------------
    server1 = lsp.Server(0, PARAMS)
    sched1 = Scheduler(min_chunk=2000, straggler_min_seconds=60.0)
    t1 = threading.Thread(
        target=server_mod.serve,
        args=(server1, sched1),
        kwargs={"tick_interval": 0.05, "checkpoint_path": ckpt},
        daemon=True,
    )
    t1.start()
    m1 = lsp.Client("127.0.0.1", server1.port, PARAMS)
    threading.Thread(
        target=miner_mod.run_miner, args=(m1, first_then_hang), daemon=True
    ).start()
    c1 = lsp.Client("127.0.0.1", server1.port, PARAMS)
    c1.write(Message.request(data, 0, mx).marshal())
    assert first_done.wait(timeout=30), "first chunk never completed"
    # Wait for a checkpoint that has folded the first chunk's result.
    deadline = time.time() + 10
    state = None
    while time.time() < deadline:
        state = server_mod.load_checkpoint(ckpt)
        if state and state["jobs"] and state["jobs"][0]["best"] is not None:
            break
        time.sleep(0.05)
    assert state and state["jobs"][0]["best"] is not None, "no checkpoint"
    server1.close()  # fleet dies mid-job
    hold.set()

    # --- fleet 2: resumes from the file -----------------------------------
    [jobdict] = state["jobs"]
    completed_upper = min(lo for lo, _ in jobdict["remaining"]) - 1
    assert completed_upper >= 0, "nothing was actually completed"

    swept = []

    def recording_search(d, lo, hi):
        swept.append((lo, hi))
        return min_hash_range(d, lo, hi)

    server2 = lsp.Server(0, PARAMS)
    sched2 = Scheduler(
        min_chunk=2000, resume_state=server_mod.load_checkpoint(ckpt)
    )
    threading.Thread(
        target=server_mod.serve, args=(server2, sched2), daemon=True
    ).start()
    m2 = lsp.Client("127.0.0.1", server2.port, PARAMS)
    threading.Thread(
        target=miner_mod.run_miner, args=(m2, recording_search), daemon=True
    ).start()
    try:
        c2 = lsp.Client("127.0.0.1", server2.port, PARAMS)
        try:
            res = client_mod.request_once(c2, data, mx)
        finally:
            c2.close()
        assert res == min_hash_range(data, 0, mx)
        assert swept, "resumed fleet did no work"
        # Nothing below the completed prefix may have been re-swept.
        assert min(lo for lo, _ in swept) > completed_upper
    finally:
        server2.close()


def test_client_disconnected_output():
    """Frozen stdout contract: server dies -> client prints Disconnected."""
    import io

    sys_ = MiningSystem(n_miners=0)
    port = sys_.port
    out = io.StringIO()

    def run():
        client_mod.main(["client", f"127.0.0.1:{port}", "x", "100000"], out=out)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    time.sleep(0.5)  # request reaches the (miner-less) scheduler
    sys_.close()
    t.join(timeout=30)
    assert not t.is_alive()
    assert out.getvalue() == "Disconnected\n"


def test_ticker_survives_checkpoint_write_failure():
    """An unwritable checkpoint path must not kill the ticker thread (and
    with it straggler recovery) — the serve loop logs and keeps going."""
    server = lsp.Server(0, PARAMS)
    sched = Scheduler(min_chunk=500)
    t = threading.Thread(
        target=server_mod.serve,
        args=(server, sched),
        kwargs={
            "tick_interval": 0.05,
            "checkpoint_path": "/nonexistent-dir/ckpt.json",
        },
        daemon=True,
    )
    t.start()
    try:
        m = lsp.Client("127.0.0.1", server.port, PARAMS)
        threading.Thread(
            target=miner_mod.run_miner,
            args=(m, miner_mod.make_search("cpu")),
            daemon=True,
        ).start()
        time.sleep(0.5)  # several failing ticks elapse
        c = lsp.Client("127.0.0.1", server.port, PARAMS)
        try:
            res = client_mod.request_once(c, "tickerok", 3000)
        finally:
            c.close()
        assert res == min_hash_range("tickerok", 0, 3000)
    finally:
        server.close()


def test_adversarial_fleet_soak():
    """Everything at once, live: 20% packet loss, a permanently hung miner
    (straggler tick reclaims), a lying miner (validation evicts), a slow
    miner, and concurrent jobs — every client still gets the bit-exact
    min (BASELINE configs 3+5 combined, plus this framework's guards)."""
    server = lsp.Server(0, PARAMS)
    sched = Scheduler(min_chunk=400, straggler_min_seconds=4.0)
    threading.Thread(
        target=server_mod.serve,
        args=(server, sched),
        kwargs={"tick_interval": 0.2},
        daemon=True,
    ).start()

    def add(search):
        c = lsp.Client("127.0.0.1", server.port, PARAMS)
        threading.Thread(
            target=miner_mod.run_miner, args=(c, search), daemon=True
        ).start()
        return c

    hold = threading.Event()
    try:
        for _ in range(5):
            add(miner_mod.make_search("cpu"))
        add(lambda d, lo, hi: hold.wait(3600))  # hung: straggler path
        add(lambda d, lo, hi: (12345, lo))  # liar: validation path

        def slow(d, lo, hi):
            time.sleep(0.2)
            return min_hash_range(d, lo, hi)

        add(slow)

        lspnet.set_write_drop_percent(20)
        jobs = [(f"soak{i}", 3000 + 500 * i) for i in range(4)]
        results = {}

        def run_job(data, mx):
            c = lsp.Client("127.0.0.1", server.port, PARAMS)
            try:
                results[data] = client_mod.request_once(c, data, mx)
            finally:
                c.close()

        ths = [
            threading.Thread(target=run_job, args=j, daemon=True) for j in jobs
        ]
        for t in ths:
            t.start()
        for t in ths:
            # Generous: this host exposes one CPU core, and a full-suite
            # run adds contention on top of the 20% loss + 8-miner fleet.
            t.join(timeout=240)
            assert not t.is_alive(), "client starved"
        for data, mx in jobs:
            assert results[data] == min_hash_range(data, 0, mx), data
    finally:
        hold.set()
        lspnet.reset_faults()
        server.close()


def test_u64_edge_fleet_e2e():
    """A job at the top of the uint64 nonce space — [2^64 − 3·10^6, 2^64 − 1],
    20-digit decimal templates — through the FULL fleet (scheduler → LSP →
    heterogeneous native + xla miners → min-fold), checked bit-exact against
    the hashlib oracle.  Pins the `Lower, Upper uint64` wire contract
    (reference bitcoin/message.go:21) end-to-end, not just at the ops tier."""
    U64 = (1 << 64) - 1
    lo = U64 - 3_000_000 + 1
    sys_ = MiningSystem(n_miners=1)  # one native/cpu-tier miner...
    try:
        sys_.add_miner(miner_mod.make_search("xla"))  # ...plus one xla-tier
        c = lsp.Client("127.0.0.1", sys_.port, PARAMS)
        try:
            c.write(Message.request("cmu440", lo, U64).marshal())
            msg = Message.unmarshal(c.read())
        finally:
            c.close()
        assert (msg.hash, msg.nonce) == min_hash_range("cmu440", lo, U64)
    finally:
        sys_.close()


def test_server_logs_health(caplog):
    """The server shell periodically logs scheduler stats + recovery
    counters (the observability surface the reference's LOGF scaffold
    implies, bitcoin/server/server.go:26-39)."""
    import logging

    logger = logging.getLogger("test.health")
    server = lsp.Server(0, PARAMS)
    sched = Scheduler(min_chunk=500)
    threading.Thread(
        target=server_mod.serve,
        args=(server, sched),
        kwargs={
            "log": logger,
            "tick_interval": 0.05,
            "health_interval": 0.2,
        },
        daemon=True,
    ).start()
    try:
        with caplog.at_level(logging.INFO, logger="test.health"):
            c = lsp.Client("127.0.0.1", server.port, PARAMS)
            mc = lsp.Client("127.0.0.1", server.port, PARAMS)
            threading.Thread(
                target=miner_mod.run_miner,
                args=(mc, miner_mod.make_search("cpu")),
                daemon=True,
            ).start()
            try:
                assert client_mod.request_once(c, "health", 2000) == (
                    min_hash_range("health", 0, 2000)
                )
                # Wait for a health line that has SEEN the fleet: the first
                # line can beat the miner's Join (ticker t=0.2 vs conn
                # handshake), and repeats are deduped, so polling for the
                # bare prefix races the join on a fast box.
                deadline = time.monotonic() + 5.0
                while (
                    time.monotonic() < deadline
                    and "'miners': 1" not in caplog.text
                ):
                    time.sleep(0.1)
            finally:
                c.close()
        assert "health {" in caplog.text
        assert "'miners': 1" in caplog.text
        assert "chunks_assigned" in caplog.text
        assert "jobs_completed" in caplog.text
    finally:
        server.close()


@pytest.mark.slow
def test_mesh_miner_cli_subprocess_fleet():
    """The --devices CLI path as real subprocesses: server + an 8-virtual-
    CPU-device mesh miner (BMT_FORCE_CPU_DEVICES — env vars alone can't
    override the boot platform here) + client, oracle-exact Result.
    Covers the pipelined sharded search behind the actual binary."""
    import os
    import subprocess
    import sys
    import time
    from pathlib import Path

    repo = Path(__file__).resolve().parents[1]
    port = 3000 + (os.getpid() * 6151) % 50000
    env = {
        **os.environ,
        "PYTHONPATH": str(repo) + os.pathsep + os.environ.get("PYTHONPATH", ""),
    }
    server = subprocess.Popen(
        [sys.executable, "-m", "bitcoin_miner_tpu.apps.server", str(port)],
        cwd=str(repo), env=env,
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
    )
    miner = None
    try:
        import select

        deadline = time.monotonic() + 30
        up = False
        while not up:
            assert time.monotonic() < deadline, "server did not come up"
            assert server.poll() is None, "server died at startup"
            ready, _, _ = select.select([server.stdout], [], [], 1.0)
            if ready:
                up = "listening" in (server.stdout.readline() or "")
        miner = subprocess.Popen(
            [sys.executable, "-m", "bitcoin_miner_tpu.apps.miner",
             f"127.0.0.1:{port}", "--devices", "8"],
            cwd=str(repo),
            env={**env, "BMT_FORCE_CPU_DEVICES": "8", "JAX_PLATFORMS": "cpu"},
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        out = subprocess.run(
            [sys.executable, "-m", "bitcoin_miner_tpu.apps.client",
             f"127.0.0.1:{port}", "meshcli", "300000"],
            cwd=str(repo), env=env, capture_output=True, text=True,
            timeout=180,
        )
        h, n = min_hash_range("meshcli", 0, 300000)
        assert out.stdout.strip() == f"Result {h} {n}", out.stdout
    finally:
        for p in (miner, server):
            if p is not None and p.poll() is None:
                p.kill()
