"""The jax.distributed multi-host path, actually executed.

tests/test_multihost.py covers the broadcast protocol single-process; this
spawns TWO real processes that join one ``jax.distributed`` job over a
loopback coordinator (CPU backend, one device per process), build the
global mesh, broadcast a Request host-0-to-all, and run the sharded sweep
over the cross-process mesh — the exact wiring
``apps/miner.py --multihost`` uses on a TPU pod (run_miner_multihost),
which previously never executed anywhere (VERDICT r3 item 25).
"""

import json
import socket
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]

WORKER = r"""
import json, sys
import numpy as np
import jax
from jax.experimental import multihost_utils

from bitcoin_miner_tpu.parallel import multihost, sweep_min_hash_sharded

host_id, port = int(sys.argv[1]), sys.argv[2]
multihost.initialize(f"127.0.0.1:{port}", 2, host_id)
assert jax.process_count() == 2, jax.process_count()
assert multihost.is_primary() == (host_id == 0)
mesh = multihost.global_mesh()
assert mesh.devices.size == 2, mesh  # one CPU device per process

# Host 0 owns the Request; everyone gets it via the collective broadcast
# (serve_multihost's loop body, apps/miner.py).
buf = (
    multihost.encode_request("mh", 95, 1999)
    if multihost.is_primary()
    else multihost.encode_shutdown()
)
req = multihost.decode_request(np.asarray(multihost_utils.broadcast_one_to_all(buf)))
assert req == ("mh", 95, 1999), req

r = sweep_min_hash_sharded(req[0], req[1], req[2], mesh=mesh, max_k=2)
if multihost.is_primary():
    print(json.dumps({"hash": r.hash, "nonce": r.nonce}), flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.timeout(180)
def test_two_process_distributed_sweep(tmp_path):
    port = _free_port()
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    import os

    env = {
        **os.environ,
        "PYTHONPATH": str(REPO),
        # One plain CPU device per process: drop the 8-virtual-device
        # XLA_FLAGS the test session itself runs under (conftest.py).
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "",
    }
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(i), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=env,
            cwd=REPO,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=150)
        assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
        outs.append(out)

    from bitcoin_miner_tpu.bitcoin.hash import min_hash_range

    result = json.loads(outs[0].strip().splitlines()[-1])
    want_hash, want_nonce = min_hash_range("mh", 95, 1999)
    assert (result["hash"], result["nonce"]) == (want_hash, want_nonce)
    # Secondary host emits no Result (only host 0 owns the LSP side);
    # runtime chatter like Gloo's connection line is fine.
    assert not [l for l in outs[1].splitlines() if l.startswith("{")]
