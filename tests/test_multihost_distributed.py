"""The jax.distributed multi-host path, actually executed.

tests/test_multihost.py covers the broadcast protocol single-process; this
spawns TWO real processes that join one ``jax.distributed`` job over a
loopback coordinator (CPU backend, TWO devices per process — the mixed
intra-process "ICI" + inter-process "DCN" shape of a real pod), build the
2x2 global mesh, broadcast a Request host-0-to-all, and run the sharded
sweep over the cross-process mesh — the exact wiring
``apps/miner.py --multihost`` uses on a TPU pod (run_miner_multihost),
which previously never executed anywhere (VERDICT r3 item 25).  A second
test drills host death: the primary of a live multihost miner is killed
mid-job and the scheduler reassigns its range to a replacement miner
(SURVEY §5 failure recovery; BASELINE config 5).
"""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]

WORKER = r"""
import json, sys
import numpy as np
import jax
from jax.experimental import multihost_utils

from bitcoin_miner_tpu.parallel import multihost, sweep_min_hash_sharded

host_id, port = int(sys.argv[1]), sys.argv[2]
multihost.initialize(f"127.0.0.1:{port}", 2, host_id)
assert jax.process_count() == 2, jax.process_count()
assert multihost.is_primary() == (host_id == 0)
assert jax.local_device_count() == 2, jax.local_devices()
mesh = multihost.global_mesh()
assert mesh.devices.size == 4, mesh  # 2 hosts x 2 devices: ICI+DCN shape

# Host 0 owns the Request; everyone gets it via the collective broadcast
# (serve_multihost's loop body, apps/miner.py).
buf = (
    multihost.encode_request("mh", 95, 1999)
    if multihost.is_primary()
    else multihost.encode_shutdown()
)
req = multihost.decode_request(np.asarray(multihost_utils.broadcast_one_to_all(buf)))
assert req == ("mh", 95, 1999), req

r = sweep_min_hash_sharded(req[0], req[1], req[2], mesh=mesh, max_k=2)
if multihost.is_primary():
    print(json.dumps({"hash": r.hash, "nonce": r.nonce}), flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_2x2_distributed_sweep(tmp_path):
    port = _free_port()
    script = tmp_path / "worker.py"
    script.write_text(WORKER)

    env = {
        **os.environ,
        "PYTHONPATH": str(REPO),
        # Two plain CPU devices per process: replaces the 8-virtual-device
        # XLA_FLAGS the test session itself runs under (conftest.py).
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
    }
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(i), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=env,
            cwd=REPO,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        # communicate(timeout=) bounds the realistic hang path (a worker
        # that never finishes); pytest-timeout isn't installed, so no mark.
        out, err = p.communicate(timeout=150)
        assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
        outs.append(out)

    from bitcoin_miner_tpu.bitcoin.hash import min_hash_range

    result = json.loads(outs[0].strip().splitlines()[-1])
    want_hash, want_nonce = min_hash_range("mh", 95, 1999)
    assert (result["hash"], result["nonce"]) == (want_hash, want_nonce)
    # Secondary host emits no Result (only host 0 owns the LSP side);
    # runtime chatter like Gloo's connection line is fine.
    assert not [l for l in outs[1].splitlines() if l.startswith("{")]


def test_host0_death_mid_job_scheduler_reassigns(tmp_path):
    """Kill the multihost miner's primary (the host holding the LSP conn)
    mid-job: the scheduler must detect the dead conn, reassign its
    outstanding range to a replacement miner, and the client must still
    get the bit-exact min."""
    from bitcoin_miner_tpu import lsp
    from bitcoin_miner_tpu.apps import client as client_mod
    from bitcoin_miner_tpu.apps import miner as miner_mod
    from bitcoin_miner_tpu.apps import server as server_mod
    from bitcoin_miner_tpu.apps.scheduler import Scheduler
    from bitcoin_miner_tpu.bitcoin.hash import min_hash_range
    from bitcoin_miner_tpu.utils.metrics import METRICS

    METRICS.reset()
    params = lsp.Params(epoch_limit=5, epoch_millis=200, window_size=5)
    server = lsp.Server(0, params)
    sched = Scheduler(min_chunk=20_000)
    threading.Thread(
        target=server_mod.serve,
        args=(server, sched),
        kwargs={"tick_interval": 0.2},
        daemon=True,
    ).start()

    coord = _free_port()
    env = {
        **os.environ,
        "PYTHONPATH": str(REPO),
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "",
    }
    hosts = [
        subprocess.Popen(
            [
                sys.executable,
                "-m",
                "bitcoin_miner_tpu.apps.miner",
                f"127.0.0.1:{server.port}",
                "--multihost",
                f"--coordinator=127.0.0.1:{coord}",
                "--num-hosts=2",
                f"--host-id={i}",
            ],
            env=env,
            cwd=REPO,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        for i in range(2)
    ]
    data, mx = "hostdeath", 2_000_000
    result_box = {}

    def run_client():
        c = lsp.Client("127.0.0.1", server.port, params)
        try:
            result_box["r"] = client_mod.request_once(c, data, mx)
        finally:
            c.close()

    ct = threading.Thread(target=run_client, daemon=True)
    backup_client = None
    try:
        ct.start()
        # Wait until the multihost miner holds assigned chunks (mid-job)...
        deadline = time.monotonic() + 120
        while (
            METRICS.get("sched.chunks_assigned") < 2
            and time.monotonic() < deadline
        ):
            time.sleep(0.1)
        assert METRICS.get("sched.chunks_assigned") >= 2, "miner never ramped"
        # ...then kill the primary outright (no goodbye over LSP).
        hosts[0].send_signal(signal.SIGKILL)
        # Replacement worker: the epoch heartbeat declares the dead conn,
        # lost() re-queues its chunks, dispatch hands them here.
        backup_client = lsp.Client("127.0.0.1", server.port, params)
        threading.Thread(
            target=miner_mod.run_miner,
            args=(backup_client, miner_mod.make_search("cpu")),
            daemon=True,
        ).start()
        ct.join(timeout=120)
        assert not ct.is_alive(), "client starved after host-0 death"
        assert result_box["r"] == min_hash_range(data, 0, mx)
        assert METRICS.get("sched.chunks_reassigned") >= 1
    finally:
        for p in hosts:
            if p.poll() is None:
                p.kill()
        server.close()
