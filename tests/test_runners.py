"""Smoke tests for the srunner/crunner echo harnesses (dev-harness parity
with the reference's srunner/crunner binaries, SURVEY §2.1): the real
main() entry points echo traffic over loopback with the reference's flag
sets (`srunner/srunner.go:15-23`, `crunner/crunner.go:16-25`).
"""

import io
import threading
import time

import pytest

from bitcoin_miner_tpu import lsp, lspnet
from bitcoin_miner_tpu.apps import crunner, srunner
from lsp_harness import random_port


@pytest.fixture(autouse=True)
def _clean_network():
    lspnet.reset_faults()
    yield
    lspnet.reset_faults()


def test_echo_loop_round_trip(monkeypatch, capsys):
    params = lsp.Params(epoch_limit=5, epoch_millis=100, window_size=4)
    server = lsp.Server(0, params)
    st = threading.Thread(target=srunner.run_server, args=(server,), daemon=True)
    st.start()
    try:
        client = lsp.Client("127.0.0.1", server.port, params)
        monkeypatch.setattr("sys.stdin", io.StringIO("hello world\nfoo\n"))
        crunner.run_client(client)
        client.close()
        out = capsys.readouterr().out
        assert out.splitlines() == ["[echo] hello", "[echo] world", "[echo] foo"]
    finally:
        server.close()
        st.join(timeout=5)


def test_mains_with_reference_flags(monkeypatch, capsys):
    """Drive the real srunner.main and crunner.main with the reference flag
    sets end to end on loopback."""
    port = random_port()
    created = {}
    real_server = lsp.Server

    def capturing_server(*a, **k):
        s = real_server(*a, **k)
        created["server"] = s
        return s

    # Both runner modules resolve lsp.Server at call time through the shared
    # lsp module, so patch the attribute there (undone by monkeypatch).
    monkeypatch.setattr(lsp, "Server", capturing_server)
    flags = ["-elim", "5", "-ems", "100", "-wsize", "4"]
    st = threading.Thread(
        target=srunner.main, args=(["-port", str(port)] + flags,), daemon=True
    )
    st.start()
    deadline = time.time() + 5
    while "server" not in created and time.time() < deadline:
        time.sleep(0.02)
    assert "server" in created, "srunner.main never bound its server"

    monkeypatch.setattr("sys.stdin", io.StringIO("ping pong\n"))
    rc = crunner.main(["-host", "127.0.0.1", "-port", str(port)] + flags)
    assert rc == 0
    assert capsys.readouterr().out.splitlines() == ["[echo] ping", "[echo] pong"]

    created["server"].close()  # unblocks run_server -> srunner.main returns
    st.join(timeout=5)
    assert not st.is_alive()
