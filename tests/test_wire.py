"""B1 golden tests: byte-compatibility with Go encoding/json on the frozen
wire structs (reference lsp/message.go, bitcoin/message.go, lsp/params.go).

Golden strings below were derived from Go's documented marshalling rules:
field names are the exported struct names, []byte marshals to std-base64
(null when nil), ints as plain numbers, no whitespace.
"""

import json

from bitcoin_miner_tpu import bitcoin
from bitcoin_miner_tpu import lsp


class TestLspMessage:
    def test_connect_golden(self):
        # Go: json.Marshal(NewConnect()) with nil payload -> null
        assert (
            lsp.Message.connect().marshal()
            == b'{"Type":0,"ConnID":0,"SeqNum":0,"Size":0,"Payload":null}'
        )

    def test_data_golden(self):
        m = lsp.Message.data(5, 3, 4, b"abcd")
        assert (
            m.marshal()
            == b'{"Type":1,"ConnID":5,"SeqNum":3,"Size":4,"Payload":"YWJjZA=="}'
        )

    def test_ack_golden(self):
        assert (
            lsp.Message.ack(7, 0).marshal()
            == b'{"Type":2,"ConnID":7,"SeqNum":0,"Size":0,"Payload":null}'
        )

    def test_roundtrip(self):
        m = lsp.Message.data(42, 17, 11, b"hello world")
        out = lsp.Message.unmarshal(m.marshal())
        assert out == m

    def test_unmarshal_go_produced_bytes(self):
        # A Data packet as the Go side would emit it.
        go_bytes = b'{"Type":1,"ConnID":1,"SeqNum":1,"Size":3,"Payload":"Zm9v"}'
        m = lsp.Message.unmarshal(go_bytes)
        assert m.type == lsp.MsgType.DATA
        assert (m.conn_id, m.seq_num, m.size, m.payload) == (1, 1, 3, b"foo")

    def test_unmarshal_junk_returns_none(self):
        assert lsp.Message.unmarshal(b"\xff\xfe not json") is None
        assert lsp.Message.unmarshal(b"[1,2,3]") is None

    def test_string_parity(self):
        # lsp/message.go:55-68 format "[Name connID seqNum payload?]"
        assert str(lsp.Message.connect()) == "[Connect 0 0]"
        assert str(lsp.Message.ack(3, 9)) == "[Ack 3 9]"
        assert str(lsp.Message.data(1, 2, 2, b"hi")) == "[Data 1 2 hi]"


class TestBitcoinMessage:
    def test_request_golden(self):
        m = bitcoin.Message.request("cmu440", 0, 9999)
        assert m.marshal() == (
            b'{"Type":1,"Data":"cmu440","Lower":0,"Upper":9999,"Hash":0,"Nonce":0}'
        )

    def test_result_golden_u64(self):
        # Values above 2^53 must round-trip exactly (Go uint64 semantics).
        h = (1 << 64) - 3
        m = bitcoin.Message.result(h, 123456789012345678)
        obj = json.loads(m.marshal())
        assert obj["Hash"] == h
        assert obj["Nonce"] == 123456789012345678
        assert bitcoin.Message.unmarshal(m.marshal()) == m

    def test_join_golden(self):
        assert bitcoin.Message.join().marshal() == (
            b'{"Type":0,"Data":"","Lower":0,"Upper":0,"Hash":0,"Nonce":0}'
        )

    def test_string_parity(self):
        assert str(bitcoin.Message.join()) == "[Join]"
        assert str(bitcoin.Message.request("d", 1, 2)) == "[Request d 1 2]"
        assert str(bitcoin.Message.result(10, 20)) == "[Result 10 20]"

    def test_unmarshal_rejects_invalid_u64(self):
        # Go json.Unmarshal errors on these for uint64 struct fields; a
        # poison Request must never reach the scheduler (it would crash the
        # miner assigned to it).
        base = '{"Type":1,"Data":"x","Lower":%s,"Upper":10,"Hash":0,"Nonce":0}'
        for bad in ("-5", "1.7", '"12"', "true", str(1 << 64)):
            assert bitcoin.Message.unmarshal((base % bad).encode()) is None, bad
        assert bitcoin.Message.unmarshal((base % "0").encode()) is not None

    def test_unmarshal_rejects_non_string_data(self):
        raw = b'{"Type":1,"Data":["x"],"Lower":0,"Upper":9,"Hash":0,"Nonce":0}'
        assert bitcoin.Message.unmarshal(raw) is None

    def test_unmarshal_rejects_non_int_type(self):
        raw = b'{"Type":1.0,"Data":"x","Lower":0,"Upper":9,"Hash":0,"Nonce":0}'
        assert bitcoin.Message.unmarshal(raw) is None


class TestParams:
    def test_defaults(self):
        p = lsp.Params()
        assert (p.epoch_limit, p.epoch_millis, p.window_size) == (5, 2000, 1)
        assert str(p) == "[EpochLimit: 5, EpochMillis: 2000, WindowSize: 1]"

    def test_max_message_size(self):
        assert lsp.MAX_MESSAGE_SIZE == 1000


class TestHashOracle:
    def test_hash_known_values(self):
        # Independently computed: SHA256(b"cmu440 0")[:8] big-endian.
        import hashlib

        for msg, nonce in [("cmu440", 0), ("cmu440", 12345), ("hello", 999999)]:
            d = hashlib.sha256(f"{msg} {nonce}".encode()).digest()
            assert bitcoin.hash_nonce(msg, nonce) == int.from_bytes(d[:8], "big")

    def test_min_hash_range_matches_bruteforce(self):
        h, n = bitcoin.min_hash_range("cmu440", 0, 500)
        best = min(
            ((bitcoin.hash_nonce("cmu440", i), i) for i in range(501)),
        )
        assert (h, n) == best


class TestCodecFuzz:
    """Malformed datagrams must decode to None (dropped), never raise —
    a junk UDP packet must not kill a transport loop or the scheduler."""

    def _fuzz_inputs(self, seed=0, count=400):
        import json
        import random

        rng = random.Random(seed)
        cases = [
            b"", b"{", b"[]", b"null", b"5", b'"str"', b"\xff\xfe\x00",
            b'{"Type": "x"}', b'{"Type": 99}', b'{"Type": true}',
            b'{"Payload": "!!!notb64"}', b'{"Payload": 5}',
            b'{"SeqNum": "NaN"}', b'{"Size": []}', b'{"ConnID": {}}',
            b'{"Lower": -1}', b'{"Upper": 18446744073709551616}',
            b'{"Hash": 1.5}', b'{"Nonce": true}', b'{"Data": 7}',
        ]
        for _ in range(count):
            n = rng.randint(0, 60)
            cases.append(bytes(rng.randrange(256) for _ in range(n)))
            # Structured junk: random JSON with reference-ish keys.
            obj = {
                rng.choice(["Type", "ConnID", "SeqNum", "Size", "Payload",
                            "Data", "Lower", "Upper", "Hash", "Nonce", "X"]):
                rng.choice([rng.randint(-(2**70), 2**70), "x", None, True,
                            [1], {"a": 1}, 1.25])
                for _ in range(rng.randint(0, 4))
            }
            cases.append(json.dumps(obj).encode())
        return cases

    def test_lsp_unmarshal_never_raises(self):
        for buf in self._fuzz_inputs(seed=1):
            m = lsp.Message.unmarshal(buf)
            assert m is None or isinstance(m, lsp.Message)

    def test_bitcoin_unmarshal_never_raises(self):
        for buf in self._fuzz_inputs(seed=2):
            m = bitcoin.Message.unmarshal(buf)
            assert m is None or isinstance(m, bitcoin.Message)

    def test_valid_messages_survive_fuzz_suite(self):
        # Sanity: the fuzz helpers didn't accidentally cover valid shapes.
        assert lsp.Message.unmarshal(lsp.Message.connect().marshal())
        assert bitcoin.Message.unmarshal(bitcoin.Message.join().marshal())
