"""Kernel correctness: the TPU sweep tiers vs the hashlib oracle (B5/B6).

The correctness contract (reference ``bitcoin/hash.go:13-17``): for every
nonce, ``Hash = BigEndian.Uint64(SHA256(b"<data> <nonce-decimal>")[:8])``,
and a range sweep returns the lexicographic min with lowest-nonce ties.
Ranges here deliberately cross decimal-digit-count boundaries — the hashed
string's length changes there, which is the hard part of the kernel layout
(SURVEY §7 hard part 3).

Test shapes stay small (low ``max_k``, short ranges): every distinct
(layout, k, batch) class is a fresh XLA:CPU compile, and Pallas-interpret
executes tiles in Python — big shapes belong on real TPU via bench.py.
"""

import hashlib

import pytest

from bitcoin_miner_tpu.bitcoin.hash import hash_nonce, min_hash_range
from bitcoin_miner_tpu.ops.sha256 import build_layout, digest_u64_py
from bitcoin_miner_tpu.ops.sweep import decompose_range, sweep_min_hash


class TestLayout:
    @pytest.mark.parametrize("data", [b"", b"x", b"cmu440", b"a" * 55, b"b" * 200])
    @pytest.mark.parametrize("digits", ["7", "42", "999", "18446744073709551615"])
    def test_layout_matches_hashlib(self, data, digits):
        layout = build_layout(data, len(digits))
        expect = int.from_bytes(
            hashlib.sha256(data + b" " + digits.encode()).digest()[:8], "big"
        )
        assert digest_u64_py(layout, digits) == expect

    def test_long_data_folds_midstate(self):
        # data >= 64 bytes: at least one whole block folds host-side
        layout = build_layout(b"q" * 130, 3)
        assert layout.n_tail_blocks < (130 + 1 + 3 + 9 + 63) // 64


class TestCompress:
    def test_unrolled_compress_matches_hashlib(self):
        """Direct check of the Mosaic-path compression (scalar shapes compile
        fast even on XLA:CPU) — the only CPU coverage of the unrolled form,
        which otherwise runs exclusively on real TPU."""
        import jax.numpy as jnp

        from bitcoin_miner_tpu.ops.sha256 import H0, compress

        msg = bytearray(64)
        msg[:3] = b"abc"
        msg[3] = 0x80
        msg[-8:] = (24).to_bytes(8, "big")
        w = [jnp.uint32(int.from_bytes(msg[i : i + 4], "big")) for i in range(0, 64, 4)]
        out = compress(tuple(jnp.uint32(int(x)) for x in H0), w)
        digest = b"".join(int(x).to_bytes(4, "big") for x in out)
        assert digest == hashlib.sha256(b"abc").digest()

    @pytest.mark.parametrize("p", [0, 3, 7, 16])
    def test_group_state_split_is_bit_identical(self, p):
        """ISSUE 14 contract: compress(stop_round=p) -> compress(
        group_state=) composes to the whole compression bit-exactly, for
        both round forms and CROSS-form (the factored xla tier produces
        the prefix and resumes with the same rolled fn; the pallas
        interpret path mixes via its comp shim)."""
        import jax.numpy as jnp

        from bitcoin_miner_tpu.ops.sha256 import H0, compress, compress_rolled

        msg = bytearray(64)
        msg[:3] = b"abc"
        msg[3] = 0x80
        msg[-8:] = (24).to_bytes(8, "big")
        w = [
            jnp.uint32(int.from_bytes(msg[i : i + 4], "big"))
            for i in range(0, 64, 4)
        ]
        st = tuple(jnp.uint32(int(x)) for x in H0)
        ref = [int(x) for x in compress(st, w)]
        for producer in (compress, compress_rolled):
            # Prefix consumes only w[0:p] — the factored kernels hand the
            # producer group-scalar words; the resume gets the full 16.
            gs = producer(st, w[:p], stop_round=p)
            assert gs[0] == p
            for resumer in (compress, compress_rolled):
                out = [int(x) for x in resumer(st, w, group_state=gs)]
                assert out == ref, (producer.__name__, resumer.__name__)
        # final_only output masks compose with the resume too.
        gs = compress(st, w, stop_round=p)
        fo = compress(st, w, group_state=gs, final_only=True)
        assert [int(fo[0]), int(fo[1])] == ref[:2]
        (h0,) = compress(st, w, group_state=gs, final_only="h0")
        assert int(h0) == ref[0]

    def test_stop_round_past_schedule_rejected(self):
        import jax.numpy as jnp

        from bitcoin_miner_tpu.ops.sha256 import H0, compress, compress_rolled

        w = [jnp.uint32(0)] * 16
        st = tuple(jnp.uint32(int(x)) for x in H0)
        for fn in (compress, compress_rolled):
            with pytest.raises(ValueError):
                fn(st, w, stop_round=17)


class TestFactorSplit:
    """The outer/inner digit split + per-group patch table (ISSUE 14)."""

    def test_split_positions_and_first_inner_word(self):
        layout = build_layout(b"cmu440", 10)
        sp = layout.factor(6, 3)
        assert (sp.k_out, sp.k_in) == (3, 3)
        low = layout.digit_pos[4:]
        assert sp.outer_pos == tuple(low[:3])
        assert sp.inner_pos == tuple(low[3:])
        assert sp.first_inner_word == min(dp.word for dp in sp.inner_pos)

    def test_invalid_k_in_rejected(self):
        from bitcoin_miner_tpu.ops.sha256 import factor_low_pos

        layout = build_layout(b"cmu440", 10)
        low = layout.digit_pos[4:]
        for bad in (0, 6, 7):
            with pytest.raises(ValueError):
                factor_low_pos(low, bad)

    def test_outer_patch_table_matches_ascii(self):
        from bitcoin_miner_tpu.ops.sha256 import outer_patch_table

        layout = build_layout(b"cmu440", 10)
        sp = layout.factor(6, 3)
        words, table = outer_patch_table(sp.outer_pos)
        assert table.shape == (1000, len(words))
        for g in (0, 7, 427, 999):
            expect = {}
            for j, dp in enumerate(sp.outer_pos):
                digit = f"{g:03d}"[j]
                expect[dp.word] = expect.get(dp.word, 0) | (
                    ord(digit) << dp.shift
                )
            assert [int(x) for x in table[g]] == [expect[w] for w in words]


class TestDecompose:
    def test_cover_exact_no_overlap(self):
        lower, upper = 7, 123456
        seen = []
        for g in decompose_range(lower, upper, max_k=3):
            for c in g.chunks:
                seen.extend(range(c.base + c.lo_off, c.base + c.hi_off))
        assert seen == list(range(lower, upper + 1))

    def test_single_nonce(self):
        groups = list(decompose_range(5, 5))
        assert len(groups) == 1
        (c,) = groups[0].chunks
        assert (c.base + c.lo_off, c.base + c.hi_off) == (5, 6)

    def test_empty_range_raises(self):
        with pytest.raises(ValueError):
            list(decompose_range(10, 9))


class TestXlaTier:
    @pytest.mark.parametrize(
        "data,lo,hi",
        [
            ("cmu440", 0, 1205),       # crosses 1->2->3->4 digit boundaries
            ("x", 95, 1205),           # partial buckets on both ends
            ("", 0, 150),              # empty job data
            ("padding-edge-55bytes-" + "z" * 33, 1, 99),  # 2-block tail
        ],
    )
    def test_matches_oracle(self, data, lo, hi):
        r = sweep_min_hash(data, lo, hi, backend="xla", max_k=2)
        assert (r.hash, r.nonce) == min_hash_range(data, lo, hi)
        assert r.lanes_swept == hi - lo + 1

    def test_single_nonce_range(self):
        r = sweep_min_hash("solo", 12345, 12345, backend="xla", max_k=2)
        assert (r.hash, r.nonce) == (hash_nonce("solo", 12345), 12345)

    def test_20_digit_nonces(self):
        # uint64-max territory: 2^64-1 has 20 digits (bitcoin/message.go:21)
        top = (1 << 64) - 1
        r = sweep_min_hash("big", top - 50, top, backend="xla", max_k=1)
        assert (r.hash, r.nonce) == min_hash_range("big", top - 50, top)


class TestPallasTier:
    """Pallas kernel in interpreter mode (Mosaic needs real TPU hardware);
    bit-exactness of the same kernel compiled for TPU is rechecked by
    bench.py on the real chip."""

    def test_matches_oracle_small(self):
        r = sweep_min_hash(
            "abc", 95, 321, backend="pallas", interpret=True, batch=2, max_k=2
        )
        assert (r.hash, r.nonce) == min_hash_range("abc", 95, 321)

    def test_matches_xla_tier_across_boundary(self):
        data, lo, hi = "cmu440", 985, 1040
        rp = sweep_min_hash(
            data, lo, hi, backend="pallas", interpret=True, batch=2, max_k=2
        )
        rx = sweep_min_hash(data, lo, hi, backend="xla", max_k=2)
        assert (rp.hash, rp.nonce) == (rx.hash, rx.nonce)

    def test_non_default_tile(self):
        # The autotune path plumbs tile through sweep_min_hash; a clamped
        # non-default tile must stay bit-exact.
        r = sweep_min_hash(
            "abc", 95, 321, backend="pallas", interpret=True,
            batch=2, max_k=2, tile=2048,
        )
        assert (r.hash, r.nonce) == min_hash_range("abc", 95, 321)

    def test_group_fold_multiple_chunks_per_program(self):
        # batch=4 with cpb=2: two chunk rows fold inside each grid program
        # (the per-group running-min path), and a range that doesn't fill
        # all rows leaves a MIXED group whose padding row must mask out.
        r = sweep_min_hash(
            "abc", 95, 321, backend="pallas", interpret=True,
            batch=4, cpb=2, max_k=2,
        )
        assert (r.hash, r.nonce) == min_hash_range("abc", 95, 321)

    def test_group_fold_tie_breaks_to_lowest_nonce(self):
        # Duplicate rows covering the same range tie on (h0, h1) in the
        # SAME program's group fold; the winner must be the lower row.
        from bitcoin_miner_tpu.ops.pallas_sha256 import make_pallas_minhash
        import numpy as np

        layout = build_layout(b"tie", 3)
        fn = make_pallas_minhash(
            layout.n_tail_blocks, layout.digit_pos[1:], 2,
            batch=2, cpb=2, interpret=True,
        )
        midstate = np.array(layout.midstate, dtype=np.uint32)
        row = np.array(layout.tail_template, dtype=np.uint64)
        dp = layout.digit_pos[0]
        row[dp.word] |= np.uint64(ord("1") << dp.shift)
        tailcb = np.tile(
            np.concatenate([row, [0, 100]]).astype(np.uint32), (2, 1)
        )
        _h0, _h1, idx = fn(midstate, tailcb)
        assert int(idx) < 100  # row 0, not the duplicate row 1

    def test_non_divisor_cpb_rejected(self):
        with pytest.raises(ValueError, match="cpb"):
            sweep_min_hash(
                "abc", 95, 99, backend="pallas", interpret=True,
                batch=4, cpb=3, max_k=2,
            )

    def test_digit_words_straddle_tail_blocks(self):
        # 61-byte data + 3-digit nonces: digit bytes 62..64 span words
        # 15 (block 0) and 16 (block 1) — both tail blocks carry vector
        # words, the layout class where constant-folding must not leak.
        data = "s" * 61
        lay = build_layout(data.encode(), 3)
        words = {p.word for p in lay.digit_pos[1:]}  # k=2 low digits
        assert min(words) < 16 <= max(words), words
        r = sweep_min_hash(
            data, 100, 460, backend="pallas", interpret=True, batch=2, max_k=2
        )
        assert (r.hash, r.nonce) == min_hash_range(data, 100, 460)

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_fuzz_data_lengths_and_ranges(self, seed):
        """Seeded fuzz over data lengths x range positions, specifically
        sampling shapes where the in-kernel digit words STRADDLE a tail
        block boundary (e.g. 57-byte data, 10-digit nonces -> words 15/16)
        — the layout class where both blocks carry vector words and the
        scalar constant-folding must not leak across blocks."""
        import random

        rng = random.Random(seed)
        for _ in range(4):
            dlen = rng.choice([0, 3, 54, 55, 56, 57, 58, 60, 61, 120])
            data = "f" * dlen
            d = rng.choice([2, 3])  # digit counts (k <= 2 keeps compiles fast)
            if rng.random() < 0.3:
                # Straddle the digit-class boundary: two classes, two
                # kernels (dyn shares one executable per k), one min-fold.
                lo = 10**d - rng.randint(5, 40)
                hi = 10**d + rng.randint(5, 40)
            else:
                lo = rng.randint(10 ** (d - 1), 10**d - 30)
                hi = min(lo + rng.randint(1, 150), 10**d - 1)
            r = sweep_min_hash(
                data, lo, hi, backend="pallas", interpret=True, batch=2, max_k=2
            )
            assert (r.hash, r.nonce) == min_hash_range(data, lo, hi), (
                dlen, d, lo, hi,
            )

    def test_argmin_index_overflow_rejected(self):
        # batch * 10^k beyond int32 would silently corrupt the flat argmin
        # index (measured wrong nonces at k=7/batch=1024 on TPU) — the
        # kernel builder must refuse the shape outright.
        from bitcoin_miner_tpu.ops.pallas_sha256 import make_pallas_minhash
        from bitcoin_miner_tpu.ops.sha256 import build_layout

        layout = build_layout(b"cmu440", 10)
        with pytest.raises(ValueError, match="int32"):
            make_pallas_minhash(
                layout.n_tail_blocks, layout.digit_pos[3:], 7, batch=1024
            )

    def test_tie_break_same_dispatch_lowest_nonce(self):
        # Two chunk rows covering the SAME nonce range in one dispatch tie
        # on (h0, h1) everywhere; the lane accumulator + final cross-lane
        # argmin must resolve to the lowest flat index -> lowest nonce.
        from bitcoin_miner_tpu.ops.pallas_sha256 import make_pallas_minhash
        from bitcoin_miner_tpu.ops.sha256 import build_layout
        import numpy as np

        layout = build_layout(b"tie", 3)
        k = 2
        fn = make_pallas_minhash(
            layout.n_tail_blocks, layout.digit_pos[1:], k,
            batch=2, interpret=True,
        )
        midstate = np.array(layout.midstate, dtype=np.uint32)
        row = np.array(layout.tail_template, dtype=np.uint64)
        dp = layout.digit_pos[0]
        row[dp.word] |= np.uint64(ord("1") << dp.shift)  # high digit '1'
        tailcb = np.tile(
            np.concatenate([row, [0, 100]]).astype(np.uint32), (2, 1)
        )
        h0, h1, idx = fn(midstate, tailcb)
        # Both rows are nonces [100, 199]; the winner must come from row 0.
        assert int(idx) < 10**k


class TestHostRouting:
    """Tiny digit classes route to the host tier (HostFold) instead of
    compiling a one-off device kernel — the r5 fix for ~14 s/class
    first-use stalls (tracing + executable load) in the mining app."""

    def test_sweep_min_hash_host_budget_matches_oracle(self):
        from bitcoin_miner_tpu.ops.sweep import sweep_min_hash

        # Budget 10^4 routes d<=4 to the host; d=5 still goes to the device.
        r = sweep_min_hash(
            "cmu440", 7, 20002, backend="xla", max_k=2,
            host_lane_budget=10**4,
        )
        assert (r.hash, r.nonce) == min_hash_range("cmu440", 7, 20002)
        assert r.lanes_swept == 20002 - 7 + 1

    def test_host_routed_groups_skip_kernel_build(self):
        from bitcoin_miner_tpu.ops.sweep import run_sweep_dispatches, HostFold

        built, folds = [], []

        def get_kernel(layout, group):
            built.append(group.d)
            raise AssertionError("device kernel built for host-routed group")

        def consume(out, bases, n_lanes):
            assert isinstance(out, HostFold)
            folds.append((out.hash, out.nonce))

        lanes = run_sweep_dispatches(
            "cmu440", 7, 9999, max_k=2, batch=4,
            get_kernel=get_kernel, run_kernel=None, consume=consume,
            host_lane_budget=10**4,
        )
        assert not built
        assert lanes == 9999 - 7 + 1
        assert min(folds) == min_hash_range("cmu440", 7, 9999)

    def test_pipeline_auto_budget_matches_oracle(self):
        from bitcoin_miner_tpu.ops.sweep import SweepPipeline

        p = SweepPipeline(backend="xla", max_k=2)  # auto host budget
        try:
            r = p.submit("cmu440", 3, 1234).result(timeout=300)
            assert (r.hash, r.nonce) == min_hash_range("cmu440", 3, 1234)
            assert r.lanes_swept == 1234 - 3 + 1
        finally:
            p.close()

    def test_prewarm_async_dedupes_and_skips_host_classes(self):
        from bitcoin_miner_tpu.ops.sweep import (
            SweepPipeline,
            auto_host_lane_budget,
        )

        p = SweepPipeline(backend="xla", max_k=2)
        try:
            host_d = 1
            assert 10**host_d <= auto_host_lane_budget()
            assert p.prewarm_async("cmu440", host_d) is False  # host-routed
            assert p.prewarm_async("cmu440", 21) is False  # beyond u64
            assert p.prewarm_async("cmu440", 9) is True
            assert p.prewarm_async("cmu440", 9) is False  # already warming
            # A sweep through the prewarmed class still matches the oracle.
            r = p.submit("cmu440", 10**8, 10**8 + 500).result(timeout=300)
            assert (r.hash, r.nonce) == min_hash_range(
                "cmu440", 10**8, 10**8 + 500
            )
        finally:
            p.close()


class TestDynKernel:
    """The digit-position-dynamic Pallas kernel: one executable serves all
    digit classes of a data length (contributions are runtime inputs)."""

    def test_one_executable_across_digit_classes(self):
        from bitcoin_miner_tpu.ops.sweep import _build_kernel, decompose_range
        from bitcoin_miner_tpu.ops.sha256 import build_layout

        kerns = []
        for d_lo in (10**7, 10**8, 10**9):
            group = next(decompose_range(d_lo, d_lo, max_k=6))
            layout = build_layout(b"cmu440", group.d)
            kerns.append(
                _build_kernel("pallas", 8, None, None, True, False, layout, group)
            )
        keys = {k.class_key for k in kerns}
        assert len(keys) == 1, "digit classes d=8..10 must share one kernel"

    @pytest.mark.parametrize("data", ["x", "cmu440", "abcdefgh"])
    def test_dyn_matches_oracle_across_phases(self, data):
        # Different data lengths shift digit_off mod 4 -> different window
        # alignments; each must stay bit-exact across a digit boundary.
        from bitcoin_miner_tpu.ops.sweep import sweep_min_hash

        r = sweep_min_hash(
            data, 9985, 10015, backend="pallas", interpret=True, max_k=2, batch=4
        )
        assert (r.hash, r.nonce) == min_hash_range(data, 9985, 10015)

    def test_window_rejects_out_of_range_digit(self):
        from bitcoin_miner_tpu.ops.pallas_sha256 import window_contribs_np
        from bitcoin_miner_tpu.ops.sha256 import build_layout

        layout = build_layout(b"cmu440", 10)
        low_pos = layout.digit_pos[4:]
        with pytest.raises(ValueError, match="window"):
            window_contribs_np(6, low_pos, 0, 1, 1024)

    def test_d1_class_falls_back_to_static_kernel(self):
        # d=1 has d == k, one short of the dyn window's d >= k+1 domain
        # (digit_off=7 for 'cmu440' puts its digit in word 1, below w_lo=2)
        # — the driver must fall back to the per-class static kernel, not
        # raise.  Regression test for the r5 review finding.
        from bitcoin_miner_tpu.ops.sweep import sweep_min_hash

        r = sweep_min_hash(
            "cmu440", 5, 15, backend="pallas", interpret=True,
            batch=2, max_k=2,
        )
        assert (r.hash, r.nonce) == min_hash_range("cmu440", 5, 15)

    def test_zero_tiles_shared_across_classes(self):
        from bitcoin_miner_tpu.ops.pallas_sha256 import (
            dyn_window, window_contribs_np, zero_tile_np,
        )
        from bitcoin_miner_tpu.ops.sha256 import build_layout

        zeros = set()
        for d in (8, 9, 10):
            layout = build_layout(b"cmu440", d)
            low_pos = layout.digit_pos[d - 6:]
            w_lo, w_hi = dyn_window(7, 16, 6)
            tiles = window_contribs_np(6, low_pos, w_lo, w_hi, 4096)
            zeros |= {id(t) for t in tiles if t is zero_tile_np(4096)}
        assert len(zeros) == 1, "untouched words must share ONE zero tile"


class TestSieve:
    """The two-stage sieve kernel (ISSUE 13): pass-1 survivor predicate
    ``h0 <= threshold`` + survivor-only pass-2 min-fold, on both backends.
    The adversarial matrix: exact ``h0 == threshold`` ties (which must
    conservatively survive), duplicate minimum hashes with the
    lowest-nonce tie-break, digit-class boundaries (9→10, 99→100), and
    the u64 upper edge — every case bit-exact vs the hashlib oracle."""

    BACKENDS = [
        ("xla", dict(backend="xla")),
        ("pallas", dict(backend="pallas", interpret=True, batch=2)),
    ]

    @pytest.mark.parametrize("name,kw", BACKENDS, ids=[b[0] for b in BACKENDS])
    @pytest.mark.parametrize(
        "lo,hi",
        [
            (5, 15),       # 9→10: d=1 (static pallas fallback) + d=2
            (93, 107),     # 99→100 digit-class boundary
            (985, 1040),   # 999→1000 (the dyn-kernel window shift)
        ],
    )
    def test_digit_class_boundaries(self, name, kw, lo, hi):
        r = sweep_min_hash("cmu440", lo, hi, max_k=2, sieve=True, **kw)
        assert (r.hash, r.nonce) == min_hash_range("cmu440", lo, hi)
        assert r.lanes_swept == hi - lo + 1

    @pytest.mark.parametrize("name,kw", BACKENDS, ids=[b[0] for b in BACKENDS])
    def test_u64_upper_edge(self, name, kw):
        top = (1 << 64) - 1
        r = sweep_min_hash("big", top - 50, top, max_k=1, sieve=True, **kw)
        assert (r.hash, r.nonce) == min_hash_range("big", top - 50, top)

    def test_multi_dispatch_threshold_tightens_bit_exact(self):
        # batch=2 at k=2 → many dispatches: later ones run against a
        # tightened running-min threshold and mostly skip pass 2; the
        # fold must stay bit-exact (cross-checked per-nonce below via
        # digest_u64_py so the layout machinery itself is in the loop).
        lo, hi = 100, 2099
        r = sweep_min_hash(
            "cmu440", lo, hi, backend="xla", max_k=2, batch=2, sieve=True
        )
        assert (r.hash, r.nonce) == min_hash_range("cmu440", lo, hi)
        best = None
        for n in range(lo, hi + 1):
            digits = str(n)
            layout = build_layout(b"cmu440", len(digits))
            cand = (digest_u64_py(layout, digits), n)
            if best is None or cand < best:
                best = cand
        assert (r.hash, r.nonce) == best

    # ---------------------------------------------------- direct kernel calls

    def _tie_setup(self):
        """One chunk row of nonces [100, 199] for data 'tie' (d=3, k=2)
        plus the oracle's (min h0, min h1, argmin lane) over it."""
        import numpy as np

        layout = build_layout(b"tie", 3)
        h, n = min_hash_range("tie", 100, 199)
        row = np.array(layout.tail_template, dtype=np.uint64)
        dp = layout.digit_pos[0]
        row[dp.word] |= np.uint64(ord("1") << dp.shift)
        midstate = np.array(layout.midstate, dtype=np.uint32)
        return layout, midstate, row, (h >> 32, h & 0xFFFFFFFF, n - 100)

    def test_xla_threshold_tie_survives(self):
        """``h0 == threshold`` exactly: the tie must survive pass 1 —
        a strict predicate would lose a lane that still wins on (h1,
        nonce)."""
        import jax.numpy as jnp
        import numpy as np

        from bitcoin_miner_tpu.ops.sweep import make_kernel_body

        layout, midstate, row, (eh0, eh1, elane) = self._tie_setup()
        kern = make_kernel_body(
            layout.n_tail_blocks, layout.digit_pos[1:], 2, batch=1,
            rolled=True, sieve=True,
        )
        tail_const = row.astype(np.uint32)[None, :]
        bounds = np.array([[0, 100]], dtype=np.int32)
        h0, h1, idx = kern(
            jnp.asarray(midstate), jnp.asarray(tail_const),
            jnp.asarray(bounds), jnp.uint32(eh0),  # thresh == exact min h0
        )
        assert (int(h0), int(h1), int(idx)) == (eh0, eh1, elane)

    def test_xla_threshold_below_min_prunes_everything(self):
        """threshold strictly below the range's min h0: no survivor, the
        I32_MAX sentinel comes back, and the host keeps its running best
        — proves the sieve actually prunes rather than vacuously passing."""
        import jax.numpy as jnp
        import numpy as np

        from bitcoin_miner_tpu.ops.sweep import I32_MAX, make_kernel_body

        layout, midstate, row, (eh0, _eh1, _elane) = self._tie_setup()
        assert eh0 > 0, "degenerate oracle minimum"
        kern = make_kernel_body(
            layout.n_tail_blocks, layout.digit_pos[1:], 2, batch=1,
            rolled=True, sieve=True,
        )
        tail_const = row.astype(np.uint32)[None, :]
        bounds = np.array([[0, 100]], dtype=np.int32)
        _h0, _h1, idx = kern(
            jnp.asarray(midstate), jnp.asarray(tail_const),
            jnp.asarray(bounds), jnp.uint32(eh0 - 1),
        )
        assert int(idx) == I32_MAX

    def test_pallas_sieve_threshold_tie_survives(self):
        """Same tie contract through the REAL prize path: the pallas
        sieve kernel's SMEM threshold scratch + survivor-only pass 2."""
        import numpy as np

        from bitcoin_miner_tpu.ops.pallas_sha256 import make_pallas_minhash

        layout, midstate, row, (eh0, eh1, elane) = self._tie_setup()
        fn = make_pallas_minhash(
            layout.n_tail_blocks, layout.digit_pos[1:], 2,
            batch=1, interpret=True, sieve=True,
        )
        tailcb = np.concatenate([row, [0, 100]]).astype(np.uint32)[None, :]
        thresh = np.array([eh0 ^ 0x80000000], dtype=np.uint32).view(np.int32)
        h0, h1, idx = fn(midstate, tailcb, thresh)
        assert (int(h0), int(h1), int(idx)) == (eh0, eh1, elane)
        # And strictly below the min: everything pruned.
        from bitcoin_miner_tpu.ops.sweep import I32_MAX

        thresh = np.array([(eh0 - 1) ^ 0x80000000], dtype=np.uint32).view(
            np.int32
        )
        _h0, _h1, idx = fn(midstate, tailcb, thresh)
        assert int(idx) == I32_MAX

    def test_pallas_sieve_duplicate_minimum_lowest_nonce(self):
        """Duplicate rows covering the same range tie on (h0, h1)
        everywhere; the sieve kernel's pass 2 must still resolve to the
        lowest flat index → lowest nonce (same contract as the baseline
        kernel's tie tests above)."""
        import numpy as np

        from bitcoin_miner_tpu.ops.pallas_sha256 import make_pallas_minhash

        layout, midstate, row, (eh0, eh1, _elane) = self._tie_setup()
        fn = make_pallas_minhash(
            layout.n_tail_blocks, layout.digit_pos[1:], 2,
            batch=2, cpb=2, interpret=True, sieve=True,
        )
        tailcb = np.tile(
            np.concatenate([row, [0, 100]]).astype(np.uint32), (2, 1)
        )
        thresh = np.array([0xFFFFFFFF ^ 0x80000000], dtype=np.uint32).view(
            np.int32
        )  # loose: everything survives, both duplicate rows fold
        h0, h1, idx = fn(midstate, tailcb, thresh)
        assert (int(h0), int(h1)) == (eh0, eh1)
        assert int(idx) < 100  # row 0, not the duplicate row 1


class TestFactored:
    """Factored-nonce compression (ISSUE 14): outer/inner digit
    decomposition with a per-group scalar round prefix, on BOTH
    backends, plain and composed with the PR-13 sieve.  The adversarial
    matrix mirrors TestSieve's: digit-class boundaries (9→10, 99→100,
    999→1000), the u64 upper edge (where k=1 leaves nothing to factor
    and the baseline fallback must ride along silently), duplicate
    minima with the lowest-nonce tie-break through the factored pallas
    kernel, threshold ties/prunes through its SMEM scratch, and a
    multi-dispatch leg cross-checked per-nonce against digest_u64_py —
    every case bit-exact."""

    BACKENDS = [
        ("xla", dict(backend="xla")),
        ("pallas", dict(backend="pallas", interpret=True, batch=2)),
    ]

    @pytest.mark.parametrize("name,kw", BACKENDS, ids=[b[0] for b in BACKENDS])
    @pytest.mark.parametrize(
        "lo,hi",
        [
            (5, 15),       # 9→10: d=1 (k=1 → unfactorable fallback) + d=2
            (93, 107),     # 99→100 digit-class boundary
            (985, 1040),   # 999→1000
        ],
    )
    def test_digit_class_boundaries(self, name, kw, lo, hi):
        r = sweep_min_hash(
            "cmu440", lo, hi, max_k=2, factored=True, sieve=False, **kw
        )
        assert (r.hash, r.nonce) == min_hash_range("cmu440", lo, hi)
        assert r.lanes_swept == hi - lo + 1

    @pytest.mark.parametrize("name,kw", BACKENDS, ids=[b[0] for b in BACKENDS])
    @pytest.mark.parametrize("lo,hi", [(93, 107), (985, 1040)])
    def test_factored_sieve_composition(self, name, kw, lo, hi):
        # Pass 1 h0-only AND pass 2 resume from ONE shared group prefix.
        r = sweep_min_hash(
            "cmu440", lo, hi, max_k=2, factored=True, sieve=True, **kw
        )
        assert (r.hash, r.nonce) == min_hash_range("cmu440", lo, hi)

    @pytest.mark.parametrize("name,kw", BACKENDS, ids=[b[0] for b in BACKENDS])
    def test_u64_upper_edge(self, name, kw):
        top = (1 << 64) - 1
        r = sweep_min_hash(
            "big", top - 50, top, max_k=1, factored=True, sieve=True, **kw
        )
        assert (r.hash, r.nonce) == min_hash_range("big", top - 50, top)

    def test_multi_dispatch_threshold_tightens_bit_exact(self):
        # Factored + sieve over many dispatches: the threshold tightens
        # host-side between dispatches AND across the group loop inside
        # each; the fold must stay bit-exact per-nonce via digest_u64_py
        # (the layout machinery itself in the loop, like TestSieve's).
        lo, hi = 100, 2099
        r = sweep_min_hash(
            "cmu440", lo, hi, backend="xla", max_k=2, batch=2,
            factored=True, sieve=True,
        )
        best = None
        for n in range(lo, hi + 1):
            digits = str(n)
            layout = build_layout(b"cmu440", len(digits))
            cand = (digest_u64_py(layout, digits), n)
            if best is None or cand < best:
                best = cand
        assert (r.hash, r.nonce) == best

    # ---------------------------------------------------- direct kernel calls

    def _tie_setup(self):
        """Same fixture as TestSieve: one chunk row of [100, 199] for
        'tie' (d=3, k=2 → k_in=1, 10 outer groups of 10 lanes)."""
        import numpy as np

        layout = build_layout(b"tie", 3)
        h, n = min_hash_range("tie", 100, 199)
        row = np.array(layout.tail_template, dtype=np.uint64)
        dp = layout.digit_pos[0]
        row[dp.word] |= np.uint64(ord("1") << dp.shift)
        midstate = np.array(layout.midstate, dtype=np.uint32)
        return layout, midstate, row, (h >> 32, h & 0xFFFFFFFF, n - 100)

    def test_pallas_factored_threshold_tie_survives_and_prunes(self):
        """h0 == threshold survives pass 1 through the factored sieve
        kernel's per-group scratch path; threshold strictly below the
        min prunes every group to the sentinel."""
        import numpy as np

        from bitcoin_miner_tpu.ops.pallas_sha256 import (
            make_pallas_minhash_factored,
        )

        layout, midstate, row, (eh0, eh1, elane) = self._tie_setup()
        fn = make_pallas_minhash_factored(
            layout.n_tail_blocks, layout.digit_pos[1:], 2, 1,
            batch=1, interpret=True, sieve=True,
        )
        tailcb = np.concatenate([row, [0, 100]]).astype(np.uint32)[None, :]
        thresh = np.array([eh0 ^ 0x80000000], dtype=np.uint32).view(np.int32)
        h0, h1, idx = fn(midstate, tailcb, thresh)
        assert (int(h0), int(h1), int(idx)) == (eh0, eh1, elane)
        from bitcoin_miner_tpu.ops.sweep import I32_MAX

        thresh = np.array([(eh0 - 1) ^ 0x80000000], dtype=np.uint32).view(
            np.int32
        )
        _h0, _h1, idx = fn(midstate, tailcb, thresh)
        assert int(idx) == I32_MAX

    def test_pallas_factored_duplicate_minimum_lowest_nonce(self):
        """Duplicate rows tie on (h0, h1) everywhere; the factored
        kernel's remapped global flat index must still resolve to row 0
        — the outer/inner remap cannot reorder the tie-break."""
        import numpy as np

        from bitcoin_miner_tpu.ops.pallas_sha256 import (
            make_pallas_minhash_factored,
        )

        layout, midstate, row, (eh0, eh1, _elane) = self._tie_setup()
        fn = make_pallas_minhash_factored(
            layout.n_tail_blocks, layout.digit_pos[1:], 2, 1,
            batch=2, cpb=2, interpret=True, sieve=False,
        )
        tailcb = np.tile(
            np.concatenate([row, [0, 100]]).astype(np.uint32), (2, 1)
        )
        h0, h1, idx = fn(midstate, tailcb)
        assert (int(h0), int(h1)) == (eh0, eh1)
        assert int(idx) < 100  # row 0, not the duplicate row 1

    def test_xla_factored_matches_direct_kernel(self):
        """The factored xla kernel body called directly (the sharded
        tier re-traces exactly this fn inside shard_map) agrees with the
        oracle's (h0, h1, lane) triple, runt bounds included."""
        import jax.numpy as jnp
        import numpy as np

        from bitcoin_miner_tpu.ops.sweep import make_kernel_body

        layout, midstate, row, _ = self._tie_setup()
        h, n = min_hash_range("tie", 130, 169)  # runt inside the chunk
        kern = make_kernel_body(
            layout.n_tail_blocks, layout.digit_pos[1:], 2, batch=1,
            rolled=True, factored=1,
        )
        tail_const = row.astype(np.uint32)[None, :]
        bounds = np.array([[30, 70]], dtype=np.int32)
        h0, h1, idx = kern(
            jnp.asarray(midstate), jnp.asarray(tail_const), jnp.asarray(bounds)
        )
        assert (int(h0), int(h1), int(idx)) == (h >> 32, h & 0xFFFFFFFF, n - 100)


class TestHotPlane:
    """The always-hot device plane (ISSUE 16): donated-carry dispatch
    steps with the device-resident running-min threshold, on both
    backends, plain and composed with the sieve and the factored tier.
    The adversarial matrix extends TestSieve's: digit-class boundaries,
    the u64 upper edge, exact (h0, h1) ties that must keep the CARRIED
    lower-nonce candidate through the device-side merge, donation
    correctness (no fresh allocations, no donation warnings), the
    one-dispatch threshold lag, and the injected-wedge drill through the
    hot fetch path — every case bit-exact vs the hashlib oracle."""

    BACKENDS = [
        ("xla", dict(backend="xla")),
        ("pallas", dict(backend="pallas", interpret=True, batch=2)),
    ]

    @pytest.mark.parametrize("name,kw", BACKENDS, ids=[b[0] for b in BACKENDS])
    @pytest.mark.parametrize(
        "lo,hi",
        [
            (5, 15),       # 9→10: d=1 (host/static fallback) + d=2
            (93, 107),     # 99→100 digit-class boundary
            (985, 1040),   # 999→1000 (the dyn-kernel window shift)
        ],
    )
    @pytest.mark.parametrize("sieve", [False, True], ids=["plain", "sieve"])
    def test_digit_class_boundaries(self, name, kw, lo, hi, sieve):
        r = sweep_min_hash(
            "cmu440", lo, hi, max_k=2, hot=True, sieve=sieve, **kw
        )
        assert (r.hash, r.nonce) == min_hash_range("cmu440", lo, hi)
        assert r.lanes_swept == hi - lo + 1

    @pytest.mark.parametrize("name,kw", BACKENDS, ids=[b[0] for b in BACKENDS])
    def test_u64_upper_edge(self, name, kw):
        top = (1 << 64) - 1
        r = sweep_min_hash(
            "big", top - 50, top, max_k=1, hot=True, sieve=True, **kw
        )
        assert (r.hash, r.nonce) == min_hash_range("big", top - 50, top)

    def test_multi_dispatch_hot_matches_per_chunk_and_oracle(self):
        # batch=2 at k=2 → many donated steps re-using ONE carry buffer;
        # the device-side merge must agree with the per-chunk host fold
        # AND the per-nonce oracle (layout machinery in the loop), with
        # the factored xla default riding along under the hot plane.
        lo, hi = 100, 2099
        r_hot = sweep_min_hash(
            "cmu440", lo, hi, backend="xla", max_k=2, batch=2,
            sieve=True, hot=True,
        )
        r_chunk = sweep_min_hash(
            "cmu440", lo, hi, backend="xla", max_k=2, batch=2,
            sieve=True, hot=False,
        )
        assert (r_hot.hash, r_hot.nonce) == (r_chunk.hash, r_chunk.nonce)
        best = None
        for n in range(lo, hi + 1):
            digits = str(n)
            layout = build_layout(b"cmu440", len(digits))
            cand = (digest_u64_py(layout, digits), n)
            if best is None or cand < best:
                best = cand
        assert (r_hot.hash, r_hot.nonce) == best

    def test_no_donation_warnings(self):
        # donate_argnums only elides the allocation when XLA actually
        # aliases the buffer; a layout mismatch falls back to a copy and
        # WARNS.  The zero-alloc claim requires silence on both backends.
        import warnings

        with warnings.catch_warnings():
            warnings.simplefilter("error", UserWarning)
            for _name, kw in self.BACKENDS:
                r = sweep_min_hash(
                    "cmu440", 95, 1205, max_k=2, hot=True, sieve=True, **kw
                )
                assert (r.hash, r.nonce) == min_hash_range("cmu440", 95, 1205)

    # ---------------------------------------------------- direct loop drive

    def _tie_setup(self):
        """Same fixture as TestSieve: one chunk row of [100, 199] for
        'tie' (d=3, k=2) plus the oracle triple over it."""
        import numpy as np

        layout = build_layout(b"tie", 3)
        h, n = min_hash_range("tie", 100, 199)
        row = np.array(layout.tail_template, dtype=np.uint64)
        dp = layout.digit_pos[0]
        row[dp.word] |= np.uint64(ord("1") << dp.shift)
        midstate = np.array(layout.midstate, dtype=np.uint32)
        return layout, midstate, row, (h >> 32, h & 0xFFFFFFFF, n - 100)

    def test_hot_loop_donation_tie_and_pruning(self):
        """Drive :class:`_HotLoop` directly through two dispatches of the
        SAME descriptor: (a) the donated carry re-uses ONE device buffer
        (zero fresh accumulator allocations); (b) after dispatch 1 drains,
        ``carry[0]`` already equals that dispatch's min h0 — the
        one-dispatch threshold lag the staleness gauge records; (c)
        dispatch 2 produces an exact (h0, h1) tie which must keep the
        CARRIED ``best_seq == 0`` candidate; (d) probe drains prune the
        seq->descriptor map to O(in-flight); (e) ``finish()`` resolves
        the carry to the oracle's (hash, nonce)."""
        import numpy as np

        from bitcoin_miner_tpu.ops.sweep import (
            _HotLoop, make_kernel_body,
        )
        from bitcoin_miner_tpu.utils.metrics import METRICS

        layout, midstate, row, (eh0, eh1, elane) = self._tie_setup()
        kern = make_kernel_body(
            layout.n_tail_blocks, layout.digit_pos[1:], 2, batch=1,
            rolled=True, sieve=True,
        )
        tail_const = row.astype(np.uint32)[None, :]
        bounds = np.array([[0, 100]], dtype=np.int32)
        refills0 = METRICS.get("sweep.ring_refills")
        donated0 = METRICS.get("sweep.donated_dispatches")
        loop = _HotLoop("xla", True)
        tok1 = loop.dispatch(kern, midstate, tail_const, bounds)
        loop.drain(tok1, [100], 100)
        ptrs1 = tuple(c.unsafe_buffer_pointer() for c in loop.carry)
        # (b) zero-staleness: the carried threshold is already this
        # dispatch's min h0 — no host round-trip, no in-flight lag.
        # Read it through the PROBE, never the carry: materialising a
        # carry element host-side pins its buffer (jax caches the numpy
        # view) and the next donation would silently fall back to a copy
        # — the exact failure mode the probe protocol exists to prevent.
        assert int(np.asarray(tok1.probe)[0]) == eh0
        assert METRICS.gauge("kernel.thresh_staleness") == 1.0
        tok2 = loop.dispatch(kern, midstate, tail_const, bounds)
        loop.drain(tok2, [100], 100)
        ptrs2 = tuple(c.unsafe_buffer_pointer() for c in loop.carry)
        # (a) donation: the steady-state step wrote the carry IN PLACE —
        # every accumulator buffer of dispatch 2 is a dispatch-1 buffer.
        assert ptrs2 == ptrs1
        # (c) the exact tie kept the carried dispatch-0 candidate.
        assert int(np.asarray(tok2.probe)[1]) == 0
        # (d) drain pruned the duplicate descriptor (seq 1 lost the tie).
        assert set(loop._bases) == {0}
        # (e) one carry fetch resolves to the oracle candidate.
        assert loop.finish() == ((eh0 << 32) | eh1, 100 + elane)
        assert METRICS.get("sweep.ring_refills") - refills0 == 2
        assert METRICS.get("sweep.donated_dispatches") - donated0 == 2

    def test_hot_loop_pallas_tie_survives_carried_threshold(self):
        """Same tie contract through the REAL prize path: the carried
        ``best_h0`` is sign-flipped ON DEVICE (:func:`_flip_thresh_traced`)
        into the pallas sieve kernel's SMEM threshold, and the exact-tie
        lane must survive pass 1 and keep the carried candidate."""
        import numpy as np

        from bitcoin_miner_tpu.ops.pallas_sha256 import make_pallas_minhash
        from bitcoin_miner_tpu.ops.sweep import _HotLoop

        layout, midstate, row, (eh0, eh1, elane) = self._tie_setup()
        fn = make_pallas_minhash(
            layout.n_tail_blocks, layout.digit_pos[1:], 2,
            batch=1, interpret=True, sieve=True,
        )
        tail_const = row.astype(np.uint32)[None, :]
        bounds = np.array([[0, 100]], dtype=np.int32)
        loop = _HotLoop("pallas", True)
        for _ in range(2):
            tok = loop.dispatch(fn, midstate, tail_const, bounds)
            loop.drain(tok, [100], 100)
        # Dispatch 2 sieved against thresh == eh0 exactly: the tie lane
        # survived pass 1 and the merge kept the dispatch-0 candidate.
        assert int(np.asarray(tok.probe)[1]) == 0
        assert loop.finish() == ((eh0 << 32) | eh1, 100 + elane)

    def test_hot_loop_all_pruned_returns_none(self):
        """A job whose every dispatch returns the sentinel (possible
        when the host fold owns every candidate) must finish() to None,
        not a bogus lane."""
        from bitcoin_miner_tpu.ops.sweep import _HotLoop

        loop = _HotLoop("xla", True)
        assert loop.finish() is None  # no dispatch at all

    def test_wedge_dispatch_fires_through_hot_path(self, monkeypatch):
        """``BMT_WEDGE_DISPATCH=1`` must hang the first fetch of a HOT
        pipeline exactly like the per-chunk drill (tokens flow through
        the same fetch queue), and the watchdog budget must abandon the
        tier and complete on the next rung."""
        from bitcoin_miner_tpu.apps import miner as miner_mod
        from bitcoin_miner_tpu.ops import sweep as sweep_mod

        monkeypatch.setenv("BMT_WEDGE_DISPATCH", "1")
        monkeypatch.setitem(sweep_mod._WEDGE_STATE, "fired", False)
        ts = miner_mod._TieredSearch(
            [
                ("xla-hot", lambda: miner_mod._PipelineSearch(
                    "xla", hot=True
                )),
                ("oracle", lambda: min_hash_range),
            ],
            wedge_seconds=4.0,
        )
        try:
            fut = ts.submit("wedgehot", 0, 80)
            assert fut.result(timeout=120) == min_hash_range("wedgehot", 0, 80)
            assert ts.active_tier == "oracle"
            assert sweep_mod._WEDGE_STATE["fired"]  # the hang was real
        finally:
            ts.close()


class TestPipelineLifecycle:
    """SweepPipeline edge behavior: close/submit ordering and concurrent
    submitters — the states a miner hits at shutdown and under the
    scheduler's 2-deep window."""

    def test_submit_after_close_raises(self):
        from bitcoin_miner_tpu.ops.sweep import SweepPipeline

        p = SweepPipeline(backend="xla", max_k=2, batch=2)
        p.close()
        with pytest.raises(RuntimeError, match="closed"):
            p.submit("cmu440", 0, 10)

    def test_jobs_submitted_before_close_still_resolve(self):
        from bitcoin_miner_tpu.ops.sweep import SweepPipeline

        p = SweepPipeline(backend="xla", max_k=2, batch=2, host_lane_budget=0)
        futs = [p.submit("cmu440", 1000 + 100 * i, 1099 + 100 * i)
                for i in range(3)]
        p.close()  # close() drains queued jobs, it does not abandon them
        for i, f in enumerate(futs):
            lo, hi = 1000 + 100 * i, 1099 + 100 * i
            r = f.result(timeout=300)
            assert (r.hash, r.nonce) == min_hash_range("cmu440", lo, hi)

    def test_concurrent_submitters_all_correct(self):
        import threading

        from bitcoin_miner_tpu.ops.sweep import SweepPipeline

        p = SweepPipeline(backend="xla", max_k=2, batch=2, host_lane_budget=0)
        results = {}
        lock = threading.Lock()

        def worker(i):
            lo, hi = 2000 + 137 * i, 2000 + 137 * i + 99
            r = p.submit("cmu440", lo, hi).result(timeout=300)
            with lock:
                results[i] = ((r.hash, r.nonce), min_hash_range("cmu440", lo, hi))

        try:
            ts = [threading.Thread(target=worker, args=(i,)) for i in range(6)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=300)
                assert not t.is_alive()
        finally:
            p.close()
        assert len(results) == 6
        for got, want in results.values():
            assert got == want


@pytest.mark.workloads
class TestBlake2bDeviceTier:
    """The second kernel family (ISSUE 20): the u32-pair BLAKE2b-64
    device kernel vs the workload's hashlib oracle.  The adversarial
    matrix mirrors TestSieve/TestFactored's: digit-class boundaries
    (9→10, 99→100, 999→1000), the u64 upper edge, duplicate minima with
    the lowest-nonce tie-break through a direct kernel call, a
    multi-dispatch leg cross-checked per-nonce against the pure-Python
    compression (the layout machinery itself in the loop), and the
    watchdog downgrade drill across the family's xla→cpu→hashlib chain."""

    @staticmethod
    def _wl():
        from bitcoin_miner_tpu import workloads

        return workloads.get("blake2b64")

    @pytest.mark.parametrize(
        "lo,hi",
        [
            (5, 15),       # 9→10: d=1 and d=2 classes in one sweep
            (93, 107),     # 99→100 digit-class boundary
            (985, 1040),   # 999→1000
        ],
    )
    def test_digit_class_boundaries(self, lo, hi):
        w = self._wl()
        r = sweep_min_hash("cmu440", lo, hi, backend="xla", max_k=2, workload=w)
        assert (r.hash, r.nonce) == w.min_range("cmu440", lo, hi)
        assert r.lanes_swept == hi - lo + 1

    def test_u64_upper_edge(self):
        w = self._wl()
        top = (1 << 64) - 1
        r = sweep_min_hash(
            "big", top - 50, top, backend="xla", max_k=1, workload=w
        )
        assert (r.hash, r.nonce) == w.min_range("big", top - 50, top)

    def test_sieve_threshold_operand_bit_exact(self):
        # The kernel's carried-threshold mask (built for the hot plane's
        # operand) must stay bit-exact when forced on.
        w = self._wl()
        r = sweep_min_hash(
            "cmu440", 93, 320, backend="xla", max_k=2, sieve=True, workload=w
        )
        assert (r.hash, r.nonce) == w.min_range("cmu440", 93, 320)

    @pytest.mark.parametrize("dlen", [126, 250])
    def test_tail_shape_classes_bit_exact(self, dlen):
        """The family's two adversarial tail shapes beyond the short-data
        tests above: a message straddling the 128-byte block boundary
        (digit bytes land past byte 128 → two tail blocks), and a prefix
        long enough that whole blocks fold into the midstate host-side.
        Each data LENGTH is its own compiled shape class, so two lengths
        buy the coverage without a compile per fuzz draw."""
        w = self._wl()
        data = "f" * dlen
        r = sweep_min_hash(data, 93, 107, backend="xla", max_k=2, workload=w)
        assert (r.hash, r.nonce) == w.min_range(data, 93, 107)

    def test_multi_dispatch_cross_checked_per_nonce(self):
        # batch=2 at k=2 → many dispatches across two digit classes; the
        # fold must agree per-nonce with the pure-Python compression
        # (digest64_py), putting the blake2b layout machinery itself in
        # the loop rather than trusting hashlib's message assembly.
        from bitcoin_miner_tpu.ops.blake2b import digest64_py

        w = self._wl()
        lo, hi = 100, 1299
        r = sweep_min_hash(
            "cmu440", lo, hi, backend="xla", max_k=2, batch=2, workload=w
        )
        best = None
        for n in range(lo, hi + 1):
            cand = (digest64_py(b"cmu440 " + str(n).encode()), n)
            if best is None or cand < best:
                best = cand
        assert (r.hash, r.nonce) == best

    def test_duplicate_minimum_lowest_nonce(self):
        """Duplicate chunk rows covering the same range tie on (h0, h1)
        everywhere; the kernel's flat argmin (and the factored remap
        behind it) must resolve to row 0 → the lowest nonce."""
        import jax.numpy as jnp
        import numpy as np

        from bitcoin_miner_tpu.ops.blake2b import (
            build_layout as b2_layout,
            make_blake2b_kernel_body,
        )

        w = self._wl()
        layout = b2_layout(b"tie", 3)
        h, n = w.min_range("tie", 100, 199)
        kern = make_blake2b_kernel_body(
            layout.msg_len, layout.tail_off, layout.n_tail_blocks,
            layout.live_words, layout.digit_pos[1:], 2, batch=2,
        )
        row = np.array(layout.tail_template, dtype=np.uint32)
        dp = layout.digit_pos[0]
        row[dp.word] |= np.uint32(ord("1") << dp.shift)  # high digit '1'
        tail_const = np.tile(row, (2, 1))
        bounds = np.array([[0, 100], [0, 100]], dtype=np.int32)
        midstate = np.array(layout.midstate, dtype=np.uint32)
        h0, h1, idx = kern(
            jnp.asarray(midstate), jnp.asarray(tail_const), jnp.asarray(bounds)
        )
        assert (int(h0), int(h1)) == (h >> 32, h & 0xFFFFFFFF)
        # Both rows are nonces [100, 199]; the winner must be row 0.
        assert int(idx) == n - 100

    def test_wedge_dispatch_downgrades_xla_to_cpu(self, monkeypatch):
        """The watchdog drill across the family's NEW 3-rung chain:
        ``BMT_WEDGE_DISPATCH=1`` hangs the blake2b xla pipeline's first
        fetch; the watchdog abandons the device rung and the chunk
        re-runs bit-exact on the cpu rung — hashlib still behind it."""
        from bitcoin_miner_tpu.apps import miner as miner_mod
        from bitcoin_miner_tpu.ops import sweep as sweep_mod
        from bitcoin_miner_tpu.utils.metrics import METRICS

        w = self._wl()
        monkeypatch.setenv("BMT_WEDGE_DISPATCH", "1")
        monkeypatch.setitem(sweep_mod._WEDGE_STATE, "fired", False)
        downgrades0 = METRICS.get("miner.tier_downgrades")
        ts = miner_mod._TieredSearch(
            [
                ("xla", lambda: w.make_async_search("xla")),
                ("cpu", lambda: w.make_async_search("cpu")),
                ("hashlib", lambda: w.min_range),
            ],
            wedge_seconds=4.0,
        )
        try:
            fut = ts.submit("b2wedge", 0, 120)
            assert fut.result(timeout=120) == w.min_range("b2wedge", 0, 120)
            assert ts.active_tier == "cpu"
            assert METRICS.get("miner.tier_downgrades") - downgrades0 == 1
            assert sweep_mod._WEDGE_STATE["fired"]  # the hang was real
        finally:
            ts.close()
