"""Test configuration.

Force JAX onto a virtual 8-device CPU platform so multi-chip sharding paths
(Mesh / shard_map / collectives) are exercised without TPU hardware.

Note: this environment's sitecustomize imports jax at interpreter boot with
``JAX_PLATFORMS`` already set, so env vars alone are too late — the platform
override must go through ``jax.config`` (backends initialize lazily, so this
still wins as long as no computation has run).  ``XLA_FLAGS`` is read at
backend init and can still be set here.  bench.py and the driver's graft
entry run outside pytest and therefore see the real TPU.
"""

from bitcoin_miner_tpu.utils.platform import (
    enable_compile_cache,
    force_virtual_cpu,
)

force_virtual_cpu(8)
# XLA:CPU compiles of the sweep kernels take seconds each; cache them across
# pytest runs so only the first invocation pays.
enable_compile_cache()
