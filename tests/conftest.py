"""Test configuration.

Force JAX onto a virtual 8-device CPU platform *before* jax is imported
anywhere, so multi-chip sharding paths (Mesh / shard_map / collectives) are
exercised without TPU hardware.  bench.py and the driver's graft entry run
outside pytest and therefore see the real TPU.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
