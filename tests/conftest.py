"""Test configuration.

Force JAX onto a virtual 8-device CPU platform so multi-chip sharding paths
(Mesh / shard_map / collectives) are exercised without TPU hardware.

Note: this environment's sitecustomize imports jax at interpreter boot with
``JAX_PLATFORMS`` already set, so env vars alone are too late — the platform
override must go through ``jax.config`` (backends initialize lazily, so this
still wins as long as no computation has run).  ``XLA_FLAGS`` is read at
backend init and can still be set here.  bench.py and the driver's graft
entry run outside pytest and therefore see the real TPU.
"""

import os

xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")
# XLA:CPU compiles of the sweep kernels take seconds each; cache them across
# pytest runs so only the first invocation pays.
jax.config.update("jax_compilation_cache_dir", "/tmp/bitcoin_miner_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
