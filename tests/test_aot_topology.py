"""AOT proof of the flagship multi-chip config without TPU hardware.

``BASELINE.json``'s scaling row promises the Pallas kernel under shard_map
with the three-stage ``lax.pmin`` cascade on a v5e-8 (SURVEY §2.3 row 1; the
scaled dimension is the reference's 2^64 nonce range,
``/root/reference/bitcoin/message.go:21``).  Real hardware in CI has one
chip, so this test compiles the exact config ahead-of-time against a virtual
``v5e:2x4`` *topology description* (``jax.experimental.topologies`` — a
compile-only PJRT TPU client, no chips needed) and asserts:

- lowering partitions over the 8-device mesh (SPMD),
- XLA inserts the cross-chip collectives (``all-reduce`` from the pmin
  cascade),
- Mosaic compiles the Pallas kernel for the v5e target (the
  ``tpu_custom_call`` survives into the final executable).

Together with test_parallel.py's interpret-mode oracle runs this makes the
sharded Pallas path compile-proven for the real target and value-proven on
the CPU mesh.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bitcoin_miner_tpu.ops.pallas_sha256 import dyn_params
from bitcoin_miner_tpu.ops.sha256 import build_layout
from bitcoin_miner_tpu.ops.sweep import decompose_range
from bitcoin_miner_tpu.parallel.sweep import (
    _make_sharded_kernel,
    _make_sharded_kernel_dyn,
)


@pytest.fixture(scope="module")
def v5e_mesh():
    from jax.experimental import topologies

    try:
        topo = topologies.get_topology_desc(
            platform="tpu", topology_name="v5e:2x4"
        )
    except RuntimeError as e:  # no libtpu compile-only client in this image
        # Deliberately narrow: an API change (TypeError/ValueError) must FAIL
        # loudly, not silently skip the repo's only Mosaic compile-proof.
        pytest.skip(f"TPU compile-only client unavailable: {e}")
    return Mesh(np.array(topo.devices).reshape(8), ("miners",))


# Both AOT legs ride the slow tier: they are broken on this image's
# jaxlib (Mosaic int-reduction lowering — pre-existing, tracked in
# ROADMAP's real-TPU follow-on), and the module fixture's
# ``initialize_pjrt_plugin("tpu")`` stalls a nondeterministic multi-minute
# retry on TPU-less hosts — a guaranteed-failure pair that can eat a third
# of the tier-1 wall budget.  Re-promote when the lowering works.
@pytest.mark.slow
def test_flagship_sharded_pallas_aot_compiles_v5e8(v5e_mesh):
    # The PRODUCTION flagship config: the digit-position-dynamic kernel
    # (one executable for all d in [7, 20]), k=6 (10^6-lane chunks),
    # per-device batch 1024 — exactly what sweep_min_hash_sharded builds
    # on real chips.
    data = b"bitcoin"
    group = next(decompose_range(10**9, 10**9 + 10**8, max_k=6))
    layout = build_layout(data, group.d)
    w_lo, w_hi = dyn_params(layout, group.k)
    per_dev_batch = 1024
    kern, n_pad = _make_sharded_kernel_dyn(
        layout.n_tail_blocks,
        w_lo,
        w_hi,
        group.k,
        per_dev_batch,
        v5e_mesh,
        "miners",
        False,  # interpret=False: real Mosaic lowering
    )

    nw = len(layout.tail_template)
    B = 8 * per_dev_batch
    row = NamedSharding(v5e_mesh, P("miners", None))
    rep = NamedSharding(v5e_mesh, P())
    rep2 = NamedSharding(v5e_mesh, P(None, None))
    contribs = tuple(
        jax.ShapeDtypeStruct((n_pad // 128, 128), jnp.uint32, sharding=rep2)
        for _ in range(w_hi - w_lo + 1)
    )
    lowered = kern.lower(
        jax.ShapeDtypeStruct((8,), jnp.uint32, sharding=rep),
        jax.ShapeDtypeStruct((B, nw), jnp.uint32, sharding=row),
        jax.ShapeDtypeStruct((B, 2), jnp.int32, sharding=row),
        *contribs,
    )
    compiled = lowered.compile()

    txt = compiled.as_text()
    # SPMD partitioning happened and the pmin cascade became cross-chip
    # collectives.
    assert "all-reduce" in txt, "pmin cascade did not lower to collectives"
    # Mosaic compiled the kernel for the TPU target (exactly this call
    # target — a generic custom-call would not prove the kernel survived).
    assert "tpu_custom_call" in txt, (
        "pallas kernel missing from the compiled executable"
    )
    # Outputs are the four replicated scalars of the collective min.
    assert len(compiled.output_shardings) == 4


@pytest.mark.slow  # see the note on the flagship leg above
def test_static_fallback_sharded_pallas_aot_compiles_v5e8(v5e_mesh):
    # The per-class static form must also partition + Mosaic-compile for
    # the v5e-8 target — built for a class production actually routes to
    # it: d == k = 1 with the digit byte one below the window (needs
    # digit_off % 4 == 3, i.e. len(data) % 4 == 2 — 'cmu440'; for most
    # data lengths even d=1 is dyn-eligible).
    data = b"cmu440"
    group = next(decompose_range(1, 9, max_k=6))
    layout = build_layout(data, group.d)
    assert group.d == group.k, "fallback test must use the d == k class"
    assert dyn_params(layout, group.k) is None, (
        "production routes this class to the static kernel"
    )
    low_pos = layout.digit_pos[layout.digit_count - group.k :]
    per_dev_batch = 1024
    kern = _make_sharded_kernel(
        layout.n_tail_blocks,
        low_pos,
        group.k,
        per_dev_batch,
        v5e_mesh,
        "miners",
        "pallas",
        False,
        False,
    )
    nw = len(layout.tail_template)
    B = 8 * per_dev_batch
    row = NamedSharding(v5e_mesh, P("miners", None))
    rep = NamedSharding(v5e_mesh, P())
    compiled = kern.lower(
        jax.ShapeDtypeStruct((8,), jnp.uint32, sharding=rep),
        jax.ShapeDtypeStruct((B, nw), jnp.uint32, sharding=row),
        jax.ShapeDtypeStruct((B, 2), jnp.int32, sharding=row),
    ).compile()
    txt = compiled.as_text()
    assert "all-reduce" in txt and "tpu_custom_call" in txt
