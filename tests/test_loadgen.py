"""tools/loadgen.py in tier-1: the serving-layer acceptance run at --fast
scale (ISSUE 3) — 8 clients, 50% duplicate signatures, every Result
bit-exact vs the hashlib oracle (the tool raises otherwise), coalesce/
cache hits visible in the gateway counters, and a repeat-submitted solved
job completing with zero chunks assigned."""

import importlib.util
import json
from pathlib import Path

import pytest

pytestmark = pytest.mark.gateway

REPO = Path(__file__).resolve().parents[1]


def _load_tool():
    spec = importlib.util.spec_from_file_location(
        "loadgen", REPO / "tools" / "loadgen.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_loadgen_fast_duplicate_heavy(capsys):
    loadgen = _load_tool()
    rc = loadgen.main(["--fast", "--clients", "8", "--dup", "0.5"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["metric"] == "loadgen_jobs_per_sec"
    assert out["value"] > 0
    assert out["clients"] == 8 and out["dup_fraction"] == 0.5
    assert out["distinct_signatures"] < out["jobs"]  # dups really happened
    # The acceptance counters: duplicates were deduplicated, not re-swept.
    gw = out["gateway_counters"]
    hits = gw.get("gateway.coalesced", 0) + gw.get("gateway.cache_hits", 0)
    # Every duplicate deduplicated (+1: the repeat probe is a cache hit).
    assert hits == out["jobs"] - out["distinct_signatures"] + 1
    assert gw.get("gateway.completed", 0) <= out["distinct_signatures"]
    # Repeat-submitted solved job: answered with ZERO chunks assigned.
    assert out["repeat_zero_chunks"] is True
    # The baseline leg re-swept duplicates; the gateway leg did not.
    assert out["swept_nonces"] <= out["baseline_swept_nonces"]


def test_loadgen_workload_is_seeded():
    loadgen = _load_tool()

    class A:
        jobs, dup, max_nonce, seed = 30, 0.5, 10_000, 11

    assert loadgen.build_workload(A) == loadgen.build_workload(A)
    assert loadgen.build_overlap_workload(A) == loadgen.build_overlap_workload(A)


def test_loadgen_fast_open_loop(capsys, monkeypatch):
    """The --open-loop leg at --fast scale (ISSUE 15): Poisson arrivals
    against the async event-loop ingress, every completed Result
    bit-exact vs the oracle (the tool raises otherwise), shed rate and
    latency quantiles stamped, and the repeat/sub-range zero-chunk
    probes still true THROUGH the async path.  BMT_SANITIZE=1 arms the
    race machinery over the ingress bridge for the whole run."""
    monkeypatch.setenv("BMT_SANITIZE", "1")
    loadgen = _load_tool()
    rc = loadgen.main(["--open-loop", "30", "--fast", "--miners", "2"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["metric"] == "loadgen_open_loop_completed_per_sec"
    assert out["mode"] == "open-loop" and out["ingress"] == "async"
    ol = out["open_loop"]
    # Open-loop accounting is exhaustive: every Poisson arrival completed,
    # failed (shed/timed out), or was cancelled at drain end (a wrong
    # answer would have raised above).
    assert ol["offered"] == ol["completed"] + ol["failed"] + ol["undrained"]
    assert ol["completed"] > 0 and ol["wrong"] == 0 and ol["undrained"] == 0
    assert 0.0 <= ol["shed_rate"] <= 1.0
    assert ol["latency_s"]["count"] == ol["completed"]
    assert ol["latency_s"]["p99"] >= ol["latency_s"]["p50"] >= 0.0
    # The serving layer's reuse machinery survives the async bridge.
    assert out["repeat_zero_chunks"] is True
    assert out["subrange_zero_chunks"] is True


@pytest.mark.intervals
def test_loadgen_fast_overlap_interval_store(capsys):
    """The --overlap leg at --fast scale (ISSUE 5): nested/overlapping
    ranges, every Result bit-exact vs the oracle (the tool raises
    otherwise), the interval store sweeping strictly fewer nonces than
    the exact-match-cache leg, span reuse visible in the counters, and a
    never-issued fully-covered SUB-RANGE answering with zero chunks."""
    loadgen = _load_tool()
    rc = loadgen.main(["--overlap", "--fast", "--clients", "4"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["metric"] == "loadgen_overlap_jobs_per_sec"
    assert out["mode"] == "overlap" and out["value"] > 0
    gw = out["span_counters"]
    # Span reuse really happened (full answers and/or remainder jobs)...
    assert gw.get("gateway.span_hits", 0) + gw.get("gateway.span_partial", 0) > 0
    assert gw.get("gateway.nonces_saved", 0) > 0
    # ...and it translated into strictly less device work than the
    # exact-match cache alone (the full-scale target — >=30% — is pinned
    # in BENCH_pr5.json; at --fast scale thread timing adds noise, so
    # tier-1 asserts the direction, not the magnitude).
    assert out["swept_nonces"] < out["exact_swept_nonces"]
    # The acceptance probes: exact repeat AND covered sub-range, zero chunks.
    assert out["repeat_zero_chunks"] is True
    assert out["subrange_zero_chunks"] is True
