"""Self-scaling capacity plane (ISSUE 18): deterministic policy suite.

Every test drives :class:`AutoscaleController` with an INJECTED clock and
synthetic evidence providers/actuators — no subprocesses, no wall-clock
sleeps, no ports — so hold/cooldown/retry semantics are pinned exactly:

- sustained burn scales up only after ``hold_ticks`` CONSECUTIVE ticks;
  a flapping alert never moves capacity;
- scale-down happens only via drain, held off by ``down_cooldown_s``
  from the LAST action in either direction (never retire what you just
  spawned);
- tenant re-weighting fires before capacity moves and restores on
  recovery;
- a failed actuation retries next tick OUTSIDE the cooldown gate;
- a cold-at-the-floor cell hands off once (axis b), never twice.

The closed loop against real worker subprocesses is the bench's job
(``tools/fleet_bench.py --autoscale``, test_fleet_bench's slow leg).
"""

from __future__ import annotations

import json

import pytest

from bitcoin_miner_tpu.autoscale import (
    AutoscaleConfig,
    AutoscaleController,
    CellActuator,
    ControllerPump,
    GatewayWeightActuator,
    parse_autoscale_config,
)
from bitcoin_miner_tpu.utils.metrics import METRICS

pytestmark = pytest.mark.autoscale


# --------------------------------------------------------------- fakes


class FakeWorkers:
    """Axis-a actuator double: live count moves instantly on
    spawn/drain (the real one's workers take time to exit — the policy
    must not care)."""

    def __init__(self, live: int = 1, fail_spawns: int = 0) -> None:
        self._live = live
        self.fail_spawns = fail_spawns
        self.spawns: list = []
        self.drains: list = []

    def live(self) -> int:
        return self._live

    def spawn(self, n: int) -> None:
        if self.fail_spawns > 0:
            self.fail_spawns -= 1
            raise OSError("exec failed")
        self._live += n
        self.spawns.append(n)

    def drain(self, n: int) -> None:
        self._live -= n
        self.drains.append(n)


class FakeWeights:
    def __init__(self) -> None:
        self.reweights: list = []
        self.restores = 0

    def reweight(self, weights: dict) -> None:
        self.reweights.append(dict(weights))

    def restore(self) -> None:
        self.restores += 1


class FakeCell:
    def __init__(self) -> None:
        self.drains = 0

    def drain_cell(self) -> None:
        self.drains += 1


class Harness:
    """One controller + mutable evidence + a hand-cranked clock."""

    def __init__(self, live: int = 1, fail_spawns: int = 0,
                 weights: bool = False, cell: bool = False,
                 **cfg_kw) -> None:
        self.now = 0.0
        self.alerts: list = []
        self.util: float | None = None
        self.workers = FakeWorkers(live=live, fail_spawns=fail_spawns)
        self.weights = FakeWeights() if weights else None
        self.cell = FakeCell() if cell else None
        self.ctl = AutoscaleController(
            self.workers,
            burn=lambda: self.alerts,
            utilization=lambda: self.util,
            weights=self.weights,
            cell=self.cell,
            config=AutoscaleConfig(**cfg_kw),
            clock=lambda: self.now,
        )

    def tick(self, dt: float = 0.0) -> dict:
        self.now += dt
        return self.ctl.tick()


def counter(name: str) -> float:
    return METRICS.get(f"autoscale.{name}")


# ------------------------------------------------------------ scale-up


def test_scale_up_only_after_hold_ticks():
    h = Harness(live=1, min_workers=1, max_workers=4, hold_ticks=3)
    h.alerts = ["request_latency"]
    sup0 = counter("actions_suppressed")
    ups0 = counter("scale_ups")
    d1 = h.tick()
    d2 = h.tick()
    assert not h.workers.spawns
    assert (d1["suppressed"], d2["suppressed"]) == (True, True)
    assert d2["state"] == "hold-up"
    assert "hold-up 2/3" in d2["suppress_reason"]
    assert counter("actions_suppressed") == sup0 + 2
    d3 = h.tick()
    assert d3["acted"] and h.workers.spawns == [1]
    assert h.workers.live() == 2
    assert d3["target"] == 2
    assert counter("scale_ups") == ups0 + 1


def test_alert_flap_never_moves_capacity():
    h = Harness(live=1, hold_ticks=3)
    for i in range(12):  # alert fires every other tick: streak never > 1
        h.alerts = ["request_latency"] if i % 2 == 0 else []
        h.util = 0.9  # busy: the quiet path stays out of the picture
        h.tick()
    assert not h.workers.spawns and not h.workers.drains


def test_up_cooldown_blocks_back_to_back_spawns():
    h = Harness(live=1, hold_ticks=1, up_cooldown_s=10.0, max_workers=4)
    h.alerts = ["request_latency"]
    h.tick()
    assert h.workers.spawns == [1]
    d = h.tick(dt=1.0)  # still burning, 1s after the spawn
    assert d["suppressed"] and d["state"] == "cooldown-up"
    assert "up-cooldown" in d["suppress_reason"]
    assert h.workers.spawns == [1]
    d = h.tick(dt=10.0)  # past the cooldown
    assert h.workers.spawns == [1, 1]
    assert d["target"] == 3


def test_never_spawns_past_max_workers():
    h = Harness(live=3, hold_ticks=1, max_workers=3)
    h.alerts = ["request_latency"]
    d = h.tick()
    assert not h.workers.spawns
    assert d["suppressed"] and "at-max" in d["suppress_reason"]


# ---------------------------------------------------------- scale-down


def test_scale_down_drains_after_hold_and_respects_down_cooldown():
    h = Harness(live=3, min_workers=1, hold_ticks=2, down_cooldown_s=5.0)
    h.util = 0.1
    downs0 = counter("scale_downs")
    d1 = h.tick()
    assert d1["suppressed"] and d1["state"] == "hold-down"
    d2 = h.tick()
    assert h.workers.drains == [1] and h.workers.live() == 2
    assert d2["target"] == 2
    assert counter("scale_downs") == downs0 + 1
    d3 = h.tick(dt=1.0)  # 1s after the drain: down-cooldown holds
    assert d3["suppressed"] and d3["state"] == "cooldown-down"
    assert h.workers.drains == [1]
    h.tick(dt=6.0)  # past the cooldown: drains to the floor
    assert h.workers.drains == [1, 1] and h.workers.live() == 1


def test_no_drain_right_after_scale_up():
    # The down-cooldown references the last action in EITHER direction:
    # the controller must never retire the worker it just spawned.
    h = Harness(live=1, hold_ticks=1, up_cooldown_s=0.0,
                down_cooldown_s=100.0)
    h.alerts = ["request_latency"]
    h.tick()
    assert h.workers.live() == 2
    h.alerts = []
    h.util = 0.0
    d = h.tick(dt=1.0)
    assert d["suppressed"] and d["state"] == "cooldown-down"
    assert not h.workers.drains


def test_unknown_utilization_never_scales_down():
    h = Harness(live=3, hold_ticks=1)
    h.util = None  # evidence unknown (stale fleet log, no gauge yet)
    for _ in range(5):
        h.tick(dt=1.0)
    assert not h.workers.drains


# ------------------------------------------------------------- weights


def test_reweight_under_burn_then_restore_on_recovery():
    h = Harness(live=1, weights=True, hold_ticks=1, max_workers=2,
                up_cooldown_s=0.0,
                overload_weights={"gold": 4.0, "free": 0.25})
    rw0 = counter("reweights")
    h.alerts = ["request_latency"]
    d1 = h.tick()
    # Axis c fires FIRST: paying traffic is protected before capacity
    # moves (the spawn lands next tick).
    assert h.weights.reweights == [{"gold": 4.0, "free": 0.25}]
    assert not h.workers.spawns
    assert d1["acted"] and counter("reweights") == rw0 + 1
    assert h.ctl.status()["weights"] == {"gold": 4.0, "free": 0.25}
    h.tick()
    assert h.workers.spawns == [1]
    h.alerts = []
    h.util = 0.9  # recovered but busy: restore is independent of drains
    h.tick()
    assert h.weights.restores == 1
    assert h.ctl.status()["weights"] == {}
    assert not h.workers.drains


# --------------------------------------------------------------- retry


def test_failed_spawn_retries_next_tick_outside_cooldown():
    h = Harness(live=1, fail_spawns=1, hold_ticks=1, up_cooldown_s=100.0)
    f0 = counter("actuator_failures")
    h.alerts = ["request_latency"]
    d1 = h.tick()
    assert counter("actuator_failures") == f0 + 1
    assert "FAILED" in d1["last_action"]
    assert h.ctl.status()["pending"] == "spawn"
    # Next tick retries the queued spawn FIRST — the 100s up-cooldown
    # must not stretch a transient exec failure into lost capacity.
    d2 = h.tick(dt=1.0)
    assert h.workers.spawns == [1] and h.workers.live() == 2
    assert d2["last_action"] == "spawn 1"
    assert h.ctl.status()["pending"] is None


# -------------------------------------------------------------- axis b


def test_cold_cell_hands_off_once():
    h = Harness(live=1, cell=True, min_workers=1, hold_ticks=1,
                cell_drain_ticks=2)
    h.util = 0.0
    h.tick()
    assert h.cell.drains == 0
    d = h.tick()
    assert h.cell.drains == 1 and d["state"] == "cell-drained"
    for _ in range(3):  # still cold: the handoff never repeats
        d = h.tick(dt=1.0)
    assert h.cell.drains == 1 and d["state"] == "cell-drained"


def test_cell_actuator_forwards_reason_and_latch():
    class Rep:
        def __init__(self):
            self.reasons = []

        def drain(self, reason="drain"):
            self.reasons.append(reason)

    fired = []
    rep = Rep()
    CellActuator(rep, reason="autoscale",
                 on_drained=lambda: fired.append(True)).drain_cell()
    assert rep.reasons == ["autoscale"] and fired == [True]


# ------------------------------------------------------------ plumbing


def test_target_workers_gauge_tracks_target():
    h = Harness(live=2, hold_ticks=1, max_workers=4)
    h.alerts = ["request_latency"]
    h.tick()
    assert METRICS.gauges()["autoscale.target_workers"] == 3.0


def test_settles_back_to_steady():
    h = Harness(live=1, hold_ticks=1, max_workers=2)
    h.alerts = ["request_latency"]
    h.tick()
    h.alerts = []
    h.util = 0.9  # in band: no action, no suppression
    d = h.tick(dt=1.0)
    assert d["state"] == "steady" and not d["acted"] and not d["suppressed"]


def test_gateway_weight_actuator_holds_the_lock():
    import threading

    class GW:
        def __init__(self):
            self.sets = []
            self.cleared = 0

        def set_tenant_weights(self, w):
            self.sets.append(w)

        def clear_tenant_weights(self):
            self.cleared += 1

    gw, lock = GW(), threading.Lock()
    act = GatewayWeightActuator(gw, lock)
    act.reweight({"gold": 4.0})
    act.restore()
    assert gw.sets == [{"gold": 4.0}] and gw.cleared == 1


def test_gateway_tenant_weight_overrides():
    from bitcoin_miner_tpu.apps.scheduler import Scheduler
    from bitcoin_miner_tpu.gateway import Gateway, ResultCache, SpanStore

    gw = Gateway(Scheduler(), cache=ResultCache(), spans=SpanStore())
    assert gw._weight_of("anyone") == 1.0
    gw.set_tenant_weights({"gold": 4.0, "free": 0.25, "bogus": 0.0})
    assert gw.tenant_weights() == {"gold": 4.0, "free": 0.25}  # 0 dropped
    assert gw._weight_of("gold") == 4.0
    assert gw._weight_of("unlisted") == 1.0
    gw.clear_tenant_weights()
    assert gw.tenant_weights() == {} and gw._weight_of("gold") == 1.0


def test_controller_pump_drives_ticks_and_stops():
    import threading

    done = threading.Event()

    class Ctl:
        def __init__(self):
            self.ticks = 0

        def tick(self, now=None):
            self.ticks += 1
            if self.ticks >= 3:
                done.set()

    ctl = Ctl()
    pump = ControllerPump(ctl, interval=0.01).start()
    assert done.wait(5.0)
    pump.stop()
    assert ctl.ticks >= 3


# ------------------------------------------------------------ the spec


def test_parse_autoscale_config_full_grammar():
    cfg, driver = parse_autoscale_config(
        "min=1,max=3,step=2,hold=2,up_cooldown=4,down_cooldown=6,"
        "util_low=0.4,cell_drain=5,interval=0.5,backend=xla,"
        "weights=gold:4;free:0.25"
    )
    assert cfg == AutoscaleConfig(
        min_workers=1, max_workers=3, step=2, hold_ticks=2,
        up_cooldown_s=4.0, down_cooldown_s=6.0, util_low=0.4,
        overload_weights={"gold": 4.0, "free": 0.25}, cell_drain_ticks=5,
    )
    assert driver == {"interval": 0.5, "backend": "xla"}


def test_parse_autoscale_config_defaults_and_errors():
    assert parse_autoscale_config("1")[0] == AutoscaleConfig()
    for bad in ("mni=2", "min=3,max=1", "min", "hold=0", "weights=gold",
                "min=x"):
        with pytest.raises(ValueError):
            parse_autoscale_config(bad)


def test_fleet_log_evidence_tails_and_goes_stale(tmp_path):
    from tools.autoscale import _FleetLogEvidence

    path = tmp_path / "fleet.jsonl"
    now = [0.0]
    ev = _FleetLogEvidence(str(path), stale_after=5.0, clock=lambda: now[0])
    ev.poll()  # file does not exist yet: evidence stays unknown
    assert ev.alerts() is None and ev.utilization() is None
    row = {"slo": {"alerts": ["request_latency"]},
           "gauges": {"fleet.utilization": 0.75}}
    with open(path, "w") as f:
        f.write(json.dumps(row) + "\n")
        f.write('{"torn')  # concurrent append: must be skipped, not crash
    ev.poll()
    assert ev.alerts() == ["request_latency"]
    assert ev.utilization() == 0.75
    now[0] = 6.0  # no new row within stale_after: evidence parks
    assert ev.alerts() is None and ev.utilization() is None
