"""Randomized ConnCore property test: exactly-once in-order delivery under
adversarial drop / reorder / duplication.

The pytest LSP suites mirror the reference's *scenarios*; this goes beyond
them (SURVEY §4 has no counterpart — the reference can't unit-test its
transport core, ours is sans-IO): two ConnCores wired through a seeded
chaos channel that drops, reorders, duplicates and stalls packets, with
epochs fired at random.  Whatever the interleaving, every written payload
must arrive exactly once, in order, on the peer — and the cores must drain
once the channel is allowed to deliver.
"""

import random

import pytest

from bitcoin_miner_tpu.lsp.conn import ConnCore
from bitcoin_miner_tpu.lsp.message import Message, MsgType
from bitcoin_miner_tpu.lsp.params import Params


class ChaosChannel:
    """Holds in-flight packets; delivery order/fate driven by the test rng."""

    def __init__(self, rng):
        self.rng = rng
        self.in_flight = []  # (dst, Message)

    def send_to(self, dst):
        def send(msg):
            self.in_flight.append((dst, msg))

        return send

    def step(self, drop_p, dup_p):
        """Deliver one randomly-chosen packet (reorder by construction),
        possibly dropping or duplicating it.  Returns False if empty."""
        if not self.in_flight:
            return False
        i = self.rng.randrange(len(self.in_flight))
        dst, msg = self.in_flight.pop(i)
        r = self.rng.random()
        if r < drop_p:
            return True  # eaten by the network
        if r < drop_p + dup_p:
            self.in_flight.append((dst, msg))  # duplicate stays in flight
        dst.heard_from_peer()
        if msg.type == MsgType.DATA:
            dst.on_data(msg)
        elif msg.type == MsgType.ACK:
            dst.on_ack(msg.seq_num)
        return True


def wire_pair(rng, window):
    # Generous epoch limit: the fuzz stalls the channel arbitrarily long and
    # loss declaration is not under test here.
    params = Params(epoch_limit=10**9, epoch_millis=1, window_size=window)
    chan = ChaosChannel(rng)
    delivered = {"a": [], "b": []}
    a = ConnCore(1, params, send_fn=None, deliver_fn=delivered["a"].append)
    b = ConnCore(1, params, send_fn=None, deliver_fn=delivered["b"].append)
    a._send = chan.send_to(b)
    b._send = chan.send_to(a)
    return chan, a, b, delivered


@pytest.mark.parametrize("seed", [0, 1, 2, 7, 42, 99, 1234, 31337])
def test_exactly_once_in_order_under_chaos(seed):
    rng = random.Random(seed)
    window = rng.choice([1, 2, 5, 32])
    n_msgs = rng.randint(20, 120)
    chan, a, b, delivered = wire_pair(rng, window)

    sent = {"a": [], "b": []}
    pending_writes = {"a": n_msgs, "b": n_msgs}
    cores = {"a": a, "b": b}
    other = {"a": "b", "b": "a"}

    steps = 0
    while (
        pending_writes["a"]
        or pending_writes["b"]
        or len(delivered["a"]) < n_msgs
        or len(delivered["b"]) < n_msgs
    ):
        steps += 1
        assert steps < 200_000, (
            f"no convergence (seed={seed}): delivered "
            f"{len(delivered['a'])}/{len(delivered['b'])} of {n_msgs}"
        )
        choice = rng.random()
        if choice < 0.25 and (pending_writes["a"] or pending_writes["b"]):
            side = rng.choice([s for s in "ab" if pending_writes[s]])
            payload = f"{side}:{n_msgs - pending_writes[side]}".encode()
            cores[side].write(payload)
            sent[side].append(payload)
            pending_writes[side] -= 1
        elif choice < 0.85 and chan.in_flight:
            # 15% drop, 10% duplicate on each delivered packet.
            chan.step(drop_p=0.15, dup_p=0.10)
        else:
            # Epoch tick on a random side: retransmit + re-ack.
            cores[rng.choice("ab")].on_epoch()

    # Drain the channel fully (no more drops) and let retransmits finish.
    for _ in range(10_000):
        if not chan.step(drop_p=0.0, dup_p=0.0):
            a.on_epoch()
            b.on_epoch()
            if a.drained and b.drained and not chan.in_flight:
                break

    assert delivered["b"] == sent["a"], f"a->b stream corrupted (seed={seed})"
    assert delivered["a"] == sent["b"], f"b->a stream corrupted (seed={seed})"
    assert a.drained and b.drained


@pytest.mark.parametrize("seed", [3, 8, 2024])
def test_window_never_exceeded(seed):
    """At no point may the sender hold more than WindowSize unacked data
    messages, nor send a seq beyond ack_base + WindowSize (rule 3).
    (Stale already-acked packets may still float in the network — the
    invariant is sender state, not channel contents.)"""
    rng = random.Random(seed)
    window = rng.choice([1, 2, 4])
    chan, a, b, delivered = wire_pair(rng, window)
    for i in range(50):
        a.write(b"m%d" % i)
        if rng.random() < 0.5:
            chan.step(drop_p=0.3, dup_p=0.1)
        if rng.random() < 0.2:
            a.on_epoch()
        assert len(a._unacked) <= window, (
            f"{len(a._unacked)} unacked > window {window}"
        )
        for seq in a._unacked:
            assert seq <= a._ack_base + window, (
                f"seq {seq} beyond window gate {a._ack_base}+{window}"
            )


@pytest.mark.parametrize("seed", [11, 4242])
def test_hostile_seq_flood_memory_bounded(seed):
    """A hostile peer spraying Data with arbitrarily large seq_nums must not
    grow the reorder buffer (or earn acks) beyond the protocol horizon —
    previously every such packet was buffered AND acked, an unbounded-memory
    DoS the reference shares (client_impl.go:277-289)."""
    rng = random.Random(seed)
    window = rng.choice([1, 4, 32])
    chan, a, b, delivered = wire_pair(rng, window)
    horizon = 2 * window
    # Legitimate out-of-order data inside the horizon MUST still buffer and
    # earn acks (rule 4/5) — the horizon is a DoS cap, not a reorder ban.
    in_horizon = list(range(2, 2 + horizon))
    for seq in in_horizon:
        b.on_data(Message.data(1, seq, 1, b"g"))
    assert set(b._reorder) == set(in_horizon), "legit reorder data not buffered"
    for _ in range(5_000):
        seq = rng.choice(
            [
                rng.randint(horizon + 2, 100 * window),
                rng.randint(10**6, 10**12),
                2**31,
            ]
        )
        payload = b"h%d" % seq
        b.on_data(Message.data(1, seq, len(payload), payload))
        assert len(b._reorder) <= horizon, (
            f"reorder buffer ballooned to {len(b._reorder)} (window {window})"
        )
    # Exactly the in-horizon seqs were acked; nothing beyond the horizon was
    # (an ack would tell a *compliant* sender its data can be forgotten).
    acked = {m.seq_num for _dst, m in chan.in_flight if m.type == MsgType.ACK}
    assert acked == set(in_horizon)
    # The connection still works: the in-order gap fill drains the buffer.
    b.on_data(Message.data(1, 1, 2, b"ok"))
    assert delivered["b"] == [b"ok"] + [b"g"] * horizon
    assert not b._reorder


def test_duplicate_data_acked_but_not_redelivered():
    rng = random.Random(5)
    chan, a, b, delivered = wire_pair(rng, window=4)
    a.write(b"x")
    # Find the data packet and deliver it twice.
    [(dst, msg)] = chan.in_flight
    chan.in_flight.clear()
    b.on_data(msg)
    b.on_data(msg)
    assert delivered["b"] == [b"x"]  # exactly once
    # Both receipts generated an ack (immediate-ack rule 5).
    acks = [m for _dst, m in chan.in_flight if m.type == MsgType.ACK]
    assert len(acks) == 2 and all(m.seq_num == 1 for m in acks)
