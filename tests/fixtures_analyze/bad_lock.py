"""Seeded lock-discipline violations (tools/analyze lock pass).

Every rule the AST checker implements has one deliberate offense here.
"""

import threading


class LeakyCounter:
    """Field annotated guarded-by, then read off-lock: field-off-lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0  # guarded-by: _lock

    def inc(self):
        with self._lock:
            self._count += 1

    def read_off_lock(self):
        return self._count  # SEEDED VIOLATION: no `with self._lock:`

    def _drain_locked(self):
        self._count = 0  # legal: _locked suffix = caller holds the lock

    def helper(self):  # guarded-by: _lock
        return self._count  # legal: def-line annotation = runs under lock

    def call_helper_off_lock(self):
        return self.helper()  # SEEDED VIOLATION: helper-off-lock


def serve_like(thing):
    lock = threading.Lock()
    state = thing  # guarded-by: lock
    with lock:
        state.ok()  # legal
    state.leak()  # SEEDED VIOLATION: local-off-lock


class PairedCounter:
    """Explicit acquire()/release() pairs beyond `with` blocks (ISSUE 5):
    the canonical try/finally pairing is legal; a read AFTER the release
    fires field-off-lock again."""

    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0  # guarded-by: _lock

    def inc(self):
        self._lock.acquire()
        try:
            self._n += 1  # legal: between acquire/release
        finally:
            self._lock.release()

    def read_after_release(self):
        self._lock.acquire()
        self._lock.release()
        return self._n  # SEEDED VIOLATION: post-release read


def serve_like_paired(thing):
    lock = threading.Lock()
    state = thing  # guarded-by: lock
    lock.acquire()
    try:
        state.ok()  # legal: between acquire/release
    finally:
        lock.release()
    state.leak()  # SEEDED VIOLATION: local read after paired release
