"""Seeded-violation fixtures for tools/analyze (tests/test_analyze.py).

One file per pass, one deliberate violation per rule.  These are the
analyzer's own regression suite: every rule must FIRE here and stay
quiet on the live repo.  Never "fix" these files — they are wrong on
purpose; pytest does not collect them (no test_ prefix) and the repo-mode
analyzer does not scan tests/.
"""
