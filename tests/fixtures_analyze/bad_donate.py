"""Seeded donation-safety violations for the ``donate`` pass
(tools/analyze/donatecheck.py) — every rule must fire on this file:

- ``drops_result`` calls a donated step without rebinding the donated
  operand (``donate-no-rebind``);
- ``reads_dead_handle`` additionally reads the dead operand afterwards
  (``donate-read-after-call``);
- ``factory_route`` binds its step from a hot-step factory (the
  repo convention: callee named like ``*hot_step*`` donates argument 0)
  and discards the result (``donate-no-rebind``);
- ``HotThing.peek`` / ``HotThing.finish`` materialise the donated job
  carry mid-job (``donate-materialize`` — int() over it, iterating it).

And the idioms that must stay CLEAN: the carry rebind in
``HotThing.dispatch`` (the exact ``_HotLoop.dispatch`` shape), the
``carry is None`` refresh test, and ``# donate-ok:`` suppressions.

The file is only parsed, never imported — ``jax`` here is a stand-in
name so the AST carries the real call shapes.
"""

import jax  # noqa: F401  (parsed, not imported — see module docstring)


def _step_impl(carry, x):
    return carry, x


_step = jax.jit(_step_impl, donate_argnums=(0,))


def make_hot_step_stub(kern):
    return _step_impl


def drops_result(carry, x):
    probe = _step(carry, x)  # VIOLATION donate-no-rebind
    return probe


def reads_dead_handle(carry, x):
    out = _step(carry, x)  # VIOLATION donate-no-rebind
    return carry[0], out  # VIOLATION donate-read-after-call


def factory_route(carry, x):
    step = make_hot_step_stub(None)
    step(carry, x)  # VIOLATION donate-no-rebind (result discarded)


def clean_rebind(carry, x):
    carry, probe = _step(carry, x)  # clean: the donated call rebinds
    return carry, probe


def sanctioned_drop(carry, x):
    probe = _step(carry, x)  # donate-ok: fixture-sanctioned throwaway
    return probe


class HotThing:
    """The donated-carry class shape (``_HotLoop`` in ops/sweep.py)."""

    def __init__(self):
        self._carry = None
        self._step = jax.jit(_step_impl, donate_argnums=(0,))

    def dispatch(self, x):
        if self._carry is None:  # clean: a None test is not a sync
            self._carry = (x,)
        # clean: the hot-carry rebind — pointer stability by construction
        self._carry, probe = self._step(self._carry, x)
        return probe

    def peek(self):
        return int(self._carry[0])  # VIOLATION donate-materialize

    def finish(self):
        return [int(v) for v in self._carry]  # VIOLATION donate-materialize

    def finish_sanctioned(self):
        return tuple(self._carry)  # donate-ok: THE job-end fetch
