"""Seeded WFQ-reimplementation violations (tools/analyze wfq pass).

Both idioms the single-WFQ rule hunts: the floor init and the
``(vt, seq)`` tie-break, hand-rolled outside utils/wfq.py.
"""


def pick_lowest(queues):
    # SEEDED VIOLATION (floor-init-reimplemented):
    floor = min((q.vt for q in queues if q.items), default=0.0)
    best = None
    for q in queues:
        # SEEDED VIOLATION (tiebreak-reimplemented):
        if best is None or (q.vt, q.seq) < (best.vt, best.seq):
            best = q
    return best, floor
