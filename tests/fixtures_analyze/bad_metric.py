"""Seeded metric-registry drift for the ``metrics`` pass
(tools/analyze/metriccheck.py) — every rule must fire on this file:

- ``fixture.documented_only`` is documented below but never emitted
  (``metric-unused``);
- ``fixture.never_documented`` is emitted but absent from the registry
  block (``metric-undocumented``);
- ``hist.fixture_latency`` is documented as a histogram but emitted via
  ``inc`` (``metric-kind-mismatch``);
- ``fleet.fixture_sources`` is a fleet-view gauge (``fleet.*`` names are
  gauge-kind, ISSUE 7) but emitted via ``inc``
  (``metric-kind-mismatch``);
- ``fed.peer_state.fixture`` is a membership gauge (the
  ``fed.peer_state`` family is gauge-kind, ISSUE 12) but emitted via
  ``inc`` (``metric-kind-mismatch``);
- ``gw.conns_live`` is the ingress live-conn gauge (the one gauge-kind
  name under ``gw.*``, ISSUE 15) but emitted via ``inc``
  (``metric-kind-mismatch``);
- ``ingress.fixture_events`` is documented below but never emitted
  (``metric-unused`` — pins the new ``ingress.*`` counter family in the
  registry cross-check);
- ``kernel.thresh_staleness`` is the hot plane's sieve-threshold lag
  gauge (the one gauge-kind name under ``kernel.*``, ISSUE 16) but
  emitted via ``inc`` (``metric-kind-mismatch``);
- ``sweep.fixture_refills`` is documented below but never emitted
  (``metric-unused`` — pins the ``sweep.*`` hot-plane counter family,
  which stays inc-kind, in the registry cross-check);
- ``autoscale.target_workers`` is the capacity plane's fleet-size gauge
  (the one gauge-kind name under ``autoscale.*``, ISSUE 18) but emitted
  via ``inc`` (``metric-kind-mismatch``);
- ``fed.conns_live`` is the federation transport's shared-loop conn
  gauge (ISSUE 18) but emitted via ``inc`` (``metric-kind-mismatch``);
- ``autoscale.fixture_actions`` is documented below but never emitted
  (``metric-unused`` — pins the ``autoscale.*`` action-counter family,
  which stays inc-kind, in the registry cross-check);
- ``sanitize.fixture_trips`` is documented below but never emitted
  (``metric-unused`` — pins the ``sanitize.*`` sanitizer-trip counter
  family (ISSUE 19: ``sanitize.loop_blocked``,
  ``sanitize.threads_leaked``), which stays inc-kind, in the registry
  cross-check);
- the computed-name ``inc`` cannot be registry-checked at all
  (``metric-dynamic-name``).
"""


class Metrics:  # stand-in so the fixture never imports the real package
    def inc(self, name, n=1):
        pass

    def observe(self, name, value):
        pass

    def set_gauge(self, name, value):
        pass


#: The fixture's registry block (same format as utils/metrics.py: the
#: contiguous ``#:`` lines directly above the METRICS assignment).
#:   fixture.documented_only   documented here, emitted nowhere
#:   hist.fixture_latency      a histogram name (observe-only kind)
#:   fleet.fixture_sources     a fleet-view gauge (set_gauge-only kind)
#:   fed.peer_state.fixture    a membership gauge (set_gauge-only kind)
#:   gw.conns_live             the ingress live-conn gauge (set_gauge-only kind)
#:   ingress.fixture_events    an ingress counter, documented but never emitted
#:   kernel.thresh_staleness   the hot plane's threshold-lag gauge (set_gauge-only kind)
#:   sweep.fixture_refills     a hot-plane counter, documented but never emitted
#:   autoscale.target_workers  the capacity plane's fleet-size gauge (set_gauge-only kind)
#:   fed.conns_live            the federation shared-loop conn gauge (set_gauge-only kind)
#:   autoscale.fixture_actions an autoscale action counter, documented but never emitted
#:   sanitize.fixture_trips    a sanitizer trip counter, documented but never emitted
METRICS = Metrics()


def provoke_metric_drift(suffix: str) -> None:
    METRICS.inc("fixture.never_documented")  # undocumented counter
    METRICS.inc("hist.fixture_latency")  # wrong emitter for a hist.* name
    METRICS.inc("fleet.fixture_sources")  # wrong emitter for a fleet.* gauge
    METRICS.inc("fed.peer_state.fixture")  # wrong emitter for a membership gauge
    METRICS.inc("gw.conns_live")  # wrong emitter for the ingress conn gauge
    METRICS.inc("kernel.thresh_staleness")  # wrong emitter for the lag gauge
    METRICS.inc("autoscale.target_workers")  # wrong emitter for the fleet-size gauge
    METRICS.inc("fed.conns_live")  # wrong emitter for the fed conn gauge
    METRICS.inc("fixture." + suffix)  # dynamic name: unverifiable
