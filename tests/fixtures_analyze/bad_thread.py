"""Seeded thread-lifecycle violations for the ``thread`` pass
(tools/analyze/threadcheck.py):

- ``LeakyWorker`` stores a thread on the instance and its ``close()``
  never joins it (``thread-unjoined`` — daemon status does NOT exempt a
  class-owned thread: it still holds the object alive after close());
- ``leaky_local`` builds a non-daemon fire-and-forget thread nobody
  joins (``thread-unjoined``).

And the idioms that must stay CLEAN: the reaper join (direct and the
for-loop-over-``self._threads`` spelling), the wait-for-workers local
join, daemon fire-and-forget locals, and the ``# thread-owner:``
deliberate-abandon annotation.
"""

import threading


def _work():
    pass


class LeakyWorker:
    def __init__(self):
        # VIOLATION thread-unjoined: close() below never joins it
        self._t = threading.Thread(target=_work, daemon=True)
        self._t.start()

    def close(self):
        pass


class CleanWorker:
    def __init__(self, n):
        self._t = threading.Thread(target=_work)
        self._t.start()
        self._threads = []
        for _ in range(n):
            t = threading.Thread(target=_work)
            t.start()
            self._threads.append(t)

    def stop(self):
        self._t.join(timeout=1)
        for t in self._threads:
            t.join(timeout=1)


class AbandonedByDesign:
    """The tiered-watchdog shape: close() must never block behind a
    wedged worker, so the daemon thread is deliberately left to the
    process reaper."""

    def __init__(self):
        self._t = threading.Thread(
            target=_work, daemon=True
        )  # thread-owner: process — deliberate abandon, see docstring
        self._t.start()

    def close(self):
        pass


def leaky_local():
    # VIOLATION thread-unjoined: non-daemon, never joined, not annotated
    t = threading.Thread(target=_work)
    t.start()


def clean_local_join(n):
    ts = [threading.Thread(target=_work) for _ in range(n)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()


def clean_local_daemon():
    threading.Thread(target=_work, daemon=True).start()


def annotated_local():
    t = threading.Thread(target=_work)  # thread-owner: harness.teardown
    t.start()
