"""Seeded frozen-contract violations (tools/analyze contracts pass).

A deliberately drifted wire codec and hash function: field order changed,
separators loosened, hash constant wrong — the exact classes of silent
drift the golden vectors exist to catch.
"""

import json
from dataclasses import dataclass


@dataclass
class Message:
    type: int = 0
    data: str = ""
    lower: int = 0
    upper: int = 0
    hash: int = 0
    nonce: int = 0

    @staticmethod
    def join():
        return Message(type=0)

    @staticmethod
    def request(data, lower, upper):
        return Message(type=1, data=data, lower=lower, upper=upper)

    @staticmethod
    def result(hash_, nonce):
        return Message(type=2, hash=hash_, nonce=nonce)

    def marshal(self):
        # DRIFTED: lower-case keys, default separators (spaces), new field
        # order — byte-incompatible with the frozen Go-JSON contract.
        return json.dumps(
            {"type": self.type, "nonce": self.nonce, "hash": self.hash,
             "data": self.data, "lower": self.lower, "upper": self.upper}
        ).encode()

    @staticmethod
    def unmarshal(buf):
        return None  # DRIFTED: cannot round-trip the frozen bytes


def hash_nonce(msg, nonce):
    return 0  # DRIFTED: every golden hash vector misses
