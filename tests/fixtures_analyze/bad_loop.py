"""Seeded loop-discipline violations for the ``loop`` pass
(tools/analyze/loopcheck.py) — every rule must fire on this file:

- ``handler`` sleeps and opens a file inside a coroutine
  (``loop-blocking-call`` ×2);
- ``locked_handler`` takes a sync lock on the loop (``loop-lock``) and
  blocks on a Future (``loop-blocking-call``);
- ``on_loop_callback`` is a plain def declared ``# on-loop:`` that
  sleeps (``loop-blocking-call`` — the annotation is what puts it in
  scope);
- ``BadBridge.write`` calls a loop-owned field without the
  ``call_soon_threadsafe`` hop (``loop-off-thread-write``).

And the idioms that must stay CLEAN: awaited reads, the thread-identity
fast path, the threadsafe hop itself, and ``# loop-ok:`` suppressions.
"""

import threading
import time


class BadBridge:
    """A loop-owned field written off-thread."""

    def __init__(self, server, loop):
        self.srv = server  # on-loop: loop_attr
        self.loop_attr = loop
        self._thread = threading.current_thread()

    def write(self, conn_id, payload):
        # VIOLATION loop-off-thread-write: bypasses the hop
        self.srv.write(conn_id, payload)

    def write_hopped(self, conn_id, payload):
        if threading.current_thread() is self._thread:
            self.srv.write(conn_id, payload)  # clean: identity fast path
            return
        # clean: the sanctioned hop (the bound method is an argument)
        self.loop_attr.call_soon_threadsafe(self.srv.write, conn_id, payload)

    def snapshot(self):
        return self.srv.conns_live()  # loop-ok: GIL-atomic snapshot read


async def handler(conn):
    time.sleep(0.1)  # VIOLATION loop-blocking-call: sync sleep on the loop
    fh = open("/tmp/bad_loop_fixture")  # VIOLATION loop-blocking-call: file I/O
    fh.close()
    return conn


async def locked_handler(lock, fut):
    with lock:  # VIOLATION loop-lock: sync lock in a coroutine
        pass
    return fut.result()  # VIOLATION loop-blocking-call: Future wait


async def clean_handler(conn, lock):
    import asyncio

    await asyncio.sleep(0)  # clean: awaited
    async with lock:  # clean: the async lock spelling
        pass
    return await conn.read()  # clean: awaited read


async def suppressed_handler():
    time.sleep(0)  # loop-ok: fixture-sanctioned zero-sleep


def on_loop_callback(state):  # on-loop: scheduled via call_soon_threadsafe
    time.sleep(0.5)  # VIOLATION loop-blocking-call: annotated def is on-loop
    return state
