"""Seeded runtime races (tools/analyze sanitize pass, BMT_SANITIZE=1).

Each ``provoke_*`` commits one concurrency crime against the sanitizer's
machinery; the pass runs them all and reports every RaceError /
LockOrderError raised — the proof the ``-race`` analogue actually fires.
"""

import threading

from bitcoin_miner_tpu.utils import sanitize


def provoke_unsynchronized_access():
    """Off-lock access to a guarded object after a second thread shared
    it — the health-line-stat-read-off-lock bug class."""
    lock = sanitize.TrackedLock("fixture.lock")
    shared = sanitize.Monitor({"n": 0}, lock, "fixture-state")

    def disciplined_toucher():
        with lock:
            shared.keys()

    t = threading.Thread(target=disciplined_toucher)
    t.start()
    t.join()
    shared.keys()  # SEEDED VIOLATION: off-lock once shared -> RaceError


def provoke_lock_order_inversion():
    """ABBA acquisition — raises deterministically from the acquisition
    graph even though this single-threaded run could never deadlock."""
    a = sanitize.TrackedLock("fixture.A")
    b = sanitize.TrackedLock("fixture.B")
    with a:
        with b:
            pass
    with b:
        with a:  # SEEDED VIOLATION: closes the A->B->A cycle
            pass
