"""Seeded JAX trace-safety violations (tools/analyze trace pass).

AST-scanned only, never imported — the imports exist so the file reads
like real kernel code.  One offense per rule.
"""

import random
import time
from functools import lru_cache

import jax


def make_bad_kernel(n_lanes):
    def kernel(x, bounds):
        if x > 0:  # SEEDED VIOLATION: trace-branch (Python if on a tracer)
            y = x + 1
        else:
            y = x
        z = int(x)  # SEEDED VIOLATION: trace-concretize (int() on a tracer)
        w = x.item()  # SEEDED VIOLATION: trace-concretize (.item() fetch)
        t0 = time.time()  # SEEDED VIOLATION: trace-wallclock
        r = random.random()  # SEEDED VIOLATION: trace-rng
        return y, z, w, t0, r

    return jax.jit(kernel)


@lru_cache(maxsize=8)
def bad_factory(shape=[8, 128]):  # SEEDED VIOLATION: trace-unhashable-static
    return shape
