"""Multi-host logical-miner protocol: broadcast codec + primary/secondary
serve loop (apps/miner.serve_multihost, parallel/multihost.py).

The real path needs N processes + jax.distributed; these tests cover the
untested-in-round-1 logic — the hand-rolled u32 broadcast buffer codec and
the lockstep Request loop — with a faked broadcast collective, per the
single-logical-miner contract in parallel/multihost.py.
"""

import numpy as np
import pytest

from bitcoin_miner_tpu import lsp
from bitcoin_miner_tpu.apps.miner import serve_multihost
from bitcoin_miner_tpu.bitcoin.hash import min_hash_range
from bitcoin_miner_tpu.bitcoin.message import Message, MsgType
from bitcoin_miner_tpu.parallel.multihost import (
    MAX_DATA,
    decode_request,
    encode_request,
    encode_shutdown,
)


class TestCodec:
    @pytest.mark.parametrize(
        "data,lo,hi",
        [
            ("cmu440", 0, 10**6),
            ("", 5, 5),
            ("héllo wörld ⚡", 0, (1 << 64) - 1),  # multi-byte UTF-8
            ("x" * MAX_DATA, 123, 456),  # exactly at the cap
        ],
    )
    def test_round_trip(self, data, lo, hi):
        assert decode_request(encode_request(data, lo, hi)) == (data, lo, hi)

    def test_shutdown_decodes_none(self):
        assert decode_request(encode_shutdown()) is None

    def test_oversize_data_rejected_not_truncated(self):
        with pytest.raises(ValueError, match="caps at"):
            encode_request("x" * (MAX_DATA + 1), 0, 1)

    def test_oversize_multibyte_rejected(self):
        # 481 three-byte chars = 1443 encoded bytes; a byte-slice truncation
        # would have split a sequence and crashed the strict decode.
        with pytest.raises(ValueError, match="caps at"):
            encode_request("⚡" * 481, 0, 1)

    def test_u64_bounds_enforced(self):
        with pytest.raises(ValueError, match="u64"):
            encode_request("d", 0, 1 << 64)
        with pytest.raises(ValueError, match="u64"):
            encode_request("d", -1, 1)

    def test_buffer_is_fixed_shape_u32(self):
        a, b = encode_request("abc", 0, 1), encode_shutdown()
        assert a.shape == b.shape and a.dtype == b.dtype == np.uint32


class FakeClient:
    """Scripted LSP client: read() pops payloads, then raises ConnLostError."""

    def __init__(self, payloads):
        self._payloads = list(payloads)
        self.written = []

    def read(self):
        if not self._payloads:
            raise lsp.ConnLostError(0)
        return self._payloads.pop(0)

    def write(self, payload):
        self.written.append(Message.unmarshal(payload))


def sweep_oracle(data, lo, hi):
    return min_hash_range(data, lo, hi)


class TestServeLoop:
    def test_primary_request_sweep_result(self):
        client = FakeClient(
            [
                Message.request("cmu440", 0, 99).marshal(),
                Message.request("cmu440", 100, 199).marshal(),
            ]
        )
        sent = []

        def broadcast(buf):
            sent.append(np.array(buf))
            return buf

        serve_multihost(client, sweep_oracle, broadcast)
        # Two Results, bit-exact, then the conn-loss shutdown broadcast.
        assert [(m.hash, m.nonce) for m in client.written] == [
            min_hash_range("cmu440", 0, 99),
            min_hash_range("cmu440", 100, 199),
        ]
        assert len(sent) == 3
        assert decode_request(sent[0]) == ("cmu440", 0, 99)
        assert decode_request(sent[2]) is None  # shutdown fans out

    def test_secondary_executes_broadcasts_in_lockstep(self):
        script = [
            encode_request("jobdata", 50, 60),
            encode_request("jobdata", 61, 70),
            encode_shutdown(),
        ]
        swept = []

        def sweep(data, lo, hi):
            swept.append((data, lo, hi))
            return min_hash_range(data, lo, hi)

        serve_multihost(None, sweep, lambda _buf: script.pop(0))
        assert swept == [("jobdata", 50, 60), ("jobdata", 61, 70)]

    def test_primary_skips_non_request_messages(self):
        client = FakeClient(
            [
                Message.join().marshal(),  # stray Join echoes are ignored
                Message.result(1, 2).marshal(),
                Message.request("d", 0, 9).marshal(),
            ]
        )
        serve_multihost(client, sweep_oracle, lambda b: b)
        assert [(m.hash, m.nonce) for m in client.written] == [
            min_hash_range("d", 0, 9)
        ]

    def test_oversize_request_shuts_down_loudly(self, capsys):
        client = FakeClient([Message.request("y" * 2000, 0, 9).marshal()])
        sent = []

        def broadcast(buf):
            sent.append(np.array(buf))
            return buf

        serve_multihost(client, sweep_oracle, broadcast)
        assert client.written == []  # no plausible-but-wrong Result
        assert len(sent) == 1 and decode_request(sent[0]) is None
        assert "rejecting request" in capsys.readouterr().err
