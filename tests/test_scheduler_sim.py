"""Randomized scheduler simulation: correctness under adversarial event
interleavings.

Drives the pure Scheduler with random joins, miner deaths, completions and
multiple concurrent clients, with a deterministic stand-in hash (the
scheduler is hash-agnostic — only the min-fold and range bookkeeping are
under test).  Invariant: every client that stays alive receives exactly the
min over its full [0, maxNonce] range, no matter which miners died when.
"""

import random

import pytest

from bitcoin_miner_tpu.apps.scheduler import Scheduler
from bitcoin_miner_tpu.bitcoin.message import MsgType

U64 = (1 << 64) - 1


def fake_hash(nonce: int) -> int:
    return (nonce * 2654435761) ^ (nonce >> 3) & U64


def fake_min(lo: int, hi: int):
    best = min(range(lo, hi + 1), key=lambda n: (fake_hash(n), n))
    return fake_hash(best), best


@pytest.mark.parametrize("seed", [1, 7, 42, 1234, 9999])
def test_random_interleavings_converge_correctly(seed):
    from collections import deque

    rng = random.Random(seed)
    depth = rng.choice([1, 2, 3])
    sched = Scheduler(
        validate_results=False,
        min_chunk=rng.choice([13, 50, 128]),
        max_chunk=500,
        pipeline_depth=depth,
    )

    next_id = [1]
    miners = {}   # conn_id -> FIFO deque of assigned (lo, hi)
    results = {}  # client_id -> (hash, nonce)
    jobs = {}     # client_id -> max_nonce
    now = [0.0]

    def apply(actions):
        for cid, msg in actions:
            if msg.type == MsgType.REQUEST:
                assert cid in miners, "request sent to a non-miner"
                assert len(miners[cid]) < depth, "pipeline overfilled"
                miners[cid].append((msg.lower, msg.upper))
            elif msg.type == MsgType.RESULT:
                assert cid in jobs, "result sent to unknown client"
                results[cid] = (msg.hash, msg.nonce)

    def tick():
        now[0] += rng.random()
        return now[0]

    # Seed the system with a couple of clients and miners.
    for _ in range(rng.randint(2, 4)):
        cid = next_id[0]; next_id[0] += 1
        mx = rng.randint(0, 700)
        jobs[cid] = mx
        apply(sched.client_request(cid, f"job{cid}", 0, mx, tick()))

    steps = 0
    while len(results) < len(jobs) and steps < 10_000:
        steps += 1
        busy = [m for m, q in miners.items() if q and m in sched.miners]
        choice = rng.random()
        if choice < 0.25 or not busy:
            mid = next_id[0]; next_id[0] += 1
            miners[mid] = deque()
            apply(sched.miner_joined(mid, tick()))
        elif choice < 0.40 and busy:
            mid = rng.choice(busy)  # kill a busy miner mid-chunks
            miners.pop(mid)
            apply(sched.lost(mid, tick()))
        else:
            mid = rng.choice(busy)  # miner completes its OLDEST chunk
            lo, hi = miners[mid].popleft()
            h, n = fake_min(lo, hi)
            apply(sched.result(mid, h, n, tick()))

    assert len(results) == len(jobs), f"jobs never completed (seed={seed})"
    for cid, mx in jobs.items():
        assert results[cid] == fake_min(0, mx), f"wrong min for client {cid}"
    assert sched.jobs == {}


def test_client_death_mid_sim():
    from collections import deque

    rng = random.Random(5)
    sched = Scheduler(validate_results=False, min_chunk=20, max_chunk=100)
    sched.client_request(100, "a", 0, 500)
    sched.client_request(101, "b", 0, 400)
    miners = {}
    results = {}

    def apply(actions):
        for cid, msg in actions:
            if msg.type == MsgType.REQUEST:
                miners[cid].append((msg.lower, msg.upper))
            elif msg.type == MsgType.RESULT:
                results[cid] = (msg.hash, msg.nonce)

    for mid in (1, 2, 3):
        miners[mid] = deque()
        apply(sched.miner_joined(mid))
    apply(sched.lost(100))  # client a dies mid-job
    for _ in range(200):
        busy = [m for m, q in miners.items() if q]
        if not busy:
            break
        mid = rng.choice(busy)
        lo, hi = miners[mid].popleft()
        h, n = fake_min(lo, hi)
        apply(sched.result(mid, h, n))
    assert 100 not in results, "dead client must not receive a Result"
    assert results[101] == fake_min(0, 400)
    assert sched.jobs == {}
