"""Regression tests for the driver entry points (__graft_entry__.py).

Round 1's only driver failures were here — the bench died on backend init
and the multichip dryrun never forced the virtual CPU platform — so the
entry points themselves are now under test: entry() must produce a
jittable, *correct* forward step, and dryrun_multichip must run (and stay
hermetic) in an already-initialized matching environment like this one.
"""

import sys
from pathlib import Path

import jax

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
import __graft_entry__  # noqa: E402

from bitcoin_miner_tpu.bitcoin.hash import min_hash_range  # noqa: E402


def test_entry_compiles_and_matches_oracle():
    fn, args = __graft_entry__.entry()
    h0, h1, flat = jax.jit(fn)(*args)
    # The example args cover nonces [10^5, 10^5 + 8*10^4) of 'cmu440'
    # contiguously, so flat index == nonce offset.
    got_hash = (int(h0) << 32) | int(h1)
    got_nonce = 10**5 + int(flat)
    assert (got_hash, got_nonce) == min_hash_range("cmu440", 10**5, 179_999)


def test_dryrun_multichip_runs_in_matching_env():
    # conftest already forced the 8-device virtual CPU platform; the
    # hermetic guard must accept a matching pre-initialized process.
    __graft_entry__.dryrun_multichip(8)


def test_dryrun_multichip_rejects_undersized_mesh():
    import pytest

    with pytest.raises(RuntimeError, match="virtual CPU devices"):
        __graft_entry__.dryrun_multichip(64)  # only 8 devices exist here
