"""Chaos soak suite: the seeded network-condition simulator + fleet
self-healing, end to end.

Three layers of assertion:

1. **Engine determinism** — the same seed fed the same packet sequence
   produces a bit-identical decision trace and counter totals (what makes
   any chaos failure replayable via ``tools/chaos_replay.py --seed N``).
2. **Self-healing units** — miner reconnect-with-backoff re-Joins across a
   server restart; the watchdog downgrades a wedged kernel tier; a client
   resubmit resumes from the scheduler's orphan stash.
3. **Seeded fleet soaks** — full in-process client/server/miner fleets
   under the standard schedules (burst loss, reorder/dup/delay, loss→
   partition→heal, miner isolation + mid-job kill), every final Result
   bit-exact against the hashlib oracle.

One fast scenario stays in tier-1; the long soaks are marked ``slow``.
"""

import io
import threading
import time

import pytest

from bitcoin_miner_tpu import lsp, lspnet
from bitcoin_miner_tpu.apps import client as client_mod
from bitcoin_miner_tpu.apps import miner as miner_mod
from bitcoin_miner_tpu.apps import server as server_mod
from bitcoin_miner_tpu.apps.drill import run_drill
from bitcoin_miner_tpu.apps.scheduler import Scheduler
from bitcoin_miner_tpu.bitcoin.hash import min_hash_range
from bitcoin_miner_tpu.bitcoin.message import Message
from bitcoin_miner_tpu.lspnet.chaos import (
    CHAOS,
    GEParams,
    NetSim,
    Schedule,
    conditions,
    heal,
    partition,
    standard_scenarios,
)
from bitcoin_miner_tpu import workloads as workloads_mod
from bitcoin_miner_tpu.utils.metrics import METRICS

from lsp_harness import random_port

pytestmark = pytest.mark.chaos

PARAMS = lsp.Params(epoch_limit=5, epoch_millis=100, window_size=5)


@pytest.fixture(autouse=True)
def _clean_network():
    lspnet.reset_faults()
    CHAOS.reset()
    yield
    CHAOS.reset()
    lspnet.reset_faults()


# --------------------------------------------------------------------------
# 1. Engine determinism + model behavior (pure, no sockets)
# --------------------------------------------------------------------------


def _pump(sim: NetSim, clock: list, n: int = 600):
    """Feed a fixed synthetic packet sequence through a simulator."""
    sim.record_trace(True)
    for i in range(n):
        clock[0] = i * 0.01
        sim.on_send("miner-1", False)
        sim.on_send("server", True)
        sim.on_recv("miner-1", False)
    return sim.trace, sim.counters()


def _scripted_sim(seed: int):
    sim = NetSim()
    sim.seed(seed)
    clock = [0.0]
    sched = (
        Schedule()
        .at(0.0, conditions(drop=15, duplicate=10, reorder=10, delay_ms=2,
                            jitter_ms=3))
        .at(2.0, conditions(ge=GEParams(p_enter_bad=3, p_exit_bad=12,
                                        loss_bad=90)))
        .at(4.0, partition("miner-1", "both"))
        .at(5.0, heal())
    )
    sim.run(sched, clock=lambda: clock[0])
    return sim, clock


def test_seeded_fault_trace_replays_identically():
    """The acceptance property: same seed + same packet sequence → the
    identical fault trace, decision for decision, counter for counter."""
    t1, c1 = _pump(*_scripted_sim(42))
    t2, c2 = _pump(*_scripted_sim(42))
    assert t1 == t2
    assert c1 == c2
    # The scenario actually exercised every fault class.
    for key in ("dropped", "duplicated", "reordered", "delayed", "partitioned"):
        assert c1.get(key, 0) > 0, (key, c1)
    # A different seed diverges (the knobs really are driven by the seed).
    t3, _ = _pump(*_scripted_sim(43))
    assert t3 != t1


def test_gilbert_elliott_loss_is_bursty():
    """GE loss must arrive in runs (mean run ≈ 100/p_exit_bad packets),
    not i.i.d. — the property that makes it a different failure mode."""
    sim = NetSim()
    sim.seed(7)
    sim.set_conditions(ge=GEParams(p_enter_bad=2, p_exit_bad=10, loss_bad=100))
    dropped = [sim.on_send(None, False)[0] for _ in range(5000)]
    rate = sum(dropped) / len(dropped)
    assert 0.05 < rate < 0.40, rate  # stationary ~1/6 at these params
    runs, cur = [], 0
    for d in dropped:
        if d:
            cur += 1
        elif cur:
            runs.append(cur)
            cur = 0
    mean_run = sum(runs) / len(runs)
    assert mean_run > 3.0, mean_run  # i.i.d. at this rate would be ~1.2


def test_schedule_steps_apply_in_time_order():
    sim = NetSim()
    sim.seed(1)
    clock = [0.0]
    sim.run(
        Schedule()
        .at(0.0, conditions(drop=100))
        .at(5.0, conditions())
        .at(10.0, partition("server", "tx"))
        .at(15.0, heal()),
        clock=lambda: clock[0],
    )
    assert sim.on_send(None, False)[0] is True  # 100% loss phase
    clock[0] = 6.0
    assert sim.on_send(None, False)[0] is False  # healed
    clock[0] = 11.0
    assert sim.on_send(None, True)[0] is True  # server tx partitioned
    assert sim.on_send(None, False)[0] is False  # clients unaffected
    clock[0] = 16.0
    assert sim.on_send(None, True)[0] is False  # healed again


def test_schedule_loop_every_replays_scenario():
    """``run(..., loop_every=N)`` re-arms the scenario every N seconds —
    sustained chaos for long benches (tools/fleet_bench.py --chaos) —
    instead of disarming after the last step."""
    sim = NetSim()
    sim.seed(1)
    clock = [0.0]
    sim.run(
        Schedule().at(0.0, conditions(drop=100)).at(1.0, conditions()),
        clock=lambda: clock[0],
        loop_every=2.0,
    )
    assert sim.on_send(None, False)[0] is True  # loss phase, period 0
    clock[0] = 1.5
    assert sim.on_send(None, False)[0] is False  # healed window
    clock[0] = 2.1
    assert sim.on_send(None, False)[0] is True  # wrapped: loss phase again
    clock[0] = 3.5
    assert sim.on_send(None, False)[0] is False  # healed window, period 1
    clock[0] = 8.1  # several periods later, mid-loss again
    assert sim.on_send(None, False)[0] is True


def test_schedule_without_loop_still_disarms():
    sim = NetSim()
    sim.seed(1)
    clock = [0.0]
    sim.run(
        Schedule().at(0.0, conditions(drop=100)).at(1.0, conditions()),
        clock=lambda: clock[0],
    )
    clock[0] = 5.0
    assert sim.on_send(None, False)[0] is False
    assert sim._enabled is False  # fast path re-disarmed


def test_heal_does_not_pin_ambient_conditions():
    """Partitioning an endpoint while ambient loss is installed, then
    healing, must not leave the endpoint pinned to a stale copy of that
    loss — partitions and conditions are orthogonal state."""
    sim = NetSim()
    sim.seed(5)
    sim.set_conditions(drop=40)
    sim.partition("server", "tx")
    sim.set_conditions()  # heal the ambient loss
    sim.heal("server")  # lift the partition
    assert all(not sim.on_send(None, True)[0] for _ in range(200))
    assert all(not sim.on_send("server", True)[0] for _ in range(200))


def test_directional_partition_cuts_only_one_side():
    sim = NetSim()
    sim.seed(3)
    sim.partition("miner-1", "rx")
    assert sim.on_recv("miner-1", False) is True
    assert sim.on_send("miner-1", False)[0] is False  # tx still flows
    assert sim.on_recv("miner-2", False) is False  # peers unaffected
    sim.heal("miner-1")
    assert sim.on_recv("miner-1", False) is False


# --------------------------------------------------------------------------
# 2. Self-healing units
# --------------------------------------------------------------------------


def test_miner_reconnect_backoff_rejoins_after_server_restart():
    """Acceptance drill: kill the server conn under the miner mid-chunk,
    restart listening on the same port, and observe re-Join + new chunk
    completion with no operator intervention."""
    port = random_port()
    reconnects0 = METRICS.get("miner.reconnects")
    first_chunk = threading.Event()
    hold = threading.Event()

    def gated_search(d, lo, hi):
        if not first_chunk.is_set():
            first_chunk.set()
            hold.wait(timeout=20)  # wedge the first chunk until the kill
        return min_hash_range(d, lo, hi)

    server1 = lsp.Server(port, PARAMS, label="server")
    threading.Thread(
        target=server_mod.serve, args=(server1, Scheduler(min_chunk=500)),
        daemon=True,
    ).start()
    threading.Thread(
        target=miner_mod.run_miner_resilient,
        args=("127.0.0.1", port, gated_search),
        kwargs={
            "params": PARAMS, "max_retries": 15, "backoff_base": 0.05,
            "backoff_cap": 0.3, "label": "miner-0",
        },
        daemon=True,
    ).start()

    out = {}

    def run_client():
        out["res"] = client_mod.request_with_retry(
            "127.0.0.1", port, "rejoin", 2000,
            retries=10, backoff_base=0.2, params=PARAMS, label="client-0",
        )

    ct = threading.Thread(target=run_client, daemon=True)
    ct.start()
    assert first_chunk.wait(timeout=30), "miner never got a chunk"
    server1.close()  # the server conn dies under the miner mid-chunk
    hold.set()
    server2 = lsp.Server(port, PARAMS, label="server")
    threading.Thread(
        target=server_mod.serve, args=(server2, Scheduler(min_chunk=500)),
        daemon=True,
    ).start()
    try:
        ct.join(timeout=60)
        assert not ct.is_alive(), "client starved after server restart"
        assert out["res"] == min_hash_range("rejoin", 0, 2000)
        assert METRICS.get("miner.reconnects") > reconnects0
    finally:
        server2.close()


def test_resilient_miner_exits_on_backend_failure():
    """A broken search backend must STOP a resilient miner — reconnecting
    to a live server after a search failure would churn join/fail/assign
    forever (the conn is fine; the compute is not)."""
    server = lsp.Server(0, PARAMS)
    threading.Thread(
        target=server_mod.serve, args=(server, Scheduler(min_chunk=500)),
        daemon=True,
    ).start()

    def broken(d, lo, hi):
        raise RuntimeError("dead backend")

    done = threading.Event()

    def run():
        miner_mod.run_miner_resilient(
            "127.0.0.1", server.port, broken,
            params=PARAMS, max_retries=5, backoff_base=0.05,
        )
        done.set()

    threading.Thread(target=run, daemon=True).start()
    try:
        c = lsp.Client("127.0.0.1", server.port, PARAMS)
        c.write(Message.request("doomed", 0, 5000).marshal())  # feeds a chunk
        assert done.wait(timeout=30), "resilient miner churned instead of exiting"
        c.close()
    finally:
        server.close()


def test_watchdog_downgrades_wedged_then_broken_tiers():
    """Pallas→XLA→hashlib in miniature: a tier that wedges and a tier that
    raises are both abandoned; the chunk re-runs and completes on the
    bottom tier."""
    downgrades0 = METRICS.get("miner.tier_downgrades")
    hold = threading.Event()

    def wedged(d, lo, hi):
        hold.wait(timeout=30)
        return (0, 0)

    def broken(d, lo, hi):
        raise RuntimeError("simulated kernel failure")

    ts = miner_mod._TieredSearch(
        [("wedged", lambda: wedged), ("broken", lambda: broken),
         ("oracle", lambda: min_hash_range)],
        wedge_seconds=0.4,
    )
    try:
        fut = ts.submit("tiers", 0, 500)
        assert fut.result(timeout=30) == min_hash_range("tiers", 0, 500)
        assert METRICS.get("miner.tier_downgrades") - downgrades0 == 2
        assert ts.active_tier == "oracle"
        # The downgraded chain keeps serving subsequent chunks directly.
        assert ts.submit("tiers2", 0, 300).result(timeout=30) == (
            min_hash_range("tiers2", 0, 300)
        )
    finally:
        hold.set()
        ts.close()


def test_watchdog_miner_serves_fleet_after_downgrade():
    """A fleet whose only miner starts on a wedging tier still answers —
    run_miner never notices the tier swap happening beneath it."""
    hold = threading.Event()

    def wedged(d, lo, hi):
        hold.wait(timeout=30)
        return (0, 0)

    server = lsp.Server(0, PARAMS)
    threading.Thread(
        target=server_mod.serve, args=(server, Scheduler(min_chunk=500)),
        daemon=True,
    ).start()
    ts = miner_mod._TieredSearch(
        [("wedged", lambda: wedged), ("cpu", lambda: miner_mod.make_search("cpu"))],
        wedge_seconds=0.5,
    )
    mc = lsp.Client("127.0.0.1", server.port, PARAMS)
    threading.Thread(
        target=miner_mod.run_miner, args=(mc, ts), daemon=True
    ).start()
    try:
        c = lsp.Client("127.0.0.1", server.port, PARAMS)
        try:
            res = client_mod.request_once(c, "wedgefleet", 2000)
        finally:
            c.close()
        assert res == min_hash_range("wedgefleet", 0, 2000)
    finally:
        hold.set()
        server.close()


def test_wedge_dispatch_hook_downgrades_real_wedged_pipeline(monkeypatch):
    """ISSUE 10 satellite (carry-over from PR 2): ``BMT_WEDGE_DISPATCH=1``
    hangs the first result fetched by a REAL :class:`SweepPipeline` — a
    genuine stuck device future inside the dispatch/fetch machinery, not
    a simulated sleeping search fn — and the watchdog's budget must abandon
    that tier, close the wedged pipeline (which releases the injected
    hang) and complete the chunk on the next rung without cascading."""
    from bitcoin_miner_tpu.ops import sweep as sweep_mod

    monkeypatch.setenv("BMT_WEDGE_DISPATCH", "1")
    monkeypatch.setitem(sweep_mod._WEDGE_STATE, "fired", False)
    downgrades0 = METRICS.get("miner.tier_downgrades")
    ts = miner_mod._TieredSearch(
        [
            ("xla-pipe", lambda: miner_mod._PipelineSearch("xla")),
            ("oracle", lambda: min_hash_range),
        ],
        wedge_seconds=4.0,
    )
    try:
        fut = ts.submit("wedgedisp", 0, 80)
        assert fut.result(timeout=120) == min_hash_range("wedgedisp", 0, 80)
        assert ts.active_tier == "oracle"
        assert METRICS.get("miner.tier_downgrades") - downgrades0 == 1
        assert sweep_mod._WEDGE_STATE["fired"]  # the hang was real
        # One-shot per process: a later chunk on the downgraded chain (or
        # any future pipeline) must not inherit the wedge.
        assert ts.submit("wedgedisp2", 0, 50).result(timeout=30) == (
            min_hash_range("wedgedisp2", 0, 50)
        )
    finally:
        ts.close()


@pytest.mark.analysis
def test_straggler_tail_steal_soak_whole_range_correct_sanitized():
    """ISSUE 10 chaos-soak leg: a seeded burst-lossy fleet whose slowest
    miner wedges flat on its first chunk (live-but-hung).  The steal scan
    re-dispatches the hostage chunk's tail to an idle healthy miner well
    before the full straggler re-queue fires, the job completes
    whole-range-correct against the hashlib oracle, and the whole weave
    runs green under the BMT_SANITIZE=1 race machinery."""
    from bitcoin_miner_tpu.utils import sanitize

    sanitize.force(True)
    sanitize.reset_order_graph()
    CHAOS.reset()
    CHAOS.seed(31)
    CHAOS.run(standard_scenarios()["burst-loss"], loop_every=2.0)
    steals0 = METRICS.get("sched.steals")
    server = lsp.Server(0, PARAMS, label="server")
    sched = Scheduler(
        min_chunk=500, max_chunk=2000,
        straggler_min_seconds=2.5,
        steal_min_seconds=0.3, steal_min_samples=4,
    )
    lock = sanitize.make_lock("steal-soak")
    threading.Thread(
        target=server_mod.serve, args=(server, sched),
        kwargs={"tick_interval": 0.1, "lock": lock}, daemon=True,
    ).start()

    wedged_once = threading.Event()

    def slow_search(d, lo, hi):
        # First chunk wedges flat for 8 s (a stuck-runtime episode, the
        # regime the steal scan exists for); honest afterwards.
        if not wedged_once.is_set():
            wedged_once.set()
            time.sleep(8.0)
        return min_hash_range(d, lo, hi)

    searches = [slow_search, min_hash_range, min_hash_range]
    for i, fn in enumerate(searches):
        mc = lsp.Client("127.0.0.1", server.port, PARAMS, label=f"m{i}")
        threading.Thread(
            target=miner_mod.run_miner, args=(mc, fn), daemon=True
        ).start()
    try:
        deadline = time.time() + 30
        while time.time() < deadline:
            with lock:
                n = sched.stats()["miners"]
            if n == len(searches):
                break
            time.sleep(0.05)
        else:
            raise AssertionError("miners never joined")
        c = lsp.Client("127.0.0.1", server.port, PARAMS, label="client-0")
        try:
            got = client_mod.request_once(c, "stealsoak", 20_000)
        finally:
            c.close()
        assert got == min_hash_range("stealsoak", 0, 20_000)
        # The induced straggler's tail really was stolen (not merely
        # ridden out by the full re-queue).
        assert METRICS.get("sched.steals") > steals0
        assert wedged_once.is_set()
    finally:
        CHAOS.reset()
        server.close()
        sanitize.force(None)
        sanitize.reset_order_graph()


def test_client_resubmit_resumes_from_orphan_stash():
    """Kill a client mid-job; the scheduler stashes the job's progress
    under its (data, lower, upper) identity, and the resubmitted identical
    Request resumes (jobs_resumed ticks) instead of restarting."""
    orphaned0 = METRICS.get("sched.jobs_orphaned")
    resumed0 = METRICS.get("sched.jobs_resumed")
    server = lsp.Server(0, PARAMS)
    # max_chunk pins the adaptive sizing so the job reliably outlives the
    # client-death detection window (epoch_limit * epoch_seconds).
    sched = Scheduler(min_chunk=300, max_chunk=300, straggler_min_seconds=30.0)
    threading.Thread(
        target=server_mod.serve, args=(server, sched), daemon=True
    ).start()

    def slow(d, lo, hi):
        time.sleep(0.1)  # keep the job alive long enough to orphan it
        return min_hash_range(d, lo, hi)

    mc = lsp.Client("127.0.0.1", server.port, PARAMS)
    threading.Thread(target=miner_mod.run_miner, args=(mc, slow), daemon=True).start()
    try:
        c1 = lsp.Client("127.0.0.1", server.port, PARAMS)
        c1.write(Message.request("resume-me", 0, 12000).marshal())

        def folded() -> bool:  # some real progress has landed
            try:
                return any(j.best is not None for j in list(sched.jobs.values()))
            except RuntimeError:  # jobs dict resized mid-snapshot: retry
                return False

        deadline = time.time() + 20
        while time.time() < deadline and not folded():
            time.sleep(0.05)
        assert folded()
        c1.close()  # client dies mid-job
        deadline = time.time() + 20
        while time.time() < deadline and not sched._resume:
            time.sleep(0.05)
        assert METRICS.get("sched.jobs_orphaned") > orphaned0
        res = client_mod.request_with_retry(
            "127.0.0.1", server.port, "resume-me", 12000,
            retries=2, params=PARAMS,
        )
        assert res == min_hash_range("resume-me", 0, 12000)
        assert METRICS.get("sched.jobs_resumed") > resumed0
    finally:
        server.close()


def test_client_disconnected_contract_under_total_drop(monkeypatch):
    """Frozen L4 contract: 100% write drop mid-job (both directions dead)
    must end with stdout exactly ``Disconnected`` — no retries by default,
    no traceback, nothing else."""
    # The CLI uses default LSP params (2 s epochs); swap in fast ones so
    # loss detection fits the tier-1 budget without touching the contract.
    real_client = lsp.Client
    monkeypatch.setattr(
        client_mod.lsp, "Client",
        lambda host, port, params=None, label=None: real_client(
            host, port, PARAMS, label=label
        ),
    )
    server = lsp.Server(0, PARAMS)
    threading.Thread(
        target=server_mod.serve, args=(server, Scheduler()), daemon=True
    ).start()  # no miners: the job can never finish
    out = io.StringIO()
    t = threading.Thread(
        target=client_mod.main,
        args=(["client", f"127.0.0.1:{server.port}", "x", "100000"],),
        kwargs={"out": out},
        daemon=True,
    )
    t.start()
    time.sleep(0.5)  # request reaches the scheduler
    lspnet.set_write_drop_percent(100)
    try:
        t.join(timeout=30)
        assert not t.is_alive(), "client never detected the dead conn"
        assert out.getvalue() == "Disconnected\n"
    finally:
        lspnet.reset_faults()
        server.close()


# --------------------------------------------------------------------------
# 3. Seeded fleet soaks (oracle bit-exactness under chaos)
# --------------------------------------------------------------------------


def test_fast_seeded_scenario_oracle_exact():
    """The tier-1 chaos gate: a small fleet rides out a seeded burst-loss
    schedule and the Result is bit-exact.  Fails?  Replay it:
    ``python tools/chaos_replay.py --scenario burst-loss --seed 11``."""
    report = run_drill(
        "burst-loss", seed=11, data="fastchaos", max_nonce=2500,
        n_miners=2, timeout=90.0,
    )
    assert report.ok, report.as_dict()
    assert report.counters.get("chaos.dropped", 0) > 0, report.as_dict()


@pytest.mark.workloads
@pytest.mark.parametrize("wname", workloads_mod.names())
def test_fast_seeded_scenario_oracle_exact_per_workload(wname):
    """The same seeded burst-loss drill over EVERY registered range-fold
    workload (ISSUE 9): the chaos/self-healing machinery is
    workload-blind — scheduler validation, miner sweeps and the oracle
    all come from the registry, and the Result stays bit-exact against
    that workload's own hashlib oracle under packet loss."""
    w = workloads_mod.get(wname)
    # max_nonce matches the unparameterized drill above (~7 chunks, not
    # 3): on a fully warm process a 1500-nonce job could finish in so few
    # datagrams that the seeded Gilbert–Elliott chain never entered its
    # bad state, and the chaos.dropped assertion flaked on suite timing.
    # Every workload's drill tier is a host sweep, so the extra nonces
    # cost milliseconds.
    report = run_drill(
        "burst-loss", seed=11, data=f"wlchaos-{wname}", max_nonce=2500,
        n_miners=2, timeout=90.0,
        workload=None if wname == workloads_mod.DEFAULT_WORKLOAD else w,
    )
    assert report.ok, report.as_dict()
    assert report.counters.get("chaos.dropped", 0) > 0, report.as_dict()


@pytest.mark.analysis
def test_fast_scenario_green_under_race_sanitizer():
    """The same tier-1 burst-loss drill with BMT_SANITIZE=1 machinery
    armed: serve()'s event lock becomes a TrackedLock, the scheduler a
    Monitor, and any off-lock access or lock-order inversion in the
    read-loop/ticker weave aborts the fleet — so the drill only passes
    if the serve-loop discipline holds under packet loss and reconnect
    churn (ISSUE 4 acceptance: the chaos soak runs green sanitized)."""
    from bitcoin_miner_tpu.utils import sanitize

    sanitize.force(True)
    sanitize.reset_order_graph()
    try:
        report = run_drill(
            "burst-loss", seed=17, data="sanichaos", max_nonce=2000,
            n_miners=2, timeout=90.0,
        )
        assert report.ok, report.as_dict()
    finally:
        sanitize.force(None)
        sanitize.reset_order_graph()


@pytest.mark.fleet
def test_telemetry_partition_ages_source_out_while_fleet_keeps_sweeping():
    """ISSUE 7: burst loss + a directional partition of the TELEMETRY
    sidecar must never touch the serving plane.  The partitioned source
    ages out of the fleet view as stale, the miner keeps sweeping to a
    bit-exact Result through the ambient loss, and the serve ticker
    (which drives the hub) never blocks — then the heal brings the
    source back fresh via exporter reconnect."""
    from bitcoin_miner_tpu.utils.fleetview import FleetView
    from bitcoin_miner_tpu.utils.telemetry import (
        TelemetryExporter,
        TelemetryHub,
    )

    CHAOS.seed(21)
    hub = TelemetryHub(
        0, params=PARAMS, publish_interval=0.1,
        fleet=FleetView(staleness_s=1.5),
    ).start()
    server = lsp.Server(0, PARAMS, label="server")
    threading.Thread(
        target=server_mod.serve,
        args=(server, Scheduler(min_chunk=500)),
        kwargs={"tick_interval": 0.1, "telemetry": hub},
        daemon=True,
    ).start()
    mc = lsp.Client("127.0.0.1", server.port, PARAMS, label="m1")
    threading.Thread(
        target=miner_mod.run_miner, args=(mc, min_hash_range), daemon=True
    ).start()
    # Exporter label defaults to tele-m1: the partition below cuts ONLY
    # the sidecar endpoint, not the miner's serving conn.
    exp = TelemetryExporter(
        "127.0.0.1", hub.port, "m1", interval=0.1, params=PARAMS
    ).start()
    try:
        deadline = time.time() + 20
        while time.time() < deadline:
            src = hub.fleet.sources().get("m1")
            if src and not src["stale"]:
                break
            time.sleep(0.05)
        assert hub.fleet.sources().get("m1"), "telemetry never arrived"
        # Chaos: ambient burst loss everywhere + sidecar blackhole.
        CHAOS.set_conditions(
            ge=GEParams(p_enter_bad=3, p_exit_bad=12, loss_bad=90)
        )
        CHAOS.partition("tele-m1", "both")
        # Miners keep sweeping: a job issued DURING the partition+loss
        # completes bit-exact (loss only costs retransmits).
        c = lsp.Client("127.0.0.1", server.port, PARAMS, label="client-0")
        try:
            res = client_mod.request_once(c, "telechaos", 3000)
        finally:
            c.close()
        assert res == min_hash_range("telechaos", 0, 3000)
        # The partitioned source ages out as stale — observed through the
        # hub the SERVE TICKER drives, so a stale source passing through
        # here also proves no serve-loop tick blocked on telemetry.
        deadline = time.time() + 20
        stale_state = None
        while time.time() < deadline:
            st = hub.last_state()
            if st and st["per_source"].get("m1", {}).get("stale"):
                stale_state = st
                break
            time.sleep(0.1)
        assert stale_state is not None, hub.last_state()
        assert stale_state["stale_sources"] >= 1
        assert METRICS.gauge("fleet.sources_stale") >= 1
        # Heal: the exporter's reconnect loop re-delivers and the source
        # returns fresh (seq restarts at 1; the view accepts it).
        CHAOS.reset()
        deadline = time.time() + 30
        back = False
        while time.time() < deadline:
            src = hub.fleet.sources().get("m1")
            if src and not src["stale"]:
                back = True
                break
            time.sleep(0.1)
        assert back, hub.fleet.sources()
    finally:
        exp.stop()
        CHAOS.reset()
        server.close()
        hub.close()


@pytest.mark.slow
@pytest.mark.parametrize(
    "scenario,seed,kill_at",
    [
        ("burst-loss", 101, None),
        ("reorder-dup-delay", 202, None),
        ("flaky-then-partition", 303, None),
        ("miner-partition", 404, 0.8),  # isolation + mid-job miner kill
    ],
)
def test_chaos_soak_schedules(scenario, seed, kill_at):
    """The long soaks: every standard schedule (plus a mid-job kill of the
    non-resilient miner in the partition scenario) must still produce the
    oracle-exact Result through reassignment, re-Join and resubmission."""
    report = run_drill(
        scenario, seed=seed, data=f"soak-{scenario}", max_nonce=6000,
        n_miners=3, kill_miner_at=kill_at, timeout=180.0,
    )
    assert report.ok, report.as_dict()


@pytest.mark.slow
def test_chaos_replay_tool_smoke():
    """tools/chaos_replay.py end to end: --list names the scenarios and a
    tiny replayed drill reports ok=true with a zero exit."""
    import json
    import os
    import subprocess
    import sys
    from pathlib import Path

    repo = Path(__file__).resolve().parents[1]
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    listing = subprocess.run(
        [sys.executable, str(repo / "tools" / "chaos_replay.py"), "--list"],
        capture_output=True, text=True, timeout=60, env=env,
    )
    assert listing.returncode == 0 and "burst-loss" in listing.stdout
    run = subprocess.run(
        [sys.executable, str(repo / "tools" / "chaos_replay.py"),
         "--scenario", "burst-loss", "--seed", "5", "--max-nonce", "1500"],
        capture_output=True, text=True, timeout=120, env=env,
    )
    assert run.returncode == 0, run.stdout + run.stderr
    report = json.loads(run.stdout.strip().splitlines()[-1])
    assert report["ok"] is True


# --------------------------------------------------------------------------
# 4. Packet-level bandwidth caps (ISSUE 8 satellite, carry-over from PR 2)
# --------------------------------------------------------------------------


class TestBandwidthCap:
    """Token-bucket bytes/s shaping per link: insufficient credit queues
    the packet (delivery delay), never drops it."""

    def _sim(self, **cond):
        sim = NetSim()
        clock = [0.0]
        sim.run(Schedule().at(0.0, conditions(**cond)),
                clock=lambda: clock[0])
        return sim, clock

    def test_burst_passes_then_backlog_queues(self):
        sim, clock = self._sim(rate_bps=1000, burst_bytes=1000)
        d1 = sim.on_send(None, False, 600)  # within the burst credit
        d2 = sim.on_send(None, False, 600)  # 200 bytes over: 0.2s queue
        d3 = sim.on_send(None, False, 1000)  # behind d2: 1.2s total backlog
        assert d1[0] is False and d1[2] == 0.0
        assert d2[0] is False and abs(d2[2] - 0.2) < 1e-9
        assert d3[0] is False and abs(d3[2] - 1.2) < 1e-9
        assert sim.counters()["throttled"] == 2

    def test_idle_time_refills_up_to_burst(self):
        sim, clock = self._sim(rate_bps=1000, burst_bytes=1000)
        sim.on_send(None, False, 1000)
        sim.on_send(None, False, 500)  # 0.5s backlog
        clock[0] = 10.0  # long idle: credit refills, capped at burst
        d = sim.on_send(None, False, 1000)
        assert d[2] == 0.0
        d = sim.on_send(None, False, 400)
        assert abs(d[2] - 0.4) < 1e-9

    def test_per_link_buckets_are_independent(self):
        sim = NetSim()
        clock = [0.0]
        sim.run(
            Schedule().at(0.0, conditions("gossip-r1", rate_bps=100,
                                          burst_bytes=100)),
            clock=lambda: clock[0],
        )
        # The capped label queues; an uncapped peer label does not.
        assert sim.on_send("gossip-r1", False, 100)[2] == 0.0
        assert sim.on_send("gossip-r1", False, 100)[2] > 0.0
        assert sim.on_send("gossip-r2", False, 10_000)[2] == 0.0
        # A second capped link would have its own credit (derived per
        # (key, direction)), so the r1 backlog never leaks across links.
        assert sim.counters()["throttled"] == 1

    def test_zero_rate_means_unlimited(self):
        sim, clock = self._sim(delay_ms=0, rate_bps=0)
        for _ in range(50):
            assert sim.on_send(None, False, 10_000) == (False, False, 0.0, False)
        assert "throttled" not in sim.counters()

    def test_shaped_link_still_delivers_e2e(self):
        """A throttled loopback fleet: the serving link capped hard enough
        to engage the shaper, the Result still lands bit-exact (shaping
        degrades to lag, not loss)."""
        CHAOS.reset()
        CHAOS.set_conditions("server", rate_bps=64_000, burst_bytes=2_000)
        server = lsp.Server(0, PARAMS, label="server")
        sched = Scheduler(min_chunk=500)
        threading.Thread(
            target=server_mod.serve, args=(server, sched),
            kwargs={"tick_interval": 0.05}, daemon=True,
        ).start()
        mc = lsp.Client("127.0.0.1", server.port, PARAMS)
        threading.Thread(
            target=miner_mod.run_miner,
            args=(mc, miner_mod.make_search("cpu")), daemon=True,
        ).start()
        try:
            c = lsp.Client("127.0.0.1", server.port, PARAMS)
            try:
                got = client_mod.request_once(c, "shaped", 3000)
            finally:
                c.close()
            assert got == min_hash_range("shaped", 0, 3000)
            assert METRICS.get("chaos.throttled") > 0
        finally:
            CHAOS.reset()
            server.close()


# --------------------------------------------------------------------------
# 5. Gateway + interval store under the chaos soak (ISSUE 8 satellite,
#    carry-over from PR 3/5): shed/coalesce/span-flush under seeded burst
#    loss, green under the race sanitizer.
# --------------------------------------------------------------------------


@pytest.mark.gateway
@pytest.mark.analysis
def test_gateway_interval_store_chaos_soak_sanitized(tmp_path):
    """An overlap-heavy burst-lossy soak through the FULL serving stack —
    admission (small max_active/max_queued so requests queue and shed),
    coalescing, the interval store with disk persistence — with
    BMT_SANITIZE=1 machinery armed.  Shed/retried clients resubmit; every
    final answer is oracle-bit-exact; the span store flushes to disk and
    reloads."""
    from bitcoin_miner_tpu.gateway import Gateway, ResultCache, SpanStore
    from bitcoin_miner_tpu.utils import sanitize

    sanitize.force(True)
    sanitize.reset_order_graph()
    CHAOS.reset()
    CHAOS.seed(23)
    CHAOS.run(standard_scenarios()["burst-loss"], loop_every=2.0)
    spans_path = str(tmp_path / "spans.json")
    server = lsp.Server(0, PARAMS, label="server")
    gw = Gateway(
        Scheduler(min_chunk=500),
        cache=ResultCache(),
        spans=SpanStore(path=spans_path),
        rate=None,
        max_active=2,
        max_queued=4,
    )
    threading.Thread(
        target=server_mod.serve, args=(server, gw),
        kwargs={"tick_interval": 0.05}, daemon=True,
    ).start()
    for _ in range(2):
        mc = lsp.Client("127.0.0.1", server.port, PARAMS)
        threading.Thread(
            target=miner_mod.run_miner,
            args=(mc, miner_mod.make_search("cpu")), daemon=True,
        ).start()
    # Nested/overlapping signatures over two data keys: coalesce hits,
    # span answers and queued/shed admission all engage at once.
    jobs = [
        ("soak-a", 0, 4000), ("soak-a", 0, 4000), ("soak-a", 1000, 3000),
        ("soak-b", 0, 3000), ("soak-b", 500, 2500), ("soak-a", 0, 2000),
        ("soak-b", 0, 3000), ("soak-a", 2000, 4000),
    ]
    out = {}

    def one(i):
        data, lo, hi = jobs[i]
        # Shed conns close like dead clients; resubmit like a real client
        # (the identical signature resumes/coalesces server-side).
        for _ in range(6):
            try:
                c = lsp.Client("127.0.0.1", server.port, PARAMS)
            except (lsp.LspError, OSError):
                continue
            try:
                got = client_mod.request_once(c, data, hi, lower=lo)
            finally:
                try:
                    c.close()
                except lsp.LspError:
                    pass
            if got is not None:
                out[i] = got
                return

    try:
        threads = [threading.Thread(target=one, args=(i,)) for i in range(len(jobs))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
            assert not t.is_alive(), "soak client starved"
        for i, (data, lo, hi) in enumerate(jobs):
            assert out.get(i) == min_hash_range(data, lo, hi), f"job {i}"
        assert METRICS.get("chaos.dropped") > 0  # the loss was real
    finally:
        CHAOS.reset()
        server.close()
        sanitize.force(None)
        sanitize.reset_order_graph()
    # The final flush persisted the solved spans; a cold store reloads
    # them and still answers a covered sub-range of the soaked work.
    from bitcoin_miner_tpu.gateway.cache import SpanStore as ColdStore

    cold = ColdStore(path=spans_path)
    assert len(cold) > 0


# --------------------------------------------------------------------------
# 6. Full-matrix BMT_SANITIZE=1 soak (ISSUE 12 carry-over satellite):
#    gateway + federation + steal legs in one sanitized run, slow tier.
# --------------------------------------------------------------------------


@pytest.mark.slow
def test_full_matrix_sanitized_soak_gateway_federation_steal(tmp_path):
    """The whole thread weave under the race sanitizer in one slow run:
    (A) a burst-lossy duplicate-heavy fleet through the full gateway
    stack, (B) the federation resilience drills — replica serve loops,
    ingest, forwarders and gossip daemons all sharing TrackedLocks, with
    the LSP loop threads joined into the acquisition-order graph
    (ISSUE 12) — and (C) a live-but-hung straggler whose tail the steal
    scan re-dispatches.  Any off-lock access, lock-order inversion, or
    loop-thread deadlock shape aborts the soak."""
    from bitcoin_miner_tpu.federation import drill as fed_drill
    from bitcoin_miner_tpu.gateway import Gateway, SpanStore
    from bitcoin_miner_tpu.utils import sanitize

    sanitize.force(True)
    sanitize.reset_order_graph()
    try:
        # ---- leg A: gateway stack under seeded burst loss -------------
        CHAOS.reset()
        CHAOS.seed(47)
        CHAOS.run(standard_scenarios()["burst-loss"], loop_every=2.0)
        server = lsp.Server(0, PARAMS, label="server")
        gw = Gateway(Scheduler(min_chunk=500), spans=SpanStore(), rate=None)
        threading.Thread(
            target=server_mod.serve, args=(server, gw),
            kwargs={"tick_interval": 0.05}, daemon=True,
        ).start()
        for _ in range(2):
            mc = lsp.Client("127.0.0.1", server.port, PARAMS)
            threading.Thread(
                target=miner_mod.run_miner,
                args=(mc, miner_mod.make_search("cpu")), daemon=True,
            ).start()
        try:
            jobs = [("mx-a", 0, 4000), ("mx-a", 0, 4000),
                    ("mx-a", 1000, 3000), ("mx-b", 0, 3000)]
            out = {}

            def one(i):
                data, lo, hi = jobs[i]
                for _ in range(6):
                    try:
                        c = lsp.Client("127.0.0.1", server.port, PARAMS)
                    except (lsp.LspError, OSError):
                        continue
                    try:
                        got = client_mod.request_once(c, data, hi, lower=lo)
                    finally:
                        try:
                            c.close()
                        except lsp.LspError:
                            pass
                    if got is not None:
                        out[i] = got
                        return

            threads = [
                threading.Thread(target=one, args=(i,))
                for i in range(len(jobs))
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
                assert not t.is_alive(), "gateway leg client starved"
            for i, (data, lo, hi) in enumerate(jobs):
                assert out.get(i) == min_hash_range(data, lo, hi), f"job {i}"
        finally:
            CHAOS.reset()
            server.close()

        # ---- leg B: federation resilience drills, sanitized -----------
        for name in ("shed-storm", "death-detect", "ack-retransmit",
                     "drain-handoff"):
            report = fed_drill.run_fed_drill(name, seed=47)
            assert report["ok"], report

        # ---- leg C: steal scan on a live-but-hung straggler -----------
        CHAOS.seed(48)
        CHAOS.run(standard_scenarios()["burst-loss"], loop_every=2.0)
        steals0 = METRICS.get("sched.steals")
        server = lsp.Server(0, PARAMS, label="server")
        sched = Scheduler(
            min_chunk=500, max_chunk=2000,
            straggler_min_seconds=2.5,
            steal_min_seconds=0.3, steal_min_samples=4,
        )
        threading.Thread(
            target=server_mod.serve, args=(server, sched),
            kwargs={"tick_interval": 0.1}, daemon=True,
        ).start()
        wedged_once = threading.Event()

        def slow_search(d, lo, hi):
            if not wedged_once.is_set():
                wedged_once.set()
                time.sleep(8.0)
            return min_hash_range(d, lo, hi)

        for i, fn in enumerate([slow_search, min_hash_range, min_hash_range]):
            mc = lsp.Client("127.0.0.1", server.port, PARAMS, label=f"m{i}")
            threading.Thread(
                target=miner_mod.run_miner, args=(mc, fn), daemon=True
            ).start()
        try:
            c = lsp.Client("127.0.0.1", server.port, PARAMS)
            try:
                got = client_mod.request_once(c, "mx-steal", 20_000)
            finally:
                c.close()
            assert got == min_hash_range("mx-steal", 0, 20_000)
            assert METRICS.get("sched.steals") > steals0
        finally:
            CHAOS.reset()
            server.close()
    finally:
        sanitize.force(None)
        sanitize.reset_order_graph()
