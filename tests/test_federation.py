"""The federated serving tier (ISSUE 8).

Three layers, mirroring how the gateway is tested:

- pure-unit: the consistent-hash ring (determinism, failover order) and
  the gossip codec/journal (round-trip through the telemetry frame
  machinery, every datagram under the frozen 1000-byte wire ceiling);
- replica e2e over loopback LSP: routing (a request landing on a
  non-home replica forwards and answers bit-exact), duplicate collapse
  across replicas, gossip convergence (a range solved on replica A
  answers a covered sub-range at replica B with ZERO chunks assigned),
  failover past a dead home, and the local fallback when every peer is
  gone;
- seeded drills (the ISSUE 8 acceptance): a scheduler cell killed
  mid-sweep with the client resubmitting through a survivor —
  whole-range-correct, oracle-bit-exact — and a gossip-link partition
  that leaves one replica stale until it heals and converges.
"""

import threading
import time

import pytest

from bitcoin_miner_tpu import lsp, lspnet
from bitcoin_miner_tpu.apps import client as client_mod
from bitcoin_miner_tpu.apps import miner as miner_mod
from bitcoin_miner_tpu.apps.scheduler import Scheduler
from bitcoin_miner_tpu.bitcoin.hash import min_hash_range
from bitcoin_miner_tpu.federation import (
    GossipSpanStore,
    Membership,
    Replica,
    Ring,
    decode_fed,
    decode_gossip,
    encode_gossip,
    encode_handoff,
)
from bitcoin_miner_tpu.federation import drill as fed_drill
from bitcoin_miner_tpu.federation.gossip import SpanGossip, apply_gossip
from bitcoin_miner_tpu.federation.membership import (
    ALIVE,
    DEAD,
    LOAD_DRAINING,
    LOAD_SHEDDING,
    SUSPECT,
)
from bitcoin_miner_tpu.lspnet.chaos import CHAOS
from bitcoin_miner_tpu.utils import sanitize
from bitcoin_miner_tpu.utils.metrics import METRICS
from bitcoin_miner_tpu.utils.telemetry import FrameAssembler

from lsp_harness import random_port

pytestmark = pytest.mark.federation

PARAMS = lsp.Params(epoch_limit=5, epoch_millis=200, window_size=5)


@pytest.fixture(autouse=True)
def _clean_network():
    lspnet.reset_faults()
    CHAOS.reset()
    yield
    lspnet.reset_faults()
    CHAOS.reset()


# ---------------------------------------------------------------------- ring


class TestRing:
    def test_deterministic_and_order_independent(self):
        a = Ring(["r1", "r2", "r3"])
        b = Ring(["r3", "r1", "r2"])
        for key in ("alpha", "beta", "gamma", "cmu440", ""):
            assert a.route(key) == b.route(key)

    def test_route_is_a_permutation_of_names(self):
        ring = Ring(["r1", "r2", "r3", "r4"])
        order = ring.route("somedata")
        assert sorted(order) == ["r1", "r2", "r3", "r4"]
        assert order[0] == ring.home("somedata")

    def test_spread_over_keys(self):
        # Not a distribution test, just non-degeneracy: with vnodes, many
        # keys must not all land on one replica.
        ring = Ring(["r1", "r2", "r3"])
        homes = {ring.home(f"key{i}") for i in range(64)}
        assert len(homes) == 3

    def test_alive_filter_preserves_order_and_falls_back(self):
        ring = Ring(["r1", "r2", "r3"])
        order = ring.route("data")
        # Dropping the home promotes the next name, preserving order.
        alive = [n for n in order if n != order[0]]
        assert ring.route("data", alive=alive) == order[1:]
        # An empty liveness view falls back to the unfiltered order.
        assert ring.route("data", alive=[]) == order

    def test_single_replica_ring(self):
        ring = Ring(["solo"])
        assert ring.home("anything") == "solo"
        assert ring.route("anything") == ["solo"]

    def test_stability_under_membership_change(self):
        # Consistent hashing's point: removing one replica only moves the
        # keys that replica owned.
        big = Ring(["r1", "r2", "r3", "r4"])
        small = Ring(["r1", "r2", "r3"])
        moved = 0
        for i in range(200):
            key = f"key{i}"
            if big.home(key) != "r4" and small.home(key) != big.home(key):
                moved += 1
        assert moved == 0


# -------------------------------------------------------------------- gossip


class TestGossipCodec:
    def test_roundtrip_through_frame_assembler(self):
        spans = [(f"data{i}", i * 100, i * 100 + 99, 12345 + i, i * 100 + 7)
                 for i in range(50)]
        frames = encode_gossip("r1", 3, spans, full=True)
        asm = FrameAssembler()
        objs = [asm.feed(f) for f in frames]
        done, obj = objs[-1]
        assert done and obj is not None
        msg = decode_gossip(obj)
        assert msg is not None
        assert msg["from"] == "r1" and msg["full"] is True
        assert [tuple(s) for s in msg["spans"]] == spans

    def test_every_datagram_under_wire_ceiling(self):
        from bitcoin_miner_tpu.lsp.message import Message as LspMessage

        # A big full sync: hundreds of spans with long data keys.
        spans = [
            (f"some-rather-long-data-key-{i:04d}", i * 1000,
             i * 1000 + 999, (i * 2654435761) % (1 << 64), i * 1000 + 13)
            for i in range(400)
        ]
        frames = encode_gossip("replica-with-a-name", 9, spans, full=True)
        assert len(frames) > 1  # actually fragmented
        for i, f in enumerate(frames):
            wire = LspMessage.data(999999, 999999, len(f), f).marshal()
            assert len(wire) <= lsp.MAX_MESSAGE_SIZE, (i, len(wire))

    def test_decode_rejects_alien_payloads(self):
        assert decode_gossip(None) is None
        assert decode_gossip({"v": 2, "kind": "spans"}) is None
        assert decode_gossip({"v": 1, "kind": "other", "from": "x"}) is None
        assert decode_gossip(
            {"v": 1, "kind": "spans", "from": "x", "spans": "nope"}
        ) is None

    def test_apply_skips_bad_rows(self):
        store = GossipSpanStore()
        msg = {
            "v": 1, "kind": "spans", "from": "r2",
            "spans": [
                ["good", 0, 99, 5, 7],
                ["short", 1],
                ["bad-types", "0", 99, 5, 7],
                ["good2", 100, 199, 4, 150],
            ],
        }
        assert apply_gossip(store, msg) == 2
        assert store.cover("good", 0, 99)[1] == []


class TestGossipStore:
    def test_local_adds_journal_remote_adds_do_not(self):
        store = GossipSpanStore()
        store.add("a", 0, 99, 50, 10)
        store.add_remote("b", 0, 99, 60, 20)
        drained = store.drain_journal()
        assert drained == [("a", 0, 99, 50, 10)]
        assert store.drain_journal() == []  # drain is destructive
        # Both landed in the store itself.
        assert store.cover("a", 0, 99)[1] == []
        assert store.cover("b", 0, 99)[1] == []

    def test_refused_spans_do_not_journal(self):
        store = GossipSpanStore()
        store.add("a", 99, 0, 5, 7)  # empty
        store.add("a", 0, 99, 5, 500)  # argmin outside
        assert store.drain_journal() == []

    def test_journal_bounded(self):
        store = GossipSpanStore(journal_max=4)
        for i in range(10):
            store.add(f"d{i}", 0, 9, 5, 3)
        assert len(store.drain_journal()) == 4

    def test_export_spans_is_full_state(self):
        store = GossipSpanStore()
        store.add("a", 0, 99, 50, 10)
        store.add_remote("b", 200, 299, 40, 250)
        exported = sorted(store.export_spans())
        assert exported == [("a", 0, 99, 50, 10), ("b", 200, 299, 40, 250)]


# -------------------------------------------- membership plane (ISSUE 12)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


class TestMembership:
    """The suspicion-based failure detector, pure-unit on a fake clock."""

    def _m(self, **kw):
        clk = FakeClock()
        m = Membership(
            "r0", ["r1", "r2"], interval=1.0,
            suspect_misses=3, confirm_misses=3, clock=clk, **kw,
        )
        return m, clk

    def test_silence_suspects_then_confirms_dead(self):
        METRICS.reset()
        m, clk = self._m()
        m.heard("r1", "OK", 7)
        clk.t = 2.0
        m.tick()
        assert m.liveness("r1") == ALIVE  # inside the suspect window
        clk.t = 3.5
        m.tick()
        assert m.liveness("r1") == SUSPECT  # miss-count tripped
        assert METRICS.get("fed.suspected") >= 1
        clk.t = 5.0
        m.heard("r2", "OK", 1)  # r2 keeps beating: only r1 is silent
        m.tick()
        assert m.liveness("r1") == SUSPECT  # confirmation window holds
        clk.t = 7.0
        m.heard("r2", "OK", 1)
        m.tick()
        assert m.liveness("r1") == DEAD  # confirmed
        assert "r1" not in m.routable()
        assert "r2" in m.routable() and "r0" in m.routable()

    def test_suspect_that_beats_again_is_a_false_suspicion(self):
        METRICS.reset()
        m, clk = self._m()
        m.heard("r1", "OK", 7)
        clk.t = 4.0
        m.tick()
        assert m.liveness("r1") == SUSPECT
        m.heard("r1", "OK", 7)
        assert m.liveness("r1") == ALIVE
        assert METRICS.get("fed.false_suspicions") == 1

    def test_shedding_peer_is_deprioritized_never_dead(self):
        METRICS.reset()
        m, clk = self._m()
        m.heard("r1", LOAD_SHEDDING, 1)
        m.heard("r2", "OK", 1)
        # A SHEDDING peer keeps beating: never suspected however long.
        for t in (1.0, 2.0, 3.0, 4.0):
            clk.t = t
            m.heard("r1", LOAD_SHEDDING, 1)
            m.heard("r2", "OK", 1)
            m.tick()
        assert m.liveness("r1") == ALIVE
        assert METRICS.get("fed.false_suspicions") == 0
        # Ring order r1-first gets re-ranked: OK peer ahead of SHEDDING.
        assert m.order(["r1", "r2"]) == ["r2", "r1"]

    def test_draining_peer_gets_no_new_forwards(self):
        m, clk = self._m()
        m.heard("r1", LOAD_DRAINING, 1)
        m.heard("r2", "OK", 1)
        assert m.order(["r1", "r2"]) == ["r2"]
        assert m.liveness("r1") == ALIVE  # draining is alive, just closed

    def test_restart_detection_via_incarnation(self):
        m, clk = self._m()
        assert m.heard("r1", "OK", 100) is False  # first contact
        assert m.heard("r1", "OK", 100) is False  # same life
        assert m.heard("r1", "OK", 101) is True  # restarted
        assert m.heard("r1", "OK", 100) is False  # stale heartbeat: no reset

    def test_fresh_requires_a_recent_heartbeat(self):
        m, clk = self._m()
        assert not m.fresh("r1")  # never heard: grace is not proof
        m.heard("r1", "OK", 1)
        assert m.fresh("r1")
        clk.t = 10.0
        assert not m.fresh("r1")  # silent too long


class TestGossipAcks:
    """Per-peer acked-delta retention (ISSUE 12), store-level."""

    def test_pending_retained_until_acked(self):
        store = GossipSpanStore()
        store.add("a", 0, 99, 50, 10)
        store.add("b", 0, 99, 60, 20)
        p1 = store.pending_for("peer")
        assert [s for _, s in p1] == [
            ("a", 0, 99, 50, 10), ("b", 0, 99, 60, 20),
        ]
        # Unacked: a second beat resends the SAME entries.
        assert store.pending_for("peer") == p1
        store.record_ack("peer", p1[0][0])  # first entry acked
        assert [s for _, s in store.pending_for("peer")] == [
            ("b", 0, 99, 60, 20),
        ]
        store.record_ack("peer", p1[1][0])
        assert store.pending_for("peer") == []

    def test_ack_floor_prunes_only_when_every_peer_acked(self):
        store = GossipSpanStore()
        store.set_peers(["p1", "p2"])
        store.add("a", 0, 99, 50, 10)
        seq = store.jseq()
        store.record_ack("p1", seq)
        # p1's ack must NOT prune what p2 (which never acked) is owed.
        assert store.pending_for("p2") != []
        assert len(store._journal) == 1
        store.record_ack("p2", seq)
        assert store.pending_for("p1") == [] and store.pending_for("p2") == []
        assert len(store._journal) == 0  # everyone acked: pruned

    def test_journal_overflow_escalates_lagging_peer_to_full_sync(self):
        store = GossipSpanStore(journal_max=4)
        for i in range(10):
            store.add(f"d{i}", 0, 9, 5, 3)
        # A peer that acked nothing can no longer be served by deltas.
        assert store.needs_full("laggard")
        # A peer past the dropped high-water can.
        store.record_ack("fresh", store.jseq())
        assert not store.needs_full("fresh")

    def test_restart_reset_voids_acks_and_seen(self):
        store = GossipSpanStore()
        store.add("a", 0, 99, 50, 10)
        store.record_ack("peer", store.jseq())
        store.record_seen("peer", 17)
        store.reset_peer("peer")
        assert store.seen_seq("peer") == 0
        assert store.pending_for("peer") != []  # retained entries resend

    def test_beat_counts_retransmits_and_standalone_heartbeats(self, monkeypatch):
        """Daemon-level unit: a delta sent once and unacked past the ack
        grace window (one reverse-beat round trip) is resent and counted
        as a retransmit; inside the window it is NOT (ordinary ack
        latency must not read as loss); a beat with nothing to ship
        still sends (the standalone heartbeat)."""
        METRICS.reset()
        store = GossipSpanStore()
        sent = []
        gossip = SpanGossip(
            "a", store, {"b": ("127.0.0.1", 1)}, threading.Lock(),
            full_every=10**9, hb_fn=lambda: {"inc": 1, "load": "OK"},
        )
        monkeypatch.setattr(gossip, "_send", lambda name, frames: (
            sent.append((name, list(frames))) or True
        ))
        gossip.beat()  # nothing journaled: heartbeat-only beat still sent
        assert len(sent) == 1
        store.add("a", 0, 99, 50, 10)
        gossip.beat()  # first send of the delta: not a retransmit
        assert METRICS.get("gossip.retransmits") == 0
        gossip.beat()  # inside the grace window: no resend, no count
        assert METRICS.get("gossip.retransmits") == 0
        gossip.beat()  # grace expired, still unacked -> retransmit
        assert METRICS.get("gossip.retransmits") == 1
        store.record_ack("b", store.jseq())
        gossip.beat()  # acked: nothing pending, heartbeat-only again
        assert METRICS.get("gossip.retransmits") == 1
        assert METRICS.get("federation.gossip_full_syncs") == 0
        assert len(sent) == 5

    def test_conn_death_resends_in_flight_tail_on_fresh_conn(self, monkeypatch):
        """The cumulative high-water ack is only sound over contiguous
        in-order delivery: when a send fails (conn died, in-flight tail
        lost), the next beat must resend EVERYTHING unacked immediately
        — no grace — or a later fresh-only delta would ack over the
        hole."""
        METRICS.reset()
        store = GossipSpanStore()
        ok = {"v": True}
        shipped = []
        gossip = SpanGossip(
            "a", store, {"b": ("127.0.0.1", 1)}, threading.Lock(),
            full_every=10**9,
        )

        def fake_send(name, frames):
            if ok["v"]:
                shipped.append(list(frames))
            return ok["v"]

        monkeypatch.setattr(gossip, "_send", fake_send)
        store.add("a", 0, 99, 50, 10)
        gossip.beat()  # delta on the wire (conn 1)
        ok["v"] = False
        store.add("b", 0, 99, 60, 20)
        gossip.beat()  # conn died mid-flight: send fails
        ok["v"] = True
        n0 = len(shipped)
        gossip.beat()  # fresh conn: BOTH unacked entries resent at once
        assert len(shipped) == n0 + 1
        asm = FrameAssembler()
        done, obj = [asm.feed(f) for f in shipped[-1]][-1]
        assert done
        msg = decode_gossip(obj)
        datas = {row[0] for row in msg["spans"]}
        assert datas == {"a", "b"}
        assert METRICS.get("gossip.retransmits") >= 1  # "a" went out before

    def test_stop_voids_send_windows_so_drain_flush_resends(self, monkeypatch):
        """Regression: stop() closes the conns (in-flight tails lost), so
        the drain path's final beat must resend every unacked entry —
        the ack grace window must not filter away spans shipped just
        before the stop, or the promised drain flush ships a heartbeat
        and nothing else."""
        store = GossipSpanStore()
        shipped = []
        gossip = SpanGossip(
            "a", store, {"b": ("127.0.0.1", 1)}, threading.Lock(),
            full_every=10**9,
        )
        monkeypatch.setattr(gossip, "_send", lambda name, frames: (
            shipped.append(list(frames)) or True
        ))
        store.add("a", 0, 99, 50, 10)
        gossip.beat()  # shipped once, unacked, grace window armed
        gossip.stop()  # drain: conns (and any in-flight tail) are gone
        gossip.beat()  # the drain flush
        asm = FrameAssembler()
        done, obj = [asm.feed(f) for f in shipped[-1]][-1]
        assert done
        msg = decode_gossip(obj)
        assert [tuple(s) for s in msg["spans"]] == [("a", 0, 99, 50, 10)]

    def test_handoff_codec_roundtrip(self):
        state = {"version": 1, "workload": "sha256d",
                 "jobs": [{"data": "x", "lower": 0, "upper": 99,
                           "best": [5, 7], "remaining": [[10, 99]]}]}
        frames = encode_handoff("r1", 3, state)
        asm = FrameAssembler()
        done, obj = [asm.feed(f) for f in frames][-1]
        assert done
        msg = decode_fed(obj)
        assert msg is not None and msg["kind"] == "handoff"
        assert msg["from"] == "r1" and msg["state"] == state
        # decode_gossip (the spans-only gate) refuses a handoff.
        assert decode_gossip(obj) is None


class TestOrphanHandoff:
    """Scheduler.export_orphans / import_orphans (ISSUE 12)."""

    def test_roundtrip_resumes_stashed_progress(self):
        a = Scheduler(min_chunk=100, max_chunk=100, validate_results=False)
        a.miner_joined(1)
        a.client_request(10, "hand", 0, 999)
        a.result(1, hash_=500, nonce=50)  # one chunk done
        a.lost(10)  # client died: progress stashed
        b = Scheduler(min_chunk=10**6)
        assert b.import_orphans(a.export_orphans()) >= 1
        b.miner_joined(2)
        acts = b.client_request(11, "hand", 0, 999)
        # The resumed job sweeps only the remaining 900 nonces.
        reqs = [m for _, m in acts if m.type.name == "REQUEST"]
        assert reqs and reqs[0].lower == 100 and reqs[0].upper == 999

    def test_import_validates_rows_and_refuses_foreign_workload(self):
        b = Scheduler()
        good = {"version": 1, "workload": "sha256d", "jobs": [
            {"data": "ok", "lower": 0, "upper": 9, "best": [5, 3],
             "remaining": [[4, 9]]},
            {"data": 123, "lower": 0, "upper": 9, "best": None,
             "remaining": [[0, 9]]},  # bad data type: skipped
            {"data": "bad-best", "lower": 0, "upper": 9, "best": [1],
             "remaining": [[0, 9]]},  # malformed best: skipped
        ]}
        assert b.import_orphans(good) == 1
        foreign = {"version": 2, "workload": "blake2b64",
                   "state": {"jobs": [{"data": "x", "lower": 0, "upper": 9,
                                       "best": [1, 2], "remaining": []}]}}
        assert b.import_orphans(foreign) == 0  # another hash family: refused

    def test_import_respects_orphan_bound(self):
        b = Scheduler(orphan_cache_max=2)
        state = {"version": 1, "workload": "sha256d", "jobs": [
            {"data": f"k{i}", "lower": 0, "upper": 9, "best": [i, 0],
             "remaining": [[0, 9]]}
            for i in range(5)
        ]}
        assert b.import_orphans(state) == 5
        assert len(b._resume) == 2  # bounded, oldest evicted


class TestRingSuccessor:
    def test_deterministic_and_distinct(self):
        ring = Ring(["r0", "r1", "r2", "r3"])
        for name in ring.names:
            succ = ring.successor(name)
            assert succ is not None and succ != name
            assert succ == Ring(["r3", "r2", "r1", "r0"]).successor(name)

    def test_alive_filter_and_degenerate_ring(self):
        ring = Ring(["r0", "r1", "r2"])
        succ = ring.successor("r0")
        alive = [n for n in ring.names if n != succ and n != "r0"]
        assert ring.successor("r0", alive=alive) == alive[0]
        assert Ring(["solo"]).successor("solo") is None
        assert ring.successor("r0", alive=[]) is None


# ---------------------------------------- resilience drills (ISSUE 12 e2e)


def test_shed_vs_death_discrimination_drill():
    """The ISSUE 12 shed-vs-death acceptance: a peer forced into
    SHEDDING via admission flood stays routable and is never suspected
    or marked down (fed.false_suspicions == 0).  The seeded storm also
    gates the ISSUE 13 flap-damping satellite: the peer-side load view
    must not oscillate OK<->SHEDDING across gossip rounds."""
    METRICS.reset()
    report = fed_drill.drill_shed_storm(seed=1)
    assert report["ok"], report
    assert report["false_suspicions"] == 0
    assert report["liveness_during_storm"] == ALIVE
    assert not report["marked_down"] and report["still_routable"]
    assert report["shed_flaps"] <= 1, report


def test_shedding_hysteresis_holds_across_quiet_beats():
    """Flap damping (ISSUE 13 satellite): a single shed makes the NEXT
    beat SHEDDING, the state holds for shed_hold_beats evidence-free
    beats, then reverts to OK — and fresh evidence re-arms the hold.
    Without the hold, a storm shedding on alternate beat pairs flips the
    fed.peer_state gauge every gossip round."""
    METRICS.reset()
    rep = Replica(
        "hold",
        {},
        params=PARAMS,
        scheduler=Scheduler(min_chunk=500),
        gossip_interval=5.0,
        shed_hold_beats=2,
    )  # never start()ed: load_state is pure state-machine + gateway reads
    try:
        assert rep.load_state() == "OK"
        rep.gateway.shed_count += 1  # one shed lands between beats
        assert rep.load_state() == "SHEDDING"  # evidence beat
        assert rep.load_state() == "SHEDDING"  # held (quiet beat 1)
        assert rep.load_state() == "SHEDDING"  # held (quiet beat 2)
        assert rep.load_state() == "OK"  # hysteresis satisfied
        assert METRICS.get("fed.shed_holds") == 2
        # Fresh evidence mid-hold re-arms the full window.
        rep.gateway.shed_count += 1
        assert rep.load_state() == "SHEDDING"
        rep.gateway.shed_count += 1
        assert rep.load_state() == "SHEDDING"  # evidence again, not a hold
        assert rep.load_state() == "SHEDDING"
        assert rep.load_state() == "SHEDDING"
        assert rep.load_state() == "OK"
        # shed_hold_beats=0 restores the point-in-time behavior.
        rep2 = Replica(
            "nohold",
            {},
            params=PARAMS,
            scheduler=Scheduler(min_chunk=500),
            gossip_interval=5.0,
            shed_hold_beats=0,
        )
        try:
            rep2.gateway.shed_count += 1
            assert rep2.load_state() == "SHEDDING"
            assert rep2.load_state() == "OK"
        finally:
            rep2.close()
    finally:
        rep.close()


def test_death_detected_by_heartbeats_within_confirmation_window():
    """A SIGKILL-shaped death is suspected then declared dead by missed
    heartbeats alone — zero forward-path connect timeouts spent."""
    METRICS.reset()
    report = fed_drill.drill_death_detect(seed=1)
    assert report["ok"], report
    assert report["suspected"] >= 1 and report["declared_dead"]
    assert report["forward_timeouts"] == 0
    assert report["forward_failovers"] == 0


def test_ack_gap_retransmit_converges_without_full_sync():
    """Lost deltas recovered by ack-gap retransmit with anti-entropy
    disabled (full_every=10**9) — the full sync can no longer mask a
    broken delta path."""
    METRICS.reset()
    report = fed_drill.drill_ack_retransmit(seed=1)
    assert report["ok"], report
    assert report["retransmits"] >= 1 and report["full_syncs"] == 0


def test_drain_handoff_successor_resumes_from_stash():
    """The ISSUE 12 drain acceptance: a cell drained mid-sweep hands its
    stash to the ring successor; the resubmitted job answers bit-exact
    with STRICTLY fewer nonces swept than a from-scratch control."""
    METRICS.reset()
    report = fed_drill.drill_drain_handoff(seed=1)
    assert report["ok"], report
    assert report["bit_exact"] and report["handoff_jobs"] >= 1
    assert report["resumed_nonces_swept"] < report["scratch_nonces_swept"]


# -------------------------------------------------------------- replica e2e


class FedFleet:
    """An in-process federation: N replicas, each with its own miners."""

    def __init__(self, n=2, miners=1, min_chunk=500, gossip_interval=0.15,
                 **replica_kwargs):
        names = [f"r{i}" for i in range(n)]
        fed_ports = {nm: random_port() + i for i, nm in enumerate(names)}
        self.replicas = {}
        for nm in names:
            peers = {o: ("127.0.0.1", fed_ports[o]) for o in names if o != nm}
            self.replicas[nm] = Replica(
                nm,
                peers,
                fed_port=fed_ports[nm],
                params=PARAMS,
                scheduler=Scheduler(min_chunk=min_chunk),
                gossip_interval=gossip_interval,
                tick_interval=0.05,
                **replica_kwargs,
            ).start()
        self.miners = []
        for nm in names:
            for _ in range(miners):
                self.add_miner(nm)

    def add_miner(self, name):
        c = lsp.Client("127.0.0.1", self.replicas[name].port, PARAMS,
                       label=f"miner-{name}")
        threading.Thread(
            target=miner_mod.run_miner,
            args=(c, miner_mod.make_search("cpu")),
            daemon=True,
        ).start()
        self.miners.append(c)
        return c

    def request_at(self, name, data, max_nonce, lower=0):
        c = lsp.Client("127.0.0.1", self.replicas[name].port, PARAMS)
        try:
            return client_mod.request_once(c, data, max_nonce, lower=lower)
        finally:
            c.close()

    def request_at_fed_port(self, name, data, max_nonce, lower=0):
        """The local-serve path: federation-port requests never forward,
        so the answer provably comes from this replica's own state."""
        c = lsp.Client("127.0.0.1", self.replicas[name].fed_port, PARAMS)
        try:
            return client_mod.request_once(c, data, max_nonce, lower=lower)
        finally:
            c.close()

    def ring(self):
        return Ring(list(self.replicas))

    def home_and_other(self, data):
        home = self.ring().home(data)
        other = next(nm for nm in self.replicas if nm != home)
        return home, other

    def close(self):
        for rep in self.replicas.values():
            rep.close()


def _wait(pred, timeout=10.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


def test_forwarded_request_answers_bit_exact():
    """A request arriving at a NON-home replica forwards to the home and
    the client still gets the oracle answer; the home's cache makes a
    repeat at the forwarding replica zero-chunk."""
    METRICS.reset()
    fleet = FedFleet(n=2)
    try:
        data, hi = "fedalpha", 3000
        home, other = fleet.home_and_other(data)
        want = min_hash_range(data, 0, hi)
        assert fleet.request_at(other, data, hi) == want
        assert METRICS.get("federation.forwarded") >= 1
        assert METRICS.get("federation.remote_results") >= 1
        # Repeat at the SAME non-home replica: its forward-populated exact
        # cache answers locally with zero new chunks — and WITHOUT another
        # round trip to the home cell.
        assigned = METRICS.get("sched.chunks_assigned")
        forwarded = METRICS.get("federation.forwarded")
        assert fleet.request_at(other, data, hi) == want
        assert METRICS.get("sched.chunks_assigned") == assigned
        assert METRICS.get("federation.forwarded") == forwarded
        assert METRICS.get("federation.local_answers") >= 1
        # And at the home replica: the home solved it, cache hit there too.
        assert fleet.request_at(home, data, hi) == want
        assert METRICS.get("sched.chunks_assigned") == assigned
    finally:
        fleet.close()


def test_async_public_ingress_keeps_federation_contracts():
    """ISSUE 15: replicas serving their PUBLIC port on the asyncio
    event-loop front end (``async_public=True`` → apps.server.AsyncIngress
    under the replica's own event lock) keep the federation contracts —
    forwarded requests answer bit-exact, and a repeat at the forwarding
    replica answers zero-chunk from the forward-populated cache, with the
    forwarder pool's Results delivered through the ingress bridge's
    cross-thread write path."""
    METRICS.reset()
    fleet = FedFleet(n=2, async_public=True)
    try:
        data, hi = "fedasync", 3000
        home, other = fleet.home_and_other(data)
        want = min_hash_range(data, 0, hi)
        assert fleet.request_at(other, data, hi) == want
        assert METRICS.get("federation.forwarded") >= 1
        assert METRICS.get("federation.remote_results") >= 1
        assigned = METRICS.get("sched.chunks_assigned")
        assert fleet.request_at(other, data, hi) == want
        assert fleet.request_at(home, data, hi) == want
        assert METRICS.get("sched.chunks_assigned") == assigned
    finally:
        fleet.close()


def test_duplicates_collapse_across_replicas():
    """Concurrent twins sprayed at BOTH replicas coalesce into one sweep
    on the home cell — the consistent-hash-routing acceptance shape."""
    METRICS.reset()
    fleet = FedFleet(n=2)
    try:
        data, hi = "fedcoal", 4000
        want = min_hash_range(data, 0, hi)
        out = {}

        def one(i, name):
            out[i] = fleet.request_at(name, data, hi)

        names = list(fleet.replicas) * 3
        threads = [
            threading.Thread(target=one, args=(i, nm))
            for i, nm in enumerate(names)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
            assert not t.is_alive(), "client starved"
        assert all(v == want for v in out.values()), out
        # One underlying sweep signature: every completion beyond the
        # first came from coalescing/cache, not a second sweep.
        assert METRICS.get("gateway.completed") <= 2
    finally:
        fleet.close()


def test_gossip_spans_answer_on_other_replica_zero_chunks():
    """The cross-replica span-reuse acceptance (ISSUE 8): replica A
    solves a range; after gossip, a covered sub-range queried at replica
    B's federation port (local serve — no forwarding) answers bit-exact
    with ZERO chunks assigned anywhere."""
    METRICS.reset()
    fleet = FedFleet(n=2)
    try:
        data, hi = "fedgossip", 5000
        home, other = fleet.home_and_other(data)
        want = min_hash_range(data, 0, hi)
        assert fleet.request_at(home, data, hi) == want
        rep_b = fleet.replicas[other]

        def covered():
            with rep_b.lock:
                best, gaps = rep_b.spans.cover(data, want[1], hi)
                return best is not None and not gaps

        assert _wait(covered, timeout=10.0), "gossip never converged"
        assigned = METRICS.get("sched.chunks_assigned")
        got = fleet.request_at_fed_port(other, data, hi, lower=want[1])
        assert got == min_hash_range(data, want[1], hi)
        assert METRICS.get("sched.chunks_assigned") == assigned
        # All gossip datagrams respected the frozen wire ceiling.
        for rep in fleet.replicas.values():
            assert rep.gossip.max_frame_bytes <= 700
        assert METRICS.get("federation.gossip_spans_merged") >= 1
    finally:
        fleet.close()


def test_cell_kill_mid_sweep_survivors_serve_whole_range():
    """The ISSUE 8 chaos drill, cell-kill half: kill a scheduler cell
    mid-sweep; the client resubmits through a surviving replica and still
    receives a whole-range-correct, oracle-bit-exact Result."""
    METRICS.reset()
    fleet = FedFleet(n=2, min_chunk=200)
    try:
        # Find a data key homed on r1 so we can kill r1 mid-sweep.
        data = next(
            f"kill{i}" for i in range(64)
            if fleet.ring().home(f"kill{i}") == "r1"
        )
        hi = 60_000
        want = min_hash_range(data, 0, hi)
        victim = fleet.replicas["r1"]
        box = {}

        def client_with_resubmit():
            # First attempt at the home replica dies with it; the retry
            # goes through the SURVIVOR's public port (the load-balancer
            # failover a real client implements).
            got = fleet.request_at("r1", data, hi)
            if got is None:
                got = fleet.request_at("r0", data, hi)
            box["got"] = got

        t = threading.Thread(target=client_with_resubmit, daemon=True)
        t.start()
        # Let the sweep start, then kill the whole cell mid-sweep.
        assert _wait(
            lambda: METRICS.get("sched.chunks_assigned") > 0, timeout=30.0
        )
        victim.close()
        t.join(timeout=120)
        assert not t.is_alive(), "client starved after cell kill"
        assert box["got"] == want
    finally:
        fleet.close()


def test_forward_fails_over_to_ring_successor_when_home_dead():
    """With the home cell dead, a request at a surviving replica is NOT
    forwarded into the void: the forwarder fails over along the ring
    (here: back to the survivor itself via local fallback) and the
    client still gets the oracle answer."""
    METRICS.reset()
    fleet = FedFleet(n=2)
    try:
        data = next(
            f"dead{i}" for i in range(64)
            if fleet.ring().home(f"dead{i}") == "r1"
        )
        hi = 2500
        fleet.replicas["r1"].close()  # the home cell is gone
        want = min_hash_range(data, 0, hi)
        got = fleet.request_at("r0", data, hi)
        assert got == want
        # The forward either failed over and fell back locally (counted),
        # or r0 served it after marking the peer down.
        assert (
            METRICS.get("federation.local_fallbacks") >= 1
            or METRICS.get("federation.forward_failovers") >= 1
        )
    finally:
        fleet.close()


def test_gossip_partition_stale_then_heals_and_converges():
    """The ISSUE 8 chaos drill, gossip-partition half: partition one
    replica's gossip channel; a range solved on the other replica stays
    unknown to it (stale) while the partition holds — requests still
    answer correctly via forwarding — then the partition lifts and the
    stale replica converges (full-sync anti-entropy), after which a
    covered sub-range answers locally with zero chunks."""
    METRICS.reset()
    fleet = FedFleet(n=2, gossip_interval=0.15)
    try:
        data, hi = "fedpart", 5000
        home, other = fleet.home_and_other(data)
        rep_b = fleet.replicas[other]
        # Cut the HOME replica's gossip tx (its label: gossip-<home>).
        CHAOS.partition(f"gossip-{home}", "both")
        want = min_hash_range(data, 0, hi)
        assert fleet.request_at(home, data, hi) == want

        def b_has_spans():
            with rep_b.lock:
                return len(rep_b.spans._maps.get(data, ())) > 0

        # Stale while partitioned: give gossip several beats to (not)
        # arrive.  Requests still answer bit-exact meanwhile (forwarding
        # is a different link).
        time.sleep(1.0)
        assert not b_has_spans(), "partitioned gossip still delivered"
        assert fleet.request_at(other, data, hi) == want  # via forward
        # Heal: the next full sync must converge the stale replica.
        CHAOS.heal(f"gossip-{home}")
        assert _wait(b_has_spans, timeout=10.0), "no convergence after heal"

        def covered():
            with rep_b.lock:
                best, gaps = rep_b.spans.cover(data, want[1], hi)
                return best is not None and not gaps

        assert _wait(covered, timeout=10.0)
        assigned = METRICS.get("sched.chunks_assigned")
        got = fleet.request_at_fed_port(other, data, hi, lower=want[1])
        assert got == min_hash_range(data, want[1], hi)
        assert METRICS.get("sched.chunks_assigned") == assigned
    finally:
        fleet.close()


def test_local_fallback_when_all_peers_unreachable():
    """A replica whose every peer is gone serves non-home data itself:
    correct everywhere beats routed nowhere."""
    METRICS.reset()
    # A one-replica "federation" with a configured-but-never-started peer.
    dead_port = random_port() + 177
    rep = Replica(
        "solo",
        {"ghost": ("127.0.0.1", dead_port)},
        params=PARAMS,
        scheduler=Scheduler(min_chunk=500),
        gossip_interval=5.0,
        tick_interval=0.05,
        peer_down_ttl=0.1,
    ).start()
    mc = lsp.Client("127.0.0.1", rep.port, PARAMS)
    threading.Thread(
        target=miner_mod.run_miner,
        args=(mc, miner_mod.make_search("cpu")),
        daemon=True,
    ).start()
    try:
        data = next(
            f"fb{i}" for i in range(64)
            if Ring(["solo", "ghost"]).home(f"fb{i}") == "ghost"
        )
        want = min_hash_range(data, 0, 2000)
        c = lsp.Client("127.0.0.1", rep.port, PARAMS)
        try:
            got = client_mod.request_once(c, data, 2000)
        finally:
            c.close()
        assert got == want
        assert METRICS.get("federation.local_fallbacks") >= 1
    finally:
        rep.close()


@pytest.mark.analysis
def test_federation_green_under_race_sanitizer(monkeypatch):
    """The shared-event-lock discipline across serve loop, federation
    ingest, forwarders and gossip, under the runtime race sanitizer."""
    from bitcoin_miner_tpu.utils import sanitize

    monkeypatch.setenv("BMT_SANITIZE", "1")
    assert sanitize.enabled()
    METRICS.reset()
    fleet = FedFleet(n=2)
    try:
        out = {}
        sigs = [("sanfed-a", 2000), ("sanfed-b", 2500)]
        want = {d: min_hash_range(d, 0, mx) for d, mx in sigs}

        def one(i):
            d, mx = sigs[i % 2]
            nm = list(fleet.replicas)[i % 2]
            out[i] = (d, fleet.request_at(nm, d, mx))

        threads = [threading.Thread(target=one, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
            assert not t.is_alive(), "client starved under sanitizer"
        for i, (d, got) in out.items():
            assert got == want[d], f"client {i}"
    finally:
        fleet.close()


# ------------------------------------------------- fed-port local semantics


def test_fed_port_never_forwards():
    """Loop-freedom's foundation: a request at the federation port is
    served locally even when the data's home is another replica."""
    METRICS.reset()
    fleet = FedFleet(n=2)
    try:
        data = next(
            f"loop{i}" for i in range(64)
            if fleet.ring().home(f"loop{i}") == "r1"
        )
        want = min_hash_range(data, 0, 1500)
        forwarded = METRICS.get("federation.forwarded")
        # Queried at r0's FED port although r1 is home: r0 must sweep it
        # itself, not forward.
        got = fleet.request_at_fed_port("r0", data, 1500)
        assert got == want
        assert METRICS.get("federation.forwarded") == forwarded
    finally:
        fleet.close()


# ------------------------------------- per-forward deadlines (ISSUE 9 sat.)


def test_read_timeout_raises_and_request_once_deadline():
    """The transport half of the per-forward deadline: a conn whose peer
    is alive but never answers raises the builtin TimeoutError from
    request_once(timeout=) instead of blocking its caller forever."""
    server = lsp.Server(0, PARAMS)
    try:
        c = lsp.Client("127.0.0.1", server.port, PARAMS)
        t0 = time.monotonic()
        with pytest.raises(TimeoutError):
            # The server accepts and keeps the conn alive (epochs) but no
            # application ever writes a Result.
            client_mod.request_once(c, "noanswer", 100, timeout=0.4)
        assert time.monotonic() - t0 < 5.0
        c.close()
    finally:
        server.close()


def test_forward_timeout_unwedges_worker_and_falls_back_local():
    """A wedged peer conn — transport alive, scheduler starved (no
    miners) — used to block a forwarder worker in request_once forever,
    head-of-line-blocking all forwarding on the replica.  With the
    per-forward deadline the forward times out, counts
    federation.forward_timeouts, and the request is served locally."""
    METRICS.reset()
    fleet = FedFleet(n=2, miners=0, forward_timeout=1.0, peer_down_ttl=0.1)
    try:
        data = next(
            f"wedge{i}" for i in range(64)
            if fleet.ring().home(f"wedge{i}") == "r1"
        )
        # Only the NON-home replica gets a miner: the home cell (r1) can
        # accept the forwarded request but never answer it.
        fleet.add_miner("r0")
        want = min_hash_range(data, 0, 2000)
        t0 = time.monotonic()
        got = fleet.request_at("r0", data, 2000)
        assert got == want
        assert METRICS.get("federation.forward_timeouts") >= 1
        assert METRICS.get("federation.local_fallbacks") >= 1
        # A wedged-but-alive peer is a timeout, NOT a dead-replica
        # failover — the two counters must not double-report.
        assert METRICS.get("federation.forward_failovers") == 0
        # Bounded by deadline + local sweep, not by a wedged read.
        assert time.monotonic() - t0 < 30.0
    finally:
        fleet.close()


# --------------------------- admission identity across forwards (ISSUE 9)


def test_forward_propagates_originating_admission_identity():
    """Forwarded traffic must not pool under one fed:peer key at the home
    cell: the forwarder sends the originating client key ahead of the
    Request, and the home charges THAT identity's bucket/tenant."""
    METRICS.reset()
    fleet = FedFleet(n=2, rate=1000.0)
    try:
        data = next(
            f"ident{i}" for i in range(64)
            if fleet.ring().home(f"ident{i}") == "r1"
        )
        want = min_hash_range(data, 0, 1500)
        assert fleet.request_at("r0", data, 1500) == want
        home = fleet.replicas["r1"]
        with home.lock:
            keys = set(home.gateway._buckets)
        # serve() binds public admission identity to the LSP peer addr;
        # the forward carried it end-to-end.
        assert "fed:addr:127.0.0.1" in keys, keys
        assert "fed:peer" not in keys
    finally:
        fleet.close()


# ------------------- peer-transport thread scaling (ISSUE 15 -> ISSUE 18)


@pytest.mark.autoscale
def test_fed_plane_threads_flat_as_peers_grow():
    """The ISSUE 18 prerequisite refactor: a cell's fed port, forwarder
    conns AND gossip clients all ride ONE shared loop, so its thread
    count is O(1) in peers.  Per-cell thread cost at mesh sizes 2 and 4
    must be EQUAL — peer growth shows up as CONNS on the shared loop
    (the ``fed_conns`` stat / ``fed.conns_live`` gauge), never as
    threads.  The PR-15 flat-threads proof (test_ingress) pins the
    public side; this pins the peer side."""

    def per_cell(n):
        # Let stragglers from earlier tests/fleets die before baselining
        # — the census settle window is the old wait-for-shrink dance,
        # now spelled via the sanitizer helper (ISSUE 19).
        before = sanitize.thread_census(settle_s=2.0)
        fleet = FedFleet(n=n, miners=0, gossip_interval=0.05)
        try:
            # Every cell's fed server must hold a live conn FROM each
            # peer's gossip client before counting — those conns are
            # exactly what cost a loop thread apiece before the refactor.
            assert _wait(lambda: all(
                rep.fed.conns_live() >= n - 1
                for rep in fleet.replicas.values()
            )), {nm: rep.fed.conns_live() for nm, rep in fleet.replicas.items()}
            conns = sum(r.fed.conns_live() for r in fleet.replicas.values())
            threads = sum(sanitize.thread_census().values()) - sum(
                before.values()
            )
        finally:
            fleet.close()
        assert threads % n == 0, (threads, n)
        return threads // n, conns

    t2, conns2 = per_cell(2)
    t4, conns4 = per_cell(4)
    assert t4 == t2, (t2, t4)
    assert conns4 > conns2  # the growth landed on conns, not threads


@pytest.mark.autoscale
def test_fed_conns_live_gauge_published_by_ticker():
    """The thread-accounting satellite: the serve ticker publishes the
    fed transport's live-conn count as the ``fed.conns_live`` gauge (the
    federation spelling of ``gw.conns_live``), and the replica's stats
    carry ``fed_conns`` so the health line shows it."""
    METRICS.reset()
    fleet = FedFleet(n=2, gossip_interval=0.05)
    try:
        assert _wait(
            lambda: METRICS.gauges().get("fed.conns_live", 0.0) >= 1.0
        ), METRICS.gauges()
        rep = fleet.replicas["r0"]
        with rep.lock:
            st = rep.router.stats()
        assert st["fed_conns"] == rep.fed.conns_live()
    finally:
        fleet.close()
