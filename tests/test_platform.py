"""Probe-based TPU detection (utils/platform.py).

Guards against the round-1 hazard: this environment's TPU plugin registers
as platform 'axon', so a ``jax.default_backend() == "tpu"`` string compare
silently routes real TPU chips onto the CPU tier.
"""

from types import SimpleNamespace

from bitcoin_miner_tpu.utils.platform import device_desc, is_tpu, is_tpu_device


def dev(platform, kind=""):
    return SimpleNamespace(platform=platform, device_kind=kind)


def test_canonical_tpu_platform():
    assert is_tpu_device(dev("tpu", "TPU v5e"))
    assert is_tpu_device(dev("TPU"))


def test_axon_plugin_name_is_tpu():
    assert is_tpu_device(dev("axon", "TPU v5 lite"))
    assert is_tpu_device(dev("axon"))  # even with no device_kind


def test_unknown_plugin_detected_via_device_kind():
    assert is_tpu_device(dev("someplugin", "TPU v6e"))


def test_cpu_and_gpu_are_not_tpu():
    assert not is_tpu_device(dev("cpu", "cpu"))
    assert not is_tpu_device(dev("cuda", "NVIDIA H100"))
    assert not is_tpu_device(dev("cpu", None))


def test_is_tpu_under_forced_cpu_platform():
    # conftest forces the virtual-CPU platform for the whole test process.
    assert is_tpu() is False


def test_device_desc():
    assert device_desc(dev("axon", "TPU v5e")) == "axon:TPU v5e"
    assert device_desc(dev("cpu", None)) == "cpu:?"
