"""Smoke tests for tools/fleet_bench.py — the tool itself, on CPU tiers.

The real artifact runs on the TPU (`benchmarks/fleet_r05*.json`); here the
same harness drives real server+miner subprocesses on loopback with tiny
jobs, so regressions in the tool (job plumbing, class-warm loop, kill
drill arming/validation) fail in CI rather than at bench time.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]


def _run_fleet(args, timeout):
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    return subprocess.run(
        [sys.executable, str(REPO / "tools" / "fleet_bench.py"), *args],
        capture_output=True,
        text=True,
        timeout=timeout,
        env=env,
        cwd=str(REPO),
    )


@pytest.mark.slow
def test_fleet_bench_smoke_cpu():
    # Native C++ tier (~1.9e7 n/s): a 3e7 job finishes in seconds.
    p = _run_fleet(
        ["--backend", "cpu", "--nonces", "30000000", "--warmup", "2000000",
         "--timeout", "120", "--stall", "30"],
        timeout=240,
    )
    assert p.returncode == 0, p.stderr[-2000:]
    out = json.loads(p.stdout.strip().splitlines()[-1])
    assert out["metric"] == "fleet_nonces_per_sec"
    assert out["nonces"] == 30000000
    assert out["value"] > 0
    assert out["miner_restarts"] == 0, p.stderr[-2000:]


@pytest.mark.slow
def test_fleet_bench_kill_drill_cpu():
    # Drill sized so the clean job takes seconds — the SIGKILL provably
    # fires mid-job (the tool raises if the Result beats the kill).
    p = _run_fleet(
        ["--backend", "cpu", "--nonces", "20000000", "--warmup", "2000000",
         "--kill-drill", "--drill-nonces", "60000000",
         "--timeout", "180", "--stall", "30"],
        timeout=360,
    )
    assert p.returncode == 0, p.stderr[-2000:]
    out = json.loads(p.stdout.strip().splitlines()[-1])
    drill = out["kill_drill"]
    assert drill["match"] is True
    assert drill["deliberate_kills"] == 1
    assert out["miner_restarts"] == 0  # deliberate kills counted separately
