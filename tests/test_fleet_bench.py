"""Smoke tests for tools/fleet_bench.py — the tool itself, on CPU tiers.

The real artifact runs on the TPU (`benchmarks/fleet_r05*.json`); here the
same harness drives real server+miner subprocesses on loopback with tiny
jobs, so regressions in the tool (job plumbing, class-warm loop, kill
drill arming/validation) fail in CI rather than at bench time.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]


def _cpu_rate() -> float:
    """Measured nonces/s of the cpu-backend sweep on THIS host (native if
    it builds, else hashlib) — job sizes scale with it so the smoke tests
    neither race a fast CI box nor crawl on a g++-less one."""
    import time

    from bitcoin_miner_tpu.apps.miner import make_search

    sweep = make_search("cpu")
    n = 200_000
    t0 = time.perf_counter()
    sweep("ratecal", 0, n - 1)
    dt = time.perf_counter() - t0
    return max(n / dt, 1e5)


def _run_fleet(args, timeout):
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    return subprocess.run(
        [sys.executable, str(REPO / "tools" / "fleet_bench.py"), *args],
        capture_output=True,
        text=True,
        timeout=timeout,
        env=env,
        cwd=str(REPO),
    )


@pytest.mark.slow
def test_fleet_bench_smoke_cpu():
    # ~2 s of cpu-tier work, whatever this host's rate is.
    nonces = int(_cpu_rate() * 2)
    p = _run_fleet(
        ["--backend", "cpu", "--nonces", str(nonces),
         "--warmup", str(max(nonces // 15, 10**5)),
         "--timeout", "120", "--stall", "30"],
        timeout=240,
    )
    assert p.returncode == 0, p.stderr[-2000:]
    out = json.loads(p.stdout.strip().splitlines()[-1])
    assert out["metric"] == "fleet_nonces_per_sec"
    assert out["nonces"] == nonces
    assert out["value"] > 0
    assert out["miner_restarts"] == 0, p.stderr[-2000:]


@pytest.mark.slow
@pytest.mark.autoscale
def test_fleet_bench_autoscale_leg():
    """The --autoscale leg end-to-end at reduced scale: the tool itself
    raises if any link of the causal chain breaks (alert fires, scale-up
    within discipline, p99 recovers, clean drain to the floor, bit-exact
    answers), so rc 0 + the JSON shape IS the assertion."""
    p = _run_fleet(
        ["--autoscale", "--as-warm-s", "4", "--as-overload-s", "14",
         "--as-deadline", "90"],
        timeout=360,
    )
    assert p.returncode == 0, p.stderr[-2000:]
    out = json.loads(p.stdout.strip().splitlines()[-1])
    assert out["metric"] == "autoscale_p99_speedup"
    assert out["value"] > 1.0
    auto = out["autoscaled"]
    assert auto["alerts_fired"] == ["request-p95"]
    assert auto["scale_ups"] >= 1 and auto["scale_downs"] >= 1
    assert auto["end_live"] == 1
    assert auto["drained_exit_codes"] and all(
        c == 0 for c in auto["drained_exit_codes"]
    )
    assert auto["workers_peak"] > out["fixed"]["workers_peak"]


@pytest.mark.slow
def test_fleet_bench_kill_drill_cpu():
    # Drill sized to ~6 s of clean sweep on this host, so the SIGKILL
    # (kill_at >= 1 s) provably fires mid-job even on a fast CI box —
    # the tool raises if the Result beats the kill.
    rate = _cpu_rate()
    p = _run_fleet(
        ["--backend", "cpu", "--nonces", str(int(rate)),
         "--warmup", str(max(int(rate) // 15, 10**5)),
         "--kill-drill", "--drill-nonces", str(int(rate * 6)),
         "--timeout", "180", "--stall", "30"],
        timeout=360,
    )
    assert p.returncode == 0, p.stderr[-2000:]
    out = json.loads(p.stdout.strip().splitlines()[-1])
    drill = out["kill_drill"]
    assert drill["match"] is True
    assert drill["deliberate_kills"] == 1
    assert out["miner_restarts"] == 0  # deliberate kills counted separately
