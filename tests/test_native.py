"""Native (C++) CPU sweep tier vs the hashlib oracle.

The compiled tier is the framework's analogue of the reference's only
native surface — Go's assembly SHA-256 under ``bitcoin/hash.go`` (SURVEY
§2.4).  Bit-exactness matters most at the incremental-tail edge cases:
digit-count rollover (re-pad), multi-block job data (midstate folding),
and the uint64 ceiling.
"""

import pytest

from bitcoin_miner_tpu import native
from bitcoin_miner_tpu.bitcoin.hash import min_hash_range

pytestmark = pytest.mark.skipif(
    not native.available(), reason="no C++ toolchain to build the native tier"
)


@pytest.mark.parametrize(
    "data,lo,hi",
    [
        ("cmu440", 0, 5000),            # digit rollovers 1->2->3->4
        ("x", 95, 1205),                # partial buckets both ends
        ("", 0, 300),                   # empty job data
        ("q" * 130, 1, 500),            # 3 constant blocks fold to midstate
        ("pad55-" + "z" * 49, 90, 120), # prefix fills a block boundary
        ("big", (1 << 64) - 51, (1 << 64) - 1),  # 20-digit ceiling
        ("solo", 12345, 12345),         # single nonce
    ],
)
def test_matches_oracle(data, lo, hi):
    assert native.min_hash_range_native(data, lo, hi) == min_hash_range(data, lo, hi)


def test_rollover_99999(it=99_990):
    # crosses 5->6 digits mid-sweep: the tail layout is rebuilt in place
    assert native.min_hash_range_native("r", it, 100_010) == min_hash_range(
        "r", it, 100_010
    )


@pytest.mark.parametrize("threads", [1, 2, 3, 8])
def test_multithreaded_matches_single(threads):
    data, lo, hi = "mtsweep", 50, 4321  # crosses digit boundaries
    want = min_hash_range(data, lo, hi)
    assert native.min_hash_range_native(data, lo, hi, threads=threads) == want


def test_multithreaded_more_threads_than_range():
    # span 3 with 8 threads: clamps to one nonce per thread
    data, lo, hi = "tiny", 7, 9
    want = min_hash_range(data, lo, hi)
    assert native.min_hash_range_native(data, lo, hi, threads=8) == want


def test_multithreaded_tie_break_lowest_nonce():
    # A single-nonce "range" duplicated across threads can't tie, so force
    # the reduce path with hardware default threads on a real range and
    # cross-check the scalar path's lowest-nonce answer.
    data, lo, hi = "tie", 0, 2000
    assert native.min_hash_range_native(
        data, lo, hi, threads=0
    ) == native.min_hash_range_native(data, lo, hi, threads=1)


def test_negative_threads_raises():
    with pytest.raises(ValueError):
        native.min_hash_range_native("x", 0, 10, threads=-1)


def test_empty_range_raises():
    with pytest.raises(ValueError):
        native.min_hash_range_native("x", 10, 9)


def test_out_of_u64_raises():
    with pytest.raises(ValueError):
        native.min_hash_range_native("x", 0, 1 << 64)


def test_top_of_u64_range():
    # The two highest nonces: span arithmetic at the ceiling, 20-digit tails,
    # and the multi-threaded clamp must all stay exact (regression for the
    # span==0 wrap one nonce further up).
    data, lo, hi = "ceil", (1 << 64) - 2, (1 << 64) - 1
    want = min_hash_range(data, lo, hi)
    assert native.min_hash_range_native(data, lo, hi) == want
    assert native.min_hash_range_native(data, lo, hi, threads=8) == want


def test_full_u64_range_rejected():
    # [0, 2^64-1] wraps the u64 span to 0 (previously integer divide-by-zero
    # UB returning (0, 0) instantly); the binding now refuses it outright.
    with pytest.raises(ValueError, match="full 2\\^64"):
        native.min_hash_range_native("x", 0, (1 << 64) - 1)


def test_records_compression_path(capsys):
    """Pin down WHICH compression path this host exercised: the plain
    portable loop or the SHA-NI x2 interleave (sha256_sweep.cc) — so a CI
    log shows the intricate path's coverage instead of passing silently."""
    shani = native.have_shani()
    with capsys.disabled():
        print(f"\n[native] compression path: {'SHA-NI x2' if shani else 'portable'}")
    # Either way the sweep must agree with the oracle on an even+odd span
    # (the x2 path pairs nonces; odd remainders fall to the scalar path).
    for lo, hi in [(10, 41), (10, 42)]:
        assert native.min_hash_range_native("path", lo, hi) == min_hash_range(
            "path", lo, hi
        )
