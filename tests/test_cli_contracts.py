"""CLI argv/usage contracts of the three mining binaries.

The reference freezes the argv shapes and error text shapes
(`bitcoin/client/client.go:12-23`, `bitcoin/server/server.go:41-51`,
`bitcoin/miner/miner.go:18-24`); these pin the error paths that the e2e
suites never hit (the happy paths are covered there).
"""

import io

from bitcoin_miner_tpu.apps import client as client_mod
from bitcoin_miner_tpu.apps import miner as miner_mod
from bitcoin_miner_tpu.apps import server as server_mod


class TestClientCLI:
    def test_usage_on_wrong_argc(self):
        out = io.StringIO()
        assert client_mod.main(["client"], out=out) == 0
        assert out.getvalue() == "Usage: ./client <hostport> <message> <maxNonce>"

    def test_non_numeric_max_nonce(self):
        out = io.StringIO()
        client_mod.main(["client", "h:1", "msg", "abc"], out=out)
        assert out.getvalue() == "abc is not a number.\n"

    def test_out_of_u64_max_nonce(self):
        out = io.StringIO()
        client_mod.main(["client", "h:1", "msg", str(1 << 64)], out=out)
        assert out.getvalue() == f"{1 << 64} is not a number.\n"

    def test_connect_failure_reported_not_raised(self):
        out = io.StringIO()
        # Unparseable port: must print a failure line, not traceback.
        assert client_mod.main(["client", "nocolonhere", "m", "5"], out=out) == 0
        assert out.getvalue().startswith("Failed to connect to server:")


class TestServerCLI:
    def test_usage_on_wrong_argc(self, capsys):
        assert server_mod.main(["server"]) == 0
        assert (
            capsys.readouterr().out
            == "Usage: ./server <port> [--checkpoint=FILE]"
        )

    def test_non_numeric_port(self, capsys):
        assert server_mod.main(["server", "notaport"]) == 0
        assert capsys.readouterr().out.startswith("Port must be a number:")

    def test_bad_gateway_flag_reported_not_raised(self, capsys):
        # Gateway admission knobs follow the --checkpoint idiom: a typoed
        # value prints a line (same shape as the client's --retries) and
        # exits cleanly before any socket is bound.
        assert server_mod.main(["server", "6060", "--rate=abc"]) == 0
        assert capsys.readouterr().out == "--rate=abc is not a number.\n"
        assert server_mod.main(["server", "6060", "--max-queued=1.5"]) == 0
        assert capsys.readouterr().out == "--max-queued=1.5 is not a number.\n"


class TestMinerCLI:
    def test_usage_on_missing_hostport(self, capsys):
        assert miner_mod.main(["miner"]) == 0
        assert capsys.readouterr().out == "Usage: ./miner <hostport>"

    def test_invalid_device_count_reported(self, capsys):
        assert miner_mod.main(["miner", "h:1", "--devices", "0"]) == 0
        assert capsys.readouterr().out.startswith("Invalid miner configuration:")

    def test_cpu_backend_with_mesh_rejected(self, capsys):
        assert (
            miner_mod.main(["miner", "h:1", "--backend", "cpu", "--devices", "8"])
            == 0
        )
        assert capsys.readouterr().out.startswith("Invalid miner configuration:")

    def test_multihost_requires_topology_flags(self, capsys):
        assert miner_mod.main(["miner", "h:1", "--multihost"]) == 0
        assert "requires" in capsys.readouterr().out
