"""The pluggable range-fold workload registry (ISSUE 9).

Four layers of coverage:

- **registry semantics** — resolution by name, default-first listing,
  registration invariants (golden vectors mandatory, ladders end at the
  un-wedgeable hashlib tier), and the frozen default staying
  byte-identical to the reference ``bitcoin/hash`` contract;
- **oracle bit-exactness per workload** — every registered workload's
  golden vectors recompute, its cpu tier matches its hashlib oracle
  across digit-class boundaries, and the families are genuinely
  distinct hash functions;
- **tier ladders** — per-workload kernel factories: the
  separator-parameterized SHA-256 template runs the preimage workload
  bit-exact on the real XLA tier, host-only workloads refuse device
  tiers loudly, and the watchdog downgrade drill passes on a
  NON-default workload (the ISSUE 9 acceptance bar);
- **serving stack e2e** — a gateway+interval-store loadgen leg runs
  end-to-end bit-exact against each NEW workload's own hashlib oracle,
  and per-workload state stamps keep checkpoints/caches/span files from
  leaking across hash families.

The gateway cache/span/coalesce slice and the seeded chaos drill are
parameterized over the registry in tests/test_gateway.py and
tests/test_chaos_soak.py (same ``workloads`` marker).
"""

import json
import threading

import pytest

from bitcoin_miner_tpu import lsp, workloads
from bitcoin_miner_tpu.apps import client as client_mod
from bitcoin_miner_tpu.apps import miner as miner_mod
from bitcoin_miner_tpu.apps import server as server_mod
from bitcoin_miner_tpu.apps.scheduler import Scheduler
from bitcoin_miner_tpu.bitcoin.hash import hash_nonce, min_hash_range
from bitcoin_miner_tpu.gateway import ResultCache, SpanStore
from bitcoin_miner_tpu.utils.metrics import METRICS
from bitcoin_miner_tpu.workloads import Sha256Workload, Workload

pytestmark = pytest.mark.workloads

PARAMS = lsp.Params(epoch_limit=5, epoch_millis=100, window_size=5)

ALL = workloads.names()
NON_DEFAULT = [n for n in ALL if n != workloads.DEFAULT_WORKLOAD]


# ------------------------------------------------------------------ registry


class TestRegistry:
    def test_default_first_and_expected_members(self):
        assert ALL[0] == workloads.DEFAULT_WORKLOAD == "sha256d"
        assert {"sha256d", "preimage", "blake2b64"} <= set(ALL)

    def test_resolve_contract(self):
        d = workloads.resolve(None)
        assert d.name == "sha256d"
        assert workloads.resolve("") is d
        p = workloads.get("preimage")
        assert workloads.resolve("preimage") is p
        assert workloads.resolve(p) is p

    def test_unknown_name_lists_valid_ones(self):
        with pytest.raises(ValueError) as ei:
            workloads.get("nope")
        for name in ALL:
            assert name in str(ei.value)

    def test_register_invariants(self):
        golden = (("x", 1, 123),)
        with pytest.raises(ValueError, match="golden"):
            workloads.register(Sha256Workload("wl-nogold"))
        with pytest.raises(ValueError, match="already registered"):
            workloads.register(
                Sha256Workload("sha256d", golden=golden)
            )

        class NoHashlibLast(Workload):
            tiers = ("hashlib", "cpu")

        bad = NoHashlibLast()
        bad.name, bad.golden = "wl-ladder", golden
        with pytest.raises(ValueError, match="hashlib"):
            workloads.register(bad)

        # native_ok is proven at register time: the sweep drivers trust it
        # to route host lanes through the default-format native/compiled
        # path, so a non-default family claiming it must be refused.
        with pytest.raises(ValueError, match="native_ok"):
            workloads.register(
                Sha256Workload("wl-native-lie", sep=":", native_ok=True,
                               golden=golden)
            )

    def test_default_is_the_frozen_reference_contract(self):
        w = workloads.resolve(None)
        for data, nonce in (("hello", 0), ("cmu440", 987654321), ("", 7)):
            assert w.hash_nonce(data, nonce) == hash_nonce(data, nonce)
        assert w.min_range("frozen", 0, 300) == min_hash_range("frozen", 0, 300)


# ------------------------------------------------------------------- oracles


@pytest.mark.parametrize("wname", ALL)
class TestOracles:
    def test_golden_vectors_recompute(self, wname):
        w = workloads.get(wname)
        assert len(w.golden) >= 3
        for data, nonce, frozen in w.golden:
            assert w.hash_nonce(data, nonce) == frozen, (wname, data, nonce)

    def test_cpu_tier_matches_oracle_across_digit_boundaries(self, wname):
        w = workloads.get(wname)
        cpu = w.make_search("cpu")
        # Digit-class boundaries are where template machinery breaks
        # first; the cpu tier must agree with the naive oracle loop.
        for lo, hi in ((0, 25), (7, 13), (95, 112), (998, 1005), (40, 400)):
            assert cpu("wl", lo, hi) == w.min_range("wl", lo, hi), (wname, lo, hi)

    def test_min_range_rejects_empty(self, wname):
        w = workloads.get(wname)
        with pytest.raises(ValueError):
            w.min_range("x", 5, 4)


def test_families_are_distinct_functions():
    probes = [("dist", 3), ("dist", 41), ("", 999)]
    seen = {}
    for name in ALL:
        w = workloads.get(name)
        sig = tuple(w.hash_nonce(d, n) for d, n in probes)
        assert sig not in seen.values(), (name, "collides with", seen)
        seen[name] = sig


# -------------------------------------------------------------- tier ladders


class TestTierLadders:
    def test_ladder_shapes(self):
        assert workloads.get("sha256d").tiers == (
            "pallas", "xla", "cpu", "hashlib")
        assert workloads.get("preimage").tiers == (
            "pallas", "xla", "cpu", "hashlib")
        # ISSUE 20: blake2b64 grew a real device tier (ops/blake2b.py);
        # no pallas rung — the family has no Mosaic lowering yet.
        assert workloads.get("blake2b64").tiers == ("xla", "cpu", "hashlib")

    def test_blake2b_refuses_pallas_tier(self):
        b = workloads.get("blake2b64")
        with pytest.raises(ValueError, match="no 'pallas' tier"):
            b.make_search("pallas")
        with pytest.raises(ValueError, match="no 'pallas' tier"):
            miner_mod.make_search("pallas", workload=b)

    def test_blake2b_xla_tier_bit_exact(self):
        """ISSUE 20's device half: the u32-pair blake2b kernel runs the
        workload bit-exact vs its own hashlib oracle across a digit-class
        boundary, and genuinely hashes the blake2b message (differs from
        the sha256 families on the same range)."""
        w = workloads.get("blake2b64")
        search = w.make_search("xla")
        assert search("b2dev", 95, 320) == w.min_range("b2dev", 95, 320)
        assert search("b2dev", 95, 320) != min_hash_range("b2dev", 95, 320)

    def test_preimage_xla_tier_bit_exact(self):
        """The tentpole's device half: the separator-parameterized layout
        drives the real (rolled, XLA:CPU-compiled) kernel for a
        non-default workload, bit-exact vs its own hashlib oracle across
        digit classes."""
        w = workloads.get("preimage")
        search = w.make_search("xla")
        assert search("pw0", 0, 300) == w.min_range("pw0", 0, 300)
        # And differs from the default family on the same range — the
        # kernel really hashed "<data>:<nonce>", not "<data> <nonce>".
        assert search("pw0", 0, 300) != min_hash_range("pw0", 0, 300)

    def test_async_search_cpu_pool(self):
        for name in NON_DEFAULT:
            w = workloads.get(name)
            s = w.make_async_search("cpu")
            try:
                assert s.submit("async", 0, 200).result(timeout=30) == (
                    w.min_range("async", 0, 200)
                )
            finally:
                s.close()

    def test_tiered_chain_is_the_workloads_ladder(self):
        # auto on a CPU host resolves to the cpu rung; the chain is the
        # suffix of the workload's own ladder from there.
        ts = miner_mod.make_tiered_search(
            "auto", workload=workloads.get("blake2b64")
        )
        try:
            assert [t for t, _ in ts._chain] == ["cpu", "hashlib"]
        finally:
            ts.close()
        ts = miner_mod.make_tiered_search(
            "xla", workload=workloads.get("preimage")
        )
        try:
            assert [t for t, _ in ts._chain] == ["xla", "cpu", "hashlib"]
        finally:
            ts.close()
        # ISSUE 20: the blake2b64 device rung heads the 3-rung watchdog
        # chain when asked for explicitly.
        ts = miner_mod.make_tiered_search(
            "xla", workload=workloads.get("blake2b64")
        )
        try:
            assert [t for t, _ in ts._chain] == ["xla", "cpu", "hashlib"]
        finally:
            ts.close()
        with pytest.raises(ValueError, match="no 'pallas' tier"):
            miner_mod.make_tiered_search(
                "pallas", workload=workloads.get("blake2b64")
            )


def test_watchdog_downgrade_drill_non_default_workload():
    """The ISSUE 9 acceptance drill: the watchdog ladder works
    per-workload — a wedged top tier is abandoned and the chunk re-runs
    bit-exact on the NON-default workload's own next tier."""
    w = workloads.get("preimage")
    downgrades0 = METRICS.get("miner.tier_downgrades")
    hold = threading.Event()

    def wedged(d, lo, hi):
        hold.wait(timeout=30)
        return (0, 0)

    ts = miner_mod._TieredSearch(
        [
            ("wedged", lambda: wedged),
            ("cpu", lambda: w.make_async_search("cpu")),
            ("hashlib", lambda: w.min_range),
        ],
        wedge_seconds=0.4,
    )
    try:
        got = ts.submit("wl-wedge", 0, 600).result(timeout=30)
        assert got == w.min_range("wl-wedge", 0, 600)
        assert METRICS.get("miner.tier_downgrades") - downgrades0 == 1
        assert ts.active_tier == "cpu"
    finally:
        hold.set()
        ts.close()


def test_watchdog_fleet_serves_non_default_workload_after_downgrade():
    """Fleet shape of the same drill: a server scheduling the preimage
    workload, whose only miner starts on a wedging tier, still answers
    the client bit-exact — run_miner never notices the tier swap."""
    w = workloads.get("preimage")
    hold = threading.Event()

    def wedged(d, lo, hi):
        hold.wait(timeout=30)
        return (0, 0)

    server = lsp.Server(0, PARAMS)
    threading.Thread(
        target=server_mod.serve,
        args=(server, Scheduler(min_chunk=500, workload=w)),
        daemon=True,
    ).start()
    ts = miner_mod._TieredSearch(
        [("wedged", lambda: wedged),
         ("cpu", lambda: w.make_async_search("cpu"))],
        wedge_seconds=0.5,
    )
    mc = lsp.Client("127.0.0.1", server.port, PARAMS)
    threading.Thread(
        target=miner_mod.run_miner, args=(mc, ts), daemon=True
    ).start()
    try:
        c = lsp.Client("127.0.0.1", server.port, PARAMS)
        try:
            res = client_mod.request_once(c, "wlfleet", 2000)
        finally:
            c.close()
        assert res == w.min_range("wlfleet", 0, 2000)
    finally:
        hold.set()
        server.close()


# --------------------------------------------------------- serving-stack e2e


@pytest.mark.parametrize("wname", NON_DEFAULT)
def test_loadgen_gateway_interval_leg_per_new_workload(wname, capsys):
    """The ISSUE 9 acceptance bar: each NEW workload runs the
    gateway+interval-store loadgen leg end-to-end — overlap-heavy
    traffic, every Result validated against that workload's own hashlib
    oracle, the repeat and covered-sub-range probes answering with zero
    chunks assigned."""
    import tools.loadgen as loadgen

    rc = loadgen.main([
        "--fast", "--overlap", "--workload", wname,
        "--jobs", "14", "--clients", "4", "--max-nonce", "2500",
    ])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["workload"] == wname
    assert out["repeat_zero_chunks"] is True
    assert out["subrange_zero_chunks"] is True
    assert out["swept_reduction"] is None or out["swept_reduction"] >= 0


class TestWorkloadStateStamps:
    """Per-workload state files refuse to load across hash families —
    resuming another function's minima would silently corrupt answers."""

    def test_scheduler_checkpoint_stamp(self):
        s = Scheduler(workload=workloads.get("preimage"))
        state = s.checkpoint()
        assert state["workload"] == "preimage"
        # Non-default state nests its payload under version 2: a
        # pre-registry reader (which gates on neither version nor stamp
        # and reads top-level "jobs" directly) must find NOTHING, not
        # another hash family's minima.
        assert state["version"] == 2 and "jobs" not in state
        jobs = [{
            "data": "x", "lower": 0, "upper": 99,
            "best": [5, 3], "remaining": [[10, 99]],
        }]
        state["state"]["jobs"] = jobs
        other = Scheduler()  # default workload
        other.load_checkpoint(state)
        assert other._resume == {}
        same = Scheduler(workload=workloads.get("preimage"))
        same.load_checkpoint(state)
        assert ("x", 0, 99) in same._resume
        # Pre-registry (unstamped, flat v1) checkpoints belong to the
        # default — and the default still WRITES that frozen flat shape.
        legacy = Scheduler()
        legacy.load_checkpoint({"version": 1, "jobs": jobs})
        assert ("x", 0, 99) in legacy._resume
        default_state = Scheduler().checkpoint()
        assert default_state["version"] == 1 and "jobs" in default_state

    def test_result_cache_stamp(self, tmp_path):
        path = str(tmp_path / "cache.json")
        c = ResultCache(path=path, workload="blake2b64")
        c.put(("d", 0, 9), 1, 2)
        c.save(path)
        # Nested non-default shape: no top-level "entries" for a
        # pre-registry reader to misread (see workloads.stamp_state).
        on_disk = json.loads((tmp_path / "cache.json").read_text())
        assert on_disk["version"] == 2 and "entries" not in on_disk
        assert ResultCache(path=path, workload="blake2b64").get(("d", 0, 9)) == (1, 2)
        assert ResultCache(path=path).get(("d", 0, 9)) is None
        assert ResultCache(path=path, workload="preimage").get(("d", 0, 9)) is None

    def test_span_store_stamp(self, tmp_path):
        path = str(tmp_path / "spans.json")
        s = SpanStore(path=path, workload="preimage")
        s.add("d", 0, 99, 7, 42)
        s.save(path)
        on_disk = json.loads((tmp_path / "spans.json").read_text())
        assert on_disk["version"] == 2 and "data" not in on_disk
        assert len(SpanStore(path=path, workload="preimage")) == 1
        assert len(SpanStore(path=path)) == 0
