"""The repo-native analysis suite, both directions (ISSUE 4).

- The live repo passes every pass clean (``python -m tools.analyze``
  exits 0) — this is the tier-1 gate every future PR runs.
- Every rule FIRES on its seeded fixture violation
  (tests/fixtures_analyze): an analyzer that cannot detect certifies
  nothing.
- The runtime race sanitizer's primitives (TrackedLock ownership,
  acquisition-order graph, Monitor discipline) unit-tested directly, and
  the only-shrink ratchet mechanics.

The BMT_SANITIZE=1 integration legs live with the suites they harden:
tests/test_chaos_soak.py (sanitized fast drill) and tests/test_gateway.py
(sanitized duplicate-heavy fleet).
"""

import importlib.util
import json
import subprocess
import sys
import threading
import time
from pathlib import Path

import pytest

pytestmark = pytest.mark.analysis

REPO = Path(__file__).resolve().parents[1]
FIXTURES = REPO / "tests" / "fixtures_analyze"

if str(REPO) not in sys.path:  # make `tools.analyze` importable in-process
    sys.path.insert(0, str(REPO))

from tools.analyze import PASSES, apply_ratchet, load_ratchet, save_ratchet
from tools.analyze import contracts as contracts_pass
from tools.analyze.common import DEFAULT_SCAN_DIRS, Finding
from tools.analyze.donatecheck import DONATE_SCAN_DIRS
from tools.analyze.tracecheck import TRACE_SCAN_DIRS

from bitcoin_miner_tpu.utils import sanitize


def _pass_findings(name, root, scan=None):
    return PASSES[name](root, scan)


# --------------------------------------------------------------------------
# 1. The live repo is clean
# --------------------------------------------------------------------------


@pytest.mark.parametrize(
    "name",
    [
        "lock", "wfq", "trace", "contracts", "sanitize", "metrics",
        "loop", "donate", "thread",
    ],
)
def test_repo_is_clean(name):
    scan = {
        "trace": TRACE_SCAN_DIRS,
        "donate": DONATE_SCAN_DIRS,
    }.get(name, DEFAULT_SCAN_DIRS)
    findings = _pass_findings(name, REPO, scan)
    ratchet = load_ratchet(REPO / "tools" / "analyze" / "ratchet.json")
    new, stale = apply_ratchet(findings, ratchet)
    assert not new, "\n".join(f.render() for f in new)
    assert not stale, stale


def test_cli_repo_mode_exits_zero():
    """The command every future PR runs — fast, CPU-safe, no network."""
    res = subprocess.run(
        [sys.executable, "-m", "tools.analyze", "-q"],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert res.returncode == 0, res.stdout + res.stderr


def test_cli_fixture_mode_exits_nonzero():
    res = subprocess.run(
        [sys.executable, "-m", "tools.analyze", "--root", str(FIXTURES)],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert res.returncode == 1, res.stdout + res.stderr
    # Every pass contributed at least one finding to the output.
    for tag in ("[lock/", "[wfq/", "[contracts/", "[trace/", "[sanitize/",
                "[metrics/", "[loop/", "[donate/", "[thread/"):
        assert tag in res.stdout, f"{tag} never fired:\n{res.stdout}"


# --------------------------------------------------------------------------
# 2. Every rule fires on its seeded fixture
# --------------------------------------------------------------------------


def _rules(findings):
    return {f.rule for f in findings}


def test_lock_rules_fire_on_fixture():
    rules = _rules(_pass_findings("lock", FIXTURES))
    assert {"field-off-lock", "helper-off-lock", "local-off-lock"} <= rules


def test_lock_pass_understands_acquire_release_pairs():
    """Explicit acquire()/release() pairing (ISSUE 5): access between the
    calls (the try/finally idiom) is LEGAL; access after the release
    fires.  Both directions checked by line, for fields and for
    serve-loop locals."""
    src = (FIXTURES / "bad_lock.py").read_text().splitlines()

    def line_of(marker):
        return next(i + 1 for i, text in enumerate(src) if marker in text)

    findings = _pass_findings("lock", FIXTURES)
    flagged = {(f.symbol, f.line) for f in findings}
    # The seeded post-release violations fire...
    assert ("PairedCounter._n", line_of("post-release read")) in flagged
    assert (
        "serve_like_paired:state",
        line_of("local read after paired release"),
    ) in flagged
    # ...and the legal between-acquire/release accesses do NOT.
    legal_lines = {
        i + 1
        for i, text in enumerate(src)
        if "legal: between acquire/release" in text
    }
    assert len(legal_lines) == 2  # one field access, one serve-loop local
    assert not {(s, ln) for s, ln in flagged if ln in legal_lines}


def test_wfq_rules_fire_on_fixture():
    rules = _rules(_pass_findings("wfq", FIXTURES))
    assert {"floor-init-reimplemented", "tiebreak-reimplemented"} <= rules


def test_trace_rules_fire_on_fixture():
    rules = _rules(_pass_findings("trace", FIXTURES))
    assert {
        "trace-branch",
        "trace-concretize",
        "trace-wallclock",
        "trace-rng",
        "trace-unhashable-static",
    } <= rules


def test_contract_rules_fire_on_drifted_codec():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bad_contract", FIXTURES / "bad_contract.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    findings = contracts_pass.run(
        FIXTURES, None, modules={"bitcoin_message": mod, "hash": mod}
    )
    rules = _rules(findings)
    assert {"codec-marshal", "codec-roundtrip", "hash-vector"} <= rules


def test_sanitize_pass_fires_on_fixture():
    findings = _pass_findings("sanitize", FIXTURES)
    provoked = {f.symbol for f in findings}
    assert {
        "provoke_unsynchronized_access",
        "provoke_lock_order_inversion",
    } <= provoked


def test_metrics_rules_fire_on_fixture():
    """Every metric-registry rule fires on bad_metric.py: an emitted-but-
    undocumented name, a documented-but-never-emitted name, a histogram
    name emitted via inc(), and a computed (unverifiable) name."""
    findings = _pass_findings("metrics", FIXTURES)
    rules = _rules(findings)
    assert {
        "metric-undocumented",
        "metric-unused",
        "metric-kind-mismatch",
        "metric-dynamic-name",
    } <= rules
    symbols = {f.symbol for f in findings}
    assert "fixture.never_documented" in symbols
    assert "fixture.documented_only" in symbols
    assert "hist.fixture_latency" in symbols
    # fleet.* names are gauge-kind (ISSUE 7): inc() on one must fire.
    assert ("metric-kind-mismatch", "fleet.fixture_sources") in {
        (f.rule, f.symbol) for f in findings
    }
    # fed.peer_state.* is the membership gauge family (ISSUE 12): inc()
    # on one must fire too, while the rest of fed.* stays counter-kind.
    assert ("metric-kind-mismatch", "fed.peer_state.fixture") in {
        (f.rule, f.symbol) for f in findings
    }
    # gw.conns_live is the ingress live-conn gauge (ISSUE 15) — the one
    # gauge-kind name under gw.* — and the ingress.* counter family rides
    # the same registry cross-check.
    assert ("metric-kind-mismatch", "gw.conns_live") in {
        (f.rule, f.symbol) for f in findings
    }
    assert ("metric-unused", "ingress.fixture_events") in {
        (f.rule, f.symbol) for f in findings
    }
    # kernel.thresh_staleness is the hot plane's threshold-lag gauge
    # (ISSUE 16) — the one gauge-kind name under kernel.* — and the
    # sweep.* hot-plane counter family rides the same registry
    # cross-check (inc-kind).
    assert ("metric-kind-mismatch", "kernel.thresh_staleness") in {
        (f.rule, f.symbol) for f in findings
    }
    assert ("metric-unused", "sweep.fixture_refills") in {
        (f.rule, f.symbol) for f in findings
    }
    # autoscale.target_workers is the capacity plane's fleet-size gauge
    # and fed.conns_live the federation transport's shared-loop conn
    # gauge (ISSUE 18); the rest of autoscale.* counts controller
    # actions and stays inc-kind, pinned by the unused-row cross-check.
    assert ("metric-kind-mismatch", "autoscale.target_workers") in {
        (f.rule, f.symbol) for f in findings
    }
    assert ("metric-kind-mismatch", "fed.conns_live") in {
        (f.rule, f.symbol) for f in findings
    }
    assert ("metric-unused", "autoscale.fixture_actions") in {
        (f.rule, f.symbol) for f in findings
    }
    # sanitize.* is the sanitizer trip-counter family (ISSUE 19) — stays
    # inc-kind, pinned by the unused-row cross-check.
    assert ("metric-unused", "sanitize.fixture_trips") in {
        (f.rule, f.symbol) for f in findings
    }


def test_loop_rules_fire_on_fixture():
    """Every loop-discipline rule fires on bad_loop.py — and none of the
    legal idioms (awaited calls, async-with locks, the identity fast
    path, the threadsafe hop, `# loop-ok:` suppressions) fire."""
    findings = _pass_findings("loop", FIXTURES)
    assert {
        "loop-blocking-call",
        "loop-lock",
        "loop-off-thread-write",
    } <= _rules(findings)
    rules_syms = {(f.rule, f.symbol) for f in findings}
    # The off-thread write on the annotated loop-owned field...
    assert ("loop-off-thread-write", "BadBridge.write") in rules_syms
    # ...the sync sleep / file open / Future wait inside coroutines...
    assert ("loop-blocking-call", "handler") in rules_syms
    assert ("loop-blocking-call", "locked_handler") in rules_syms
    assert ("loop-lock", "locked_handler") in rules_syms
    # ...and a PLAIN def pulled into scope by its `# on-loop:` header.
    assert ("loop-blocking-call", "on_loop_callback") in rules_syms
    # The clean idioms never appear at all.
    symbols = {f.symbol for f in findings}
    for clean in (
        "BadBridge.write_hopped",  # identity fast path + threadsafe hop
        "BadBridge.snapshot",      # trailing # loop-ok:
        "clean_handler",           # awaited read / async with
        "suppressed_handler",      # trailing # loop-ok:
        "BadBridge.__init__",      # the annotation site itself
    ):
        assert clean not in symbols, (clean, symbols)


def test_donate_rules_fire_on_fixture():
    """Every donation-safety rule fires on bad_donate.py — via both the
    explicit ``jax.jit(..., donate_argnums=...)`` spelling and the
    hot-step factory convention — while the hot-carry rebind idiom
    (the exact ``_HotLoop.dispatch`` shape), the ``carry is None``
    refresh test, and ``# donate-ok:`` suppressions stay clean."""
    findings = _pass_findings("donate", FIXTURES)
    assert {
        "donate-no-rebind",
        "donate-read-after-call",
        "donate-materialize",
    } <= _rules(findings)
    rules_syms = {(f.rule, f.symbol) for f in findings}
    assert ("donate-no-rebind", "drops_result") in rules_syms
    assert ("donate-no-rebind", "reads_dead_handle") in rules_syms
    assert ("donate-read-after-call", "reads_dead_handle") in rules_syms
    # The factory route: callee named like *hot_step* donates arg 0.
    assert ("donate-no-rebind", "factory_route") in rules_syms
    # Mid-job materialization of the donated carry, both spellings.
    assert ("donate-materialize", "HotThing.peek") in rules_syms
    assert ("donate-materialize", "HotThing.finish") in rules_syms
    symbols = {f.symbol for f in findings}
    for clean in (
        "clean_rebind",              # the donated call rebinds
        "sanctioned_drop",           # trailing # donate-ok:
        "HotThing.dispatch",         # hot-carry rebind + None test
        "HotThing.finish_sanctioned",  # the annotated job-end fetch
    ):
        assert clean not in symbols, (clean, symbols)


def test_thread_rules_fire_on_fixture():
    """thread-unjoined fires on both ownership shapes — the class-owned
    thread whose close() never joins it (daemon does NOT exempt) and the
    fire-and-forget non-daemon local — while the reaper joins (direct
    and for-loop-over-list), the wait-for-workers local join, daemon
    locals, and `# thread-owner:` abandons stay clean."""
    findings = _pass_findings("thread", FIXTURES)
    assert "thread-unjoined" in _rules(findings)
    symbols = {f.symbol for f in findings}
    assert "LeakyWorker.__init__" in symbols
    assert "leaky_local" in symbols
    for clean in (
        "CleanWorker.__init__",       # joined in stop(), both spellings
        "AbandonedByDesign.__init__",  # trailing # thread-owner:
        "clean_local_join",
        "clean_local_daemon",
        "annotated_local",
    ):
        assert clean not in symbols, (clean, symbols)


def test_metrics_pass_honors_metric_ok_declaration(tmp_path):
    """A dynamic emit with `# metric-ok: prefix.*` is legal and marks the
    documented prefix as emitted (the chaos layer's one dynamic site);
    declaring an unknown name still fails."""
    good = tmp_path / "dyn_ok.py"
    good.write_text(
        "class Metrics:\n"
        "    def inc(self, name):\n"
        "        pass\n"
        "\n"
        "#: registry block\n"
        "#:   dyn.alpha   covered by the declared glob\n"
        "#:   dyn.beta    covered by the declared glob\n"
        "METRICS = Metrics()\n"
        "\n"
        "def emit(what):\n"
        "    METRICS.inc('dyn.' + what)  # metric-ok: dyn.*\n"
    )
    assert _pass_findings("metrics", tmp_path) == []
    bad = tmp_path / "dyn_ok.py"
    bad.write_text(
        bad.read_text().replace("# metric-ok: dyn.*",
                                "# metric-ok: dyn.alpha dyn.gamma")
    )
    findings = _pass_findings("metrics", tmp_path)
    rules_syms = {(f.rule, f.symbol) for f in findings}
    assert ("metric-undocumented", "dyn.gamma") in rules_syms  # bad token
    assert ("metric-unused", "dyn.beta") in rules_syms  # no longer covered


def test_trace_pass_does_not_flag_static_branches(tmp_path):
    """The taint heuristic must not cry wolf on the repo's real kernel
    idioms: static Python loops/branches and dict-membership over static
    keys inside a kernel factory."""
    clean = tmp_path / "clean_kernel.py"
    clean.write_text(
        "import jax\nimport jax.numpy as jnp\n\n"
        "def make_kernel(n_blocks, k):\n"
        "    def kernel(midstate, bounds):\n"
        "        i = jnp.arange(10 ** k)\n"
        "        contrib = {}\n"
        "        for b in range(n_blocks):\n"
        "            contrib[b] = i + b\n"
        "        w = []\n"
        "        for widx in range(16):\n"
        "            if widx in contrib:\n"
        "                w.append(contrib[widx])\n"
        "        if n_blocks > 1:\n"
        "            w.append(jnp.min(i))\n"
        "        return w\n"
        "    return jax.jit(kernel)\n"
    )
    assert _pass_findings("trace", tmp_path) == []


def test_trace_pass_collects_sieve_kernel_bodies():
    """ISSUE 13 coverage meta-test: the trace-safety lint must SEE the
    two-stage sieve kernel paths — both passes, both backends — exactly
    like the baseline kernels.  The sieve bodies live inside the factory
    convention (``make_kernel_body`` / ``_build_call`` /
    ``make_pallas_minhash*``), so _collect_kernel_bodies must return
    them; if a refactor ever moves them outside the convention, this
    test (not silence) is what fails."""
    import ast

    from tools.analyze.common import file_comments
    from tools.analyze.tracecheck import FACTORY_RE, _collect_kernel_bodies

    # The sieve factory naming is part of the convention now.
    assert FACTORY_RE.search("make_pallas_sieve")
    collected = {}
    for mod in ("ops/sweep.py", "ops/pallas_sha256.py"):
        src = (REPO / "bitcoin_miner_tpu" / mod).read_text()
        tree = ast.parse(src)
        names = [
            fn.name
            for fn in _collect_kernel_bodies(tree, file_comments(src))
        ]
        collected[mod] = names
    # ops/sweep.py: the xla tier's baseline AND sieve kernel bodies (two
    # defs named `kernel`) plus the shared assemble/hash/fold helpers
    # pass 1 and pass 2 run through.
    assert collected["ops/sweep.py"].count("kernel") >= 2
    for helper in ("_assemble", "_hash", "_fold"):
        assert helper in collected["ops/sweep.py"]
    # ops/pallas_sha256.py: the pallas kernel body (pass 1 + pass 2 in
    # one def) and the jit wrappers of both factories.
    assert "kernel" in collected["ops/pallas_sha256.py"]
    assert collected["ops/pallas_sha256.py"].count("minhash") >= 2


def test_trace_pass_collects_factored_kernel_bodies():
    """ISSUE 14 coverage meta-test: the trace-safety lint must SEE the
    factored kernel paths on both backends — the outer-group assembly /
    scalar-prefix / resumed-hash helpers of the xla tier's factored
    branch (inside ``make_kernel_body``) and the factored pallas body
    (inside ``_build_factored_call`` / ``make_pallas_minhash_factored``).
    If a refactor moves them outside the factory convention, this test
    (not silence) fails."""
    import ast

    from tools.analyze.common import file_comments
    from tools.analyze.tracecheck import FACTORY_RE, _collect_kernel_bodies

    # The factored factory naming is part of the convention now.
    assert FACTORY_RE.search("make_factored_kernel")
    assert FACTORY_RE.search("_build_factored_call")
    assert FACTORY_RE.search("make_pallas_minhash_factored")
    collected = {}
    for mod in ("ops/sweep.py", "ops/pallas_sha256.py"):
        src = (REPO / "bitcoin_miner_tpu" / mod).read_text()
        tree = ast.parse(src)
        names = [
            fn.name
            for fn in _collect_kernel_bodies(tree, file_comments(src))
        ]
        collected[mod] = names
    # ops/sweep.py: the factored branch's kernel defs push the `kernel`
    # count past the baseline+sieve pair, and its helpers are visible.
    assert collected["ops/sweep.py"].count("kernel") >= 4
    for helper in ("_assemble_group", "_group_prefix", "_hash_resumed"):
        assert helper in collected["ops/sweep.py"]
    # ops/pallas_sha256.py: the factored call's kernel body and the
    # factored jit wrapper join the static + dyn ones.
    assert collected["ops/pallas_sha256.py"].count("kernel") >= 2
    assert collected["ops/pallas_sha256.py"].count("minhash") >= 3


def test_trace_pass_collects_hot_step_bodies():
    """ISSUE 16 coverage meta-test: the trace-safety lint must SEE the
    always-hot plane's donated ring-loop step bodies.  ``make_hot_step``
    builds one jitted ``step`` per backend variant (xla / pallas / mesh)
    plus the shared ``_merge`` carry combine — all of them trace with a
    carried device threshold, so the concretize/branch/wallclock rules
    must gate them exactly like the kernels they wrap.  If a refactor
    renames the factory outside the ``|hot`` convention, this test (not
    silence) fails."""
    import ast

    from tools.analyze.common import file_comments
    from tools.analyze.tracecheck import FACTORY_RE, _collect_kernel_bodies

    # The hot factory naming is part of the convention now.
    assert FACTORY_RE.search("make_hot_step")
    src = (REPO / "bitcoin_miner_tpu" / "ops" / "sweep.py").read_text()
    names = [
        fn.name
        for fn in _collect_kernel_bodies(ast.parse(src), file_comments(src))
    ]
    # All three backend-variant step bodies and the carry combine.
    assert names.count("step") >= 3
    assert "_merge" in names


def test_trace_pass_collects_blake2b_kernel_bodies():
    """ISSUE 20 coverage meta-test: the trace-safety lint must SEE the
    second kernel family's bodies — the blake2b compression sweep body
    nested in ``make_blake2b_kernel_body`` (and the sharded wrapper's, in
    parallel/sweep.py) via the grown ``|blake2b`` factory convention, and
    the module-level u32-pair device primitives via their explicit
    ``# jit-kernel`` marks.  If a refactor renames a factory outside the
    convention or drops a mark, this test (not silence) fails."""
    import ast

    from tools.analyze.common import file_comments
    from tools.analyze.tracecheck import FACTORY_RE, _collect_kernel_bodies

    # The blake2b factory naming is part of the convention now.
    assert FACTORY_RE.search("make_blake2b_kernel_body")
    assert FACTORY_RE.search("_make_blake2b_kernel")
    assert FACTORY_RE.search("_make_sharded_blake2b_kernel")
    collected = {}
    for mod in ("ops/blake2b.py", "parallel/sweep.py"):
        src = (REPO / "bitcoin_miner_tpu" / mod).read_text()
        names = [
            fn.name
            for fn in _collect_kernel_bodies(ast.parse(src), file_comments(src))
        ]
        collected[mod] = names
    # The factory-nested compression sweep body...
    assert "kernel" in collected["ops/blake2b.py"]
    # ...the marked module-level device primitives the body calls into
    # (they sit outside any factory, so only the marks admit them)...
    for helper in ("_addm", "_rotr64", "_G", "_compress_pairs", "_bswap32"):
        assert helper in collected["ops/blake2b.py"]
    # ...and the mesh plane's traced bodies the sharded blake2b factory
    # composes: the per-shard `local` body and the collective-cascade
    # `shard_fn` wrapper (the blake2b body itself is built in
    # ops/blake2b.py and collected there as `kernel`).
    assert {"local", "shard_fn"} <= set(collected["parallel/sweep.py"])
    # The contracts pass pins the same family's arithmetic end-to-end:
    # every blake2b64 golden recomputes through the xla device tier.
    from tools.analyze.contracts import WORKLOAD_DEVICE_TIERS

    assert WORKLOAD_DEVICE_TIERS.get("blake2b64") == "xla"


# --------------------------------------------------------------------------
# 2b. lockcheck --fix: the mechanical lock fixer (ISSUE 12 carry-over)
# --------------------------------------------------------------------------


_FIXABLE = """\
import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0  # guarded-by: _lock

    def bump(self):
        self._n += 1

    def read(self):
        return self._n
"""

_UNFIXABLE = """\
import threading


class Scanner:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0  # guarded-by: _lock

    def spin(self):
        while self._n < 10:
            pass
"""


def _run_lockfix(root, *extra):
    return subprocess.run(
        [sys.executable, "-m", "tools.analyze", "lockcheck", "--fix",
         "--root", str(root), *extra],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )


def test_lockfix_wraps_safe_findings_and_recheck_is_clean(tmp_path):
    """Direction 1: simple-statement findings are mechanically wrapped in
    `with self._lock:` and the lock pass then finds nothing."""
    (tmp_path / "fixme.py").write_text(_FIXABLE)
    res = _run_lockfix(tmp_path)
    assert res.returncode == 0, res.stdout + res.stderr
    fixed = (tmp_path / "fixme.py").read_text()
    assert fixed.count("with self._lock:") == 2
    assert "with self._lock:\n            self._n += 1" in fixed
    assert "with self._lock:\n            return self._n" in fixed
    assert _pass_findings("lock", tmp_path) == []  # idempotent + clean
    res2 = _run_lockfix(tmp_path)
    assert res2.returncode == 0
    assert (tmp_path / "fixme.py").read_text() == fixed  # nothing to redo


def test_lockfix_refuses_compound_headers_and_emits_review_diff(tmp_path):
    """Direction 2: an access in a loop header cannot be wrapped without
    changing control flow — the file stays byte-identical and the
    annotated context block names the spot for review."""
    (tmp_path / "scanner.py").write_text(_UNFIXABLE)
    res = _run_lockfix(tmp_path)
    assert res.returncode == 1, res.stdout + res.stderr
    assert (tmp_path / "scanner.py").read_text() == _UNFIXABLE
    assert "NOT auto-fixable" in res.stdout
    assert "scanner.py" in res.stdout and "Scanner._n" in res.stdout
    assert "while self._n < 10:" in res.stdout  # the annotated context


def test_lockfix_dry_run_touches_nothing(tmp_path):
    (tmp_path / "fixme.py").write_text(_FIXABLE)
    res = _run_lockfix(tmp_path, "--dry-run")
    assert (tmp_path / "fixme.py").read_text() == _FIXABLE
    assert "proposed (dry run)" in res.stdout
    assert "+        with self._lock:" in res.stdout


def test_lockfix_handles_serve_loop_locals(tmp_path):
    """The function-local `# guarded-by: lock` vocabulary wraps with the
    bare lock name, not `self.`."""
    (tmp_path / "serveish.py").write_text(
        "import threading\n"
        "\n"
        "\n"
        "def serve_like(lock):\n"
        "    state = {}  # guarded-by: lock\n"
        "    with lock:\n"
        "        state['a'] = 1\n"
        "    state['b'] = 2\n"
    )
    res = _run_lockfix(tmp_path)
    assert res.returncode == 0, res.stdout + res.stderr
    fixed = (tmp_path / "serveish.py").read_text()
    assert "    with lock:\n        state['b'] = 2" in fixed
    assert _pass_findings("lock", tmp_path) == []


_HOPPABLE = """\
class Bridge:
    def __init__(self, server, loop):
        self.srv = server  # on-loop: lp
        self.lp = loop

    def poke(self, conn_id, payload):
        self.srv.write(conn_id, payload)
"""

_UNHOPPABLE = """\
class Bridge:
    def __init__(self, server, loop):
        self.srv = server  # on-loop: lp
        self.lp = loop

    def query(self, conn_id):
        n = self.srv.pending(conn_id)
        return n
"""


def test_lockfix_hops_simple_off_loop_writes(tmp_path):
    """ISSUE 19: a bare fire-and-forget call on a loop-owned field is
    mechanically rewritten to the call_soon_threadsafe hop the finding
    message spells, the loop pass then finds nothing, and a second run
    has nothing to do."""
    (tmp_path / "bridge.py").write_text(_HOPPABLE)
    res = _run_lockfix(tmp_path)
    assert res.returncode == 0, res.stdout + res.stderr
    fixed = (tmp_path / "bridge.py").read_text()
    assert (
        "self.lp.call_soon_threadsafe(self.srv.write, conn_id, payload)"
        in fixed
    )
    assert _pass_findings("loop", tmp_path) == []  # recheck is clean
    res2 = _run_lockfix(tmp_path)
    assert res2.returncode == 0
    assert (tmp_path / "bridge.py").read_text() == fixed  # idempotent


def test_lockfix_refuses_hops_that_need_the_return_value(tmp_path):
    """A write whose result is bound cannot become a fire-and-forget
    hop — the file stays byte-identical and the review block names the
    spot."""
    (tmp_path / "bridge.py").write_text(_UNHOPPABLE)
    res = _run_lockfix(tmp_path)
    assert res.returncode == 1, res.stdout + res.stderr
    assert (tmp_path / "bridge.py").read_text() == _UNHOPPABLE
    assert "NOT auto-hoppable" in res.stdout
    assert "Bridge.query" in res.stdout
    assert "n = self.srv.pending(conn_id)" in res.stdout  # the context


def test_lockfix_hop_dry_run_touches_nothing(tmp_path):
    (tmp_path / "bridge.py").write_text(_HOPPABLE)
    res = _run_lockfix(tmp_path, "--dry-run")
    assert (tmp_path / "bridge.py").read_text() == _HOPPABLE
    assert "proposed (dry run)" in res.stdout
    assert "+        self.lp.call_soon_threadsafe(self.srv.write" in res.stdout


def test_lockfix_repo_mode_is_a_noop_on_a_clean_repo():
    """The repo carries no findings, so --fix must change nothing (and
    exit 0) — the tier-1-safe property."""
    res = subprocess.run(
        [sys.executable, "-m", "tools.analyze", "lockcheck", "--fix",
         "--dry-run"],
        cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    assert res.returncode == 0, res.stdout + res.stderr
    assert "0 finding(s) wrapped" in res.stdout


# --------------------------------------------------------------------------
# 3. Ratchet mechanics: the grandfather list may only shrink
# --------------------------------------------------------------------------


def _finding(rule="r", path="p.py", symbol="s"):
    return Finding("lock", rule, path, 1, symbol, "msg")


def test_ratchet_grandfathers_up_to_count_and_flags_excess():
    ratchet = {_finding().key: 1}
    new, stale = apply_ratchet([_finding(), _finding()], ratchet)
    assert len(new) == 1 and not stale  # one allowed, one new


def test_ratchet_stale_entry_must_shrink():
    ratchet = {_finding().key: 2}
    new, stale = apply_ratchet([_finding()], ratchet)
    assert not new
    assert stale == [_finding().key]  # fired 1 < recorded 2: shrink the file


def test_ratchet_save_load_roundtrip(tmp_path):
    path = tmp_path / "ratchet.json"
    save_ratchet(path, [_finding(), _finding(), _finding(rule="other")])
    loaded = load_ratchet(path)
    assert loaded[_finding().key] == 2
    assert loaded[_finding(rule="other").key] == 1
    assert "only shrink" in json.loads(path.read_text())["comment"]


def test_checked_in_ratchet_is_empty():
    """The repo carries no grandfathered debt today; if a future PR must
    add some, it does so explicitly — and the file can then only shrink."""
    assert load_ratchet(REPO / "tools" / "analyze" / "ratchet.json") == {}


# --------------------------------------------------------------------------
# 4. ruff + mypy (configured in pyproject.toml; the image may not ship the
#    tools — skip, don't fail, so tier-1 stays hermetic)
# --------------------------------------------------------------------------


def _have(tool: str) -> bool:
    return importlib.util.find_spec(tool) is not None


@pytest.mark.skipif(not _have("ruff"), reason="ruff not installed in this image")
def test_ruff_clean():
    res = subprocess.run(
        [sys.executable, "-m", "ruff", "check", "bitcoin_miner_tpu", "tools", "tests"],
        cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    assert res.returncode == 0, res.stdout + res.stderr


@pytest.mark.skipif(not _have("mypy"), reason="mypy not installed in this image")
def test_mypy_clean():
    res = subprocess.run(
        [sys.executable, "-m", "mypy", "--no-error-summary"],
        cwd=REPO, capture_output=True, text=True, timeout=600,
    )
    assert res.returncode == 0, res.stdout + res.stderr


# --------------------------------------------------------------------------
# 5. Race-sanitizer primitives
# --------------------------------------------------------------------------


@pytest.fixture
def sanitizer():
    sanitize.force(True)
    sanitize.reset_order_graph()
    yield sanitize
    sanitize.force(None)
    sanitize.reset_order_graph()


def test_tracked_lock_ownership(sanitizer):
    lock = sanitize.TrackedLock("t.own")
    assert not lock.held()
    with lock:
        assert lock.held()
        box = {}

        def peek():
            box["other"] = lock.held()

        t = threading.Thread(target=peek)
        t.start()
        t.join()
        assert box["other"] is False  # held() is per-thread, not per-lock
    assert not lock.held()


def test_lock_order_graph_is_transitive(sanitizer):
    a, b, c = (sanitize.TrackedLock(n) for n in ("g.A", "g.B", "g.C"))
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    with pytest.raises(sanitize.LockOrderError):
        with c:
            with a:  # A->B->C->A: caught via transitivity, not direct edge
                pass


def test_monitor_allows_thread_confined_use(sanitizer):
    lock = sanitize.TrackedLock("t.confined")
    obj = sanitize.guard({"n": 1}, lock, "conf")
    assert obj.keys() is not None  # single-threaded, off-lock: the setup window


def test_monitor_raises_once_shared(sanitizer):
    lock = sanitize.TrackedLock("t.shared")
    obj = sanitize.guard({"n": 1}, lock, "shared")

    def locked_touch():
        with lock:
            obj.keys()

    t = threading.Thread(target=locked_touch)
    t.start()
    t.join()
    with pytest.raises(sanitize.RaceError):
        obj.keys()
    with lock:
        obj.keys()  # disciplined access still fine


def test_guard_is_identity_when_disabled():
    sanitize.force(False)
    try:
        lock = sanitize.make_lock("t.off")
        assert isinstance(lock, type(threading.Lock()))
        obj = {"n": 1}
        assert sanitize.guard(obj, lock, "x") is obj
    finally:
        sanitize.force(None)


def test_loop_thread_self_call_raises_race_error(sanitizer):
    """ISSUE 12 carry-over: calling a blocking _LoopThread proxy FROM its
    own loop thread is a guaranteed deadlock (the Future can never
    resolve while its loop blocks on it) — refused outright."""
    from bitcoin_miner_tpu.lsp.sync import _LoopThread

    lt = _LoopThread("san-selfcall")
    try:
        box = {}

        def from_loop():
            try:
                lt.call(lambda: None)
            except BaseException as e:
                return e
            return None

        box["err"] = lt.call(lambda: from_loop())
        # from_loop ran ON the loop thread; its nested call() must raise.
        assert isinstance(box["err"], sanitize.RaceError), box["err"]
    finally:
        lt.stop()


def test_loop_thread_joins_lock_order_graph(sanitizer):
    """The Future-spelled ABBA: a loop whose callback takes the event
    lock, and a caller that blocks on the loop WHILE HOLDING that lock,
    is a deadlock-in-waiting — the order graph catches it
    deterministically, whichever side runs first."""
    from bitcoin_miner_tpu.lsp.sync import _LoopThread

    event = sanitize.TrackedLock("san.loop.event")
    lt = _LoopThread("san-order")
    try:
        # Leg 1: a loop callback acquires the event lock -> loop->event.
        def takes_event():
            with event:
                pass

        lt.call(takes_event)
        # Leg 2: blocking on the loop while holding the event lock adds
        # event->loop, closing the cycle.
        with pytest.raises(sanitize.LockOrderError):
            with event:
                lt.call(lambda: None)
    finally:
        lt.stop()


def test_loop_thread_clean_order_is_silent(sanitizer):
    """The repo's real discipline — locks taken outside loop waits, loop
    callbacks lock-free — records edges but never a cycle."""
    from bitcoin_miner_tpu.lsp.sync import _LoopThread

    event = sanitize.TrackedLock("san.loop.clean")
    lt = _LoopThread("san-clean")
    try:
        with event:
            lt.call(lambda: None)  # event->loop only: fine
        lt.call(lambda: None)
        with event:
            pass
    finally:
        lt.stop()


def test_serve_loop_discipline_clean_under_monitor(sanitizer):
    """The exact shape serve() runs: scheduler behind a Monitor, read loop
    + ticker threads, all access under the event lock — silent."""
    from bitcoin_miner_tpu.apps.scheduler import Scheduler

    lock = sanitize.make_lock("t.serve")
    sched = sanitize.guard(Scheduler(), lock, "scheduler")
    errors = []

    def actor(event_fn):
        try:
            for i in range(100):
                with lock:
                    event_fn(i)
        except BaseException as e:
            errors.append(e)

    threads = [
        threading.Thread(target=actor, args=(lambda i: sched.tick(float(i)),)),
        threading.Thread(target=actor, args=(lambda i: sched.stats(),)),
        threading.Thread(target=actor, args=(lambda i: sched.drain_evictions(),)),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors


# --------------------------------------------------------------------------
# 5. Loop-discipline runtime (ISSUE 19): the dynamic half of the `loop`
#    pass — blocking() declarations, the graph-based lock-on-loop check,
#    and the always-on thread census the flat-thread legs ride.
# --------------------------------------------------------------------------


def _returning_exc(fn):
    """Run ``fn``, returning the exception it raised (or None)."""
    try:
        fn()
    except BaseException as e:
        return e
    return None


def test_blocking_raises_only_on_registered_loop_threads(sanitizer):
    """sanitize.blocking() is free on a plain thread and a hard
    LoopBlockedError on a registered loop thread — the runtime spelling
    of loopcheck's loop-blocking-call rule."""
    from bitcoin_miner_tpu.lsp.sync import _LoopThread

    sanitize.blocking("test.plain_thread")  # plain thread: free
    lt = _LoopThread("san-blocking")
    try:
        err = lt.call(
            lambda: _returning_exc(lambda: sanitize.blocking("test.on_loop"))
        )
        assert isinstance(err, sanitize.LoopBlockedError), err
    finally:
        lt.stop()
    sanitize.blocking("test.after_stop")  # still free off-loop


def test_cross_loop_facade_wait_raises_loop_blocked(sanitizer):
    """A loop thread blocking on ANOTHER loop's proxy Future is the trip
    the sync facades now declare via sanitize.blocking: the nested call
    raises instead of stalling every conn riding the outer loop."""
    from bitcoin_miner_tpu.lsp.sync import _LoopThread

    a = _LoopThread("san-cross-a")
    b = _LoopThread("san-cross-b")
    try:
        err = a.call(
            lambda: _returning_exc(lambda: b.call(lambda: None))
        )
        assert isinstance(err, sanitize.LoopBlockedError), err
    finally:
        a.stop()
        b.stop()


def test_tracked_lock_on_loop_thread_uses_the_block_edge(sanitizer):
    """Taking a tracked lock ON a loop thread is legal in itself (the
    event plane does it every event) — it only becomes a refusal once
    some thread has BLOCKED on that loop while holding the same lock,
    because the next on-loop acquisition then closes a deadlock cycle."""
    from bitcoin_miner_tpu.lsp.sync import _LoopThread

    def take(lock):
        return _returning_exc(lambda: lock.acquire()) or lock.release()

    free = sanitize.TrackedLock("san.loopedge.free")
    event = sanitize.TrackedLock("san.loopedge.event")
    lt = _LoopThread("san-loopedge")
    try:
        # No block edge: an on-loop acquisition is silent.
        assert lt.call(lambda: take(free)) in (None, False)
        # Record event->loop: a thread blocks on the loop holding event.
        with event:
            lt.call(lambda: None)
        # Now the same lock ON the loop thread is the deadlock cycle.
        err = lt.call(lambda: _returning_exc(event.acquire))
        assert isinstance(err, sanitize.LoopBlockedError), err
    finally:
        lt.stop()


def test_thread_census_and_leak_check():
    """The always-on runtime half of the `thread` pass: the census
    baselines by name, threads_leaked names offenders (and feeds the
    sanitize.threads_leaked counter), and a reaped fleet drains clean."""
    from bitcoin_miner_tpu.utils.metrics import METRICS

    base = sanitize.thread_census()
    before = METRICS.get("sanitize.threads_leaked")
    stop = threading.Event()
    t = threading.Thread(target=stop.wait, name="census-probe")
    t.start()
    try:
        leaked = sanitize.threads_leaked(base)
        assert leaked.count("census-probe") == 1, leaked
        assert METRICS.get("sanitize.threads_leaked") >= before + 1
    finally:
        stop.set()
        t.join()
    assert sanitize.threads_leaked(base, settle_s=5.0) == []


# --------------------------------------------------------------------------
# 6. Incremental mode: --changed (ISSUE 19), the pre-commit-hook shape
# --------------------------------------------------------------------------


def test_cli_changed_mode_agrees_with_full_run_and_is_fast():
    """--changed must reach the same verdict as the full run (scoping
    may skip work, never flip the exit code) AND clear the pre-commit
    bar: a warm scoped run over a small diff in well under five seconds
    (a full run pays the whole-repo parse; the scoped run must not).
    The full run doubles as the cache warmer for the timed leg."""
    full = subprocess.run(
        [sys.executable, "-m", "tools.analyze", "-q"],
        cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    probe = REPO / "bitcoin_miner_tpu" / "_changed_probe.py"
    probe.write_text(
        '"""Untracked --changed timing probe (created and removed by '
        'tests/test_analyze.py)."""\n'
    )
    try:
        t0 = time.monotonic()
        inc = subprocess.run(
            [sys.executable, "-m", "tools.analyze", "--changed", "-q"],
            cwd=REPO, capture_output=True, text=True, timeout=60,
        )
        dt = time.monotonic() - t0
    finally:
        probe.unlink()
    assert inc.returncode == full.returncode, (
        full.stdout + full.stderr + inc.stdout + inc.stderr
    )
    assert dt < 5.0, f"--changed took {dt:.2f}s on a small diff"


def test_cli_changed_rejects_incompatible_flags():
    """--changed scopes the LIVE repo against git: combining it with an
    alternate --root or with --update-ratchet is a usage error."""
    for extra in (["--root", str(FIXTURES)], ["--update-ratchet"]):
        res = subprocess.run(
            [sys.executable, "-m", "tools.analyze", "--changed", *extra],
            cwd=REPO, capture_output=True, text=True, timeout=60,
        )
        assert res.returncode == 2, (extra, res.stdout, res.stderr)
