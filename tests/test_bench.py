"""Driver-artifact contract test: `python bench.py` must always emit
exactly one parseable JSON line on stdout with the fields the driver and
judge read (BENCH_r{N}.json).  Round 1 lost its entire perf artifact to an
unguarded backend init; this pins the hardened contract.
"""

import json
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]


def run_bench(*flags, env=None, timeout=560):
    full_env = None
    if env:
        import os

        full_env = {**os.environ, **env}
    return subprocess.run(
        [sys.executable, str(REPO / "bench.py"), *flags],
        capture_output=True,
        text=True,
        timeout=timeout,
        cwd=REPO,
        env=full_env,
    )


def test_sharded_devices_mode_on_virtual_mesh():
    """--devices N must run the sharded sweep on a virtual CPU mesh when
    there aren't N real chips, and report per-device + overlap stats."""
    p = run_bench("--devices", "2", "--cpu")
    assert p.returncode == 0, p.stderr[-2000:]
    lines = p.stdout.strip().splitlines()
    assert len(lines) == 1, lines
    out = json.loads(lines[0])
    assert out["metric"] == "nonces_per_sec_total_sharded"
    assert out["devices"] == 2
    assert out["value"] > 0
    # value and per_device are rounded independently from the raw rate.
    assert abs(out["per_device"] - out["value"] / 2) <= 1
    assert out["dispatches"] >= 1
    assert "fetch_wait_seconds" in out


def test_post_probe_wedge_still_emits_json():
    """If the in-process backend init hangs AFTER the subprocess probe (the
    tunnel wedging between probe and jax.devices), the watchdog must still
    land an error JSON artifact instead of hanging forever (round-1 failure
    mode; VERDICT r3 weak-item 4)."""
    p = run_bench(
        "--cpu",
        env={"BENCH_WATCHDOG_SECS": "2", "BENCH_SIMULATE_WEDGE": "60"},
        timeout=30,
    )
    assert p.returncode == 2, (p.returncode, p.stderr[-500:])
    lines = p.stdout.strip().splitlines()
    assert len(lines) == 1
    out = json.loads(lines[0])
    assert "hung" in out["error"]


def test_sieve_compare_fast_leg():
    """``--sieve-compare --fast`` (ISSUE 13): the tier-1 correctness leg
    of the sieve-vs-baseline comparison — both kernels oracle-gated on a
    digit-boundary range, the interpret-mode pallas sieve included, and
    the JSON honest about which kernel auto_tune keeps: a losing sieve
    must demonstrably keep the baseline."""
    p = run_bench("--sieve-compare", "--fast", "--cpu")
    assert p.returncode == 0, p.stderr[-2000:]
    lines = p.stdout.strip().splitlines()
    assert len(lines) == 1, lines
    out = json.loads(lines[0])
    assert out["metric"] == "sieve_compare"
    assert out["bitexact"] is True
    assert out["interpret_pallas_sieve_bitexact"] is True
    assert out["baseline_nps"] > 0 and out["sieve_nps"] > 0
    assert out["fast"] is True
    # The honesty contract: on a shape where the sieve leg loses, the
    # auto_tune rung must keep the baseline kernel (and vice versa the
    # sieve default may only claim a shape where it does not lose).
    if out["ratio"] < 1.0:
        assert out["kept_kernel"] == "baseline"
    assert out["auto_tune_sieve"] == (out["kept_kernel"] == "sieve")


def test_factor_compare_fast_leg():
    """``--factor-compare --fast`` (ISSUE 14): the tier-1 correctness leg
    of the factored-vs-baseline comparison — both kernels oracle-gated on
    a digit-boundary range, the interpret-mode pallas factored kernel
    (plain and sieve-composed) included, and the JSON honest about which
    kernel auto_tune keeps (BENCH_pr14.json is the full-speed artifact:
    the factored xla kernel wins 2.7x on this host, so auto_tune keeps
    it there)."""
    p = run_bench("--factor-compare", "--fast", "--cpu")
    assert p.returncode == 0, p.stderr[-2000:]
    lines = p.stdout.strip().splitlines()
    assert len(lines) == 1, lines
    out = json.loads(lines[0])
    assert out["metric"] == "factor_compare"
    assert out["bitexact"] is True
    assert out["interpret_pallas_factored_bitexact"] is True
    assert out["baseline_nps"] > 0 and out["factored_nps"] > 0
    assert out["fast"] is True
    # The honesty contract here is SELF-consistency: the JSON must record
    # exactly what auto_tune picks for this backend.  (Unlike the sieve
    # test, no ratio→kept coupling: the xla factored rung is calibrated
    # on the FULL-SPEED same-seed pair — BENCH_pr14.json, 2.76× — and the
    # --fast leg's tiny window under tier-1 load is a correctness gate,
    # not a measurement; asserting on its noisy ratio would flake.)
    assert out["auto_tune_factored"] == (out["kept_kernel"] == "factored")
    assert out["kept_kernel"] in ("baseline", "factored")


def test_hot_compare_fast_leg():
    """``--hot-compare --fast`` (ISSUE 16): the tier-1 correctness leg
    of the persistent-vs-per-chunk dispatch comparison — both disciplines
    oracle-gated on a digit-boundary range, the interpret-mode pallas hot
    plane (plain and sieve-composed, threshold device-carried) included,
    and the JSON honest about which dispatch auto_tune keeps
    (BENCH_pr16.json is the full-speed artifact)."""
    p = run_bench("--hot-compare", "--fast", "--cpu")
    assert p.returncode == 0, p.stderr[-2000:]
    lines = p.stdout.strip().splitlines()
    assert len(lines) == 1, lines
    out = json.loads(lines[0])
    assert out["metric"] == "hot_compare"
    assert out["bitexact"] is True
    assert out["interpret_pallas_hot_bitexact"] is True
    assert out["perchunk_nps"] > 0 and out["hot_nps"] > 0
    assert out["fast"] is True
    # The honesty contract here is SELF-consistency: the JSON must record
    # exactly what auto_tune picks for this backend.  (No ratio→kept
    # coupling: the hot rung is calibrated on the FULL-SPEED same-seed
    # pair — BENCH_pr16.json — and the --fast leg's tiny window under
    # tier-1 load is a correctness gate, not a measurement; asserting on
    # its noisy ratio would flake.)
    assert out["auto_tune_hot"] == (out["kept_kernel"] == "hot")
    assert out["kept_kernel"] in ("per-chunk", "hot")


def test_tier_compare_fast_leg():
    """``--tier-compare --fast`` (ISSUE 20): the tier-1 correctness leg
    of the heterogeneous-plane comparison — the blake2b64 device tier and
    the cpu tier both oracle-gated on digit-boundary ranges (long AND
    sub-block-tail payload shapes) before the tiny timed windows, with
    the JSON honest about the platform, the pallas rung probe, and what
    auto_tune keeps for the family (BENCH_pr20.json is the full-speed
    same-seed artifact; the --fast ratio is load-noisy, so no ratio
    assertion here)."""
    p = run_bench("--tier-compare", "--workload", "blake2b64", "--fast", "--cpu")
    assert p.returncode == 0, p.stderr[-2000:]
    lines = p.stdout.strip().splitlines()
    assert len(lines) == 1, lines
    out = json.loads(lines[0])
    assert out["metric"] == "tier_compare"
    assert out["workload"] == "blake2b64"
    assert out["device_tier"] == "xla"
    assert out["bitexact"] is True
    assert out["device_nps"] > 0 and out["cpu_nps"] > 0
    assert out["short_device_nps"] > 0 and out["short_cpu_nps"] > 0
    assert out["fast"] is True
    # Honesty fields: the pallas rung must be reported as probed (null
    # off-TPU/GPU — never silently assumed), and kept_kernel must record
    # exactly what auto_tune picks for the blake2b family on this host.
    assert "pallas_platform" in out
    assert out["auto_tune_factored"] == ("factored" in out["kept_kernel"])
    assert out["auto_tune_hot"] == ("hot" in out["kept_kernel"])


def test_cpu_bench_emits_one_valid_json_line():
    p = run_bench("--cpu")
    assert p.returncode == 0, p.stderr[-2000:]
    lines = p.stdout.strip().splitlines()
    assert len(lines) == 1, f"stdout must be exactly one JSON line: {lines}"
    out = json.loads(lines[0])
    assert out["metric"] == "nonces_per_sec_per_chip"
    assert out["unit"] == "nonces/s"
    assert out["value"] > 0
    assert out["vs_baseline"] == round(out["value"] / 1e9, 4)
    # Attribution fields (VERDICT round 1: numbers must be attributable).
    assert out["platform"] == "cpu"
    assert out["backend"] in ("native", "xla")
    assert "device_kind" in out
