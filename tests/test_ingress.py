"""Event-loop ingress tier-1 smoke (ISSUE 15): the asyncio serve front
end (`apps.server.AsyncIngress`) holds hundreds of live conns at a FLAT
process thread count — the axis the threaded facade stack is O(n) on —
while serving bit-exact through the unchanged gateway plane, and a solved
signature keeps answering with zero chunks assigned through the async
path.  The shared-loop sync facade (`lsp.shared_loop`) costs ONE thread
for N conns — the federation forwarder pool's new shape.

Both suites run with the BMT_SANITIZE=1 machinery armed: the ingress
loop joins the sanitizer's loop-shaped-resource graph (`ingress.loop.*`),
so a bridge callback that could ABBA-deadlock against the event lock —
or any off-lock policy-object access from the loop — raises here instead
of hanging production.
"""

import asyncio
import threading

import pytest

from bitcoin_miner_tpu import lsp
from bitcoin_miner_tpu.apps import client as client_mod
from bitcoin_miner_tpu.apps import miner as miner_mod
from bitcoin_miner_tpu.apps import server as server_mod
from bitcoin_miner_tpu.apps.scheduler import Scheduler
from bitcoin_miner_tpu.bitcoin.hash import min_hash_range
from bitcoin_miner_tpu.bitcoin.message import Message, MsgType
from bitcoin_miner_tpu.gateway import Gateway, ResultCache, SpanStore
from bitcoin_miner_tpu.utils import sanitize
from bitcoin_miner_tpu.utils.metrics import METRICS

pytestmark = pytest.mark.gateway

# Long epochs: hundreds of idle conns' keepalive traffic scales with
# 1/epoch, and nothing here probes loss timing.
PARAMS = lsp.Params(epoch_limit=8, epoch_millis=500, window_size=5)


async def _connect_n(port: int, n: int):
    return list(
        await asyncio.gather(
            *(
                lsp.AsyncClient.connect("127.0.0.1", port, PARAMS)
                for _ in range(n)
            )
        )
    )


async def _ask_all(conns, data: str, lo: int, hi: int):
    async def one(c):
        c.write(Message.request(data, lo, hi).marshal())
        while True:
            payload = await asyncio.wait_for(c.read(), 60)
            m = Message.unmarshal(payload)
            if m is not None and m.type == MsgType.RESULT:
                return (m.hash, m.nonce)

    return await asyncio.gather(*(one(c) for c in conns))


async def _close_all(conns):
    await asyncio.gather(
        *(asyncio.wait_for(c.close(), 5) for c in conns),
        return_exceptions=True,
    )


def test_async_ingress_conn_scale_thread_flat():
    """Hundreds of concurrent live conns on one ingress: the thread count
    does NOT grow with conns (the acceptance axis), every conn completes
    a bit-exact round trip, and the repeat wave of a solved signature
    assigns zero new chunks through the async path."""
    sanitize.force(True)
    sanitize.reset_order_graph()
    ingress = None
    lt = None
    conns: list = []
    try:
        engine = Gateway(
            Scheduler(min_chunk=500),
            cache=ResultCache(),
            spans=SpanStore(),
            rate=None,
        )
        ingress = server_mod.AsyncIngress(
            0, scheduler=engine, params=PARAMS, tick_interval=0.05
        ).start()
        mc = lsp.Client("127.0.0.1", ingress.port, PARAMS)
        threading.Thread(
            target=miner_mod.run_miner,
            args=(mc, miner_mod.make_search("cpu")),
            daemon=True,
        ).start()
        # Solve once, so the conn-liveness wave below is pure cache hits
        # (zero device work for the 240-way fan-in).
        c = lsp.Client("127.0.0.1", ingress.port, PARAMS)
        try:
            got = client_mod.request_once(c, "ingress", 2500, timeout=120)
        finally:
            c.close()
        want = min_hash_range("ingress", 0, 2500)
        assert got == want
        assigned_after_solve = METRICS.get("sched.chunks_assigned")

        lt = lsp.shared_loop("test-aclients")

        def run(coro):
            return asyncio.run_coroutine_threadsafe(coro, lt.loop).result(
                timeout=180
            )

        conns.extend(run(_connect_n(ingress.port, 120)))
        census_half = sanitize.thread_census()
        conns.extend(run(_connect_n(ingress.port, 120)))
        # The acceptance axis: +120 live conns, zero new threads — the
        # sanitizer census helper is the one flat-thread spelling
        # (ISSUE 19), and it names any offender instead of just counting.
        assert sanitize.threads_leaked(census_half, settle_s=2.0) == []
        assert ingress.conns_live() >= len(conns)
        # Every conn is genuinely live (full duplex round trip, oracle
        # bit-exact) ...
        results = run(_ask_all(conns, "ingress", 0, 2500))
        assert all(g == want for g in results)
        # ... and the whole wave was answered from the serving layer's
        # cache: zero chunks assigned past the initial solve.
        assert METRICS.get("sched.chunks_assigned") == assigned_after_solve
    finally:
        try:
            if conns:
                for s in range(0, len(conns), 80):
                    asyncio.run_coroutine_threadsafe(
                        _close_all(conns[s:s + 80]), lt.loop
                    ).result(timeout=30)
        finally:
            if lt is not None:
                lt.stop()
            if ingress is not None:
                ingress.close()
            sanitize.force(None)


def test_shared_loop_clients_cost_one_thread():
    """N sync-facade conns on one `lsp.shared_loop` cost exactly ONE loop
    thread (the federation forwarder pool's conn cache rides this), and
    closing a borrowed-loop client leaves the loop running."""
    sanitize.force(True)
    sanitize.reset_order_graph()
    server = lsp.Server(0, PARAMS)
    lt = None
    try:
        lt = lsp.shared_loop("test-shared")
        clients = [lsp.Client("127.0.0.1", server.port, PARAMS, loop=lt)]
        # Baseline AFTER the first conn: the loop thread plus asyncio's
        # lazily-spawned resolver-executor worker are one-time constants;
        # the claim under test is O(1) threads in CONNS.
        base = sanitize.thread_census()
        clients.extend(
            lsp.Client("127.0.0.1", server.port, PARAMS, loop=lt)
            for _ in range(5)
        )
        assert sanitize.threads_leaked(base) == []
        for c in clients:
            c.close()
        # The borrowed loop survives its clients: a fresh conn still works.
        c = lsp.Client("127.0.0.1", server.port, PARAMS, loop=lt)
        c.close()
        assert sanitize.threads_leaked(base, settle_s=2.0) == []
    finally:
        if lt is not None:
            lt.stop()
        server.close()
        sanitize.force(None)


def test_ingress_soak_loop_blocked_detector_quiet_on_green_fleet():
    """ISSUE 19 chaos-soak leg: repeated connect / solve / close waves
    on a green async-ingress fleet never trip the blocking-on-loop
    detector — the ``sanitize.loop_blocked`` counter stays flat — while
    the detector is provably LIVE on that very loop: a seeded
    ``sanitize.blocking`` probe scheduled onto the ingress loop raises
    ``LoopBlockedError`` and bumps the counter by exactly one."""
    sanitize.force(True)
    sanitize.reset_order_graph()
    ingress = None
    try:
        engine = Gateway(
            Scheduler(min_chunk=500),
            cache=ResultCache(),
            spans=SpanStore(),
            rate=None,
        )
        ingress = server_mod.AsyncIngress(
            0, scheduler=engine, params=PARAMS, tick_interval=0.05
        ).start()
        mc = lsp.Client("127.0.0.1", ingress.port, PARAMS)
        threading.Thread(
            target=miner_mod.run_miner,
            args=(mc, miner_mod.make_search("cpu")),
            daemon=True,
        ).start()
        before = METRICS.get("sanitize.loop_blocked")
        want = min_hash_range("soak", 0, 1500)
        for _ in range(3):
            c = lsp.Client("127.0.0.1", ingress.port, PARAMS)
            try:
                got = client_mod.request_once(c, "soak", 1500, timeout=120)
            finally:
                c.close()
            assert got == want
        # Green fleet: zero trips across the whole churn.
        assert METRICS.get("sanitize.loop_blocked") == before
        # ... and the detector is armed on this exact loop, so the quiet
        # above is evidence, not absence: a declared-blocking statement
        # scheduled ONTO the ingress loop must raise.
        caught: list = []
        done = threading.Event()

        def _probe() -> None:
            try:
                sanitize.blocking("soak.seeded_probe")
            except BaseException as e:
                caught.append(e)
            finally:
                done.set()

        ingress._loop.call_soon_threadsafe(_probe)
        assert done.wait(5)
        assert len(caught) == 1, caught
        assert isinstance(caught[0], sanitize.LoopBlockedError)
        assert METRICS.get("sanitize.loop_blocked") == before + 1
    finally:
        if ingress is not None:
            ingress.close()
        sanitize.force(None)
        sanitize.reset_order_graph()
