"""The serving gateway: coalescing, result cache, admission control (ISSUE 3).

Three layers of coverage, mirroring how the scheduler is tested:

- pure-unit: the admission primitives (token bucket, fair queue) and the
  result cache, including the torn-file persistence contract;
- event-level: a Gateway over a real Scheduler driven by ids + ``now``,
  no sockets — coalescing fan-out, cache-hit-zero-chunks, last-waiter
  cancellation into the orphan stash, shedding, the throttle queue
  draining as tokens refill, and the fair-queue delay bound for a client
  competing with a rate-limited flood;
- end-to-end: the gateway behind ``apps.server.serve`` over loopback LSP
  with real miner threads, duplicate-heavy traffic bit-exact vs the
  hashlib oracle and a repeat-submitted solved job answering with zero
  chunks assigned (the ISSUE 3 acceptance shape).
"""

import threading
import time

import pytest

from bitcoin_miner_tpu import lsp, lspnet
from bitcoin_miner_tpu.apps import client as client_mod
from bitcoin_miner_tpu.apps import miner as miner_mod
from bitcoin_miner_tpu.apps import server as server_mod
from bitcoin_miner_tpu.apps.scheduler import Scheduler
from bitcoin_miner_tpu.bitcoin.hash import min_hash_range
from bitcoin_miner_tpu.bitcoin.message import Message, MsgType
from bitcoin_miner_tpu.gateway import (
    FairQueue,
    Gateway,
    ResultCache,
    SpanStore,
    TokenBucket,
)
from bitcoin_miner_tpu.utils.metrics import METRICS

pytestmark = pytest.mark.gateway

DATA = "cmu440"


def results(actions):
    return [(cid, m) for cid, m in actions if m.type == MsgType.RESULT]


def requests(actions):
    return [(cid, m) for cid, m in actions if m.type == MsgType.REQUEST]


# --------------------------------------------------------------- primitives


class TestTokenBucket:
    def test_burst_then_refill(self):
        b = TokenBucket(rate=1.0, burst=2.0, now=0.0)
        assert b.try_take(0.0) and b.try_take(0.0)  # the burst allowance
        assert not b.try_take(0.0)  # empty
        assert not b.try_take(0.5)  # half a token is not a token
        assert b.try_take(1.0)  # one second -> one token
        assert not b.try_take(1.0)

    def test_refill_caps_at_burst(self):
        b = TokenBucket(rate=100.0, burst=3.0, now=0.0)
        for _ in range(3):
            assert b.try_take(1000.0)
        assert not b.try_take(1000.0)

    def test_clock_never_runs_backward(self):
        b = TokenBucket(rate=1.0, burst=1.0, now=10.0)
        assert b.try_take(10.0)
        b.try_take(5.0)  # stale now must not mint tokens or corrupt state
        assert b.try_take(11.0)


class TestFairQueue:
    def test_fifo_within_one_key(self):
        q = FairQueue()
        q.push("a", (1,))
        q.push("a", (2,))
        assert q.pop() == ("a", (1,))
        assert q.pop() == ("a", (2,))
        assert q.pop() is None

    def test_interleaves_a_flood_with_a_singleton(self):
        q = FairQueue()
        for i in range(10):
            q.push("flood", (i,))
        q.push("quiet", ("q",))
        popped = [q.pop()[0] for _ in range(3)]
        # The singleton must surface within the first two pops (its vt
        # starts at the active minimum), not behind the whole flood.
        assert "quiet" in popped[:2]

    def test_weights_bias_the_share(self):
        q = FairQueue()
        for i in range(20):
            q.push("heavy", (i,), weight=3.0)
            q.push("light", (i,), weight=1.0)
        first12 = [q.pop()[0] for _ in range(12)]
        assert first12.count("heavy") >= 8  # ~3:1, not 1:1

    def test_remove_where(self):
        q = FairQueue()
        q.push("a", (1, "x"))
        q.push("a", (2, "y"))
        q.push("b", (3, "x"))
        assert q.remove_where(lambda item: item[1] == "x") == 2
        assert len(q) == 1
        assert q.pop() == ("a", (2, "y"))


class TestResultCache:
    def test_lru_eviction_and_counters(self):
        METRICS.reset()
        c = ResultCache(capacity=2)
        c.put(("a", 0, 9), 1, 1)
        c.put(("b", 0, 9), 2, 2)
        c.get(("a", 0, 9))  # freshen a: b is now the LRU victim
        c.put(("c", 0, 9), 3, 3)
        assert c.get(("b", 0, 9)) is None
        assert c.get(("a", 0, 9)) == (1, 1)
        assert c.get(("c", 0, 9)) == (3, 3)
        assert METRICS.get("gateway.cache_evictions") == 1

    def test_capacity_zero_disables(self):
        c = ResultCache(capacity=0)
        c.put(("a", 0, 9), 1, 1)
        assert c.get(("a", 0, 9)) is None
        assert len(c) == 0

    def test_persistence_roundtrip(self, tmp_path):
        path = str(tmp_path / "cache.json")
        c = ResultCache(capacity=8, path=path)
        c.put((DATA, 0, 99), 555, 42)
        c.put(("other", 5, 9), 7, 6)
        c.save(path)
        c2 = ResultCache(capacity=8, path=path)
        assert c2.get((DATA, 0, 99)) == (555, 42)
        assert c2.get(("other", 5, 9)) == (7, 6)

    def test_flush_is_dirty_gated(self, tmp_path):
        """Persistence rides the shell's tick: flush() hands back state
        only when something changed since the last snapshot/save."""
        c = ResultCache(capacity=8, path=str(tmp_path / "c.json"))
        assert c.flush() is None  # clean at birth
        c.put((DATA, 0, 99), 555, 42)
        state = c.flush()
        assert state is not None
        assert state["entries"] == [[DATA, 0, 99, 555, 42]]
        assert c.flush() is None  # flush cleared the flag
        c.get((DATA, 0, 99))
        assert c.flush() is None  # reads do not dirty

    def test_torn_file_starts_empty(self, tmp_path):
        path = tmp_path / "cache.json"
        path.write_text('{"version": 1, "entries": [["a", 0')  # truncated
        c = ResultCache(capacity=8, path=str(path))
        assert len(c) == 0

    def test_bad_rows_skipped_not_fatal(self, tmp_path):
        path = tmp_path / "cache.json"
        path.write_text(
            '{"version": 1, "entries": [["good", 0, 9, 1, 2], '
            '["short"], [3, 0, 9, 1, 2], ["bool", 0, 9, true, 2]]}'
        )
        c = ResultCache(capacity=8, path=str(path))
        assert len(c) == 1
        assert c.get(("good", 0, 9)) == (1, 2)


# ------------------------------------------------------------- event-level


def make_gateway(**kw):
    kw.setdefault("rate", None)
    sched_kw = kw.pop("sched", {})
    sched_kw.setdefault("validate_results", False)
    sched_kw.setdefault("min_chunk", 100)
    return Gateway(Scheduler(**sched_kw), **kw)


# ------------------------------------------------------- workload slice
# A representative cache/span/coalesce slice parameterized over EVERY
# registered range-fold workload (ISSUE 9), with result validation ON so
# each workload's own oracle gates the folds: the serving layer is
# workload-blind by construction, and this pins it.


from bitcoin_miner_tpu import workloads as workloads_mod  # noqa: E402

WORKLOAD_NAMES = workloads_mod.names()


@pytest.mark.workloads
@pytest.mark.parametrize("wname", WORKLOAD_NAMES)
class TestWorkloadServingSlice:
    def _gateway(self, wname, **sched_kw):
        w = workloads_mod.get(wname)
        sched_kw.setdefault("min_chunk", 100)
        sched_kw.setdefault("max_chunk", 100)
        return w, Gateway(Scheduler(workload=w, **sched_kw), rate=None)

    def test_coalesced_twins_fan_out_validated(self, wname):
        METRICS.reset()
        w, g = self._gateway(wname)
        g.miner_joined(1)
        acts = g.client_request(10, DATA, 0, 99, now=0.0)
        assert len(requests(acts)) == 1
        assert g.client_request(11, DATA, 0, 99, now=0.0) == []  # coalesced
        h, n = w.min_range(DATA, 0, 99)
        done = results(g.result(1, hash_=h, nonce=n, now=1.0))
        assert sorted(cid for cid, _ in done) == [10, 11]
        assert all((m.hash, m.nonce) == (h, n) for _, m in done)
        assert METRICS.get("gateway.coalesced") == 1
        # A WRONG workload's answer for the same nonce must be rejected
        # by this workload's validation (unless the families collide).
        other = next(
            (workloads_mod.get(o) for o in WORKLOAD_NAMES if o != wname)
        )
        bad = other.hash_nonce(DATA, 50)
        if bad != w.hash_nonce(DATA, 50):
            g2_w, g2 = self._gateway(wname)
            g2.miner_joined(1)
            g2.client_request(10, DATA, 0, 99, now=0.0)
            assert results(g2.result(1, hash_=bad, nonce=50, now=1.0)) == []
            assert METRICS.get("sched.results_rejected") == 1

    def test_solved_job_cache_hit_zero_chunks(self, wname):
        METRICS.reset()
        w, g = self._gateway(wname)
        g.miner_joined(1)
        g.client_request(10, DATA, 0, 99, now=0.0)
        h, n = w.min_range(DATA, 0, 99)
        g.result(1, hash_=h, nonce=n, now=1.0)
        assigned = METRICS.get("sched.chunks_assigned")
        acts = g.client_request(20, DATA, 0, 99, now=2.0)
        assert results(acts) == [(20, acts[0][1])]
        assert (acts[0][1].hash, acts[0][1].nonce) == (h, n)
        assert METRICS.get("sched.chunks_assigned") == assigned
        assert METRICS.get("gateway.cache_hits") == 1

    def test_covered_subrange_span_answer(self, wname):
        METRICS.reset()
        w, g = self._gateway(wname)
        g.miner_joined(1, now=0.0)
        g.client_request(10, DATA, 0, 299, now=0.0)
        # Three validated 100-nonce chunks; each span's fold is this
        # workload's true per-chunk argmin, so every span is answerable
        # for any query containing its argmin.
        folds = [w.min_range(DATA, lo, lo + 99) for lo in (0, 100, 200)]
        for t, (h, n) in enumerate(folds):
            g.result(1, hash_=h, nonce=n, now=1.0 + t)
        assigned = METRICS.get("sched.chunks_assigned")
        qlo = min(n for _h, n in folds)
        qhi = max(n for _h, n in folds)
        if (qlo, qhi) == (0, 299):
            pytest.skip("degenerate argmin geometry for this workload/data")
        acts = g.client_request(20, DATA, qlo, qhi, now=5.0)
        got = results(acts)
        assert got, "covered sub-range should answer from spans"
        want = min(folds)
        assert (got[0][1].hash, got[0][1].nonce) == want
        assert METRICS.get("sched.chunks_assigned") == assigned
        assert METRICS.get("gateway.span_hits") == 1
        # Bit-exact against the workload's own oracle over the sub-range.
        assert want == w.min_range(DATA, qlo, qhi)


class TestCoalescing:
    def test_twin_requests_share_one_sweep_and_fan_out(self):
        METRICS.reset()
        g = make_gateway()
        g.miner_joined(1)
        acts = g.client_request(10, DATA, 0, 99, now=0.0)
        assert len(requests(acts)) == 1  # one chunk stream started
        assert g.client_request(11, DATA, 0, 99, now=0.0) == []  # coalesced
        assert g.client_request(12, DATA, 0, 99, now=0.0) == []
        done = results(g.result(1, hash_=555, nonce=42, now=1.0))
        assert sorted(cid for cid, _ in done) == [10, 11, 12]
        assert all(m.hash == 555 and m.nonce == 42 for _, m in done)
        assert METRICS.get("gateway.coalesced") == 2
        assert METRICS.get("gateway.fanout") == 2
        assert METRICS.get("sched.jobs_completed") == 1  # ONE sweep

    def test_different_signatures_do_not_coalesce(self):
        g = make_gateway()
        g.miner_joined(1)
        g.client_request(10, DATA, 0, 99, now=0.0)
        acts = g.client_request(11, DATA, 0, 199, now=0.0)  # different range
        assert g.stats()["gw_inflight"] == 2

    def test_waiter_death_leaves_twin_running(self):
        g = make_gateway()
        g.miner_joined(1)
        g.client_request(10, DATA, 0, 99, now=0.0)
        g.client_request(11, DATA, 0, 99, now=0.0)
        assert g.lost(10, now=0.5) == []  # first waiter dies
        done = results(g.result(1, hash_=555, nonce=42, now=1.0))
        assert [cid for cid, _ in done] == [11]  # survivor still answered

    def test_last_waiter_death_cancels_and_stashes_progress(self):
        METRICS.reset()
        g = make_gateway(sched={"min_chunk": 100, "max_chunk": 100,
                                "validate_results": False})
        g.miner_joined(1, now=0.0)
        g.client_request(10, DATA, 0, 299, now=0.0)
        g.result(1, hash_=700, nonce=5, now=0.5)  # [0,99] swept
        g.lost(10, now=1.0)  # last waiter gone -> job cancelled
        assert g.stats()["gw_inflight"] == 0
        assert METRICS.get("sched.jobs_orphaned") == 1
        # A resubmission RESUMES the sweep instead of restarting it.
        acts = g.client_request(20, DATA, 0, 299, now=2.0)
        assert METRICS.get("sched.jobs_resumed") == 1
        # The miner still holds the orphan's stale chunks (depth-2 queue),
        # so nothing dispatches until those drain; the first fresh chunk
        # handed out starts at 100 — [0,99] is never re-swept.
        assert requests(acts) == []
        acts = g.result(1, hash_=701, nonce=105, now=2.1)  # stale, jobless
        req = requests(acts)
        assert req and req[0][1].lower == 100

    def test_repeat_submit_on_same_conn_ignored(self):
        g = make_gateway()
        g.miner_joined(1)
        g.client_request(10, DATA, 0, 99, now=0.0)
        assert g.client_request(10, DATA, 0, 199, now=0.0) == []
        assert g.stats()["gw_inflight"] == 1

    def test_poison_range_rejected_stateless(self):
        g = make_gateway()
        assert g.client_request(10, DATA, 5, 1 << 64, now=0.0) == []
        assert g.stats()["gw_inflight"] == 0
        assert g.stats()["gw_waiters"] == 0


class TestCacheFront:
    def test_solved_job_answers_with_zero_chunks(self):
        METRICS.reset()
        g = make_gateway()
        g.miner_joined(1)
        g.client_request(10, DATA, 0, 99, now=0.0)
        g.result(1, hash_=555, nonce=42, now=1.0)
        assigned = METRICS.get("sched.chunks_assigned")
        acts = g.client_request(20, DATA, 0, 99, now=2.0)
        assert results(acts) == [(20, acts[0][1])]
        assert acts[0][1].hash == 555 and acts[0][1].nonce == 42
        # The acceptance bar: the repeat assigned NO chunk at all.
        assert METRICS.get("sched.chunks_assigned") == assigned
        assert METRICS.get("gateway.cache_hits") == 1

    def test_empty_range_result_is_cached_consistently(self):
        g = make_gateway()
        a1 = g.client_request(10, DATA, 5, 4, now=0.0)  # empty range
        a2 = g.client_request(11, DATA, 5, 4, now=1.0)  # cache hit
        assert results(a1)[0][1].hash == results(a2)[0][1].hash == 0

    def test_checkpoint_passthrough_roundtrip(self):
        g = make_gateway(sched={"min_chunk": 100, "max_chunk": 100,
                                "validate_results": False})
        g.miner_joined(1, now=0.0)
        g.client_request(10, DATA, 0, 299, now=0.0)
        g.result(1, hash_=700, nonce=5, now=0.5)
        state = g.checkpoint()
        [j] = state["jobs"]
        assert j["best"] == [700, 5]
        g2 = make_gateway()
        g2.load_checkpoint(state)
        assert g2.checkpoint()["jobs"] == state["jobs"]


class TestIntervalServing:
    """The interval-algebra result store on the serving path (ISSUE 5):
    solved chunk spans answer sub-range queries; partial coverage sweeps
    only the uncovered remainder and merges via the scheduler seed."""

    def _solve_three_chunks(self, g, nonces=(50, 150, 210)):
        """One [0,299] job swept as three 100-nonce chunks with controlled
        argmins; returns after the job completed and spans recorded."""
        g.miner_joined(1, now=0.0)
        g.client_request(10, DATA, 0, 299, now=0.0)
        g.result(1, hash_=700, nonce=nonces[0], now=1.0)
        g.result(1, hash_=600, nonce=nonces[1], now=2.0)
        g.result(1, hash_=650, nonce=nonces[2], now=3.0)

    def test_covered_subrange_answers_with_zero_chunks(self):
        METRICS.reset()
        g = make_gateway(sched={"min_chunk": 100, "max_chunk": 100,
                                "validate_results": False})
        self._solve_three_chunks(g)
        assigned = METRICS.get("sched.chunks_assigned")
        # A NEVER-ISSUED strict sub-range, fully covered by solved spans
        # (every overlapping span's argmin lies inside it).
        acts = g.client_request(20, DATA, 50, 249, now=4.0)
        assert results(acts) == [(20, acts[0][1])]
        assert (acts[0][1].hash, acts[0][1].nonce) == (600, 150)
        assert METRICS.get("sched.chunks_assigned") == assigned
        assert METRICS.get("gateway.span_hits") == 1
        assert METRICS.get("gateway.nonces_saved") == 200
        # The span answer landed in the exact cache: a repeat is a plain
        # cache hit even if the spans are later evicted.
        acts = g.client_request(21, DATA, 50, 249, now=5.0)
        assert METRICS.get("gateway.cache_hits") == 1

    def test_argmin_outside_subrange_is_not_answered(self):
        """A span whose minimum lives OUTSIDE the query proves nothing
        about it: the portion must re-sweep (bit-exactness over reuse)."""
        METRICS.reset()
        g = make_gateway(sched={"min_chunk": 300, "max_chunk": 300,
                                "validate_results": False})
        g.miner_joined(1, now=0.0)
        g.client_request(10, DATA, 0, 299, now=0.0)
        g.result(1, hash_=700, nonce=290, now=1.0)  # argmin near the top
        acts = g.client_request(20, DATA, 0, 99, now=2.0)
        # Not answerable from the span: a real sweep starts instead.
        assert results(acts) == []
        assert requests(acts) and requests(acts)[0][1].lower == 0
        assert METRICS.get("gateway.span_hits") == 0

    def test_partial_coverage_sweeps_only_the_gap_and_merges(self):
        METRICS.reset()
        g = make_gateway(sched={"min_chunk": 100, "max_chunk": 100,
                                "validate_results": False})
        self._solve_three_chunks(g)
        acts = g.client_request(20, DATA, 0, 499, now=4.0)
        # Only the uncovered remainder [300, 499] is carved into chunks.
        req = requests(acts)
        assert req and all(m.lower >= 300 for _, m in req)
        assert METRICS.get("gateway.span_partial") == 1
        assert METRICS.get("gateway.nonces_saved") == 300
        # Remainder results fold with the span seed: the final Result is
        # the whole range's minimum even though [0,299] was never re-swept.
        g.result(1, hash_=640, nonce=350, now=5.0)
        done = results(g.result(1, hash_=660, nonce=450, now=6.0))
        assert done == [(20, done[0][1])]
        assert (done[0][1].hash, done[0][1].nonce) == (600, 150)
        # The remainder's own chunks became spans too: a strict sub-range
        # of the extended sweep (containing every boundary argmin) is now
        # fully covered...
        assert g.spans.cover(DATA, 50, 459)[1] == []
        # ...while one cutting a boundary span away from its argmin (450)
        # correctly keeps that portion in the gap list.
        assert g.spans.cover(DATA, 50, 449)[1] == [(400, 449)]

    def test_merged_result_bit_exact_vs_oracle(self):
        """With real hashes and validation ON: solve [0,399] honestly,
        then request the chunk-straddling [100,499] — the spans answer
        [100,399] (those chunks sit fully inside), only [400,499] sweeps,
        and the merged Result equals a from-scratch full sweep."""
        g = Gateway(Scheduler(min_chunk=100, max_chunk=100), rate=None)
        g.miner_joined(1, now=0.0)
        outstanding = {}  # chunk assignments we owe honest answers to

        def serve_requests(acts, now):
            done = []
            for cid, m in acts:
                if m.type == MsgType.REQUEST:
                    outstanding[(m.lower, m.upper)] = cid
            while outstanding:
                (lo, hi), cid = next(iter(outstanding.items()))
                del outstanding[(lo, hi)]
                h, n = min_hash_range(DATA, lo, hi)
                now += 1.0
                done += serve_requests(g.result(cid, h, n, now), now)
            return done + results(acts)

        first = serve_requests(g.client_request(10, DATA, 0, 399, now=0.0), 0.0)
        assert [(c, m.hash, m.nonce) for c, m in first if c == 10] == [
            (10, *min_hash_range(DATA, 0, 399))
        ]
        swept_before = METRICS.get("sched.nonces_swept")
        second = serve_requests(g.client_request(20, DATA, 100, 499, now=50.0), 50.0)
        assert [(c, m.hash, m.nonce) for c, m in second if c == 20] == [
            (20, *min_hash_range(DATA, 100, 499))
        ]
        # Only the uncovered remainder [400,499] was re-swept.
        assert METRICS.get("sched.nonces_swept") - swept_before == 100

    def test_queued_request_replans_at_admit_time(self):
        """Spans solved while a request waits in the admission queue are
        visible at dispatch: a fully covered twin resolves from the queue
        with no slot at all.  (A DIFFERENT data key, so the request
        queues on the full slot rather than span-wait-parking on the
        in-flight sweep — parking is TestInflightSpanWait's subject.)"""
        METRICS.reset()
        g = make_gateway(max_active=1,
                         sched={"min_chunk": 300, "max_chunk": 300,
                                "validate_results": False})
        g.miner_joined(1, now=0.0)
        g.client_request(10, DATA, 0, 299, now=0.0)
        g.client_request(11, "otherdata", 100, 200, now=0.1)  # queued: slot full
        assert g.stats()["gw_queued"] == 1
        g.result(1, hash_=700, nonce=150, now=1.0)
        # 10 answered; 11 admitted into the freed slot and sweeping.
        g.miner_joined(2, now=1.1)
        acts = g.client_request(12, "otherdata", 100, 200, now=1.2)
        assert acts == []  # coalesced into 11's now-running sweep
        assert g.stats()["gw_queued"] == 0
        done = results(g.result(1, hash_=500, nonce=170, now=2.0))
        assert sorted(cid for cid, _ in done) == [11, 12]
        # Full-coverage replan FROM THE QUEUE: a request overlapping but
        # not inside the running sweep (so it queues, not parks) becomes
        # fully covered by the completion's span + an older span, and
        # resolves at admit time with zero chunks.
        METRICS.reset()
        g2 = make_gateway(max_active=1,
                          sched={"min_chunk": 300, "max_chunk": 300,
                                 "validate_results": False})
        g2.miner_joined(1, now=0.0)
        g2.client_request(9, DATA, 300, 500, now=0.0)
        g2.result(1, hash_=300, nonce=400, now=0.5)  # span [300,500]@400
        g2.client_request(10, DATA, 0, 299, now=0.6)
        # [250,450] is NOT inside [0,299]: it queues on the full slot.
        g2.client_request(11, DATA, 250, 450, now=0.7)
        assert g2.stats()["gw_queued"] == 1
        acts = g2.result(1, hash_=700, nonce=270, now=1.0)
        # Completion answers 10 AND resolves 11 from the queue: spans
        # [0,299]@270 + [300,500]@400 fully cover [250,450].
        assert sorted(cid for cid, _ in results(acts)) == [10, 11]
        assert g2.stats()["gw_queued"] == 0
        assert METRICS.get("gateway.span_hits") == 1

    def test_spans_disabled_gateway_still_correct(self):
        METRICS.reset()
        g = make_gateway(spans=SpanStore(capacity=0),
                         sched={"min_chunk": 100, "max_chunk": 100,
                                "validate_results": False})
        self._solve_three_chunks(g)
        assert g.sched.record_spans is False  # export never armed
        acts = g.client_request(20, DATA, 50, 249, now=4.0)
        assert results(acts) == []  # no span answers: a fresh sweep
        assert requests(acts)
        assert METRICS.get("gateway.span_hits") == 0

    def test_gap_job_orphan_stash_is_whole_range_correct(self):
        """A gap job dying mid-remainder stashes (seed-folded best,
        remaining gaps) under the FULL signature: the resumed twin sweeps
        only what was never covered by spans nor by the dead job."""
        g = make_gateway(sched={"min_chunk": 100, "max_chunk": 100,
                                "validate_results": False})
        self._solve_three_chunks(g)
        g.client_request(20, DATA, 0, 499, now=4.0)  # gaps [300,499]
        g.result(1, hash_=640, nonce=350, now=5.0)  # [300,399] swept
        g.lost(20, now=6.0)  # last waiter dies: orphan-stash the gap job
        state = g.checkpoint()
        [j] = [j for j in state["jobs"] if (j["lower"], j["upper"]) == (0, 499)]
        assert j["best"] == [600, 150]  # the span seed survived the stash
        assert j["remaining"] == [[400, 499]]


class TestInflightSpanWait:
    """Span-aware coalescing of IN-FLIGHT jobs (ISSUE 8 satellite): a
    sub-range request fully inside a currently-running sweep parks on
    that sweep's completion instead of re-sweeping the overlap, then
    replans against the freshly recorded chunk spans."""

    def _gateway(self, **kw):
        return make_gateway(sched={"min_chunk": 100, "max_chunk": 100,
                                   "validate_results": False}, **kw)

    def test_subrange_parks_then_answers_with_zero_extra_chunks(self):
        METRICS.reset()
        g = self._gateway()
        g.miner_joined(1, now=0.0)
        g.client_request(10, DATA, 0, 299, now=0.0)
        assigned = METRICS.get("sched.chunks_assigned")
        # Fully inside the running sweep: parks, no new chunks, no queue.
        assert g.client_request(20, DATA, 50, 249, now=0.1) == []
        assert METRICS.get("gateway.inflight_span_waits") == 1
        assert METRICS.get("sched.chunks_assigned") == assigned
        assert g.stats()["gw_span_waits"] == 1
        g.result(1, hash_=700, nonce=50, now=1.0)
        g.result(1, hash_=600, nonce=150, now=2.0)
        acts = g.result(1, hash_=650, nonce=210, now=3.0)
        # The completion answers BOTH: 10 with the full range's min, 20
        # from the chunk spans (every boundary argmin inside [50,249]).
        done = dict(results(acts))
        assert (done[10].hash, done[10].nonce) == (600, 150)
        assert (done[20].hash, done[20].nonce) == (600, 150)
        # Every chunk assigned belonged to the ONE super sweep (3×100);
        # the parked request cost zero device work of its own.
        assert METRICS.get("sched.chunks_assigned") == 3
        assert g.stats()["gw_span_waits"] == 0

    def test_release_sweeps_only_unanswerable_sliver(self):
        """A boundary chunk whose argmin falls OUTSIDE the parked range
        cannot answer its portion: the release submits just that sliver,
        seeded with the answered portions' fold."""
        METRICS.reset()
        g = self._gateway()
        g.miner_joined(1, now=0.0)
        g.client_request(10, DATA, 0, 299, now=0.0)
        assert g.client_request(20, DATA, 50, 249, now=0.1) == []
        g.result(1, hash_=700, nonce=50, now=1.0)
        g.result(1, hash_=600, nonce=150, now=2.0)
        # Last chunk's argmin (290) is outside [50,249]: its [200,249]
        # portion is not answerable and must sweep.
        acts = g.result(1, hash_=650, nonce=290, now=3.0)
        done = dict(results(acts))
        assert 10 in done and 20 not in done
        req = requests(acts)
        assert [(m.lower, m.upper) for _, m in req] == [(200, 249)]
        assert METRICS.get("gateway.span_partial") == 1
        done2 = dict(results(g.result(1, hash_=660, nonce=230, now=4.0)))
        assert (done2[20].hash, done2[20].nonce) == (600, 150)

    def test_parked_waiter_death_leaves_sweep_alone(self):
        METRICS.reset()
        g = self._gateway()
        g.miner_joined(1, now=0.0)
        g.client_request(10, DATA, 0, 299, now=0.0)
        g.client_request(20, DATA, 50, 249, now=0.1)
        assert g.lost(20, now=0.5) == []
        assert g.stats()["gw_span_waits"] == 0
        g.result(1, hash_=700, nonce=50, now=1.0)
        g.result(1, hash_=600, nonce=150, now=2.0)
        acts = g.result(1, hash_=650, nonce=210, now=3.0)
        assert [cid for cid, _ in results(acts)] == [10]  # sweep unharmed

    def test_cancelled_sweep_resubmits_parked_waiters(self):
        """The covering sweep's last primary waiter dies: the sweep
        cancels into the orphan stash, and each parked sub-range request
        is replanned as its own job — the chunks the sweep DID finish
        answer as spans, only the rest sweeps."""
        METRICS.reset()
        g = self._gateway()
        g.miner_joined(1, now=0.0)
        g.client_request(10, DATA, 0, 299, now=0.0)
        g.client_request(20, DATA, 50, 249, now=0.1)
        g.result(1, hash_=700, nonce=50, now=1.0)  # [0,99] solved
        g.lost(10, now=2.0)  # last primary waiter gone: cancel
        # 20 was resubmitted as its own job: [50,99] answers from the
        # solved chunk's span, only [100,249] needs sweeping.  The miner
        # is still draining the DEAD sweep's pipelined chunks, so the new
        # job's dispatches ride the next completions.
        assert g.stats()["gw_span_waits"] == 0
        assert g.stats()["jobs"] == 1  # the resubmitted remainder job
        acts = g.result(1, hash_=600, nonce=150, now=3.0)  # dead [100,199]
        req = requests(acts)
        assert req and all(100 <= m.lower and m.upper <= 249 for _, m in req)
        done = {}
        # FIFO: the dead sweep's second pipelined chunk drains first
        # (discarded — no job), then the remainder job's two chunks.
        for h, n, t in ((610, 260, 4.0), (620, 170, 5.0), (660, 230, 6.0)):
            done.update(dict(results(g.result(1, hash_=h, nonce=n, now=t))))
        assert (done[20].hash, done[20].nonce) == (620, 170)

    def test_no_parking_with_spans_disabled(self):
        """Without the interval store the wait would end in a full
        re-sweep anyway: the request runs as its own job immediately."""
        METRICS.reset()
        g = make_gateway(spans=SpanStore(capacity=0),
                         sched={"min_chunk": 100, "max_chunk": 100,
                                "validate_results": False})
        g.miner_joined(1, now=0.0)
        g.miner_joined(2, now=0.0)
        g.client_request(10, DATA, 0, 299, now=0.0)
        acts = g.client_request(20, DATA, 50, 249, now=0.1)
        assert requests(acts)  # its own sweep, right now
        assert METRICS.get("gateway.inflight_span_waits") == 0

    def test_parked_conn_is_one_job_like_everyone(self):
        g = self._gateway()
        g.miner_joined(1, now=0.0)
        g.client_request(10, DATA, 0, 299, now=0.0)
        g.client_request(20, DATA, 50, 249, now=0.1)
        assert g.client_request(20, "other", 0, 99, now=0.2) == []
        assert g.miner_joined(20, now=0.3) == []  # role confusion refused


class TestSpeculativePrefill:
    """Speculative span prefill (ISSUE 10): an idle fleet sweeps gaps and
    extensions adjacent to HOT spans under a near-zero-weight tenant, so
    future overlapping queries answer fully-covered; any real request
    preempts the speculation outright."""

    def _hot_solved_gateway(self, prefill=100):
        """[0,299] solved as three 100-nonce chunks, then a covered
        sub-range query marks DATA hot; fleet idle afterwards."""
        g = make_gateway(
            sched={"min_chunk": 100, "max_chunk": 100,
                   "validate_results": False},
            prefill=prefill,
        )
        g.miner_joined(1, now=0.0)
        g.client_request(10, DATA, 0, 299, now=0.0)
        g.result(1, hash_=700, nonce=50, now=1.0)
        g.result(1, hash_=600, nonce=150, now=2.0)
        g.result(1, hash_=650, nonce=210, now=3.0)
        acts = g.client_request(20, DATA, 50, 249, now=4.0)  # span hit: hot
        assert results(acts)
        return g

    def test_idle_fleet_prefills_extension_of_hot_span(self):
        METRICS.reset()
        g = self._hot_solved_gateway()
        acts = g.tick(5.0)
        req = requests(acts)
        # The speculative sweep extends past the hot span's top.
        assert [(m.lower, m.upper) for _, m in req] == [(300, 399)]
        assert req[0][0] == 1  # dispatched to the idle miner
        assert METRICS.get("gateway.prefill_jobs") == 1
        assert METRICS.get("sched.prefill_chunks") == 1
        # One speculation in flight at a time.
        assert g.tick(5.1) == []

    def test_prefill_result_enters_spans_and_overlap_answers_zero_chunks(self):
        METRICS.reset()
        g = self._hot_solved_gateway()
        g.tick(5.0)
        # The idle miner completes the speculative chunk: no waiter to
        # serve, the fold lands in the span store AND the exact cache.
        acts = g.result(1, hash_=800, nonce=300, now=6.0)
        assert results(acts) == []  # no client ever asked for it
        assigned = METRICS.get("sched.chunks_assigned")
        # An overlapping query past the originally requested range: fully
        # covered by real spans + the speculative extension.
        acts = g.client_request(30, DATA, 50, 349, now=7.0)
        assert results(acts) == [(30, acts[-1][1])]
        assert (acts[-1][1].hash, acts[-1][1].nonce) == (600, 150)
        assert METRICS.get("sched.chunks_assigned") == assigned

    def test_real_request_preempts_prefill_outright(self):
        METRICS.reset()
        g = self._hot_solved_gateway()
        g.tick(5.0)  # speculation dispatched to the lone miner
        # A real request for OTHER data: the prefill job is cancelled NOW
        # (not merely outscheduled) and the real sweep gets the miner.
        acts = g.client_request(40, "other-data", 0, 99, now=5.5)
        req = requests(acts)
        assert [m.data for _, m in req] == ["other-data"]
        assert METRICS.get("gateway.prefill_preempted") == 1
        assert g.sched.stats()["jobs"] == 1  # only the real job remains
        # The preempted chunk's eventual Result is stale (its synthetic
        # client is lost): ignored, miner back to work.
        assert results(g.result(1, hash_=800, nonce=300, now=6.0)) == []

    def test_prefill_respects_queued_and_inflight_work(self):
        METRICS.reset()
        g = self._hot_solved_gateway()
        g.client_request(50, DATA, 0, 999, now=5.0)  # real work in flight
        assert g.tick(5.1) == []  # busy fleet: no speculation
        assert METRICS.get("gateway.prefill_jobs") == 0

    def test_prefill_disabled_by_default(self):
        METRICS.reset()
        g = self._hot_solved_gateway(prefill=0)
        assert g.tick(5.0) == []
        assert METRICS.get("gateway.prefill_jobs") == 0

    def test_prefill_idle_dwell_gates_speculation(self):
        """A sub-dwell gap between requests is not idleness: speculation
        waits for prefill_idle_s of CONTINUOUS idle, and real work
        restarts the clock."""
        METRICS.reset()
        g = make_gateway(
            sched={"min_chunk": 100, "max_chunk": 100,
                   "validate_results": False},
            prefill=100, prefill_idle_s=2.0,
        )
        g.miner_joined(1, now=0.0)
        g.client_request(10, DATA, 0, 299, now=0.0)
        g.result(1, hash_=700, nonce=50, now=1.0)
        g.result(1, hash_=600, nonce=150, now=2.0)
        g.result(1, hash_=650, nonce=210, now=3.0)
        g.client_request(20, DATA, 50, 249, now=4.0)  # hot
        assert g.tick(5.0) == []  # dwell clock starts here
        assert g.tick(6.0) == []  # 1.0 s idle < 2.0 s dwell
        # Real work mid-dwell restarts the clock entirely.
        g.client_request(30, DATA, 0, 399, now=6.5)
        g.tick(6.6)
        g.result(1, hash_=640, nonce=350, now=7.0)  # [300,399] sweep done
        assert g.tick(8.0) == []  # first idle tick: dwell restarts here
        assert g.tick(9.9) == []  # 1.9 s idle < 2.0 s dwell
        acts = g.tick(10.1)  # 2.1 s of continuous idle: speculate
        assert requests(acts)
        assert METRICS.get("gateway.prefill_jobs") == 1

    def test_preempted_extension_refunds_unswept_budget(self):
        """prefill_target charges the whole planned extension up front;
        a preemption before any chunk lands must refund it, or a request
        cadence that keeps interrupting speculation burns the per-key cap
        (default 8×size) without sweeping a nonce and permanently
        disables prefill for exactly the hot keys it targets."""
        METRICS.reset()
        g = self._hot_solved_gateway()
        now = 5.0
        for i in range(10):  # 10 cycles > the 8-cycle cap
            acts = g.tick(now)
            req = requests(acts)
            # Nothing ever completes, so the planned extension never moves.
            assert [(m.lower, m.upper) for _, m in req] == [(300, 399)], i
            # Real request preempts before the speculative chunk lands.
            acts = g.client_request(40 + i, f"other-{i}", 0, 99, now + 0.1)
            assert requests(acts)
            now += 1.0
            assert results(g.result(1, hash_=800, nonce=300, now=now)) == []
            acts = g.result(1, hash_=900 + i, nonce=9, now=now + 0.1)
            assert [cid for cid, _ in results(acts)] == [40 + i]
            now += 1.0
        assert METRICS.get("gateway.prefill_jobs") == 10
        assert METRICS.get("gateway.prefill_preempted") == 10

    def test_prefill_never_enters_resume_stash_or_checkpoint(self):
        """Speculative jobs must not stash under their synthetic keys:
        the bounded orphan FIFO (and the checkpoint built from it) would
        evict REAL dead clients' resume progress for work that is already
        persisted as solved spans."""
        METRICS.reset()
        g = self._hot_solved_gateway()
        g.tick(5.0)  # speculation over (DATA, 300, 399) in flight

        def keys(ck):
            return {(j["data"], j["lower"], j["upper"]) for j in ck["jobs"]}

        assert keys(g.sched.checkpoint()) == set()  # live prefill skipped
        orphaned = METRICS.get("sched.jobs_orphaned")
        g.client_request(40, "other-data", 0, 99, now=5.5)  # preempt
        assert METRICS.get("sched.jobs_orphaned") == orphaned
        # Only the live REAL job's key may appear in the snapshot.
        assert keys(g.sched.checkpoint()) == {("other-data", 0, 99)}


class TestHotnessDecay:
    """Recency-weighted prefill hotness (ISSUE 12 satellite): cover-hit
    scores decay with a half-life, so a formerly-hot key stops hogging
    idle prefill capacity and a newly-hot key overtakes it."""

    def _store(self, half_life=10.0):
        clock = {"t": 0.0}
        store = SpanStore(hot_half_life_s=half_life,
                          clock=lambda: clock["t"])
        return store, clock

    def test_cold_key_overtakes_formerly_hot_key(self):
        store, clock = self._store()
        # OLD gets very hot at t=0: solved spans with an internal gap
        # (so prefill_target has a gap to offer) + 5 cover hits.
        store.add("old", 0, 99, 700, 10)
        store.add("old", 200, 299, 650, 250)
        for _ in range(5):
            store.cover("old", 0, 50)
        assert store.prefill_target(50)[0] == "old"
        # Ten half-lives later, NEW gets a single hit.
        clock["t"] = 100.0
        store.add("new", 0, 99, 500, 5)
        store.add("new", 200, 299, 450, 250)
        store.cover("new", 0, 50)
        # 5 * 2^-10 ≈ 0.005 < 1.0: the cold key overtakes — and OLD has
        # decayed below the floor entirely, so it no longer competes.
        target = store.prefill_target(50)
        assert target is not None and target[0] == "new"

    def test_decayed_cold_key_stops_hogging_idle_capacity(self):
        store, clock = self._store()
        store.add("old", 0, 99, 700, 10)
        store.add("old", 200, 299, 650, 250)
        store.cover("old", 0, 50)
        assert store.prefill_target(50) is not None
        clock["t"] = 50.0  # five half-lives: score ~0.03 < HOT_MIN
        assert store.prefill_target(50) is None

    def test_fresh_hits_rebuild_hotness(self):
        store, clock = self._store()
        store.add("d", 0, 99, 700, 10)
        store.add("d", 200, 299, 650, 250)
        store.cover("d", 0, 50)
        clock["t"] = 50.0
        assert store.prefill_target(50) is None  # decayed out
        store.cover("d", 0, 50)  # reused again: hot again
        assert store.prefill_target(50) is not None

    def test_half_life_none_disables_decay(self):
        clock = {"t": 0.0}
        store = SpanStore(hot_half_life_s=None, clock=lambda: clock["t"])
        store.add("d", 0, 99, 700, 10)
        store.add("d", 200, 299, 650, 250)
        store.cover("d", 0, 50)
        clock["t"] = 1e9
        assert store.prefill_target(50) is not None  # legacy behavior


class TestAdmission:
    def test_max_active_queues_then_admits_on_completion(self):
        g = make_gateway(max_active=1)
        g.miner_joined(1)
        g.client_request(10, "a", 0, 99, now=0.0)
        assert g.client_request(11, "b", 0, 99, now=0.0) == []  # queued
        assert g.stats()["gw_queued"] == 1
        acts = g.result(1, hash_=5, nonce=5, now=1.0)
        # Completion of "a" both answers conn 10 and admits "b".
        assert [cid for cid, _ in results(acts)] == [10]
        assert requests(acts)  # "b"'s first chunk went out
        assert g.stats()["gw_queued"] == 0
        assert METRICS.get("gateway.throttled") >= 1

    def test_queued_duplicate_coalesces_at_admit_time(self):
        g = make_gateway(max_active=1)
        g.miner_joined(1)
        g.client_request(10, "a", 0, 99, now=0.0)
        g.client_request(11, "b", 0, 99, now=0.0)  # queued
        g.client_request(12, "b", 0, 99, now=0.0)  # queued twin of "b"
        acts = g.result(1, hash_=5, nonce=5, now=1.0)  # frees a slot
        # Both queued "b" requests ride ONE sweep.
        assert g.stats()["gw_inflight"] == 1
        assert g.stats()["gw_queued"] == 0
        done = results(g.result(1, hash_=6, nonce=6, now=2.0))
        assert sorted(cid for cid, _ in done) == [11, 12]

    def test_completion_both_answers_and_admits_backlog(self):
        g = make_gateway(max_active=1)
        g.miner_joined(1)
        g.client_request(10, "a", 0, 99, now=0.0)
        g.client_request(11, "a", 0, 99, now=0.0)  # coalesces (in flight)
        g.client_request(12, "b", 0, 99, now=0.0)  # queued
        acts = g.result(1, hash_=5, nonce=5, now=1.0)
        # "a" completed -> 10 and 11 answered; "b" admitted.
        assert sorted(cid for cid, _ in results(acts)) == [10, 11]
        done = results(g.result(1, hash_=6, nonce=6, now=2.0))
        assert [cid for cid, _ in done] == [12]

    def test_overflow_sheds_conn_via_evictions(self):
        METRICS.reset()
        g = make_gateway(max_active=1, max_queued=1)
        g.miner_joined(1)
        g.client_request(10, "a", 0, 99, now=0.0)
        g.client_request(11, "b", 0, 99, now=0.0)  # fills the queue
        assert g.client_request(12, "c", 0, 99, now=0.0) == []  # shed
        assert g.drain_evictions() == [12]
        assert g.drain_evictions() == []
        assert METRICS.get("gateway.shed") == 1

    def test_overflow_sheds_flood_tail_not_newcomer(self):
        """When one client's backlog fills the queue, the overflow victim
        is the FLOOD's newest request, not the quiet client arriving."""
        METRICS.reset()
        g = make_gateway(max_active=1, max_queued=3)
        g.miner_joined(1)
        g.client_request(10, "a", 0, 99, now=0.0, client_key="flood")
        for i, conn in enumerate((11, 12, 13)):  # fill the queue as one key
            g.client_request(conn, f"f{i}", 0, 99, now=0.0,
                             client_key="flood")
        assert g.stats()["gw_queued"] == 3
        g.client_request(30, "quiet", 0, 99, now=0.0, client_key="quiet")
        # The flood's newest parked request paid; the newcomer is queued.
        assert g.drain_evictions() == [13]
        assert g.stats()["gw_queued"] == 3
        assert METRICS.get("gateway.shed") == 1

    def test_request_then_join_refused(self):
        """A conn holding a gateway-tracked Request cannot re-enroll as a
        miner: under virtual ids the scheduler's own role guard is blind
        to it, and accepting would leak a phantom miner on conn death."""
        g = make_gateway()
        g.miner_joined(1)
        g.client_request(10, DATA, 0, 99, now=0.0)  # waiter
        assert g.miner_joined(10) == []
        assert 10 not in g.sched.miners
        # Same for a conn parked in the admission queue.
        g2 = make_gateway(max_active=1)
        g2.client_request(20, "a", 0, 99, now=0.0)
        g2.client_request(21, "b", 0, 99, now=0.0)  # queued
        assert g2.miner_joined(21) == []
        assert 21 not in g2.sched.miners

    def test_queued_conn_death_forgotten(self):
        g = make_gateway(max_active=1)
        g.miner_joined(1)
        g.client_request(10, "a", 0, 99, now=0.0)
        g.client_request(11, "b", 0, 99, now=0.0)  # queued
        g.lost(11, now=0.5)
        acts = g.result(1, hash_=5, nonce=5, now=1.0)
        # The dead conn's request must NOT be admitted.
        assert g.stats()["gw_inflight"] == 0
        assert g.stats()["gw_queued"] == 0

    def test_token_bucket_throttles_then_tick_drains(self):
        g = make_gateway(rate=1.0, burst=2.0)
        g.miner_joined(1)
        # One client key floods 4 distinct signatures at t=0.
        for i, conn in enumerate((10, 11, 12, 13)):
            g.client_request(conn, f"job{i}", 0, 99, now=0.0,
                             client_key="flood")
        assert g.stats()["gw_inflight"] == 2  # the burst allowance
        assert g.stats()["gw_queued"] == 2  # the rest wait for tokens
        assert g.tick(0.5) == []  # half a token: still parked
        acts = g.tick(1.0)  # one token refilled
        assert g.stats()["gw_inflight"] == 3
        g.tick(2.0)
        assert g.stats()["gw_inflight"] == 4
        assert g.stats()["gw_queued"] == 0

    def test_flood_does_not_delay_other_client_beyond_fair_bound(self):
        """The ISSUE 3 acceptance property: with a rate-limited flood from
        one client queued ahead of it, another client's single request is
        admitted at the NEXT admission opportunity (fair-queue bound: one
        pop), not behind the flood's whole backlog."""
        g = make_gateway(rate=1.0, burst=1.0, max_active=1)
        g.miner_joined(1)
        g.client_request(10, "f0", 0, 99, now=0.0, client_key="flood")
        for i, conn in enumerate(range(11, 19)):  # 8 more flood requests
            g.client_request(conn, f"f{i + 1}", 0, 99, now=0.0,
                             client_key="flood")
        g.client_request(30, "quiet", 0, 99, now=0.1, client_key="quiet")
        assert g.stats()["gw_queued"] == 9
        # Completion 1 (t=5, tokens refilled for both keys): the freed slot
        # goes to ONE more flood request — quiet activated at the same
        # virtual time as the flood and the flood is older (FIFO tie).
        g.result(1, hash_=5, nonce=5, now=5.0)
        # Completion 2: the flood's virtual time now exceeds quiet's, so
        # quiet is admitted next — one pop behind, NOT behind the 7 flood
        # requests still parked.  That is the fair-queue bound.
        g.result(1, hash_=6, nonce=6, now=6.0)
        done = results(g.result(1, hash_=7, nonce=7, now=7.0))
        assert [cid for cid, _ in done] == [30]
        # Completion 3 also admitted flood #3: 6 flood requests still wait.
        assert g.stats()["gw_queued"] == 6

    def test_per_client_bucket_state_is_bounded(self):
        """One bucket per client key must not leak for the server's
        lifetime: refilled-to-burst buckets are pruned at the cap."""
        g = make_gateway(rate=1000.0, burst=1.0, max_buckets=8,
                         max_active=512)
        g.miner_joined(1)
        for i in range(100):
            g.client_request(1000 + i, f"sig{i}", 0, 99, now=float(i),
                             client_key=f"client{i}")
        assert len(g._buckets) <= 9

    def test_rate_none_never_throttles(self):
        g = make_gateway(rate=None)
        g.miner_joined(1)
        for i in range(20):
            g.client_request(100 + i, f"j{i}", 0, 99, now=0.0,
                             client_key="one")
        assert g.stats()["gw_inflight"] == 20
        assert g.stats()["gw_queued"] == 0


class TestSchedulerWFQ:
    def test_flooding_tenant_gets_one_share(self):
        """One tenant with 8 jobs vs one tenant with 1 job: chunk
        assignments interleave ~1:1 per tenant, not 8:1 per job count."""
        s = Scheduler(validate_results=False, min_chunk=100, max_chunk=100,
                      pipeline_depth=1)
        for i in range(8):
            s.client_request(10 + i, f"flood{i}", 0, 10**6, tenant="F")
        s.client_request(50, "quiet", 0, 10**6, tenant="Q")
        s.miner_joined(1, now=0.0)
        seq = []
        for k in range(20):
            acts = s.result(1, hash_=5, nonce=5, now=float(k + 1))
            for _, m in requests(acts):
                seq.append("Q" if m.data == "quiet" else "F")
        # Equal weights, equal chunk sizes: Q holds ~half the assignments.
        assert seq.count("Q") >= len(seq) // 2 - 1

    def test_weight_skews_share(self):
        s = Scheduler(validate_results=False, min_chunk=100, max_chunk=100,
                      pipeline_depth=1)
        s.client_request(10, "heavy", 0, 10**6, tenant="H", weight=3.0)
        s.client_request(11, "light", 0, 10**6, tenant="L", weight=1.0)
        s.miner_joined(1, now=0.0)
        seq = []
        for k in range(16):
            acts = s.result(1, hash_=5, nonce=5, now=float(k + 1))
            for _, m in requests(acts):
                seq.append(m.data)
        assert seq.count("heavy") >= 10  # ~3:1 of 16

    def test_new_tenant_starts_at_active_floor(self):
        """A tenant arriving late must neither starve incumbents (vt=0
        debt) nor be starved (inherited charges)."""
        s = Scheduler(validate_results=False, min_chunk=100, max_chunk=100,
                      pipeline_depth=1)
        s.client_request(10, "old", 0, 10**6, tenant="A")
        s.miner_joined(1, now=0.0)
        for k in range(10):  # A accrues virtual time
            s.result(1, hash_=5, nonce=5, now=float(k + 1))
        s.client_request(11, "new", 0, 10**6, tenant="B")
        seq = []
        for k in range(10):
            acts = s.result(1, hash_=5, nonce=5, now=float(20 + k))
            for _, m in requests(acts):
                seq.append(m.data)
        assert 4 <= seq.count("new") <= 6  # ~half, not all, not none

    def test_tenant_cleanup_on_finish_and_loss(self):
        s = Scheduler(validate_results=False, min_chunk=1000)
        s.miner_joined(1)
        s.client_request(10, "a", 0, 99, tenant="T")
        s.client_request(11, "b", 0, 99, tenant="T")
        assert s.stats()["tenants"] == 1
        s.result(1, hash_=5, nonce=5)  # finishes "a"
        assert s.stats()["tenants"] == 1  # "b" keeps T alive
        s.lost(11)
        assert s.stats()["tenants"] == 0


# -------------------------------------------------------------- end-to-end

PARAMS = lsp.Params(epoch_limit=5, epoch_millis=200, window_size=5)


@pytest.fixture(autouse=True)
def _clean_network():
    lspnet.reset_faults()
    yield
    lspnet.reset_faults()


class GatewayFleet:
    """In-process cluster: gateway-fronted scheduler + miner threads."""

    def __init__(self, n_miners=2, min_chunk=500, **gw_kwargs):
        gw_kwargs.setdefault("rate", None)
        self.server = lsp.Server(0, PARAMS)
        self.scheduler = Scheduler(min_chunk=min_chunk)
        self.gateway = Gateway(self.scheduler, **gw_kwargs)
        threading.Thread(
            target=server_mod.serve,
            args=(self.server, self.gateway),
            kwargs={"tick_interval": 0.05},
            daemon=True,
        ).start()
        for _ in range(n_miners):
            self.add_miner()

    def add_miner(self, search=None):
        c = lsp.Client("127.0.0.1", self.server.port, PARAMS)
        threading.Thread(
            target=miner_mod.run_miner,
            args=(c, search or miner_mod.make_search("cpu")),
            daemon=True,
        ).start()
        return c

    def request(self, data, max_nonce):
        c = lsp.Client("127.0.0.1", self.server.port, PARAMS)
        try:
            return client_mod.request_once(c, data, max_nonce)
        finally:
            c.close()

    def close(self):
        self.server.close()


def test_gateway_fleet_duplicate_heavy_bit_exact():
    """Six concurrent clients, two distinct signatures: every answer
    bit-exact, at most two underlying sweeps, coalesce/cache hits > 0,
    and a post-hoc repeat assigns zero chunks — the acceptance shape at
    test scale (tools/loadgen.py runs it at 8 clients / 50% dups)."""
    METRICS.reset()
    fleet = GatewayFleet(n_miners=2)
    sigs = [("gwalpha", 3000), ("gwbeta", 4000)]
    expected = {d: min_hash_range(d, 0, mx) for d, mx in sigs}
    out = {}

    def one(i):
        d, mx = sigs[i % 2]
        out[i] = (d, fleet.request(d, mx))

    try:
        threads = [threading.Thread(target=one, args=(i,)) for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
            assert not t.is_alive(), "client starved"
        for i, (d, got) in out.items():
            assert got == expected[d], f"client {i}"
        assert METRICS.get("gateway.requests") == 6
        assert METRICS.get("gateway.completed") <= 2  # <= one sweep per sig
        assert (
            METRICS.get("gateway.coalesced") + METRICS.get("gateway.cache_hits")
            == 4
        )
        # Repeat-submitted solved job: zero chunks assigned.
        assigned = METRICS.get("sched.chunks_assigned")
        d, mx = sigs[0]
        assert fleet.request(d, mx) == expected[d]
        assert METRICS.get("sched.chunks_assigned") == assigned
    finally:
        fleet.close()


def test_gateway_fleet_shed_conn_sees_disconnected():
    """A shed request's conn is closed exactly like a dead client: the
    waiting client unblocks with None (the Disconnected contract)."""
    hold = threading.Event()
    fleet = GatewayFleet(
        n_miners=0, max_active=1, max_queued=0,
    )
    try:
        fleet.add_miner(lambda d, lo, hi: (hold.wait(30), min_hash_range(d, lo, hi))[1])
        box = {}

        def first():
            box["a"] = fleet.request("gwheld", 2000)

        ta = threading.Thread(target=first, daemon=True)
        ta.start()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not fleet.gateway.stats()["gw_inflight"]:
            time.sleep(0.05)
        assert fleet.gateway.stats()["gw_inflight"] == 1
        # Queue is size 0: the next distinct signature is shed.
        assert fleet.request("gwshed", 2000) is None
        hold.set()
        ta.join(timeout=30)
        assert box["a"] == min_hash_range("gwheld", 0, 2000)
    finally:
        hold.set()
        fleet.close()


@pytest.mark.analysis
def test_gateway_fleet_green_under_race_sanitizer():
    """Duplicate-heavy serving with BMT_SANITIZE=1 machinery armed: the
    gateway (coalescing + cache + admission state) runs behind a Monitor
    on serve()'s TrackedLock, so any off-lock touch of the serving-layer
    state during concurrent client bursts aborts the fleet.  Green here
    means the gateway's "under the event lock" discipline is enforced by
    machinery, not comments (ISSUE 4)."""
    from bitcoin_miner_tpu.utils import sanitize

    sanitize.force(True)
    sanitize.reset_order_graph()
    fleet = None
    try:
        fleet = GatewayFleet(n_miners=2)
        want = min_hash_range("gwsani", 0, 2500)
        out = {}

        def one(i):
            out[i] = fleet.request("gwsani", 2500)

        threads = [threading.Thread(target=one, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
            assert not t.is_alive(), "client starved under sanitizer"
        # len check first: a RaceError inside a client thread would kill it
        # before it writes out[i] — iterating only surviving keys would
        # pass vacuously and mask exactly what this test exists to catch.
        assert len(out) == 4, f"client thread(s) died: {sorted(out)}"
        assert all(out[i] == want for i in out), out
    finally:
        if fleet is not None:
            fleet.close()
        sanitize.force(None)
        sanitize.reset_order_graph()


def test_gateway_cache_persists_across_fleet_restart(tmp_path):
    """Fleet 1 solves a job; fleet 2 (fresh server+scheduler, same cache
    file) answers the repeat with no miners at all."""
    path = str(tmp_path / "results.json")
    fleet = GatewayFleet(n_miners=1, cache=ResultCache(path=path))
    want = min_hash_range("gwpersist", 0, 2500)
    try:
        assert fleet.request("gwpersist", 2500) == want
        # Persistence rides the serve ticker (50 ms here): wait for the
        # flush to land before killing the fleet, or the restart below
        # would block forever on a miner-less server.
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not len(ResultCache(path=path)):
            time.sleep(0.05)
        assert len(ResultCache(path=path)) == 1, "cache flush never landed"
    finally:
        fleet.close()
    # Miner-less restart: only the cache can answer — and it does.
    fleet2 = GatewayFleet(n_miners=0, cache=ResultCache(path=path))
    try:
        assert fleet2.request("gwpersist", 2500) == want
    finally:
        fleet2.close()


def test_gateway_spans_persist_and_answer_subrange_after_restart(tmp_path):
    """The ISSUE 5 acceptance shape over a real fleet: fleet 1 solves
    [0, 2500]; fleet 2 (fresh server+scheduler, NO miners, same span
    file) answers a never-issued strict SUB-RANGE purely from the
    persisted interval store — zero chunks assigned, bit-exact."""
    path = str(tmp_path / "spans.json")
    fleet = GatewayFleet(n_miners=1, spans=SpanStore(path=path))
    data = "gwspans"
    try:
        assert fleet.request(data, 2500) == min_hash_range(data, 0, 2500)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not len(SpanStore(path=path)):
            time.sleep(0.05)
        assert len(SpanStore(path=path)) > 0, "span flush never landed"
    finally:
        fleet.close()
    fleet2 = GatewayFleet(n_miners=0, spans=SpanStore(path=path))
    try:
        # Pick a strict sub-range the store fully covers (candidates from
        # the span geometry, verified through the planner — the same
        # probe tools/loadgen.py --overlap runs).
        sub = None
        for _lo, s_hi, _h, n in fleet2.gateway.spans._maps[data].spans():
            for qlo, qhi in ((0, s_hi), (0, n), (n, 2500)):
                if (qlo, qhi) == (0, 2500) or qlo > qhi:
                    continue
                best, gaps = fleet2.gateway.spans.cover(data, qlo, qhi)
                if not gaps and best is not None:
                    sub = (qlo, qhi)
                    break
            if sub:
                break
        assert sub is not None, "no covered strict sub-range to probe"
        assigned = METRICS.get("sched.chunks_assigned")
        c = lsp.Client("127.0.0.1", fleet2.server.port, PARAMS)
        try:
            got = client_mod.request_once(c, data, sub[1], lower=sub[0])
        finally:
            c.close()
        assert got == min_hash_range(data, sub[0], sub[1])
        # Miner-less: only the interval store could have answered.
        assert METRICS.get("sched.chunks_assigned") == assigned
    finally:
        fleet2.close()


def test_gateway_buckets_bind_to_peer_addr_across_conns():
    """Admission identity is the LSP remote addr, not the ephemeral conn
    id (ISSUE 5 satellite): distinct conns from one host share ONE token
    bucket, so rate limits survive reconnects."""
    fleet = GatewayFleet(n_miners=1, rate=1000.0, burst=50.0)
    try:
        assert fleet.request("gwaddr1", 1500) == min_hash_range("gwaddr1", 0, 1500)
        assert fleet.request("gwaddr2", 1500) == min_hash_range("gwaddr2", 0, 1500)
        # Two requests, two conns, one host -> exactly one addr-keyed
        # bucket (conn-keyed buckets would have minted two).
        keys = set(fleet.gateway._buckets)
        assert keys == {"addr:127.0.0.1"}, keys
    finally:
        fleet.close()
