"""Suite 2 parity: sliding-window semantics (reference lsp/lsp2_test.go).

TestWindow1-3 "max capacity" (lsp2_test.go:339-367,476-495): with the
receiver's acks 100% blackholed, a sender streaming W+K messages must get
exactly the first W delivered (window gate), and everything once acks
resume.

TestWindow4-6 "scattered" (lsp2_test.go:397-434,497-516): the first half of
a stream is dropped in flight; the receiver must deliver *nothing* (ordered
delivery) until epoch retransmits fill the gap, then everything in order.
"""

import time

import pytest

from bitcoin_miner_tpu import lsp, lspnet
from lsp_harness import spawn

EPOCH_MS = 100
PARAMS = lambda w: lsp.Params(epoch_limit=10, epoch_millis=EPOCH_MS, window_size=w)


@pytest.fixture(autouse=True)
def _reset_faults():
    lspnet.reset_faults()
    yield
    lspnet.reset_faults()


def _echo_none_server(params):
    """Server that reads and records but never writes back."""
    server = lsp.Server(0, params)
    received = []

    def loop():
        while True:
            try:
                _cid, payload = server.read()
                received.append(payload)
            except lsp.ConnLostError:
                continue
            except lsp.LspError:
                return

    t = spawn(loop)
    return server, received, t


@pytest.mark.parametrize("w,extra", [(1, 3), (5, 5), (10, 5)])
def test_window_max_capacity(w, extra):
    params = PARAMS(w)
    server, received, _t = _echo_none_server(params)
    client = lsp.Client("127.0.0.1", server.port, params)

    # Blackhole the server's outbound acks: client's window can never slide.
    lspnet.set_server_write_drop_percent(100)
    total = w + extra
    for i in range(total):
        client.write(b"m%d" % i)

    # Give the client several epochs to (re)send whatever it believes is
    # in-window; the receiver must have exactly the first W messages.
    time.sleep(6 * EPOCH_MS / 1000)
    assert received == [b"m%d" % i for i in range(w)], (
        f"expected exactly first {w} messages, got {received}"
    )

    # Heal: acks flow again; the remainder must arrive, in order.
    lspnet.set_server_write_drop_percent(0)
    deadline = time.time() + 40 * EPOCH_MS / 1000
    while len(received) < total and time.time() < deadline:
        time.sleep(0.02)
    assert received == [b"m%d" % i for i in range(total)]

    client.close()
    server.close()


@pytest.mark.parametrize("count", [6, 20])
def test_window_scattered_gap_fill(count):
    """First half dropped in flight; Read yields nothing until retransmits
    fill the gap, then everything in order."""
    w = count  # window wide enough for the whole stream
    params = PARAMS(w)
    server, received, _t = _echo_none_server(params)
    client = lsp.Client("127.0.0.1", server.port, params)

    # Drop all client->server packets for the first half of the stream.
    lspnet.set_client_write_drop_percent(100)
    for i in range(count // 2):
        client.write(b"m%d" % i)
    time.sleep(0.05)
    lspnet.set_client_write_drop_percent(0)
    for i in range(count // 2, count):
        client.write(b"m%d" % i)

    # The second half arrives before the first: ordered delivery demands the
    # receiver NEVER exposes an out-of-order prefix — sample continuously
    # until the epoch retransmits fill the gap and everything drains.
    want = [b"m%d" % i for i in range(count)]
    deadline = time.time() + 40 * EPOCH_MS / 1000
    while time.time() < deadline:
        snap = list(received)
        assert snap == want[: len(snap)], f"out-of-order delivery: {snap}"
        if len(snap) == count:
            break
        time.sleep(0.01)
    assert received == want

    client.close()
    server.close()


def test_server_side_window_gate():
    """Symmetric check: the server's writes also respect the window when
    the client's acks are blackholed (lsp2 exercises both directions)."""
    w = 3
    params = PARAMS(w)
    server = lsp.Server(0, params)
    client = lsp.Client("127.0.0.1", server.port, params)
    got = []

    def client_reader():
        while True:
            try:
                got.append(client.read())
            except lsp.LspError:
                return

    spawn(client_reader)
    # client must announce itself so the server has the conn
    client.write(b"hello")
    cid, payload = server.read()
    assert payload == b"hello"

    lspnet.set_client_write_drop_percent(100)  # client's acks vanish
    for i in range(w + 4):
        server.write(cid, b"s%d" % i)
    time.sleep(6 * EPOCH_MS / 1000)
    assert got == [b"s%d" % i for i in range(w)], got

    lspnet.set_client_write_drop_percent(0)
    deadline = time.time() + 40 * EPOCH_MS / 1000
    while len(got) < w + 4 and time.time() < deadline:
        time.sleep(0.02)
    assert got == [b"s%d" % i for i in range(w + 4)]

    client.close()
    server.close()
