"""Suite 1 parity: echo correctness (reference lsp/lsp1_test.go).

N clients x M messages, each echoed value verified, under various window
sizes, message counts and write-drop rates.  TestBasic1-9 / TestSendReceive
/ TestRobust scenarios (lsp1_test.go:201-335), with counts trimmed to keep
wall-clock sane at 100 ms epochs.
"""

import pytest

from bitcoin_miner_tpu import lspnet
from lsp_harness import TestSystem


@pytest.fixture(autouse=True)
def _reset_faults():
    lspnet.reset_faults()
    yield
    lspnet.reset_faults()


class TestBasic:
    def test_basic_1_single_client_single_msg(self):
        TestSystem(num_clients=1, num_msgs=1, window=1).run_echo()

    def test_basic_2_single_client_many_msgs(self):
        TestSystem(num_clients=1, num_msgs=100, window=1).run_echo()

    def test_basic_3_two_clients(self):
        TestSystem(num_clients=2, num_msgs=50, window=1).run_echo()

    def test_basic_4_many_clients(self):
        TestSystem(num_clients=10, num_msgs=30, window=1).run_echo()

    def test_basic_5_window_10(self):
        TestSystem(num_clients=3, num_msgs=60, window=10).run_echo()

    def test_basic_6_window_20(self):
        TestSystem(num_clients=2, num_msgs=100, window=20).run_echo()

class TestSendReceive:
    """Epochs too long to help: correctness must not depend on
    retransmission (lsp1_test.go:267-287)."""

    def test_send_receive_no_retransmit(self):
        TestSystem(
            num_clients=2, num_msgs=50, window=5,
            epoch_millis=2000, epoch_limit=5, max_epochs=10,
        ).run_echo()


class TestRobust:
    """20% write drop, fast epochs (lsp1_test.go:289-335)."""

    def test_robust_1(self):
        TestSystem(
            num_clients=1, num_msgs=30, window=1,
            epoch_millis=50, write_drop=20, max_epochs=400,
        ).run_echo()

    def test_robust_2_windowed(self):
        TestSystem(
            num_clients=2, num_msgs=30, window=5,
            epoch_millis=50, write_drop=20, max_epochs=400,
        ).run_echo()

    def test_robust_3_many_clients(self):
        TestSystem(
            num_clients=5, num_msgs=20, window=3,
            epoch_millis=50, write_drop=20, max_epochs=400,
        ).run_echo()
