"""Suite 1 parity: echo correctness (reference lsp/lsp1_test.go).

N clients x M messages, each echoed value verified, under various window
sizes, message counts and write-drop rates.  Full TestBasic1-9 /
TestSendReceive1-3 / TestRobust1-6 scenario coverage (lsp1_test.go:201-335)
at reference scale — including the 500-message streams (TestBasic5/6) and
the random-delay variants (setMaxSleepMillis, TestBasic7-9) — at 100 ms
epochs instead of the reference's 2000 ms so wall-clock stays sane.
"""

import pytest

from bitcoin_miner_tpu import lspnet
from lsp_harness import TestSystem


@pytest.fixture(autouse=True)
def _reset_faults():
    lspnet.reset_faults()
    yield
    lspnet.reset_faults()


class TestBasic:
    def test_basic_1_single_client_single_msg(self):
        TestSystem(num_clients=1, num_msgs=1, window=1).run_echo()

    def test_basic_2_single_client_many_msgs(self):
        TestSystem(num_clients=1, num_msgs=100, window=1).run_echo()

    def test_basic_3_two_clients(self):
        TestSystem(num_clients=2, num_msgs=50, window=1).run_echo()

    def test_basic_4_many_clients(self):
        TestSystem(num_clients=10, num_msgs=30, window=1).run_echo()

    def test_basic_5_two_clients_500_msgs(self):
        # lsp1_test.go:229-234 TestBasic5 at full scale.
        TestSystem(num_clients=2, num_msgs=500, window=2, max_epochs=600).run_echo()

    def test_basic_6_ten_clients_500_msgs_window_20(self):
        # lsp1_test.go:236-241 TestBasic6 at full scale — the big stream.
        TestSystem(
            num_clients=10, num_msgs=500, window=20, max_epochs=1200
        ).run_echo()

    def test_basic_7_random_delays(self):
        # lsp1_test.go:243-249 TestBasic7: random client+server sleeps.
        TestSystem(
            num_clients=4, num_msgs=10, window=2,
            sleep_max_ms=100, max_epochs=300,
        ).run_echo()

    def test_basic_8_random_delays_window_10(self):
        # lsp1_test.go:251-256 TestBasic8.
        TestSystem(
            num_clients=5, num_msgs=10, window=10,
            sleep_max_ms=100, max_epochs=300,
        ).run_echo()

    def test_basic_9_random_delays_50_msgs(self):
        # lsp1_test.go:258-264 TestBasic9.
        TestSystem(
            num_clients=2, num_msgs=50, window=10,
            sleep_max_ms=100, max_epochs=600,
        ).run_echo()


class TestSendReceive:
    """Epochs too long to help: correctness must not depend on
    retransmission (lsp1_test.go:267-287)."""

    def test_send_receive_no_retransmit(self):
        TestSystem(
            num_clients=2, num_msgs=50, window=5,
            epoch_millis=2000, epoch_limit=5, max_epochs=10,
        ).run_echo()

    def test_send_receive_random_delays(self):
        # lsp1_test.go:281-287 TestSendReceive3: no-retransmit correctness
        # with random delays (epochs far longer than any sleep).
        TestSystem(
            num_clients=4, num_msgs=6, window=1,
            epoch_millis=2000, epoch_limit=3, sleep_max_ms=100, max_epochs=10,
        ).run_echo()


class TestRobust:
    """20% write drop at 50 ms epochs, epoch limit 20 — the reference
    regime exactly (lsp1_test.go:289-335 TestRobust1-6)."""

    def test_robust_1(self):
        TestSystem(
            num_clients=1, num_msgs=10, window=1,
            epoch_millis=50, epoch_limit=20, write_drop=20, max_epochs=400,
        ).run_echo()

    def test_robust_2_three_clients(self):
        TestSystem(
            num_clients=3, num_msgs=15, window=1,
            epoch_millis=50, epoch_limit=20, write_drop=20, max_epochs=400,
        ).run_echo()

    def test_robust_3_five_clients(self):
        TestSystem(
            num_clients=5, num_msgs=10, window=1,
            epoch_millis=50, epoch_limit=20, write_drop=20, max_epochs=400,
        ).run_echo()

    def test_robust_4_window_2(self):
        TestSystem(
            num_clients=1, num_msgs=10, window=2,
            epoch_millis=50, epoch_limit=20, write_drop=20, max_epochs=400,
        ).run_echo()

    def test_robust_5_window_5(self):
        TestSystem(
            num_clients=3, num_msgs=15, window=5,
            epoch_millis=50, epoch_limit=20, write_drop=20, max_epochs=400,
        ).run_echo()

    def test_robust_6_window_10(self):
        TestSystem(
            num_clients=5, num_msgs=10, window=10,
            epoch_millis=50, epoch_limit=20, write_drop=20, max_epochs=400,
        ).run_echo()

    def test_robust_sustained_stream(self):
        # Beyond the reference counts: a sustained 100-msg stream per client
        # under the same 20%-drop/50ms regime, so the transport is observed
        # under load+loss for many window generations, not just a burst.
        TestSystem(
            num_clients=3, num_msgs=100, window=5,
            epoch_millis=50, epoch_limit=20, write_drop=20, max_epochs=1200,
        ).run_echo()
