"""Fleet metrics plane suite (ISSUE 7): telemetry sidecar, merged fleet
view, SLO burn-rate alerts, straggler detection, dashboard.

Four layers:

1. **Mergeable state** — Histogram state round-trips, FleetView merge
   semantics (counters sum, gauges LWW, histograms merge), staleness
   aging, Prometheus exposition.
2. **SLO engine** — burn-rate math on a fake clock: an outage fires the
   multi-window alert and recovery resolves it; a fast-window spike
   alone never pages; no evidence is not an outage.
3. **Channel** — fragment framing round-trips under the frozen
   1000-byte LSP wire ceiling; a real exporter→hub loopback merges; a
   subscriber (the dash --connect path) receives published states.
4. **The acceptance drill** — a real in-process fleet with an induced
   straggler under seeded burst loss fires the SLO alert and the
   detector names the induced miner; the clean run stays alert-quiet.
"""

import json
import os
import threading
import time

import pytest

from bitcoin_miner_tpu import lsp, lspnet
from bitcoin_miner_tpu.apps import client as client_mod
from bitcoin_miner_tpu.apps import miner as miner_mod
from bitcoin_miner_tpu.apps import server as server_mod
from bitcoin_miner_tpu.apps.scheduler import Scheduler
from bitcoin_miner_tpu.bitcoin.hash import min_hash_range
from bitcoin_miner_tpu.lspnet.chaos import CHAOS, GEParams
from bitcoin_miner_tpu.utils.fleetview import FleetView, render_prometheus
from bitcoin_miner_tpu.utils.metrics import (
    METRICS,
    Histogram,
    Metrics,
    format_quantiles,
)
from bitcoin_miner_tpu.utils.slo import (
    SloEngine,
    SloSpec,
    default_slos,
    parse_slo_config,
)
from bitcoin_miner_tpu.utils.telemetry import (
    FrameAssembler,
    TelemetryExporter,
    TelemetryHub,
    encode_frames,
    encode_subscribe,
)

pytestmark = pytest.mark.fleet

PARAMS = lsp.Params(epoch_limit=5, epoch_millis=100, window_size=5)


@pytest.fixture(autouse=True)
def _clean_network():
    lspnet.reset_faults()
    CHAOS.reset()
    yield
    CHAOS.reset()
    lspnet.reset_faults()


def _hist_of(samples):
    h = Histogram()
    for s in samples:
        h.observe(s)
    return h


# --------------------------------------------------------------------------
# 1. Mergeable state + fleet view
# --------------------------------------------------------------------------


def test_histogram_state_roundtrips_and_merges():
    h = _hist_of([0.001, 0.5, 0.5, 2.0])
    h2 = Histogram.from_state(h.state())
    assert h2.buckets() == h.buckets()
    assert h2.count() == h.count()
    assert h2.quantile(0.5) == h.quantile(0.5)
    # state survives a JSON round-trip (the wire format)
    h3 = Histogram.from_state(json.loads(json.dumps(h.state())))
    assert h3.buckets() == h.buckets()


def test_histogram_from_state_tolerates_garbage():
    for bad in ({}, {"buckets": "x"}, {"buckets": {"a": "b"}}, "junk", None,
                {"buckets": {"1": 2}, "count": "many"}):
        h = Histogram.from_state(bad)
        assert h.count() in (0,) or isinstance(h.count(), int)
    assert Histogram.from_state({"buckets": "x"}).count() == 0


def test_histogram_count_above():
    h = _hist_of([0.1, 0.1, 0.3, 1.0, 4.0])
    assert h.count_above(0.5) == 2
    assert h.count_above(0.05) == 5
    assert h.count_above(100.0) == 0
    assert h.count_above(0.0) == h.count()


def test_fleetview_counters_sum_gauges_lww_hists_merge():
    fv = FleetView(staleness_s=10.0, clock=lambda: 0.0)
    h = _hist_of([0.5])
    fv.ingest("a", {"seq": 1, "counters": {"n": 1}, "gauges": {"g": 1.0},
                    "hists": {"hist.x": h.state()}}, now=0.0)
    fv.ingest("b", {"seq": 1, "counters": {"n": 2}, "gauges": {"g": 7.0},
                    "hists": {"hist.x": h.state()}}, now=1.0)
    m = fv.merged(now=1.0)
    assert m["counters"]["n"] == 3
    assert m["gauges"]["g"] == 7.0  # freshest write wins
    assert m["hists"]["hist.x"].count() == 2
    assert m["sources"] == 2 and m["stale_sources"] == 0


def test_fleetview_staleness_ages_gauges_out_keeps_counters():
    fv = FleetView(staleness_s=5.0, clock=lambda: 0.0)
    fv.ingest("old", {"seq": 1, "counters": {"n": 10}, "gauges": {"g": 3.0},
                      "hists": {"hist.x": _hist_of([1.0]).state()}}, now=0.0)
    fv.ingest("new", {"seq": 1, "counters": {"n": 1}}, now=6.0)
    m = fv.merged(now=6.0)
    assert m["stale_sources"] == 1 and m["sources"] == 1
    # cumulative totals stand; point-in-time views age out
    assert m["counters"]["n"] == 11
    assert "g" not in m["gauges"]
    assert "hist.x" not in m["hists"]
    src = fv.sources(now=6.0)
    assert src["old"]["stale"] and not src["new"]["stale"]


def test_fleetview_drops_replayed_seq_accepts_reconnect_restart():
    fv = FleetView(staleness_s=10.0, clock=lambda: 0.0)
    assert fv.ingest("m", {"seq": 5, "counters": {"n": 5}}, now=0.0)
    assert not fv.ingest("m", {"seq": 4, "counters": {"n": 99}}, now=1.0)
    assert fv.merged(now=1.0)["counters"]["n"] == 5
    # a reconnected exporter restarts at seq 1: always accepted
    assert fv.ingest("m", {"seq": 1, "counters": {"n": 6}}, now=2.0)
    assert fv.merged(now=2.0)["counters"]["n"] == 6


def test_straggler_detector_names_the_slow_source_only():
    fv = FleetView(staleness_s=60.0, clock=lambda: 0.0)
    for name, scale in (("m0", 0.01), ("m1", 0.012), ("m2", 0.011),
                        ("slowpoke", 0.4)):
        h = _hist_of([scale * (1 + 0.1 * (i % 3)) for i in range(20)])
        fv.ingest(name, {"seq": 1,
                         "hists": {"hist.miner_chunk_s": h.state()}}, now=0.0)
    out = fv.stragglers(now=0.0)
    assert [s["source"] for s in out] == ["slowpoke"]
    assert out[0]["ratio"] > 3.0
    # exclusion drops it from consideration entirely
    assert fv.stragglers(now=0.0, exclude=("slowpoke",)) == []


def test_straggler_detector_guards():
    fv = FleetView(staleness_s=60.0, clock=lambda: 0.0)
    # below min_samples: no verdicts, however skewed
    fv.ingest("a", {"seq": 1,
                    "hists": {"hist.miner_chunk_s": _hist_of([9.0]).state()}},
              now=0.0)
    fv.ingest("b", {"seq": 1,
                    "hists": {"hist.miner_chunk_s": _hist_of([0.1]).state()}},
              now=0.0)
    assert fv.stragglers(now=0.0, min_samples=8) == []
    # a single source has no peers to be slower than
    fv2 = FleetView(staleness_s=60.0, clock=lambda: 0.0)
    h = _hist_of([1.0] * 20)
    fv2.ingest("only", {"seq": 1, "hists": {"hist.miner_chunk_s": h.state()}},
               now=0.0)
    assert fv2.stragglers(now=0.0) == []


def test_render_prometheus_exposition():
    fv = FleetView(staleness_s=10.0, clock=lambda: 0.0)
    fv.ingest("m", {"seq": 1, "counters": {"sched.jobs_completed": 3},
                    "gauges": {"gauge.miners_live": 2.0},
                    "hists": {"hist.request_s": _hist_of([0.5, 1.0]).state()}},
              now=0.0)
    text = render_prometheus(fv.merged(now=0.0))
    assert "# TYPE bmt_sched_jobs_completed counter" in text
    assert "bmt_sched_jobs_completed 3" in text
    assert "bmt_gauge_miners_live 2" in text
    assert "# TYPE bmt_hist_request_s histogram" in text
    assert 'bmt_hist_request_s_bucket{le="+Inf"} 2' in text
    assert "bmt_hist_request_s_count 2" in text
    assert "bmt_fleet_sources 1" in text
    # cumulative buckets are monotone non-decreasing
    cums = [int(line.rsplit(" ", 1)[1]) for line in text.splitlines()
            if line.startswith("bmt_hist_request_s_bucket")]
    assert cums == sorted(cums)


# --------------------------------------------------------------------------
# 2. SLO engine
# --------------------------------------------------------------------------


def _latency_spec(**kw):
    base = dict(
        name="req", kind="latency", objective=0.95, hist="hist.request_s",
        threshold_s=0.5, fast_window_s=2.0, slow_window_s=6.0,
        burn_threshold=2.0, min_events=2,
    )
    base.update(kw)
    return SloSpec(**base)


def _feed(engine, fv, clock, t, hist):
    clock[0] = t
    fv.ingest("gw", {"seq": 1, "hists": {"hist.request_s": hist.state()}},
              now=t)
    return engine.tick(fv, now=t)


def test_slo_outage_fires_multi_window_alert_then_resolves():
    clock = [0.0]
    engine = SloEngine([_latency_spec()], clock=lambda: clock[0])
    fv = FleetView(staleness_s=1e6, clock=lambda: clock[0])
    fired0 = METRICS.get("slo.alerts_fired")
    resolved0 = METRICS.get("slo.alerts_resolved")
    h = Histogram()
    out = None
    for t in range(0, 8):  # sustained outage: every sample above threshold
        h.observe(5.0)
        out = _feed(engine, fv, clock, float(t), h)
    assert out["alerts"] == ["req"], out
    assert engine.verdicts() == {"req": False}
    assert METRICS.get("slo.alerts_fired") == fired0 + 1
    # recovery: a flood of good samples drains both windows' bad fraction
    for t in range(8, 40):
        for _ in range(50):
            h.observe(0.01)
        out = _feed(engine, fv, clock, float(t), h)
    assert out["alerts"] == [], out
    assert engine.verdicts() == {"req": True}
    assert METRICS.get("slo.alerts_resolved") == resolved0 + 1


def test_slo_fast_spike_alone_does_not_page():
    """The multi-window property: a burst that fills the fast window but
    not the slow one (long good history behind it) stays quiet."""
    clock = [0.0]
    engine = SloEngine(
        [_latency_spec(fast_window_s=1.0, slow_window_s=30.0,
                       burn_threshold=3.0)],
        clock=lambda: clock[0],
    )
    fv = FleetView(staleness_s=1e6, clock=lambda: clock[0])
    h = Histogram()
    for t in range(0, 25):  # long healthy history
        for _ in range(20):
            h.observe(0.01)
        _feed(engine, fv, clock, float(t), h)
    out = None
    for t in range(25, 27):  # 2s spike
        for _ in range(5):
            h.observe(5.0)
        out = _feed(engine, fv, clock, float(t), h)
    assert out["alerts"] == [], out
    st = [s for s in out["slos"] if s["name"] == "req"][0]
    assert st["burn_fast"] > 3.0  # the spike IS visible fast...
    assert st["burn_slow"] < 3.0  # ...but the slow window vetoes the page


def test_slo_no_evidence_is_not_an_outage():
    clock = [0.0]
    engine = SloEngine([_latency_spec(min_events=4)], clock=lambda: clock[0])
    fv = FleetView(staleness_s=1e6, clock=lambda: clock[0])
    h = Histogram()
    h.observe(9.0)  # one bad sample, below min_events
    out = _feed(engine, fv, clock, 0.0, h)
    st = out["slos"][0]
    assert st["burn_fast"] == 0.0 and not st["firing"]


def test_slo_ratio_orphan_rate():
    clock = [0.0]
    spec = SloSpec(
        "orphans", "ratio", objective=0.9,
        bad=("sched.jobs_orphaned",),
        total=("sched.jobs_completed", "sched.jobs_orphaned"),
        fast_window_s=2.0, slow_window_s=6.0, burn_threshold=2.0,
        min_events=2,
    )
    engine = SloEngine([spec], clock=lambda: clock[0])
    fv = FleetView(staleness_s=1e6, clock=lambda: clock[0])
    done, orphaned = 0, 0
    out = None
    for t in range(0, 8):
        clock[0] = float(t)
        done += 1
        orphaned += 1  # 50% orphan rate >> 10% budget
        fv.ingest("s", {"seq": 1, "counters": {
            "sched.jobs_completed": done, "sched.jobs_orphaned": orphaned,
        }}, now=clock[0])
        out = engine.tick(fv, now=clock[0])
    assert out["alerts"] == ["orphans"]


def test_slo_liveness_counts_stale_sources():
    clock = [0.0]
    spec = SloSpec(
        "live", "liveness", objective=0.6, fast_window_s=2.0,
        slow_window_s=6.0, burn_threshold=1.0, min_events=2,
    )
    engine = SloEngine([spec], clock=lambda: clock[0])
    fv = FleetView(staleness_s=1.0, clock=lambda: clock[0])
    fv.ingest("gone", {"seq": 1}, now=0.0)
    fv.ingest("here", {"seq": 1}, now=0.0)
    out = None
    for t in range(0, 8):
        clock[0] = float(t)
        fv.ingest("here", {"seq": 1 + t}, now=clock[0])
        out = engine.tick(fv, now=clock[0])  # "gone" stale from t>=2
    assert out["alerts"] == ["live"], out


def test_slo_liveness_excludes_the_hubs_own_source():
    """Regression: the server ingests its own registry every tick, so it
    is always fresh — counting it would dilute a dead miner's stale
    fraction (1 dead of {miner, server} = 0.5 -> burn 5 < default 6:
    a fully dead fleet member never pages).  The hub passes exclude=
    (its source,) and liveness must honor it."""
    clock = [0.0]
    spec = SloSpec(
        "live", "liveness", objective=0.9, fast_window_s=2.0,
        slow_window_s=6.0, burn_threshold=6.0, min_events=2,
    )
    engine = SloEngine([spec], clock=lambda: clock[0])
    fv = FleetView(staleness_s=1.0, clock=lambda: clock[0])
    fv.ingest("miner", {"seq": 1}, now=0.0)
    out = None
    for t in range(0, 8):
        clock[0] = float(t)
        fv.ingest("server", {"seq": 1 + t}, now=clock[0])  # always fresh
        out = engine.tick(fv, now=clock[0], exclude=("server",))
    # the one real fleet member is 100% stale: burn 1.0/0.1 = 10 > 6
    assert out["alerts"] == ["live"], out


def test_slo_latency_evidence_is_monotonic_across_staleness():
    """Regression: SLO evidence diffs CUMULATIVE totals, so it must come
    from the include_stale merge — with a fresh-only view, a source
    carrying old bad samples that goes silent past the window and then
    reconnects UNCHANGED would re-add its whole history as one step and
    fire an alert with zero new events."""
    clock = [0.0]
    engine = SloEngine(
        [_latency_spec(fast_window_s=5.0, slow_window_s=20.0,
                       burn_threshold=2.0)],
        clock=lambda: clock[0],
    )
    fv = FleetView(staleness_s=2.0, clock=lambda: clock[0])
    h = _hist_of([5.0] * 40 + [0.01] * 60)  # old mixed history: 40% bad
    st = h.state()
    fired0 = METRICS.get("slo.alerts_fired")
    fv.ingest("m", {"seq": 1, "hists": {"hist.request_s": st}}, now=0.0)
    out = engine.tick(fv, now=0.0)
    # silence well past every window: the source goes stale
    for t in (10.0, 20.0, 30.0):
        clock[0] = t
        out = engine.tick(fv, now=t)
        assert out["alerts"] == [], out
    # reconnect with the IDENTICAL cumulative state: no new events, so
    # no window may see a delta and nothing may fire
    clock[0] = 31.0
    fv.ingest("m", {"seq": 1, "hists": {"hist.request_s": st}}, now=31.0)
    for t in (31.0, 32.0, 33.0):
        clock[0] = t
        out = engine.tick(fv, now=t)
        assert out["alerts"] == [], out
    assert METRICS.get("slo.alerts_fired") == fired0


def test_parse_slo_config_vocabulary():
    assert [s.name for s in parse_slo_config("")] == [
        "request-p95", "chunk-rtt-p95", "orphan-rate", "miner-liveness"]
    specs = parse_slo_config("req_p95=0.25,window=5/20,burn=2,orphan=0.02")
    by = {s.name: s for s in specs}
    assert by["request-p95"].threshold_s == 0.25
    assert by["request-p95"].fast_window_s == 5.0
    assert by["request-p95"].slow_window_s == 20.0
    assert by["request-p95"].burn_threshold == 2.0
    assert by["orphan-rate"].objective == pytest.approx(0.98)
    with pytest.raises(ValueError):
        parse_slo_config("nonsense=1")
    with pytest.raises(ValueError):
        parse_slo_config("req_p95")


# --------------------------------------------------------------------------
# 3. The channel: framing + exporter→hub loopback + subscriber stream
# --------------------------------------------------------------------------


def test_frames_roundtrip_under_wire_ceiling():
    big = {"v": 1, "source": "x", "blob": os.urandom(3000).hex()}
    frames = encode_frames(big, 42)
    assert len(frames) > 1
    # every fragment's marshaled LSP datagram must fit the frozen
    # 1000-byte read-buffer ceiling (lsp.MAX_MESSAGE_SIZE)
    from bitcoin_miner_tpu.lsp.message import Message as LspMessage

    for i, f in enumerate(frames):
        wire = LspMessage.data(999999, 999999, len(f), f).marshal()
        assert len(wire) <= lsp.MAX_MESSAGE_SIZE, (i, len(wire))
    asm = FrameAssembler()
    outs = [asm.feed(f) for f in frames]
    assert outs[-1] == (True, big)
    assert all(done is False for done, _ in outs[:-1])


def test_frame_assembler_tolerates_garbage_and_torn_streams():
    asm = FrameAssembler()
    assert asm.feed(b"T1|x|y|z|junk") == (True, None)
    assert asm.feed(b"\xff\xferaw")[0] is True
    a, b = encode_frames({"v": 1, "source": "a",
                          "blob": os.urandom(600).hex()}, 1)[:2]
    # torn stream: first fragment of msg 1, then a fresh msg 2 restarts
    assert asm.feed(a) == (False, None)
    small = {"v": 1, "source": "b"}
    (frame,) = encode_frames(small, 2)
    assert asm.feed(frame) == (True, small)
    # joining mid-message is dropped, then recovery works
    assert asm.feed(b)[1] is None
    assert asm.feed(frame) == (True, small)


def test_frame_assembler_counts_one_loss_per_message_not_per_fragment():
    """Regression: a lost 8-fragment message must show as ONE decode
    error, not 7 — the counter an operator judges channel health by
    must not over-report by the fragmentation factor."""
    frames = encode_frames(
        {"v": 1, "source": "a", "blob": os.urandom(2000).hex()}, 9
    )
    assert len(frames) >= 4
    asm = FrameAssembler()
    # joined mid-message: fragment 1..n of msg 9 without fragment 0
    outs = [asm.feed(f) for f in frames[1:]]
    assert outs[0] == (True, None)  # the one reported loss
    assert all(o == (False, None) for o in outs[1:])  # silently skipped
    # a fresh complete message afterwards still assembles
    small = {"v": 1, "source": "b"}
    (frame,) = encode_frames(small, 10)
    assert asm.feed(frame) == (True, small)


def test_frame_assembler_bounds_hostile_input():
    """The ingest port is unauthenticated: a peer declaring a billion
    fragments or shipping a zlib bomb must be dropped, not buffered or
    inflated."""
    import zlib as _zlib

    from bitcoin_miner_tpu.utils.telemetry import _FRAG_LIMIT, _MAX_MSG_BYTES

    asm = FrameAssembler()
    bomb_header = b"T1|1|0|1000000000|" + b"x" * 100
    assert asm.feed(bomb_header) == (True, None)
    assert asm._parts == []  # nothing buffered
    # zlib bomb: ~100KB compressed -> ~1GB decompressed must not inflate
    blob = _zlib.compress(b"\x00" * (_MAX_MSG_BYTES * 4))
    n = (len(blob) + 479) // 480
    assert n <= _FRAG_LIMIT
    frames = [
        b"T1|2|%d|%d|" % (i, n) + blob[i * 480:(i + 1) * 480]
        for i in range(n)
    ]
    outs = [asm.feed(f) for f in frames]
    assert outs[-1] == (True, None)  # dropped at the inflate cap


def test_exporter_hub_loopback_merges_and_publishes():
    tmp_log = None
    hub = TelemetryHub(
        0, params=PARAMS, slo=SloEngine(default_slos()),
        publish_interval=0.1, source=None,
    ).start(self_tick=0.1)
    reg = Metrics()
    reg.inc("miner.nonces", 77)
    for i in range(30):
        reg.observe("hist.miner_chunk_s", 0.05 * (1 + i % 3))
    exp = TelemetryExporter(
        "127.0.0.1", hub.port, "m1", interval=0.1, params=PARAMS,
        registry=reg,
    ).start()
    try:
        deadline = time.time() + 15
        while time.time() < deadline:
            st = hub.last_state()
            if st and st["counters"].get("miner.nonces") == 77:
                break
            time.sleep(0.05)
        st = hub.last_state()
        assert st and st["counters"].get("miner.nonces") == 77, st
        assert st["hists"]["hist.miner_chunk_s"]["count"] == 30
        assert st["slo"]["alerts"] == []
        assert st["per_source"]["m1"]["stale"] is False
        # subscriber stream: the tools.dash --connect path
        c = lsp.Client("127.0.0.1", hub.port, PARAMS)
        try:
            c.write(encode_subscribe())
            asm = FrameAssembler()
            got = None
            deadline = time.time() + 15
            while got is None and time.time() < deadline:
                done, obj = asm.feed(c.read())
                if done and isinstance(obj, dict):
                    got = obj
            assert got is not None and "counters" in got and "sources" in got
        finally:
            c.close()
    finally:
        exp.stop()
        hub.close()


def test_hub_publish_sinks_fleet_log_and_prom(tmp_path):
    fleet_log = str(tmp_path / "fleet.jsonl")
    prom = str(tmp_path / "metrics.prom")
    reg = Metrics()
    reg.inc("sched.jobs_completed", 2)
    hub = TelemetryHub(
        0, params=PARAMS, publish_interval=0.0, source="server",
        registry=reg, fleet_log=fleet_log, prom_path=prom,
    ).start()
    try:
        hub.tick()
        hub.tick()
        rows = [json.loads(line) for line in open(fleet_log)]
        assert rows and rows[-1]["counters"]["sched.jobs_completed"] == 2
        text = open(prom).read()
        assert "bmt_sched_jobs_completed 2" in text
    finally:
        hub.close()


# --------------------------------------------------------------------------
# 4. Dashboard rendering
# --------------------------------------------------------------------------


def _sample_state():
    return {
        "sources": 2, "stale_sources": 1,
        "per_source": {
            "m1": {"age_s": 0.5, "stale": False, "seq": 9},
            "m2": {"age_s": 22.0, "stale": True, "seq": 4},
            "server": {"age_s": 0.1, "stale": False, "seq": -1},
        },
        "counters": {"sched.jobs_completed": 5, "telemetry.exports": 9},
        "gauges": {"gauge.miners_live": 2.0},
        "hists": {
            "hist.request_s": {"count": 4, "mean": 0.2, "p50": 0.2,
                               "p95": 0.4, "p99": 0.4},
            "hist.chunk_rtt_s": {"count": 0, "mean": 0.0, "p50": 0.0,
                                 "p95": 0.0, "p99": 0.0},
        },
        "stragglers": [{"source": "m2", "p50_s": 1.2, "fleet_p50_s": 0.2,
                        "ratio": 6.0, "samples": 12}],
        "slo": {
            "slos": [
                {"name": "request-p95", "kind": "latency", "objective": 0.95,
                 "burn_fast": 8.2, "burn_slow": 7.1, "window_events": 40,
                 "firing": True, "ok": False},
            ],
            "alerts": ["request-p95"],
        },
    }


def test_dash_render_frame_shows_slo_stragglers_and_dashes():
    from tools.dash import render_frame

    frame = render_frame(_sample_state())
    assert "2/3 sources fresh" in frame
    assert "STALE" in frame
    assert "request-p95" in frame and "ALERT" in frame
    assert "m2" in frame and "6.0x" in frame
    # the empty histogram renders -, never a misleading 0 (ISSUE 7)
    assert "-/-/-" in frame
    assert "sched.jobs_completed" in frame


def test_dash_follow_waits_for_a_fleet_log_that_does_not_exist_yet(tmp_path):
    """Regression: --follow races the server's FIRST publish (the hub
    creates the file on its first rate-limited beat) — follow mode must
    wait for the file, not die on FileNotFoundError."""
    from tools.dash import _states_from_file

    path = tmp_path / "later.jsonl"
    gen = _states_from_file(str(path), follow=True, poll_s=0.05)

    def _create():
        time.sleep(0.2)
        with open(path, "w") as f:
            f.write(json.dumps({"sources": 1, "stale_sources": 0}) + "\n")

    t = threading.Thread(target=_create, daemon=True)
    t.start()
    state = next(gen)
    assert state["sources"] == 1
    # non-follow mode on a missing file still reports the error
    with pytest.raises(SystemExit):
        next(_states_from_file(str(tmp_path / "nope.jsonl"), follow=False,
                               poll_s=0.05))


def test_dash_main_once_reads_fleet_log(tmp_path, capsys):
    from tools.dash import main as dash_main

    path = tmp_path / "fleet.jsonl"
    with open(path, "w") as f:
        f.write("this line is torn garbage\n")
        f.write(json.dumps(_sample_state()) + "\n")
        f.write('{"half": ')  # torn tail: skipped
    assert dash_main([str(path), "--once"]) == 0
    out = capsys.readouterr().out
    assert "sources fresh" in out and "request-p95" in out
    # an empty file reports no state
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert dash_main([str(empty), "--once"]) == 1


def test_loadgen_rejects_combined_overhead_flags():
    """The two overhead modes share one bare comparison leg; combining
    them would misattribute the planes' combined cost to each number."""
    import tools.loadgen as loadgen

    with pytest.raises(SystemExit):
        loadgen.main(["--fast", "--telemetry-overhead", "--trace-overhead"])


def test_server_main_reports_busy_telemetry_port_cleanly(capsys):
    """A busy --telemetry-port gets the same friendly one-line error as a
    busy serving port — never a traceback."""
    squatter = lsp.Server(0, PARAMS)
    try:
        rc = server_mod.main(
            ["server", "0", f"--telemetry-port={squatter.port}"]
        )
        assert rc == 0
        out = capsys.readouterr().out
        assert "Traceback" not in out
        assert "Server listening on port" in out  # serving port was fine
    finally:
        squatter.close()


# --------------------------------------------------------------------------
# 5. The acceptance drill: induced straggler + burst loss vs clean run
# --------------------------------------------------------------------------


def _run_drill_fleet(slow_idx, chaos_seed, data, n_miners=3, jobs=4,
                     max_nonce=1500):
    """A real loopback fleet with per-miner telemetry registries.  The
    ``slow_idx`` miner sleeps 1.5 s per chunk (the induced straggler);
    ``chaos_seed`` arms seeded Gilbert–Elliott burst loss on the wire.
    The chunk-RTT objective sits at 0.75 s: half the induced latency
    (every straggler chunk is definitively bad) but far above anything a
    healthy loopback chunk hits even on a loaded CI box — the clean leg
    must stay quiet without wall-clock luck.
    Returns (results, final hub state, alerts seen at any tick)."""
    if chaos_seed is not None:
        # Sustained (not scheduled) burst loss for the whole drill — mild
        # enough that LSP retransmits ride it out, bursty enough to be a
        # real degraded-network leg.
        CHAOS.seed(chaos_seed)
        CHAOS.set_conditions(
            ge=GEParams(p_enter_bad=4, p_exit_bad=25, loss_bad=60)
        )
    engine = SloEngine(default_slos(
        chunk_threshold_s=0.75, fast_window_s=3.0, slow_window_s=8.0,
        burn_threshold=2.0, min_events=3,
    ))
    hub = TelemetryHub(
        0, params=PARAMS, slo=engine, publish_interval=0.2,
        straggler_min_samples=4,
    ).start()
    server = lsp.Server(0, PARAMS, label="server")
    threading.Thread(
        target=server_mod.serve,
        args=(server, Scheduler(min_chunk=300, max_chunk=300,
                                straggler_min_seconds=30.0)),
        kwargs={"tick_interval": 0.1, "health_interval": 1.0,
                "telemetry": hub},
        daemon=True,
    ).start()
    exporters = []
    stop_evt = threading.Event()
    try:
        for i in range(n_miners):
            reg = Metrics()

            def search(d, lo, hi, _i=i, _reg=reg):
                t0 = time.monotonic()
                if _i == slow_idx:
                    time.sleep(1.5)
                r = min_hash_range(d, lo, hi)
                _reg.observe("hist.miner_chunk_s", time.monotonic() - t0)
                return r

            # The self-healing miner lifetime: burst loss may kill a conn
            # mid-drill and the re-Join machinery (PR 2) rides it out.
            threading.Thread(
                target=miner_mod.run_miner_resilient,
                args=("127.0.0.1", server.port, search),
                kwargs={"params": PARAMS, "max_retries": 10,
                        "backoff_base": 0.05, "backoff_cap": 0.3,
                        "label": f"miner-{i}", "stop": stop_evt},
                daemon=True,
            ).start()
            exporters.append(TelemetryExporter(
                "127.0.0.1", hub.port, f"m{i}", interval=0.15,
                params=PARAMS, registry=reg,
            ).start())
        results = []
        alerts_seen = set()
        stragglers_seen = set()
        for j in range(jobs):
            results.append(
                (f"{data}{j}",
                 client_mod.request_with_retry(
                     "127.0.0.1", server.port, f"{data}{j}", max_nonce,
                     retries=5, backoff_base=0.1, params=PARAMS,
                     label=f"client-{j}",
                 ))
            )
            st = hub.last_state()
            if st:
                alerts_seen.update(st.get("slo", {}).get("alerts", []))
                stragglers_seen.update(
                    s["source"] for s in st.get("stragglers", [])
                )
        # a few extra beats so the last chunks' evidence lands
        deadline = time.time() + 8
        while time.time() < deadline:
            st = hub.last_state()
            if st:
                alerts_seen.update(st.get("slo", {}).get("alerts", []))
                stragglers_seen.update(
                    s["source"] for s in st.get("stragglers", [])
                )
            if (slow_idx is None) or (
                alerts_seen and f"m{slow_idx}" in stragglers_seen
            ):
                break
            time.sleep(0.1)
        return results, hub.last_state(), alerts_seen, stragglers_seen
    finally:
        stop_evt.set()
        for e in exporters:
            e.stop()
        CHAOS.reset()
        server.close()
        hub.close()


@pytest.mark.chaos
def test_acceptance_drill_straggler_and_burst_loss_fire_alert():
    """ISSUE 7 acceptance: the seeded drill (induced straggler m2 +
    Gilbert–Elliott burst loss) fires the chunk-RTT burn-rate alert and
    the straggler detector names the induced miner — with every Result
    still bit-exact."""
    fired0 = METRICS.get("slo.alerts_fired")
    results, state, alerts, stragglers = _run_drill_fleet(
        slow_idx=2, chaos_seed=11, data="drillhot"
    )
    for data, got in results:
        assert got == min_hash_range(data, 0, 1500), data
    assert "chunk-rtt-p95" in alerts, (alerts, state and state.get("slo"))
    assert "m2" in stragglers, (stragglers, state)
    assert METRICS.get("slo.alerts_fired") > fired0


@pytest.mark.chaos
def test_acceptance_drill_clean_run_stays_quiet():
    """The control leg: same fleet, no straggler, no chaos — every SLO
    quiet and nobody flagged."""
    fired0 = METRICS.get("slo.alerts_fired")
    results, state, alerts, stragglers = _run_drill_fleet(
        slow_idx=None, chaos_seed=None, data="drillcold"
    )
    for data, got in results:
        assert got == min_hash_range(data, 0, 1500), data
    assert alerts == set(), (alerts, state and state.get("slo"))
    assert stragglers == set(), stragglers
    assert METRICS.get("slo.alerts_fired") == fired0


# --------------------------------------------------------------------------
# Federation view: per-cell fleet views merged into one (ISSUE 8 satellite)
# --------------------------------------------------------------------------


class TestFederationView:
    """Two cells' hub views folded into one federation FleetView via
    export_sources/ingest_cell: counters must not regress or double
    count, sources keep per-cell identity, and the straggler detector
    still names the right miner."""

    def _cell(self, sources, now=0.0):
        fv = FleetView(staleness_s=10.0, clock=lambda: 0.0)
        for name, counters, hist_samples in sources:
            state = {"seq": 1, "counters": counters}
            if hist_samples is not None:
                state["hists"] = {
                    "hist.miner_chunk_s": _hist_of(hist_samples).state()
                }
            fv.ingest(name, state, now=now)
        return fv

    def test_counters_sum_once_across_cells(self):
        a = self._cell([("m1", {"miner.nonces": 100}, None),
                        ("m2", {"miner.nonces": 50}, None)])
        b = self._cell([("m3", {"miner.nonces": 7}, None)])
        fed = FleetView(staleness_s=10.0, clock=lambda: 0.0)
        assert fed.ingest_cell("cellA", a.export_sources(now=0.0), now=0.0) == 2
        assert fed.ingest_cell("cellB", b.export_sources(now=0.0), now=0.0) == 1
        merged = fed.merged(now=0.0)
        assert merged["counters"]["miner.nonces"] == 157
        assert merged["sources"] == 3

    def test_reingest_does_not_double_count(self):
        a = self._cell([("m1", {"miner.nonces": 100}, None)])
        fed = FleetView(staleness_s=10.0, clock=lambda: 0.0)
        export = a.export_sources(now=0.0)
        fed.ingest_cell("cellA", export, now=0.0)
        fed.ingest_cell("cellA", export, now=0.0)  # a republished export
        merged = fed.merged(now=0.0)
        assert merged["counters"]["miner.nonces"] == 100  # not 200
        assert merged["sources"] == 1

    def test_same_miner_name_in_two_cells_stays_distinct(self):
        a = self._cell([("m1", {"miner.nonces": 10}, None)])
        b = self._cell([("m1", {"miner.nonces": 5}, None)])
        fed = FleetView(staleness_s=10.0, clock=lambda: 0.0)
        fed.ingest_cell("cellA", a.export_sources(now=0.0), now=0.0)
        fed.ingest_cell("cellB", b.export_sources(now=0.0), now=0.0)
        merged = fed.merged(now=0.0)
        # Two sources, both contributions counted — the cell prefix is
        # what makes the collision impossible.
        assert merged["sources"] == 2
        assert merged["counters"]["miner.nonces"] == 15
        assert set(fed.sources(now=0.0)) == {"cellA/m1", "cellB/m1"}

    def test_staleness_carries_across_the_cell_boundary(self):
        a = self._cell([("fresh", {"n": 1}, None)])
        a.ingest("stale", {"seq": 1, "counters": {"n": 1}}, now=-30.0)
        fed = FleetView(staleness_s=10.0, clock=lambda: 0.0)
        fed.ingest_cell("cellA", a.export_sources(now=0.0), now=0.0)
        src = fed.sources(now=0.0)
        assert src["cellA/fresh"]["stale"] is False
        assert src["cellA/stale"]["stale"] is True
        merged = fed.merged(now=0.0)
        assert merged["sources"] == 1 and merged["stale_sources"] == 1

    def test_straggler_detection_names_the_right_cell_miner(self):
        fast = [0.01 * (1 + 0.1 * (i % 3)) for i in range(20)]
        slow = [0.4 * (1 + 0.1 * (i % 3)) for i in range(20)]
        a = self._cell([("m0", {}, fast), ("m1", {}, fast)])
        b = self._cell([("m0", {}, fast), ("slowpoke", {}, slow)])
        fed = FleetView(staleness_s=10.0, clock=lambda: 0.0)
        fed.ingest_cell("cellA", a.export_sources(now=0.0), now=0.0)
        fed.ingest_cell("cellB", b.export_sources(now=0.0), now=0.0)
        out = fed.stragglers(now=0.0)
        assert [s["source"] for s in out] == ["cellB/slowpoke"]

    def test_dash_cells_frame_merges_for_display(self):
        """tools/dash.py --cells: per-cell merged states render as one
        federation frame — counters summed, sources/stragglers prefixed."""
        import sys
        from pathlib import Path

        sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
        from tools.dash import merge_cell_states, render_frame

        state_a = {
            "sources": 2, "stale_sources": 0,
            "per_source": {"m1": {"age_s": 1.0, "stale": False}},
            "counters": {"sched.jobs_completed": 3},
            "hists": {"hist.request_s": {"count": 2, "p50": 0.1,
                                         "p95": 0.2, "p99": 0.2}},
            "stragglers": [{"source": "m1", "p50_s": 0.4,
                            "fleet_p50_s": 0.01, "ratio": 40.0}],
        }
        state_b = {
            "sources": 1, "stale_sources": 1,
            "per_source": {"m1": {"age_s": 2.0, "stale": False}},
            "counters": {"sched.jobs_completed": 4},
            "slo": {"slos": [{"name": "req_p95", "burn_fast": 9.0,
                              "burn_slow": 8.0, "ok": False,
                              "firing": True}], "alerts": ["req_p95"]},
        }
        merged = merge_cell_states({"cellA": state_a, "cellB": state_b})
        assert merged["sources"] == 3 and merged["stale_sources"] == 1
        assert merged["counters"]["sched.jobs_completed"] == 7
        assert set(merged["per_source"]) == {"cellA/m1", "cellB/m1"}
        assert merged["stragglers"][0]["source"] == "cellA/m1"
        assert merged["slo"]["alerts"] == ["cellB/req_p95"]
        frame = render_frame(merged)
        assert "cellA/m1" in frame and "cellB/req_p95" in frame
