"""Suite 3 parity: close/CloseConn semantics + slow-start
(reference lsp/lsp3_test.go).

- TestServerSlowStart1-2 (:322-338): the server starts epochs late; the
  client's Connect-retry loop must still establish the connection
  (:177-181).
- TestClientClose1-2 (:340-392): client closes after N echoes; close blocks
  until pending sends are acked; the server must observe the client's death
  via a Read error carrying the conn id (:202-207).
- TestServerCloseConns / TestServerClose: one side closes; the other
  observes termination via Read error (:302-311).
- Connect to a dead port fails with CannotEstablishConnection after
  EpochLimit epochs (lsp/client_impl.go:111-125).
"""

import time

import pytest

from bitcoin_miner_tpu import lsp, lspnet
from lsp_harness import random_port, spawn

EPOCH_MS = 100


def params(limit=5, w=2):
    return lsp.Params(epoch_limit=limit, epoch_millis=EPOCH_MS, window_size=w)


@pytest.fixture(autouse=True)
def _reset_faults():
    lspnet.reset_faults()
    yield
    lspnet.reset_faults()


class TestSlowStart:
    def test_server_starts_late(self):
        port = random_port()
        results = {}

        def connect():
            try:
                c = lsp.Client("127.0.0.1", port, params())
                c.write(b"ping")
                results["echo"] = c.read()
                c.close()
            except lsp.LspError as e:
                results["err"] = e

        t = spawn(connect)
        time.sleep(3 * EPOCH_MS / 1000)  # 3 epochs of darkness
        server = lsp.Server(port, params())
        cid, payload = server.read()
        assert payload == b"ping"
        server.write(cid, payload)
        t.join(timeout=5)
        assert results.get("echo") == b"ping", results
        server.close()

    def test_connect_gives_up_after_epoch_limit(self):
        t0 = time.time()
        with pytest.raises(lsp.CannotEstablishConnectionError):
            lsp.Client("127.0.0.1", random_port(), params(limit=3))
        elapsed = time.time() - t0
        # 3 epochs of retries (plus scheduling slack), not forever.
        assert 2.5 * EPOCH_MS / 1000 <= elapsed <= 20 * EPOCH_MS / 1000, elapsed


class TestClientClose:
    def test_close_drains_pending_sends(self):
        """Write a burst beyond the window, close immediately: every message
        must still reach the server (lsp4's FastClose cousin lives in suite
        4; this is the loss-free drain)."""
        server = lsp.Server(0, params(w=2))
        received = []

        def server_loop():
            while True:
                try:
                    _cid, p = server.read()
                    received.append(p)
                except lsp.ConnLostError:
                    continue
                except lsp.LspError:
                    return

        spawn(server_loop)
        client = lsp.Client("127.0.0.1", server.port, params(w=2))
        total = 20
        for i in range(total):
            client.write(b"x%d" % i)
        client.close()  # must block until all 20 are acked
        deadline = time.time() + 1.0
        while len(received) < total and time.time() < deadline:
            time.sleep(0.01)
        assert received == [b"x%d" % i for i in range(total)]
        server.close()

    def test_server_detects_client_death(self):
        server = lsp.Server(0, params(limit=3))
        client = lsp.Client("127.0.0.1", server.port, params(limit=3))
        client.write(b"hello")
        cid, _ = server.read()
        client.close()
        # After the client goes silent, the server must surface the loss as
        # a Read error carrying the dead conn id (server_api.go:10-16).
        with pytest.raises(lsp.ConnLostError) as ei:
            while True:
                server.read()
        assert ei.value.conn_id == cid
        server.close()

    def test_write_after_close_raises(self):
        server = lsp.Server(0, params())
        client = lsp.Client("127.0.0.1", server.port, params())
        client.write(b"a")
        server.read()
        client.close()
        with pytest.raises(lsp.LspError):
            client.write(b"b")
        server.close()


class TestServerClose:
    def test_close_conn_terminates_client(self):
        server = lsp.Server(0, params(limit=3))
        client = lsp.Client("127.0.0.1", server.port, params(limit=3))
        client.write(b"hi")
        cid, _ = server.read()
        server.close_conn(cid)
        with pytest.raises(lsp.LspError):
            while True:
                client.read()
        server.close()

    def test_server_close_terminates_all_clients(self):
        server = lsp.Server(0, params(limit=3))
        clients = []
        for _ in range(3):
            c = lsp.Client("127.0.0.1", server.port, params(limit=3))
            c.write(b"hi")
            clients.append(c)
        seen = set()
        for _ in range(3):
            cid, _ = server.read()
            seen.add(cid)
        assert len(seen) == 3
        server.close()
        # server.read now reports closure
        with pytest.raises(lsp.ConnClosedError):
            server.read()
        # every client observes termination
        for c in clients:
            with pytest.raises(lsp.LspError):
                while True:
                    c.read()

    def test_server_close_drains_pending_writes(self):
        """Server writes a burst to a client and closes; the client must
        still receive everything (drain-before-shutdown)."""
        server = lsp.Server(0, params(w=2))
        client = lsp.Client("127.0.0.1", server.port, params(w=2))
        client.write(b"hi")
        cid, _ = server.read()
        total = 15
        for i in range(total):
            server.write(cid, b"s%d" % i)
        server.close()  # blocks until drained
        got = []
        try:
            while len(got) < total:
                got.append(client.read())
        except lsp.LspError:
            pass
        assert got == [b"s%d" % i for i in range(total)]
        client.close()
