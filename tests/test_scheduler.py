"""Unit tests of the pure Scheduler logic (no sockets, no JAX).

The reference has no scheduler tests (its server is a stub); these pin the
behavior SURVEY §3.6 reconstructs from the frozen contracts: join/request/
result folding, adaptive chunking, dead-miner reassignment, dead-client
cancellation, fairness.
"""

from bitcoin_miner_tpu.apps.scheduler import Scheduler
from bitcoin_miner_tpu.bitcoin.hash import min_hash_range
from bitcoin_miner_tpu.bitcoin.message import MsgType


def drain(job_actions):
    return {cid: msg for cid, msg in job_actions}


class TestBasicFlow:
    def test_join_then_request_assigns(self):
        s = Scheduler(validate_results=False, min_chunk=100)
        assert s.miner_joined(1) == []
        actions = s.client_request(10, "data", 0, 99)
        assert len(actions) == 1
        cid, msg = actions[0]
        assert cid == 1
        assert msg.type == MsgType.REQUEST
        assert (msg.lower, msg.upper) == (0, 99)

    def test_request_then_join_assigns(self):
        s = Scheduler(validate_results=False, min_chunk=100)
        assert s.client_request(10, "data", 0, 99) == []
        actions = s.miner_joined(1)
        assert len(actions) == 1
        assert actions[0][0] == 1

    def test_result_completes_job(self):
        s = Scheduler(validate_results=False, min_chunk=1000)
        s.miner_joined(1)
        s.client_request(10, "data", 0, 99)
        actions = s.result(1, hash_=555, nonce=42)
        assert actions[0] == (10, actions[0][1])
        msg = actions[0][1]
        assert msg.type == MsgType.RESULT
        assert (msg.hash, msg.nonce) == (555, 42)
        assert s.jobs == {}
        assert s.miners[1].job is None  # miner idle again

    def test_range_split_across_miners_min_folds(self):
        s = Scheduler(validate_results=False, min_chunk=50)
        for m in (1, 2):
            s.miner_joined(m)
        actions = s.client_request(10, "data", 0, 99)
        assert len(actions) == 2
        ranges = sorted((m.lower, m.upper) for _, m in actions)
        assert ranges == [(0, 49), (50, 99)]
        assert s.result(1, hash_=900, nonce=7) == []  # half done: no reply yet
        final = s.result(2, hash_=300, nonce=61)
        # min-fold picks the smaller hash
        assert final[0][1].hash == 300 and final[0][1].nonce == 61

    def test_tie_break_lowest_nonce(self):
        s = Scheduler(validate_results=False, min_chunk=50)
        s.miner_joined(1)
        s.miner_joined(2)
        s.client_request(10, "d", 0, 99)
        s.result(2, hash_=100, nonce=80)
        final = s.result(1, hash_=100, nonce=3)
        assert final[0][1].nonce == 3

    def test_empty_range_answers_immediately(self):
        s = Scheduler(validate_results=False)
        actions = s.client_request(10, "d", 5, 4)
        assert actions[0][0] == 10
        assert actions[0][1].type == MsgType.RESULT


class TestFaults:
    def test_dead_miner_chunk_reassigned(self):
        s = Scheduler(validate_results=False, min_chunk=1000)
        s.miner_joined(1)
        s.client_request(10, "d", 0, 499)
        actions = s.lost(1)  # miner dies mid-chunk
        assert actions == []  # nobody to reassign to yet
        actions = s.miner_joined(2)  # replacement arrives
        assert len(actions) == 1
        assert (actions[0][1].lower, actions[0][1].upper) == (0, 499)

    def test_dead_miner_with_idle_peer_reassigns_immediately(self):
        s = Scheduler(validate_results=False, min_chunk=1000)
        s.miner_joined(1)
        s.miner_joined(2)
        s.client_request(10, "d", 0, 499)  # one chunk -> one miner busy
        busy = next(m for m in s.miners.values() if m.job is not None).conn_id
        actions = s.lost(busy)
        assert len(actions) == 1  # idle peer picks it straight up
        assert (actions[0][1].lower, actions[0][1].upper) == (0, 499)

    def test_dead_client_drops_job_and_result_ignored(self):
        s = Scheduler(validate_results=False, min_chunk=1000)
        s.miner_joined(1)
        s.client_request(10, "d", 0, 499)
        assert s.lost(10) == []  # client dies: job cancelled silently
        assert s.jobs == {}
        actions = s.result(1, hash_=5, nonce=5)  # stale result arrives
        assert actions == []  # ignored, miner back to idle
        assert s.miners[1].job is None

    def test_miner_death_preserves_low_nonce_order(self):
        s = Scheduler(validate_results=False, min_chunk=100, max_chunk=100)
        s.miner_joined(1)
        s.client_request(10, "d", 0, 299)  # miner 1 gets [0,99]
        s.lost(1)
        actions = s.miner_joined(2)  # must get [0,99] back first, not [100,199]
        assert (actions[0][1].lower, actions[0][1].upper) == (0, 99)


class TestPipelining:
    def test_results_match_fifo(self):
        # Two chunks queued at one miner; results close them oldest-first.
        s = Scheduler(validate_results=False, min_chunk=100, max_chunk=100)
        s.miner_joined(1, now=0.0)
        s.client_request(10, "d", 0, 299, now=0.0)
        assert [a.interval for a in s.miners[1].queue] == [(0, 99), (100, 199)]
        s.result(1, hash_=5, nonce=7, now=1.0)
        # (0,99) closed; (100,199) promoted to front; refill appended.
        assert s.miners[1].queue[0].interval == (100, 199)
        assert 0 not in [iv for lst in s.jobs[10].outstanding.values() for iv in lst]

    def test_rate_uses_result_gap_not_assignment_time(self):
        # Both chunks assigned at t=0; results at t=10 and t=11.  The second
        # sample must be size/1s (result gap), not size/11s.
        s = Scheduler(
            validate_results=False, min_chunk=100, max_chunk=100, rate_alpha=1.0
        )
        s.miner_joined(1, now=0.0)
        s.client_request(10, "d", 0, 299, now=0.0)
        s.result(1, hash_=5, nonce=7, now=10.0)
        assert s.miners[1].rate == 100 / 10.0
        s.result(1, hash_=5, nonce=107, now=11.0)
        assert s.miners[1].rate == 100 / 1.0

    def test_lost_miner_requeues_all_chunks_in_order(self):
        s = Scheduler(validate_results=False, min_chunk=100, max_chunk=100)
        s.miner_joined(1, now=0.0)
        s.client_request(10, "d", 0, 299, now=0.0)  # holds (0,99),(100,199)
        s.lost(1, now=1.0)
        assert list(s.jobs[10].pending) == [(0, 99), (100, 199), (200, 299)]

    def test_evicted_liar_requeues_queued_chunks(self):
        from bitcoin_miner_tpu.bitcoin.hash import min_hash_range

        s = Scheduler(min_chunk=100, max_chunk=100, max_rejects=1)
        s.miner_joined(1, now=0.0)
        s.client_request(10, "cmu440", 0, 299, now=0.0)
        s.result(1, hash_=1, nonce=2, now=1.0)  # lie -> instant eviction
        assert 1 not in s.miners
        # Both the lied-about front chunk AND the queued second chunk are
        # back in pending, in nonce order.
        assert list(s.jobs[10].pending) == [(0, 99), (100, 199), (200, 299)]
        s.miner_joined(2, now=2.0)
        h, n = min_hash_range("cmu440", 0, 299)
        for lo in (0, 100, 200):
            hh, nn = min_hash_range("cmu440", lo, lo + 99)
            final = s.result(2, hh, nn, now=3.0 + lo)
        assert final[0][1].hash == h and final[0][1].nonce == n

    def test_straggler_cascade_times_out_successor(self):
        # Front times out at t=11; the queued successor's clock starts
        # there, so it times out ~10s later, not immediately.
        s = Scheduler(
            validate_results=False,
            min_chunk=100,
            max_chunk=100,
            straggler_min_seconds=10.0,
        )
        s.miner_joined(1, now=0.0)
        s.client_request(10, "d", 0, 299, now=0.0)
        s.tick(11.0)
        assert [a.timed_out for a in s.miners[1].queue] == [True, False]
        assert s.tick(12.0) == []  # successor's deadline not reached
        s.tick(22.0)
        assert [a.timed_out for a in s.miners[1].queue] == [True, True]
        # Both duplicates pending (plus the never-assigned third chunk).
        assert sorted(s.jobs[10].pending) == [(0, 99), (100, 199), (200, 299)]

    def test_hung_miner_gets_no_new_work(self):
        s = Scheduler(
            validate_results=False,
            min_chunk=100,
            max_chunk=100,
            straggler_min_seconds=10.0,
        )
        s.miner_joined(1, now=0.0)
        s.client_request(10, "d", 0, 99, now=0.0)
        assert s.tick(11.0) == []  # re-queued, but the only miner is hung
        assert list(s.jobs[10].pending) == [(0, 99)]
        assert len(s.miners[1].queue) == 1  # NOT handed its own duplicate

    def test_ramp_boost_grows_chunks_geometrically(self):
        # A fast miner completing min_chunk in a blink gets ramp_factor x
        # its last chunk, not just rate*target (which the per-chunk latency
        # in the EWMA understates during ramp) — snapped to the nearest
        # 10^k rung of the aligned size ladder (8000 -> 10^4, ISSUE 10).
        s = Scheduler(
            validate_results=False,
            min_chunk=1000,
            target_chunk_seconds=0.5,
            rate_alpha=1.0,
            pipeline_depth=1,
            ramp_factor=8,
        )
        s.miner_joined(1, now=0.0)
        s.client_request(10, "d", 0, 10**9, now=0.0)
        # 1000 nonces in 0.2s -> EWMA rate 5000/s -> rate-based next chunk
        # would be 2500; the boost gives 8x1000 = 8000 -> rung 10^4.  The
        # carve cuts on the rung boundary, so lower=1000 runs to 9999 (a
        # runt up to the boundary); the NEXT chunk is a full aligned rung.
        actions = s.result(1, hash_=5, nonce=7, now=0.2)
        nxt = actions[0][1]
        assert (nxt.lower, nxt.upper) == (1000, 9999)
        # Still fast -> the ramp keeps climbing the ladder: next chunk is
        # a full aligned rung (10^5 here: 8x the 9000-nonce runt, snapped).
        actions = s.result(1, hash_=5, nonce=nxt.lower, now=0.4)
        nxt = actions[0][1]
        assert (nxt.lower, nxt.upper) == (10_000, 99_999)
        # Legacy (ladder off) keeps the raw boosted size.
        s2 = Scheduler(
            validate_results=False, min_chunk=1000,
            target_chunk_seconds=0.5, rate_alpha=1.0,
            pipeline_depth=1, ramp_factor=8, adaptive_chunks=False,
        )
        s2.miner_joined(1, now=0.0)
        s2.client_request(10, "d", 0, 10**9, now=0.0)
        actions = s2.result(1, hash_=5, nonce=7, now=0.2)
        assert actions[0][1].upper - actions[0][1].lower + 1 == 8000


class TestAdaptiveChunking:
    def test_fast_miner_gets_bigger_chunks(self):
        s = Scheduler(validate_results=False, min_chunk=100, max_chunk=10**9, target_chunk_seconds=1.0)
        s.miner_joined(1, now=0.0)
        s.client_request(10, "d", 0, 10**9, now=0.0)
        # first chunk is min_chunk (rate unknown)
        first = s.miners[1].interval
        assert first == (0, 99)
        # completes 100 nonces in 1 ms -> rate 1e5/s -> next chunk ~1e5
        actions = s.result(1, hash_=7, nonce=0, now=0.001)
        nxt = actions[0][1]
        size = nxt.upper - nxt.lower + 1
        assert 50_000 <= size <= 200_000

    def test_chunk_capped_at_max(self):
        s = Scheduler(validate_results=False, min_chunk=10, max_chunk=1000, target_chunk_seconds=1.0)
        s.miner_joined(1, now=0.0)
        s.client_request(10, "d", 0, 10**9, now=0.0)
        actions = s.result(1, hash_=7, nonce=0, now=1e-9)  # absurd rate
        nxt = actions[0][1]
        # Capped at max_chunk (the 10^3 rung) and cut on the rung
        # boundary: lower=20 (after the two cold chunks) runs to 999.
        assert nxt.upper - nxt.lower + 1 <= 1000
        assert (nxt.upper + 1) % 1000 == 0


class TestStealScan:
    """Straggler tail re-dispatch (ISSUE 10): a slow chunk's tail is
    handed to an idle miner, first completed sub-interval wins, and the
    interval-subtraction bookkeeping keeps every completion order
    bit-exact against a from-scratch sweep."""

    def _one_chunk_fleet(self, **kw):
        # Whole range in ONE chunk at miner 1; miner 2 idle.
        kw.setdefault("validate_results", False)
        kw.setdefault("min_chunk", 10**6)
        s = Scheduler(**kw)
        s.miner_joined(1, now=0.0)
        s.client_request(10, "d", 0, 999, now=0.0)
        s.miner_joined(2, now=0.0)
        return s

    def test_marked_straggler_tail_stolen_to_idle_miner(self):
        s = self._one_chunk_fleet()
        s.mark_straggler(1)  # the PR-7 fleet detector's external naming
        acts = s.tick(now=0.1)  # no age evidence needed: mark suffices
        assert len(acts) == 1
        cid, msg = acts[0]
        assert cid == 2 and msg.type == MsgType.REQUEST
        # The upper half: the straggler sweeps low nonces first.
        assert (msg.lower, msg.upper) == (500, 999)
        # The holder still owes the WHOLE interval; the tail is recorded
        # as its duplicated portion.
        assert s.miners[1].queue[0].stolen == (500, 999)

    def test_age_based_steal_needs_fleet_p50_evidence(self):
        s = Scheduler(
            validate_results=False, min_chunk=100, max_chunk=100,
            pipeline_depth=1, steal_min_seconds=0.0, steal_min_samples=4,
            straggler_min_seconds=0.0,
        )
        s.miner_joined(1, now=0.0)
        # Exactly 5 chunks: after 4 completions the LAST chunk is the
        # front and the job has no pending work left for a joiner.
        s.client_request(10, "d", 0, 499, now=0.0)
        # Build fleet evidence: 4 accepted chunks at ~0.1 s each
        # (miner EWMA rate ~1000 nonces/s).
        for i in range(4):
            s.result(1, hash_=5, nonce=100 * i, now=0.1 * (i + 1))
        s.miner_joined(2, now=0.45)  # idle thief, nothing to dispatch
        # Miner 1's running chunk started at 0.4; at 0.5 it is younger
        # than steal_factor(2.0) x p50(0.1) -> no steal yet.
        assert s.tick(now=0.5) == []
        # Age evidence is in at 0.7, but the rate-aware cut point (ISSUE
        # 13 satellite) says the straggler's ~1000 n/s EWMA finishes the
        # remaining 100 nonces well before its re-queue deadline
        # (0.4 + 4.0 x 0.1 = 0.8) -> stealing would be pure duplication.
        assert s.tick(now=0.7) == []
        # At 0.75 only ~50 nonces fit before the deadline: the
        # unfinishable tail (and ONLY it) is re-dispatched to the thief.
        acts = s.tick(now=0.75)
        assert [m.type for _, m in acts] == [MsgType.REQUEST]
        assert acts[0][0] == 2
        msg = acts[0][1]
        assert (msg.lower, msg.upper) == (450, 499)
        assert s.miners[1].queue[0].stolen == (450, 499)

    def test_rate_aware_cut_grows_as_deadline_nears(self):
        """The satellite's core property: the stolen tail is exactly the
        portion the straggler's EWMA rate cannot cover by its re-queue
        deadline, so successive ticks (deadline approaching, nothing
        answered) would steal strictly more."""
        def fleet():
            s = Scheduler(
                validate_results=False, min_chunk=1000, max_chunk=1000,
                pipeline_depth=1, steal_min_seconds=0.0,
                steal_min_samples=1, straggler_min_seconds=0.0,
            )
            s.miner_joined(1, now=0.0)
            s.client_request(10, "d", 0, 1999, now=0.0)
            # One completed chunk: rate = 1000/1.0 = 1000 n/s, p50 = 1 s.
            s.result(1, hash_=5, nonce=7, now=1.0)
            s.miner_joined(2, now=1.0)
            return s

        # Chunk [1000, 1999] started at 1.0; re-queue deadline = 1.0 +
        # 4.0 x (1000/1000) = 5.0.  At now=4.25 the straggler covers
        # 1000 x 0.75 = 750 more nonces -> steal [1750, 1999].  (Times
        # are binary-exact so int() truncation is deterministic.)
        s = fleet()
        acts = s.tick(now=4.25)
        (thief, msg), = acts
        assert thief == 2 and (msg.lower, msg.upper) == (1750, 1999)
        # Closer to the deadline the unfinishable tail is larger: at
        # now=4.75 only 250 nonces fit -> steal [1250, 1999].
        s = fleet()
        acts = s.tick(now=4.75)
        (thief, msg), = acts
        assert thief == 2 and (msg.lower, msg.upper) == (1250, 1999)

    def test_marked_straggler_ignores_own_rate(self):
        """An externally marked miner (fleet-detector leave-one-out
        evidence) keeps the legacy half split even when its own EWMA
        claims it finishes in time — the mark exists because that EWMA
        is not trustworthy."""
        s = Scheduler(
            validate_results=False, min_chunk=1000, max_chunk=1000,
            pipeline_depth=1, steal_min_seconds=0.0, steal_min_samples=64,
        )
        s.miner_joined(1, now=0.0)
        s.client_request(10, "d", 0, 1999, now=0.0)
        s.result(1, hash_=5, nonce=7, now=0.1)  # EWMA 10^4 n/s: "fast"
        s.miner_joined(2, now=0.1)
        s.mark_straggler(1)
        acts = s.tick(now=0.2)
        (thief, msg), = acts
        assert thief == 2 and (msg.lower, msg.upper) == (1500, 1999)

    def test_cold_fleet_never_steals_on_guesses(self):
        s = self._one_chunk_fleet(steal_min_seconds=0.0)
        # No chunk has EVER completed: no p50, no steal however old (5 s
        # stays under the full straggler re-queue's 10 s floor).
        assert s.tick(now=5.0) == []

    def test_steal_flagged_miner_gets_no_new_work(self):
        s = self._one_chunk_fleet()
        s.mark_straggler(1)
        s.tick(now=0.1)
        # A second job: every chunk must route around the flagged holder.
        acts = s.client_request(11, "e", 0, 999, now=0.2)
        assert {cid for cid, _ in acts} == {2}

    def test_stolen_front_never_restolen(self):
        s = self._one_chunk_fleet()
        s.mark_straggler(1)
        s.tick(now=0.1)
        s.miner_joined(3, now=0.2)  # another idle miner appears
        s.mark_straggler(1)
        assert s.tick(now=0.3) == []  # escalation is the full re-queue

    def test_valid_answer_clears_stale_straggler_mark(self):
        """A mark that found no idle thief must die when the miner
        answers: stale fleet-detector evidence cannot steal from a
        fresh, healthy chunk minutes later."""
        s = Scheduler(validate_results=False, min_chunk=10**6)
        s.miner_joined(1, now=0.0)
        s.client_request(10, "d", 0, 999, now=0.0)
        s.mark_straggler(1)  # no idle miner exists: mark cannot act
        assert s.tick(now=0.1) == []
        s.result(1, hash_=5, nonce=7, now=0.2)  # the miner ANSWERS
        s.client_request(11, "e", 0, 999, now=0.3)  # fresh chunk, miner 1
        s.miner_joined(2, now=0.4)  # an idle thief appears later
        # The fresh front chunk is not stolen on the stale mark (and is
        # far too young for age evidence).
        assert s.tick(now=0.5) == []
        s = Scheduler(validate_results=False, min_chunk=10**6)
        s.miner_joined(1, now=0.0)
        s.client_request(10, "d", 0, 999, now=0.0, prefill=True)
        s.miner_joined(2, now=0.0)
        s.mark_straggler(1)
        assert s.tick(now=0.1) == []  # speculation isn't worth duplicating

    def test_split_on_steal_bit_exact_property(self):
        """The ISSUE 10 property: random split points over real hashlib
        minima — whichever sub-interval completes first, the winner's
        fold plus the discarded loser's overlap equals a from-scratch
        sweep, with oracle validation ON."""
        import random

        rng = random.Random(0xBEEF)
        for trial in range(6):
            lo = rng.randrange(0, 800)
            hi = lo + rng.randrange(40, 400)
            data = f"steal-{trial}"
            order = trial % 3
            s = Scheduler(min_chunk=10**6, pipeline_depth=1)
            s.miner_joined(1, now=0.0)
            s.client_request(10, data, lo, hi, now=0.0)
            s.miner_joined(2, now=0.0)
            s.mark_straggler(1)
            acts = s.tick(now=0.5)
            (thief, tail_msg), = acts
            t_lo, t_hi = tail_msg.lower, tail_msg.upper
            assert thief == 2 and lo < t_lo <= t_hi == hi
            done = []
            if order == 0:
                # Thief first, then the straggler's full interval: the
                # losing duplicate folds harmlessly (min over a superset).
                done += s.result(2, *min_hash_range(data, t_lo, t_hi), now=1.0)
                done += s.result(1, *min_hash_range(data, lo, hi), now=2.0)
            elif order == 1:
                # Straggler's full interval first: it wins outright, the
                # thief's in-flight duplicate is withdrawn/ignored.
                done += s.result(1, *min_hash_range(data, lo, hi), now=1.0)
                done += s.result(2, *min_hash_range(data, t_lo, t_hi), now=2.0)
            else:
                # Straggler never answers: the full straggler re-queue
                # escalates (head only — the tail copy is already live),
                # and the thief sweeps both halves.
                s.tick(now=100.0)  # past straggler_min_seconds
                acts = s.result(2, *min_hash_range(data, t_lo, t_hi), now=101.0)
                heads = [
                    (m.lower, m.upper) for cid, m in acts
                    if cid == 2 and m.type == MsgType.REQUEST
                ]
                assert heads == [(lo, t_lo - 1)]
                done += acts
                done += s.result(2, *min_hash_range(data, lo, t_lo - 1), now=102.0)
            final = [(cid, m) for cid, m in done if m.type == MsgType.RESULT]
            assert len(final) == 1 and final[0][0] == 10
            want = min_hash_range(data, lo, hi)
            assert (final[0][1].hash, final[0][1].nonce) == want

    def test_late_straggler_result_withdraws_tail_duplicate(self):
        """Thief still computing when the straggler answers after all:
        the tail's PENDING portion is withdrawn so it never re-dispatches,
        and the job completes on the straggler's fold alone."""
        s = Scheduler(validate_results=False, min_chunk=10**6)
        s.miner_joined(1, now=0.0)
        s.client_request(10, "d", 0, 999, now=0.0)
        s.mark_straggler(1)
        assert s.tick(now=0.1) == []  # no idle miner: tail stays pending?
        # No steal happened (no idle miner); now one appears and the
        # steal lands, but the thief dies before answering.
        s.miner_joined(2, now=0.2)
        s.mark_straggler(1)
        s.tick(now=0.3)
        s.lost(2, now=0.4)  # thief dies: tail back to pending
        done = s.result(1, hash_=5, nonce=3, now=0.5)
        final = [(cid, m) for cid, m in done if m.type == MsgType.RESULT]
        assert len(final) == 1 and final[0][0] == 10
        assert s.jobs == {}  # nothing pending: duplicate fully withdrawn


class TestFairness:
    def test_round_robin_across_jobs(self):
        s = Scheduler(validate_results=False, min_chunk=10, max_chunk=10)
        s.client_request(10, "a", 0, 99)
        s.client_request(11, "b", 0, 99)
        served = []
        for m in range(1, 5):
            for cid, msg in s.miner_joined(m):
                served.append(msg.data)
        # Each join fills the miner's pipeline (depth 2), round-robin
        # across jobs: both jobs get an equal share.
        assert served.count("a") == 4 and served.count("b") == 4

    def test_pipeline_fills_breadth_first(self):
        # With 2 miners and depth 2, every miner must hold its FIRST chunk
        # before anyone is handed a second.
        s = Scheduler(validate_results=False, min_chunk=10, max_chunk=10)
        s.miner_joined(1)
        s.miner_joined(2)
        actions = s.client_request(10, "a", 0, 39)
        order = [cid for cid, _ in actions]
        assert sorted(order[:2]) == [1, 2]  # level 0 first
        assert sorted(order[2:]) == [1, 2]  # then level 1
        assert all(len(m.queue) == 2 for m in s.miners.values())

    def test_duplicate_join_ignored(self):
        s = Scheduler(validate_results=False)
        s.miner_joined(1)
        assert s.miner_joined(1) == []
        assert len(s.miners) == 1

    def test_second_request_on_same_conn_ignored(self):
        s = Scheduler(validate_results=False, min_chunk=10**6)
        s.miner_joined(1)
        s.client_request(10, "a", 0, 9)
        assert s.client_request(10, "b", 0, 9) == []

    def test_stats(self):
        s = Scheduler(validate_results=False, min_chunk=10, max_chunk=10)
        s.miner_joined(1)
        s.client_request(10, "a", 0, 99)
        st = s.stats()
        assert st["miners"] == 1 and st["idle_miners"] == 0
        # depth-2 pipeline: the lone miner holds two chunks.
        assert st["jobs"] == 1 and st["outstanding_chunks"] == 2


class TestAdaptiveDepth:
    """Adaptive pipeline depth (ISSUE 14 satellite, PR-10 carry-over):
    the per-miner assignment window tracks the observed per-dispatch
    device latency instead of the static 2 — deep enough to hide a
    tunnelled TPU's dispatch+fetch latency, shallow when latency doesn't
    warrant it (which also keeps enqueue-time sieve thresholds fresh).
    The latency provider is injected so these stay deterministic."""

    def _sched(self, latency, **kw):
        return Scheduler(
            validate_results=False,
            min_chunk=10,
            max_chunk=10,
            target_chunk_seconds=0.5,
            adaptive_depth=True,
            dispatch_latency=lambda: latency,
            **kw,
        )

    def test_static_without_flag(self):
        s = Scheduler(validate_results=False)
        assert s.effective_depth() == s.pipeline_depth == 2
        s.tick(0.0)
        assert s.effective_depth() == 2

    def test_no_evidence_keeps_configured_depth(self):
        s = self._sched(None)
        s.tick(0.0)
        assert s.effective_depth() == 2

    def test_high_latency_deepens_window(self):
        # p50 2s against a 0.5s chunk target wants 1 + ceil(4) = 5,
        # clamped to depth_cap.
        s = self._sched(2.0, depth_cap=4)
        s.tick(0.0)
        assert s.effective_depth() == 4

    def test_low_latency_shallows_window_to_one(self):
        # Sub-millisecond dispatches (in-process fleets): nothing to
        # hide, so one chunk in flight — the freshest sieve thresholds.
        s = self._sched(0.0)
        s.tick(0.0)
        assert s.effective_depth() == 1

    def test_moderate_latency_keeps_two(self):
        s = self._sched(0.2)  # ceil(0.4) = 1 -> depth 2, the old static
        s.tick(0.0)
        assert s.effective_depth() == 2

    def test_depth_governs_assignment_window(self):
        # With latency evidence saying depth 1, a lone miner holds ONE
        # chunk; flip the evidence to 2s and the next tick re-deepens.
        lat = {"v": 0.0}
        s = Scheduler(
            validate_results=False,
            min_chunk=10,
            max_chunk=10,
            target_chunk_seconds=0.5,
            adaptive_depth=True,
            dispatch_latency=lambda: lat["v"],
        )
        s.tick(0.0)
        s.miner_joined(1)
        s.client_request(10, "a", 0, 99)
        assert s.stats()["outstanding_chunks"] == 1
        lat["v"] = 2.0
        actions = s.tick(1.0)
        # The deeper window back-fills the queue on the same tick.
        assert s.stats()["outstanding_chunks"] >= 2
        assert all(m.type == MsgType.REQUEST for _, m in actions)

    def test_depth_adapt_counts_metric(self):
        from bitcoin_miner_tpu.utils.metrics import METRICS

        before = METRICS.get("sched.depth_adapt")
        s = self._sched(2.0)
        s.tick(0.0)
        assert METRICS.get("sched.depth_adapt") == before + 1
        s.tick(1.0)  # unchanged evidence: no second bump
        assert METRICS.get("sched.depth_adapt") == before + 1
