"""Unit tests of the pure Scheduler logic (no sockets, no JAX).

The reference has no scheduler tests (its server is a stub); these pin the
behavior SURVEY §3.6 reconstructs from the frozen contracts: join/request/
result folding, adaptive chunking, dead-miner reassignment, dead-client
cancellation, fairness.
"""

from bitcoin_miner_tpu.apps.scheduler import Scheduler
from bitcoin_miner_tpu.bitcoin.message import MsgType


def drain(job_actions):
    return {cid: msg for cid, msg in job_actions}


class TestBasicFlow:
    def test_join_then_request_assigns(self):
        s = Scheduler(validate_results=False, min_chunk=100)
        assert s.miner_joined(1) == []
        actions = s.client_request(10, "data", 0, 99)
        assert len(actions) == 1
        cid, msg = actions[0]
        assert cid == 1
        assert msg.type == MsgType.REQUEST
        assert (msg.lower, msg.upper) == (0, 99)

    def test_request_then_join_assigns(self):
        s = Scheduler(validate_results=False, min_chunk=100)
        assert s.client_request(10, "data", 0, 99) == []
        actions = s.miner_joined(1)
        assert len(actions) == 1
        assert actions[0][0] == 1

    def test_result_completes_job(self):
        s = Scheduler(validate_results=False, min_chunk=1000)
        s.miner_joined(1)
        s.client_request(10, "data", 0, 99)
        actions = s.result(1, hash_=555, nonce=42)
        assert actions[0] == (10, actions[0][1])
        msg = actions[0][1]
        assert msg.type == MsgType.RESULT
        assert (msg.hash, msg.nonce) == (555, 42)
        assert s.jobs == {}
        assert s.miners[1].job is None  # miner idle again

    def test_range_split_across_miners_min_folds(self):
        s = Scheduler(validate_results=False, min_chunk=50)
        for m in (1, 2):
            s.miner_joined(m)
        actions = s.client_request(10, "data", 0, 99)
        assert len(actions) == 2
        ranges = sorted((m.lower, m.upper) for _, m in actions)
        assert ranges == [(0, 49), (50, 99)]
        assert s.result(1, hash_=900, nonce=7) == []  # half done: no reply yet
        final = s.result(2, hash_=300, nonce=61)
        # min-fold picks the smaller hash
        assert final[0][1].hash == 300 and final[0][1].nonce == 61

    def test_tie_break_lowest_nonce(self):
        s = Scheduler(validate_results=False, min_chunk=50)
        s.miner_joined(1)
        s.miner_joined(2)
        s.client_request(10, "d", 0, 99)
        s.result(2, hash_=100, nonce=80)
        final = s.result(1, hash_=100, nonce=3)
        assert final[0][1].nonce == 3

    def test_empty_range_answers_immediately(self):
        s = Scheduler(validate_results=False)
        actions = s.client_request(10, "d", 5, 4)
        assert actions[0][0] == 10
        assert actions[0][1].type == MsgType.RESULT


class TestFaults:
    def test_dead_miner_chunk_reassigned(self):
        s = Scheduler(validate_results=False, min_chunk=1000)
        s.miner_joined(1)
        s.client_request(10, "d", 0, 499)
        actions = s.lost(1)  # miner dies mid-chunk
        assert actions == []  # nobody to reassign to yet
        actions = s.miner_joined(2)  # replacement arrives
        assert len(actions) == 1
        assert (actions[0][1].lower, actions[0][1].upper) == (0, 499)

    def test_dead_miner_with_idle_peer_reassigns_immediately(self):
        s = Scheduler(validate_results=False, min_chunk=1000)
        s.miner_joined(1)
        s.miner_joined(2)
        s.client_request(10, "d", 0, 499)  # one chunk -> one miner busy
        busy = next(m for m in s.miners.values() if m.job is not None).conn_id
        actions = s.lost(busy)
        assert len(actions) == 1  # idle peer picks it straight up
        assert (actions[0][1].lower, actions[0][1].upper) == (0, 499)

    def test_dead_client_drops_job_and_result_ignored(self):
        s = Scheduler(validate_results=False, min_chunk=1000)
        s.miner_joined(1)
        s.client_request(10, "d", 0, 499)
        assert s.lost(10) == []  # client dies: job cancelled silently
        assert s.jobs == {}
        actions = s.result(1, hash_=5, nonce=5)  # stale result arrives
        assert actions == []  # ignored, miner back to idle
        assert s.miners[1].job is None

    def test_miner_death_preserves_low_nonce_order(self):
        s = Scheduler(validate_results=False, min_chunk=100, max_chunk=100)
        s.miner_joined(1)
        s.client_request(10, "d", 0, 299)  # miner 1 gets [0,99]
        s.lost(1)
        actions = s.miner_joined(2)  # must get [0,99] back first, not [100,199]
        assert (actions[0][1].lower, actions[0][1].upper) == (0, 99)


class TestAdaptiveChunking:
    def test_fast_miner_gets_bigger_chunks(self):
        s = Scheduler(validate_results=False, min_chunk=100, max_chunk=10**9, target_chunk_seconds=1.0)
        s.miner_joined(1, now=0.0)
        s.client_request(10, "d", 0, 10**9, now=0.0)
        # first chunk is min_chunk (rate unknown)
        first = s.miners[1].interval
        assert first == (0, 99)
        # completes 100 nonces in 1 ms -> rate 1e5/s -> next chunk ~1e5
        actions = s.result(1, hash_=7, nonce=0, now=0.001)
        nxt = actions[0][1]
        size = nxt.upper - nxt.lower + 1
        assert 50_000 <= size <= 200_000

    def test_chunk_capped_at_max(self):
        s = Scheduler(validate_results=False, min_chunk=10, max_chunk=1000, target_chunk_seconds=1.0)
        s.miner_joined(1, now=0.0)
        s.client_request(10, "d", 0, 10**9, now=0.0)
        actions = s.result(1, hash_=7, nonce=0, now=1e-9)  # absurd rate
        nxt = actions[0][1]
        assert nxt.upper - nxt.lower + 1 == 1000


class TestFairness:
    def test_round_robin_across_jobs(self):
        s = Scheduler(validate_results=False, min_chunk=10, max_chunk=10)
        s.client_request(10, "a", 0, 99)
        s.client_request(11, "b", 0, 99)
        served = []
        for m in range(1, 5):
            for cid, msg in s.miner_joined(m):
                served.append(msg.data)
        assert served.count("a") == 2 and served.count("b") == 2

    def test_duplicate_join_ignored(self):
        s = Scheduler(validate_results=False)
        s.miner_joined(1)
        assert s.miner_joined(1) == []
        assert len(s.miners) == 1

    def test_second_request_on_same_conn_ignored(self):
        s = Scheduler(validate_results=False, min_chunk=10**6)
        s.miner_joined(1)
        s.client_request(10, "a", 0, 9)
        assert s.client_request(10, "b", 0, 9) == []

    def test_stats(self):
        s = Scheduler(validate_results=False, min_chunk=10, max_chunk=10)
        s.miner_joined(1)
        s.client_request(10, "a", 0, 99)
        st = s.stats()
        assert st["miners"] == 1 and st["idle_miners"] == 0
        assert st["jobs"] == 1 and st["outstanding_chunks"] == 1
