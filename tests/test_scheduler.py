"""Unit tests of the pure Scheduler logic (no sockets, no JAX).

The reference has no scheduler tests (its server is a stub); these pin the
behavior SURVEY §3.6 reconstructs from the frozen contracts: join/request/
result folding, adaptive chunking, dead-miner reassignment, dead-client
cancellation, fairness.
"""

from bitcoin_miner_tpu.apps.scheduler import Scheduler
from bitcoin_miner_tpu.bitcoin.message import MsgType


def drain(job_actions):
    return {cid: msg for cid, msg in job_actions}


class TestBasicFlow:
    def test_join_then_request_assigns(self):
        s = Scheduler(validate_results=False, min_chunk=100)
        assert s.miner_joined(1) == []
        actions = s.client_request(10, "data", 0, 99)
        assert len(actions) == 1
        cid, msg = actions[0]
        assert cid == 1
        assert msg.type == MsgType.REQUEST
        assert (msg.lower, msg.upper) == (0, 99)

    def test_request_then_join_assigns(self):
        s = Scheduler(validate_results=False, min_chunk=100)
        assert s.client_request(10, "data", 0, 99) == []
        actions = s.miner_joined(1)
        assert len(actions) == 1
        assert actions[0][0] == 1

    def test_result_completes_job(self):
        s = Scheduler(validate_results=False, min_chunk=1000)
        s.miner_joined(1)
        s.client_request(10, "data", 0, 99)
        actions = s.result(1, hash_=555, nonce=42)
        assert actions[0] == (10, actions[0][1])
        msg = actions[0][1]
        assert msg.type == MsgType.RESULT
        assert (msg.hash, msg.nonce) == (555, 42)
        assert s.jobs == {}
        assert s.miners[1].job is None  # miner idle again

    def test_range_split_across_miners_min_folds(self):
        s = Scheduler(validate_results=False, min_chunk=50)
        for m in (1, 2):
            s.miner_joined(m)
        actions = s.client_request(10, "data", 0, 99)
        assert len(actions) == 2
        ranges = sorted((m.lower, m.upper) for _, m in actions)
        assert ranges == [(0, 49), (50, 99)]
        assert s.result(1, hash_=900, nonce=7) == []  # half done: no reply yet
        final = s.result(2, hash_=300, nonce=61)
        # min-fold picks the smaller hash
        assert final[0][1].hash == 300 and final[0][1].nonce == 61

    def test_tie_break_lowest_nonce(self):
        s = Scheduler(validate_results=False, min_chunk=50)
        s.miner_joined(1)
        s.miner_joined(2)
        s.client_request(10, "d", 0, 99)
        s.result(2, hash_=100, nonce=80)
        final = s.result(1, hash_=100, nonce=3)
        assert final[0][1].nonce == 3

    def test_empty_range_answers_immediately(self):
        s = Scheduler(validate_results=False)
        actions = s.client_request(10, "d", 5, 4)
        assert actions[0][0] == 10
        assert actions[0][1].type == MsgType.RESULT


class TestFaults:
    def test_dead_miner_chunk_reassigned(self):
        s = Scheduler(validate_results=False, min_chunk=1000)
        s.miner_joined(1)
        s.client_request(10, "d", 0, 499)
        actions = s.lost(1)  # miner dies mid-chunk
        assert actions == []  # nobody to reassign to yet
        actions = s.miner_joined(2)  # replacement arrives
        assert len(actions) == 1
        assert (actions[0][1].lower, actions[0][1].upper) == (0, 499)

    def test_dead_miner_with_idle_peer_reassigns_immediately(self):
        s = Scheduler(validate_results=False, min_chunk=1000)
        s.miner_joined(1)
        s.miner_joined(2)
        s.client_request(10, "d", 0, 499)  # one chunk -> one miner busy
        busy = next(m for m in s.miners.values() if m.job is not None).conn_id
        actions = s.lost(busy)
        assert len(actions) == 1  # idle peer picks it straight up
        assert (actions[0][1].lower, actions[0][1].upper) == (0, 499)

    def test_dead_client_drops_job_and_result_ignored(self):
        s = Scheduler(validate_results=False, min_chunk=1000)
        s.miner_joined(1)
        s.client_request(10, "d", 0, 499)
        assert s.lost(10) == []  # client dies: job cancelled silently
        assert s.jobs == {}
        actions = s.result(1, hash_=5, nonce=5)  # stale result arrives
        assert actions == []  # ignored, miner back to idle
        assert s.miners[1].job is None

    def test_miner_death_preserves_low_nonce_order(self):
        s = Scheduler(validate_results=False, min_chunk=100, max_chunk=100)
        s.miner_joined(1)
        s.client_request(10, "d", 0, 299)  # miner 1 gets [0,99]
        s.lost(1)
        actions = s.miner_joined(2)  # must get [0,99] back first, not [100,199]
        assert (actions[0][1].lower, actions[0][1].upper) == (0, 99)


class TestPipelining:
    def test_results_match_fifo(self):
        # Two chunks queued at one miner; results close them oldest-first.
        s = Scheduler(validate_results=False, min_chunk=100, max_chunk=100)
        s.miner_joined(1, now=0.0)
        s.client_request(10, "d", 0, 299, now=0.0)
        assert [a.interval for a in s.miners[1].queue] == [(0, 99), (100, 199)]
        s.result(1, hash_=5, nonce=7, now=1.0)
        # (0,99) closed; (100,199) promoted to front; refill appended.
        assert s.miners[1].queue[0].interval == (100, 199)
        assert 0 not in [iv for lst in s.jobs[10].outstanding.values() for iv in lst]

    def test_rate_uses_result_gap_not_assignment_time(self):
        # Both chunks assigned at t=0; results at t=10 and t=11.  The second
        # sample must be size/1s (result gap), not size/11s.
        s = Scheduler(
            validate_results=False, min_chunk=100, max_chunk=100, rate_alpha=1.0
        )
        s.miner_joined(1, now=0.0)
        s.client_request(10, "d", 0, 299, now=0.0)
        s.result(1, hash_=5, nonce=7, now=10.0)
        assert s.miners[1].rate == 100 / 10.0
        s.result(1, hash_=5, nonce=107, now=11.0)
        assert s.miners[1].rate == 100 / 1.0

    def test_lost_miner_requeues_all_chunks_in_order(self):
        s = Scheduler(validate_results=False, min_chunk=100, max_chunk=100)
        s.miner_joined(1, now=0.0)
        s.client_request(10, "d", 0, 299, now=0.0)  # holds (0,99),(100,199)
        s.lost(1, now=1.0)
        assert list(s.jobs[10].pending) == [(0, 99), (100, 199), (200, 299)]

    def test_evicted_liar_requeues_queued_chunks(self):
        from bitcoin_miner_tpu.bitcoin.hash import min_hash_range

        s = Scheduler(min_chunk=100, max_chunk=100, max_rejects=1)
        s.miner_joined(1, now=0.0)
        s.client_request(10, "cmu440", 0, 299, now=0.0)
        s.result(1, hash_=1, nonce=2, now=1.0)  # lie -> instant eviction
        assert 1 not in s.miners
        # Both the lied-about front chunk AND the queued second chunk are
        # back in pending, in nonce order.
        assert list(s.jobs[10].pending) == [(0, 99), (100, 199), (200, 299)]
        s.miner_joined(2, now=2.0)
        h, n = min_hash_range("cmu440", 0, 299)
        for lo in (0, 100, 200):
            hh, nn = min_hash_range("cmu440", lo, lo + 99)
            final = s.result(2, hh, nn, now=3.0 + lo)
        assert final[0][1].hash == h and final[0][1].nonce == n

    def test_straggler_cascade_times_out_successor(self):
        # Front times out at t=11; the queued successor's clock starts
        # there, so it times out ~10s later, not immediately.
        s = Scheduler(
            validate_results=False,
            min_chunk=100,
            max_chunk=100,
            straggler_min_seconds=10.0,
        )
        s.miner_joined(1, now=0.0)
        s.client_request(10, "d", 0, 299, now=0.0)
        s.tick(11.0)
        assert [a.timed_out for a in s.miners[1].queue] == [True, False]
        assert s.tick(12.0) == []  # successor's deadline not reached
        s.tick(22.0)
        assert [a.timed_out for a in s.miners[1].queue] == [True, True]
        # Both duplicates pending (plus the never-assigned third chunk).
        assert sorted(s.jobs[10].pending) == [(0, 99), (100, 199), (200, 299)]

    def test_hung_miner_gets_no_new_work(self):
        s = Scheduler(
            validate_results=False,
            min_chunk=100,
            max_chunk=100,
            straggler_min_seconds=10.0,
        )
        s.miner_joined(1, now=0.0)
        s.client_request(10, "d", 0, 99, now=0.0)
        assert s.tick(11.0) == []  # re-queued, but the only miner is hung
        assert list(s.jobs[10].pending) == [(0, 99)]
        assert len(s.miners[1].queue) == 1  # NOT handed its own duplicate

    def test_ramp_boost_grows_chunks_geometrically(self):
        # A fast miner completing min_chunk in a blink gets ramp_factor x
        # its last chunk, not just rate*target (which the per-chunk latency
        # in the EWMA understates during ramp).
        s = Scheduler(
            validate_results=False,
            min_chunk=1000,
            target_chunk_seconds=0.5,
            rate_alpha=1.0,
            pipeline_depth=1,
            ramp_factor=8,
        )
        s.miner_joined(1, now=0.0)
        s.client_request(10, "d", 0, 10**9, now=0.0)
        # 1000 nonces in 0.2s -> EWMA rate 5000/s -> rate-based next chunk
        # would be 2500; the boost gives 8x1000 = 8000.
        actions = s.result(1, hash_=5, nonce=7, now=0.2)
        nxt = actions[0][1]
        assert nxt.upper - nxt.lower + 1 == 8000


class TestAdaptiveChunking:
    def test_fast_miner_gets_bigger_chunks(self):
        s = Scheduler(validate_results=False, min_chunk=100, max_chunk=10**9, target_chunk_seconds=1.0)
        s.miner_joined(1, now=0.0)
        s.client_request(10, "d", 0, 10**9, now=0.0)
        # first chunk is min_chunk (rate unknown)
        first = s.miners[1].interval
        assert first == (0, 99)
        # completes 100 nonces in 1 ms -> rate 1e5/s -> next chunk ~1e5
        actions = s.result(1, hash_=7, nonce=0, now=0.001)
        nxt = actions[0][1]
        size = nxt.upper - nxt.lower + 1
        assert 50_000 <= size <= 200_000

    def test_chunk_capped_at_max(self):
        s = Scheduler(validate_results=False, min_chunk=10, max_chunk=1000, target_chunk_seconds=1.0)
        s.miner_joined(1, now=0.0)
        s.client_request(10, "d", 0, 10**9, now=0.0)
        actions = s.result(1, hash_=7, nonce=0, now=1e-9)  # absurd rate
        nxt = actions[0][1]
        assert nxt.upper - nxt.lower + 1 == 1000


class TestFairness:
    def test_round_robin_across_jobs(self):
        s = Scheduler(validate_results=False, min_chunk=10, max_chunk=10)
        s.client_request(10, "a", 0, 99)
        s.client_request(11, "b", 0, 99)
        served = []
        for m in range(1, 5):
            for cid, msg in s.miner_joined(m):
                served.append(msg.data)
        # Each join fills the miner's pipeline (depth 2), round-robin
        # across jobs: both jobs get an equal share.
        assert served.count("a") == 4 and served.count("b") == 4

    def test_pipeline_fills_breadth_first(self):
        # With 2 miners and depth 2, every miner must hold its FIRST chunk
        # before anyone is handed a second.
        s = Scheduler(validate_results=False, min_chunk=10, max_chunk=10)
        s.miner_joined(1)
        s.miner_joined(2)
        actions = s.client_request(10, "a", 0, 39)
        order = [cid for cid, _ in actions]
        assert sorted(order[:2]) == [1, 2]  # level 0 first
        assert sorted(order[2:]) == [1, 2]  # then level 1
        assert all(len(m.queue) == 2 for m in s.miners.values())

    def test_duplicate_join_ignored(self):
        s = Scheduler(validate_results=False)
        s.miner_joined(1)
        assert s.miner_joined(1) == []
        assert len(s.miners) == 1

    def test_second_request_on_same_conn_ignored(self):
        s = Scheduler(validate_results=False, min_chunk=10**6)
        s.miner_joined(1)
        s.client_request(10, "a", 0, 9)
        assert s.client_request(10, "b", 0, 9) == []

    def test_stats(self):
        s = Scheduler(validate_results=False, min_chunk=10, max_chunk=10)
        s.miner_joined(1)
        s.client_request(10, "a", 0, 99)
        st = s.stats()
        assert st["miners"] == 1 and st["idle_miners"] == 0
        # depth-2 pipeline: the lone miner holds two chunks.
        assert st["jobs"] == 1 and st["outstanding_chunks"] == 2
