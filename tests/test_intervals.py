"""The interval-algebra primitive + span store (ISSUE 5).

Three layers, fast enough for tier-1 (marker ``intervals``):

- pure-unit: ``merge_intervals`` / ``intersect_intervals`` algebra and
  the :class:`IntervalMap` mechanics (overlap folding, adjacency kept
  for resolution, budgeted shrink, the argmin-inside answerability rule);
- property-style: random solved-span layouts over real hashlib minima —
  for every random query, folding ``cover()``'s best with brute-force
  sweeps of its gaps must be bit-identical to a from-scratch full sweep
  (the ISSUE 5 bit-exactness acceptance, lowest-nonce ties included);
- persistence: :class:`SpanStore` round-trip, torn/corrupt file -> clean
  empty store, bad rows skipped, and the LRU/budget bounds.
"""

import random

import pytest

from bitcoin_miner_tpu.bitcoin.hash import min_hash_range
from bitcoin_miner_tpu.gateway import SpanStore
from bitcoin_miner_tpu.utils.intervals import (
    IntervalMap,
    intersect_intervals,
    interval_total,
    merge_intervals,
)

pytestmark = pytest.mark.intervals


# ---------------------------------------------------------------- algebra


def test_merge_intervals():
    assert merge_intervals([]) == []
    assert merge_intervals([(5, 9), (0, 4)]) == [(0, 9)]  # adjacent
    assert merge_intervals([(0, 9), (3, 5)]) == [(0, 9)]  # contained
    assert merge_intervals([(0, 2), (4, 6)]) == [(0, 2), (4, 6)]  # gap


def test_intersect_intervals():
    assert intersect_intervals([], [(0, 9)]) == []
    assert intersect_intervals([(0, 9)], [(5, 15)]) == [(5, 9)]
    assert intersect_intervals([(0, 3), (6, 9)], [(2, 7)]) == [(2, 3), (6, 7)]
    assert intersect_intervals([(0, 9)], [(0, 9)]) == [(0, 9)]
    assert intersect_intervals([(0, 4)], [(5, 9)]) == []
    # unsorted/overlapping inputs are normalized first
    assert intersect_intervals([(5, 9), (0, 6)], [(4, 4)]) == [(4, 4)]


def test_interval_total():
    assert interval_total([]) == 0
    assert interval_total([(0, 0), (5, 9)]) == 6


# ------------------------------------------------------------ IntervalMap


def test_map_disjoint_spans_kept_adjacent_not_merged():
    m = IntervalMap()
    m.add(0, 99, 700, 50)
    m.add(100, 199, 600, 150)  # adjacent: kept separate (resolution)
    assert m.spans() == [(0, 99, 700, 50), (100, 199, 600, 150)]


def test_map_overlapping_spans_fold():
    m = IntervalMap()
    m.add(0, 99, 700, 50)
    m.add(50, 149, 600, 120)  # overlap: union covered -> fold is exact
    assert m.spans() == [(0, 149, 600, 120)]


def test_map_refuses_malformed_spans():
    m = IntervalMap()
    m.add(10, 5, 1, 7)  # empty
    m.add(0, 9, 1, 50)  # argmin outside its own range: unusable evidence
    assert len(m) == 0


def test_cover_full_when_argmins_inside():
    m = IntervalMap()
    m.add(0, 99, 700, 50)
    m.add(100, 199, 600, 150)
    best, gaps = m.cover(20, 180)
    assert best == (600, 150) and gaps == []


def test_cover_argmin_outside_query_is_a_gap():
    m = IntervalMap()
    m.add(0, 99, 700, 90)
    # The span's minimum lives at 90, outside [0, 50]: the fold proves
    # nothing about [0, 50], which must be re-swept.
    best, gaps = m.cover(0, 50)
    assert best is None and gaps == [(0, 50)]
    # ...but any query containing the argmin is answered.
    best, gaps = m.cover(50, 99)
    assert best == (700, 90) and gaps == []


def test_cover_mixed_gaps_and_answers():
    m = IntervalMap()
    m.add(10, 19, 700, 15)
    m.add(40, 49, 600, 45)
    best, gaps = m.cover(0, 60)
    assert best == (600, 45)
    assert gaps == [(0, 9), (20, 39), (50, 60)]


def test_cover_empty_and_miss():
    m = IntervalMap()
    assert m.cover(5, 4) == (None, [])
    assert m.cover(0, 9) == (None, [(0, 9)])


def test_budget_prefers_adjacent_coalesce_then_drops_narrowest():
    m = IntervalMap(max_spans=2)
    m.add(0, 9, 700, 5)
    m.add(10, 19, 600, 15)
    m.add(30, 39, 650, 35)
    # Three spans, budget two: the adjacent pair [0,9]+[10,19] coalesces
    # (fold min), the disjoint [30,39] survives untouched.
    assert m.spans() == [(0, 19, 600, 15), (30, 39, 650, 35)]
    m.add(60, 69, 640, 65)
    # No adjacency left: the narrowest span is forgotten (all are width
    # 10 except the coalesced [0,19] — a width-10 one goes).
    assert len(m) == 2
    assert (0, 19, 600, 15) in m.spans()


def test_coalesce_prefers_merge_that_keeps_answerability():
    """ISSUE 10 satellite: the merged span keeps the smaller fold, so the
    LOSING side's width is the sub-range answerability erased.  Given a
    narrow loser in a WIDE pair and a wide loser in a NARROW pair, the
    argmin-placement-aware policy picks the narrow loser — the old
    narrowest-combined-first rule would have picked the other."""
    m = IntervalMap(max_spans=3)
    m.add(0, 149, 1, 0)      # wide winner...
    m.add(150, 154, 9, 152)  # ...adjacent narrow loser: cost 5, combined 155
    m.add(200, 249, 7, 210)  # wide loser...
    m.add(250, 259, 2, 255)  # ...adjacent narrow winner: cost 50, combined 60
    # Narrowest-combined would merge [200,259] (60 < 155) and erase the
    # 50-nonce [200,249]'s argmin; the answerability-aware rule merges
    # [0,154] and erases only 5 nonces.
    assert m.spans() == [
        (0, 154, 1, 0), (200, 249, 7, 210), (250, 259, 2, 255)
    ]
    assert m.lost_answerability == 5
    # The preserved wide span still answers its own sub-queries.
    assert m.cover(200, 249) == ((7, 210), [])


def test_lost_answerability_accrues_on_drop_too():
    m = IntervalMap(max_spans=1)
    m.add(0, 99, 5, 50)
    m.add(200, 219, 7, 210)  # no adjacency: narrowest span is forgotten
    assert m.spans() == [(0, 99, 5, 50)]
    assert m.lost_answerability == 20


def test_spanstore_counts_coalesce_lost_metric():
    from bitcoin_miner_tpu.utils.metrics import METRICS

    METRICS.reset()
    s = SpanStore(max_spans_per_data=2)
    s.add("a", 0, 99, 1, 0)
    s.add("a", 100, 109, 9, 105)
    s.add("a", 200, 299, 3, 250)  # over budget: [0,99]+[100,109] merge
    assert METRICS.get("gateway.coalesce_lost") == 10
    assert s.cover("a", 0, 109) == ((1, 0), [])


def test_spanstore_prefill_targets_hot_gaps_then_bounded_extension():
    """ISSUE 10: speculative targets come from HOT keys only (span-hit
    counters), internal gaps before extensions, extensions bounded per
    key so an idle fleet never sweeps toward u64 forever."""
    s = SpanStore()
    s.add("cold", 0, 99, 5, 50)
    s.add("hot", 0, 99, 1, 10)
    s.add("hot", 200, 299, 2, 250)
    assert s.prefill_target(100) is None  # nothing hot yet
    assert s.cover("hot", 0, 99) == ((1, 10), [])  # span reuse: hot now
    # The internal gap [100,199] comes first, clipped to the ask size.
    assert s.prefill_target(50) == ("hot", 100, 149)
    s.add("hot", 100, 199, 4, 120)  # gap swept (speculatively)
    # Then extensions past the top span, bounded at 2 x 50 nonces.
    assert s.prefill_target(50, max_extend=100) == ("hot", 300, 349)
    s.add("hot", 300, 349, 6, 320)
    assert s.prefill_target(50, max_extend=100) == ("hot", 350, 399)
    s.add("hot", 350, 399, 7, 360)
    assert s.prefill_target(50, max_extend=100) is None  # budget spent


@pytest.mark.parametrize("seed", range(6))
def test_property_cover_plus_remainder_equals_full_sweep(seed):
    """Random span layouts over REAL minima: for any query, span-fold +
    gap-sweep == from-scratch sweep, bit-exact, lowest-nonce ties."""
    rng = random.Random(seed)
    data = f"prop{seed}"
    m = IntervalMap(max_spans=rng.choice([3, 8, 64]))
    domain = 500
    for _ in range(rng.randint(1, 10)):
        lo = rng.randint(0, domain - 1)
        hi = min(domain - 1, lo + rng.randint(0, 80))
        h, n = min_hash_range(data, lo, hi)
        m.add(lo, hi, h, n)
    for _ in range(8):
        qlo = rng.randint(0, domain - 1)
        qhi = rng.randint(qlo, domain - 1)
        best, gaps = m.cover(qlo, qhi)
        # gaps are sorted, disjoint, inside the query
        assert gaps == merge_intervals(gaps)
        assert all(qlo <= lo <= hi <= qhi for lo, hi in gaps)
        folded = [best] if best is not None else []
        folded += [min_hash_range(data, lo, hi) for lo, hi in gaps]
        assert folded, "cover returned neither answers nor gaps"
        assert min(folded) == min_hash_range(data, qlo, qhi)


# -------------------------------------------------- SpanStore persistence


def test_spanstore_roundtrip(tmp_path):
    path = str(tmp_path / "spans.json")
    s = SpanStore(path=path)
    s.add("a", 0, 99, 700, 50)
    s.add("a", 100, 199, 600, 150)
    s.add("b", 10, 19, 500, 12)
    s.save(path)
    s2 = SpanStore(path=path)
    assert len(s2) == 3
    assert s2.cover("a", 20, 180) == ((600, 150), [])
    assert s2.cover("b", 10, 19) == ((500, 12), [])


def test_spanstore_flush_is_dirty_gated(tmp_path):
    s = SpanStore(path=str(tmp_path / "s.json"))
    assert s.flush() is None  # clean at birth
    s.add("a", 0, 9, 700, 5)
    state = s.flush()
    assert state is not None and state["data"] == [["a", [[0, 9, 700, 5]]]]
    assert s.flush() is None  # flush cleared the flag
    s.cover("a", 0, 9)
    assert s.flush() is None  # reads do not dirty
    s.mark_dirty()
    assert s.flush() is not None  # the shell's write-failure re-arm


def test_spanstore_torn_file_starts_empty(tmp_path):
    path = tmp_path / "spans.json"
    path.write_text('{"version": 1, "data": [["a", [[0')  # truncated
    s = SpanStore(path=str(path))
    assert len(s) == 0
    assert s.flush() is None  # an empty fresh load is not dirty


def test_spanstore_bad_rows_skipped_not_fatal(tmp_path):
    path = tmp_path / "spans.json"
    path.write_text(
        '{"version": 1, "data": ['
        '["good", [[0, 9, 700, 5], [99], [0, 9, true, 5], [0, 9, 700, 50]]], '
        '[3, [[0, 9, 1, 2]]], "junk"]}'
    )
    s = SpanStore(path=str(path))
    # one valid row survives ([0,9,700,50] has its argmin outside -> refused)
    assert len(s) == 1
    assert s.cover("good", 0, 9) == ((700, 5), [])


def test_spanstore_lru_bounds_data_keys(tmp_path):
    from bitcoin_miner_tpu.utils.metrics import METRICS

    METRICS.reset()
    s = SpanStore(capacity=2)
    s.add("a", 0, 9, 700, 5)
    s.add("b", 0, 9, 600, 5)
    s.cover("a", 0, 9)  # freshen a: b is now the LRU victim
    s.add("c", 0, 9, 650, 5)
    assert s.data_count() == 2
    assert s.cover("b", 0, 9) == (None, [(0, 9)])  # evicted
    assert s.cover("a", 0, 9)[0] == (700, 5)
    assert METRICS.get("gateway.span_evictions") == 1


def test_spanstore_span_budget_bounded(tmp_path):
    s = SpanStore(max_spans_per_data=4)
    for i in range(0, 40, 2):  # 20 NON-adjacent spans (gaps between)
        lo = i * 10
        s.add("a", lo, lo + 5, 700 + i, lo)
    assert len(s) <= 4  # budget held even with nothing to coalesce
    path_free = SpanStore(capacity=0)
    path_free.add("a", 0, 9, 700, 5)
    assert len(path_free) == 0  # capacity=0 disables storage entirely
    assert path_free.cover("a", 0, 9) == (None, [(0, 9)])
