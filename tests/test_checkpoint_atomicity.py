"""Checkpoint durability: a crash at any point in the save path must never
let ``load_checkpoint`` observe a torn state.

``save_checkpoint`` is write-temp-then-``os.replace`` — the only atomic
primitive POSIX gives us.  These tests crash-inject at each step of that
sequence (mid-``json.dump``, between temp write and replace, inside
``os.replace`` itself) and feed the loader every flavor of corrupt file a
real crash can leave behind (truncated JSON, binary garbage, a stale
``.tmp`` sibling, a non-dict top level).  In every case the loader must
return either the *previous complete snapshot* or None — never a mix.
"""

import json
import os

from bitcoin_miner_tpu.apps.scheduler import Scheduler
from bitcoin_miner_tpu.apps.server import load_checkpoint, save_checkpoint

STATE_1 = {"version": 1, "jobs": [
    {"data": "a", "lower": 0, "upper": 99, "best": [5, 7],
     "remaining": [[8, 99]]},
]}
STATE_2 = {"version": 1, "jobs": [
    {"data": "a", "lower": 0, "upper": 99, "best": [3, 42],
     "remaining": [[50, 99]]},
]}


def test_crash_inside_replace_keeps_previous_state(tmp_path, monkeypatch):
    """os.replace dies (disk full, power cut): the previous snapshot must
    survive byte-identically."""
    path = str(tmp_path / "ckpt.json")
    save_checkpoint(path, STATE_1)

    def exploding_replace(src, dst):
        raise OSError("crash-injected between temp write and replace")

    monkeypatch.setattr(os, "replace", exploding_replace)
    try:
        save_checkpoint(path, STATE_2)
    except OSError:
        pass
    monkeypatch.undo()
    assert load_checkpoint(path) == STATE_1


def test_crash_mid_json_dump_keeps_previous_state(tmp_path, monkeypatch):
    """The temp write itself dies halfway: the half-written temp file must
    not shadow or corrupt the real checkpoint."""
    path = str(tmp_path / "ckpt.json")
    save_checkpoint(path, STATE_1)
    real_dump = json.dump

    def torn_dump(obj, f, **kw):
        f.write('{"version": 1, "jobs": [{"da')  # partial bytes, then crash
        raise OSError("crash-injected mid-write")

    monkeypatch.setattr(json, "dump", torn_dump)
    try:
        save_checkpoint(path, STATE_2)
    except OSError:
        pass
    monkeypatch.setattr(json, "dump", real_dump)
    assert load_checkpoint(path) == STATE_1


def test_stale_tmp_sibling_is_never_loaded(tmp_path):
    """A crash can orphan ``<path>.tmp``; the loader must read only the
    committed file."""
    path = str(tmp_path / "ckpt.json")
    save_checkpoint(path, STATE_1)
    with open(path + ".tmp", "w") as f:
        f.write('{"version": 1, "jobs": [{"TORN')
    assert load_checkpoint(path) == STATE_1


def test_missing_file_returns_none(tmp_path):
    assert load_checkpoint(str(tmp_path / "nope.json")) is None


def test_truncated_json_returns_none(tmp_path):
    path = str(tmp_path / "ckpt.json")
    with open(path, "w") as f:
        f.write('{"version": 1, "jobs": [')
    assert load_checkpoint(path) is None


def test_binary_garbage_returns_none(tmp_path):
    path = str(tmp_path / "ckpt.json")
    with open(path, "wb") as f:
        f.write(b"\xff\xfe\x00garbage\x9c")  # not even valid UTF-8
    assert load_checkpoint(path) is None


def test_non_dict_top_level_returns_none(tmp_path):
    path = str(tmp_path / "ckpt.json")
    with open(path, "w") as f:
        f.write('["valid", "json", "wrong", "shape"]')
    assert load_checkpoint(path) is None


def test_unreadable_file_returns_none(tmp_path):
    path = str(tmp_path / "ckpt.json")
    save_checkpoint(path, STATE_1)
    os.chmod(path, 0o000)
    try:
        if os.access(path, os.R_OK):  # running as root: chmod is a no-op
            return
        assert load_checkpoint(path) is None
    finally:
        os.chmod(path, 0o644)


def test_scheduler_resumes_from_survivor_after_torn_save(tmp_path, monkeypatch):
    """End to end: a checkpoint that survived a torn save still resumes a
    matching Request — the loader/scheduler pair never see the crash."""
    path = str(tmp_path / "ckpt.json")
    save_checkpoint(path, STATE_1)
    monkeypatch.setattr(
        os, "replace",
        lambda s, d: (_ for _ in ()).throw(OSError("crash-injected")),
    )
    try:
        save_checkpoint(path, STATE_2)
    except OSError:
        pass
    monkeypatch.undo()
    sched = Scheduler(min_chunk=10, resume_state=load_checkpoint(path))
    actions = sched.client_request(1, "a", 0, 99)
    assert sched.jobs[1].best == (5, 7)  # STATE_1's best, not STATE_2's
    assert list(sched.jobs[1].pending) == [(8, 99)]
    assert actions == []  # no miners yet: nothing to dispatch
