"""Multi-chip sharding: shard_map sweep + collective min on the virtual
8-device CPU mesh (SURVEY §2.3 — the ICI plane).

The Pallas tier can't run sharded here (Mosaic needs a TPU; interpret mode
deadlocks XLA:CPU's in-process collective rendezvous), so the sharded path
is validated with the xla tier — identical sharding structure, identical
collective cascade.  The driver's dryrun_multichip uses the same path.
"""

import jax

from bitcoin_miner_tpu.bitcoin.hash import min_hash_range
from bitcoin_miner_tpu.parallel import default_mesh, sweep_min_hash_sharded


def test_mesh_has_8_devices():
    assert len(jax.devices()) == 8
    mesh = default_mesh()
    assert mesh.devices.size == 8


def test_sharded_matches_oracle_single_group():
    # One digit group (d=4, k=2) -> one kernel compile; 13 chunks pad across
    # 8 devices x batch 2, exercising padded-row masking.
    r = sweep_min_hash_sharded(
        "cmu440", 1000, 2234, backend="xla", max_k=2, batch_per_device=2
    )
    assert (r.hash, r.nonce) == min_hash_range("cmu440", 1000, 2234)
    assert r.lanes_swept == 2234 - 1000 + 1


def test_sharded_matches_oracle_digit_boundary():
    r = sweep_min_hash_sharded(
        "x", 95, 305, backend="xla", max_k=1, batch_per_device=2
    )
    assert (r.hash, r.nonce) == min_hash_range("x", 95, 305)


def test_sharded_subset_mesh():
    mesh = default_mesh(2)
    r = sweep_min_hash_sharded(
        "cmu440", 1000, 1999, mesh=mesh, backend="xla", max_k=2, batch_per_device=2
    )
    assert (r.hash, r.nonce) == min_hash_range("cmu440", 1000, 1999)


def test_sharded_matches_single_device_tier():
    from bitcoin_miner_tpu.ops.sweep import sweep_min_hash

    # Same data/digit-count as the single-group test -> reuses its compile.
    data, lo, hi = "cmu440", 1100, 3333
    rs = sweep_min_hash_sharded(
        data, lo, hi, backend="xla", max_k=2, batch_per_device=2
    )
    r1 = sweep_min_hash(data, lo, hi, backend="xla", max_k=2)
    assert (rs.hash, rs.nonce) == (r1.hash, r1.nonce)
