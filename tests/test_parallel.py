"""Multi-chip sharding: shard_map sweep + collective min on the virtual
8-device CPU mesh (SURVEY §2.3 — the ICI plane).

The sharded path is validated three ways:
- the xla tier (identical sharding structure + collective cascade) on the
  CPU mesh,
- the *Pallas* tier in interpret mode on the same mesh (the round-4 claim
  that interpret mode deadlocks XLA:CPU's collective rendezvous does not
  reproduce on jax 0.9.0 — both a minimal shard_map+pallas+pmin repro and
  the full kernel run clean, so the flagship tier is now oracle-checked
  sharded),
- AOT: the exact flagship config (Pallas under shard_map + pmin cascade)
  lowered and Mosaic-compiled against a virtual 8-device v5e:2x4 TPU
  topology (no chips needed) in test_aot_topology.py.
The driver's dryrun_multichip runs the first two.
"""

import jax

from bitcoin_miner_tpu.bitcoin.hash import min_hash_range
from bitcoin_miner_tpu.parallel import default_mesh, sweep_min_hash_sharded


def test_mesh_has_8_devices():
    assert len(jax.devices()) == 8
    mesh = default_mesh()
    assert mesh.devices.size == 8


def test_sharded_matches_oracle_single_group():
    # One digit group (d=4, k=2) -> one kernel compile; 13 chunks pad across
    # 8 devices x batch 2, exercising padded-row masking.
    r = sweep_min_hash_sharded(
        "cmu440", 1000, 2234, backend="xla", max_k=2, batch_per_device=2
    )
    assert (r.hash, r.nonce) == min_hash_range("cmu440", 1000, 2234)
    assert r.lanes_swept == 2234 - 1000 + 1


def test_sharded_matches_oracle_digit_boundary():
    r = sweep_min_hash_sharded(
        "x", 95, 305, backend="xla", max_k=1, batch_per_device=2
    )
    assert (r.hash, r.nonce) == min_hash_range("x", 95, 305)


def test_sharded_subset_mesh():
    mesh = default_mesh(2)
    r = sweep_min_hash_sharded(
        "cmu440", 1000, 1999, mesh=mesh, backend="xla", max_k=2, batch_per_device=2
    )
    assert (r.hash, r.nonce) == min_hash_range("cmu440", 1000, 1999)


def test_sharded_pallas_interpret_matches_oracle():
    # The flagship tier, sharded: Pallas kernel (interpret mode — Mosaic
    # itself needs a TPU) under shard_map + the pmin cascade, 8 devices.
    # Bit-exactness proves the kernel's in-VMEM running-min composes with
    # the cross-device collective min, including lowest-nonce tie-break.
    r = sweep_min_hash_sharded(
        "cmu440", 1000, 2234, backend="pallas", interpret=True,
        max_k=2, batch_per_device=2,
    )
    assert (r.hash, r.nonce) == min_hash_range("cmu440", 1000, 2234)
    assert r.lanes_swept == 2234 - 1000 + 1


def test_sharded_pallas_interpret_digit_boundary():
    # Crosses a digit-count boundary -> two kernel shapes, both sharded.
    r = sweep_min_hash_sharded(
        "x", 95, 305, backend="pallas", interpret=True,
        max_k=1, batch_per_device=2,
    )
    assert (r.hash, r.nonce) == min_hash_range("x", 95, 305)


def test_sharded_per_shard_sieve_matches_oracle():
    # Per-shard sieve (ISSUE 14 satellite): the sharded tier no longer
    # forces the baseline kernel — each shard's pass 1 seeds from the
    # replicated dispatch threshold ahead of the collective argmin
    # cascade, and survivor-less shards contribute the sentinel the
    # cascade orders last.  batch_per_device=2 over 8 devices with a
    # digit-boundary range: later dispatches carry a tightened running
    # min, so most shards prune to the sentinel and the fold must STILL
    # be bit-exact, lowest-nonce ties included.
    r = sweep_min_hash_sharded(
        "cmu440", 1000, 2234, backend="xla", max_k=2, batch_per_device=2,
        sieve=True,
    )
    assert (r.hash, r.nonce) == min_hash_range("cmu440", 1000, 2234)
    assert r.lanes_swept == 2234 - 1000 + 1


def test_sharded_per_shard_sieve_digit_boundary():
    r = sweep_min_hash_sharded(
        "x", 95, 305, backend="xla", max_k=1, batch_per_device=2, sieve=True
    )
    assert (r.hash, r.nonce) == min_hash_range("x", 95, 305)


def test_sharded_pallas_interpret_per_shard_sieve():
    # The flagship sharded composition: the dyn pallas SIEVE kernel under
    # shard_map — each shard tightens its own local running min in SMEM
    # scratch (the "per-shard local running-min") before the pmin cascade.
    r = sweep_min_hash_sharded(
        "cmu440", 1000, 2234, backend="pallas", interpret=True,
        max_k=2, batch_per_device=2, sieve=True,
    )
    assert (r.hash, r.nonce) == min_hash_range("cmu440", 1000, 2234)


def test_mesh_pipeline_per_shard_sieve_matches_oracle():
    # SweepPipeline mesh mode threads the enqueue-time running-min into
    # every sharded dispatch (sieve no longer pinned off in mesh mode).
    from bitcoin_miner_tpu.ops.sweep import SweepPipeline

    p = SweepPipeline(
        backend="xla", mesh=default_mesh(8), max_k=2, batch=2,
        host_lane_budget=0, sieve=True,
    )
    try:
        futs = [
            p.submit("cmu440", 1000, 2234),
            p.submit("cmu440", 2235, 3499),
        ]
        wants = [("cmu440", 1000, 2234), ("cmu440", 2235, 3499)]
        for f, (d, lo, hi) in zip(futs, wants):
            r = f.result(timeout=300)
            assert (r.hash, r.nonce) == min_hash_range(d, lo, hi), (d, lo, hi)
    finally:
        p.close()


def test_sharded_factored_matches_oracle():
    # Factored sharded tier (ISSUE 16 satellite): the outer/inner digit
    # split now threads through _make_sharded_kernel, so mesh xla miners
    # get the per-group schedule-buffer shrink that won 2.76x on the
    # single-device tier.  Same shard_map + collective cascade, with the
    # factored kernel's remapped global flat index feeding the per-device
    # argmin — bit-exact, lowest-nonce ties included.
    r = sweep_min_hash_sharded(
        "cmu440", 1000, 2234, backend="xla", max_k=2, batch_per_device=2,
        factored=True,
    )
    assert (r.hash, r.nonce) == min_hash_range("cmu440", 1000, 2234)
    assert r.lanes_swept == 2234 - 1000 + 1


def test_sharded_factored_digit_boundary():
    # k=1 leaves nothing to factor (k_in=0 -> baseline fallback) on one
    # side of the boundary; the d=3 class factors.  Both shapes sharded.
    r = sweep_min_hash_sharded(
        "x", 95, 305, backend="xla", max_k=1, batch_per_device=2,
        factored=True,
    )
    assert (r.hash, r.nonce) == min_hash_range("x", 95, 305)


def test_sharded_factored_sieve_composition():
    # Factored + per-shard sieve, sharded: pass 1 and pass 2 resume from
    # ONE shared group prefix inside each shard, the dispatch threshold
    # replicated ahead of the cascade.
    r = sweep_min_hash_sharded(
        "cmu440", 1000, 2234, backend="xla", max_k=2, batch_per_device=2,
        factored=True, sieve=True,
    )
    assert (r.hash, r.nonce) == min_hash_range("cmu440", 1000, 2234)


def test_sharded_hot_matches_oracle():
    # The always-hot plane over the mesh (ISSUE 16): donated replicated
    # carry merged AFTER the collective cascade, the carried best_dev
    # scaling the row exactly like the per-chunk sharded fold.  The xla
    # leg rides the factored sharded default.
    r = sweep_min_hash_sharded(
        "cmu440", 1000, 2234, backend="xla", max_k=2, batch_per_device=2,
        sieve=True, hot=True,
    )
    assert (r.hash, r.nonce) == min_hash_range("cmu440", 1000, 2234)
    assert r.lanes_swept == 2234 - 1000 + 1


def test_sharded_hot_digit_boundary():
    r = sweep_min_hash_sharded(
        "x", 95, 305, backend="xla", max_k=1, batch_per_device=2, hot=True
    )
    assert (r.hash, r.nonce) == min_hash_range("x", 95, 305)


def test_mesh_pipeline_hot_matches_oracle():
    # SweepPipeline mesh mode with the hot plane on: back-to-back jobs,
    # one donated carry per job, tokens through the same fetch queue.
    from bitcoin_miner_tpu.ops.sweep import SweepPipeline

    p = SweepPipeline(
        backend="xla", mesh=default_mesh(8), max_k=2, batch=2,
        host_lane_budget=0, sieve=True, hot=True,
    )
    try:
        futs = [
            p.submit("cmu440", 1000, 2234),
            p.submit("cmu440", 2235, 3499),
        ]
        wants = [("cmu440", 1000, 2234), ("cmu440", 2235, 3499)]
        for f, (d, lo, hi) in zip(futs, wants):
            r = f.result(timeout=300)
            assert (r.hash, r.nonce) == min_hash_range(d, lo, hi), (d, lo, hi)
    finally:
        p.close()


def test_sharded_matches_single_device_tier():
    from bitcoin_miner_tpu.ops.sweep import sweep_min_hash

    # Same data/digit-count as the single-group test -> reuses its compile.
    data, lo, hi = "cmu440", 1100, 3333
    rs = sweep_min_hash_sharded(
        data, lo, hi, backend="xla", max_k=2, batch_per_device=2
    )
    r1 = sweep_min_hash(data, lo, hi, backend="xla", max_k=2)
    assert (rs.hash, rs.nonce) == (r1.hash, r1.nonce)


def test_mesh_pipeline_matches_oracle():
    # The cross-request SweepPipeline in mesh mode: back-to-back sharded
    # jobs over the 8-device mesh, each bit-exact vs the oracle — the
    # multi-chip miner's production search path (apps/miner.py
    # make_async_search with --devices N).
    from bitcoin_miner_tpu.ops.sweep import SweepPipeline

    p = SweepPipeline(
        backend="xla", mesh=default_mesh(8), max_k=2, batch=2,
        host_lane_budget=0,
    )
    try:
        futs = [
            p.submit("cmu440", 1000, 2234),
            p.submit("cmu440", 2235, 3499),
            p.submit("x", 95, 305),  # different data + digit boundary
        ]
        wants = [("cmu440", 1000, 2234), ("cmu440", 2235, 3499), ("x", 95, 305)]
        for f, (d, lo, hi) in zip(futs, wants):
            r = f.result(timeout=300)
            assert (r.hash, r.nonce) == min_hash_range(d, lo, hi), (d, lo, hi)
            assert r.lanes_swept == hi - lo + 1
    finally:
        p.close()


def test_make_async_search_routes_mesh_to_pipeline():
    from bitcoin_miner_tpu.apps.miner import _PipelineSearch, make_async_search

    s = make_async_search("auto", devices=8)
    try:
        assert isinstance(s, _PipelineSearch)
        h, n = s.submit("cmu440", 1000, 1999).result(timeout=300)
        assert (h, n) == min_hash_range("cmu440", 1000, 1999)
    finally:
        s.close()
