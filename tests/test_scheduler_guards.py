"""Scheduler guard rails: Result validation, straggler recovery, and
job-level checkpoint/resume.

The reference's epoch machinery detects dead *connections* only
(``lsp/params.go:16-19``); these tests pin the framework's additional
guarantees: a lying miner cannot corrupt a job's answer, a live-but-hung
miner cannot stall a job forever, and a restarted fleet resumes a job
without re-sweeping completed sub-ranges.
"""

from bitcoin_miner_tpu.apps.scheduler import Scheduler, _merge_intervals
from bitcoin_miner_tpu.bitcoin.hash import hash_nonce, min_hash_range
from bitcoin_miner_tpu.bitcoin.message import MsgType
from bitcoin_miner_tpu.utils.metrics import METRICS

DATA = "cmu440"


def honest(data, lo, hi):
    """What a correct miner replies for chunk [lo, hi]."""
    return min_hash_range(data, lo, hi)


def requests(actions):
    return [(cid, m) for cid, m in actions if m.type == MsgType.REQUEST]


def results(actions):
    return [(cid, m) for cid, m in actions if m.type == MsgType.RESULT]


class TestResultValidation:
    def test_honest_result_accepted(self):
        METRICS.reset()
        s = Scheduler(min_chunk=1000)
        s.miner_joined(1)
        s.client_request(10, DATA, 0, 99)
        h, n = honest(DATA, 0, 99)
        final = results(s.result(1, h, n))
        assert final[0][1].hash == h and final[0][1].nonce == n
        assert METRICS.get("sched.results_rejected") == 0

    def test_lying_hash_rejected_and_chunk_requeued(self):
        METRICS.reset()
        s = Scheduler(min_chunk=1000)
        s.miner_joined(1)
        s.miner_joined(2)
        s.client_request(10, DATA, 0, 99)
        liar = next(m for m in s.miners.values() if m.job is not None).conn_id
        other = 3 - liar
        # Bogus hash: valid nonce, wrong value.
        acts = s.result(liar, hash_=12345, nonce=7)
        assert results(acts) == []  # job must NOT complete on a lie
        assert METRICS.get("sched.results_rejected") == 1
        # Chunk went straight to the idle honest miner.
        req = requests(acts)
        assert req and req[0][0] == other
        h, n = honest(DATA, 0, 99)
        final = results(s.result(other, h, n))
        assert (final[0][1].hash, final[0][1].nonce) == (h, n)

    def test_out_of_range_nonce_rejected(self):
        METRICS.reset()
        s = Scheduler(min_chunk=1000)
        s.miner_joined(1)
        s.client_request(10, DATA, 0, 99)
        # Correct hash for a nonce outside the assigned interval.
        n = 500
        acts = s.result(1, hash_nonce(DATA, n), n)
        assert results(acts) == []
        assert METRICS.get("sched.results_rejected") == 1

    def test_liar_evicted_after_max_rejects(self):
        METRICS.reset()
        s = Scheduler(min_chunk=1000, max_rejects=2)
        s.miner_joined(1)
        s.client_request(10, DATA, 0, 99)
        s.result(1, 1, 1)  # strike 1 (chunk re-queued, re-assigned to 1)
        assert 1 in s.miners
        s.result(1, 2, 2)  # strike 2 -> evicted
        assert 1 not in s.miners
        assert METRICS.get("sched.miners_evicted") == 1
        assert s.drain_evictions() == [1]  # shell is told to close the conn
        assert s.drain_evictions() == []  # drained once
        # A re-Join on the same conn must NOT reset the strike count.
        assert s.miner_joined(1) == []
        assert 1 not in s.miners
        # An honest replacement still completes the job.
        acts = s.miner_joined(2)
        assert requests(acts)[0][1].lower == 0
        h, n = honest(DATA, 0, 99)
        assert results(s.result(2, h, n))


class TestStragglerRecovery:
    def test_hung_miner_chunk_requeued_after_timeout(self):
        METRICS.reset()
        s = Scheduler(min_chunk=100, straggler_min_seconds=10.0)
        s.miner_joined(1, now=0.0)
        s.miner_joined(2, now=0.0)
        s.client_request(10, DATA, 0, 99, now=0.0)  # one chunk, one busy miner
        hung = next(m for m in s.miners.values() if m.job is not None).conn_id
        other = 3 - hung
        assert s.tick(5.0) == []  # before the deadline: nothing
        acts = s.tick(11.0)  # past straggler_min_seconds
        req = requests(acts)
        assert req and req[0][0] == other  # idle peer picked the chunk up
        assert METRICS.get("sched.chunks_straggler_requeued") == 1
        # The fast peer's Result completes the job.
        h, n = honest(DATA, 0, 99)
        final = results(s.result(other, h, n, now=11.5))
        assert (final[0][1].hash, final[0][1].nonce) == (h, n)
        # The hung miner's late duplicate is folded harmlessly and idles it.
        assert s.result(hung, h, n, now=60.0) == []
        assert s.miners[hung].job is None

    def test_rate_based_deadline(self):
        # A miner with a known fast rate gets a deadline ~4x its expected
        # chunk duration, not the 10s floor... unless the floor is larger.
        # depth=1 so exactly one assignment's deadline is under test.
        s = Scheduler(
            min_chunk=100,
            straggler_factor=4.0,
            straggler_min_seconds=0.5,
            target_chunk_seconds=1.0,
            pipeline_depth=1,
        )
        s.miner_joined(1, now=0.0)
        s.client_request(10, DATA, 0, 10**6, now=0.0)
        h, n = honest(DATA, 0, 99)
        s.result(1, h, n, now=0.001)  # 100 nonces/ms -> rate 1e5/s
        # Next chunk targets 1s of work; deadline = 4x expected = ~4s.
        assert s.tick(2.0) == []  # not yet
        assert s.miners[1].timed_out is False
        s.tick(5.0)
        assert s.miners[1].timed_out is True

    def test_straggler_result_arrives_first_withdraws_duplicate(self):
        s = Scheduler(min_chunk=100, straggler_min_seconds=1.0)
        s.miner_joined(1, now=0.0)
        s.client_request(10, DATA, 0, 99, now=0.0)
        s.tick(2.0)  # re-queued, but no peer to take it
        job = s.jobs[10]
        assert list(job.pending) == [(0, 99)]
        h, n = honest(DATA, 0, 99)
        final = results(s.result(1, h, n, now=3.0))  # slowpoke delivers
        assert (final[0][1].hash, final[0][1].nonce) == (h, n)
        assert 10 not in s.jobs  # duplicate withdrawn, job closed

    def test_straggler_withdrawal_survives_chunk_resplitting(self):
        # Dispatch may cut the re-queued duplicate into different chunk
        # shapes; the late Result must still withdraw what remains pending
        # (interval subtraction, not whole-tuple matching).  depth=1 keeps
        # the replacement miner to a single differently-shaped chunk.
        s = Scheduler(min_chunk=300, straggler_min_seconds=1.0, pipeline_depth=1)
        s.miner_joined(1, now=0.0)
        s.client_request(10, DATA, 0, 299, now=0.0)  # miner 1 holds (0,299)
        s.tick(2.0)  # re-queued; no peer yet
        s.min_chunk = 100  # replacement carves a smaller chunk
        acts = s.miner_joined(2, now=2.5)
        req = requests(acts)
        assert (req[0][1].lower, req[0][1].upper) == (0, 99)
        assert list(s.jobs[10].pending) == [(100, 299)]
        # The hung miner delivers its full-range Result after all.
        h, n = honest(DATA, 0, 299)
        assert results(s.result(1, h, n, now=3.0)) == []  # miner 2 still out
        assert list(s.jobs[10].pending) == []  # (100,299) withdrawn, NOT re-swept
        h2, n2 = honest(DATA, 0, 99)
        final = results(s.result(2, h2, n2, now=3.5))
        assert (final[0][1].hash, final[0][1].nonce) == (h, n)

    def test_lost_after_timeout_does_not_duplicate_chunk(self):
        s = Scheduler(min_chunk=100, straggler_min_seconds=1.0)
        s.miner_joined(1, now=0.0)
        s.client_request(10, DATA, 0, 99, now=0.0)
        s.tick(2.0)
        s.lost(1, now=3.0)  # hung miner finally dies
        job = s.jobs[10]
        assert list(job.pending) == [(0, 99)]  # exactly one copy


class TestCheckpointResume:
    def test_resume_skips_completed_subranges(self):
        s = Scheduler(min_chunk=100, max_chunk=100)
        s.miner_joined(1, now=0.0)
        s.client_request(10, DATA, 0, 299, now=0.0)  # chunks of 100
        h0, n0 = honest(DATA, 0, 99)
        s.result(1, h0, n0, now=10.0)  # [0,99] done; [100,199] assigned
        state = s.checkpoint()
        [jobdict] = state["jobs"]
        assert jobdict["best"] == [h0, n0]
        # Remaining = outstanding [100,199] + pending [200,299], merged.
        assert jobdict["remaining"] == [[100, 299]]

        # Fleet restart: fresh scheduler, client resubmits the same job.
        s2 = Scheduler(min_chunk=1000, resume_state=state)
        s2.miner_joined(5, now=0.0)
        acts = s2.client_request(20, DATA, 0, 299, now=0.0)
        req = requests(acts)
        assert (req[0][1].lower, req[0][1].upper) == (100, 299)  # no re-sweep
        h1, n1 = honest(DATA, 100, 299)
        final = results(s2.result(5, h1, n1, now=1.0))
        assert (final[0][1].hash, final[0][1].nonce) == min_hash_range(
            DATA, 0, 299
        )

    def test_resume_fully_swept_job_answers_immediately(self):
        s = Scheduler(min_chunk=1000)
        s.miner_joined(1)
        s.client_request(10, DATA, 0, 99)
        h, n = honest(DATA, 0, 99)
        s.result(1, h, n)
        # Job completed -> nothing to checkpoint for it...
        assert s.checkpoint()["jobs"] == []
        # ...but a checkpoint taken mid-flight with zero remaining resumes
        # to an instant answer.
        state = {
            "version": 1,
            "jobs": [
                {
                    "data": DATA,
                    "lower": 0,
                    "upper": 99,
                    "best": [h, n],
                    "remaining": [],
                }
            ],
        }
        s2 = Scheduler(resume_state=state)
        acts = s2.client_request(20, DATA, 0, 99)
        final = results(acts)
        assert (final[0][1].hash, final[0][1].nonce) == (h, n)

    def test_mismatched_request_does_not_resume(self):
        state = {
            "version": 1,
            "jobs": [
                {
                    "data": DATA,
                    "lower": 0,
                    "upper": 99,
                    "best": [1, 1],
                    "remaining": [],
                }
            ],
        }
        s = Scheduler(min_chunk=1000, resume_state=state)
        s.miner_joined(1)
        # Different range -> a fresh job covering the full range.
        acts = s.client_request(20, DATA, 0, 199)
        req = requests(acts)
        assert (req[0][1].lower, req[0][1].upper) == (0, 199)

    def test_checkpoint_roundtrips_orphaned_progress(self):
        state = {
            "version": 1,
            "jobs": [
                {
                    "data": "x",
                    "lower": 0,
                    "upper": 9,
                    "best": None,
                    "remaining": [[5, 9]],
                }
            ],
        }
        s = Scheduler(resume_state=state)
        assert s.checkpoint()["jobs"] == state["jobs"]

    def test_duplicate_key_entries_merge_not_overwrite(self):
        """A live job and a staler orphaned entry for the same (data, lo, hi)
        used to round-trip last-wins — the orphan could clobber the live
        job's fresher progress.  They must merge: min-fold best, union
        remaining."""
        orphan = {
            "data": DATA,
            "lower": 0,
            "upper": 299,
            "best": [500, 42],
            "remaining": [[0, 299]],  # stale: nothing swept yet
        }
        s = Scheduler(
            min_chunk=100, max_chunk=100,
            resume_state={"version": 1, "jobs": [orphan, dict(orphan)]},
        )
        # Duplicate entries within one load already collapse to one.
        assert len(s.checkpoint()["jobs"]) == 1
        s.miner_joined(1, now=0.0)
        # A DIFFERENT client id resubmits; the resume entry is consumed and
        # the job advances past the orphan's snapshot.
        s.client_request(10, DATA, 0, 299, now=0.0)
        h0, n0 = honest(DATA, 0, 99)
        better = min((h0, n0), (500, 42))
        s.result(1, h0, n0, now=1.0)  # [0,99] swept
        # Re-stage the stale orphan AFTER the live job progressed.
        s.load_checkpoint({"version": 1, "jobs": [orphan]})
        state = s.checkpoint()
        [j] = state["jobs"]
        # best: the min of live progress and the orphan's (real) hash.
        assert j["best"] == list(better)
        # remaining: the union — the stale full-range claim wins space-wise
        # (conservative re-sweep), but fresher best is never lost.
        assert j["remaining"] == [[0, 299]]

        # Round-trip into a fresh scheduler: still one entry, same content.
        s2 = Scheduler(resume_state=state)
        assert s2.checkpoint()["jobs"] == state["jobs"]

    def test_two_resubmits_after_lost_first_resumes_second_restarts(self):
        """The gateway cancels a coalesced job through ``lost()`` when its
        last waiter dies; if TWO clients then resubmit the identical
        signature, exactly one consumes the orphan stash (first come) and
        the other starts full-range — never a double-consume, never a
        lost best-so-far, and the checkpoint folds back to one entry."""
        METRICS.reset()
        s = Scheduler(min_chunk=100, max_chunk=100, validate_results=False)
        s.miner_joined(1, now=0.0)
        s.client_request(10, DATA, 0, 299, now=0.0)
        s.result(1, hash_=700, nonce=5, now=0.5)  # [0,99] swept
        s.lost(10, now=1.0)
        assert METRICS.get("sched.jobs_orphaned") == 1
        s.client_request(20, DATA, 0, 299, now=2.0)
        s.client_request(21, DATA, 0, 299, now=2.0)
        assert METRICS.get("sched.jobs_resumed") == 1  # exactly one resume
        resumed, fresh = s.jobs[20], s.jobs[21]
        assert resumed.best == (700, 5)  # stashed progress carried over
        assert fresh.best is None  # the twin starts from scratch...
        remaining_fresh = list(fresh.pending) + [
            iv for lst in fresh.outstanding.values() for iv in lst
        ]
        assert sorted(remaining_fresh)[0][0] == 0  # ...over the full range
        # One merged checkpoint entry covers both, best preserved.
        [j] = s.checkpoint()["jobs"]
        assert j["best"] == [700, 5]
        assert j["remaining"] == [[0, 299]]

    def test_resume_entry_races_live_identical_twin(self):
        """A staged checkpoint entry consumed by one request while an
        identical twin runs concurrently (the shape behind a gateway
        coalesce racing checkpoint-resume): the resumed job must keep the
        stashed best and skip swept ranges, the twin must sweep the full
        range, and both must answer bit-exact."""
        staged_best = [hash_nonce(DATA, 150), 150]
        state = {
            "version": 1,
            "jobs": [
                {
                    "data": DATA,
                    "lower": 0,
                    "upper": 199,
                    "best": staged_best,
                    "remaining": [[100, 199]],
                }
            ],
        }
        s = Scheduler(min_chunk=1000, resume_state=state)
        s.miner_joined(1, now=0.0)
        s.miner_joined(2, now=0.0)
        s.client_request(10, DATA, 0, 199, now=0.0)  # consumes the stash
        s.client_request(11, DATA, 0, 199, now=0.0)  # identical twin, fresh
        # Miner 1 holds the resumed tail [100,199]; miner 2 the full range.
        assert s.jobs[10].outstanding[1] == [(100, 199)]
        assert s.jobs[11].outstanding[2] == [(0, 199)]
        # Mid-flight, the merged checkpoint is ONE conservative entry.
        [j] = s.checkpoint()["jobs"]
        assert j["best"] == staged_best
        assert j["remaining"] == [[0, 199]]
        h1, n1 = honest(DATA, 100, 199)
        final_a = results(s.result(1, h1, n1, now=1.0))
        assert (final_a[0][1].hash, final_a[0][1].nonce) == min(
            (tuple(staged_best)), (h1, n1)
        )
        h2, n2 = honest(DATA, 0, 199)
        final_b = results(s.result(2, h2, n2, now=1.5))
        assert (final_b[0][1].hash, final_b[0][1].nonce) == (h2, n2)

    def test_two_identical_concurrent_jobs_checkpoint_merges(self):
        """Two clients running the same (data, lower, upper) concurrently
        produce one merged checkpoint entry covering both jobs' unswept
        work and the better best."""
        s = Scheduler(min_chunk=100, max_chunk=100)
        s.miner_joined(1, now=0.0)
        s.miner_joined(2, now=0.0)
        s.client_request(10, DATA, 0, 299, now=0.0)
        s.client_request(11, DATA, 0, 299, now=0.0)
        h0, n0 = honest(DATA, 0, 99)
        s.result(1, h0, n0, now=1.0)  # job 10: [0,99] swept
        [j] = s.checkpoint()["jobs"]
        assert j["best"] == [h0, n0]
        assert j["remaining"] == [[0, 299]]  # job 11 still needs [0,99]


def test_merge_intervals():
    assert _merge_intervals([]) == []
    assert _merge_intervals([(5, 9), (0, 4)]) == [(0, 9)]  # adjacent
    assert _merge_intervals([(0, 9), (3, 5)]) == [(0, 9)]  # contained
    assert _merge_intervals([(0, 2), (4, 6)]) == [(0, 2), (4, 6)]  # gap
    assert _merge_intervals([(0, 5), (3, 8)]) == [(0, 8)]  # overlap


def test_max_chunk_cannot_outgrow_pallas_argmin_guard():
    """Couples the scheduler's chunk cap to the kernel's int32-argmin guard
    (ops/pallas_sha256.py: batch * 10^k lanes must fit int32 or the kernel
    would return silently wrong nonces).  A max_chunk-sized chunk is split
    into dispatches of (batch, 10^k) by the sweep driver, so the binding
    invariant is on the pallas tier's DEFAULTS — build the kernels for a
    full-size chunk's decomposition and let the guard raise if the two
    limits ever drift apart."""
    from bitcoin_miner_tpu.ops.pallas_sha256 import make_pallas_minhash
    from bitcoin_miner_tpu.ops.sweep import (
        _layout_cache,
        auto_tune,
        decompose_range,
    )

    backend, batch, max_k, _sieve, _factored, _hot = auto_tune(
        "pallas", None, None
    )
    assert batch * 10**max_k <= 2**31 - 1, "pallas defaults overflow argmin"
    s = Scheduler()
    lo = 10**9
    for group in decompose_range(lo, lo + s.max_chunk - 1, max_k=max_k):
        layout = _layout_cache(b"cmu440", group.d)
        low_pos = layout.digit_pos[layout.digit_count - group.k :]
        # Raises ValueError at construction if batch*10^k overflows int32.
        make_pallas_minhash(layout.n_tail_blocks, low_pos, group.k, batch)
